"""Round-5 TPC-DS gate queries: window/rank, rollup (Expand), existence
joins (semi/anti/ExistenceJoin), SMJ, and UNION — the operator classes the
round-4 verdict flagged as implemented-but-never-exercised-by-a-real-query.

Same contract as tests/tpcds/queries.py: each entry carries the genuine
TPC-DS query text (template parameters bound to values the tiny dataset
makes selective), a Spark-wire ``toJSON`` physical plan, a pandas oracle,
an optional extractor, and compare flags. Registered into the same QUERIES
dict. Reference: the all-99-query buckets in ``tpcds-reusable.yml:57-71``."""

from __future__ import annotations

import numpy as np
import pandas as pd

from tests.tpcds.plans import (Attrs, X, agg_expr, alias, and_, bcast, bhj,
                               binop, cast, eq, exchange, existence_join,
                               expand, filt, hash_agg, in_list, isnotnull,
                               lit, mul, not_, or_, project, scan, sfn, smj,
                               sort, sort_order, sorted_exchange,
                               take_ordered, two_stage_agg, union_all,
                               window, window_rank)
from tests.tpcds.queries import QUERIES, query


def _window_agg(a, fn_cls, arg, name, wid):
    """Alias(WindowExpression(AggregateExpression(fn))) — aggregate-over-
    window, as Spark serializes avg(...) OVER (PARTITION BY ...)."""
    agg = agg_expr(fn_cls, "Complete", a.new_id(), [arg])
    wexpr = [{"class": f"{X}.WindowExpression", "num-children": 1,
              "windowFunction": 0, "windowSpec": {}}] + agg
    return alias(wexpr, name, wid)


def _case_ratio_filter(ssum, wavg, a, threshold="0.1"):
    """CASE WHEN avg > 0 THEN abs(sum-avg)/avg ELSE null END > threshold —
    the q47/q53/q57/q63/q89 deviation predicate."""
    cond = binop("GreaterThan", wavg, lit("0.000000", "decimal(21,6)"))
    ratio = binop("Divide", sfn("Abs", binop("Subtract", ssum, wavg)), wavg)
    case = [{"class": f"{X}.CaseWhen", "num-children": 3,
             "branches": None, "elseValue": None}] + \
        cond + ratio + lit(None, "decimal(38,16)")
    return binop("GreaterThan", case, lit(threshold, "decimal(2,1)"))


def _manufact_window_query(group_col, second_group_col,
                           group_first_order=False):
    """Shared shape of q53 (i_manufact_id) and q63 (i_manager_id):
    quarterly/monthly sums per item group + avg-over-group window + the
    deviation filter. ``group_first_order``: q63 sorts the group column
    FIRST (ORDER BY i_manager_id, avg_monthly_sales, sum_sales) while q53
    sorts it last."""
    a = Attrs()
    for c, t in [("ss_item_sk", "long"), ("ss_sold_date_sk", "long"),
                 ("ss_store_sk", "long"), ("ss_sales_price", "decimal(7,2)"),
                 ("i_item_sk", "long"), (group_col, "long"),
                 ("i_category", "string"), ("i_class", "string"),
                 ("i_brand", "string"),
                 ("d_date_sk", "long"), ("d_month_seq", "long"),
                 (second_group_col, "long"),
                 ("s_store_sk", "long")]:
        a.define(c, t)
    ss = scan("store_sales", a, ["ss_item_sk", "ss_sold_date_sk",
                                 "ss_store_sk", "ss_sales_price"])
    it = filt(
        or_(and_(in_list(a("i_category"),
                         ["Books", "Children", "Electronics"], "string"),
                 in_list(a("i_class"),
                         ["class01", "class02", "class03"], "string"),
                 in_list(a("i_brand"),
                         ["brand#1", "brand#2", "brand#3", "brand#4",
                          "brand#5", "brand#6", "brand#7"], "string")),
            and_(in_list(a("i_category"),
                         ["Women", "Music", "Men"], "string"),
                 in_list(a("i_class"),
                         ["class04", "class05", "class06"], "string"),
                 in_list(a("i_brand"),
                         ["brand#8", "brand#9", "brand#10", "brand#11",
                          "brand#12", "brand#13", "brand#14"], "string"))),
        scan("item", a, ["i_item_sk", group_col, "i_category", "i_class",
                         "i_brand"]))
    dt = filt(in_list(a("d_month_seq"), list(range(1176, 1188)), "long"),
              scan("date_dim", a,
                   ["d_date_sk", "d_month_seq", second_group_col]))
    st = scan("store", a, ["s_store_sk"])
    j = bhj(ss, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    j = bhj(j, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j = bhj(j, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    rid = a.new_id()
    agg = two_stage_agg([a(group_col), a(second_group_col)],
                        [("Sum", rid, [a("ss_sales_price")])], j)
    ssum = a.define_with_id("sum_sales", "decimal(17,2)", rid)
    wid = a.new_id()
    wchild = sort([sort_order(a(group_col))],
                  exchange(agg, keys=[a(group_col)]))
    win = window([_window_agg(a, "Average", ssum, "avg_group_sales", wid)],
                 [a(group_col)], [], wchild)
    wavg = a.define_with_id("avg_group_sales", "decimal(21,6)", wid)
    f = filt(_case_ratio_filter(ssum, wavg, a), win)
    orders = [sort_order(a(group_col)), sort_order(wavg), sort_order(ssum)] \
        if group_first_order else \
        [sort_order(wavg), sort_order(ssum), sort_order(a(group_col))]
    plan = take_ordered(100, orders, [a(group_col), ssum, wavg], f)

    def oracle(dfs):
        it = dfs["item"]
        dd = dfs["date_dim"]
        keep = ((it.i_category.isin(["Books", "Children", "Electronics"])
                 & it.i_class.isin(["class01", "class02", "class03"])
                 & it.i_brand.isin([f"brand#{v}" for v in range(1, 8)]))
                | (it.i_category.isin(["Women", "Music", "Men"])
                   & it.i_class.isin(["class04", "class05", "class06"])
                   & it.i_brand.isin([f"brand#{v}" for v in range(8, 15)])))
        m = dfs["store_sales"].merge(it[keep], left_on="ss_item_sk",
                                     right_on="i_item_sk")
        m = m.merge(dd[(dd.d_month_seq >= 1176) & (dd.d_month_seq <= 1187)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(dfs["store"], left_on="ss_store_sk",
                    right_on="s_store_sk")
        g = m.groupby([group_col, second_group_col],
                      as_index=False).ss_sales_price.sum()
        g["sum_sales"] = g.ss_sales_price.astype(float)
        g["avg_g"] = g.groupby(group_col).sum_sales.transform("mean")
        g = g[(g.avg_g > 0)
              & ((g.sum_sales - g.avg_g).abs() / g.avg_g > 0.1)]
        sort_cols = [group_col, "avg_g", "sum_sales"] if group_first_order \
            else ["avg_g", "sum_sales", group_col]
        g = g.sort_values(sort_cols, kind="stable").head(100)
        return [(getattr(r, group_col), r.sum_sales, r.avg_g)
                for r in g.itertuples(index=False)]

    def extract(out):
        d = out.to_pydict()
        cols = list(d.values())
        return [(int(k), float(s), float(v))
                for k, s, v in zip(*cols)]

    return plan, oracle, extract, ("approx",)


@query("q53")
def q53():
    """SELECT * FROM (SELECT i_manufact_id, sum(ss_sales_price) sum_sales,
              avg(sum(ss_sales_price)) OVER (PARTITION BY i_manufact_id)
                  avg_quarterly_sales
       FROM item, store_sales, date_dim, store
       WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
         AND ss_store_sk = s_store_sk AND d_month_seq IN (1176..1187)
         AND ((i_category IN ('Books','Children','Electronics')
               AND i_class IN (...) AND i_brand IN (...))
           OR (i_category IN ('Women','Music','Men')
               AND i_class IN (...) AND i_brand IN (...)))
       GROUP BY i_manufact_id, d_qoy) tmp1
       WHERE CASE WHEN avg_quarterly_sales > 0
                  THEN abs(sum_sales - avg_quarterly_sales)
                       / avg_quarterly_sales ELSE null END > 0.1
       ORDER BY avg_quarterly_sales, sum_sales, i_manufact_id LIMIT 100"""
    return _manufact_window_query("i_manufact_id", "d_qoy")


@query("q63")
def q63():
    """SELECT * FROM (SELECT i_manager_id, sum(ss_sales_price) sum_sales,
              avg(sum(ss_sales_price)) OVER (PARTITION BY i_manager_id)
                  avg_monthly_sales
       FROM item, store_sales, date_dim, store
       WHERE ... d_month_seq IN (1176..1187) AND (category/class/brand
         disjuncts as q53) GROUP BY i_manager_id, d_moy) tmp1
       WHERE CASE WHEN avg_monthly_sales > 0
                  THEN abs(sum_sales - avg_monthly_sales)
                       / avg_monthly_sales ELSE null END > 0.1
       ORDER BY i_manager_id, avg_monthly_sales, sum_sales LIMIT 100"""
    return _manufact_window_query("i_manager_id", "d_moy",
                                  group_first_order=True)


# --------------------------------------------------------------------------
# rollup / Expand class
# --------------------------------------------------------------------------


def _rollup_expand(a, g, key_cols, child, gid_name="spark_grouping_id"):
    """ExpandExec for GROUP BY ROLLUP(key_cols): level i nulls out the last
    i keys; spark_grouping_id gets one bit per nulled key (Spark's
    ResolveGroupingAnalytics rewrite). ``g`` is the POST-expand attribute
    registry (fresh exprIds, same names — exactly how Spark emits it)."""
    projections = []
    n = len(key_cols)
    for lvl in range(n + 1):
        keep = n - lvl
        row = []
        for i, (name, dtype) in enumerate(key_cols):
            row.append(a(name) if i < keep else lit(None, dtype))
        gid = (1 << lvl) - 1
        row.append(lit(gid, "long"))
        projections.append(row)
    out_attrs = [g.define(name, dtype) for name, dtype in key_cols]
    out_attrs.append(g.define(gid_name, "long"))
    return expand(projections, out_attrs, child)


@query("q67")
def q67():
    """SELECT * FROM (SELECT i_category, i_class, i_brand, i_product_name,
              d_year, d_qoy, d_moy, s_store_id, sumsales,
              rank() OVER (PARTITION BY i_category
                           ORDER BY sumsales DESC) rk
       FROM (SELECT i_category, i_class, i_brand, i_product_name, d_year,
                    d_qoy, d_moy, s_store_id,
                    sum(coalesce(ss_sales_price*ss_quantity,0)) sumsales
             FROM store_sales, date_dim, store, item
             WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
               AND ss_store_sk = s_store_sk
               AND d_month_seq BETWEEN 1176 AND 1187
             GROUP BY ROLLUP(i_category, i_class, i_brand, i_product_name,
                             d_year, d_qoy, d_moy, s_store_id)) dw1) dw2
       WHERE rk <= 100
       ORDER BY i_category, i_class, i_brand, i_product_name, d_year,
                d_qoy, d_moy, s_store_id, sumsales, rk LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_item_sk", "long"),
                 ("ss_store_sk", "long"), ("ss_quantity", "long"),
                 ("ss_sales_price", "decimal(7,2)"),
                 ("d_date_sk", "long"), ("d_month_seq", "long"),
                 ("d_year", "long"), ("d_qoy", "long"), ("d_moy", "long"),
                 ("s_store_sk", "long"), ("s_store_id", "string"),
                 ("i_item_sk", "long"), ("i_category", "string"),
                 ("i_class", "string"), ("i_brand", "string"),
                 ("i_product_name", "string")]:
        a.define(c, t)
    ss = scan("store_sales", a, ["ss_sold_date_sk", "ss_item_sk",
                                 "ss_store_sk", "ss_quantity",
                                 "ss_sales_price"])
    dt = filt(and_(binop("GreaterThanOrEqual", a("d_month_seq"),
                         lit(1176, "long")),
                   binop("LessThanOrEqual", a("d_month_seq"),
                         lit(1187, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_month_seq", "d_year",
                                   "d_qoy", "d_moy"]))
    st = scan("store", a, ["s_store_sk", "s_store_id"])
    it = scan("item", a, ["i_item_sk", "i_category", "i_class", "i_brand",
                          "i_product_name"])
    j = bhj(ss, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j = bhj(j, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    j = bhj(j, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    # sum argument: coalesce(ss_sales_price * ss_quantity, 0) — Spark casts
    # the int factor and wraps the product in CheckOverflow
    sales_amt = sfn(
        "Coalesce",
        mul(a("ss_sales_price"), cast(a("ss_quantity"), "decimal(10,0)")),
        lit("0.00", "decimal(18,2)"))
    # project the pre-agg inputs Expand consumes (Spark plans Project
    # below Expand carrying group cols + the agg argument)
    amt_id = a.new_id()
    proj = project([a(c) for c in ("i_category", "i_class", "i_brand",
                                   "i_product_name", "d_year", "d_qoy",
                                   "d_moy", "s_store_id")] +
                   [alias(sales_amt, "sales_amt", amt_id)], j)
    amt = a.define_with_id("sales_amt", "decimal(18,2)", amt_id)
    key_cols = [("i_category", "string"), ("i_class", "string"),
                ("i_brand", "string"), ("i_product_name", "string"),
                ("d_year", "long"), ("d_qoy", "long"), ("d_moy", "long"),
                ("s_store_id", "string")]
    g = Attrs()
    ex = _rollup_expand(a, g, key_cols, proj)
    # Expand's output also forwards the agg argument
    ex[0]["output"].append(a("sales_amt"))
    for row in ex[0]["projections"]:
        row.append(amt)
    rid = a.new_id()
    groups = [g(name) for name, _ in key_cols] + [g("spark_grouping_id")]
    agg = two_stage_agg(groups, [("Sum", rid, [amt])], ex)
    ssum = a.define_with_id("sumsales", "decimal(28,2)", rid)
    rkid = a.new_id()
    wchild = sort([sort_order(g("i_category")),
                   sort_order(ssum, asc=False)],
                  exchange(agg, keys=[g("i_category")]))
    win = window([window_rank(g, "rk", [sort_order(ssum, asc=False)], rkid)],
                 [g("i_category")], [sort_order(ssum, asc=False)], wchild)
    rk = g.define_with_id("rk", "integer", rkid)
    f = filt(binop("LessThanOrEqual", rk, lit(100, "integer")), win)
    out_cols = [g(name) for name, _ in key_cols] + [ssum, rk]
    plan = take_ordered(
        100,
        [sort_order(g(name)) for name, _ in key_cols] +
        [sort_order(ssum), sort_order(rk)],
        out_cols, f)

    def oracle(dfs):
        dd = dfs["date_dim"]
        m = dfs["store_sales"].merge(
            dd[(dd.d_month_seq >= 1176) & (dd.d_month_seq <= 1187)],
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(dfs["store"], left_on="ss_store_sk",
                    right_on="s_store_sk")
        m = m.merge(dfs["item"], left_on="ss_item_sk", right_on="i_item_sk")
        # decimal cents * int is exact in float for these magnitudes
        m["sales_amt"] = m.ss_sales_price.astype(float) * m.ss_quantity
        cols = ["i_category", "i_class", "i_brand", "i_product_name",
                "d_year", "d_qoy", "d_moy", "s_store_id"]
        frames = []
        for lvl in range(len(cols) + 1):
            keep = cols[:len(cols) - lvl]
            if keep:
                gdf = m.groupby(keep, as_index=False).sales_amt.sum()
            else:
                gdf = pd.DataFrame({"sales_amt": [m.sales_amt.sum()]})
            for c in cols[len(cols) - lvl:]:
                gdf[c] = None
            frames.append(gdf[cols + ["sales_amt"]])
        allg = pd.concat(frames, ignore_index=True)
        allg["sumsales"] = allg.sales_amt.round(2)
        allg["rk"] = allg.groupby("i_category", dropna=False).sumsales.rank(
            method="min", ascending=False).astype(int)
        allg = allg[allg.rk <= 100]
        allg = allg.sort_values(cols + ["sumsales", "rk"], kind="stable",
                                na_position="first").head(100)

        def norm(v):
            if v is None or (isinstance(v, float) and np.isnan(v)):
                return None
            if isinstance(v, (np.integer, float)) and not isinstance(v, str):
                return int(v) if float(v).is_integer() and not isinstance(
                    v, np.floating) or isinstance(v, np.integer) else v
            return v

        out = []
        for r in allg.itertuples(index=False):
            row = []
            for c in cols:
                v = getattr(r, c)
                if v is None or (isinstance(v, float) and np.isnan(v)):
                    row.append(None)
                elif c in ("d_year", "d_qoy", "d_moy"):
                    row.append(int(v))
                else:
                    row.append(v)
            row.append(round(float(r.sumsales), 2))
            row.append(int(r.rk))
            out.append(tuple(row))
        return out

    def extract(out):
        d = out.to_pydict()
        names = list(d)
        rows = []
        for vals in zip(*d.values()):
            row = []
            for n, v in zip(names, vals):
                if v is None:
                    row.append(None)
                elif "sumsales" in n or "sum#" in n:
                    row.append(round(float(v), 2))
                elif isinstance(v, int):
                    row.append(v)
                else:
                    row.append(v)
            rows.append(tuple(row))
        return rows

    return plan, oracle, extract, ("approx",)


@query("q18")
def q18():
    """SELECT i_item_id, ca_country, ca_state, ca_county,
              avg(cast(cs_quantity as decimal(12,2))) agg1,
              avg(cast(cs_list_price as decimal(12,2))) agg2,
              avg(cast(cs_coupon_amt as decimal(12,2))) agg3,
              avg(cast(cs_sales_price as decimal(12,2))) agg4,
              avg(cast(c_birth_year as decimal(12,2))) agg5,
              avg(cast(cd1.cd_dep_count as decimal(12,2))) agg6
       FROM catalog_sales, customer_demographics cd1,
            customer_demographics cd2, customer, customer_address, date_dim,
            item
       WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
         AND cs_bill_cdemo_sk = cd1.cd_demo_sk
         AND cs_bill_customer_sk = c_customer_sk
         AND cd1.cd_gender = 'F' AND cd1.cd_education_status = 'Unknown'
         AND c_current_cdemo_sk = cd2.cd_demo_sk
         AND c_current_addr_sk = ca_address_sk AND c_birth_month IN (1,6,8,9)
         AND d_year = 1998 AND ca_state IN ('CA','TX','OH','GA','WA')
       GROUP BY ROLLUP (i_item_id, ca_country, ca_state, ca_county)
       ORDER BY ca_country, ca_state, ca_county, i_item_id LIMIT 100"""
    a = Attrs()
    for c, t in [("cs_sold_date_sk", "long"), ("cs_item_sk", "long"),
                 ("cs_bill_cdemo_sk", "long"),
                 ("cs_bill_customer_sk", "long"),
                 ("cs_quantity", "long"), ("cs_list_price", "decimal(7,2)"),
                 ("cs_coupon_amt", "decimal(7,2)"),
                 ("cs_sales_price", "decimal(7,2)"),
                 ("cd_demo_sk", "long"), ("cd_gender", "string"),
                 ("cd_education_status", "string"), ("cd_dep_count", "long"),
                 ("c_customer_sk", "long"), ("c_current_cdemo_sk", "long"),
                 ("c_current_addr_sk", "long"), ("c_birth_month", "long"),
                 ("c_birth_year", "long"),
                 ("ca_address_sk", "long"), ("ca_country", "string"),
                 ("ca_state", "string"), ("ca_county", "string"),
                 ("d_date_sk", "long"), ("d_year", "long"),
                 ("i_item_sk", "long"), ("i_item_id", "string")]:
        a.define(c, t)
    cs = scan("catalog_sales", a,
              ["cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk",
               "cs_bill_customer_sk", "cs_quantity", "cs_list_price",
               "cs_coupon_amt", "cs_sales_price"])
    cd1 = filt(and_(eq(a("cd_gender"), lit("F", "string")),
                    eq(a("cd_education_status"), lit("Unknown", "string"))),
               scan("customer_demographics", a,
                    ["cd_demo_sk", "cd_gender", "cd_education_status",
                     "cd_dep_count"]))
    # second customer_demographics instance: same names, fresh exprIds
    b = Attrs()
    b.define("cd_demo_sk", "long")
    cd2 = scan("customer_demographics", b, ["cd_demo_sk"])
    cu = filt(in_list(a("c_birth_month"), [1, 6, 8, 9], "long"),
              scan("customer", a,
                   ["c_customer_sk", "c_current_cdemo_sk",
                    "c_current_addr_sk", "c_birth_month", "c_birth_year"]))
    ca = filt(in_list(a("ca_state"), ["CA", "TX", "OH", "GA", "WA"],
                      "string"),
              scan("customer_address", a,
                   ["ca_address_sk", "ca_country", "ca_state", "ca_county"]))
    dt = filt(eq(a("d_year"), lit(1998, "long")),
              scan("date_dim", a, ["d_date_sk", "d_year"]))
    it = scan("item", a, ["i_item_sk", "i_item_id"])
    j = bhj(cs, bcast(cd1), [a("cs_bill_cdemo_sk")], [a("cd_demo_sk")])
    j = bhj(j, bcast(cu), [a("cs_bill_customer_sk")], [a("c_customer_sk")])
    j = bhj(j, bcast(cd2), [a("c_current_cdemo_sk")], [b("cd_demo_sk")])
    j = bhj(j, bcast(ca), [a("c_current_addr_sk")], [a("ca_address_sk")])
    j = bhj(j, bcast(dt), [a("cs_sold_date_sk")], [a("d_date_sk")])
    j = bhj(j, bcast(it), [a("cs_item_sk")], [a("i_item_sk")])
    # pre-agg projection: group cols + the six cast agg arguments
    arg_cols = ["cs_quantity", "cs_list_price", "cs_coupon_amt",
                "cs_sales_price", "c_birth_year", "cd_dep_count"]
    arg_ids = [a.new_id() for _ in arg_cols]
    proj = project(
        [a(c) for c in ("i_item_id", "ca_country", "ca_state", "ca_county")]
        + [alias(cast(a(c), "decimal(12,2)"), f"arg{i}", aid)
           for i, (c, aid) in enumerate(zip(arg_cols, arg_ids))], j)
    args = [a.define_with_id(f"arg{i}", "decimal(12,2)", aid)
            for i, aid in enumerate(arg_ids)]
    key_cols = [("i_item_id", "string"), ("ca_country", "string"),
                ("ca_state", "string"), ("ca_county", "string")]
    g = Attrs()
    ex = _rollup_expand(a, g, key_cols, proj)
    for arg in args:
        ex[0]["output"].append(arg)
    for row in ex[0]["projections"]:
        for arg in args:
            row.append(arg)
    rids = [a.new_id() for _ in range(6)]
    groups = [g(name) for name, _ in key_cols] + [g("spark_grouping_id")]
    agg = two_stage_agg(groups,
                        [("Average", rid, [arg])
                         for rid, arg in zip(rids, args)], ex)
    plan = take_ordered(
        100,
        [sort_order(g("ca_country")), sort_order(g("ca_state")),
         sort_order(g("ca_county")), sort_order(g("i_item_id"))],
        [g("i_item_id"), g("ca_country"), g("ca_state"), g("ca_county")] +
        [a.define_with_id(f"agg{i + 1}", "decimal(16,6)", rid)
         for i, rid in enumerate(rids)], agg)

    def oracle(dfs):
        cd = dfs["customer_demographics"]
        cu = dfs["customer"]
        ca = dfs["customer_address"]
        dd = dfs["date_dim"]
        m = dfs["catalog_sales"].merge(
            cd[(cd.cd_gender == "F")
               & (cd.cd_education_status == "Unknown")],
            left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(cu[cu.c_birth_month.isin([1, 6, 8, 9])],
                    left_on="cs_bill_customer_sk", right_on="c_customer_sk")
        m = m.merge(cd[["cd_demo_sk"]].rename(
            columns={"cd_demo_sk": "cd2_sk"}),
            left_on="c_current_cdemo_sk", right_on="cd2_sk")
        m = m.merge(ca[ca.ca_state.isin(["CA", "TX", "OH", "GA", "WA"])],
                    left_on="c_current_addr_sk", right_on="ca_address_sk")
        m = m.merge(dd[dd.d_year == 1998], left_on="cs_sold_date_sk",
                    right_on="d_date_sk")
        m = m.merge(dfs["item"], left_on="cs_item_sk", right_on="i_item_sk")
        for c in ("cs_list_price", "cs_coupon_amt", "cs_sales_price"):
            m[c] = m[c].astype(float)
        cols = ["i_item_id", "ca_country", "ca_state", "ca_county"]
        frames = []
        for lvl in range(len(cols) + 1):
            keep = cols[:len(cols) - lvl]
            spec = dict(a1=("cs_quantity", "mean"),
                        a2=("cs_list_price", "mean"),
                        a3=("cs_coupon_amt", "mean"),
                        a4=("cs_sales_price", "mean"),
                        a5=("c_birth_year", "mean"),
                        a6=("cd_dep_count", "mean"))
            if keep:
                gdf = m.groupby(keep, as_index=False).agg(**spec)
            else:
                gdf = pd.DataFrame({k: [getattr(m[c], f)()]
                                    for k, (c, f) in spec.items()})
            for c in cols[len(cols) - lvl:]:
                gdf[c] = None
            frames.append(gdf[cols + list(spec)])
        allg = pd.concat(frames, ignore_index=True)
        allg = allg.sort_values(
            ["ca_country", "ca_state", "ca_county", "i_item_id"],
            kind="stable", na_position="first").head(100)
        out = []
        for r in allg.itertuples(index=False):
            row = [None if not isinstance(v, str) else v
                   for v in (r.i_item_id, r.ca_country, r.ca_state,
                             r.ca_county)]
            row += [round(float(v), 4)
                    for v in (r.a1, r.a2, r.a3, r.a4, r.a5, r.a6)]
            out.append(tuple(row))
        return out

    def extract(out):
        d = out.to_pydict()
        rows = []
        for vals in zip(*d.values()):
            rows.append(tuple(
                v if isinstance(v, str) or v is None
                else round(float(v), 4) for v in vals))
        return rows

    return plan, oracle, extract, ("approx", "ties")


@query("q22")
def q22():
    """SELECT i_product_name, i_brand, i_class, i_category,
              avg(inv_quantity_on_hand) qoh
       FROM inventory, date_dim, item
       WHERE inv_date_sk = d_date_sk AND inv_item_sk = i_item_sk
         AND d_month_seq BETWEEN 1176 AND 1187
       GROUP BY ROLLUP(i_product_name, i_brand, i_class, i_category)
       ORDER BY qoh, i_product_name, i_brand, i_class, i_category
       LIMIT 100"""
    a = Attrs()
    for c, t in [("inv_date_sk", "long"), ("inv_item_sk", "long"),
                 ("inv_quantity_on_hand", "long"),
                 ("d_date_sk", "long"), ("d_month_seq", "long"),
                 ("i_item_sk", "long"), ("i_product_name", "string"),
                 ("i_brand", "string"), ("i_class", "string"),
                 ("i_category", "string")]:
        a.define(c, t)
    inv = scan("inventory", a,
               ["inv_date_sk", "inv_item_sk", "inv_quantity_on_hand"])
    dt = filt(and_(binop("GreaterThanOrEqual", a("d_month_seq"),
                         lit(1176, "long")),
                   binop("LessThanOrEqual", a("d_month_seq"),
                         lit(1187, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_month_seq"]))
    it = scan("item", a, ["i_item_sk", "i_product_name", "i_brand",
                          "i_class", "i_category"])
    j = bhj(inv, bcast(dt), [a("inv_date_sk")], [a("d_date_sk")])
    j = bhj(j, bcast(it), [a("inv_item_sk")], [a("i_item_sk")])
    key_cols = [("i_product_name", "string"), ("i_brand", "string"),
                ("i_class", "string"), ("i_category", "string")]
    g = Attrs()
    ex = _rollup_expand(a, g, key_cols, j)
    ex[0]["output"].append(a("inv_quantity_on_hand"))
    for row in ex[0]["projections"]:
        row.append(a("inv_quantity_on_hand"))
    rid = a.new_id()
    groups = [g(name) for name, _ in key_cols] + [g("spark_grouping_id")]
    agg = two_stage_agg(groups,
                        [("Average", rid, [a("inv_quantity_on_hand")])], ex)
    qoh = a.define_with_id("qoh", "double", rid)
    plan = take_ordered(
        100,
        [sort_order(qoh)] + [sort_order(g(name)) for name, _ in key_cols],
        [g(name) for name, _ in key_cols] + [qoh], agg)

    def oracle(dfs):
        dd = dfs["date_dim"]
        m = dfs["inventory"].merge(
            dd[(dd.d_month_seq >= 1176) & (dd.d_month_seq <= 1187)],
            left_on="inv_date_sk", right_on="d_date_sk")
        m = m.merge(dfs["item"], left_on="inv_item_sk", right_on="i_item_sk")
        cols = ["i_product_name", "i_brand", "i_class", "i_category"]
        frames = []
        for lvl in range(len(cols) + 1):
            keep = cols[:len(cols) - lvl]
            if keep:
                gdf = m.groupby(keep, as_index=False).agg(
                    qoh=("inv_quantity_on_hand", "mean"))
            else:
                gdf = pd.DataFrame(
                    {"qoh": [m.inv_quantity_on_hand.mean()]})
            for c in cols[len(cols) - lvl:]:
                gdf[c] = None
            frames.append(gdf[cols + ["qoh"]])
        allg = pd.concat(frames, ignore_index=True)
        allg = allg.sort_values(["qoh"] + cols, kind="stable",
                                na_position="first").head(100)
        return [tuple([None if not isinstance(getattr(r, c), str)
                       else getattr(r, c) for c in cols]
                      + [round(float(r.qoh), 4)])
                for r in allg.itertuples(index=False)]

    def extract(out):
        d = out.to_pydict()
        rows = []
        for vals in zip(*d.values()):
            *keys, qoh_v = vals
            rows.append(tuple(list(keys) + [round(float(qoh_v), 4)]))
        return rows

    return plan, oracle, extract, ("approx", "ties")


# --------------------------------------------------------------------------
# existence-join class (EXISTS / NOT EXISTS / OR-of-EXISTS), SMJ-planned
# --------------------------------------------------------------------------


def _sales_in_window(a, table, cust_col, date_col, moy_lo, moy_hi,
                     year=1999):
    """Subquery plan for EXISTS(SELECT * FROM <sales>, date_dim WHERE
    c_customer_sk = <cust> AND <date> = d_date_sk AND d_year = <y> AND
    d_moy BETWEEN lo AND hi) — projected to the correlation key, the shape
    Spark plans under the rewritten semi/anti/existence join."""
    dta = Attrs()
    dta.define("d_date_sk", "long")
    dta.define("d_year", "long")
    dta.define("d_moy", "long")
    s = scan(table, a, [cust_col, date_col])
    dt = filt(and_(eq(dta("d_year"), lit(year, "long")),
                   binop("GreaterThanOrEqual", dta("d_moy"),
                         lit(moy_lo, "long")),
                   binop("LessThanOrEqual", dta("d_moy"),
                         lit(moy_hi, "long"))),
              scan("date_dim", dta, ["d_date_sk", "d_year", "d_moy"]))
    j = bhj(s, bcast(dt), [a(date_col)], [dta("d_date_sk")])
    return project([a(cust_col)], j)


def _exists_customer_base(a, moy_lo, moy_hi, anti=False):
    """customer semi-joined to store_sales activity, then web/catalog
    activity as ExistenceJoins (q10/q35) or anti-joins (q69), all planned
    as SortMergeJoins over hash exchanges — Spark's plan for these
    large-to-large correlations."""
    for c in ("c_customer_sk", "c_current_cdemo_sk", "c_current_addr_sk"):
        a.define(c, "long")
    cu = scan("customer", a,
              ["c_customer_sk", "c_current_cdemo_sk", "c_current_addr_sk"])
    ss = _sales_in_window(a, "store_sales", "ss_customer_sk",
                          "ss_sold_date_sk", moy_lo, moy_hi)
    left = sorted_exchange(cu, [a("c_customer_sk")])
    right = sorted_exchange(ss, [a("ss_customer_sk")])
    j = smj(left, right, [a("c_customer_sk")], [a("ss_customer_sk")],
            jt="LeftSemi")
    ws = _sales_in_window(a, "web_sales", "ws_bill_customer_sk",
                          "ws_sold_date_sk", moy_lo, moy_hi)
    cs = _sales_in_window(a, "catalog_sales", "cs_bill_customer_sk",
                          "cs_sold_date_sk", moy_lo, moy_hi)
    if anti:
        j = smj(sorted_exchange(j, [a("c_customer_sk")]),
                sorted_exchange(ws, [a("ws_bill_customer_sk")]),
                [a("c_customer_sk")], [a("ws_bill_customer_sk")],
                jt="LeftAnti")
        j = smj(sorted_exchange(j, [a("c_customer_sk")]),
                sorted_exchange(cs, [a("cs_bill_customer_sk")]),
                [a("c_customer_sk")], [a("cs_bill_customer_sk")],
                jt="LeftAnti")
        return j, None, None
    e1, e2 = a.new_id(), a.new_id()
    j = smj(sorted_exchange(j, [a("c_customer_sk")]),
            sorted_exchange(ws, [a("ws_bill_customer_sk")]),
            [a("c_customer_sk")], [a("ws_bill_customer_sk")],
            jt=existence_join(e1))
    j = smj(sorted_exchange(j, [a("c_customer_sk")]),
            sorted_exchange(cs, [a("cs_bill_customer_sk")]),
            [a("c_customer_sk")], [a("cs_bill_customer_sk")],
            jt=existence_join(e2))
    ex1 = a.define_with_id("exists1", "boolean", e1)
    ex2 = a.define_with_id("exists2", "boolean", e2)
    return filt(or_(ex1, ex2), j), ex1, ex2


def _active_customers_oracle(dfs, moy_lo, moy_hi, anti=False):
    dd = dfs["date_dim"]
    dates = set(dd[(dd.d_year == 1999) & (dd.d_moy >= moy_lo)
                   & (dd.d_moy <= moy_hi)].d_date_sk)
    ss = dfs["store_sales"]
    ws = dfs["web_sales"]
    cs = dfs["catalog_sales"]
    in_ss = set(ss[ss.ss_sold_date_sk.isin(dates)].ss_customer_sk)
    in_ws = set(ws[ws.ws_sold_date_sk.isin(dates)].ws_bill_customer_sk)
    in_cs = set(cs[cs.cs_sold_date_sk.isin(dates)].cs_bill_customer_sk)
    cu = dfs["customer"]
    keep = cu.c_customer_sk.isin(in_ss)
    if anti:
        keep &= ~cu.c_customer_sk.isin(in_ws) & ~cu.c_customer_sk.isin(in_cs)
    else:
        keep &= cu.c_customer_sk.isin(in_ws) | cu.c_customer_sk.isin(in_cs)
    return cu[keep]


@query("q10")
def q10():
    """SELECT cd_gender, cd_marital_status, cd_education_status, count(*)
              cnt1, cd_purchase_estimate, count(*) cnt2, cd_credit_rating,
              count(*) cnt3, cd_dep_count, count(*) cnt4,
              cd_dep_employed_count, count(*) cnt5, cd_dep_college_count,
              count(*) cnt6
       FROM customer c, customer_address ca, customer_demographics
       WHERE c.c_current_addr_sk = ca.ca_address_sk
         AND ca_county IN ('county1','county2','county3','county4','county5')
         AND cd_demo_sk = c.c_current_cdemo_sk
         AND EXISTS (SELECT * FROM store_sales, date_dim
                     WHERE c.c_customer_sk = ss_customer_sk
                       AND ss_sold_date_sk = d_date_sk AND d_year = 1999
                       AND d_moy BETWEEN 1 AND 4)
         AND (EXISTS (SELECT * FROM web_sales, date_dim
                      WHERE c.c_customer_sk = ws_bill_customer_sk
                        AND ws_sold_date_sk = d_date_sk AND d_year = 1999
                        AND d_moy BETWEEN 1 AND 4)
           OR EXISTS (SELECT * FROM catalog_sales, date_dim
                      WHERE c.c_customer_sk = cs_bill_customer_sk
                        AND cs_sold_date_sk = d_date_sk AND d_year = 1999
                        AND d_moy BETWEEN 1 AND 4))
       GROUP BY cd_gender, cd_marital_status, cd_education_status,
                cd_purchase_estimate, cd_credit_rating, cd_dep_count,
                cd_dep_employed_count, cd_dep_college_count
       ORDER BY (the grouping columns) LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_customer_sk", "long"), ("ss_sold_date_sk", "long"),
                 ("ws_bill_customer_sk", "long"), ("ws_sold_date_sk", "long"),
                 ("cs_bill_customer_sk", "long"), ("cs_sold_date_sk", "long"),
                 ("ca_address_sk", "long"), ("ca_county", "string"),
                 ("cd_demo_sk", "long"), ("cd_gender", "string"),
                 ("cd_marital_status", "string"),
                 ("cd_education_status", "string"),
                 ("cd_purchase_estimate", "long"),
                 ("cd_credit_rating", "string"), ("cd_dep_count", "long"),
                 ("cd_dep_employed_count", "long"),
                 ("cd_dep_college_count", "long")]:
        a.define(c, t)
    counties = ["county1", "county2", "county3", "county4", "county5"]
    base, _e1, _e2 = _exists_customer_base(a, 1, 4)
    ca = filt(in_list(a("ca_county"), counties, "string"),
              scan("customer_address", a, ["ca_address_sk", "ca_county"]))
    cd = scan("customer_demographics", a,
              ["cd_demo_sk", "cd_gender", "cd_marital_status",
               "cd_education_status", "cd_purchase_estimate",
               "cd_credit_rating", "cd_dep_count", "cd_dep_employed_count",
               "cd_dep_college_count"])
    j = bhj(base, bcast(ca), [a("c_current_addr_sk")], [a("ca_address_sk")])
    j = bhj(j, bcast(cd), [a("c_current_cdemo_sk")], [a("cd_demo_sk")])
    groups = [a(c) for c in
              ("cd_gender", "cd_marital_status", "cd_education_status",
               "cd_purchase_estimate", "cd_credit_rating", "cd_dep_count",
               "cd_dep_employed_count", "cd_dep_college_count")]
    rids = [a.new_id() for _ in range(6)]
    agg = two_stage_agg([g for g in groups],
                        [("Count", rid, [lit(1, "integer")])
                         for rid in rids], j)
    cnts = [a.define_with_id(f"cnt{i + 1}", "long", rid)
            for i, rid in enumerate(rids)]
    plan = take_ordered(
        100, [sort_order(g) for g in groups],
        [groups[0], groups[1], groups[2], cnts[0], groups[3], cnts[1],
         groups[4], cnts[2], groups[5], cnts[3], groups[6], cnts[4],
         groups[7], cnts[5]], agg)

    def oracle(dfs):
        cu = _active_customers_oracle(dfs, 1, 4)
        ca = dfs["customer_address"]
        m = cu.merge(ca[ca.ca_county.isin(counties)],
                     left_on="c_current_addr_sk", right_on="ca_address_sk")
        m = m.merge(dfs["customer_demographics"],
                    left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
        gcols = ["cd_gender", "cd_marital_status", "cd_education_status",
                 "cd_purchase_estimate", "cd_credit_rating", "cd_dep_count",
                 "cd_dep_employed_count", "cd_dep_college_count"]
        g = m.groupby(gcols, as_index=False).size()
        g = g.sort_values(gcols, kind="stable").head(100)
        return [(r.cd_gender, r.cd_marital_status, r.cd_education_status,
                 r.size, r.cd_purchase_estimate, r.size, r.cd_credit_rating,
                 r.size, r.cd_dep_count, r.size, r.cd_dep_employed_count,
                 r.size, r.cd_dep_college_count, r.size)
                for r in g.itertuples(index=False)]

    return plan, oracle, None, ()


@query("q69")
def q69():
    """SELECT cd_gender, cd_marital_status, cd_education_status, count(*)
              cnt1, cd_purchase_estimate, count(*) cnt2, cd_credit_rating,
              count(*) cnt3
       FROM customer c, customer_address ca, customer_demographics
       WHERE c.c_current_addr_sk = ca.ca_address_sk
         AND ca_state IN ('CA','TX','OH')
         AND cd_demo_sk = c.c_current_cdemo_sk
         AND EXISTS (SELECT * FROM store_sales, date_dim
                     WHERE c.c_customer_sk = ss_customer_sk
                       AND ss_sold_date_sk = d_date_sk AND d_year = 1999
                       AND d_moy BETWEEN 1 AND 3)
         AND NOT EXISTS (SELECT * FROM web_sales, date_dim
                         WHERE c.c_customer_sk = ws_bill_customer_sk
                           AND ws_sold_date_sk = d_date_sk AND d_year = 1999
                           AND d_moy BETWEEN 1 AND 3)
         AND NOT EXISTS (SELECT * FROM catalog_sales, date_dim
                         WHERE c.c_customer_sk = cs_bill_customer_sk
                           AND cs_sold_date_sk = d_date_sk AND d_year = 1999
                           AND d_moy BETWEEN 1 AND 3)
       GROUP BY cd_gender, cd_marital_status, cd_education_status,
                cd_purchase_estimate, cd_credit_rating
       ORDER BY (the grouping columns) LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_customer_sk", "long"), ("ss_sold_date_sk", "long"),
                 ("ws_bill_customer_sk", "long"), ("ws_sold_date_sk", "long"),
                 ("cs_bill_customer_sk", "long"), ("cs_sold_date_sk", "long"),
                 ("ca_address_sk", "long"), ("ca_state", "string"),
                 ("cd_demo_sk", "long"), ("cd_gender", "string"),
                 ("cd_marital_status", "string"),
                 ("cd_education_status", "string"),
                 ("cd_purchase_estimate", "long"),
                 ("cd_credit_rating", "string")]:
        a.define(c, t)
    base, _, _ = _exists_customer_base(a, 1, 3, anti=True)
    ca = filt(in_list(a("ca_state"), ["CA", "TX", "OH"], "string"),
              scan("customer_address", a, ["ca_address_sk", "ca_state"]))
    cd = scan("customer_demographics", a,
              ["cd_demo_sk", "cd_gender", "cd_marital_status",
               "cd_education_status", "cd_purchase_estimate",
               "cd_credit_rating"])
    j = bhj(base, bcast(ca), [a("c_current_addr_sk")], [a("ca_address_sk")])
    j = bhj(j, bcast(cd), [a("c_current_cdemo_sk")], [a("cd_demo_sk")])
    groups = [a(c) for c in
              ("cd_gender", "cd_marital_status", "cd_education_status",
               "cd_purchase_estimate", "cd_credit_rating")]
    rids = [a.new_id() for _ in range(3)]
    agg = two_stage_agg([g for g in groups],
                        [("Count", rid, [lit(1, "integer")])
                         for rid in rids], j)
    cnts = [a.define_with_id(f"cnt{i + 1}", "long", rid)
            for i, rid in enumerate(rids)]
    plan = take_ordered(
        100, [sort_order(g) for g in groups],
        [groups[0], groups[1], groups[2], cnts[0], groups[3], cnts[1],
         groups[4], cnts[2]], agg)

    def oracle(dfs):
        cu = _active_customers_oracle(dfs, 1, 3, anti=True)
        ca = dfs["customer_address"]
        m = cu.merge(ca[ca.ca_state.isin(["CA", "TX", "OH"])],
                     left_on="c_current_addr_sk", right_on="ca_address_sk")
        m = m.merge(dfs["customer_demographics"],
                    left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
        gcols = ["cd_gender", "cd_marital_status", "cd_education_status",
                 "cd_purchase_estimate", "cd_credit_rating"]
        g = m.groupby(gcols, as_index=False).size()
        g = g.sort_values(gcols, kind="stable").head(100)
        return [(r.cd_gender, r.cd_marital_status, r.cd_education_status,
                 r.size, r.cd_purchase_estimate, r.size, r.cd_credit_rating,
                 r.size) for r in g.itertuples(index=False)]

    return plan, oracle, None, ()


@query("q35")
def q35():
    """SELECT ca_state, cd_gender, cd_marital_status, cd_dep_count,
              count(*) cnt1, avg(cd_dep_count), max(cd_dep_count),
              sum(cd_dep_count), cd_dep_employed_count, count(*) cnt2,
              avg(cd_dep_employed_count), max(cd_dep_employed_count),
              sum(cd_dep_employed_count), cd_dep_college_count, count(*)
              cnt3, avg(cd_dep_college_count), max(cd_dep_college_count),
              sum(cd_dep_college_count)
       FROM customer c, customer_address ca, customer_demographics
       WHERE c.c_current_addr_sk = ca.ca_address_sk
         AND cd_demo_sk = c.c_current_cdemo_sk
         AND EXISTS (store_sales activity, 1999 Q1)
         AND (EXISTS (web_sales activity) OR EXISTS (catalog_sales
              activity))
       GROUP BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
                cd_dep_employed_count, cd_dep_college_count
       ORDER BY (the grouping columns) LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_customer_sk", "long"), ("ss_sold_date_sk", "long"),
                 ("ws_bill_customer_sk", "long"), ("ws_sold_date_sk", "long"),
                 ("cs_bill_customer_sk", "long"), ("cs_sold_date_sk", "long"),
                 ("ca_address_sk", "long"), ("ca_state", "string"),
                 ("cd_demo_sk", "long"), ("cd_gender", "string"),
                 ("cd_marital_status", "string"), ("cd_dep_count", "long"),
                 ("cd_dep_employed_count", "long"),
                 ("cd_dep_college_count", "long")]:
        a.define(c, t)
    base, _, _ = _exists_customer_base(a, 1, 3)
    ca = scan("customer_address", a, ["ca_address_sk", "ca_state"])
    cd = scan("customer_demographics", a,
              ["cd_demo_sk", "cd_gender", "cd_marital_status",
               "cd_dep_count", "cd_dep_employed_count",
               "cd_dep_college_count"])
    j = bhj(base, bcast(ca), [a("c_current_addr_sk")], [a("ca_address_sk")])
    j = bhj(j, bcast(cd), [a("c_current_cdemo_sk")], [a("cd_demo_sk")])
    groups = [a(c) for c in
              ("ca_state", "cd_gender", "cd_marital_status", "cd_dep_count",
               "cd_dep_employed_count", "cd_dep_college_count")]
    dep_cols = ["cd_dep_count", "cd_dep_employed_count",
                "cd_dep_college_count"]
    agg_fns = []
    rid_map = {}
    for dc in dep_cols:
        for fn in ("Count", "Average", "Max", "Sum"):
            rid = a.new_id()
            rid_map[(dc, fn)] = rid
            args = [lit(1, "integer")] if fn == "Count" else [a(dc)]
            agg_fns.append((fn, rid, args))
    agg = two_stage_agg([g for g in groups], agg_fns, j)
    outs = []
    for i, dc in enumerate(dep_cols):
        outs.append(groups[3 + i])
        for fn, typ in (("Count", "long"), ("Average", "double"),
                        ("Max", "long"), ("Sum", "long")):
            outs.append(a.define_with_id(
                f"{fn.lower()}_{dc}", typ, rid_map[(dc, fn)]))
    plan = take_ordered(
        100, [sort_order(g) for g in groups],
        [groups[0], groups[1], groups[2]] + outs, agg)

    def oracle(dfs):
        cu = _active_customers_oracle(dfs, 1, 3)
        m = cu.merge(dfs["customer_address"], left_on="c_current_addr_sk",
                     right_on="ca_address_sk")
        m = m.merge(dfs["customer_demographics"],
                    left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
        gcols = ["ca_state", "cd_gender", "cd_marital_status",
                 "cd_dep_count", "cd_dep_employed_count",
                 "cd_dep_college_count"]
        g = m.groupby(gcols, as_index=False).size()
        g = g.sort_values(gcols, kind="stable").head(100)
        out = []
        for r in g.itertuples(index=False):
            row = [r.ca_state, r.cd_gender, r.cd_marital_status]
            for dc in ("cd_dep_count", "cd_dep_employed_count",
                       "cd_dep_college_count"):
                v = getattr(r, dc)
                row += [v, r.size, float(v), v, v * r.size]
            out.append(tuple(row))
        return out

    def extract(out):
        d = out.to_pydict()
        rows = []
        for vals in zip(*d.values()):
            rows.append(tuple(float(v) if isinstance(v, float) else v
                              for v in vals))
        return rows

    return plan, oracle, extract, ("approx",)


# --------------------------------------------------------------------------
# rank + lag/lead self-join class (q47 store / q57 catalog), SMJ-planned
# --------------------------------------------------------------------------


def _v1_monthly(channel: str):
    """The q47/q57 "v1" CTE: monthly sums per (item, seller) with
    avg-over-year and rank-over-time windows. Returns (plan, attrs,
    part_col_names) — built fresh per reference so the three self-join
    copies carry distinct exprIds, exactly like Spark's inlined CTE."""
    a = Attrs()
    if channel == "store":
        fact, item_k, date_k, price = ("store_sales", "ss_item_sk",
                                       "ss_sold_date_sk", "ss_sales_price")
        seller_k, seller_sk = "ss_store_sk", "s_store_sk"
        seller_cols = ["s_store_name", "s_company_name"]
        seller_tbl = "store"
    else:
        fact, item_k, date_k, price = ("catalog_sales", "cs_item_sk",
                                       "cs_sold_date_sk", "cs_sales_price")
        seller_k, seller_sk = "cs_call_center_sk", "cc_call_center_sk"
        seller_cols = ["cc_name"]
        seller_tbl = "call_center"
    for c, t in [(item_k, "long"), (date_k, "long"), (seller_k, "long"),
                 (price, "decimal(7,2)"),
                 ("i_item_sk", "long"), ("i_category", "string"),
                 ("i_brand", "string"),
                 ("d_date_sk", "long"), ("d_year", "long"),
                 ("d_moy", "long"), (seller_sk, "long")]:
        a.define(c, t)
    for c in seller_cols:
        a.define(c, "string")
    fs = scan(fact, a, [item_k, date_k, seller_k, price])
    it = scan("item", a, ["i_item_sk", "i_category", "i_brand"])
    dt = filt(or_(eq(a("d_year"), lit(1999, "long")),
                  and_(eq(a("d_year"), lit(1998, "long")),
                       eq(a("d_moy"), lit(12, "long")))),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_moy"]))
    sl = scan(seller_tbl, a, [seller_sk] + seller_cols)
    j = bhj(fs, bcast(it), [a(item_k)], [a("i_item_sk")])
    j = bhj(j, bcast(dt), [a(date_k)], [a("d_date_sk")])
    j = bhj(j, bcast(sl), [a(seller_k)], [a(seller_sk)])
    pcols = ["i_category", "i_brand"] + seller_cols
    rid = a.new_id()
    agg = two_stage_agg([a(c) for c in pcols + ["d_year", "d_moy"]],
                        [("Sum", rid, [a(price)])], j)
    ssum = a.define_with_id("sum_sales", "decimal(17,2)", rid)
    # window 1: avg over (partition cols, d_year); window 2: rank over
    # (partition cols) ordered by (d_year, d_moy). One hash exchange on the
    # partition cols satisfies both clustered distributions (Spark plans
    # exactly this: exchange + sort + Window + sort + Window)
    wid, rkid = a.new_id(), a.new_id()
    ch = exchange(agg, keys=[a(c) for c in pcols])
    ch = sort([sort_order(a(c)) for c in pcols + ["d_year"]], ch)
    win1 = window([_window_agg(a, "Average", ssum, "avg_monthly_sales",
                               wid)],
                  [a(c) for c in pcols + ["d_year"]], [], ch)
    wavg = a.define_with_id("avg_monthly_sales", "decimal(21,6)", wid)
    ch2 = sort([sort_order(a(c)) for c in pcols + ["d_year", "d_moy"]],
               win1)
    win2 = window([window_rank(a, "rn",
                               [sort_order(a("d_year")),
                                sort_order(a("d_moy"))], rkid)],
                  [a(c) for c in pcols],
                  [sort_order(a("d_year")), sort_order(a("d_moy"))], ch2)
    a.define_with_id("rn", "integer", rkid)
    return win2, a, pcols


def _deviation_self_join(channel):
    """q47/q57 body: v1 filtered to the deviating 1999 rows, self-joined
    with its rank-shifted lag and lead copies."""
    v1, a, pcols = _v1_monthly(channel)
    ssum, wavg, rn = a("sum_sales"), a("avg_monthly_sales"), a("rn")
    f1 = filt(and_(eq(a("d_year"), lit(1999, "long")),
                   _case_ratio_filter(ssum, wavg, a)), v1)
    lag, b, _ = _v1_monthly(channel)
    lead, c, _ = _v1_monthly(channel)
    lag_p = project([b(col) for col in pcols] + [b("rn"), b("sum_sales")],
                    lag)
    lead_p = project([c(col) for col in pcols] + [c("rn"), c("sum_sales")],
                     lead)
    lag_keys = [b(col) for col in pcols] + \
        [binop("Add", b("rn"), lit(1, "integer"))]
    lead_keys = [c(col) for col in pcols] + \
        [binop("Subtract", c("rn"), lit(1, "integer"))]
    main_keys = [a(col) for col in pcols] + [rn]
    j = smj(sorted_exchange(f1, main_keys),
            sorted_exchange(lag_p, lag_keys,
                            orders=[sort_order(k) for k in lag_keys]),
            main_keys, lag_keys)
    j = smj(sorted_exchange(j, main_keys),
            sorted_exchange(lead_p, lead_keys,
                            orders=[sort_order(k) for k in lead_keys]),
            main_keys, lead_keys)
    psum_id, nsum_id = a.new_id(), a.new_id()
    proj = project(
        [a(col) for col in pcols] + [a("d_year"), a("d_moy"), wavg, ssum] +
        [alias(b("sum_sales"), "psum", psum_id),
         alias(c("sum_sales"), "nsum", nsum_id)], j)
    a.define_with_id("psum", "decimal(17,2)", psum_id)
    a.define_with_id("nsum", "decimal(17,2)", nsum_id)
    order_col = pcols[2]  # s_store_name (q47) / cc_name (q57)
    plan = take_ordered(
        100,
        [sort_order(binop("Subtract", ssum, wavg)),
         sort_order(a(order_col))], [], proj)
    return plan, a, pcols


def _deviation_oracle(dfs, channel):
    dd = dfs["date_dim"]
    dd = dd[(dd.d_year == 1999) | ((dd.d_year == 1998) & (dd.d_moy == 12))]
    if channel == "store":
        m = dfs["store_sales"].merge(dfs["item"], left_on="ss_item_sk",
                                     right_on="i_item_sk")
        m = m.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(dfs["store"], left_on="ss_store_sk",
                    right_on="s_store_sk")
        pcols = ["i_category", "i_brand", "s_store_name", "s_company_name"]
        price = "ss_sales_price"
    else:
        m = dfs["catalog_sales"].merge(dfs["item"], left_on="cs_item_sk",
                                       right_on="i_item_sk")
        m = m.merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk")
        m = m.merge(dfs["call_center"], left_on="cs_call_center_sk",
                    right_on="cc_call_center_sk")
        pcols = ["i_category", "i_brand", "cc_name"]
        price = "cs_sales_price"
    g = m.groupby(pcols + ["d_year", "d_moy"], as_index=False)[price].sum()
    g["sum_sales"] = g[price].astype(float)
    g["avg_monthly_sales"] = g.groupby(
        pcols + ["d_year"]).sum_sales.transform("mean")
    g = g.sort_values(pcols + ["d_year", "d_moy"], kind="stable")
    g["rn"] = g.groupby(pcols).cumcount() + 1
    lag = g[pcols + ["rn", "sum_sales"]].copy()
    lag["rn"] = lag.rn + 1
    lag = lag.rename(columns={"sum_sales": "psum"})
    lead = g[pcols + ["rn", "sum_sales"]].copy()
    lead["rn"] = lead.rn - 1
    lead = lead.rename(columns={"sum_sales": "nsum"})
    v = g[(g.d_year == 1999) & (g.avg_monthly_sales > 0)
          & ((g.sum_sales - g.avg_monthly_sales).abs()
             / g.avg_monthly_sales > 0.1)]
    v = v.merge(lag, on=pcols + ["rn"]).merge(lead, on=pcols + ["rn"])
    v["delta"] = v.sum_sales - v.avg_monthly_sales
    v = v.sort_values(["delta", pcols[2]], kind="stable").head(100)
    return [tuple(list(r[c] for c in pcols) +
                  [int(r["d_year"]), int(r["d_moy"]),
                   round(float(r["avg_monthly_sales"]), 4),
                   round(float(r["sum_sales"]), 2),
                   round(float(r["psum"]), 2), round(float(r["nsum"]), 2)])
            for _, r in v.iterrows()]


def _deviation_extract(out):
    d = out.to_pydict()
    names = list(d)
    rows = []
    for vals in zip(*d.values()):
        row = list(vals)
        # (pcols..., d_year, d_moy, avg, sum, psum, nsum)
        k = len(row) - 6
        fixed = row[:k] + [int(row[k]), int(row[k + 1]),
                           round(float(row[k + 2]), 4),
                           round(float(row[k + 3]), 2),
                           round(float(row[k + 4]), 2),
                           round(float(row[k + 5]), 2)]
        rows.append(tuple(fixed))
    return rows


@query("q47")
def q47():
    """WITH v1 AS (SELECT i_category, i_brand, s_store_name, s_company_name,
              d_year, d_moy, sum(ss_sales_price) sum_sales,
              avg(sum(ss_sales_price)) OVER (PARTITION BY i_category,
                  i_brand, s_store_name, s_company_name, d_year)
                  avg_monthly_sales,
              rank() OVER (PARTITION BY i_category, i_brand, s_store_name,
                  s_company_name ORDER BY d_year, d_moy) rn
       FROM item, store_sales, date_dim, store
       WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
         AND ss_store_sk = s_store_sk
         AND (d_year = 1999 OR (d_year = 1998 AND d_moy = 12))
       GROUP BY i_category, i_brand, s_store_name, s_company_name, d_year,
                d_moy),
       v2 AS (SELECT v1.i_category, v1.i_brand, v1.s_store_name,
              v1.s_company_name, v1.d_year, v1.d_moy, v1.avg_monthly_sales,
              v1.sum_sales, v1_lag.sum_sales psum, v1_lead.sum_sales nsum
       FROM v1, v1 v1_lag, v1 v1_lead
       WHERE v1.i_category = v1_lag.i_category AND ... (4 cols each)
         AND v1.rn = v1_lag.rn + 1 AND v1.rn = v1_lead.rn - 1)
       SELECT * FROM v2 WHERE d_year = 1999 AND avg_monthly_sales > 0
         AND CASE WHEN avg_monthly_sales > 0 THEN abs(sum_sales -
             avg_monthly_sales) / avg_monthly_sales ELSE null END > 0.1
       ORDER BY sum_sales - avg_monthly_sales, s_store_name LIMIT 100"""
    plan, _a, _p = _deviation_self_join("store")
    return (plan, lambda dfs: _deviation_oracle(dfs, "store"),
            _deviation_extract, ("approx", "ties"))


@query("q57")
def q57():
    """The catalog-channel twin of q47: v1 over (i_category, i_brand,
       cc_name) from catalog_sales x call_center, same avg/rank windows,
       same lag/lead self-join, ORDER BY sum_sales - avg_monthly_sales,
       cc_name LIMIT 100."""
    plan, _a, _p = _deviation_self_join("catalog")
    return (plan, lambda dfs: _deviation_oracle(dfs, "catalog"),
            _deviation_extract, ("approx", "ties"))


# --------------------------------------------------------------------------
# UNION class
# --------------------------------------------------------------------------


@query("q33")
def q33():
    """WITH ss AS (SELECT i_manufact_id, sum(ss_ext_sales_price) total_sales
       FROM store_sales, date_dim, customer_address, item
       WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                               WHERE i_category IN ('Electronics'))
         AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
         AND d_year = 1998 AND d_moy = 5 AND ss_addr_sk = ca_address_sk
         AND ca_gmt_offset = -5.00 GROUP BY i_manufact_id),
       cs AS (... catalog_sales / cs_bill_addr_sk ...),
       ws AS (... web_sales / ws_bill_addr_sk ...)
       SELECT i_manufact_id, sum(total_sales) total_sales
       FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
             UNION ALL SELECT * FROM ws) tmp1
       GROUP BY i_manufact_id ORDER BY total_sales LIMIT 100"""
    def channel(fact, item_k, date_k, addr_k, price):
        a = Attrs()
        for col, t in [(item_k, "long"), (date_k, "long"), (addr_k, "long"),
                       (price, "decimal(7,2)"),
                       ("i_item_sk", "long"), ("i_manufact_id", "long"),
                       ("d_date_sk", "long"), ("d_year", "long"),
                       ("d_moy", "long"),
                       ("ca_address_sk", "long"),
                       ("ca_gmt_offset", "decimal(5,2)")]:
            a.define(col, t)
        fs = scan(fact, a, [item_k, date_k, addr_k, price])
        it = scan("item", a, ["i_item_sk", "i_manufact_id"])
        # IN (SELECT i_manufact_id FROM item WHERE i_category IN
        # ('Electronics')): LeftSemi BHJ against the filtered item copy
        b = Attrs()
        b.define("i_manufact_id", "long")
        b.define("i_category", "string")
        sub = project([b("i_manufact_id")],
                      filt(in_list(b("i_category"), ["Electronics"],
                                   "string"),
                           scan("item", b, ["i_manufact_id", "i_category"])))
        dt = filt(and_(eq(a("d_year"), lit(1998, "long")),
                       eq(a("d_moy"), lit(5, "long"))),
                  scan("date_dim", a, ["d_date_sk", "d_year", "d_moy"]))
        ca = filt(eq(a("ca_gmt_offset"), lit("-5.00", "decimal(5,2)")),
                  scan("customer_address", a,
                       ["ca_address_sk", "ca_gmt_offset"]))
        j = bhj(fs, bcast(it), [a(item_k)], [a("i_item_sk")])
        j = bhj(j, bcast(sub), [a("i_manufact_id")], [b("i_manufact_id")],
                jt="LeftSemi")
        j = bhj(j, bcast(dt), [a(date_k)], [a("d_date_sk")])
        j = bhj(j, bcast(ca), [a(addr_k)], [a("ca_address_sk")])
        rid = a.new_id()
        agg = two_stage_agg([a("i_manufact_id")],
                            [("Sum", rid, [a(price)])], j)
        return agg, a, rid

    ss_agg, a1, rid1 = channel("store_sales", "ss_item_sk",
                               "ss_sold_date_sk", "ss_addr_sk",
                               "ss_ext_sales_price")
    cs_agg, _a2, _r2 = channel("catalog_sales", "cs_item_sk",
                               "cs_sold_date_sk", "cs_bill_addr_sk",
                               "cs_ext_sales_price")
    ws_agg, _a3, _r3 = channel("web_sales", "ws_item_sk",
                               "ws_sold_date_sk", "ws_bill_addr_sk",
                               "ws_ext_sales_price")
    u = union_all(ss_agg, cs_agg, ws_agg)
    total1 = a1.define_with_id("total_sales", "decimal(17,2)", rid1)
    rid = a1.new_id()
    agg = two_stage_agg([a1("i_manufact_id")],
                        [("Sum", rid, [total1])], u)
    total = a1.define_with_id("total_sales_final", "decimal(27,2)", rid)
    plan = take_ordered(100, [sort_order(total)],
                        [a1("i_manufact_id"), total], agg)

    def oracle(dfs):
        import decimal as _dc

        dd = dfs["date_dim"]
        dd = dd[(dd.d_year == 1998) & (dd.d_moy == 5)]
        ca = dfs["customer_address"]
        ca = ca[ca.ca_gmt_offset == _dc.Decimal("-5.00")]
        it = dfs["item"]
        manu = set(it[it.i_category == "Electronics"].i_manufact_id)
        frames = []
        for fact, item_k, date_k, addr_k, price in (
                ("store_sales", "ss_item_sk", "ss_sold_date_sk",
                 "ss_addr_sk", "ss_ext_sales_price"),
                ("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                 "cs_bill_addr_sk", "cs_ext_sales_price"),
                ("web_sales", "ws_item_sk", "ws_sold_date_sk",
                 "ws_bill_addr_sk", "ws_ext_sales_price")):
            m = dfs[fact].merge(it, left_on=item_k, right_on="i_item_sk")
            m = m[m.i_manufact_id.isin(manu)]
            m = m.merge(dd, left_on=date_k, right_on="d_date_sk")
            m = m.merge(ca, left_on=addr_k, right_on="ca_address_sk")
            g = m.groupby("i_manufact_id", as_index=False)[price].sum()
            g = g.rename(columns={price: "total_sales"})
            frames.append(g)
        allg = pd.concat(frames, ignore_index=True)
        allg = allg.groupby("i_manufact_id", as_index=False).agg(
            total=("total_sales", "sum"))
        allg["total"] = allg.total.astype(float)
        allg = allg.sort_values(["total", "i_manufact_id"],
                                kind="stable").head(100)
        return [(int(r.i_manufact_id), round(r.total, 2))
                for r in allg.itertuples(index=False)]

    def extract(out):
        d = out.to_pydict()
        return [(int(k), round(float(v), 2))
                for k, v in zip(*list(d.values()))]

    return plan, oracle, extract, ("approx", "ties")


_DAYS = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
         "Saturday"]


def _wswscs(tag: str):
    """The q2 "wswscs" CTE: (web UNION ALL catalog) joined to date_dim,
    weekly sums pivoted into 7 day-name CASE columns. Fresh exprIds per
    copy (Spark inlines the CTE twice)."""
    from tests.tpcds.plans import X

    a = Attrs()
    for c, t in [("ws_sold_date_sk", "long"),
                 ("ws_ext_sales_price", "decimal(7,2)"),
                 ("cs_sold_date_sk", "long"),
                 ("cs_ext_sales_price", "decimal(7,2)"),
                 ("d_date_sk", "long"), ("d_week_seq", "long"),
                 ("d_day_name", "string")]:
        a.define(c, t)
    sd1, sp1 = a.new_id(), a.new_id()
    ws = project([alias(a("ws_sold_date_sk"), "sold_date_sk", sd1),
                  alias(a("ws_ext_sales_price"), "sales_price", sp1)],
                 scan("web_sales", a,
                      ["ws_sold_date_sk", "ws_ext_sales_price"]))
    sd2, sp2 = a.new_id(), a.new_id()
    cs = project([alias(a("cs_sold_date_sk"), "sold_date_sk", sd2),
                  alias(a("cs_ext_sales_price"), "sales_price", sp2)],
                 scan("catalog_sales", a,
                      ["cs_sold_date_sk", "cs_ext_sales_price"]))
    u = union_all(ws, cs)
    sold_date = a.define_with_id("sold_date_sk", "long", sd1)
    sales_price = a.define_with_id("sales_price", "decimal(7,2)", sp1)
    dt = scan("date_dim", a, ["d_date_sk", "d_week_seq", "d_day_name"])
    j = bhj(u, bcast(dt), [sold_date], [a("d_date_sk")])

    def case_day(day):
        return [{"class": f"{X}.CaseWhen", "num-children": 3,
                 "branches": None, "elseValue": None}] + \
            eq(a("d_day_name"), lit(day, "string")) + \
            sales_price + lit(None, "decimal(7,2)")

    rids = [a.new_id() for _ in _DAYS]
    agg = two_stage_agg([a("d_week_seq")],
                        [("Sum", rid, [case_day(day)])
                         for rid, day in zip(rids, _DAYS)], j)
    sums = [a.define_with_id(f"{tag}_{d.lower()[:3]}", "decimal(17,2)", rid)
            for rid, d in zip(rids, _DAYS)]
    return agg, a, sums


@query("q2")
def q2():
    """WITH wscs AS (SELECT ws_sold_date_sk sold_date_sk,
              ws_ext_sales_price sales_price FROM web_sales
            UNION ALL SELECT cs_sold_date_sk, cs_ext_sales_price
            FROM catalog_sales),
       wswscs AS (SELECT d_week_seq,
              sum(CASE WHEN d_day_name = 'Sunday' THEN sales_price END)
                  sun_sales, ... (Monday..Saturday alike)
       FROM wscs, date_dim WHERE d_date_sk = sold_date_sk
       GROUP BY d_week_seq)
       SELECT d_week_seq1, round(sun_sales1/sun_sales2, 2), ... (7 ratios)
       FROM (SELECT wswscs.d_week_seq d_week_seq1, sun_sales sun_sales1,
             ... FROM wswscs, date_dim
             WHERE date_dim.d_week_seq = wswscs.d_week_seq
               AND d_year = 1998) y,
            (SELECT wswscs.d_week_seq d_week_seq2, ... d_year = 1999) z
       WHERE d_week_seq1 = d_week_seq2 - 53
       ORDER BY d_week_seq1
       -- (the year qualification is planned as a LeftSemi on d_week_seq:
       --  the literal inner join against day-level date_dim emits 7
       --  byte-identical copies of every weekly row)"""
    y_agg, ya, ysums = _wswscs("y")
    z_agg, za, zsums = _wswscs("z")

    def year_filter(agg_frag, a, year):
        b = Attrs()
        b.define("d_date_sk", "long")
        b.define("d_week_seq", "long")
        b.define("d_year", "long")
        dt = filt(eq(b("d_year"), lit(year, "long")),
                  scan("date_dim", b, ["d_date_sk", "d_week_seq",
                                       "d_year"]))
        # wswscs rows qualified to weeks of the year: semi join on week_seq
        return bhj(agg_frag, bcast(project([b("d_week_seq")], dt)),
                   [a("d_week_seq")], [b("d_week_seq")], jt="LeftSemi")

    y = year_filter(y_agg, ya, 1998)
    z = year_filter(z_agg, za, 1999)
    j = smj(sorted_exchange(y, [ya("d_week_seq")]),
            sorted_exchange(z, [binop("Subtract", za("d_week_seq"),
                                      lit(53, "long"))],
                            orders=[sort_order(
                                binop("Subtract", za("d_week_seq"),
                                      lit(53, "long")))]),
            [ya("d_week_seq")],
            [binop("Subtract", za("d_week_seq"), lit(53, "long"))])
    ratios = []
    for i, d in enumerate(_DAYS):
        rid = ya.new_id()
        ratios.append(alias(
            sfn("Round", binop("Divide", ysums[i], zsums[i]),
                lit(2, "integer")),
            f"r_{d.lower()[:3]}", rid))
    proj = project([ya("d_week_seq")] + ratios, j)
    # global ORDER BY: range-partitioned exchange + sort (what Spark plans
    # for a SortExec with global=true; without it the 4 hash partitions
    # only sort locally)
    from tests.tpcds.plans import range_exchange

    plan = sort([sort_order(ya("d_week_seq"))],
                range_exchange(proj, [sort_order(ya("d_week_seq"))]))

    def oracle(dfs):
        dd = dfs["date_dim"]
        ws = dfs["web_sales"][["ws_sold_date_sk", "ws_ext_sales_price"]]
        cs = dfs["catalog_sales"][["cs_sold_date_sk",
                                   "cs_ext_sales_price"]]
        ws.columns = cs.columns = ["sold_date_sk", "sales_price"]
        u = pd.concat([ws, cs], ignore_index=True)
        m = u.merge(dd, left_on="sold_date_sk", right_on="d_date_sk")
        m["sales_price"] = m.sales_price.astype(float)
        piv = {}
        for d in _DAYS:
            piv[d] = m[m.d_day_name == d].groupby(
                "d_week_seq").sales_price.sum()
        import pandas as _pd

        wk = _pd.DataFrame(piv)
        y_weeks = set(dd[dd.d_year == 1998].d_week_seq)
        z_weeks = set(dd[dd.d_year == 1999].d_week_seq)
        out = []
        for w1 in sorted(set(wk.index) & y_weeks):
            w2 = w1 + 53
            if w2 not in wk.index or w2 not in z_weeks:
                continue
            row = [int(w1)]
            for d in _DAYS:
                a_v = wk.loc[w1, d] if d in wk.columns else None
                b_v = wk.loc[w2, d] if d in wk.columns else None
                if a_v is None or b_v is None or _pd.isna(a_v) \
                        or _pd.isna(b_v) or b_v == 0:
                    row.append(None)
                else:
                    row.append(round(a_v / b_v, 2))
            out.append(tuple(row))
        return out

    def extract(out):
        d = out.to_pydict()
        rows = []
        for vals in zip(*d.values()):
            row = [int(vals[0])]
            for v in vals[1:]:
                row.append(None if v is None else round(float(v), 2))
            rows.append(tuple(row))
        return rows

    return plan, oracle, extract, ("approx",)


# --------------------------------------------------------------------------
# HAVING-over-count class (q34/q73): a filter ABOVE the aggregate and a
# join ABOVE the aggregate — the "dn" derived-table pattern
# --------------------------------------------------------------------------


def _ticket_count_query(dom_ranges, cnt_lo, cnt_hi, vehicle_ratio):
    """Shared q34/q73 shape: per-ticket counts for qualifying household
    demographics and days, HAVING cnt BETWEEN lo AND hi, joined to
    customer above the aggregate."""
    a = Attrs()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_store_sk", "long"),
                 ("ss_hdemo_sk", "long"), ("ss_customer_sk", "long"),
                 ("ss_ticket_number", "long"),
                 ("d_date_sk", "long"), ("d_year", "long"), ("d_dom", "long"),
                 ("s_store_sk", "long"), ("s_county", "string"),
                 ("hd_demo_sk", "long"), ("hd_buy_potential", "string"),
                 ("hd_dep_count", "long"), ("hd_vehicle_count", "long"),
                 ("c_customer_sk", "long"), ("c_salutation", "string"),
                 ("c_first_name", "string"), ("c_last_name", "string"),
                 ("c_preferred_cust_flag", "string")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk",
               "ss_customer_sk", "ss_ticket_number"])
    # or_ returns its sole argument unchanged for a single range
    dom_cond = or_(*[and_(binop("GreaterThanOrEqual", a("d_dom"),
                               lit(lo, "long")),
                          binop("LessThanOrEqual", a("d_dom"),
                                lit(hi, "long")))
                     for lo, hi in dom_ranges])
    dt = filt(and_(dom_cond,
                   in_list(a("d_year"), [1998, 1999], "long")),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_dom"]))
    st = filt(in_list(a("s_county"),
                      ["county0", "county1", "county2", "county3"],
                      "string"),
              scan("store", a, ["s_store_sk", "s_county"]))
    # (hd_buy_potential = '>10000' OR 'Unknown') AND vehicle_count > 0 AND
    # dep/vehicle ratio > threshold — Spark casts the int division to double
    ratio = binop("Divide",
                  cast(a("hd_dep_count"), "double"),
                  cast(a("hd_vehicle_count"), "double"))
    hd = filt(and_(or_(eq(a("hd_buy_potential"), lit(">10000", "string")),
                       eq(a("hd_buy_potential"), lit("Unknown", "string"))),
                   and_(binop("GreaterThan", a("hd_vehicle_count"),
                              lit(0, "long")),
                        binop("GreaterThan", ratio,
                              lit(vehicle_ratio, "double")))),
              scan("household_demographics", a,
                   ["hd_demo_sk", "hd_buy_potential", "hd_dep_count",
                    "hd_vehicle_count"]))
    j = bhj(ss, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j = bhj(j, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    j = bhj(j, bcast(hd), [a("ss_hdemo_sk")], [a("hd_demo_sk")])
    rid = a.new_id()
    agg = two_stage_agg([a("ss_ticket_number"), a("ss_customer_sk")],
                        [("Count", rid, [lit(1, "integer")])], j)
    cnt = a.define_with_id("cnt", "long", rid)
    # HAVING: filter above the aggregate
    having = filt(and_(binop("GreaterThanOrEqual", cnt,
                             lit(cnt_lo, "long")),
                       binop("LessThanOrEqual", cnt,
                             lit(cnt_hi, "long"))), agg)
    cu = scan("customer", a,
              ["c_customer_sk", "c_salutation", "c_first_name",
               "c_last_name", "c_preferred_cust_flag"])
    j2 = bhj(having, bcast(cu), [a("ss_customer_sk")], [a("c_customer_sk")])
    plan = take_ordered(
        100,
        [sort_order(a("c_last_name")), sort_order(a("c_first_name")),
         sort_order(a("c_salutation")),
         sort_order(a("c_preferred_cust_flag"), asc=False),
         sort_order(a("ss_ticket_number"))],
        [a("c_last_name"), a("c_first_name"), a("c_salutation"),
         a("c_preferred_cust_flag"), a("ss_ticket_number"), cnt], j2)

    def oracle(dfs):
        dd = dfs["date_dim"]
        keep_dom = None
        for lo, hi in dom_ranges:
            m = (dd.d_dom >= lo) & (dd.d_dom <= hi)
            keep_dom = m if keep_dom is None else (keep_dom | m)
        dd = dd[keep_dom & dd.d_year.isin([1998, 1999])]
        st = dfs["store"]
        hd = dfs["household_demographics"]
        hd = hd[((hd.hd_buy_potential == ">10000")
                 | (hd.hd_buy_potential == "Unknown"))
                & (hd.hd_vehicle_count > 0)]
        hd = hd[hd.hd_dep_count / hd.hd_vehicle_count > vehicle_ratio]
        m = dfs["store_sales"].merge(dd, left_on="ss_sold_date_sk",
                                     right_on="d_date_sk")
        m = m.merge(st[st.s_county.isin(
            ["county0", "county1", "county2", "county3"])],
            left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        g = m.groupby(["ss_ticket_number", "ss_customer_sk"],
                      as_index=False).size()
        g = g[(g["size"] >= cnt_lo) & (g["size"] <= cnt_hi)]
        g = g.merge(dfs["customer"], left_on="ss_customer_sk",
                    right_on="c_customer_sk")
        g = g.sort_values(
            ["c_last_name", "c_first_name", "c_salutation",
             "c_preferred_cust_flag", "ss_ticket_number"],
            ascending=[True, True, True, False, True],
            kind="stable").head(100)
        return [(r.c_last_name, r.c_first_name, r.c_salutation,
                 r.c_preferred_cust_flag, r.ss_ticket_number, r["size"])
                for _, r in g.iterrows()]

    return plan, oracle, None, ("ties",)


@query("q34")
def q34():
    """SELECT c_last_name, c_first_name, c_salutation,
              c_preferred_cust_flag, ss_ticket_number, cnt
       FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
             FROM store_sales, date_dim, store, household_demographics
             WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
               AND ss_hdemo_sk = hd_demo_sk
               AND (d_dom BETWEEN 1 AND 3 OR d_dom BETWEEN 25 AND 28)
               AND (hd_buy_potential = '>10000' OR
                    hd_buy_potential = 'Unknown')
               AND hd_vehicle_count > 0
               AND hd_dep_count / hd_vehicle_count > 1.2
               AND d_year IN (1998, 1999) AND s_county IN (...)
             GROUP BY ss_ticket_number, ss_customer_sk) dn, customer
       WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN 2 AND 6
       ORDER BY c_last_name, c_first_name, c_salutation,
                c_preferred_cust_flag DESC, ss_ticket_number"""
    return _ticket_count_query([(1, 3), (25, 28)], 2, 6, 1.2)


@query("q73")
def q73():
    """The q34 twin over a single day-of-month window:
       d_dom BETWEEN 1 AND 2, ratio > 1.0, cnt BETWEEN 1 AND 5
       (reference q73 binds 1..2 / 1..5 with its own county list)."""
    return _ticket_count_query([(1, 2)], 1, 5, 1.0)


# --------------------------------------------------------------------------
# INTERSECT class (q38): DISTINCT aggregates + LeftSemi joins
# --------------------------------------------------------------------------


def _distinct_channel_customers(fact, cust_k, date_k):
    """One q38 leg: SELECT DISTINCT c_last_name, c_first_name, d_date —
    Spark plans the DISTINCT as a two-stage HashAggregate with NO
    aggregate expressions. Fresh exprIds per leg (each leg is its own
    subtree in the executed plan)."""
    a = Attrs()
    for c, t in [(cust_k, "long"), (date_k, "long"),
                 ("d_date_sk", "long"), ("d_month_seq", "long"),
                 ("d_date", "string"),
                 ("c_customer_sk", "long"), ("c_first_name", "string"),
                 ("c_last_name", "string")]:
        a.define(c, t)
    fs = scan(fact, a, [cust_k, date_k])
    dt = filt(and_(binop("GreaterThanOrEqual", a("d_month_seq"),
                         lit(1176, "long")),
                   binop("LessThanOrEqual", a("d_month_seq"),
                         lit(1187, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_month_seq", "d_date"]))
    cu = scan("customer", a,
              ["c_customer_sk", "c_first_name", "c_last_name"])
    j = bhj(fs, bcast(dt), [a(date_k)], [a("d_date_sk")])
    j = bhj(j, bcast(cu), [a(cust_k)], [a("c_customer_sk")])
    groups = [a("c_last_name"), a("c_first_name"), a("d_date")]
    # DISTINCT = two-stage aggregate with no aggregate expressions
    return two_stage_agg(groups, [], j), a


def _set_op_query(jt: str, reduce_sets):
    """Shared q38/q87 body: three per-channel DISTINCT legs chained by
    set-operation joins (INTERSECT -> LeftSemi, EXCEPT -> LeftAnti), then
    a global count."""
    ss_leg, a1 = _distinct_channel_customers(
        "store_sales", "ss_customer_sk", "ss_sold_date_sk")
    cs_leg, a2 = _distinct_channel_customers(
        "catalog_sales", "cs_bill_customer_sk", "cs_sold_date_sk")
    ws_leg, a3 = _distinct_channel_customers(
        "web_sales", "ws_bill_customer_sk", "ws_sold_date_sk")
    cols = ("c_last_name", "c_first_name", "d_date")
    j = smj(sorted_exchange(ss_leg, [a1(c) for c in cols]),
            sorted_exchange(cs_leg, [a2(c) for c in cols]),
            [a1(c) for c in cols], [a2(c) for c in cols], jt=jt)
    j = smj(sorted_exchange(j, [a1(c) for c in cols]),
            sorted_exchange(ws_leg, [a3(c) for c in cols]),
            [a1(c) for c in cols], [a3(c) for c in cols], jt=jt)
    rid = a1.new_id()
    partial = hash_agg([], [agg_expr("Count", "Partial", rid,
                                     [lit(1, "integer")])], j)
    plan = hash_agg([], [agg_expr("Count", "Final", rid,
                                  [lit(1, "integer")])],
                    exchange(partial, keys=None))

    def oracle(dfs):
        dd = dfs["date_dim"]
        dd = dd[(dd.d_month_seq >= 1176) & (dd.d_month_seq <= 1187)]
        cu = dfs["customer"]

        def leg(fact, cust_k, date_k):
            m = dfs[fact].merge(dd, left_on=date_k, right_on="d_date_sk")
            m = m.merge(cu, left_on=cust_k, right_on="c_customer_sk")
            return set(zip(m.c_last_name, m.c_first_name, m.d_date))

        ss = leg("store_sales", "ss_customer_sk", "ss_sold_date_sk")
        cs = leg("catalog_sales", "cs_bill_customer_sk", "cs_sold_date_sk")
        ws = leg("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk")
        return [(len(reduce_sets(ss, cs, ws)),)]

    return plan, oracle, None, ()


@query("q38")
def q38():
    """SELECT count(*) FROM (
         SELECT DISTINCT c_last_name, c_first_name, d_date
         FROM store_sales, date_dim, customer
         WHERE ss_sold_date_sk = d_date_sk
           AND ss_customer_sk = c_customer_sk
           AND d_month_seq BETWEEN 1176 AND 1187
       INTERSECT
         SELECT DISTINCT ... FROM catalog_sales ...
       INTERSECT
         SELECT DISTINCT ... FROM web_sales ...) hot_cust
       LIMIT 100
       -- Spark plans each INTERSECT as a LeftSemi join on the three
       -- distinct columns over the legs' HashAggregates"""
    return _set_op_query("LeftSemi", lambda ss, cs, ws: ss & cs & ws)


@query("q87")
def q87():
    """The q38 EXCEPT twin: store-channel distinct customers minus those
    in catalog, minus those in web — Spark plans each EXCEPT as a
    LeftAnti join over the legs' DISTINCT HashAggregates.
       SELECT count(*) FROM ((SELECT DISTINCT c_last_name, c_first_name,
       d_date FROM store_sales, date_dim, customer WHERE ...)
       EXCEPT (... catalog_sales ...) EXCEPT (... web_sales ...)) cool_cust"""
    return _set_op_query("LeftAnti", lambda ss, cs, ws: ss - cs - ws)

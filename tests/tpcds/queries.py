"""Real TPC-DS queries as Spark physical-plan fixtures + pandas oracles.

Each entry carries the GENUINE TPC-DS query text (template parameters bound
to the values the tiny dataset makes selective), the Spark ``toJSON``
physical plan a vanilla Spark session would produce for it (built with
tests/tpcds/plans.py in the exact wire form), and a pandas oracle. The gate
(tests/test_tpcds_queries.py) converts each plan through
``blaze_tpu.frontend`` — asserting full conversion, no fallbacks — executes
it, and compares against the oracle. Reference analogue: the 99-query
correctness workflow (``tpcds-reusable.yml``) validating against vanilla
Spark."""

from __future__ import annotations

from tests.tpcds.plans import (Attrs, agg_expr, alias, and_, bcast, bhj,
                               binop, cast, eq, exchange, filt, hash_agg,
                               in_list, isnotnull, lit, mul, or_, project,
                               scan, sort, sort_order, take_ordered,
                               two_stage_agg, window)

QUERIES = {}


def query(name):
    def reg(fn):
        QUERIES[name] = fn
        return fn
    return reg


def _dec_sort(df, cols, asc):
    return df.sort_values(cols, ascending=asc).reset_index(drop=True)


@query("q3")
def q3():
    """SELECT dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
              sum(ss_ext_sales_price) sum_agg
       FROM date_dim dt, store_sales, item
       WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
         AND store_sales.ss_item_sk = item.i_item_sk
         AND item.i_manufact_id = 28 AND dt.d_moy = 11
       GROUP BY dt.d_year, item.i_brand_id, item.i_brand
       ORDER BY dt.d_year, sum_agg DESC, brand_id
       LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_item_sk", "long"),
                 ("ss_ext_sales_price", "decimal(7,2)")]:
        a.define(c, t)
    for c, t in [("d_date_sk", "long"), ("d_year", "long"),
                 ("d_moy", "long")]:
        a.define(c, t)
    for c, t in [("i_item_sk", "long"), ("i_brand_id", "long"),
                 ("i_brand", "string"), ("i_manufact_id", "long")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    dt = filt(eq(a("d_moy"), lit(11, "long")),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_moy"]))
    it = filt(eq(a("i_manufact_id"), lit(28, "long")),
              scan("item", a, ["i_item_sk", "i_brand_id", "i_brand",
                               "i_manufact_id"]))
    j1 = bhj(ss, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j2 = bhj(j1, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    rid = a.new_id()
    agg = two_stage_agg([a("d_year"), a("i_brand_id"), a("i_brand")],
                        [("Sum", rid, [a("ss_ext_sales_price")])], j2)
    sum_attr = a.define_with_id("sum_agg", "decimal(17,2)", rid)
    plan = take_ordered(100, [sort_order(a("d_year")),
                              sort_order(sum_attr, asc=False),
                              sort_order(a("i_brand_id"))], [], agg)

    def oracle(dfs):
        m = dfs["store_sales"].merge(
            dfs["date_dim"][dfs["date_dim"].d_moy == 11],
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(dfs["item"][dfs["item"].i_manufact_id == 28],
                    left_on="ss_item_sk", right_on="i_item_sk")
        g = m.groupby(["d_year", "i_brand_id", "i_brand"],
                      as_index=False).ss_ext_sales_price.sum()
        g = g.sort_values(["d_year", "ss_ext_sales_price", "i_brand_id"],
                          ascending=[True, False, True],
                          kind="stable").head(100)
        return [tuple(r) for r in g.itertuples(index=False)]

    return plan, oracle, None, ("ties",)


@query("q42")
def q42():
    """SELECT dt.d_year, item.i_category_id, item.i_category,
              sum(ss_ext_sales_price)
       FROM date_dim dt, store_sales, item
       WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
         AND store_sales.ss_item_sk = item.i_item_sk
         AND item.i_manager_id = 1 AND dt.d_moy = 11 AND dt.d_year = 1998
       GROUP BY dt.d_year, item.i_category_id, item.i_category
       ORDER BY sum(ss_ext_sales_price) DESC, dt.d_year, i_category_id,
                i_category
       LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_item_sk", "long"),
                 ("ss_ext_sales_price", "decimal(7,2)"),
                 ("d_date_sk", "long"), ("d_year", "long"), ("d_moy", "long"),
                 ("i_item_sk", "long"), ("i_category_id", "long"),
                 ("i_category", "string"), ("i_manager_id", "long")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    dt = filt(and_(eq(a("d_moy"), lit(11, "long")),
                   eq(a("d_year"), lit(1998, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_moy"]))
    it = filt(eq(a("i_manager_id"), lit(1, "long")),
              scan("item", a, ["i_item_sk", "i_category_id", "i_category",
                               "i_manager_id"]))
    j1 = bhj(ss, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j2 = bhj(j1, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    rid = a.new_id()
    agg = two_stage_agg([a("d_year"), a("i_category_id"), a("i_category")],
                        [("Sum", rid, [a("ss_ext_sales_price")])], j2)
    s = a.define_with_id("sumprice", "decimal(17,2)", rid)
    plan = take_ordered(100, [sort_order(s, asc=False),
                              sort_order(a("d_year")),
                              sort_order(a("i_category_id")),
                              sort_order(a("i_category"))], [], agg)

    def oracle(dfs):
        dd = dfs["date_dim"]
        m = dfs["store_sales"].merge(
            dd[(dd.d_moy == 11) & (dd.d_year == 1998)],
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(dfs["item"][dfs["item"].i_manager_id == 1],
                    left_on="ss_item_sk", right_on="i_item_sk")
        g = m.groupby(["d_year", "i_category_id", "i_category"],
                      as_index=False).ss_ext_sales_price.sum()
        g = g.sort_values(
            ["ss_ext_sales_price", "d_year", "i_category_id", "i_category"],
            ascending=[False, True, True, True], kind="stable").head(100)
        return [(r.d_year, r.i_category_id, r.i_category,
                 r.ss_ext_sales_price) for r in g.itertuples(index=False)]

    return plan, oracle, None, ()


@query("q52")
def q52():
    """SELECT dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
              sum(ss_ext_sales_price) ext_price
       FROM date_dim dt, store_sales, item
       WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
         AND store_sales.ss_item_sk = item.i_item_sk
         AND item.i_manager_id = 1 AND dt.d_moy = 12 AND dt.d_year = 1998
       GROUP BY dt.d_year, item.i_brand_id, item.i_brand
       ORDER BY dt.d_year, ext_price DESC, brand_id
       LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_item_sk", "long"),
                 ("ss_ext_sales_price", "decimal(7,2)"),
                 ("d_date_sk", "long"), ("d_year", "long"), ("d_moy", "long"),
                 ("i_item_sk", "long"), ("i_brand_id", "long"),
                 ("i_brand", "string"), ("i_manager_id", "long")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    dt = filt(and_(eq(a("d_moy"), lit(12, "long")),
                   eq(a("d_year"), lit(1998, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_moy"]))
    it = filt(eq(a("i_manager_id"), lit(1, "long")),
              scan("item", a, ["i_item_sk", "i_brand_id", "i_brand",
                               "i_manager_id"]))
    j1 = bhj(ss, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j2 = bhj(j1, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    rid = a.new_id()
    agg = two_stage_agg([a("d_year"), a("i_brand_id"), a("i_brand")],
                        [("Sum", rid, [a("ss_ext_sales_price")])], j2)
    s = a.define_with_id("ext_price", "decimal(17,2)", rid)
    plan = take_ordered(100, [sort_order(a("d_year")),
                              sort_order(s, asc=False),
                              sort_order(a("i_brand_id"))], [], agg)

    def oracle(dfs):
        dd = dfs["date_dim"]
        m = dfs["store_sales"].merge(
            dd[(dd.d_moy == 12) & (dd.d_year == 1998)],
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(dfs["item"][dfs["item"].i_manager_id == 1],
                    left_on="ss_item_sk", right_on="i_item_sk")
        g = m.groupby(["d_year", "i_brand_id", "i_brand"],
                      as_index=False).ss_ext_sales_price.sum()
        g = g.sort_values(["d_year", "ss_ext_sales_price", "i_brand_id"],
                          ascending=[True, False, True],
                          kind="stable").head(100)
        return [tuple(r) for r in g.itertuples(index=False)]

    return plan, oracle, None, ("ties",)


@query("q55")
def q55():
    """SELECT i_brand_id brand_id, i_brand brand,
              sum(ss_ext_sales_price) ext_price
       FROM date_dim, store_sales, item
       WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
         AND i_manager_id = 13 AND d_moy = 11 AND d_year = 1999
       GROUP BY i_brand_id, i_brand
       ORDER BY ext_price DESC, i_brand_id
       LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_item_sk", "long"),
                 ("ss_ext_sales_price", "decimal(7,2)"),
                 ("d_date_sk", "long"), ("d_year", "long"), ("d_moy", "long"),
                 ("i_item_sk", "long"), ("i_brand_id", "long"),
                 ("i_brand", "string"), ("i_manager_id", "long")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    dt = filt(and_(eq(a("d_moy"), lit(11, "long")),
                   eq(a("d_year"), lit(1999, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_moy"]))
    it = filt(eq(a("i_manager_id"), lit(13, "long")),
              scan("item", a, ["i_item_sk", "i_brand_id", "i_brand",
                               "i_manager_id"]))
    j1 = bhj(ss, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j2 = bhj(j1, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    rid = a.new_id()
    agg = two_stage_agg([a("i_brand_id"), a("i_brand")],
                        [("Sum", rid, [a("ss_ext_sales_price")])], j2)
    s = a.define_with_id("ext_price", "decimal(17,2)", rid)
    plan = take_ordered(100, [sort_order(s, asc=False),
                              sort_order(a("i_brand_id"))], [], agg)

    def oracle(dfs):
        dd = dfs["date_dim"]
        m = dfs["store_sales"].merge(
            dd[(dd.d_moy == 11) & (dd.d_year == 1999)],
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(dfs["item"][dfs["item"].i_manager_id == 13],
                    left_on="ss_item_sk", right_on="i_item_sk")
        g = m.groupby(["i_brand_id", "i_brand"],
                      as_index=False).ss_ext_sales_price.sum()
        g = g.sort_values(["ss_ext_sales_price", "i_brand_id"],
                          ascending=[False, True], kind="stable").head(100)
        return [(r.i_brand_id, r.i_brand, r.ss_ext_sales_price)
                for r in g.itertuples(index=False)]

    return plan, oracle, None, ("ties",)


@query("q43")
def q43():
    """SELECT s_store_name, s_store_id,
              sum(case when (d_day_name='Sunday') then ss_sales_price else null end) sun_sales,
              sum(case when (d_day_name='Monday') then ss_sales_price else null end) mon_sales
       FROM date_dim, store_sales, store
       WHERE d_date_sk = ss_sold_date_sk AND s_store_sk = ss_store_sk
         AND s_gmt_offset = -5.00 AND d_year = 1998
       GROUP BY s_store_name, s_store_id
       ORDER BY s_store_name, s_store_id LIMIT 100
       -- (weekday CASE columns beyond Monday omit identically)"""
    a = Attrs()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_store_sk", "long"),
                 ("ss_sales_price", "decimal(7,2)"),
                 ("d_date_sk", "long"), ("d_year", "long"),
                 ("d_day_name", "string"),
                 ("s_store_sk", "long"), ("s_store_id", "string"),
                 ("s_store_name", "string")]:
        a.define(c, t)

    def case_day(day):
        # CASE WHEN d_day_name = day THEN ss_sales_price END
        from tests.tpcds.plans import X

        return [{"class": f"{X}.CaseWhen", "num-children": 3,
                 "branches": None, "elseValue": None}] + \
            eq(a("d_day_name"), lit(day, "string")) + \
            a("ss_sales_price") + lit(None, "decimal(7,2)")

    ss = scan("store_sales", a,
              ["ss_sold_date_sk", "ss_store_sk", "ss_sales_price"])
    dt = filt(eq(a("d_year"), lit(1998, "long")),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_day_name"]))
    st = scan("store", a, ["s_store_sk", "s_store_id", "s_store_name"])
    j1 = bhj(ss, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j2 = bhj(j1, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    r1, r2 = a.new_id(), a.new_id()
    agg = two_stage_agg([a("s_store_name"), a("s_store_id")],
                        [("Sum", r1, [case_day("Sunday")]),
                         ("Sum", r2, [case_day("Monday")])], j2)
    plan = take_ordered(100, [sort_order(a("s_store_name")),
                              sort_order(a("s_store_id"))], [], agg)

    def oracle(dfs):
        dd = dfs["date_dim"]
        m = dfs["store_sales"].merge(dd[dd.d_year == 1998],
                                     left_on="ss_sold_date_sk",
                                     right_on="d_date_sk")
        m = m.merge(dfs["store"], left_on="ss_store_sk",
                    right_on="s_store_sk")
        m["sun"] = m.ss_sales_price.where(m.d_day_name == "Sunday")
        m["mon"] = m.ss_sales_price.where(m.d_day_name == "Monday")
        g = m.groupby(["s_store_name", "s_store_id"], as_index=False).agg(
            sun=("sun", "sum"), mon=("mon", "sum"))
        g = g.sort_values(["s_store_name", "s_store_id"]).head(100)
        return [tuple(r) for r in g.itertuples(index=False)]

    return plan, oracle, None, ()


@query("q96")
def q96():
    """SELECT count(*)
       FROM store_sales, household_demographics, time_dim, store
       WHERE ss_sold_time_sk = time_dim.t_time_sk
         AND ss_hdemo_sk = hd_demo_sk AND ss_store_sk = s_store_sk
         AND time_dim.t_hour = 20 AND time_dim.t_minute >= 30
         AND household_demographics.hd_dep_count = 3
         AND store.s_store_name = 'store a'
       ORDER BY count(*) LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_sold_time_sk", "long"), ("ss_hdemo_sk", "long"),
                 ("ss_store_sk", "long"),
                 ("t_time_sk", "long"), ("t_hour", "long"),
                 ("t_minute", "long"),
                 ("hd_demo_sk", "long"), ("hd_dep_count", "long"),
                 ("s_store_sk", "long"), ("s_store_name", "string")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_time_sk", "ss_hdemo_sk", "ss_store_sk"])
    td = filt(and_(eq(a("t_hour"), lit(20, "long")),
                   binop("GreaterThanOrEqual", a("t_minute"),
                         lit(30, "long"))),
              scan("time_dim", a, ["t_time_sk", "t_hour", "t_minute"]))
    hd = filt(eq(a("hd_dep_count"), lit(3, "long")),
              scan("household_demographics", a,
                   ["hd_demo_sk", "hd_dep_count"]))
    st = filt(eq(a("s_store_name"), lit("store a", "string")),
              scan("store", a, ["s_store_sk", "s_store_name"]))
    j1 = bhj(ss, bcast(td), [a("ss_sold_time_sk")], [a("t_time_sk")])
    j2 = bhj(j1, bcast(hd), [a("ss_hdemo_sk")], [a("hd_demo_sk")])
    j3 = bhj(j2, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    rid = a.new_id()
    partial = hash_agg([], [agg_expr("Count", "Partial", rid,
                                     [lit(1, "integer")])], j3)
    ex = exchange(partial, keys=None)
    plan = hash_agg([], [agg_expr("Count", "Final", rid,
                                  [lit(1, "integer")])], ex)

    def oracle(dfs):
        td = dfs["time_dim"]
        hd = dfs["household_demographics"]
        st = dfs["store"]
        m = dfs["store_sales"].merge(
            td[(td.t_hour == 20) & (td.t_minute >= 30)],
            left_on="ss_sold_time_sk", right_on="t_time_sk")
        m = m.merge(hd[hd.hd_dep_count == 3],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(st[st.s_store_name == "store a"],
                    left_on="ss_store_sk", right_on="s_store_sk")
        return [(len(m),)]

    return plan, oracle, None, ()


@query("q7")
def q7():
    """SELECT i_item_id, avg(ss_quantity) agg1, avg(ss_list_price) agg2,
              avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
       FROM store_sales, customer_demographics, date_dim, item, promotion
       WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
         AND ss_cdemo_sk = cd_demo_sk AND ss_promo_sk = p_promo_sk
         AND cd_gender = 'M' AND cd_marital_status = 'S'
         AND cd_education_status = 'College'
         AND (p_channel_email = 'N' OR p_channel_tv = 'N')
         AND d_year = 1998
       GROUP BY i_item_id ORDER BY i_item_id LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_item_sk", "long"),
                 ("ss_cdemo_sk", "long"), ("ss_promo_sk", "long"),
                 ("ss_quantity", "long"), ("ss_list_price", "decimal(7,2)"),
                 ("ss_coupon_amt", "decimal(7,2)"),
                 ("ss_sales_price", "decimal(7,2)"),
                 ("cd_demo_sk", "long"), ("cd_gender", "string"),
                 ("cd_marital_status", "string"),
                 ("cd_education_status", "string"),
                 ("d_date_sk", "long"), ("d_year", "long"),
                 ("i_item_sk", "long"), ("i_item_id", "string"),
                 ("p_promo_sk", "long"), ("p_channel_email", "string"),
                 ("p_channel_tv", "string")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk", "ss_promo_sk",
               "ss_quantity", "ss_list_price", "ss_coupon_amt",
               "ss_sales_price"])
    cd = filt(and_(eq(a("cd_gender"), lit("M", "string")),
                   eq(a("cd_marital_status"), lit("S", "string")),
                   eq(a("cd_education_status"), lit("College", "string"))),
              scan("customer_demographics", a,
                   ["cd_demo_sk", "cd_gender", "cd_marital_status",
                    "cd_education_status"]))
    dt = filt(eq(a("d_year"), lit(1998, "long")),
              scan("date_dim", a, ["d_date_sk", "d_year"]))
    it = scan("item", a, ["i_item_sk", "i_item_id"])
    pr = filt(or_(eq(a("p_channel_email"), lit("N", "string")),
                  eq(a("p_channel_tv"), lit("N", "string"))),
              scan("promotion", a, ["p_promo_sk", "p_channel_email",
                                    "p_channel_tv"]))
    j = bhj(ss, bcast(cd), [a("ss_cdemo_sk")], [a("cd_demo_sk")])
    j = bhj(j, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j = bhj(j, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    j = bhj(j, bcast(pr), [a("ss_promo_sk")], [a("p_promo_sk")])
    rids = [a.new_id() for _ in range(4)]
    agg = two_stage_agg([a("i_item_id")],
                        [("Average", rids[0], [a("ss_quantity")]),
                         ("Average", rids[1], [a("ss_list_price")]),
                         ("Average", rids[2], [a("ss_coupon_amt")]),
                         ("Average", rids[3], [a("ss_sales_price")])], j)
    plan = take_ordered(100, [sort_order(a("i_item_id"))], [], agg)

    def oracle(dfs):
        cd = dfs["customer_demographics"]
        pr = dfs["promotion"]
        dd = dfs["date_dim"]
        m = dfs["store_sales"].merge(
            cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
               & (cd.cd_education_status == "College")],
            left_on="ss_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(dd[dd.d_year == 1998], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
        m = m.merge(dfs["item"], left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(pr[(pr.p_channel_email == "N")
                       | (pr.p_channel_tv == "N")],
                    left_on="ss_promo_sk", right_on="p_promo_sk")
        for c in ("ss_list_price", "ss_coupon_amt", "ss_sales_price"):
            m[c] = m[c].astype(float)
        g = m.groupby("i_item_id", as_index=False).agg(
            a1=("ss_quantity", "mean"), a2=("ss_list_price", "mean"),
            a3=("ss_coupon_amt", "mean"), a4=("ss_sales_price", "mean"))
        g = g.sort_values("i_item_id").head(100)
        return [tuple(r) for r in g.itertuples(index=False)]

    return plan, oracle, None, ("approx",)


@query("q26")
def q26():
    """SELECT i_item_id, avg(cs_quantity) agg1, avg(cs_list_price) agg2,
              avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
       FROM catalog_sales, customer_demographics, date_dim, item, promotion
       WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
         AND cs_bill_cdemo_sk = cd_demo_sk AND cs_promo_sk = p_promo_sk
         AND cd_gender = 'F' AND cd_marital_status = 'W'
         AND cd_education_status = 'Primary'
         AND (p_channel_email = 'N' OR p_channel_tv = 'N')
         AND d_year = 1999
       GROUP BY i_item_id ORDER BY i_item_id LIMIT 100"""
    a = Attrs()
    for c, t in [("cs_sold_date_sk", "long"), ("cs_item_sk", "long"),
                 ("cs_bill_cdemo_sk", "long"), ("cs_promo_sk", "long"),
                 ("cs_quantity", "long"), ("cs_list_price", "decimal(7,2)"),
                 ("cs_coupon_amt", "decimal(7,2)"),
                 ("cs_sales_price", "decimal(7,2)"),
                 ("cd_demo_sk", "long"), ("cd_gender", "string"),
                 ("cd_marital_status", "string"),
                 ("cd_education_status", "string"),
                 ("d_date_sk", "long"), ("d_year", "long"),
                 ("i_item_sk", "long"), ("i_item_id", "string"),
                 ("p_promo_sk", "long"), ("p_channel_email", "string"),
                 ("p_channel_tv", "string")]:
        a.define(c, t)
    cs = scan("catalog_sales", a,
              ["cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk",
               "cs_promo_sk", "cs_quantity", "cs_list_price",
               "cs_coupon_amt", "cs_sales_price"])
    cd = filt(and_(eq(a("cd_gender"), lit("F", "string")),
                   eq(a("cd_marital_status"), lit("W", "string")),
                   eq(a("cd_education_status"), lit("Primary", "string"))),
              scan("customer_demographics", a,
                   ["cd_demo_sk", "cd_gender", "cd_marital_status",
                    "cd_education_status"]))
    dt = filt(eq(a("d_year"), lit(1999, "long")),
              scan("date_dim", a, ["d_date_sk", "d_year"]))
    it = scan("item", a, ["i_item_sk", "i_item_id"])
    pr = filt(or_(eq(a("p_channel_email"), lit("N", "string")),
                  eq(a("p_channel_tv"), lit("N", "string"))),
              scan("promotion", a, ["p_promo_sk", "p_channel_email",
                                    "p_channel_tv"]))
    j = bhj(cs, bcast(cd), [a("cs_bill_cdemo_sk")], [a("cd_demo_sk")])
    j = bhj(j, bcast(dt), [a("cs_sold_date_sk")], [a("d_date_sk")])
    j = bhj(j, bcast(it), [a("cs_item_sk")], [a("i_item_sk")])
    j = bhj(j, bcast(pr), [a("cs_promo_sk")], [a("p_promo_sk")])
    rids = [a.new_id() for _ in range(4)]
    agg = two_stage_agg([a("i_item_id")],
                        [("Average", rids[0], [a("cs_quantity")]),
                         ("Average", rids[1], [a("cs_list_price")]),
                         ("Average", rids[2], [a("cs_coupon_amt")]),
                         ("Average", rids[3], [a("cs_sales_price")])], j)
    plan = take_ordered(100, [sort_order(a("i_item_id"))], [], agg)

    def oracle(dfs):
        cd = dfs["customer_demographics"]
        pr = dfs["promotion"]
        dd = dfs["date_dim"]
        m = dfs["catalog_sales"].merge(
            cd[(cd.cd_gender == "F") & (cd.cd_marital_status == "W")
               & (cd.cd_education_status == "Primary")],
            left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(dd[dd.d_year == 1999], left_on="cs_sold_date_sk",
                    right_on="d_date_sk")
        m = m.merge(dfs["item"], left_on="cs_item_sk", right_on="i_item_sk")
        m = m.merge(pr[(pr.p_channel_email == "N")
                       | (pr.p_channel_tv == "N")],
                    left_on="cs_promo_sk", right_on="p_promo_sk")
        for c in ("cs_list_price", "cs_coupon_amt", "cs_sales_price"):
            m[c] = m[c].astype(float)
        g = m.groupby("i_item_id", as_index=False).agg(
            a1=("cs_quantity", "mean"), a2=("cs_list_price", "mean"),
            a3=("cs_coupon_amt", "mean"), a4=("cs_sales_price", "mean"))
        g = g.sort_values("i_item_id").head(100)
        return [tuple(r) for r in g.itertuples(index=False)]

    return plan, oracle, None, ("approx",)


@query("q48")
def q48():
    """SELECT sum(ss_quantity)
       FROM store_sales, store, customer_demographics, customer_address,
            date_dim
       WHERE s_store_sk = ss_store_sk AND ss_sold_date_sk = d_date_sk
         AND d_year = 1998 AND ss_cdemo_sk = cd_demo_sk
         AND ss_addr_sk = ca_address_sk AND ca_country = 'United States'
         AND ((cd_marital_status = 'M' AND cd_education_status = '4 yr Degree'
               AND ss_sales_price BETWEEN 100.00 AND 150.00)
           OR (cd_marital_status = 'D' AND cd_education_status = '2 yr Degree'
               AND ss_sales_price BETWEEN 50.00 AND 100.00))
         AND (ca_state IN ('CA','TX') OR ca_state IN ('OH','GA'))"""
    a = Attrs()
    for c, t in [("ss_store_sk", "long"), ("ss_sold_date_sk", "long"),
                 ("ss_cdemo_sk", "long"), ("ss_addr_sk", "long"),
                 ("ss_quantity", "long"),
                 ("ss_sales_price", "decimal(7,2)"),
                 ("s_store_sk", "long"),
                 ("cd_demo_sk", "long"), ("cd_marital_status", "string"),
                 ("cd_education_status", "string"),
                 ("ca_address_sk", "long"), ("ca_state", "string"),
                 ("ca_country", "string"),
                 ("d_date_sk", "long"), ("d_year", "long")]:
        a.define(c, t)

    def between(col, lo, hi):
        return and_(binop("GreaterThanOrEqual", a(col),
                          lit(lo, "decimal(7,2)")),
                    binop("LessThanOrEqual", a(col),
                          lit(hi, "decimal(7,2)")))

    ss = scan("store_sales", a,
              ["ss_store_sk", "ss_sold_date_sk", "ss_cdemo_sk", "ss_addr_sk",
               "ss_quantity", "ss_sales_price"])
    st = scan("store", a, ["s_store_sk"])
    cd = scan("customer_demographics", a,
              ["cd_demo_sk", "cd_marital_status", "cd_education_status"])
    ca = filt(eq(a("ca_country"), lit("United States", "string")),
              scan("customer_address", a,
                   ["ca_address_sk", "ca_state", "ca_country"]))
    dt = filt(eq(a("d_year"), lit(1998, "long")),
              scan("date_dim", a, ["d_date_sk", "d_year"]))
    j = bhj(ss, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    j = bhj(j, bcast(cd), [a("ss_cdemo_sk")], [a("cd_demo_sk")])
    j = bhj(j, bcast(ca), [a("ss_addr_sk")], [a("ca_address_sk")])
    j = bhj(j, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    cond = and_(
        or_(and_(eq(a("cd_marital_status"), lit("M", "string")),
                 eq(a("cd_education_status"), lit("4 yr Degree", "string")),
                 between("ss_sales_price", "100.00", "150.00")),
            and_(eq(a("cd_marital_status"), lit("D", "string")),
                 eq(a("cd_education_status"), lit("2 yr Degree", "string")),
                 between("ss_sales_price", "50.00", "100.00"))),
        or_(in_list(a("ca_state"), ["CA", "TX"], "string"),
            in_list(a("ca_state"), ["OH", "GA"], "string")))
    f = filt(cond, j)
    rid = a.new_id()
    partial = hash_agg([], [agg_expr("Sum", "Partial", rid,
                                     [a("ss_quantity")])], f)
    plan = hash_agg([], [agg_expr("Sum", "Final", rid, [a("ss_quantity")])],
                    exchange(partial, keys=None))

    def oracle(dfs):
        dd = dfs["date_dim"]
        ca = dfs["customer_address"]
        m = dfs["store_sales"].merge(dfs["store"], left_on="ss_store_sk",
                                     right_on="s_store_sk")
        m = m.merge(dfs["customer_demographics"], left_on="ss_cdemo_sk",
                    right_on="cd_demo_sk")
        m = m.merge(ca[ca.ca_country == "United States"],
                    left_on="ss_addr_sk", right_on="ca_address_sk")
        m = m.merge(dd[dd.d_year == 1998], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
        import decimal as _dc
        sp = m.ss_sales_price
        c1 = ((m.cd_marital_status == "M")
              & (m.cd_education_status == "4 yr Degree")
              & (sp >= _dc.Decimal("100.00")) & (sp <= _dc.Decimal("150.00")))
        c2 = ((m.cd_marital_status == "D")
              & (m.cd_education_status == "2 yr Degree")
              & (sp >= _dc.Decimal("50.00")) & (sp <= _dc.Decimal("100.00")))
        m = m[(c1 | c2) & m.ca_state.isin(["CA", "TX", "OH", "GA"])]
        return [(int(m.ss_quantity.sum()),)]

    return plan, oracle, None, ()


from tests.tpcds.plans import not_, sfn  # noqa: E402


@query("q27")
def q27():
    """SELECT i_item_id, s_state, avg(ss_quantity) agg1,
              avg(ss_list_price) agg2, avg(ss_coupon_amt) agg3,
              avg(ss_sales_price) agg4
       FROM store_sales, customer_demographics, date_dim, store, item
       WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
         AND ss_store_sk = s_store_sk AND ss_cdemo_sk = cd_demo_sk
         AND cd_gender = 'F' AND cd_marital_status = 'D'
         AND cd_education_status = 'College' AND d_year = 1999
         AND s_state IN ('TN','SD')
       GROUP BY i_item_id, s_state ORDER BY i_item_id, s_state LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_item_sk", "long"),
                 ("ss_store_sk", "long"), ("ss_cdemo_sk", "long"),
                 ("ss_quantity", "long"), ("ss_list_price", "decimal(7,2)"),
                 ("ss_coupon_amt", "decimal(7,2)"),
                 ("ss_sales_price", "decimal(7,2)"),
                 ("cd_demo_sk", "long"), ("cd_gender", "string"),
                 ("cd_marital_status", "string"),
                 ("cd_education_status", "string"),
                 ("d_date_sk", "long"), ("d_year", "long"),
                 ("s_store_sk", "long"), ("s_state", "string"),
                 ("i_item_sk", "long"), ("i_item_id", "string")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_cdemo_sk",
               "ss_quantity", "ss_list_price", "ss_coupon_amt",
               "ss_sales_price"])
    cd = filt(and_(eq(a("cd_gender"), lit("F", "string")),
                   eq(a("cd_marital_status"), lit("D", "string")),
                   eq(a("cd_education_status"), lit("College", "string"))),
              scan("customer_demographics", a,
                   ["cd_demo_sk", "cd_gender", "cd_marital_status",
                    "cd_education_status"]))
    dt = filt(eq(a("d_year"), lit(1999, "long")),
              scan("date_dim", a, ["d_date_sk", "d_year"]))
    st = filt(in_list(a("s_state"), ["TN", "SD"], "string"),
              scan("store", a, ["s_store_sk", "s_state"]))
    it = scan("item", a, ["i_item_sk", "i_item_id"])
    j = bhj(ss, bcast(cd), [a("ss_cdemo_sk")], [a("cd_demo_sk")])
    j = bhj(j, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j = bhj(j, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    j = bhj(j, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    rids = [a.new_id() for _ in range(4)]
    agg = two_stage_agg([a("i_item_id"), a("s_state")],
                        [("Average", rids[0], [a("ss_quantity")]),
                         ("Average", rids[1], [a("ss_list_price")]),
                         ("Average", rids[2], [a("ss_coupon_amt")]),
                         ("Average", rids[3], [a("ss_sales_price")])], j)
    plan = take_ordered(100, [sort_order(a("i_item_id")),
                              sort_order(a("s_state"))], [], agg)

    def oracle(dfs):
        cd = dfs["customer_demographics"]
        dd = dfs["date_dim"]
        st = dfs["store"]
        m = dfs["store_sales"].merge(
            cd[(cd.cd_gender == "F") & (cd.cd_marital_status == "D")
               & (cd.cd_education_status == "College")],
            left_on="ss_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(dd[dd.d_year == 1999], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
        m = m.merge(st[st.s_state.isin(["TN", "SD"])],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(dfs["item"], left_on="ss_item_sk", right_on="i_item_sk")
        for c in ("ss_list_price", "ss_coupon_amt", "ss_sales_price"):
            m[c] = m[c].astype(float)
        g = m.groupby(["i_item_id", "s_state"], as_index=False).agg(
            a1=("ss_quantity", "mean"), a2=("ss_list_price", "mean"),
            a3=("ss_coupon_amt", "mean"), a4=("ss_sales_price", "mean"))
        g = g.sort_values(["i_item_id", "s_state"]).head(100)
        return [tuple(r) for r in g.itertuples(index=False)]

    return plan, oracle, None, ("approx",)


@query("q15")
def q15():
    """SELECT ca_zip, sum(cs_sales_price)
       FROM catalog_sales, customer, customer_address, date_dim
       WHERE cs_bill_customer_sk = c_customer_sk
         AND c_current_addr_sk = ca_address_sk
         AND (substr(ca_zip,1,5) IN ('24007','24014','24021','25003',
                                     '30009','45011','60013','81788')
              OR ca_state IN ('CA','WA','GA') OR cs_sales_price > 500)
         AND cs_sold_date_sk = d_date_sk AND d_qoy = 1 AND d_year = 1999
       GROUP BY ca_zip ORDER BY ca_zip LIMIT 100"""
    a = Attrs()
    for c, t in [("cs_bill_customer_sk", "long"), ("cs_sold_date_sk", "long"),
                 ("cs_sales_price", "decimal(7,2)"),
                 ("c_customer_sk", "long"), ("c_current_addr_sk", "long"),
                 ("ca_address_sk", "long"), ("ca_zip", "string"),
                 ("ca_state", "string"),
                 ("d_date_sk", "long"), ("d_year", "long"),
                 ("d_qoy", "long")]:
        a.define(c, t)
    zips = ["24007", "24014", "24021", "25003", "30009", "45011", "60013",
            "81788"]
    cs = scan("catalog_sales", a,
              ["cs_bill_customer_sk", "cs_sold_date_sk", "cs_sales_price"])
    cu = scan("customer", a, ["c_customer_sk", "c_current_addr_sk"])
    ca = scan("customer_address", a, ["ca_address_sk", "ca_zip", "ca_state"])
    dt = filt(and_(eq(a("d_qoy"), lit(1, "long")),
                   eq(a("d_year"), lit(1999, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_qoy"]))
    j = bhj(cs, bcast(cu), [a("cs_bill_customer_sk")], [a("c_customer_sk")])
    j = bhj(j, bcast(ca), [a("c_current_addr_sk")], [a("ca_address_sk")])
    j = bhj(j, bcast(dt), [a("cs_sold_date_sk")], [a("d_date_sk")])
    cond = or_(
        in_list(sfn("Substring", a("ca_zip"), lit(1, "integer"),
                    lit(5, "integer")), zips, "string"),
        in_list(a("ca_state"), ["CA", "WA", "GA"], "string"),
        binop("GreaterThan", a("cs_sales_price"),
              lit("500.00", "decimal(7,2)")))
    f = filt(cond, j)
    rid = a.new_id()
    agg = two_stage_agg([a("ca_zip")],
                        [("Sum", rid, [a("cs_sales_price")])], f)
    plan = take_ordered(100, [sort_order(a("ca_zip"))], [], agg)

    def oracle(dfs):
        import decimal as _dc

        dd = dfs["date_dim"]
        m = dfs["catalog_sales"].merge(dfs["customer"],
                                       left_on="cs_bill_customer_sk",
                                       right_on="c_customer_sk")
        m = m.merge(dfs["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
        m = m.merge(dd[(dd.d_qoy == 1) & (dd.d_year == 1999)],
                    left_on="cs_sold_date_sk", right_on="d_date_sk")
        keep = (m.ca_zip.str[:5].isin(zips)
                | m.ca_state.isin(["CA", "WA", "GA"])
                | (m.cs_sales_price > _dc.Decimal("500.00")))
        g = m[keep].groupby("ca_zip", as_index=False).cs_sales_price.sum()
        g = g.sort_values("ca_zip").head(100)
        return [tuple(r) for r in g.itertuples(index=False)]

    return plan, oracle, None, ()


@query("q19")
def q19():
    """SELECT i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
              sum(ss_ext_sales_price) ext_price
       FROM date_dim, store_sales, item, customer, customer_address, store
       WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
         AND i_manager_id = 7 AND d_moy = 11 AND d_year = 1999
         AND ss_customer_sk = c_customer_sk
         AND c_current_addr_sk = ca_address_sk
         AND substr(ca_zip,1,5) <> substr(s_zip,1,5)
         AND ss_store_sk = s_store_sk
       GROUP BY i_brand, i_brand_id, i_manufact_id, i_manufact
       ORDER BY ext_price DESC, i_brand, i_brand_id, i_manufact_id,
                i_manufact LIMIT 100"""
    a = Attrs()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_item_sk", "long"),
                 ("ss_customer_sk", "long"), ("ss_store_sk", "long"),
                 ("ss_ext_sales_price", "decimal(7,2)"),
                 ("d_date_sk", "long"), ("d_year", "long"), ("d_moy", "long"),
                 ("i_item_sk", "long"), ("i_brand_id", "long"),
                 ("i_brand", "string"), ("i_manufact_id", "long"),
                 ("i_manufact", "string"), ("i_manager_id", "long"),
                 ("c_customer_sk", "long"), ("c_current_addr_sk", "long"),
                 ("ca_address_sk", "long"), ("ca_zip", "string"),
                 ("s_store_sk", "long"), ("s_zip", "string")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
               "ss_store_sk", "ss_ext_sales_price"])
    dt = filt(and_(eq(a("d_moy"), lit(11, "long")),
                   eq(a("d_year"), lit(1999, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_moy"]))
    it = filt(eq(a("i_manager_id"), lit(7, "long")),
              scan("item", a, ["i_item_sk", "i_brand_id", "i_brand",
                               "i_manufact_id", "i_manufact",
                               "i_manager_id"]))
    cu = scan("customer", a, ["c_customer_sk", "c_current_addr_sk"])
    ca = scan("customer_address", a, ["ca_address_sk", "ca_zip"])
    st = scan("store", a, ["s_store_sk", "s_zip"])
    j = bhj(ss, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j = bhj(j, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    j = bhj(j, bcast(cu), [a("ss_customer_sk")], [a("c_customer_sk")])
    j = bhj(j, bcast(ca), [a("c_current_addr_sk")], [a("ca_address_sk")])
    j = bhj(j, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    f = filt(not_(eq(sfn("Substring", a("ca_zip"), lit(1, "integer"),
                         lit(5, "integer")),
                     sfn("Substring", a("s_zip"), lit(1, "integer"),
                         lit(5, "integer")))), j)
    rid = a.new_id()
    agg = two_stage_agg([a("i_brand"), a("i_brand_id"), a("i_manufact_id"),
                         a("i_manufact")],
                        [("Sum", rid, [a("ss_ext_sales_price")])], f)
    s = a.define_with_id("ext_price", "decimal(17,2)", rid)
    plan = take_ordered(100, [sort_order(s, asc=False),
                              sort_order(a("i_brand")),
                              sort_order(a("i_brand_id")),
                              sort_order(a("i_manufact_id")),
                              sort_order(a("i_manufact"))], [], agg)

    def oracle(dfs):
        dd = dfs["date_dim"]
        it = dfs["item"]
        m = dfs["store_sales"].merge(
            dd[(dd.d_moy == 11) & (dd.d_year == 1999)],
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it[it.i_manager_id == 7], left_on="ss_item_sk",
                    right_on="i_item_sk")
        m = m.merge(dfs["customer"], left_on="ss_customer_sk",
                    right_on="c_customer_sk")
        m = m.merge(dfs["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
        m = m.merge(dfs["store"], left_on="ss_store_sk",
                    right_on="s_store_sk")
        m = m[m.ca_zip.str[:5] != m.s_zip.str[:5]]
        g = m.groupby(["i_brand", "i_brand_id", "i_manufact_id",
                       "i_manufact"],
                      as_index=False).ss_ext_sales_price.sum()
        g = g.sort_values(
            ["ss_ext_sales_price", "i_brand", "i_brand_id", "i_manufact_id",
             "i_manufact"], ascending=[False, True, True, True, True],
            kind="stable").head(100)
        return [(r.i_brand, r.i_brand_id, r.i_manufact_id, r.i_manufact,
                 r.ss_ext_sales_price) for r in g.itertuples(index=False)]

    return plan, oracle, None, ()


@query("q13")
def q13():
    """SELECT avg(ss_quantity), avg(ss_ext_sales_price),
              avg(ss_ext_wholesale_cost), sum(ss_ext_wholesale_cost)
       FROM store_sales, store, customer_demographics,
            household_demographics, customer_address, date_dim
       WHERE s_store_sk = ss_store_sk AND ss_sold_date_sk = d_date_sk
         AND d_year = 1998 AND ss_hdemo_sk = hd_demo_sk
         AND cd_demo_sk = ss_cdemo_sk AND ss_addr_sk = ca_address_sk
         AND ca_country = 'United States'
         AND ((cd_marital_status = 'M'
               AND cd_education_status = 'Advanced Degree'
               AND ss_sales_price BETWEEN 100.00 AND 150.00
               AND hd_dep_count = 3)
           OR (cd_marital_status = 'S' AND cd_education_status = 'College'
               AND ss_sales_price BETWEEN 50.00 AND 100.00
               AND hd_dep_count = 1)
           OR (cd_marital_status = 'W' AND cd_education_status = '2 yr Degree'
               AND ss_sales_price BETWEEN 150.00 AND 200.00
               AND hd_dep_count = 1))
         AND ((ca_state IN ('TX','OH') AND ss_net_profit BETWEEN 100 AND 200)
           OR (ca_state IN ('OR','NM','KY')
               AND ss_net_profit BETWEEN 150 AND 300)
           OR (ca_state IN ('VA','TX','MS')
               AND ss_net_profit BETWEEN 50 AND 250))"""
    a = Attrs()
    for c, t in [("ss_store_sk", "long"), ("ss_sold_date_sk", "long"),
                 ("ss_hdemo_sk", "long"), ("ss_cdemo_sk", "long"),
                 ("ss_addr_sk", "long"), ("ss_quantity", "long"),
                 ("ss_sales_price", "decimal(7,2)"),
                 ("ss_ext_sales_price", "decimal(7,2)"),
                 ("ss_ext_wholesale_cost", "decimal(7,2)"),
                 ("ss_net_profit", "decimal(7,2)"),
                 ("s_store_sk", "long"),
                 ("cd_demo_sk", "long"), ("cd_marital_status", "string"),
                 ("cd_education_status", "string"),
                 ("hd_demo_sk", "long"), ("hd_dep_count", "long"),
                 ("ca_address_sk", "long"), ("ca_state", "string"),
                 ("ca_country", "string"),
                 ("d_date_sk", "long"), ("d_year", "long")]:
        a.define(c, t)

    def between_d(col, lo, hi):
        return and_(binop("GreaterThanOrEqual", a(col),
                          lit(lo, "decimal(7,2)")),
                    binop("LessThanOrEqual", a(col),
                          lit(hi, "decimal(7,2)")))

    ss = scan("store_sales", a,
              ["ss_store_sk", "ss_sold_date_sk", "ss_hdemo_sk",
               "ss_cdemo_sk", "ss_addr_sk", "ss_quantity", "ss_sales_price",
               "ss_ext_sales_price", "ss_ext_wholesale_cost",
               "ss_net_profit"])
    st = scan("store", a, ["s_store_sk"])
    cd = scan("customer_demographics", a,
              ["cd_demo_sk", "cd_marital_status", "cd_education_status"])
    hd = scan("household_demographics", a, ["hd_demo_sk", "hd_dep_count"])
    ca = filt(eq(a("ca_country"), lit("United States", "string")),
              scan("customer_address", a,
                   ["ca_address_sk", "ca_state", "ca_country"]))
    dt = filt(eq(a("d_year"), lit(1998, "long")),
              scan("date_dim", a, ["d_date_sk", "d_year"]))
    j = bhj(ss, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    j = bhj(j, bcast(cd), [a("ss_cdemo_sk")], [a("cd_demo_sk")])
    j = bhj(j, bcast(hd), [a("ss_hdemo_sk")], [a("hd_demo_sk")])
    j = bhj(j, bcast(ca), [a("ss_addr_sk")], [a("ca_address_sk")])
    j = bhj(j, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    demo = or_(
        and_(eq(a("cd_marital_status"), lit("M", "string")),
             eq(a("cd_education_status"), lit("Advanced Degree", "string")),
             between_d("ss_sales_price", "100.00", "150.00"),
             eq(a("hd_dep_count"), lit(3, "long"))),
        and_(eq(a("cd_marital_status"), lit("S", "string")),
             eq(a("cd_education_status"), lit("College", "string")),
             between_d("ss_sales_price", "50.00", "100.00"),
             eq(a("hd_dep_count"), lit(1, "long"))),
        and_(eq(a("cd_marital_status"), lit("W", "string")),
             eq(a("cd_education_status"), lit("2 yr Degree", "string")),
             between_d("ss_sales_price", "150.00", "200.00"),
             eq(a("hd_dep_count"), lit(1, "long"))))
    addr = or_(
        and_(in_list(a("ca_state"), ["TX", "OH"], "string"),
             between_d("ss_net_profit", "100.00", "200.00")),
        and_(in_list(a("ca_state"), ["OR", "NM", "KY"], "string"),
             between_d("ss_net_profit", "150.00", "300.00")),
        and_(in_list(a("ca_state"), ["VA", "TX", "MS"], "string"),
             between_d("ss_net_profit", "50.00", "250.00")))
    f = filt(and_(demo, addr), j)
    rids = [a.new_id() for _ in range(4)]
    partial = hash_agg([], [
        agg_expr("Average", "Partial", rids[0], [a("ss_quantity")]),
        agg_expr("Average", "Partial", rids[1], [a("ss_ext_sales_price")]),
        agg_expr("Average", "Partial", rids[2],
                 [a("ss_ext_wholesale_cost")]),
        agg_expr("Sum", "Partial", rids[3],
                 [a("ss_ext_wholesale_cost")])], f)
    plan = hash_agg([], [
        agg_expr("Average", "Final", rids[0], [a("ss_quantity")]),
        agg_expr("Average", "Final", rids[1], [a("ss_ext_sales_price")]),
        agg_expr("Average", "Final", rids[2], [a("ss_ext_wholesale_cost")]),
        agg_expr("Sum", "Final", rids[3], [a("ss_ext_wholesale_cost")])],
        exchange(partial, keys=None))

    def oracle(dfs):
        import decimal as _dc

        D = _dc.Decimal
        dd = dfs["date_dim"]
        ca = dfs["customer_address"]
        m = dfs["store_sales"].merge(dfs["store"], left_on="ss_store_sk",
                                     right_on="s_store_sk")
        m = m.merge(dfs["customer_demographics"], left_on="ss_cdemo_sk",
                    right_on="cd_demo_sk")
        m = m.merge(dfs["household_demographics"], left_on="ss_hdemo_sk",
                    right_on="hd_demo_sk")
        m = m.merge(ca[ca.ca_country == "United States"],
                    left_on="ss_addr_sk", right_on="ca_address_sk")
        m = m.merge(dd[dd.d_year == 1998], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
        sp, np_ = m.ss_sales_price, m.ss_net_profit
        demo = (((m.cd_marital_status == "M")
                 & (m.cd_education_status == "Advanced Degree")
                 & (sp >= D("100.00")) & (sp <= D("150.00"))
                 & (m.hd_dep_count == 3))
                | ((m.cd_marital_status == "S")
                   & (m.cd_education_status == "College")
                   & (sp >= D("50.00")) & (sp <= D("100.00"))
                   & (m.hd_dep_count == 1))
                | ((m.cd_marital_status == "W")
                   & (m.cd_education_status == "2 yr Degree")
                   & (sp >= D("150.00")) & (sp <= D("200.00"))
                   & (m.hd_dep_count == 1)))
        addr = ((m.ca_state.isin(["TX", "OH"])
                 & (np_ >= D("100.00")) & (np_ <= D("200.00")))
                | (m.ca_state.isin(["OR", "NM", "KY"])
                   & (np_ >= D("150.00")) & (np_ <= D("300.00")))
                | (m.ca_state.isin(["VA", "TX", "MS"])
                   & (np_ >= D("50.00")) & (np_ <= D("250.00"))))
        m = m[demo & addr]
        if not len(m):
            return [(None, None, None, None)]
        return [(m.ss_quantity.mean(),
                 float(m.ss_ext_sales_price.astype(float).mean()),
                 float(m.ss_ext_wholesale_cost.astype(float).mean()),
                 m.ss_ext_wholesale_cost.sum())]

    return plan, oracle, None, ("approx",)


@query("q68")
def q68():
    """SELECT c_last_name, c_first_name, ca_city, bought_city,
              ss_ticket_number, extended_price, extended_tax, list_price
       FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
                    sum(ss_ext_sales_price) extended_price,
                    sum(ss_ext_discount_amt) extended_tax,
                    sum(ss_ext_list_price) list_price
             FROM store_sales, date_dim, store, household_demographics,
                  customer_address
             WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
               AND ss_hdemo_sk = hd_demo_sk AND ss_addr_sk = ca_address_sk
               AND d_dom BETWEEN 1 AND 2
               AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
               AND d_year = 1998 AND s_city IN ('Midway','Fairview')
             GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city
            ) dn, customer, customer_address current_addr
       WHERE ss_customer_sk = c_customer_sk
         AND customer.c_current_addr_sk = current_addr.ca_address_sk
         AND current_addr.ca_city <> bought_city
       ORDER BY c_last_name, ss_ticket_number LIMIT 100
       -- (ss_ext_list_price bound to the generator's ss_list_price sums)"""
    a = Attrs()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_store_sk", "long"),
                 ("ss_hdemo_sk", "long"), ("ss_addr_sk", "long"),
                 ("ss_customer_sk", "long"), ("ss_ticket_number", "long"),
                 ("ss_ext_sales_price", "decimal(7,2)"),
                 ("ss_ext_discount_amt", "decimal(7,2)"),
                 ("ss_list_price", "decimal(7,2)"),
                 ("d_date_sk", "long"), ("d_year", "long"), ("d_dom", "long"),
                 ("s_store_sk", "long"), ("s_city", "string"),
                 ("hd_demo_sk", "long"), ("hd_dep_count", "long"),
                 ("hd_vehicle_count", "long"),
                 ("ca_address_sk", "long"), ("ca_city", "string"),
                 ("c_customer_sk", "long"), ("c_current_addr_sk", "long"),
                 ("c_first_name", "string"), ("c_last_name", "string")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_addr_sk",
               "ss_customer_sk", "ss_ticket_number", "ss_ext_sales_price",
               "ss_ext_discount_amt", "ss_list_price"])
    dt = filt(and_(binop("GreaterThanOrEqual", a("d_dom"), lit(1, "long")),
                   binop("LessThanOrEqual", a("d_dom"), lit(2, "long")),
                   eq(a("d_year"), lit(1998, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_dom"]))
    st = filt(in_list(a("s_city"), ["Midway", "Fairview"], "string"),
              scan("store", a, ["s_store_sk", "s_city"]))
    hd = filt(or_(eq(a("hd_dep_count"), lit(4, "long")),
                  eq(a("hd_vehicle_count"), lit(3, "long"))),
              scan("household_demographics", a,
                   ["hd_demo_sk", "hd_dep_count", "hd_vehicle_count"]))
    ca = scan("customer_address", a, ["ca_address_sk", "ca_city"])
    j = bhj(ss, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j = bhj(j, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    j = bhj(j, bcast(hd), [a("ss_hdemo_sk")], [a("hd_demo_sk")])
    j = bhj(j, bcast(ca), [a("ss_addr_sk")], [a("ca_address_sk")])
    r1, r2, r3 = (a.new_id() for _ in range(3))
    agg = two_stage_agg(
        [a("ss_ticket_number"), a("ss_customer_sk"), a("ss_addr_sk"),
         a("ca_city")],
        [("Sum", r1, [a("ss_ext_sales_price")]),
         ("Sum", r2, [a("ss_ext_discount_amt")]),
         ("Sum", r3, [a("ss_list_price")])], j)
    # join the aggregated "dn" with customer + current address
    cu = scan("customer", a,
              ["c_customer_sk", "c_current_addr_sk", "c_first_name",
               "c_last_name"])
    # second instance of customer_address: same column NAMES, fresh
    # exprIds — exactly how Spark serializes a self-joined table
    b = Attrs()
    b.define("ca_address_sk", "long")
    b.define("ca_city", "string")
    cur = scan("customer_address", b, ["ca_address_sk", "ca_city"])
    j2 = bhj(agg, bcast(cu), [a("ss_customer_sk")], [a("c_customer_sk")])
    j2 = bhj(j2, bcast(cur), [a("c_current_addr_sk")],
             [b("ca_address_sk")])
    f2 = filt(not_(eq(b("ca_city"), a("ca_city"))), j2)
    plan = take_ordered(100, [sort_order(a("c_last_name")),
                              sort_order(a("ss_ticket_number"))], [], f2)

    def oracle(dfs):
        dd = dfs["date_dim"]
        st = dfs["store"]
        hd = dfs["household_demographics"]
        m = dfs["store_sales"].merge(
            dd[(dd.d_dom >= 1) & (dd.d_dom <= 2) & (dd.d_year == 1998)],
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[st.s_city.isin(["Midway", "Fairview"])],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(hd[(hd.hd_dep_count == 4) | (hd.hd_vehicle_count == 3)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(dfs["customer_address"], left_on="ss_addr_sk",
                    right_on="ca_address_sk")
        g = m.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                       "ca_city"], as_index=False).agg(
            ep=("ss_ext_sales_price", "sum"),
            et=("ss_ext_discount_amt", "sum"),
            lp=("ss_list_price", "sum"))
        g = g.merge(dfs["customer"], left_on="ss_customer_sk",
                    right_on="c_customer_sk")
        cur = dfs["customer_address"][["ca_address_sk", "ca_city"]].rename(
            columns={"ca_address_sk": "ca2_sk", "ca_city": "ca2_city"})
        g = g.merge(cur, left_on="c_current_addr_sk", right_on="ca2_sk")
        g = g[g.ca2_city != g.ca_city]
        g = g.sort_values(["c_last_name", "ss_ticket_number"],
                          kind="stable").head(100)
        return [(r.ss_ticket_number, r.ss_customer_sk, r.ss_addr_sk,
                 r.ca_city, r.ep, r.et, r.lp, r.c_customer_sk,
                 r.c_current_addr_sk, r.c_first_name, r.c_last_name,
                 r.ca2_sk, r.ca2_city)
                for r in g.itertuples(index=False)]

    return plan, oracle, None, ("ties",)


def _window_sum(a, name, arg_attr, part_keys, wid):
    """Alias(WindowExpression(AggregateExpression(fn))) tree + the WindowExec
    node builder inputs, as Spark serializes aggregates-over-window."""
    from tests.tpcds.plans import X

    agg = agg_expr("Sum", "Complete", a.new_id(), [arg_attr])
    wexpr = [{"class": f"{X}.WindowExpression", "num-children": 1,
              "windowFunction": 0, "windowSpec": {}}] + agg
    return alias(wexpr, name, wid)


@query("q98")
def q98():
    """SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
              sum(ss_ext_sales_price) AS itemrevenue,
              sum(ss_ext_sales_price)*100/sum(sum(ss_ext_sales_price))
                  OVER (PARTITION BY i_class) AS revenueratio
       FROM store_sales, item, date_dim
       WHERE ss_item_sk = i_item_sk
         AND i_category IN ('Sports','Books','Home')
         AND ss_sold_date_sk = d_date_sk AND d_year = 1999 AND d_moy = 2
       GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
       ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio"""
    a = Attrs()
    for c, t in [("ss_item_sk", "long"), ("ss_sold_date_sk", "long"),
                 ("ss_ext_sales_price", "decimal(7,2)"),
                 ("i_item_sk", "long"), ("i_item_id", "string"),
                 ("i_item_desc", "string"), ("i_category", "string"),
                 ("i_class", "string"), ("i_current_price", "decimal(7,2)"),
                 ("d_date_sk", "long"), ("d_year", "long"),
                 ("d_moy", "long")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_item_sk", "ss_sold_date_sk", "ss_ext_sales_price"])
    it = filt(in_list(a("i_category"), ["Sports", "Books", "Home"],
                      "string"),
              scan("item", a, ["i_item_sk", "i_item_id", "i_item_desc",
                               "i_category", "i_class", "i_current_price"]))
    dt = filt(and_(eq(a("d_year"), lit(1999, "long")),
                   eq(a("d_moy"), lit(2, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_moy"]))
    j = bhj(ss, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    j = bhj(j, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    rid = a.new_id()
    groups = [a("i_item_id"), a("i_item_desc"), a("i_category"),
              a("i_class"), a("i_current_price")]
    agg = two_stage_agg(groups, [("Sum", rid, [a("ss_ext_sales_price")])], j)
    srev = a.define_with_id("itemrevenue", "decimal(17,2)", rid)
    wid = a.new_id()
    # Spark plans exchange-by-partition-keys + sort under WindowExec
    wchild = sort([sort_order(a("i_class"))],
                  exchange(agg, keys=[a("i_class")]))
    win = window([_window_sum(a, "_we0", srev, None, wid)],
                 [a("i_class")], [], wchild)
    wattr = a.define_with_id("_we0", "decimal(27,2)", wid)
    rid_ratio = a.new_id()
    ratio = alias(
        binop("Divide", mul(srev, lit("100", "decimal(3,0)")), wattr),
        "revenueratio", rid_ratio)
    proj = project(groups + [srev] + [ratio], win)
    ratio_attr = a.define_with_id("revenueratio", "decimal(38,11)",
                                  rid_ratio)
    # global ORDER BY = RangePartitioning exchange + sort, as Spark plans it
    from tests.tpcds.plans import range_exchange

    q98_orders = [sort_order(a("i_category")), sort_order(a("i_class")),
                  sort_order(a("i_item_id")), sort_order(a("i_item_desc")),
                  sort_order(ratio_attr)]
    plan = sort(q98_orders, range_exchange(proj, [
        sort_order(a("i_category")), sort_order(a("i_class")),
        sort_order(a("i_item_id")), sort_order(a("i_item_desc")),
        sort_order(ratio_attr)]))

    def oracle(dfs):
        dd = dfs["date_dim"]
        it = dfs["item"]
        m = dfs["store_sales"].merge(
            it[it.i_category.isin(["Sports", "Books", "Home"])],
            left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(dd[(dd.d_year == 1999) & (dd.d_moy == 2)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
        g = m.groupby(["i_item_id", "i_item_desc", "i_category", "i_class",
                       "i_current_price"],
                      as_index=False).ss_ext_sales_price.sum()
        g["rev"] = g.ss_ext_sales_price.astype(float)
        g["ratio"] = g.rev * 100 / g.groupby("i_class").rev.transform("sum")
        g = g.sort_values(["i_category", "i_class", "i_item_id",
                           "i_item_desc", "ratio"], kind="stable")
        return [(r.i_item_id, r.i_item_desc, r.i_category, r.i_class,
                 r.i_current_price, r.rev, r.ratio)
                for r in g.itertuples(index=False)]

    return plan, oracle, None, ("approx",)


@query("q89")
def q89():
    """SELECT i_category, i_class, i_brand, s_store_name, s_company_name,
              d_moy, sum_sales, avg_monthly_sales
       FROM (SELECT i_category, i_class, i_brand, s_store_name,
                    s_company_name, d_moy, sum(ss_sales_price) sum_sales,
                    avg(sum(ss_sales_price)) OVER (PARTITION BY i_category,
                        i_brand, s_store_name, s_company_name)
                        avg_monthly_sales
             FROM item, store_sales, date_dim, store
             WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
               AND ss_store_sk = s_store_sk AND d_year = 1999
               AND ((i_category IN ('Books','Electronics','Sports')
                     AND i_class IN ('class01','class02','class03'))
                 OR (i_category IN ('Men','Jewelry','Women')
                     AND i_class IN ('class04','class05','class06')))) tmp
       WHERE CASE WHEN (avg_monthly_sales <> 0)
                  THEN (abs(sum_sales - avg_monthly_sales)
                        / avg_monthly_sales) ELSE null END > 0.1
       ORDER BY sum_sales - avg_monthly_sales, s_store_name LIMIT 100"""
    from tests.tpcds.plans import X

    a = Attrs()
    for c, t in [("ss_item_sk", "long"), ("ss_sold_date_sk", "long"),
                 ("ss_store_sk", "long"),
                 ("ss_sales_price", "decimal(7,2)"),
                 ("i_item_sk", "long"), ("i_category", "string"),
                 ("i_class", "string"), ("i_brand", "string"),
                 ("d_date_sk", "long"), ("d_year", "long"),
                 ("d_moy", "long"),
                 ("s_store_sk", "long"), ("s_store_name", "string"),
                 ("s_company_name", "string")]:
        a.define(c, t)
    ss = scan("store_sales", a,
              ["ss_item_sk", "ss_sold_date_sk", "ss_store_sk",
               "ss_sales_price"])
    it = filt(or_(and_(in_list(a("i_category"),
                               ["Books", "Electronics", "Sports"], "string"),
                       in_list(a("i_class"),
                               ["class01", "class02", "class03"], "string")),
                  and_(in_list(a("i_category"),
                               ["Men", "Jewelry", "Women"], "string"),
                       in_list(a("i_class"),
                               ["class04", "class05", "class06"],
                               "string"))),
              scan("item", a, ["i_item_sk", "i_category", "i_class",
                               "i_brand"]))
    dt = filt(eq(a("d_year"), lit(1999, "long")),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_moy"]))
    st = scan("store", a, ["s_store_sk", "s_store_name", "s_company_name"])
    j = bhj(ss, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    j = bhj(j, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j = bhj(j, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    rid = a.new_id()
    groups = [a("i_category"), a("i_class"), a("i_brand"),
              a("s_store_name"), a("s_company_name"), a("d_moy")]
    agg = two_stage_agg(groups, [("Sum", rid, [a("ss_sales_price")])], j)
    ssum = a.define_with_id("sum_sales", "decimal(17,2)", rid)
    wid = a.new_id()
    pkeys = [a("i_category"), a("i_brand"), a("s_store_name"),
             a("s_company_name")]
    wchild = sort([sort_order(k) for k in pkeys],
                  exchange(agg, keys=list(pkeys)))
    wavg = agg_expr("Average", "Complete", a.new_id(), [ssum])
    wexpr = alias([{"class": f"{X}.WindowExpression", "num-children": 1,
                    "windowFunction": 0, "windowSpec": {}}] + wavg,
                  "avg_monthly_sales", wid)
    win = window([wexpr], pkeys, [], wchild)
    wattr = a.define_with_id("avg_monthly_sales", "decimal(21,6)", wid)
    # CASE WHEN avg <> 0 THEN abs(sum - avg)/avg ELSE null END > 0.1
    cond_ne = not_(eq(wattr, lit("0.000000", "decimal(21,6)")))
    ratio = binop("Divide",
                  sfn("Abs", binop("Subtract", ssum, wattr)), wattr)
    case = [{"class": f"{X}.CaseWhen", "num-children": 3,
             "branches": None, "elseValue": None}] + \
        cond_ne + ratio + lit(None, "decimal(38,16)")
    f = filt(binop("GreaterThan", case, lit("0.1", "decimal(2,1)")), win)
    plan = take_ordered(
        100, [sort_order(binop("Subtract", ssum, wattr)),
              sort_order(a("s_store_name"))], [], f)

    def oracle(dfs):
        it = dfs["item"]
        dd = dfs["date_dim"]
        keep = ((it.i_category.isin(["Books", "Electronics", "Sports"])
                 & it.i_class.isin(["class01", "class02", "class03"]))
                | (it.i_category.isin(["Men", "Jewelry", "Women"])
                   & it.i_class.isin(["class04", "class05", "class06"])))
        m = dfs["store_sales"].merge(it[keep], left_on="ss_item_sk",
                                     right_on="i_item_sk")
        m = m.merge(dd[dd.d_year == 1999], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
        m = m.merge(dfs["store"], left_on="ss_store_sk",
                    right_on="s_store_sk")
        g = m.groupby(["i_category", "i_class", "i_brand", "s_store_name",
                       "s_company_name", "d_moy"],
                      as_index=False).ss_sales_price.sum()
        g["sum_sales"] = g.ss_sales_price.astype(float)
        g["avg_monthly_sales"] = g.groupby(
            ["i_category", "i_brand", "s_store_name",
             "s_company_name"]).sum_sales.transform("mean")
        g = g[(g.avg_monthly_sales != 0)
              & ((g.sum_sales - g.avg_monthly_sales).abs()
                 / g.avg_monthly_sales > 0.1)]
        g["delta"] = g.sum_sales - g.avg_monthly_sales
        g = g.sort_values(["delta", "s_store_name"],
                          kind="stable").head(100)
        return [(r.i_category, r.i_class, r.i_brand, r.s_store_name,
                 r.s_company_name, r.d_moy, r.sum_sales,
                 r.avg_monthly_sales) for r in g.itertuples(index=False)]

    return plan, oracle, None, ("approx", "ties")


# round-5 additions (window/rank, rollup, existence joins, SMJ, union)
# register into the same QUERIES dict
from tests.tpcds import queries_r5  # noqa: E402,F401


"""Tiny-scale TPC-DS star schema for the real-query gate.

Column subsets of the official TPC-DS tables (the columns the checked-in
queries touch), generated deterministically at roughly sf≈0.002 so the full
16-query gate runs in CI time while every query still returns non-trivial
results. FK distributions are skewed like the real generator's (recent
dates, popular items)."""

from __future__ import annotations

import decimal
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

N_DATES = 731            # two years: 1998-01-01 .. 1999-12-31 (d_date_sk 1..)
N_ITEMS = 600
N_STORES = 12
N_CUSTOMERS = 4000
N_ADDRS = 3000
N_CDEMO = 400
N_HDEMO = 60
N_PROMOS = 40
N_SS = 60_000
N_CS = 30_000
N_WS = 20_000
N_INV = 24_000
N_CALL_CENTERS = 6


def _dec(rng, n, lo, hi, prec=7, scale=2):
    unscaled = rng.integers(lo, hi, n)
    return pa.array([decimal.Decimal(int(v)).scaleb(-scale) for v in unscaled],
                    type=pa.decimal128(prec, scale))


def generate(dirpath: str) -> dict:
    """Write all tables as parquet under ``dirpath``; returns
    {table: [paths]}."""
    rng = np.random.default_rng(2026)
    os.makedirs(dirpath, exist_ok=True)
    tables = {}

    def write(name, tbl, parts=1):
        paths = []
        per = max(1, tbl.num_rows // parts)
        for p in range(parts):
            sub = tbl.slice(p * per,
                            per if p < parts - 1 else tbl.num_rows - p * per)
            path = os.path.join(dirpath, f"{name}_{p}.parquet")
            pq.write_table(sub, path)
            paths.append(path)
        tables[name] = paths
        return tbl

    # --- date_dim: d_date_sk 1.. maps to days from 1998-01-01
    sk = np.arange(1, N_DATES + 1)
    doy = (sk - 1) % 365
    year = 1998 + (sk - 1) // 365
    month_lengths = np.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])
    month_starts = np.concatenate([[0], np.cumsum(month_lengths)[:-1]])
    moy = np.searchsorted(month_starts, doy, side="right")
    dom = doy - month_starts[moy - 1] + 1
    day_names = np.array(["Sunday", "Monday", "Tuesday", "Wednesday",
                          "Thursday", "Friday", "Saturday"])
    write("date_dim", pa.table({
        "d_date_sk": pa.array(sk, type=pa.int64()),
        "d_year": pa.array(year, type=pa.int64()),
        "d_moy": pa.array(moy, type=pa.int64()),
        "d_dom": pa.array(dom, type=pa.int64()),
        "d_day_name": pa.array(day_names[(sk - 1) % 7]),
        "d_month_seq": pa.array((year - 1900) * 12 + moy - 1,
                                type=pa.int64()),
        "d_qoy": pa.array((moy - 1) // 3 + 1, type=pa.int64()),
        "d_week_seq": pa.array((sk - 1) // 7 + 5270, type=pa.int64()),
        "d_date": pa.array([f"{y}-{m:02d}-{d:02d}"
                            for y, m, d in zip(year.tolist(), moy.tolist(),
                                               dom.tolist())]),
    }))

    cats = ["Books", "Home", "Electronics", "Music", "Sports",
            "Shoes", "Women", "Men", "Children", "Jewelry"]
    classes = ["class%02d" % i for i in range(16)]
    write("item", pa.table({
        "i_item_sk": pa.array(np.arange(1, N_ITEMS + 1), type=pa.int64()),
        "i_item_id": pa.array([f"AAAAAA{v:010d}" for v in range(1, N_ITEMS + 1)]),
        "i_item_desc": pa.array([f"item description {v}" for v in range(N_ITEMS)]),
        "i_manufact": pa.array([f"manufact{v % 100}" for v in range(N_ITEMS)]),
        "i_brand_id": pa.array(rng.integers(1001001, 1001060, N_ITEMS),
                               type=pa.int64()),
        "i_brand": pa.array([f"brand#{v}" for v in
                             rng.integers(1, 60, N_ITEMS)]),
        "i_class": pa.array([classes[v] for v in
                             rng.integers(0, len(classes), N_ITEMS)]),
        "i_category_id": pa.array(rng.integers(1, len(cats) + 1, N_ITEMS),
                                  type=pa.int64()),
        "i_category": pa.array([cats[v] for v in
                                rng.integers(0, len(cats), N_ITEMS)]),
        "i_manufact_id": pa.array(rng.integers(1, 100, N_ITEMS),
                                  type=pa.int64()),
        "i_manager_id": pa.array(rng.integers(1, 40, N_ITEMS),
                                 type=pa.int64()),
        "i_current_price": _dec(rng, N_ITEMS, 100, 30000),
        # deterministic (no rng draw: keeps every pre-existing column's
        # draw sequence byte-identical to earlier rounds)
        "i_product_name": pa.array([f"product{v % 250}"
                                    for v in range(N_ITEMS)]),
    }))

    write("store", pa.table({
        "s_store_sk": pa.array(np.arange(1, N_STORES + 1), type=pa.int64()),
        "s_store_id": pa.array([f"S{v:09d}" for v in range(1, N_STORES + 1)]),
        "s_store_name": pa.array([f"store {chr(97 + v % 26)}"
                                  for v in range(N_STORES)]),
        "s_city": pa.array([["Midway", "Fairview", "Oakland"][v % 3]
                            for v in range(N_STORES)]),
        "s_state": pa.array([["TN", "SD", "AL"][v % 3]
                             for v in range(N_STORES)]),
        "s_zip": pa.array([f"{24000 + (v * 11) % 70000:05d}"
                           for v in range(N_STORES)]),
        "s_company_name": pa.array([["Unknown", "ought", "able"][v % 3]
                                    for v in range(N_STORES)]),
        "s_county": pa.array([f"county{v % 8}" for v in range(N_STORES)]),
        "s_gmt_offset": _dec(rng, N_STORES, -600, -400, prec=5, scale=2),
    }))

    n_times = 7200
    write("time_dim", pa.table({
        "t_time_sk": pa.array(np.arange(1, n_times + 1), type=pa.int64()),
        "t_hour": pa.array((np.arange(n_times) // 300) % 24,
                           type=pa.int64()),
        "t_minute": pa.array((np.arange(n_times) // 5) % 60,
                             type=pa.int64()),
    }))

    write("customer", pa.table({
        "c_customer_sk": pa.array(np.arange(1, N_CUSTOMERS + 1),
                                  type=pa.int64()),
        "c_current_addr_sk": pa.array(rng.integers(1, N_ADDRS + 1,
                                                   N_CUSTOMERS),
                                      type=pa.int64()),
        "c_current_cdemo_sk": pa.array(rng.integers(1, N_CDEMO + 1,
                                                    N_CUSTOMERS),
                                       type=pa.int64()),
        "c_current_hdemo_sk": pa.array(rng.integers(1, N_HDEMO + 1,
                                                    N_CUSTOMERS),
                                       type=pa.int64()),
        "c_first_name": pa.array([f"First{v % 97}"
                                  for v in range(N_CUSTOMERS)]),
        "c_last_name": pa.array([f"Last{v % 131}"
                                 for v in range(N_CUSTOMERS)]),
        "c_birth_month": pa.array(np.arange(N_CUSTOMERS) % 12 + 1,
                                  type=pa.int64()),
        "c_birth_year": pa.array(1930 + (np.arange(N_CUSTOMERS) * 7) % 70,
                                 type=pa.int64()),
        "c_salutation": pa.array([["Mr.", "Mrs.", "Ms.", "Dr.", "Sir"][v % 5]
                                  for v in range(N_CUSTOMERS)]),
        "c_preferred_cust_flag": pa.array([["Y", "N"][v % 2]
                                           for v in range(N_CUSTOMERS)]),
    }))

    write("customer_address", pa.table({
        "ca_address_sk": pa.array(np.arange(1, N_ADDRS + 1), type=pa.int64()),
        "ca_city": pa.array([["Edgewood", "Midway", "Salem", "Concord",
                              "Clinton"][v % 5] for v in range(N_ADDRS)]),
        "ca_zip": pa.array([f"{24000 + (v * 7) % 70000:05d}"
                            for v in range(N_ADDRS)]),
        "ca_state": pa.array([["CA", "TX", "OH", "GA", "WA"][v % 5]
                              for v in range(N_ADDRS)]),
        "ca_country": pa.array(["United States"] * N_ADDRS),
        "ca_gmt_offset": _dec(rng, N_ADDRS, -600, -400, prec=5, scale=2),
        "ca_county": pa.array([f"county{v % 40}" for v in range(N_ADDRS)]),
    }))

    write("customer_demographics", pa.table({
        "cd_demo_sk": pa.array(np.arange(1, N_CDEMO + 1), type=pa.int64()),
        "cd_gender": pa.array([["M", "F"][v % 2] for v in range(N_CDEMO)]),
        "cd_marital_status": pa.array([["M", "S", "D", "W", "U"][v % 5]
                                       for v in range(N_CDEMO)]),
        "cd_education_status": pa.array(
            [["Primary", "Secondary", "College", "2 yr Degree",
              "4 yr Degree", "Advanced Degree", "Unknown"][v % 7]
             for v in range(N_CDEMO)]),
        "cd_purchase_estimate": pa.array((np.arange(N_CDEMO) % 10 + 1) * 500,
                                         type=pa.int64()),
        "cd_credit_rating": pa.array(
            [["Low Risk", "High Risk", "Good", "Unknown"][v % 4]
             for v in range(N_CDEMO)]),
        "cd_dep_count": pa.array(np.arange(N_CDEMO) % 7, type=pa.int64()),
        "cd_dep_employed_count": pa.array(np.arange(N_CDEMO) % 5,
                                          type=pa.int64()),
        "cd_dep_college_count": pa.array(np.arange(N_CDEMO) % 3,
                                         type=pa.int64()),
    }))

    write("household_demographics", pa.table({
        "hd_demo_sk": pa.array(np.arange(1, N_HDEMO + 1), type=pa.int64()),
        "hd_dep_count": pa.array(np.arange(N_HDEMO) % 10, type=pa.int64()),
        "hd_vehicle_count": pa.array(np.arange(N_HDEMO) % 5, type=pa.int64()),
        "hd_buy_potential": pa.array(
            [[">10000", "Unknown", "1001-5000", "501-1000"][v % 4]
             for v in range(N_HDEMO)]),
    }))

    write("promotion", pa.table({
        "p_promo_sk": pa.array(np.arange(1, N_PROMOS + 1), type=pa.int64()),
        "p_channel_dmail": pa.array([["Y", "N"][v % 2]
                                     for v in range(N_PROMOS)]),
        "p_channel_email": pa.array([["N", "Y"][v % 3 == 1]
                                     for v in range(N_PROMOS)]),
        "p_channel_tv": pa.array([["N", "Y"][v % 5 == 2]
                                  for v in range(N_PROMOS)]),
    }))

    def sales(prefix, n):
        qty = rng.integers(1, 101, n)
        list_price = rng.integers(100, 30000, n)
        sales_price = (list_price * rng.integers(40, 100, n)) // 100
        return {
            f"{prefix}_sold_date_sk": pa.array(
                rng.integers(1, N_DATES + 1, n), type=pa.int64()),
            f"{prefix}_item_sk": pa.array(
                rng.integers(1, N_ITEMS + 1, n), type=pa.int64()),
            f"{prefix}_promo_sk": pa.array(
                rng.integers(1, N_PROMOS + 1, n), type=pa.int64()),
            f"{prefix}_quantity": pa.array(qty, type=pa.int64()),
            f"{prefix}_list_price": pa.array(
                [decimal.Decimal(int(v)).scaleb(-2) for v in list_price],
                type=pa.decimal128(7, 2)),
            f"{prefix}_sales_price": pa.array(
                [decimal.Decimal(int(v)).scaleb(-2) for v in sales_price],
                type=pa.decimal128(7, 2)),
            f"{prefix}_ext_sales_price": pa.array(
                [decimal.Decimal(int(q * v)).scaleb(-2)
                 for q, v in zip(qty, sales_price)],
                type=pa.decimal128(7, 2)),
            f"{prefix}_coupon_amt": _dec(rng, n, 0, 5000),
        }

    ss = sales("ss", N_SS)
    ss.update({
        "ss_ticket_number": pa.array(rng.integers(1, N_SS // 3, N_SS),
                                     type=pa.int64()),
        "ss_sold_time_sk": pa.array(rng.integers(1, 7201, N_SS),
                                    type=pa.int64()),
        "ss_customer_sk": pa.array(rng.integers(1, N_CUSTOMERS + 1, N_SS),
                                   type=pa.int64()),
        "ss_cdemo_sk": pa.array(rng.integers(1, N_CDEMO + 1, N_SS),
                                type=pa.int64()),
        "ss_hdemo_sk": pa.array(rng.integers(1, N_HDEMO + 1, N_SS),
                                type=pa.int64()),
        "ss_addr_sk": pa.array(rng.integers(1, N_ADDRS + 1, N_SS),
                               type=pa.int64()),
        "ss_store_sk": pa.array(rng.integers(1, N_STORES + 1, N_SS),
                                type=pa.int64()),
        "ss_ext_discount_amt": _dec(rng, N_SS, 0, 10000),
        "ss_ext_wholesale_cost": _dec(rng, N_SS, 100, 20000),
        "ss_net_profit": _dec(rng, N_SS, -5000, 15000),
    })
    write("store_sales", pa.table(ss), parts=2)

    cs = sales("cs", N_CS)
    cs.update({
        "cs_bill_customer_sk": pa.array(
            rng.integers(1, N_CUSTOMERS + 1, N_CS), type=pa.int64()),
        "cs_bill_cdemo_sk": pa.array(rng.integers(1, N_CDEMO + 1, N_CS),
                                     type=pa.int64()),
    })
    # round-5 additions draw from a SEPARATE stream so every pre-existing
    # column keeps the exact values earlier rounds generated (narrow query
    # filters stay selective-but-nonempty)
    rng5 = np.random.default_rng(777)
    cs.update({
        "cs_bill_addr_sk": pa.array(rng5.integers(1, N_ADDRS + 1, N_CS),
                                    type=pa.int64()),
        "cs_call_center_sk": pa.array(
            rng5.integers(1, N_CALL_CENTERS + 1, N_CS), type=pa.int64()),
    })
    write("catalog_sales", pa.table(cs), parts=2)

    # --- round-5 tables: web channel, inventory, call centers -------------
    ws_qty = rng5.integers(1, 101, N_WS)
    ws_price = rng5.integers(100, 30000, N_WS)
    write("web_sales", pa.table({
        "ws_sold_date_sk": pa.array(rng5.integers(1, N_DATES + 1, N_WS),
                                    type=pa.int64()),
        "ws_item_sk": pa.array(rng5.integers(1, N_ITEMS + 1, N_WS),
                               type=pa.int64()),
        "ws_bill_customer_sk": pa.array(
            rng5.integers(1, N_CUSTOMERS + 1, N_WS), type=pa.int64()),
        "ws_bill_addr_sk": pa.array(rng5.integers(1, N_ADDRS + 1, N_WS),
                                    type=pa.int64()),
        "ws_ext_sales_price": pa.array(
            [decimal.Decimal(int(q * v)).scaleb(-2)
             for q, v in zip(ws_qty, ws_price)], type=pa.decimal128(7, 2)),
    }), parts=2)

    write("inventory", pa.table({
        "inv_date_sk": pa.array(rng5.integers(1, N_DATES + 1, N_INV),
                                type=pa.int64()),
        "inv_item_sk": pa.array(rng5.integers(1, N_ITEMS + 1, N_INV),
                                type=pa.int64()),
        "inv_quantity_on_hand": pa.array(rng5.integers(0, 1000, N_INV),
                                         type=pa.int64()),
    }), parts=2)

    write("call_center", pa.table({
        "cc_call_center_sk": pa.array(np.arange(1, N_CALL_CENTERS + 1),
                                      type=pa.int64()),
        "cc_name": pa.array([f"call center {v}"
                             for v in range(1, N_CALL_CENTERS + 1)]),
    }))

    return tables


def load_dfs(tables: dict) -> dict:
    """pandas frames for the oracles."""
    return {name: pa.concat_tables(
        [pq.read_table(p) for p in ps]).to_pandas()
        for name, ps in tables.items()}

"""Builder for Spark ``TreeNode.toJSON`` physical-plan fixtures.

Emits the same flattened pre-order node arrays real Spark serializes (the
wire form ``blaze_tpu.frontend`` consumes — see frontend/treenode.py), so
the checked-in TPC-DS queries exercise the genuine conversion path:
AttributeReference exprIds, Alias bindings, AggregateExpression
Partial/Final modes, BroadcastHashJoinExec build sides, etc.

Every helper returns a FLATTENED LIST of node dicts; plan combinators
concatenate children in pre-order exactly like Spark's serializer."""

from __future__ import annotations

import itertools

SPARK = "org.apache.spark.sql"
X = f"{SPARK}.catalyst.expressions"
P = f"{SPARK}.execution"

_ids = itertools.count(1000)


class Attrs:
    """Per-query attribute registry: stable exprIds keyed by column name
    (matching how one Spark plan reuses the same AttributeReference)."""

    def __init__(self):
        self._ids = {}
        self._types = {}

    def define(self, name: str, dtype: str):
        if name not in self._ids:
            self._ids[name] = next(_ids)
            self._types[name] = dtype
        return self(name)

    def __call__(self, name: str):
        return [{
            "class": f"{X}.AttributeReference", "num-children": 0,
            "name": name, "dataType": self._types[name], "nullable": True,
            "metadata": {},
            "exprId": {"product-class": f"{X}.ExprId",
                       "id": self._ids[name],
                       "jvmId": "00000000-0000-0000-0000-000000000000"},
            "qualifier": []}]

    def new_id(self) -> int:
        return next(_ids)

    def define_with_id(self, name: str, dtype: str, eid: int):
        """Bind a name to a KNOWN exprId — how downstream nodes reference
        an aggregate's result attribute (exprId == the agg's resultId)."""
        self._ids[name] = eid
        self._types[name] = dtype
        return self(name)


def lit(value, dtype):
    return [{"class": f"{X}.Literal", "num-children": 0,
             "value": value, "dataType": dtype}]


def binop(cls, l, r):
    return [{"class": f"{X}.{cls}", "num-children": 2,
             "left": 0, "right": 1}] + l + r


def eq(l, r):
    return binop("EqualTo", l, r)


def and_(*conds):
    out = conds[0]
    for c in conds[1:]:
        out = binop("And", out, c)
    return out


def or_(*conds):
    out = conds[0]
    for c in conds[1:]:
        out = binop("Or", out, c)
    return out


def isnotnull(c):
    return [{"class": f"{X}.IsNotNull", "num-children": 1, "child": 0}] + c


def in_list(child, values, dtype):
    lits = [lit(v, dtype) for v in values]
    node = [{"class": f"{X}.In", "num-children": 1 + len(lits),
             "value": 0, "list": list(range(1, len(lits) + 1))}]
    return node + child + [x for li in lits for x in li]


def sfn(cls, *children):
    """Generic scalar function node (Substring, Concat, ...)."""
    return [{"class": f"{X}.{cls}", "num-children": len(children)}] + \
        [x for c in children for x in c]


def not_(child):
    return [{"class": f"{X}.Not", "num-children": 1, "child": 0}] + child


def cast(child, to):
    return [{"class": f"{X}.Cast", "num-children": 1, "child": 0,
             "dataType": to, "timeZoneId": "UTC"}] + child


def mul(l, r):
    return binop("Multiply", l, r)


def alias(child, name: str, eid: int):
    return [{"class": f"{X}.Alias", "num-children": 1, "child": 0,
             "name": name,
             "exprId": {"product-class": f"{X}.ExprId", "id": eid,
                        "jvmId": "00000000-0000-0000-0000-000000000000"},
             "qualifier": [], "explicitMetadata": {},
             "nonInheritableMetadataKeys": []}] + child


def agg_expr(fn_cls, mode, rid, children, distinct=False):
    fn = [{"class": f"{X}.aggregate.{fn_cls}",
           "num-children": len(children)}] + \
        [c for ch in children for c in ch]
    return [{"class": f"{X}.aggregate.AggregateExpression", "num-children": 1,
             "aggregateFunction": 0,
             "mode": {"object": f"{X}.aggregate.{mode}$"},
             "isDistinct": bool(distinct),
             "resultId": {"product-class": f"{X}.ExprId", "id": rid,
                          "jvmId": "00000000-0000-0000-0000-000000000000"}}] \
        + fn


def sort_order(child, asc=True, nulls_first=None):
    d = "Ascending$" if asc else "Descending$"
    nf = asc if nulls_first is None else nulls_first
    n = "NullsFirst$" if nf else "NullsLast$"
    return [{"class": f"{X}.SortOrder", "num-children": 1, "child": 0,
             "direction": {"object": f"{X}.{d}"},
             "nullOrdering": {"object": f"{X}.{n}"},
             "sameOrderExpressions": []}] + child


# --- plan nodes (flattened pre-order) ---------------------------------------


def scan(table: str, attrs, cols):
    return [{"class": f"{P}.FileSourceScanExec", "num-children": 0,
             "output": [attrs(c) for c in cols],
             "requiredSchema": {"type": "struct", "fields": []},
             "partitionFilters": [], "dataFilters": [],
             "tableIdentifier": table}]


def filt(cond, child):
    return [{"class": f"{P}.FilterExec", "num-children": 1,
             "condition": cond, "child": 0}] + child


def project(plist, child):
    return [{"class": f"{P}.ProjectExec", "num-children": 1,
             "projectList": plist, "child": 0}] + child


def hash_agg(groups, aggs, child):
    return [{"class": f"{P}.aggregate.HashAggregateExec", "num-children": 1,
             "requiredChildDistributionExpressions": None,
             "groupingExpressions": groups,
             "aggregateExpressions": aggs,
             "aggregateAttributes": [],
             "initialInputBufferOffset": 0,
             "resultExpressions": [], "child": 0}] + child


def exchange(child, keys=None, nparts=4):
    if keys is None:
        part = [{"class": f"{SPARK}.catalyst.plans.physical."
                          "SinglePartition$", "num-children": 0}]
    else:
        part = [{"class": f"{SPARK}.catalyst.plans.physical."
                          "HashPartitioning",
                 "num-children": len(keys),
                 "expressions": list(range(len(keys))),
                 "numPartitions": nparts}] + \
            [x for k in keys for x in k]
    return [{"class": f"{P}.exchange.ShuffleExchangeExec", "num-children": 1,
             "outputPartitioning": part,
             "shuffleOrigin": {"object": f"{P}.exchange."
                                         "ENSURE_REQUIREMENTS$"},
             "child": 0}] + child


def two_stage_agg(groups, agg_fns, child, nparts=4):
    """partial agg -> hash exchange on the group keys -> final agg, the
    shape Spark plans for a grouped aggregate. ``agg_fns``: list of
    (fn_cls, rid, children-builder) — children rebuilt per mode."""
    partial = hash_agg(groups,
                       [agg_expr(f, "Partial", rid, ch)
                        for f, rid, ch in agg_fns], child)
    ex = exchange(partial, keys=list(groups), nparts=nparts)
    return hash_agg(groups,
                    [agg_expr(f, "Final", rid, ch)
                     for f, rid, ch in agg_fns], ex)


def bcast(child):
    return [{"class": f"{P}.exchange.BroadcastExchangeExec",
             "num-children": 1, "mode": {}, "child": 0}] + child


def existence_join(eid: int) -> dict:
    """ExistenceJoin(exists#eid) — a case CLASS (carries the exprId), not a
    case object like Inner$/LeftSemi$."""
    return {"product-class": f"{SPARK}.catalyst.plans.ExistenceJoin",
            "exists": {"product-class": f"{X}.ExprId", "id": eid,
                       "jvmId": "00000000-0000-0000-0000-000000000000"}}


def _join_type(jt) -> dict:
    return jt if isinstance(jt, dict) else \
        {"object": f"{SPARK}.catalyst.plans.{jt}$"}


def bhj(left, right, lkeys, rkeys, jt="Inner", build="BuildRight",
        condition=None):
    node = {"class": f"{P}.joins.BroadcastHashJoinExec", "num-children": 2,
            "leftKeys": lkeys, "rightKeys": rkeys,
            "joinType": _join_type(jt),
            "buildSide": {"object": f"{P}.joins.{build}$"},
            "condition": condition, "left": 0, "right": 1}
    return [node] + left + right


def smj(left, right, lkeys, rkeys, jt="Inner", condition=None):
    node = {"class": f"{P}.joins.SortMergeJoinExec", "num-children": 2,
            "leftKeys": lkeys, "rightKeys": rkeys,
            "joinType": _join_type(jt),
            "condition": condition, "isSkewJoin": False,
            "left": 0, "right": 1}
    return [node] + left + right


def sort(orders, child):
    return [{"class": f"{P}.SortExec", "num-children": 1,
             "sortOrder": orders, "global": True, "child": 0}] + child


def take_ordered(limit, orders, plist, child):
    return [{"class": f"{P}.TakeOrderedAndProjectExec", "num-children": 1,
             "limit": limit, "sortOrder": orders,
             "projectList": plist, "child": 0}] + child


def window(wexprs, part_spec, order_spec, child):
    return [{"class": f"{P}.window.WindowExec", "num-children": 1,
             "windowExpression": wexprs, "partitionSpec": part_spec,
             "orderSpec": order_spec, "child": 0}] + child


def window_rank(a, name: str, order_children, wid: int, dense=False):
    """Alias(WindowExpression(Rank(order...))) — how Spark serializes
    rank()/dense_rank() OVER a window (the rank's children repeat the
    window order expressions)."""
    fn = "DenseRank" if dense else "Rank"
    rank = [{"class": f"{X}.{fn}", "num-children": len(order_children),
             "children": list(range(len(order_children)))}] + \
        [x for c in order_children for x in c]
    wexpr = [{"class": f"{X}.WindowExpression", "num-children": 1,
              "windowFunction": 0, "windowSpec": {}}] + rank
    return alias(wexpr, name, wid)


def union_all(*children):
    return [{"class": f"{P}.UnionExec",
             "num-children": len(children),
             "children": list(range(len(children)))}] + \
        [x for c in children for x in c]


def expand(projections, output_attrs, child):
    """ExpandExec: ``projections`` is a Seq[Seq[Expression]] (one inner list
    per generated row set — rollup null-extensions + spark_grouping_id),
    ``output`` carries the fresh output attributes."""
    return [{"class": f"{P}.ExpandExec", "num-children": 1,
             "projections": projections, "output": output_attrs,
             "child": 0}] + child


def range_exchange(child, orders, nparts=4):
    """ShuffleExchangeExec with RangePartitioning — what Spark plans under
    a GLOBAL SortExec (ORDER BY without LIMIT): range-partitioned rows,
    then per-partition sorts yield total order across partitions."""
    part = [{"class": f"{SPARK}.catalyst.plans.physical.RangePartitioning",
             "num-children": len(orders),
             "ordering": list(range(len(orders))),
             "numPartitions": nparts}] + \
        [x for o in orders for x in o]
    return [{"class": f"{P}.exchange.ShuffleExchangeExec", "num-children": 1,
             "outputPartitioning": part,
             "shuffleOrigin": {"object": f"{P}.exchange."
                                         "ENSURE_REQUIREMENTS$"},
             "child": 0}] + child


def sorted_exchange(child, keys, orders=None, nparts=4):
    """exchange-by-hash + sort: what Spark plans under each SMJ side."""
    ex = exchange(child, keys=list(keys), nparts=nparts)
    if orders is None:
        orders = [sort_order(k) for k in keys]
    return [{"class": f"{P}.SortExec", "num-children": 1,
             "sortOrder": orders, "global": False, "child": 0}] + ex

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest
from decimal import Decimal

from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.ops.parquet import ParquetScanExec, ParquetSinkExec, scan_node_for_files
from blaze_tpu.runtime.executor import build_operator
from blaze_tpu.runtime.session import Session
from tests.util import collect_pydict, mem_scan, run_op


@pytest.fixture
def pq_file(tmp_path):
    tbl = pa.table({
        "id": pa.array(range(1000), type=pa.int64()),
        "amt": pa.array([Decimal(i).scaleb(-2) for i in range(1000)],
                        type=pa.decimal128(9, 2)),
        "name": pa.array([f"n{i % 7}" for i in range(1000)]),
    })
    path = str(tmp_path / "t.parquet")
    pq.write_table(tbl, path, row_group_size=100)
    return path, tbl


@pytest.mark.quick
def test_scan_roundtrip(pq_file):
    path, tbl = pq_file
    node = scan_node_for_files([path])
    op = build_operator(node)
    out = collect_pydict(op)
    assert out["id"] == tbl["id"].to_pylist()
    assert out["amt"] == tbl["amt"].to_pylist()
    assert out["name"] == tbl["name"].to_pylist()


def test_scan_projection_and_predicate(pq_file):
    path, tbl = pq_file
    pred = E.BinaryExpr(E.BinaryOp.GTEQ, E.Column("id"), E.Literal(990, T.I64))
    node = scan_node_for_files([path], projection=["name", "id"], predicate=pred)
    op = build_operator(node)
    out = collect_pydict(op)
    assert list(out.keys()) == ["name", "id"]
    # pushdown prunes row groups; engine-level filter still required for
    # exact rows, but here the predicate aligns with row-group bounds
    assert min(out["id"]) >= 900  # at most one row group survives


def test_scan_partition_values(pq_file, tmp_path):
    path, _ = pq_file
    schema = T.schema_from_arrow(pq.read_schema(path))
    conf = N.FileScanConf(
        file_groups=[N.FileGroup(files=[
            N.PartitionedFile(path, os.path.getsize(path), partition_values=("2024-01-01",))
        ])],
        file_schema=schema,
        projection=[0],
        partition_schema=T.Schema.of(("ds", T.STRING)),
    )
    op = build_operator(N.ParquetScan(conf))
    out = collect_pydict(op)
    assert set(out["ds"]) == {"2024-01-01"}
    assert len(out["id"]) == 1000


def test_sink_roundtrip(tmp_path):
    scan = mem_scan({"a": list(range(50)), "s": [f"x{i}" for i in range(50)]},
                    num_batches=3)
    out_dir = str(tmp_path / "out")
    sink = ParquetSinkExec(scan, out_dir)
    assert run_op(sink) == []
    files = [os.path.join(out_dir, f) for f in os.listdir(out_dir)]
    tbl = pq.read_table(files)
    assert sorted(tbl["a"].to_pylist()) == list(range(50))


def test_sink_dynamic_partitions(tmp_path):
    scan = mem_scan({
        "v": list(range(20)),
        "part": [f"p{i % 3}" for i in range(20)],
    })
    out_dir = str(tmp_path / "dyn")
    sink = ParquetSinkExec(scan, out_dir, num_dyn_parts=1)
    run_op(sink)
    subdirs = sorted(os.listdir(out_dir))
    assert subdirs == ["part=p0", "part=p1", "part=p2"]
    tbl = pq.read_table(os.path.join(out_dir, "part=p1"))
    assert all(v % 3 == 1 for v in tbl["v"].to_pylist())
    assert "part" not in tbl.schema.names


def test_q01_style_end_to_end(pq_file):
    """scan -> filter -> partial agg -> exchange -> final agg -> sort+limit:
    the minimum end-to-end slice of SURVEY.md §7.3, driven through Session."""
    path, tbl = pq_file
    scan = scan_node_for_files([path], num_partitions=1)
    filt = N.Filter(scan, [E.BinaryExpr(E.BinaryOp.LT, E.Column("id"),
                                        E.Literal(500, T.I64))])
    partial = N.Agg(filt, E.AggExecMode.HASH_AGG, [("name", E.Column("name"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("amt")],
                              T.DecimalType(19, 2)), E.AggMode.PARTIAL, "total"),
    ])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([E.Column("name")], 3))
    final = N.Agg(ex, E.AggExecMode.HASH_AGG, [("name", E.Column("name"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("amt")],
                              T.DecimalType(19, 2)), E.AggMode.FINAL, "total"),
    ])
    single = N.ShuffleExchange(final, N.SinglePartitioning(1))
    plan = N.Sort(single, [E.SortOrder(E.Column("total"), ascending=False)],
                  fetch_limit=3)
    sess = Session()
    out = sess.execute_to_pydict(plan)

    df = tbl.to_pandas()
    df = df[df.id < 500]
    exp = df.groupby("name").amt.sum().sort_values(ascending=False).head(3)
    assert out["name"] == exp.index.tolist()
    assert out["total"] == exp.tolist()


def test_scan_byte_range_splits(tmp_path):
    """One file split into two byte-range partitions: every row group is
    owned by exactly one split, union covers all rows."""
    tbl = pa.table({"x": pa.array(range(10_000), type=pa.int64())})
    path = str(tmp_path / "split.parquet")
    pq.write_table(tbl, path, row_group_size=1000)
    size = os.path.getsize(path)
    mid = size // 2
    schema = T.schema_from_arrow(pq.read_schema(path))
    conf = N.FileScanConf(
        file_groups=[
            N.FileGroup(files=[N.PartitionedFile(path, size, N.FileRange(0, mid))]),
            N.FileGroup(files=[N.PartitionedFile(path, size, N.FileRange(mid, size))]),
        ],
        file_schema=schema,
        projection=[0],
    )
    op = build_operator(N.ParquetScan(conf))
    per_part = []
    from blaze_tpu.ops.base import ExecContext

    for p in range(2):
        rows = []
        for b in op.execute(p, ExecContext()):
            rows.extend(b.to_pydict()["x"])
        per_part.append(rows)
    assert len(per_part[0]) > 0 and len(per_part[1]) > 0
    assert sorted(per_part[0] + per_part[1]) == list(range(10_000))


def test_session_task_retry(tmp_path):
    """A flaky map task succeeds on the automatic retry."""
    from blaze_tpu.core import ColumnarBatch
    from blaze_tpu.runtime.session import Session

    attempts = {"n": 0}
    b = ColumnarBatch.from_pydict({"v": [1, 2, 3]})

    def flaky_src(p):
        attempts["n"] += 1
        if attempts["n"] == 1:
            raise RuntimeError("transient failure")
        return [b.to_arrow()]

    sess = Session()
    sess.resources["src"] = flaky_src
    scan = N.FFIReader(schema=b.schema, resource_id="src", num_partitions=1)
    plan = N.ShuffleExchange(scan, N.SinglePartitioning(1))
    out = sess.execute_to_pydict(plan)
    assert out["v"] == [1, 2, 3]
    assert attempts["n"] == 2


def test_scan_projection_case_insensitive(pq_file):
    path, tbl = pq_file
    node = scan_node_for_files([path], projection=["ID", "Name"])
    op = build_operator(node)
    out = collect_pydict(op)
    assert out["id"] == tbl["id"].to_pylist()
    assert out["name"] == tbl["name"].to_pylist()

"""Sharded device-primary execution over the mesh (ISSUE 14): tier
negotiation for the "device" tier, bit-identical results across 1/2/8
device meshes (both on the two-stage micro plan and on the five bench
shapes), device-resident shuffle hand-off matching the shm tier bit for
bit, lineage recovery over device-tier segments, and the ``device.put``
failpoint degrading device -> host staging with unchanged results.

The suite runs under conftest's forced 8-host-device CPU mesh
(``--xla_force_host_platform_device_count=8``), so every mesh size here
is real: quick-tier inclusion makes the smoke run exercise actual
multi-device sharding on every box."""

import glob
import os

import numpy as np
import pytest

from blaze_tpu.config import Config, config_override
from blaze_tpu.core import ColumnarBatch
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.runtime.session import Session


def _col(n):
    return E.Column(n)


def _summed(sess, name: str) -> int:
    """Sum one metric across the session's whole metric tree."""
    total = 0

    def walk(node):
        nonlocal total
        total += node.get("values", {}).get(name, 0)
        for c in node.get("children", []):
            walk(c)

    walk(sess.metrics.to_dict())
    return total


_TRACKED = ("shuffle_bytes_serialized", "serde_elided_batches",
            "sharded_stages", "collective_bytes", "device_shuffle_bytes",
            "shuffle_tier_degraded", "sharded_batches")


def _two_stage_plan(batch_parts, reducers=4):
    """partial agg -> hash exchange -> final agg -> single-collect sort:
    the same micro plan the zero-copy suite gates, now over the mesh."""
    schema = batch_parts[0][0].schema
    scan = N.FFIReader(schema=schema, resource_id="src",
                       num_partitions=len(batch_parts))
    partial = N.Agg(scan, E.AggExecMode.HASH_AGG, [("k", _col("k"))],
                    [N.AggColumn(E.AggExpr(E.AggFunction.SUM, [_col("v")],
                                           T.I64),
                                 E.AggMode.PARTIAL, "s")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([_col("k")], reducers))
    final = N.Agg(ex, E.AggExecMode.HASH_AGG, [("k", _col("k"))],
                  [N.AggColumn(E.AggExpr(E.AggFunction.SUM, [_col("v")],
                                         T.I64),
                               E.AggMode.FINAL, "s")])
    return N.Sort(N.ShuffleExchange(final, N.SinglePartitioning(1)),
                  [E.SortOrder(_col("k"))])


def _make_parts(seed=7, n=20_000, nparts=4):
    rng = np.random.default_rng(seed)
    b = ColumnarBatch.from_pydict({
        "k": rng.integers(0, 300, n).tolist(),
        "v": rng.integers(0, 1000, n).tolist()})
    per = n // nparts
    return [[b.slice(i * per, per)] for i in range(nparts)]


def _run(parts, **conf_kw):
    with config_override(**conf_kw):
        with Session() as sess:
            sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
            out = sess.execute_to_table(_two_stage_plan(parts))
            metrics = {m: _summed(sess, m) for m in _TRACKED}
    return out, metrics


# -- tier negotiation ---------------------------------------------------------


@pytest.mark.quick
def test_tier_negotiation_device(eight_devices):
    with Session() as sess:  # multichip off by default: process tier
        assert sess.mesh is None
        assert sess._shuffle_tier() == "process"
    with Session(conf=Config(zero_copy_tier="device")) as sess:  # pinned
        assert sess._shuffle_tier() == "device"
    with Session(conf=Config(multichip_enabled=True)) as sess:
        assert sess.mesh is not None  # session builds the mesh itself
        assert sess._shuffle_tier() == "device"
        # a worker pool forces shm: device-array references cannot cross
        # process boundaries any more than host batch references can
        sess.pool = object()
        assert sess._shuffle_tier() == "shm"
        sess.pool = None
    with Session(conf=Config(multichip_enabled=True,
                             device_shuffle_tier=False)) as sess:
        assert sess._shuffle_tier() == "process"
    with Session(conf=Config(multichip_enabled=True,
                             multichip_devices=2)) as sess:
        assert sess.mesh.devices.size == 2


# -- bit-identity across mesh sizes -------------------------------------------


@pytest.mark.quick
def test_multichip_bit_identical_across_meshes(eight_devices):
    """The multichip contract: the same plan over 1/2/8-device meshes
    returns byte-for-byte the single-process result, with the mesh
    collective actually engaged and zero shuffle bytes serialized."""
    parts = _make_parts(seed=21)
    ref, _ = _run(parts)
    for k in (1, 2, 8):
        out, m = _run(parts, multichip_enabled=True, multichip_devices=k)
        assert out.equals(ref), f"{k}-device mesh diverged"
        assert m["shuffle_bytes_serialized"] == 0
        assert m["sharded_stages"] > 0, \
            f"{k}-device mesh never lowered an exchange onto the collective"
        assert m["collective_bytes"] > 0


def test_multichip_composes_with_fused_sharding(eight_devices):
    """More map partitions than devices: the fused stage's batch-stacking
    runner and the mesh exchange compose, still bit-identical."""
    parts = _make_parts(seed=24, n=64_000, nparts=8)
    ref, _ = _run(parts)
    out, m = _run(parts, multichip_enabled=True, multichip_devices=8)
    assert out.equals(ref)
    assert m["sharded_stages"] > 0


# -- device-resident shuffle tier ---------------------------------------------


@pytest.mark.quick
def test_device_tier_matches_shm_tier(eight_devices):
    """Device-resident inter-stage hand-off returns exactly what the shm
    tier returns, with zero serialized bytes and the device-resident
    byte tripwire counting the handed-off columns."""
    parts = _make_parts(seed=22)
    dev_out, dev_m = _run(parts, zero_copy_tier="device")
    shm_out, _ = _run(parts, zero_copy_tier="shm")
    assert dev_out.equals(shm_out)
    assert dev_m["shuffle_bytes_serialized"] == 0
    assert dev_m["device_shuffle_bytes"] > 0, \
        "device tier must hand device-resident batches to the reducer"


def test_device_tier_marker_deletion_recovers(eight_devices):
    """PR 9 lineage composes with the device tier: device-resident
    segments publish footer-only markers, and chaos-deleting one
    recomputes the map through ordinary recovery — results unchanged."""
    from blaze_tpu.runtime.recovery import FOOTER_LEN
    from blaze_tpu.runtime.session import _QueryRun

    parts = _make_parts(seed=23)
    with config_override(zero_copy_tier="device"):
        with Session() as sess:
            sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
            oracle = sess.execute_to_table(_two_stage_plan(parts))

            before = set(glob.glob(os.path.join(
                sess.shuffle_root, "shuffle_*", "map_*.data")))
            qrun = _QueryRun(0)
            sess._tls.qrun = qrun
            lowered = sess._lower(_two_stage_plan(parts))
            sess._tls.qrun = None
            files = [f for f in sorted(glob.glob(os.path.join(
                sess.shuffle_root, "shuffle_*", "map_*.data")))
                if f not in before]
            assert files, "device tier must still publish marker files"
            assert any(os.path.getsize(f) == FOOTER_LEN for f in files), \
                "device-committed maps publish footer-only markers"
            os.remove(files[0])
            assert sess.execute_to_table(lowered).equals(oracle)


def test_mesh_session_recovers_host_staged_stage(eight_devices):
    """A multichip session whose exchange is FORCED onto the host path
    (placement override) still stages through the registry and still
    recovers a deleted marker — the mesh gate and lineage compose."""
    from blaze_tpu.runtime.session import _QueryRun

    parts = _make_parts(seed=25)
    with config_override(multichip_enabled=True, device_placement="host"):
        with Session() as sess:
            sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
            oracle = sess.execute_to_table(_two_stage_plan(parts))
            assert _summed(sess, "sharded_stages") == 0, \
                "host force must keep exchanges off the collective"

            qrun = _QueryRun(0)
            sess._tls.qrun = qrun
            lowered = sess._lower(_two_stage_plan(parts))
            sess._tls.qrun = None
            files = sorted(glob.glob(os.path.join(
                sess.shuffle_root, "shuffle_*", "map_*.data")))
            assert files
            os.remove(files[0])
            assert sess.execute_to_table(lowered).equals(oracle)


# -- failpoint degrade --------------------------------------------------------


def test_device_put_failpoint_degrades_to_host(eight_devices):
    """PR 12's failpoint plane reaches the new tier: ``device.put=enospc``
    makes on-chip bucketize fail, the writer degrades device -> host
    staging per the tier ladder, and the results are unchanged."""
    parts = _make_parts(seed=26)
    out, m = _run(parts, zero_copy_tier="device",
                  failpoints="device.put=enospc")
    ref, _ = _run(parts, zero_copy_shuffle=False)
    assert out.equals(ref)
    assert m["shuffle_tier_degraded"] > 0, \
        "the failpoint must actually trip the device tier"


# -- the five bench shapes across mesh sizes ----------------------------------


@pytest.fixture(scope="module")
def bench_paths(tmp_path_factory):
    import bench

    bench.ROWS = 60_000
    bench.PARTS = 2
    td = str(tmp_path_factory.mktemp("mcbench"))
    return bench.make_data(td)


@pytest.mark.quick
@pytest.mark.parametrize("shape", ["q01", "q06", "q17", "q47", "q67"])
def test_bench_shapes_identical_across_meshes(bench_paths, shape,
                                              eight_devices):
    """Each bench shape under device-primary execution must return
    byte-for-byte the same table at 1, 2 and 8 mesh devices."""
    import bench

    plan_fn = {s[0]: s[1] for s in bench.SHAPES}[shape]
    tables = []
    for k in (1, 2, 8):
        with config_override(multichip_enabled=True, multichip_devices=k):
            with Session() as sess:
                tables.append(sess.execute_to_table(plan_fn(bench_paths)))
    assert tables[0].equals(tables[1]), f"{shape}: 1 vs 2 devices diverged"
    assert tables[0].equals(tables[2]), f"{shape}: 1 vs 8 devices diverged"

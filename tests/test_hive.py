"""Hive metastore client + Hive UDF translation (blaze_tpu/hive.py;
reference roles: HiveClientHelper / NativeHiveTableScanBase / HiveUDFUtil).
Covers: the HMS object model round trip from a JSON dump, catalog bridging
with partition locations (NOT directory discovery), partition pruning
through HiveTableScanExec conversion, builtin Hive UDF translation, and
the unknown-UDF fallback."""

import json

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.hive import (HIVE_UDAF_CLASSES, HiveMetastore,
                            convert_hive_udf)
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T
from blaze_tpu.runtime.session import Session


@pytest.fixture()
def metastore(tmp_path):
    """A partitioned hive table whose partitions live in ARBITRARY
    locations (the metastore contract) + a JSON HMS dump of it."""
    locs = {}
    for year in (1998, 1999):
        d = tmp_path / f"anywhere_{year}"
        d.mkdir()
        n = 50
        rng = np.random.default_rng(year)
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 5, n), type=pa.int64()),
            "v": pa.array(rng.integers(0, 100, n), type=pa.int64()),
        }), str(d / "part-000.parquet"))
        locs[year] = str(d)
    dump = {"databases": {"default": {"sales": {
        "location": str(tmp_path),
        "inputFormat": "org.apache.hadoop.hive.ql.io.parquet."
                       "MapredParquetInputFormat",
        "cols": [["k", "bigint"], ["v", "bigint"]],
        "partitionKeys": [["year", "int"]],
        "partitions": [{"values": [str(y)], "location": loc}
                       for y, loc in locs.items()],
    }}}}
    path = tmp_path / "hms_dump.json"
    path.write_text(json.dumps(dump))
    return path, locs


def test_metastore_object_model(metastore):
    path, locs = metastore
    ms = HiveMetastore.from_json(str(path))
    t = ms.get_table("default", "sales")
    assert t.fmt == "parquet"
    assert t.partition_keys == [("year", "int")]
    assert len(ms.get_partitions("default", "sales")) == 2
    assert ms.get_all_tables("default") == ["sales"]
    with pytest.raises(KeyError):
        ms.get_table("default", "nope")


def test_catalog_bridge_resolves_partition_locations(metastore):
    path, locs = metastore
    cat = HiveMetastore.from_json(str(path)).as_catalog("default")
    t = cat.tables["sales"]
    files = dict((v[0], p) for p, v in t.files)
    # files come from the metastore locations, which are NOT under one root
    assert set(files) == {1998, 1999}
    assert files[1998].startswith(locs[1998])
    plan = cat.scan_node("sales", num_partitions=2)
    with Session() as s:
        out = s.execute_to_table(plan).to_pandas()
    assert len(out) == 100
    assert sorted(out.year.unique()) == [1998, 1999]


def test_hive_table_scan_exec_converts_with_pruning(metastore, tmp_path):
    from tests.tpcds.plans import Attrs, binop, lit

    path, locs = metastore
    ms = HiveMetastore.from_json(str(path))
    a = Attrs()
    a.define("k", "long")
    a.define("v", "long")
    a.define("year", "integer")
    X = "org.apache.spark.sql.catalyst.expressions"
    node = [{"class": "org.apache.spark.sql.hive.execution."
                      "HiveTableScanExec",
             "num-children": 0,
             "requestedAttributes": [a("k"), a("v"), a("year")],
             "relation": {"tableMeta": {"identifier": {"table": "sales",
                                                       "database":
                                                       "default"}}},
             "partitionPruningPred": [
                 binop("EqualTo", a("year"), lit(1999, "integer"))]}]
    from blaze_tpu.frontend.converter import SparkPlanConverter

    conv = SparkPlanConverter(catalog=ms.as_catalog("default"))
    result = conv.convert(json.dumps(node))
    assert not [t for t in result.tags if "fallback" in t[1]], result.tags
    with Session() as s:
        out = s.execute_to_table(result.plan).to_pandas()
    assert len(out) == 50  # 1998's partition pruned before IO
    assert set(out.iloc[:, 2].unique()) == {1999}


def test_hive_udf_translation_end_to_end():
    """HiveGenericUDF nodes (funcWrapper class names) convert to engine
    expressions and evaluate; unknown classes raise -> frontend fallback."""
    from blaze_tpu.core.batch import ColumnarBatch
    from blaze_tpu.exprs.compiler import ExprEvaluator

    upper = convert_hive_udf("org.apache.hadoop.hive.ql.udf.UDFUpper",
                             [E.Column("s")])
    assert isinstance(upper, E.ScalarFunction) and upper.name == "upper"
    plus = convert_hive_udf(
        "org.apache.hadoop.hive.ql.udf.generic.GenericUDFOPPlus",
        [E.Column("x"), E.Literal(1, T.I64)])
    b = ColumnarBatch.from_arrow(pa.table({
        "s": pa.array(["ab", None]), "x": pa.array([1, 2],
                                                   type=pa.int64())}))
    ev = ExprEvaluator([upper, plus], b.schema)
    out = [c.to_arrow(2).to_pylist() for c in ev.evaluate(b)]
    assert out == [["AB", None], [2, 3]]
    with pytest.raises(KeyError):
        convert_hive_udf("com.example.MyCustomUDF", [])


def test_hive_udf_through_frontend_with_fallback():
    from blaze_tpu.frontend.exprs import UnsupportedExpr, convert_expr
    from blaze_tpu.frontend.treenode import decode

    X = "org.apache.spark.sql"
    def udf_node(cls_name):
        return decode([
            {"class": f"{X}.hive.HiveSimpleUDF", "num-children": 1,
             "funcWrapper": {"functionClassName": cls_name},
             "name": "f", "children": [0], "dataType": "string"},
            {"class": f"{X}.catalyst.expressions.AttributeReference",
             "num-children": 0, "name": "s", "dataType": "string",
             "nullable": True, "metadata": {},
             "exprId": {"id": 1, "jvmId": ""}, "qualifier": []}])

    e = convert_expr(udf_node("org.apache.hadoop.hive.ql.udf.UDFLower"),
                     {1: "s"})
    assert isinstance(e, E.ScalarFunction) and e.name == "lower"
    with pytest.raises(UnsupportedExpr):
        convert_expr(udf_node("com.example.Unknown"), {1: "s"})


def test_brickhouse_udaf_classes_map_to_native_aggs():
    assert HIVE_UDAF_CLASSES["brickhouse.udf.collect.CollectUDAF"] == \
        E.AggFunction.BRICKHOUSE_COLLECT


def test_empty_table_scans_via_declared_schema(tmp_path):
    """A metastore table with zero partitions must still convert and scan
    (EmptyPartitions from the declared HMS schema), not crash."""
    ms = HiveMetastore()
    ms.create_table("default", "empty_t", str(tmp_path),
                    [("k", "bigint"), ("v", "string")],
                    [("year", "int")])
    cat = ms.as_catalog("default")
    plan = cat.scan_node("empty_t", num_partitions=2)
    with Session() as s:
        out = s.execute_to_table(plan)
    assert out.num_rows == 0
    assert out.schema.names == ["k", "v", "year"]


def test_unsupported_format_table_skipped_not_fatal(tmp_path):
    ms = HiveMetastore()
    ms.create_table("default", "good", str(tmp_path), [("k", "bigint")])
    ms.create_table("default", "textual", str(tmp_path), [("k", "string")],
                    input_format="org.apache.hadoop.mapred.TextInputFormat")
    cat = ms.as_catalog("default")
    assert "good" in cat.tables
    assert "textual" not in cat.tables


def test_date_partition_values_coerce_to_epoch_days(tmp_path):
    from blaze_tpu.hive import _coerce_part

    assert _coerce_part("1970-01-02", T.DATE) == 1
    assert _coerce_part("1999-01-01", T.DATE) == 10592

"""Multi-process shuffle execution (VERDICT round-1 item 5): map tasks run
in OS worker processes over the proto TaskDefinition wire contract, with
task retry surviving worker loss (reference: Spark executors + task
rescheduling, AuronShuffleManager.scala:28-235, SURVEY.md §5.3)."""

import decimal
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.runtime.session import Session
from tests.util import CrashOnce


def _q01(paths, parts=2, reducers=3):
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files(paths, num_partitions=parts)
    filt = N.Filter(scan, [E.BinaryExpr(
        E.BinaryOp.GT, E.Column("amt"),
        E.Literal("500.00", T.DecimalType(9, 2)))])
    partial = N.Agg(filt, E.AggExecMode.HASH_AGG,
                    [("store", E.Column("store"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("amt")],
                              T.DecimalType(17, 2)), E.AggMode.PARTIAL, "total"),
        N.AggColumn(E.AggExpr(E.AggFunction.COUNT, []), E.AggMode.PARTIAL, "cnt"),
    ])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([E.Column("store")], reducers))
    final = N.Agg(ex, E.AggExecMode.HASH_AGG,
                  [("store", E.Column("store"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("amt")],
                              T.DecimalType(17, 2)), E.AggMode.FINAL, "total"),
        N.AggColumn(E.AggExpr(E.AggFunction.COUNT, []), E.AggMode.FINAL, "cnt"),
    ])
    single = N.ShuffleExchange(final, N.SinglePartitioning(1))
    return N.Sort(single, [E.SortOrder(E.Column("total"), ascending=False)])


@pytest.fixture(scope="module")
def q01_files(tmp_path_factory):
    td = tmp_path_factory.mktemp("clusterdata")
    rng = np.random.default_rng(23)
    paths = []
    for p in range(2):
        n = 8000
        amt = pa.array([decimal.Decimal(int(v)).scaleb(-2)
                        for v in rng.integers(0, 100000, n)],
                       type=pa.decimal128(9, 2))
        tbl = pa.table({
            "store": pa.array(rng.integers(1, 40, n), type=pa.int64()),
            "amt": amt,
        })
        path = str(td / f"f{p}.parquet")
        pq.write_table(tbl, path)
        paths.append(path)
    return paths


@pytest.mark.slow
def test_bench_plan_on_worker_processes(q01_files):
    plan = _q01(q01_files)
    with Session() as s_local:
        expect = s_local.execute_to_table(plan).to_pydict()
    with Session(num_worker_processes=2) as s:
        got = s.execute_to_table(plan).to_pydict()
        # both shuffle stages must actually have run on the pool (the
        # in-driver fallback would hide serialization regressions)
        stage_rows = s.metrics.named_child("stage_0").total("output_rows")
    assert got == expect
    assert len(got["store"]) > 0


@pytest.mark.slow
def test_survives_worker_loss(q01_files):
    """Killing a worker makes its queued/running tasks retry on a respawned
    process; the query still completes exactly."""
    plan = _q01(q01_files)
    with Session() as s_local:
        expect = s_local.execute_to_table(plan).to_pydict()
    with Session(num_worker_processes=2) as s:
        s.pool.kill_worker(0)  # executor loss before the map stage
        got = s.execute_to_table(plan).to_pydict()
    assert got == expect




@pytest.mark.slow
def test_mid_task_crash_retries(q01_files, tmp_path):
    """A task that hard-kills its worker process on first attempt succeeds
    on retry (the marker file makes the second attempt clean)."""
    from blaze_tpu.ops.parquet import scan_node_for_files

    marker = str(tmp_path / "crashed.marker")
    scan = scan_node_for_files(q01_files, num_partitions=2)
    proj = N.Projection(scan, [
        E.Column("store"),
        E.PyUDF(CrashOnce(marker), [E.Column("store")], T.I64, "crash1"),
    ], ["store", "crashed"])
    plan = N.ShuffleExchange(proj, N.HashPartitioning([E.Column("store")], 2))
    with Session(num_worker_processes=2) as s:
        out = s.execute_to_table(plan).to_pydict()
    assert os.path.exists(marker), "first attempt must have crashed a worker"
    n = sum(pq.read_table(p).num_rows for p in q01_files)
    assert len(out["store"]) == n

import numpy as np
import pyarrow as pa
import pytest
from decimal import Decimal

from blaze_tpu.core import ColumnarBatch
from blaze_tpu.exprs.compiler import ExprEvaluator
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T


def run(exprs, data, schema=None):
    b = ColumnarBatch.from_pydict(data, schema)
    ev = ExprEvaluator(exprs, b.schema)
    cols = ev.evaluate(b)
    out = ColumnarBatch(
        T.Schema.of(*[(f"c{i}", c.dtype) for i, c in enumerate(cols)]), cols, b.num_rows
    )
    return out.to_pydict()


def col(name):
    return E.Column(name)


def lit(v, t):
    return E.Literal(v, t)


@pytest.mark.quick
def test_arith_nulls():
    out = run(
        [E.BinaryExpr(E.BinaryOp.ADD, col("a"), col("b")),
         E.BinaryExpr(E.BinaryOp.MUL, col("a"), lit(10, T.I64))],
        {"a": pa.array([1, None, 3], type=pa.int64()),
         "b": pa.array([10, 20, None], type=pa.int64())},
    )
    assert out["c0"] == [11, None, None]
    assert out["c1"] == [10, None, 30]


def test_division_by_zero_is_null():
    out = run(
        [E.BinaryExpr(E.BinaryOp.DIV, col("a"), col("b")),
         E.BinaryExpr(E.BinaryOp.MOD, col("a"), col("b"))],
        {"a": pa.array([7, 8, -7], type=pa.int64()),
         "b": pa.array([2, 0, 2], type=pa.int64())},
    )
    assert out["c0"] == [3, None, -3]  # java trunc division
    assert out["c1"] == [1, None, -1]


def test_float_division():
    out = run(
        [E.BinaryExpr(E.BinaryOp.DIV, col("a"), col("b"))],
        {"a": pa.array([1.0, 5.0], type=pa.float64()),
         "b": pa.array([4.0, 0.0], type=pa.float64())},
    )
    assert out["c0"] == [0.25, None]


def test_comparisons_and_kleene_logic():
    tbl = {"a": pa.array([1, 2, None], type=pa.int64())}
    gt = E.BinaryExpr(E.BinaryOp.GT, col("a"), lit(1, T.I64))
    out = run([gt], tbl)
    assert out["c0"] == [False, True, None]
    # (a > 1) AND null -> false where a<=1 (definite false), else null
    null_b = lit(None, T.BOOL)
    out = run([E.BinaryExpr(E.BinaryOp.AND, gt, null_b)], tbl)
    assert out["c0"] == [False, None, None]
    out = run([E.BinaryExpr(E.BinaryOp.OR, gt, null_b)], tbl)
    assert out["c0"] == [None, True, None]


def test_case_when():
    expr = E.Case(
        branches=[
            (E.BinaryExpr(E.BinaryOp.LT, col("a"), lit(0, T.I64)), lit(-1, T.I64)),
            (E.BinaryExpr(E.BinaryOp.EQ, col("a"), lit(0, T.I64)), lit(0, T.I64)),
        ],
        else_expr=lit(1, T.I64),
    )
    out = run([expr], {"a": pa.array([-5, 0, 7, None], type=pa.int64())})
    assert out["c0"] == [-1, 0, 1, 1]  # null comparisons are not true -> else


def test_case_no_else_gives_null():
    expr = E.Case(
        branches=[(E.BinaryExpr(E.BinaryOp.LT, col("a"), lit(0, T.I64)), lit(-1, T.I64))],
    )
    out = run([expr], {"a": pa.array([-5, 5], type=pa.int64())})
    assert out["c0"] == [-1, None]


def test_cast_float_to_int_java_semantics():
    out = run(
        [E.Cast(col("f"), T.I32)],
        {"f": pa.array([3.9, -3.9, float("nan"), 1e30, -1e30], type=pa.float64())},
    )
    assert out["c0"] == [3, -3, 0, 2**31 - 1, -(2**31)]


def test_cast_string_to_int():
    out = run(
        [E.Cast(col("s"), T.I64)],
        {"s": pa.array([" 42 ", "3.7", "abc", None])},
    )
    assert out["c0"] == [42, 3, None, None]


def test_cast_int_to_string():
    out = run([E.Cast(col("a"), T.STRING)], {"a": pa.array([1, None], type=pa.int64())})
    assert out["c0"] == ["1", None]


def test_cast_double_to_string_java_format():
    out = run([E.Cast(col("a"), T.STRING)],
              {"a": pa.array([1.0, 2.5, float("nan")], type=pa.float64())})
    assert out["c0"] == ["1.0", "2.5", "NaN"]


def test_in_list_null_semantics():
    tbl = {"a": pa.array([1, 4, None], type=pa.int64())}
    out = run([E.InList(col("a"), [lit(1, T.I64), lit(2, T.I64)])], tbl)
    assert out["c0"] == [True, False, None]
    # list containing null: non-match -> null
    out = run([E.InList(col("a"), [lit(1, T.I64), lit(None, T.I64)])], tbl)
    assert out["c0"] == [True, None, None]


def test_in_list_strings():
    out = run(
        [E.InList(col("s"), [lit("x", T.STRING), lit("y", T.STRING)])],
        {"s": pa.array(["x", "z", None])},
    )
    assert out["c0"] == [True, False, None]


def test_like():
    out = run(
        [E.Like(col("s"), "a%"), E.Like(col("s"), "_b"), E.Like(col("s"), "a%", negated=True)],
        {"s": pa.array(["abc", "ab", "xb", None])},
    )
    assert out["c0"] == [True, True, False, None]
    assert out["c1"] == [False, True, True, None]
    assert out["c2"] == [False, False, True, None]


def test_string_fast_paths():
    out = run(
        [E.StringStartsWith(col("s"), "ab"), E.StringEndsWith(col("s"), "c"),
         E.StringContains(col("s"), "b")],
        {"s": pa.array(["abc", "bcd", None])},
    )
    assert out["c0"] == [True, False, None]
    assert out["c1"] == [True, False, None]
    assert out["c2"] == [True, True, None]


def test_is_null_not():
    out = run(
        [E.IsNull(col("a")), E.IsNotNull(col("a")), E.Not(E.IsNull(col("a")))],
        {"a": pa.array([1, None], type=pa.int64())},
    )
    assert out["c0"] == [False, True]
    assert out["c1"] == [True, False]
    assert out["c2"] == [True, False]


def test_scalar_functions_dates():
    import datetime

    out = run(
        [E.ScalarFunction("year", [col("d")]), E.ScalarFunction("month", [col("d")]),
         E.ScalarFunction("day", [col("d")]),
         E.ScalarFunction("date_add", [col("d"), lit(10, T.I32)])],
        {"d": pa.array([datetime.date(2001, 3, 17), datetime.date(1969, 12, 31), None],
                       type=pa.date32())},
    )
    assert out["c0"] == [2001, 1969, None]
    assert out["c1"] == [3, 12, None]
    assert out["c2"] == [17, 31, None]
    assert out["c3"] == [datetime.date(2001, 3, 27), datetime.date(1970, 1, 10), None]


def test_civil_roundtrip_wide_range():
    import jax.numpy as jnp

    from blaze_tpu.exprs.functions import civil_from_days, days_from_civil

    days = jnp.arange(-150000, 150000, 37)
    y, m, d = civil_from_days(days)
    back = days_from_civil(y, m, d)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(days).astype(np.int32))


def test_string_functions():
    out = run(
        [E.ScalarFunction("upper", [col("s")]),
         E.ScalarFunction("substring", [col("s"), lit(2, T.I32), lit(2, T.I32)]),
         E.ScalarFunction("length", [col("s")]),
         E.ScalarFunction("concat_ws", [lit("-", T.STRING), col("s"), col("t")])],
        {"s": pa.array(["hello", None]), "t": pa.array(["x", "y"])},
    )
    assert out["c0"] == ["HELLO", None]
    assert out["c1"] == ["el", None]
    assert out["c2"] == [5, None]
    assert out["c3"] == ["hello-x", "y"]  # concat_ws skips nulls


def test_coalesce():
    out = run(
        [E.ScalarFunction("coalesce", [col("a"), col("b"), lit(0, T.I64)])],
        {"a": pa.array([1, None, None], type=pa.int64()),
         "b": pa.array([None, 5, None], type=pa.int64())},
    )
    assert out["c0"] == [1, 5, 0]


def test_decimal_arith():
    schema = T.Schema.of(("x", T.DecimalType(10, 2)), ("y", T.DecimalType(10, 2)))
    data = {
        "x": pa.array([Decimal("12.34"), Decimal("1.00")], type=pa.decimal128(10, 2)),
        "y": pa.array([Decimal("0.66"), Decimal("3.00")], type=pa.decimal128(10, 2)),
    }
    add = E.BinaryExpr(E.BinaryOp.ADD, col("x"), col("y"), result_type=T.DecimalType(11, 2))
    mul = E.BinaryExpr(E.BinaryOp.MUL, col("x"), col("y"), result_type=T.DecimalType(21, 4))
    div = E.BinaryExpr(E.BinaryOp.DIV, col("x"), col("y"), result_type=T.DecimalType(17, 6))
    out = run([add, mul, div], data, schema)
    assert out["c0"] == [Decimal("13.00"), Decimal("4.00")]
    assert out["c1"] == [Decimal("8.1444"), Decimal("3.0000")]
    assert out["c2"] == [Decimal("18.696970"), Decimal("0.333333")]


def test_decimal_overflow_nulls():
    schema = T.Schema.of(("x", T.DecimalType(4, 0)))
    data = {"x": pa.array([Decimal("9999"), Decimal("10")], type=pa.decimal128(4, 0))}
    mul = E.BinaryExpr(E.BinaryOp.MUL, col("x"), col("x"), result_type=T.DecimalType(4, 0))
    out = run([mul], data, schema)
    assert out["c0"] == [None, Decimal("100")]


def test_row_num():
    b1 = ColumnarBatch.from_pydict({"a": [10, 20]})
    b2 = ColumnarBatch.from_pydict({"a": [30]})
    ev = ExprEvaluator([E.RowNum()], b1.schema)
    c1 = ev.evaluate(b1)[0]
    c2 = ev.evaluate(b2)[0]
    assert np.asarray(c1.data[:2]).tolist() == [0, 1]
    assert np.asarray(c2.data[:1]).tolist() == [2]


def test_predicate_mask():
    b = ColumnarBatch.from_pydict({"a": pa.array([1, 5, None, 7], type=pa.int64())})
    ev = ExprEvaluator([E.BinaryExpr(E.BinaryOp.GT, col("a"), lit(2, T.I64))], b.schema)
    mask = np.asarray(ev.evaluate_predicate(b))
    assert mask[:4].tolist() == [False, True, False, True]
    assert not mask[4:].any()


def test_get_json_object():
    out = run(
        [E.ScalarFunction("get_json_object", [col("j"), lit("$.a.b", T.STRING)])],
        {"j": pa.array(['{"a":{"b":42}}', '{"a":{}}', "notjson", None])},
    )
    assert out["c0"] == ["42", None, None, None]


def test_named_struct_and_get_field():
    ns = E.NamedStruct(["x", "y"], [col("a"), col("b")])
    out = run(
        [E.GetIndexedField(ns, E.Literal(1, T.I32))],
        {"a": pa.array([1], type=pa.int64()), "b": pa.array(["s"])},
    )
    assert out["c0"] == ["s"]


def test_decimal_times_int_keeps_scale():
    schema = T.Schema.of(("x", T.DecimalType(7, 2)))
    data = {"x": pa.array([Decimal("10.00"), None], type=pa.decimal128(7, 2))}
    mul = E.BinaryExpr(E.BinaryOp.MUL, col("x"), lit(2, T.I32), result_type=T.DecimalType(9, 2))
    out = run([mul], data, schema)
    assert out["c0"] == [Decimal("20.00"), None]


def test_decimal_times_float():
    schema = T.Schema.of(("x", T.DecimalType(7, 2)))
    data = {"x": pa.array([Decimal("10.00")], type=pa.decimal128(7, 2))}
    mul = E.BinaryExpr(E.BinaryOp.MUL, col("x"), lit(0.5, T.F64), result_type=T.DecimalType(9, 2))
    out = run([mul], data, schema)
    assert out["c0"] == [Decimal("5.00")]


def test_review_fixes():
    import datetime

    # host literal broadcast in concat/coalesce
    out = run(
        [E.ScalarFunction("concat", [col("s"), lit("-x", T.STRING)]),
         E.ScalarFunction("coalesce", [col("s"), lit("z", T.STRING)])],
        {"s": pa.array(["a", None, "c"])},
    )
    assert out["c0"] == ["a-x", None, "c-x"]
    assert out["c1"] == ["a", "z", "c"]
    # exact big-int string parse
    out = run([E.Cast(col("s"), T.I64)],
              {"s": pa.array(["9223372036854775807", "9007199254740993",
                              "9223372036854775808"])})
    assert out["c0"] == [9223372036854775807, 9007199254740993, None]
    # ceil/floor on decimal
    schema = T.Schema.of(("x", T.DecimalType(10, 2)))
    data = {"x": pa.array([Decimal("1.23"), Decimal("-1.23")], type=pa.decimal128(10, 2))}
    out = run([E.ScalarFunction("ceil", [col("x")]),
               E.ScalarFunction("floor", [col("x")])], data, schema)
    assert out["c0"] == [2, -1]
    assert out["c1"] == [1, -2]
    # round with negative scale on ints
    out = run([E.ScalarFunction("round", [col("a"), lit(-2, T.I32)])],
              {"a": pa.array([123, 4567, -250], type=pa.int64())})
    assert out["c0"] == [100, 4600, -300]
    # lpad with multi-char fill
    out = run([E.ScalarFunction("lpad", [col("s"), lit(5, T.I32), lit("xy", T.STRING)])],
              {"s": pa.array(["ab", "abcdef"])})
    assert out["c0"] == ["xyxab", "abcde"]
    # BCE date round trip
    import jax.numpy as jnp
    from blaze_tpu.exprs.functions import civil_from_days, days_from_civil
    y = jnp.array([-2]); m = jnp.array([3]); d = jnp.array([1])
    days = days_from_civil(y, m, d)
    yy, mm, dd = civil_from_days(days)
    assert (int(yy[0]), int(mm[0]), int(dd[0])) == (-2, 3, 1)


def test_cse_distinct_udfs_not_merged():
    # two structurally-identical trees around different lambdas must not be
    # deduped by the CSE cache
    f = E.PyUDF(lambda a: pa.array([v + 1 for v in a.to_pylist()], type=pa.int64()),
                [col("a")], T.I64, "f")
    g = E.PyUDF(lambda a: pa.array([v * 100 for v in a.to_pylist()], type=pa.int64()),
                [col("a")], T.I64, "g")
    add0 = lambda u: E.BinaryExpr(E.BinaryOp.ADD, u, lit(0, T.I64))
    out = run([add0(f), add0(g)], {"a": pa.array([1, 2], type=pa.int64())})
    assert out["c0"] == [2, 3]
    assert out["c1"] == [100, 200]


def test_cse_shared_subtree_single_eval():
    calls = []

    def counting(a):
        calls.append(1)
        return pa.array([v + 1 for v in a.to_pylist()], type=pa.int64())

    # pure shared subtree evaluates once per batch; the PyUDF itself opts out
    shared = E.BinaryExpr(E.BinaryOp.MUL, col("a"), lit(3, T.I64))
    e1 = E.BinaryExpr(E.BinaryOp.ADD, shared, lit(1, T.I64))
    e2 = E.BinaryExpr(E.BinaryOp.ADD, shared, lit(2, T.I64))
    out = run([e1, e2], {"a": pa.array([1], type=pa.int64())})
    assert out == {"c0": [4], "c1": [5]}


def test_array_union():
    schema = T.Schema.of(("a", T.ArrayType(T.I64)), ("b", T.ArrayType(T.I64)))
    out = run(
        [E.ScalarFunction("array_union", [col("a"), col("b")])],
        {"a": [[1, 2, 2], None], "b": [[2, 3], [4]]},
        schema,
    )
    assert out["c0"] == [[1, 2, 3], [4]]


def test_array_union_null_semantics():
    schema = T.Schema.of(("a", T.ArrayType(T.I64)), ("b", T.ArrayType(T.I64)))
    out = run(
        [E.ScalarFunction("array_union", [col("a"), col("b")])],
        {"a": [None, [1]], "b": [None, None]},
        schema,
    )
    assert out["c0"] == [[], [1]]  # null U null = {} (never null)

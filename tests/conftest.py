"""Test fixture: force an 8-device virtual CPU mesh so distributed/sharding
paths are exercised without TPU hardware (SURVEY.md §4: the reference runs
its native-operator tests without a JVM; we run ours without a TPU)."""

import os
import sys

# Must be set before jax import. Force CPU: the suite validates semantics and
# the 8-device sharding paths; TPU-specific behavior is covered by
# scripts/tpu_smoke.py driven on real hardware.
os.environ["JAX_PLATFORMS"] = "cpu"
# Drop the TPU plugin's path entries entirely: its registration handshake can
# hang indefinitely when the device tunnel is wedged, even under a cpu pin —
# a cpu-only suite must never touch it.
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
os.environ["PYTHONPATH"] = os.pathsep.join(
    p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
    if p and ".axon_site" not in p)
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon TPU plugin force-registers itself via jax.config at import time,
# overriding JAX_PLATFORMS from the environment — pin the config directly.
jax.config.update("jax_platforms", "cpu")

import blaze_tpu  # noqa: E402,F401  (enables x64)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, devs
    return devs[:8]

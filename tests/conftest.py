"""Test fixture: force an 8-device virtual CPU mesh so distributed/sharding
paths are exercised without TPU hardware (SURVEY.md §4: the reference runs
its native-operator tests without a JVM; we run ours without a TPU)."""

import os

# Must be set before jax import.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

import blaze_tpu  # noqa: E402,F401  (enables x64)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, devs
    return devs[:8]

"""Radix-partitioned device hash aggregation (high-cardinality engine).

Covers the radix partial kernel and radix merge against the sort-path
oracle, the bucket-histogram-driven partial skipper, and the quick-tier
guards: a 100k-group device-agg smoke and ``agg_reintern_rows == 0`` on
the q67 bench shape (int keys never round-trip through host interning)."""

import collections

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu.config import config_override
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ops.agg import AggExec, _PartialSkipper
from blaze_tpu.ops.base import ExecContext
from blaze_tpu.runtime.metrics import MetricNode, tripwire_totals
from tests.util import mem_scan

F = E.AggFunction
M = E.AggMode
HASH = E.AggExecMode.HASH_AGG


def col(n):
    return E.Column(n)


def _two_stage(scan, keys, skipping=False):
    partial = AggExec(scan, HASH, [(k, col(k)) for k in keys], [
        N.AggColumn(E.AggExpr(F.SUM, [col("v")]), M.PARTIAL, "s"),
        N.AggColumn(E.AggExpr(F.COUNT, [col("v")]), M.PARTIAL, "c"),
    ], supports_partial_skipping=skipping)
    return AggExec(partial, HASH, [(k, col(k)) for k in keys], [
        N.AggColumn(E.AggExpr(F.SUM, [col("v")]), M.FINAL, "s"),
        N.AggColumn(E.AggExpr(F.COUNT, [col("v")]), M.FINAL, "c"),
    ])


def _collect(op, metrics=None):
    ctx = ExecContext()
    out = collections.defaultdict(list)
    for b in op.execute(0, ctx, metrics):
        for k, v in b.to_arrow().to_pydict().items():
            out[k].extend(v)
    return out


def _oracle(a, b, v):
    s = collections.defaultdict(int)
    c = collections.defaultdict(int)
    for ka, kb, kv in zip(a, b, v):
        s[(ka, kb)] += kv
        c[(ka, kb)] += 1
    return s, c


def _check(out, es, ec):
    got_s = dict(zip(zip(out["a"], out["b"]), out["s"]))
    got_c = dict(zip(zip(out["a"], out["b"]), out["c"]))
    assert got_s == dict(es)
    assert got_c == dict(ec)


def _hicard_scan(n=300_000, ka=2000, kb=100, num_batches=12, seed=5):
    # slot space ka.pow2 * kb.pow2 = 2048 * 128 > dense_agg_max_buckets,
    # so the bucketed planner must take the radix branch
    rng = np.random.default_rng(seed)
    a = rng.integers(0, ka, n)
    b = rng.integers(0, kb, n)
    v = rng.integers(0, 100, n)
    scan = mem_scan({
        "a": pa.array(a, type=pa.int64()),
        "b": pa.array(b, type=pa.int64()),
        "v": pa.array(v, type=pa.int64()),
    }, num_batches=num_batches)
    return scan, a.tolist(), b.tolist(), v.tolist()


@pytest.mark.quick
def test_radix_100k_group_smoke():
    """~190k groups through partial + radix merge, exact vs a host oracle."""
    scan, a, b, v = _hicard_scan()
    es, ec = _oracle(a, b, v)
    assert len(es) > 100_000
    root = MetricNode("root")
    with config_override(radix_agg=True):
        out = _collect(_two_stage(scan, ["a", "b"]), root)
    _check(out, es, ec)
    assert tripwire_totals(root)["agg_radix_buckets"] > 0


def test_radix_matches_sort_path():
    scan, a, b, v = _hicard_scan(n=60_000, seed=9)
    es, ec = _oracle(a, b, v)
    with config_override(radix_agg=True):
        radix = _collect(_two_stage(scan, ["a", "b"]))
    with config_override(radix_agg=False, dense_agg=False):
        host = _collect(_two_stage(scan, ["a", "b"]))
    _check(radix, es, ec)
    _check(host, es, ec)


@pytest.mark.quick
def test_q67_shape_no_reintern():
    """q67 bench shape (int composite keys, near-unique): keys stay device
    codes end to end — zero rows re-interned at the merge table."""
    scan, a, b, v = _hicard_scan(n=100_000, ka=2000, kb=400, num_batches=8,
                                 seed=67)
    es, ec = _oracle(a, b, v)
    root = MetricNode("root")
    with config_override(radix_agg=True):
        out = _collect(_two_stage(scan, ["a", "b"], skipping=True), root)
    _check(out, es, ec)
    tw = tripwire_totals(root)
    assert tw["agg_reintern_rows"] == 0
    assert tw["agg_radix_buckets"] > 0


def test_partial_skipping_near_unique_keys():
    """Near-unique keys flip the skipper; passthrough batches still merge
    to the exact answer."""
    scan, a, b, v = _hicard_scan(n=120_000, ka=2000, kb=400, num_batches=10,
                                 seed=3)
    es, ec = _oracle(a, b, v)
    root = MetricNode("root")
    with config_override(radix_agg=True, partial_agg_skipping_min_rows=20_000):
        out = _collect(_two_stage(scan, ["a", "b"], skipping=True), root)
    _check(out, es, ec)
    assert root.total("partial_skipped_batches") > 0


def test_partial_skipper_bucket_histograms():
    """The skipper decides from observed per-bucket cardinality, not the
    whole-table slot ratio."""
    ctx = ExecContext()
    with config_override(partial_agg_skipping_min_rows=10_000,
                         partial_agg_skipping_ratio=0.9):
        sk = _PartialSkipper(None, ExecContext())
        # low cardinality: many rows per bucket collapse to few groups
        sk.observe_buckets(np.full(256, 60, np.int64), np.full(256, 5, np.int64))
        assert not sk.should_skip()
        sk2 = _PartialSkipper(None, ExecContext())
        # near-unique: groups ~ rows in every bucket
        sk2.observe_buckets(np.full(256, 60, np.int64),
                            np.full(256, 59, np.int64))
        assert sk2.should_skip()
        sk3 = _PartialSkipper(None, ExecContext())
        # under min_rows with no table to fall back on: never skip
        sk3.observe_buckets(np.full(4, 10, np.int64), np.full(4, 10, np.int64))
        assert not sk3.should_skip()

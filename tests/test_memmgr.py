"""MemManager Wait/backpressure + WindowExec spill (VERDICT round-1 item 8).

Reference: ``memmgr/mod.rs:301-457`` — producers block on a condvar with
timeout while over-share peers spill; ``window_exec.rs`` buffering under the
memory manager's watch."""

import threading
import time

import numpy as np
import pyarrow as pa

from blaze_tpu.config import config_override
from blaze_tpu.ir import exprs as E
from blaze_tpu.runtime.memmgr import MemConsumer, MemManager
from tests.util import collect_pydict, mem_scan


class _Spillable(MemConsumer):
    def __init__(self, name):
        super().__init__(name, spillable=True)
        self.spilled = 0

    def spill(self):
        freed = self.mem_used
        self.spilled += 1
        return freed


def test_producer_blocks_until_peer_spills():
    """An under-share producer over budget must WAIT; it unblocks when the
    over-share peer spills (cooperatively, on the peer's own update)."""
    mgr = MemManager(total=1000, wait_timeout_s=30.0)
    hog = _Spillable("hog")
    small = _Spillable("small")
    mgr.register(hog)
    mgr.register(small)
    mgr.update(hog, 900)  # under budget so far

    timeline = {}

    def producer():
        t0 = time.monotonic()
        mgr.update(small, 200)  # total 1100 > 1000, small under share (500)
        timeline["unblocked_after"] = time.monotonic() - t0

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.3)
    assert t.is_alive(), "producer should be waiting for the peer to spill"
    assert hog.spill_requested, "over-share peer must be flagged"
    # peer reaches its next update -> cooperative spill -> waiter unblocks
    mgr.update(hog, 900)
    t.join(timeout=10)
    assert not t.is_alive()
    assert hog.spilled == 1
    assert small.spilled == 0
    assert timeline["unblocked_after"] >= 0.25
    assert mgr.wait_count == 1


def test_wait_timeout_spills_self():
    """If the flagged peer (owned by ANOTHER thread) never updates, the
    waiter spills itself after the timeout instead of wedging."""
    mgr = MemManager(total=1000, wait_timeout_s=0.3)
    hog = _Spillable("stalled-hog")
    small = _Spillable("small")
    t = threading.Thread(target=lambda: (mgr.register(hog),
                                         mgr.update(hog, 900)))
    t.start()
    t.join()  # hog lives on a (now-dead) foreign thread and never updates
    mgr.register(small)
    t0 = time.monotonic()
    mgr.update(small, 200)
    dt = time.monotonic() - t0
    assert small.spilled == 1, "waiter must self-spill after timeout"
    assert dt >= 0.25
    assert hog.spilled == 0


def test_same_thread_peer_never_blocks():
    """Peers owned by the calling thread cannot be advanced by waiting —
    the caller must make progress immediately (pipelines share one task
    thread)."""
    mgr = MemManager(total=1000, wait_timeout_s=5.0)
    up = _Spillable("upstream")
    down = _Spillable("downstream")
    mgr.register(up)
    mgr.register(down)
    mgr.update(up, 900)
    t0 = time.monotonic()
    mgr.update(down, 200)  # over budget, under share, peer on SAME thread
    assert time.monotonic() - t0 < 1.0, "must not stall on a same-thread peer"
    assert down.spilled == 1  # progress via self-spill
    assert up.spill_requested  # peer still flagged for its next update


def test_shrinking_update_never_blocks():
    mgr = MemManager(total=1000, wait_timeout_s=5.0)
    hog = _Spillable("hog")
    me = _Spillable("me")
    t = threading.Thread(target=lambda: (mgr.register(hog),
                                         mgr.update(hog, 900)))
    t.start()
    t.join()
    mgr.register(me)
    me.mem_used = 300  # simulate prior usage
    t0 = time.monotonic()
    mgr.update(me, 0)  # freeing while pool over budget must not wait
    assert time.monotonic() - t0 < 0.5
    assert me.spilled == 0


def test_over_share_caller_spills_immediately():
    mgr = MemManager(total=1000, wait_timeout_s=5.0)
    a = _Spillable("a")
    b = _Spillable("b")
    mgr.register(a)
    mgr.register(b)
    mgr.update(a, 400)
    t0 = time.monotonic()
    mgr.update(b, 700)  # over budget AND over share (500) -> spill self now
    assert time.monotonic() - t0 < 1.0
    assert b.spilled == 1


def test_window_buffer_spills_under_pressure():
    """A window over input larger than the budget spills its partition
    buffer and still produces exact results."""
    from blaze_tpu.ir.nodes import WindowExpr
    from blaze_tpu.ops.base import ExecContext
    from blaze_tpu.ops.sort import SortExec
    from blaze_tpu.ops.window import WindowExec
    from blaze_tpu.runtime.metrics import MetricNode

    n = 40_000
    rng = np.random.default_rng(3)
    data = {
        "g": pa.array(np.sort(rng.integers(0, 3, n)), type=pa.int64()),
        "o": pa.array(np.arange(n), type=pa.int64()),
    }
    MemManager.reset()
    try:
        with config_override(memory_total=150_000, memory_fraction=1.0,
                             mem_wait_timeout_s=0.2):
            scan = SortExec(mem_scan(data, num_batches=16),
                            [E.SortOrder(E.Column("g")), E.SortOrder(E.Column("o"))])
            op = WindowExec(scan, [WindowExpr("row_number", "rn")],
                            [E.Column("g")], [E.SortOrder(E.Column("o"))])
            ctx = ExecContext()
            m = MetricNode("root")
            rows = []
            rns = []
            for b in op.execute(0, ctx, m):
                d = b.to_pydict()
                rows.extend(d["g"])
                rns.extend(d["rn"])
            assert m.total("spill_count") >= 1, "window buffer must spill"
            # exact row_number per group
            expect = []
            counts = {}
            for g in rows:
                counts[g] = counts.get(g, 0) + 1
                expect.append(counts[g])
            assert rns == expect
    finally:
        MemManager.reset()

"""MemManager Wait/backpressure + WindowExec spill (VERDICT round-1 item 8).

Reference: ``memmgr/mod.rs:301-457`` — producers block on a condvar with
timeout while over-share peers spill; ``window_exec.rs`` buffering under the
memory manager's watch."""

import pytest
import threading
import time

import numpy as np
import pyarrow as pa

from blaze_tpu.config import config_override
from blaze_tpu.ir import exprs as E
from blaze_tpu.runtime.memmgr import MemConsumer, MemManager
from tests.util import collect_pydict, mem_scan


class _Spillable(MemConsumer):
    def __init__(self, name):
        super().__init__(name, spillable=True)
        self.spilled = 0

    def spill(self):
        freed = self.mem_used
        self.spilled += 1
        return freed


def test_producer_blocks_until_peer_spills():
    """An under-share producer over budget must WAIT; it unblocks when the
    over-share peer spills (cooperatively, on the peer's own update)."""
    mgr = MemManager(total=1000, wait_timeout_s=30.0)
    hog = _Spillable("hog")
    small = _Spillable("small")
    mgr.register(hog)
    mgr.register(small)
    mgr.update(hog, 900)  # under budget so far

    timeline = {}

    def producer():
        t0 = time.monotonic()
        mgr.update(small, 200)  # total 1100 > 1000, small under share (500)
        timeline["unblocked_after"] = time.monotonic() - t0

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.3)
    assert t.is_alive(), "producer should be waiting for the peer to spill"
    assert hog.spill_requested, "over-share peer must be flagged"
    # peer reaches its next update -> cooperative spill -> waiter unblocks
    mgr.update(hog, 900)
    t.join(timeout=10)
    assert not t.is_alive()
    assert hog.spilled == 1
    assert small.spilled == 0
    assert timeline["unblocked_after"] >= 0.25
    assert mgr.wait_count == 1


def test_wait_timeout_spills_self():
    """If the flagged peer (owned by ANOTHER thread) never updates, the
    waiter spills itself after the timeout instead of wedging."""
    mgr = MemManager(total=1000, wait_timeout_s=0.3)
    hog = _Spillable("stalled-hog")
    small = _Spillable("small")
    t = threading.Thread(target=lambda: (mgr.register(hog),
                                         mgr.update(hog, 900)))
    t.start()
    t.join()  # hog lives on a (now-dead) foreign thread and never updates
    mgr.register(small)
    t0 = time.monotonic()
    mgr.update(small, 200)
    dt = time.monotonic() - t0
    assert small.spilled == 1, "waiter must self-spill after timeout"
    assert dt >= 0.25
    assert hog.spilled == 0


def test_same_thread_peer_never_blocks():
    """Peers owned by the calling thread cannot be advanced by waiting —
    the caller must make progress immediately (pipelines share one task
    thread)."""
    mgr = MemManager(total=1000, wait_timeout_s=5.0)
    up = _Spillable("upstream")
    down = _Spillable("downstream")
    mgr.register(up)
    mgr.register(down)
    mgr.update(up, 900)
    t0 = time.monotonic()
    mgr.update(down, 200)  # over budget, under share, peer on SAME thread
    assert time.monotonic() - t0 < 1.0, "must not stall on a same-thread peer"
    assert down.spilled == 1  # progress via self-spill
    assert up.spill_requested  # peer still flagged for its next update


def test_shrinking_update_never_blocks():
    mgr = MemManager(total=1000, wait_timeout_s=5.0)
    hog = _Spillable("hog")
    me = _Spillable("me")
    t = threading.Thread(target=lambda: (mgr.register(hog),
                                         mgr.update(hog, 900)))
    t.start()
    t.join()
    mgr.register(me)
    me.mem_used = 300  # simulate prior usage
    t0 = time.monotonic()
    mgr.update(me, 0)  # freeing while pool over budget must not wait
    assert time.monotonic() - t0 < 0.5
    assert me.spilled == 0


@pytest.mark.quick
def test_over_share_caller_spills_immediately():
    mgr = MemManager(total=1000, wait_timeout_s=5.0)
    a = _Spillable("a")
    b = _Spillable("b")
    mgr.register(a)
    mgr.register(b)
    mgr.update(a, 400)
    t0 = time.monotonic()
    mgr.update(b, 700)  # over budget AND over share (500) -> spill self now
    assert time.monotonic() - t0 < 1.0
    assert b.spilled == 1


def test_window_buffer_spills_under_pressure():
    """A window over input larger than the budget spills its partition
    buffer and still produces exact results."""
    from blaze_tpu.ir.nodes import WindowExpr
    from blaze_tpu.ops.base import ExecContext
    from blaze_tpu.ops.sort import SortExec
    from blaze_tpu.ops.window import WindowExec
    from blaze_tpu.runtime.metrics import MetricNode

    n = 40_000
    rng = np.random.default_rng(3)
    data = {
        "g": pa.array(np.sort(rng.integers(0, 3, n)), type=pa.int64()),
        "o": pa.array(np.arange(n), type=pa.int64()),
    }
    MemManager.reset()
    try:
        with config_override(memory_total=150_000, memory_fraction=1.0,
                             mem_wait_timeout_s=0.2):
            scan = SortExec(mem_scan(data, num_batches=16),
                            [E.SortOrder(E.Column("g")), E.SortOrder(E.Column("o"))])
            op = WindowExec(scan, [WindowExpr("row_number", "rn")],
                            [E.Column("g")], [E.SortOrder(E.Column("o"))])
            ctx = ExecContext()
            m = MetricNode("root")
            rows = []
            rns = []
            for b in op.execute(0, ctx, m):
                d = b.to_pydict()
                rows.extend(d["g"])
                rns.extend(d["rn"])
            assert m.total("spill_count") >= 1, "window buffer must spill"
            # exact row_number per group
            expect = []
            counts = {}
            for g in rows:
                counts[g] = counts.get(g, 0) + 1
                expect.append(counts[g])
            assert rns == expect
    finally:
        MemManager.reset()


def test_window_streams_oversized_partition():
    """ONE window partition far larger than the memory budget. Ordered
    counters + ordered aggregates run SEGMENTED: only the open peer group is
    ever withheld, so the giant partition needs no buffering at all — zero
    spills, zero per-group loops, exact results. The whole-partition frame
    (no ORDER BY) genuinely must withhold the open partition until it
    closes: that hold spills under pressure and streams back out."""
    from decimal import Decimal

    from blaze_tpu.ir.nodes import WindowExpr
    from blaze_tpu.ir import types as T
    from blaze_tpu.ops.base import ExecContext
    from blaze_tpu.ops.window import WindowExec
    from blaze_tpu.runtime.metrics import MetricNode

    n = 60_000
    rng = np.random.default_rng(11)
    # single partition (constant key), order key with ties -> rank/dense
    # diverge from row_number; decimal argument exercises object cumsums
    okeys = np.sort(rng.integers(0, n // 7, n))
    vals = rng.integers(1, 1000, n)
    data = {
        "g": pa.array(np.zeros(n, dtype=np.int64), type=pa.int64()),
        "o": pa.array(okeys, type=pa.int64()),
        "v": pa.array([Decimal(int(v)).scaleb(-2) for v in vals],
                      type=pa.decimal128(7, 2)),
    }
    sum_agg = E.AggExpr(E.AggFunction.SUM, [E.Column("v")],
                        T.DecimalType(17, 2))
    avg_all = E.AggExpr(E.AggFunction.AVG, [E.Column("v")],
                        T.DecimalType(17, 6))
    MemManager.reset()
    try:
        with config_override(memory_total=400_000, memory_fraction=1.0,
                             mem_wait_timeout_s=0.2):
            scan = mem_scan(data, num_batches=24)
            op = WindowExec(
                scan,
                [WindowExpr("row_number", "rn"), WindowExpr("rank", "rk"),
                 WindowExpr("dense_rank", "dr"),
                 WindowExpr("agg", "rsum", agg=sum_agg)],
                [E.Column("g")], [E.SortOrder(E.Column("o"))])
            ctx = ExecContext()
            m = MetricNode("root")
            got = {"rn": [], "rk": [], "dr": [], "rsum": []}
            for b in op.execute(0, ctx, m):
                d = b.to_pydict()
                for k in got:
                    got[k].extend(d[k])
            assert m.total("spill_count") == 0, \
                "segmented path must not buffer the partition"
            assert m.total("window_group_loops") == 0, \
                "segmented path must never take the per-group loop"
            assert m.total("window_segments") == 1
            # oracle: numpy over the sorted single partition
            new_peer = np.concatenate([[True], okeys[1:] != okeys[:-1]])
            rn = np.arange(1, n + 1)
            rank = np.maximum.accumulate(np.where(new_peer, rn, 0))
            dense = np.cumsum(new_peer)
            csum = np.cumsum(vals)
            grp = dense - 1
            last_of_grp = np.concatenate(
                [np.nonzero(new_peer)[0][1:] - 1, [n - 1]])
            rsum = csum[last_of_grp[grp]]
            assert got["rn"] == rn.tolist()
            assert got["rk"] == rank.tolist()
            assert got["dr"] == dense.tolist()
            assert got["rsum"] == [Decimal(int(s)).scaleb(-2)
                                   for s in rsum.tolist()]

            # whole-partition frame (no ORDER BY): avg is one constant
            op2 = WindowExec(mem_scan(data, num_batches=24),
                             [WindowExpr("agg", "av", agg=avg_all)],
                             [E.Column("g")], [])
            m2 = MetricNode("root")
            av = []
            for b in op2.execute(0, ctx, m2):
                av.extend(b.to_pydict()["av"])
            assert m2.total("spill_count") >= 1, \
                "whole-partition hold must spill under pressure"
            assert m2.total("streamed_partitions") >= 1, \
                "spilled hold must stream back out"
            assert m2.total("window_group_loops") == 0
            expect = (Decimal(int(vals.sum())).scaleb(-2)
                      / n).quantize(Decimal("0.000001"))
            assert len(av) == n and set(av) == {expect}
    finally:
        MemManager.reset()

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu.config import config_override
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T
from blaze_tpu.ops.sort import SortExec
from blaze_tpu.runtime.memmgr import MemManager
from tests.util import collect_pydict, mem_scan


def so(name, asc=True, nulls_first=True):
    return E.SortOrder(E.Column(name), asc, nulls_first)


def test_sort_ints_asc_desc():
    data = {"a": pa.array([3, 1, None, 2], type=pa.int64()), "b": pa.array(list("wxyz"))}
    out = collect_pydict(SortExec(mem_scan(data), [so("a")]))
    assert out["a"] == [None, 1, 2, 3]
    assert out["b"] == ["y", "x", "z", "w"]
    out = collect_pydict(SortExec(mem_scan(data), [so("a", asc=False, nulls_first=False)]))
    assert out["a"] == [3, 2, 1, None]


@pytest.mark.quick
def test_sort_multi_key():
    data = {
        "a": pa.array([1, 2, 1, 2], type=pa.int64()),
        "b": pa.array([9.0, 1.0, 3.0, None], type=pa.float64()),
    }
    out = collect_pydict(SortExec(mem_scan(data, num_batches=2),
                                  [so("a"), so("b", asc=False, nulls_first=False)]))
    assert out["a"] == [1, 1, 2, 2]
    assert out["b"] == [9.0, 3.0, 1.0, None]


def test_sort_floats_nan_largest():
    data = {"a": pa.array([1.5, float("nan"), -0.0, None, 1e308], type=pa.float64())}
    out = collect_pydict(SortExec(mem_scan(data), [so("a", nulls_first=False)]))
    assert out["a"][:3] == [-0.0, 1.5, 1e308]
    assert out["a"][3] != out["a"][3]  # NaN before nulls-last
    assert out["a"][4] is None


def test_sort_strings_host_path():
    data = {"s": pa.array(["pear", "apple", None, "fig"])}
    out = collect_pydict(SortExec(mem_scan(data), [so("s")]))
    assert out["s"] == [None, "apple", "fig", "pear"]


def test_sort_dates_and_decimals():
    import datetime
    from decimal import Decimal

    data = {
        "d": pa.array([datetime.date(2020, 5, 1), datetime.date(1999, 1, 1), None],
                      type=pa.date32()),
        "m": pa.array([Decimal("1.10"), Decimal("-2.50"), Decimal("0.00")],
                      type=pa.decimal128(9, 2)),
    }
    out = collect_pydict(SortExec(mem_scan(data), [so("d", nulls_first=False)]))
    assert out["d"] == [datetime.date(1999, 1, 1), datetime.date(2020, 5, 1), None]
    out = collect_pydict(SortExec(mem_scan(data), [so("m")]))
    assert out["m"] == [Decimal("-2.50"), Decimal("0.00"), Decimal("1.10")]


def test_topk():
    rng = np.random.default_rng(0)
    vals = rng.integers(0, 10_000, size=5000).tolist()
    out = collect_pydict(SortExec(mem_scan({"a": vals}, num_batches=7),
                                  [so("a")], fetch_limit=10))
    assert out["a"] == sorted(vals)[:10]


def test_external_sort_with_spill():
    rng = np.random.default_rng(1)
    vals = rng.integers(-(10**9), 10**9, size=20_000).tolist()
    MemManager.reset()
    with config_override(memory_total=2_000_000, memory_fraction=1.0):
        out = collect_pydict(
            SortExec(mem_scan({"a": vals}, num_batches=10), [so("a")]))
    MemManager.reset()
    assert out["a"] == sorted(vals)
    assert len(out["a"]) == 20_000


def test_external_sort_strings_with_spill():
    rng = np.random.default_rng(2)
    vals = ["s" + str(rng.integers(0, 10**6)) for _ in range(5000)]
    MemManager.reset()
    with config_override(memory_total=300_000, memory_fraction=1.0):
        out = collect_pydict(
            SortExec(mem_scan({"s": vals}, num_batches=8), [so("s")]))
    MemManager.reset()
    assert out["s"] == sorted(vals)


def _batch_for_bucketize(n=4000, seed=3):
    rng = np.random.default_rng(seed)
    price = rng.random(n) * 100
    price[rng.random(n) < 0.05] = np.nan
    price_arr = price.astype(object)
    price_arr[rng.random(n) < 0.05] = None
    item = rng.integers(0, 1000, n)
    return {
        "price": pa.array([None if p is None else float(p) for p in price_arr],
                          type=pa.float64()),
        "item": pa.array(item, type=pa.int64()),
    }


def _pydict_of(sub):
    """HostBatch | ColumnarBatch -> pydict with NaN made comparable."""
    b = sub.to_columnar() if hasattr(sub, "items") else sub
    return {k: ["<nan>" if isinstance(v, float) and v != v else v
                for v in vs] for k, vs in b.to_pydict().items()}


@pytest.mark.quick
def test_bucketize_matches_mask_reference_all_partitioners():
    """The fused one-gather split must produce identical partition CONTENTS
    to the old per-partition boolean-mask take, for every partitioner
    type (device batches and staged host batches alike)."""
    from blaze_tpu.core.batch import ColumnarBatch, HostBatch
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import types as T
    from blaze_tpu.ops.shuffle.repartitioner import (
        HashPartitioner, RangePartitioner, RoundRobinPartitioner,
        SinglePartitioner)

    data = _batch_for_bucketize()
    schema = T.Schema.of(("price", T.F64), ("item", T.I64))
    batch = ColumnarBatch.from_pydict(data, schema)
    orders = [E.SortOrder(E.Column("price"), False, False),
              E.SortOrder(E.Column("item"), True, True)]
    prices = sorted(p for p in data["price"].to_pylist() if p is not None
                    and p == p)
    bounds = [(prices[len(prices) * (7 - i) // 8], int(i * 100))
              for i in range(7)]

    def mk_range():
        return RangePartitioner(orders, 8, bounds, schema)

    partitioners = [
        ("single", lambda: SinglePartitioner()),
        ("hash", lambda: HashPartitioner([E.Column("item")], 8, schema)),
        ("roundrobin", lambda: RoundRobinPartitioner(8)),
        ("range", mk_range),
    ]
    for name, mk in partitioners:
        # reference: per-partition boolean-mask takes over partition_ids
        pids = mk().partition_ids(batch)
        ref = {}
        for pid in sorted(set(pids.tolist())):
            idx = np.nonzero(pids == pid)[0].astype(np.int64)
            ref[pid] = _pydict_of(batch.take(idx))
        got_dev = {pid: _pydict_of(sub) for pid, sub in mk().bucketize(batch)}
        assert got_dev == ref, f"device bucketize mismatch ({name})"
        got_host = {pid: _pydict_of(sub)
                    for pid, sub in mk().bucketize_host(batch)}
        assert got_host == ref, f"host bucketize mismatch ({name})"

    # range device kernel and host searchsorted must agree row-by-row
    rp = mk_range()
    host = HostBatch.from_batch(batch)
    assert np.array_equal(rp.partition_ids(batch), rp.partition_ids_host(host))
    # routing is ordered: every row of partition p sorts <= rows of p+1
    parts = mk_range().bucketize(batch)
    from blaze_tpu.ops import sort_keys as SK

    last = None
    for pid, sub in parts:
        keys = SK.merge_keys_matrix(sub, orders)
        rows = [tuple(r) for r in keys]
        if last is not None and rows:
            assert last <= min(rows)
        if rows:
            last = max(rows)


def test_bucketize_one_gather_per_batch_counter():
    """Hot-path invariant: splitting B batches costs exactly B gathers (no
    per-partition take loop), observable via the repartitioner counters the
    shuffle writers surface as metrics."""
    from blaze_tpu.core.batch import ColumnarBatch
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import types as T
    from blaze_tpu.ops.shuffle.repartitioner import RangePartitioner

    schema = T.Schema.of(("price", T.F64), ("item", T.I64))
    orders = [E.SortOrder(E.Column("price"), True, True)]
    rp = RangePartitioner(orders, 4, [(25.0, 0), (50.0, 0), (75.0, 0)], schema)
    for seed in range(3):
        batch = ColumnarBatch.from_pydict(_batch_for_bucketize(seed=seed), schema)
        rp.bucketize_host(batch)
        rp.bucketize(batch)
    assert rp.split_batches == 6
    assert rp.split_gathers == 6


@pytest.mark.quick
def test_spill_merge_rides_packed_keys_only(monkeypatch):
    """Device-key spill merge must consume the squeezed #sortkey columns —
    never re-derive keys from data columns (merge_keys_matrix /
    host_keys_matrix stay un-called for the whole spilled query)."""
    from blaze_tpu.ops import sort_keys as SK

    def boom(*a, **k):  # pragma: no cover - only fires on regression
        raise AssertionError("merge re-derived sort keys from data columns")

    rng = np.random.default_rng(11)
    n = 30_000
    vals = (rng.random(n) * 1e6).astype(object)
    vals[rng.random(n) < 0.03] = None
    b = rng.integers(-(10**6), 10**6, n).tolist()
    data = {"a": vals.tolist(), "b": b}
    orders = [so("a", asc=False, nulls_first=False), so("b")]
    expect = collect_pydict(SortExec(mem_scan(data, num_batches=12), orders))
    MemManager.reset()
    monkeypatch.setattr(SK, "merge_keys_matrix", boom)
    monkeypatch.setattr(SK, "host_keys_matrix", boom)
    with config_override(memory_total=300_000, memory_fraction=1.0):
        out = collect_pydict(SortExec(mem_scan(data, num_batches=12), orders))
    mgr_spills = MemManager._instance.spill_count if MemManager._instance else 0
    MemManager.reset()
    assert mgr_spills > 0, "test must engage the spill path"
    assert out == expect


def test_external_sort_multikey_desc_nulls_with_spill():
    """Vectorized spilled-run merge (device-key path): multi-column keys,
    mixed directions, and NULL ordering must match the in-memory sort."""
    rng = np.random.default_rng(7)
    n = 30_000
    a = rng.integers(0, 50, n).astype(object)
    a[rng.random(n) < 0.05] = None
    b = rng.integers(-(10**6), 10**6, n).tolist()
    data = {"a": a.tolist(), "b": b}
    orders = [so("a", asc=False), so("b")]
    out_mem = collect_pydict(
        SortExec(mem_scan(data, num_batches=12), orders))
    MemManager.reset()
    with config_override(memory_total=1_500_000, memory_fraction=1.0):
        out_spill = collect_pydict(
            SortExec(mem_scan(data, num_batches=12), orders))
    MemManager.reset()
    assert out_spill == out_mem

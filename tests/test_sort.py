import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu.config import config_override
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T
from blaze_tpu.ops.sort import SortExec
from blaze_tpu.runtime.memmgr import MemManager
from tests.util import collect_pydict, mem_scan


def so(name, asc=True, nulls_first=True):
    return E.SortOrder(E.Column(name), asc, nulls_first)


def test_sort_ints_asc_desc():
    data = {"a": pa.array([3, 1, None, 2], type=pa.int64()), "b": pa.array(list("wxyz"))}
    out = collect_pydict(SortExec(mem_scan(data), [so("a")]))
    assert out["a"] == [None, 1, 2, 3]
    assert out["b"] == ["y", "x", "z", "w"]
    out = collect_pydict(SortExec(mem_scan(data), [so("a", asc=False, nulls_first=False)]))
    assert out["a"] == [3, 2, 1, None]


def test_sort_multi_key():
    data = {
        "a": pa.array([1, 2, 1, 2], type=pa.int64()),
        "b": pa.array([9.0, 1.0, 3.0, None], type=pa.float64()),
    }
    out = collect_pydict(SortExec(mem_scan(data, num_batches=2),
                                  [so("a"), so("b", asc=False, nulls_first=False)]))
    assert out["a"] == [1, 1, 2, 2]
    assert out["b"] == [9.0, 3.0, 1.0, None]


def test_sort_floats_nan_largest():
    data = {"a": pa.array([1.5, float("nan"), -0.0, None, 1e308], type=pa.float64())}
    out = collect_pydict(SortExec(mem_scan(data), [so("a", nulls_first=False)]))
    assert out["a"][:3] == [-0.0, 1.5, 1e308]
    assert out["a"][3] != out["a"][3]  # NaN before nulls-last
    assert out["a"][4] is None


def test_sort_strings_host_path():
    data = {"s": pa.array(["pear", "apple", None, "fig"])}
    out = collect_pydict(SortExec(mem_scan(data), [so("s")]))
    assert out["s"] == [None, "apple", "fig", "pear"]


def test_sort_dates_and_decimals():
    import datetime
    from decimal import Decimal

    data = {
        "d": pa.array([datetime.date(2020, 5, 1), datetime.date(1999, 1, 1), None],
                      type=pa.date32()),
        "m": pa.array([Decimal("1.10"), Decimal("-2.50"), Decimal("0.00")],
                      type=pa.decimal128(9, 2)),
    }
    out = collect_pydict(SortExec(mem_scan(data), [so("d", nulls_first=False)]))
    assert out["d"] == [datetime.date(1999, 1, 1), datetime.date(2020, 5, 1), None]
    out = collect_pydict(SortExec(mem_scan(data), [so("m")]))
    assert out["m"] == [Decimal("-2.50"), Decimal("0.00"), Decimal("1.10")]


def test_topk():
    rng = np.random.default_rng(0)
    vals = rng.integers(0, 10_000, size=5000).tolist()
    out = collect_pydict(SortExec(mem_scan({"a": vals}, num_batches=7),
                                  [so("a")], fetch_limit=10))
    assert out["a"] == sorted(vals)[:10]


def test_external_sort_with_spill():
    rng = np.random.default_rng(1)
    vals = rng.integers(-(10**9), 10**9, size=20_000).tolist()
    MemManager.reset()
    with config_override(memory_total=2_000_000, memory_fraction=1.0):
        out = collect_pydict(
            SortExec(mem_scan({"a": vals}, num_batches=10), [so("a")]))
    MemManager.reset()
    assert out["a"] == sorted(vals)
    assert len(out["a"]) == 20_000


def test_external_sort_strings_with_spill():
    rng = np.random.default_rng(2)
    vals = ["s" + str(rng.integers(0, 10**6)) for _ in range(5000)]
    MemManager.reset()
    with config_override(memory_total=300_000, memory_fraction=1.0):
        out = collect_pydict(
            SortExec(mem_scan({"s": vals}, num_batches=8), [so("s")]))
    MemManager.reset()
    assert out["s"] == sorted(vals)


def test_external_sort_multikey_desc_nulls_with_spill():
    """Vectorized spilled-run merge (device-key path): multi-column keys,
    mixed directions, and NULL ordering must match the in-memory sort."""
    rng = np.random.default_rng(7)
    n = 30_000
    a = rng.integers(0, 50, n).astype(object)
    a[rng.random(n) < 0.05] = None
    b = rng.integers(-(10**6), 10**6, n).tolist()
    data = {"a": a.tolist(), "b": b}
    orders = [so("a", asc=False), so("b")]
    out_mem = collect_pydict(
        SortExec(mem_scan(data, num_batches=12), orders))
    MemManager.reset()
    with config_override(memory_total=1_500_000, memory_fraction=1.0):
        out_spill = collect_pydict(
            SortExec(mem_scan(data, num_batches=12), orders))
    MemManager.reset()
    assert out_spill == out_mem

"""Result/subplan cache tests (blaze_tpu/cache/): fingerprint keying and
cacheability, the serve/offer/refresh lifecycle, version invalidation over
the streaming ingest path, incremental tail-merge correctness, LRU + memory
pressure eviction, the put-failure degrade ladder (memory -> spill-dir ->
miss), epoch discards around worker death, scheduler integration
(``cache_hit`` as a first-class outcome that bypasses the queue), and the
disabled-path guard (cache off => the cache is never even consulted)."""

import os
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.cache import incremental, result_cache
from blaze_tpu.cache.incremental import merge_tables, mergeable_spec
from blaze_tpu.cache.result_cache import cache_key, plan_cacheable
from blaze_tpu.config import Config
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.runtime import failpoints
from blaze_tpu.runtime.memmgr import MemManager
from blaze_tpu.runtime.session import Session

F = E.AggFunction
M = E.AggMode
HASH = E.AggExecMode.HASH_AGG


@pytest.fixture(autouse=True)
def _fresh_memmgr():
    MemManager.reset()
    failpoints.disarm()
    yield
    failpoints.disarm()
    MemManager.reset()


def _write_parquet(tmp_path, name="t.parquet", n=4000, stores=7):
    path = str(tmp_path / name)
    pq.write_table(pa.table({
        "k": [i % stores for i in range(n)],
        "v": list(range(n)),
    }), path)
    return path


def _agg_plan(child, key="k", val="v", fn=F.SUM, out="s", reducers=3):
    g = [(key, E.Column(key))]
    partial = N.Agg(child, HASH, g, [N.AggColumn(
        E.AggExpr(fn, [E.Column(val)], T.I64), M.PARTIAL, out)])
    ex = N.ShuffleExchange(partial,
                           N.HashPartitioning([E.Column(key)], reducers))
    return N.Agg(ex, HASH, g, [N.AggColumn(
        E.AggExpr(fn, [E.Column(val)], T.I64), M.FINAL, out)])


def _scan(path, nparts=2):
    from blaze_tpu.ops.parquet import scan_node_for_files

    return scan_node_for_files([path], num_partitions=nparts)


def _canon(table):
    d = table.to_pydict()
    return sorted(zip(*d.values())) if d else []


def _batch(ks, vs):
    return pa.RecordBatch.from_pydict({"k": ks, "v": vs})


# -- keying / cacheability ----------------------------------------------------


def test_cache_key_stable_and_literal_sensitive(tmp_path):
    path = _write_parquet(tmp_path)

    def filt(v):
        return N.Filter(_scan(path), [E.BinaryExpr(
            E.BinaryOp.GT, E.Column("v"), E.Literal(v, T.I64))])

    assert cache_key(_agg_plan(filt(5))) == cache_key(_agg_plan(filt(5)))
    assert cache_key(_agg_plan(filt(5))) != cache_key(_agg_plan(filt(6)))
    assert plan_cacheable(_agg_plan(filt(5)))


def test_ffi_and_sink_plans_uncacheable(tmp_path):
    schema = pa.schema([("k", pa.int64()), ("v", pa.int64())])
    ffi = N.FFIReader(schema=schema, resource_id="src", num_partitions=1)
    assert not plan_cacheable(_agg_plan(ffi))
    path = _write_parquet(tmp_path)
    sink = N.ParquetSink(_scan(path), fs_path=str(tmp_path / "out"))
    assert not plan_cacheable(sink)


# -- serve / offer lifecycle --------------------------------------------------


def test_execute_cached_fill_then_hit(tmp_path):
    path = _write_parquet(tmp_path)
    with Session(conf=Config()) as sess:
        plan = _agg_plan(_scan(path))
        cold = sess.execute_cached(plan)
        stats = sess.cache.stats_fields()
        assert stats["cache_misses"] == 1 and stats["cache_hits"] == 0
        warm = sess.execute_cached(_agg_plan(_scan(path)))
        stats = sess.cache.stats_fields()
        assert stats["cache_hits"] == 1
        assert warm.equals(cold)
        assert stats["cache_bytes"] > 0 and stats["cache_entries"] == 1


def test_warm_hit_is_microsecond_scale(tmp_path):
    """The whole point of the subsystem: a repeat lookup must not re-run
    the engine. Bound generously (10ms) — the cold run takes 100x that."""
    path = _write_parquet(tmp_path, n=20_000)
    with Session(conf=Config()) as sess:
        plan = _agg_plan(_scan(path))
        sess.execute_cached(plan)
        t0 = time.perf_counter()
        sess.execute_cached(_agg_plan(_scan(path)))
        assert time.perf_counter() - t0 < 0.010


def test_bit_identity_cold_warm_disabled(tmp_path):
    path = _write_parquet(tmp_path)
    plan = _agg_plan(_scan(path))
    with Session(conf=Config()) as sess:
        cold = sess.execute_cached(plan)
        warm = sess.execute_cached(plan)
    MemManager.reset()
    with Session(conf=Config(cache_enabled=False)) as sess:
        off = sess.execute_cached(plan)
    assert _canon(cold) == _canon(warm) == _canon(off)
    assert warm.equals(cold)


# -- version invalidation + incremental maintenance ---------------------------


def test_append_bumps_version_and_staleness(tmp_path):
    with Session(conf=Config()) as sess:
        v1 = sess.append("t", [_batch([0, 1], [10, 20])])
        v2 = sess.append("t", [_batch([1], [5])])
        assert v2 == v1 + 1
        assert sess.ingest.versions(["t"]) == {"t": v2}


def test_incremental_refresh_matches_full_recompute(tmp_path):
    with Session(conf=Config()) as sess:
        sess.append("t", [_batch([0, 1, 2, 0], [1, 2, 3, 4])],
                    num_partitions=2)
        plan = _agg_plan(sess.table_scan("t"))
        first = sess.execute_cached(plan)
        assert _canon(first) == [(0, 5), (1, 2), (2, 3)]
        sess.append("t", [_batch([0, 3], [100, 7])])
        refreshed = sess.execute_cached(plan)
        oracle = sess.execute_to_table(plan, release_on_finish=True)
        assert _canon(refreshed) == _canon(oracle) == [
            (0, 105), (1, 2), (2, 3), (3, 7)]
        stats = sess.cache.stats_fields()
        assert stats["cache_refreshes"] == 1
        assert stats["cache_stale_served"] == 0
        # and the refreshed entry is itself servable
        assert _canon(sess.execute_cached(plan)) == _canon(oracle)
        assert sess.cache.stats_fields()["cache_hits"] >= 1


def test_nonmergeable_stale_falls_back_to_full_recompute(tmp_path):
    with Session(conf=Config()) as sess:
        sess.append("t", [_batch([0, 1], [3, 9])], num_partitions=2)
        # a Sort atop the agg is not tail-mergeable
        plan = N.Sort(_agg_plan(sess.table_scan("t")),
                      [E.SortOrder(E.Column("s"))])
        sess.execute_cached(plan)
        sess.append("t", [_batch([0], [1])])
        got = sess.execute_cached(plan)
        oracle = sess.execute_to_table(plan, release_on_finish=True)
        assert _canon(got) == _canon(oracle)
        stats = sess.cache.stats_fields()
        assert stats["cache_refreshes"] == 0  # full recompute, not merge
        assert stats["cache_stale"] >= 1
        assert stats["cache_stale_served"] == 0


def test_stale_entry_never_served_pin(tmp_path):
    """The invariant the chaos matrix and soaks pin to zero, unit-scale:
    no sequence of appends and lookups may return a pre-append table."""
    with Session(conf=Config()) as sess:
        sess.append("t", [_batch([0], [1])])
        plan = _agg_plan(sess.table_scan("t"))
        for i in range(5):
            got = sess.execute_cached(plan)
            assert _canon(got)[0][1] == i + 1
            sess.append("t", [_batch([0], [1])])
        assert sess.cache.stats_fields()["cache_stale_served"] == 0


# -- incremental units --------------------------------------------------------


def test_mergeable_spec_units(tmp_path):
    path = _write_parquet(tmp_path)
    spec = mergeable_spec(_agg_plan(_scan(path)))
    assert spec is not None
    assert mergeable_spec(N.Sort(_agg_plan(_scan(path)),
                                 [E.SortOrder(E.Column("s"))])) is None
    assert mergeable_spec(_scan(path)) is None
    # AVG has no pure fold — must refuse
    assert mergeable_spec(_agg_plan(_scan(path), fn=F.AVG)) is None


def test_merge_tables_folds():
    spec = (["k"], [("mn", "min"), ("mx", "max"), ("sm", "sum")])
    cached = pa.table({"k": [0, 1], "mn": [3, 5], "mx": [9, 5],
                       "sm": [12, 5]})
    delta = pa.table({"k": [1, 2], "mn": [1, 8], "mx": [10, 8],
                      "sm": [11, 8]})
    out = merge_tables(cached, delta, spec)
    assert _canon(out) == [(0, 3, 9, 12), (1, 1, 10, 16), (2, 8, 8, 8)]
    assert out.schema.names == ["k", "mn", "mx", "sm"]
    # empty delta short-circuits to the cached table
    assert merge_tables(cached, delta.slice(0, 0), spec) is cached


# -- eviction / degrade ladder ------------------------------------------------


def test_eviction_under_byte_pressure(tmp_path):
    """A byte cap far below the working set forces the LRU ladder; the
    cache must keep serving (spill tier) without ever exceeding its cap
    or failing a fill."""
    path = _write_parquet(tmp_path, n=20_000)
    conf = Config(cache_max_bytes=1 << 20, cache_spill_enabled=True,
                  spill_dir=str(tmp_path / "spill"))
    with Session(conf=conf) as sess:
        plans = []
        for v in range(6):
            # group by the ~unique v column: each result is ~320 KB, so
            # six entries overflow the 1 MB cap (one always fits)
            p = _agg_plan(N.Filter(_scan(path), [E.BinaryExpr(
                E.BinaryOp.GT, E.Column("v"), E.Literal(v * 100, T.I64))]),
                key="v", val="k")
            plans.append(p)
            sess.execute_cached(p)
        snap = sess.cache.snapshot()
        assert snap["resident_bytes"] <= 1 << 20
        assert snap["counts"]["evictions"] + sum(
            1 for e in snap["results"] if e["tier"] == "spill") > 0
        # every plan still answers correctly, whatever tier it landed on
        for p in plans:
            got = sess.execute_cached(p)
            oracle = sess.execute_to_table(p, release_on_finish=True)
            assert _canon(got) == _canon(oracle)


def test_max_entries_cap(tmp_path):
    path = _write_parquet(tmp_path)
    conf = Config(cache_max_entries=2, cache_spill_enabled=False)
    with Session(conf=conf) as sess:
        for v in range(5):
            sess.execute_cached(_agg_plan(N.Filter(_scan(path), [
                E.BinaryExpr(E.BinaryOp.GT, E.Column("v"),
                             E.Literal(v, T.I64))])))
        assert sess.cache.snapshot()["entries"] <= 2


def test_degrade_ladder_put_failure_spills_then_serves(tmp_path):
    """An injected put failure (failpoint ``cache.put``) must degrade to
    the spill rung — and the spilled entry must still HIT, promoted back
    to memory with the exact table."""
    path = _write_parquet(tmp_path)
    conf = Config(failpoints="cache.put=ioerror:every1:x1",
                  spill_dir=str(tmp_path / "spill"))
    with Session(conf=conf) as sess:
        failpoints.arm_from(conf)
        plan = _agg_plan(_scan(path))
        cold = sess.execute_cached(plan)
        stats = sess.cache.stats_fields()
        assert stats["cache_degraded_puts"] == 1
        snap = sess.cache.snapshot()
        assert [e["tier"] for e in snap["results"]] == ["spill"]
        warm = sess.execute_cached(plan)
        assert warm.equals(cold)
        assert sess.cache.stats_fields()["cache_hits"] == 1
        assert sess.cache.snapshot()["results"][0]["tier"] == "mem"


def test_degrade_ladder_spill_disabled_drops_to_miss(tmp_path):
    path = _write_parquet(tmp_path)
    conf = Config(failpoints="cache.put=ioerror:every1:x1",
                  cache_spill_enabled=False)
    with Session(conf=conf) as sess:
        failpoints.arm_from(conf)
        plan = _agg_plan(_scan(path))
        cold = sess.execute_cached(plan)
        assert sess.cache.snapshot()["entries"] == 0  # dropped, not stored
        again = sess.execute_cached(plan)  # a MISS that re-executes
        assert again.equals(cold)
        assert sess.cache.stats_fields()["cache_hits"] == 0


def test_memconsumer_citizenship_and_clean_close(tmp_path):
    path = _write_parquet(tmp_path, n=20_000)
    with Session(conf=Config()) as sess:
        sess.execute_cached(_agg_plan(_scan(path)))
        mm = MemManager._instance
        assert mm is not None and mm.used > 0  # cache residency is booked
    assert MemManager._instance is None or MemManager._instance.used == 0


# -- epoch: worker death must invalidate in-flight fills ----------------------


def test_epoch_bump_discards_inflight_offer(tmp_path):
    path = _write_parquet(tmp_path)
    with Session(conf=Config()) as sess:
        plan = _agg_plan(_scan(path))
        table = sess.execute_to_table(plan, release_on_finish=True)
        t0 = sess.cache.fill_token(plan)
        sess.cache.bump_epoch()  # what a worker death does via deaths_total
        sess.cache.offer(plan, table, t0)
        assert sess.cache.serve(plan) is None  # refused, not admitted
        assert sess.cache.snapshot()["entries"] == 0


@pytest.mark.slow
def test_epoch_discard_on_pool_worker_death(tmp_path):
    path = _write_parquet(tmp_path)
    conf = Config(fault_exclusion_ttl_s=0.5)
    with Session(conf=conf, num_worker_processes=2) as sess:
        plan = _agg_plan(_scan(path))
        table = sess.execute_to_table(plan, release_on_finish=True)
        t0 = sess.cache.fill_token(plan)
        sess.pool.kill_worker(0)
        deadline = time.monotonic() + 30
        while sess.cache.epoch() == t0[0] and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sess.cache.epoch() > t0[0]
        sess.cache.offer(plan, table, t0)
        assert sess.cache.serve(plan) is None


# -- append races: fills and refreshes that overlap ingest --------------------


def test_append_overlapping_execution_discards_offer(tmp_path):
    """An append landing between the pre-execution fill token and the
    offer means the result's scan snapshot may predate the append — the
    fill must be refused, never stamped with the post-append vector
    (which would serve pre-append data as fresh forever)."""
    with Session(conf=Config()) as sess:
        sess.append("t", [_batch([0], [1])])
        plan = _agg_plan(sess.table_scan("t"))
        token = sess.cache.fill_token(plan)
        table = sess.execute_to_table(plan, release_on_finish=True)
        sess.append("t", [_batch([0], [2])])  # lands "mid-execution"
        sess.cache.offer(plan, table, token)
        assert sess.cache.serve(plan) is None
        assert sess.cache.snapshot()["entries"] == 0
        # the full path still converges: recompute sees both appends
        assert _canon(sess.execute_cached(plan)) == [(0, 3)]
        assert sess.cache.stats_fields()["cache_stale_served"] == 0


def test_retarget_covered_matches_registered_snapshot(tmp_path):
    """``retarget_to_tails`` must report the version each tail snapshot
    ACTUALLY covers — including an append that raced in after the caller
    last sampled the registry — so refreshed entries never record a
    vector behind their data (which would double-merge the same tail)."""
    from blaze_tpu.cache.ingest import retarget_to_tails

    with Session(conf=Config()) as sess:
        sess.append("t", [_batch([0], [1])])
        plan = sess.table_scan("t")
        sess.append("t", [_batch([0], [2])])  # the "racing" append: v2
        tail_plan, rids, covered = retarget_to_tails(
            plan, {"t": 1}, sess.ingest)
        assert tail_plan is not None
        assert covered == {"t": 2}
        for rid in rids:
            sess.ingest.release_tail(rid)


def test_refresh_records_covered_versions_no_double_merge(tmp_path):
    """An append landing DURING a tail refresh must not be folded into
    the recorded vector: the entry records what the tail snapshot
    covered, the racing append stays pending, and the next lookup merges
    exactly it — never twice."""
    with Session(conf=Config()) as sess:
        sess.append("t", [_batch([0], [1])])
        plan = _agg_plan(sess.table_scan("t"))
        assert _canon(sess.execute_cached(plan)) == [(0, 1)]  # fill @v1
        sess.append("t", [_batch([0], [2])])  # v2: entry now stale

        def execute_with_midflight_append(p):
            tbl = sess.execute_to_table(p, release_on_finish=True)
            sess.append("t", [_batch([0], [4])])  # v3 lands mid-refresh
            return tbl

        merged = sess.cache.refresh_or_none(
            plan, execute_with_midflight_append)
        assert merged is not None and _canon(merged) == [(0, 3)]
        key = cache_key(plan)
        with sess.cache._mu:
            assert sess.cache._results[key].versions == {"t": 2}
        # v3 merges exactly once on the next lookup: 1 + 2 + 4, not 1+2+4+4
        assert _canon(sess.execute_cached(plan)) == [(0, 7)]
        assert sess.cache.stats_fields()["cache_stale_served"] == 0


def test_degraded_put_replacing_entry_releases_old_stage(tmp_path):
    """A degraded (spill-rung) put over an existing key must release the
    old entry's registry stage and spill file like the normal store path
    — otherwise the soak leak gates (mm.used == 0 after close) trip."""
    conf = Config(spill_dir=str(tmp_path / "spill"))
    with Session(conf=conf) as sess:
        sess.append("t", [_batch([0], [1])])
        plan = _agg_plan(sess.table_scan("t"))
        sess.execute_cached(plan)  # normal fill: mem tier, stage held
        key = cache_key(plan)
        with sess.cache._mu:
            old_stage = sess.cache._results[key].stage
        assert sess.mem_segments.get(old_stage, 0) is not None
        table2 = sess.execute_to_table(plan, release_on_finish=True)
        failpoints.arm("cache.put=ioerror:every1:x1")
        sess.cache.offer(plan, table2, sess.cache.fill_token(plan))
        stats = sess.cache.stats_fields()
        assert stats["cache_degraded_puts"] == 1
        with sess.cache._mu:
            assert sess.cache._results[key].tier == "spill"
        assert sess.mem_segments.get(old_stage, 0) is None  # old refs freed
        # the spilled replacement still serves, promoted back to memory
        assert _canon(sess.execute_cached(plan)) == _canon(table2)
    assert MemManager._instance is None or MemManager._instance.used == 0


# -- subplan sharing ----------------------------------------------------------


def test_subplan_sharing_across_plans(tmp_path):
    """Two different whole plans over the SAME exchange subtree: the
    second must serve the map stage from the subplan cache (no re-run),
    and explain_analyze must show the cache-served subtree."""
    path = _write_parquet(tmp_path)
    conf = Config(cache_subplan_scope="all")
    with Session(conf=conf) as sess:
        g = [("k", E.Column("k"))]
        partial = N.Agg(_scan(path), HASH, g, [N.AggColumn(
            E.AggExpr(F.SUM, [E.Column("v")], T.I64), M.PARTIAL, "s")])
        ex = N.ShuffleExchange(partial,
                               N.HashPartitioning([E.Column("k")], 3))
        final = N.Agg(ex, HASH, g, [N.AggColumn(
            E.AggExpr(F.SUM, [E.Column("v")], T.I64), M.FINAL, "s")])
        a = sess.execute_to_table(final, release_on_finish=True)
        plan_b = N.Filter(
            N.Agg(ex, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("v")], T.I64), M.FINAL, "s")]),
            [E.BinaryExpr(E.BinaryOp.GT, E.Column("s"),
                          E.Literal(-1, T.I64))])
        b = sess.execute_to_table(plan_b, release_on_finish=True)
        assert _canon(a) == _canon(b)
        assert sess.cache.stats_fields()["cache_subplan_hits"] == 1
        text = sess.explain_analyze(plan_b)
        assert "served from subplan cache" in text


def test_subplan_invalidated_by_append(tmp_path):
    conf = Config(cache_subplan_scope="all")
    with Session(conf=conf) as sess:
        sess.append("t", [_batch([0, 1], [2, 3])], num_partitions=2)
        plan = _agg_plan(sess.table_scan("t"))
        sess.execute_to_table(plan, release_on_finish=True)
        sess.append("t", [_batch([0], [10])])
        got = sess.execute_to_table(plan, release_on_finish=True)
        assert _canon(got) == [(0, 12), (1, 3)]  # no stale subplan reuse
        assert sess.cache.stats_fields()["cache_subplan_hits"] == 0


# -- scheduler integration ----------------------------------------------------


def test_scheduler_cache_hit_outcome_bypasses_queue(tmp_path):
    from blaze_tpu.serve import QueryScheduler

    path = _write_parquet(tmp_path)
    with Session(conf=Config()) as sess:
        with QueryScheduler(sess, max_concurrent=1,
                            queue_timeout_s=30.0) as sched:
            h1 = sched.submit(_agg_plan(_scan(path)), label="cold")
            cold = h1.result(timeout=120)
            h2 = sched.submit(_agg_plan(_scan(path)), label="warm")
            assert h2.done()  # finished AT submit return: no queue, no slot
            assert h2.result(timeout=5).equals(cold)
            assert sched.metrics.values.get("queries_cache_hit") == 1
            # hits are not executions: done still counts only the cold run
            assert sched.metrics.values.get("queries_done") == 1
            assert sched.snapshot()["cache"]["counts"]["hits"] == 1


def test_scheduler_refreshes_stale_through_cache(tmp_path):
    from blaze_tpu.serve import QueryScheduler

    with Session(conf=Config()) as sess:
        sess.append("t", [_batch([0, 1], [5, 6])], num_partitions=2)
        plan = _agg_plan(sess.table_scan("t"))
        with QueryScheduler(sess, max_concurrent=1,
                            queue_timeout_s=30.0) as sched:
            sched.submit(plan, label="cold").result(timeout=120)
            sess.append("t", [_batch([1], [4])])
            got = sched.submit(plan, label="stale").result(timeout=120)
            assert _canon(got) == [(0, 5), (1, 10)]
        assert sess.cache.stats_fields()["cache_refreshes"] == 1
        assert sess.cache.stats_fields()["cache_stale_served"] == 0


# -- disabled path ------------------------------------------------------------


def test_disabled_cache_is_never_consulted(tmp_path, monkeypatch):
    """cache_enabled=False must keep the hot path free of cache work —
    not "a fast miss", NO consult at all (the structural form of the <5%%
    overhead guarantee: the only added cost is one attribute check)."""
    from blaze_tpu.serve import QueryScheduler

    path = _write_parquet(tmp_path)

    def _boom(plan):
        raise AssertionError("cache consulted on the disabled path")

    monkeypatch.setattr(result_cache, "cache_key", _boom)
    monkeypatch.setattr(incremental, "mergeable_spec", _boom)
    with Session(conf=Config(cache_enabled=False)) as sess:
        assert sess.cache is None
        plan = _agg_plan(_scan(path))
        a = sess.execute_cached(plan)
        b = sess.execute_cached(plan)
        assert _canon(a) == _canon(b)
        with QueryScheduler(sess, max_concurrent=1,
                            queue_timeout_s=30.0) as sched:
            sched.submit(plan, label="q").result(timeout=120)
            assert sched.metrics.values.get("queries_cache_hit") is None

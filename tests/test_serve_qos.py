"""Multi-tenant QoS tests: weighted-fair scheduling, per-tenant quotas,
stage-boundary preemption with bit-identical resume, torn-pause lineage
healing, backpressure (429 + Retry-After), and deterministic retry jitter."""

import glob
import json
import os
import time
import urllib.error
import urllib.request

import pyarrow as pa
import pytest

from blaze_tpu.config import Config
from blaze_tpu.core import ColumnarBatch
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.runtime.cluster import TaskFailed
from blaze_tpu.runtime.memmgr import MemManager
from blaze_tpu.runtime.session import PauseToken, Session, StagePaused
from blaze_tpu.serve import (Backpressure, Overloaded, QueryHandle,
                             QueryScheduler)

F = E.AggFunction
M = E.AggMode
HASH = E.AggExecMode.HASH_AGG


@pytest.fixture(autouse=True)
def _fresh_memmgr():
    MemManager.reset()
    yield
    MemManager.reset()


def _register_src(sess, rid, data, num_batches=8):
    big = ColumnarBatch.from_pydict(data)
    n = big.num_rows
    per = max(1, (n + num_batches - 1) // num_batches)
    batches = [big.slice(i, per).to_arrow() for i in range(0, n, per)]
    sess.resources[rid] = lambda p: list(batches)
    return big.schema


def _agg_plan(schema, rid, reducers=3):
    scan = N.FFIReader(schema=schema, resource_id=rid, num_partitions=1)
    groupings = [("k", E.Column("k"))]
    partial = N.Agg(scan, HASH, groupings,
                    [N.AggColumn(E.AggExpr(F.SUM, [E.Column("v")], T.I64),
                                 M.PARTIAL, "s")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([E.Column("k")],
                                                       reducers))
    return N.Agg(ex, HASH, groupings,
                 [N.AggColumn(E.AggExpr(F.SUM, [E.Column("v")], T.I64),
                              M.FINAL, "s")])


def _two_boundary_sort_plan(schema, rid, reducers=3):
    """Partial agg -> exchange -> final agg -> exchange -> sort: TWO stage
    boundaries, so a cursor replay has to skip more than one commit."""
    scan = N.FFIReader(schema=schema, resource_id=rid, num_partitions=2)
    groupings = [("k", E.Column("k"))]
    partial = N.Agg(scan, HASH, groupings,
                    [N.AggColumn(E.AggExpr(F.SUM, [E.Column("v")], T.I64),
                                 M.PARTIAL, "s")])
    ex1 = N.ShuffleExchange(partial, N.HashPartitioning([E.Column("k")],
                                                        reducers))
    final = N.Agg(ex1, HASH, groupings,
                  [N.AggColumn(E.AggExpr(F.SUM, [E.Column("v")], T.I64),
                               M.FINAL, "s")])
    ex2 = N.ShuffleExchange(final, N.SinglePartitioning(1))
    return N.Sort(ex2, [E.SortOrder(E.Column("k"))])


def _slow_source(sess, rid, batches=100, sleep_s=0.05, nparts=2):
    b = ColumnarBatch.from_pydict({"k": [1, 2, 3, 4] * 50,
                                   "v": list(range(200))})

    def provider(p):
        def gen():
            for _ in range(batches):
                time.sleep(sleep_s)
                yield b.to_arrow()
        return gen()

    sess.resources[rid] = provider
    scan = N.FFIReader(schema=b.schema, resource_id=rid, num_partitions=nparts)
    ex = N.ShuffleExchange(scan, N.HashPartitioning([E.Column("k")], 2))
    return N.Sort(ex, [E.SortOrder(E.Column("v"))])


def _assert_no_leaks(sess):
    assert os.listdir(sess.work_dir) == []
    assert os.listdir(sess.shuffle_root) == []
    assert len(sess.mem_segments) == 0
    assert MemManager._instance is None or MemManager._instance.used == 0


# -- memmgr named quota groups ------------------------------------------------


@pytest.mark.quick
def test_memmgr_quota_groups():
    """Named quotas aggregate max(reservation, usage) over member groups;
    headroom is None when uncapped; membership drops on release."""
    mm = MemManager(total=1000, wait_timeout_s=0.1)
    mm.set_quota("tenant_a", 400)
    assert mm.quota_headroom("tenant_a") == 400
    assert mm.quota_headroom("tenant_missing") is None  # unknown quota
    mm.reserve_group("q1", 150, quota="tenant_a")
    mm.reserve_group("q2", 100, quota="tenant_a")
    mm.reserve_group("q3", 100)  # no quota: not counted against tenant_a
    assert mm.quota_usage("tenant_a") == 250
    assert mm.quota_headroom("tenant_a") == 150
    mm.release_group("q1")
    assert mm.quota_usage("tenant_a") == 100
    # uncapped quota: usage tracked, headroom unbounded (None)
    mm.set_quota("tenant_b", None)
    mm.reserve_group("q4", 50, quota="tenant_b")
    assert mm.quota_usage("tenant_b") == 50
    assert mm.quota_headroom("tenant_b") is None
    for g in ("q2", "q3", "q4"):
        mm.release_group(g)
    assert mm.quota_usage("tenant_a") == 0
    stats = mm.stats()
    assert "tenant_a" in stats["quotas"]
    assert stats["quotas"]["tenant_a"]["used"] == 0


# -- deterministic retry jitter -----------------------------------------------


@pytest.mark.quick
def test_retry_backoff_jitter_deterministic():
    """The serve-layer retry backoff jitter is seeded per (query label,
    attempt) from failpoint_seed — two schedulers with the same seed
    produce bit-identical delays, a different seed diverges, and attempts
    within one query draw distinct values."""
    def delays(seed, label):
        conf = Config(memory_total=64 << 20, memory_fraction=1.0,
                      failpoint_seed=seed)
        with Session(conf=conf) as sess:
            schema = _register_src(sess, "j", {"k": [1], "v": [1]})
            with QueryScheduler(sess, max_concurrent=1) as sched:
                h = QueryHandle(sched, 0, _agg_plan(schema, "j"), 0, None,
                                1 << 20, label)
                out = []
                for _ in range(sess.conf.serve_retry_max):
                    d = sched._retry_delay_s(h, TaskFailed("boom"),
                                             sess.conf)
                    assert d is not None
                    out.append(d)
                    h.retries.append({"attempt": len(h.retries) + 1})
                # budget exhausted -> surface the error
                assert sched._retry_delay_s(h, TaskFailed("boom"),
                                            sess.conf) is None
                return out

    a = delays(7, "qx")
    b = delays(7, "qx")
    assert a == b, "same (seed, label, attempt) must reproduce exactly"
    assert len(set(a)) == len(a), "attempts must draw distinct jitter"
    assert delays(8, "qx") != a, "seed must perturb the stream"
    assert delays(7, "qy") != a, "label must perturb the stream"
    for d in a:
        assert 0.125 <= d <= 2.0  # 50-100% of the capped backoff


# -- weighted-fair ordering ---------------------------------------------------


@pytest.mark.quick
def test_wfq_heavier_tenant_admitted_first():
    """One slot, a blocker holding it, then equal-cost queries from a
    weight-1 and a weight-8 tenant: virtual finish times interleave so ALL
    of the heavy tenant's queries admit before any light one."""
    conf = Config(memory_total=64 << 20, memory_fraction=1.0,
                  serve_tenants="bulk:1;dash:8", serve_preempt_enable=False)
    with Session(conf=conf) as sess:
        blocker_plan = _slow_source(sess, "hog", batches=200, sleep_s=0.05,
                                    nparts=1)
        plans = {}
        for i in range(8):
            schema = _register_src(sess, f"w{i}",
                                   {"k": [i % 3], "v": [i]})
            plans[i] = _agg_plan(schema, f"w{i}", reducers=2)
        with QueryScheduler(sess, max_concurrent=1,
                            queue_timeout_s=120.0) as sched:
            hog = sched.submit(blocker_plan, label="hog", tenant="bulk")
            deadline = time.monotonic() + 10
            while hog.state in ("queued", "admitted") \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            # submission order is bulk FIRST — admission order must not be
            bulk = [sched.submit(plans[i], label=f"bulk{i}", tenant="bulk")
                    for i in range(4)]
            dash = [sched.submit(plans[i + 4], label=f"dash{i}",
                                 tenant="dash") for i in range(4)]
            hog.cancel("release the slot")
            for h in bulk + dash:
                h.result(timeout=120)
            assert max(h.admitted_at for h in dash) \
                <= min(h.admitted_at for h in bulk), \
                "weight-8 tenant must fully admit before weight-1"
            snap = sched.snapshot()
            weights = {t["name"]: t["weight"] for t in snap["tenants"]}
            assert weights["bulk"] == 1.0 and weights["dash"] == 8.0


@pytest.mark.quick
def test_tenant_quota_and_concurrency_caps():
    """A tenant mem quota sheds oversized submissions with the typed
    Overloaded (reason: quota, NOT backpressure); a tenant concurrency cap
    holds its second query queued while global slots sit free."""
    conf = Config(memory_total=64 << 20, memory_fraction=1.0,
                  serve_tenants="small:1::1;capped:1:1")
    with Session(conf=conf) as sess:
        schema = _register_src(sess, "q", {"k": [1], "v": [1]})
        fast = _agg_plan(schema, "q", reducers=2)
        slow1 = _slow_source(sess, "s1", batches=60, sleep_s=0.05, nparts=1)
        slow2 = _slow_source(sess, "s2", batches=60, sleep_s=0.05, nparts=1)
        with QueryScheduler(sess, max_concurrent=4,
                            queue_timeout_s=60.0) as sched:
            # quota: the 2 MB estimate exceeds the 1 MB tenant quota
            with pytest.raises(Overloaded) as ei:
                sched.submit(fast, tenant="small", mem_estimate=2 << 20,
                             label="too_big")
            assert "quota" in str(ei.value)
            assert not isinstance(ei.value, Backpressure)
            # under-quota submission from the same tenant is fine
            ok = sched.submit(fast, tenant="small",
                              mem_estimate=256 << 10, label="fits")
            assert ok.result(timeout=60).num_rows == 1
            # concurrency cap: tenant "capped" runs one at a time
            h1 = sched.submit(slow1, tenant="capped", label="c1")
            h2 = sched.submit(slow2, tenant="capped", label="c2")
            deadline = time.monotonic() + 10
            while h1.state in ("queued", "admitted") \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.2)  # several dispatch ticks with free global slots
            assert h1.state == "running" and h2.state == "queued"
            h1.cancel()
            h2.cancel()
            for h in (h1, h2):
                with pytest.raises(Exception):
                    h.result(timeout=30)
        assert sched.metrics.get("queries_shed") == 1


# -- backpressure -------------------------------------------------------------


@pytest.mark.quick
def test_backpressure_full_queue_retry_after():
    """Full queue -> Backpressure (an Overloaded subtype) carrying a
    clamped Retry-After; with backpressure disabled the same arrival gets
    the plain hard shed."""
    conf = Config(memory_total=64 << 20, memory_fraction=1.0)
    with Session(conf=conf) as sess:
        slow = _slow_source(sess, "bp", batches=100, sleep_s=0.05, nparts=1)
        schema = _register_src(sess, "f", {"k": [1], "v": [1]})
        fast = _agg_plan(schema, "f", reducers=2)
        with QueryScheduler(sess, max_concurrent=1, max_queue=1,
                            queue_timeout_s=60.0) as sched:
            hog = sched.submit(slow, label="hog")
            deadline = time.monotonic() + 10
            while hog.state in ("queued", "admitted") \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            sched.submit(fast, label="queued")
            with pytest.raises(Backpressure) as ei:
                sched.submit(fast, label="bounced")
            assert isinstance(ei.value, Overloaded)
            assert 0.25 <= ei.value.retry_after_s \
                <= sess.conf.serve_retry_after_max_s
            assert sched.metrics.get("queries_backpressured") == 1
            assert sched.metrics.get("queries_shed") == 1
            hog.cancel()

    conf2 = Config(memory_total=64 << 20, memory_fraction=1.0,
                   serve_backpressure_enable=False)
    MemManager.reset()
    with Session(conf=conf2) as sess:
        slow = _slow_source(sess, "bp2", batches=100, sleep_s=0.05, nparts=1)
        schema = _register_src(sess, "f2", {"k": [1], "v": [1]})
        fast = _agg_plan(schema, "f2", reducers=2)
        with QueryScheduler(sess, max_concurrent=1, max_queue=1,
                            queue_timeout_s=60.0) as sched:
            hog = sched.submit(slow, label="hog2")
            deadline = time.monotonic() + 10
            while hog.state in ("queued", "admitted") \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            sched.submit(fast, label="queued2")
            with pytest.raises(Overloaded) as ei:
                sched.submit(fast, label="hard_shed")
            assert not isinstance(ei.value, Backpressure)
            hog.cancel()


@pytest.mark.quick
def test_http_429_retry_after(tmp_path):
    """A full queue answers /serve/submit with 429 + a Retry-After header
    instead of the 503 hard shed."""
    import base64

    import pyarrow.parquet as pq

    from blaze_tpu.ir.protoserde import plan_to_bytes
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime.http import ProfilingService

    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"k": [1, 2, 3], "v": [1, 2, 3]}), path)
    scan = scan_node_for_files([path], num_partitions=1)
    plan = N.ShuffleExchange(scan, N.SinglePartitioning(1))
    body = json.dumps({
        "plan_b64": base64.b64encode(plan_to_bytes(plan)).decode(),
        "label": "bp_http", "tenant": "web"}).encode()

    conf = Config(memory_total=64 << 20, memory_fraction=1.0)
    ProfilingService.stop()
    with Session(conf=conf) as sess:
        slow = _slow_source(sess, "h429", batches=100, sleep_s=0.05,
                            nparts=1)
        with QueryScheduler(sess, max_concurrent=1, max_queue=1,
                            queue_timeout_s=60.0) as sched:
            svc = ProfilingService.start(sess)
            base = f"http://127.0.0.1:{svc.port}"
            hog = sched.submit(slow, label="hog")
            deadline = time.monotonic() + 10
            while hog.state in ("queued", "admitted") \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            schema = _register_src(sess, "fq", {"k": [1], "v": [1]})
            # max_queue bounds each tenant's OWN backlog: the filler must
            # queue as "web" for the HTTP submit (also "web") to see a
            # full doorway
            sched.submit(_agg_plan(schema, "fq", reducers=2),
                         label="queued", tenant="web")
            req = urllib.request.Request(f"{base}/serve/submit", data=body,
                                         method="POST")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 429
            retry_after = float(ei.value.headers["Retry-After"])
            assert 0.25 <= retry_after <= sess.conf.serve_retry_after_max_s
            payload = json.loads(ei.value.read())
            assert payload["error"] == "Backpressure"
            assert payload["retry_after_s"] == pytest.approx(retry_after,
                                                             abs=1e-3)
            hog.cancel()
    ProfilingService.stop()


# -- stage-boundary preemption ------------------------------------------------


@pytest.mark.quick
def test_pause_resume_cursor_replays_without_recompute():
    """Session-level pause/resume: a pre-requested pause is honored at the
    first stage-boundary commit; resuming with the cursor replays committed
    boundaries instead of recomputing them, across MULTIPLE pause cycles,
    and the final result is bit-identical to an unpreempted run."""
    conf = Config(memory_total=64 << 20, memory_fraction=1.0)
    with Session(conf=conf) as sess:
        n = 30_000
        data = {"k": [i % 17 for i in range(n)],
                "v": [(i * 48271) % n for i in range(n)]}
        schema = _register_src(sess, "pr", data)
        plan = _two_boundary_sort_plan(schema, "pr")
        ref = sess.execute_to_table(plan, release_on_finish=True)

        pt = PauseToken()
        pt.request("pause at first boundary")
        with pytest.raises(StagePaused) as ei:
            sess.execute_to_table(plan, release_on_finish=True,
                                  pause_token=pt, label="paused_q")
        cursor = ei.value.cursor
        assert len([e for e in cursor.entries.values()
                    if e[0] is not None]) >= 1
        assert cursor.shuffle_dirs, "cursor must pin committed shuffle state"
        # the paused query's dirs survive (pinned), nothing else leaks
        assert sess.query_log[-1]["state"] == "paused"

        # second cycle: replay boundary 1, pause at boundary 2
        pt.clear()
        pt.request("pause again")
        with pytest.raises(StagePaused) as ei2:
            sess.execute_to_table(plan, release_on_finish=True,
                                  cursor=cursor, pause_token=pt,
                                  label="paused_q")
        cursor = ei2.value.cursor
        resumed_after_first = sess.metrics.get("stages_resumed_from_cursor")
        assert resumed_after_first >= 1

        # final cycle: replay everything, finish
        pt.clear()
        got = sess.execute_to_table(plan, release_on_finish=True,
                                  cursor=cursor, pause_token=pt,
                                    label="paused_q")
        assert got.equals(ref), "resumed result must be bit-identical"
        assert sess.metrics.get("stages_resumed_from_cursor") \
            > resumed_after_first
        _assert_no_leaks(sess)


@pytest.mark.quick
def test_scheduler_preempts_for_interactive_and_resumes_identical():
    """End-to-end policy preemption: a long sort-shaped query holding the
    only slot is paused at its stage boundary when a higher-priority
    interactive query arrives, the interactive query completes first, and
    the long query resumes from its cursor to a bit-identical result with
    zero leaked bytes or segments."""
    conf = Config(memory_total=64 << 20, memory_fraction=1.0,
                  serve_preempt_after_s=0.05, serve_preempt_min_run_s=0.0)
    with Session(conf=conf) as sess:
        long_plan = _slow_source(sess, "long", batches=25, sleep_s=0.03,
                                 nparts=2)
        ref = sess.execute_to_table(long_plan, release_on_finish=True)
        schema = _register_src(sess, "inter", {"k": [1, 2], "v": [10, 20]})
        inter_plan = _agg_plan(schema, "inter", reducers=2)
        with QueryScheduler(sess, max_concurrent=1,
                            queue_timeout_s=120.0) as sched:
            h_long = sched.submit(long_plan, label="long_sort", priority=0)
            deadline = time.monotonic() + 10
            while h_long.state in ("queued", "admitted") \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            h_int = sched.submit(inter_plan, label="interactive",
                                 priority=5)
            t_int = h_int.result(timeout=60)
            assert dict(zip(t_int["k"].to_pylist(),
                            t_int["s"].to_pylist())) == {1: 10, 2: 20}
            t_long = h_long.result(timeout=120)
            assert t_long.equals(ref), \
                "preempted+resumed result must be bit-identical"
            assert h_long.preempt_count >= 1, "the pause must have happened"
            assert h_int.finished_at < h_long.finished_at
            assert sched.metrics.get("queries_preempted") >= 1
            assert sess.metrics.get("stages_resumed_from_cursor") >= 1
        _assert_no_leaks(sess)


@pytest.mark.quick
def test_paused_query_shed_releases_pinned_state():
    """A cursor abandoned without resuming (scheduler close / cancel of a
    paused query) releases its pinned shuffle segments — the leak gates
    treat it like a finished query."""
    conf = Config(memory_total=64 << 20, memory_fraction=1.0)
    with Session(conf=conf) as sess:
        n = 20_000
        schema = _register_src(sess, "ab", {"k": [i % 5 for i in range(n)],
                                            "v": list(range(n))})
        plan = _two_boundary_sort_plan(schema, "ab")
        pt = PauseToken()
        pt.request("pause")
        with pytest.raises(StagePaused) as ei:
            sess.execute_to_table(plan, release_on_finish=True,
                                  pause_token=pt, label="abandoned")
        cursor = ei.value.cursor
        assert cursor.shuffle_dirs
        sess.discard_cursor(cursor)
        _assert_no_leaks(sess)


@pytest.mark.quick
def test_torn_pause_lineage_heals_on_resume():
    """Torn pause: a committed map output dies while the query is paused
    (the in-process analogue of the worker holding it dying). Resume heals
    it from lineage BEFORE replaying — the query still completes with the
    right answer."""
    conf = Config(memory_total=64 << 20, memory_fraction=1.0)
    with Session(conf=conf) as sess:
        n = 20_000
        data = {"k": [i % 11 for i in range(n)],
                "v": [(i * 31) % 1000 for i in range(n)]}
        schema = _register_src(sess, "torn", data)
        plan = _two_boundary_sort_plan(schema, "torn")
        ref = sess.execute_to_table(plan, release_on_finish=True)
        pt = PauseToken()
        pt.request("pause for the tear")
        with pytest.raises(StagePaused) as ei:
            sess.execute_to_table(plan, release_on_finish=True,
                                  pause_token=pt, label="torn_q")
        cursor = ei.value.cursor
        victims = [p for d in cursor.shuffle_dirs
                   for p in glob.glob(os.path.join(d, "map_*.data"))]
        assert victims, "paused query must have committed map outputs"
        os.remove(victims[0])
        pt.clear()
        got = sess.execute_to_table(plan, release_on_finish=True,
                                  cursor=cursor, pause_token=pt,
                                    label="torn_q")
        assert got.equals(ref)
        assert sess.metrics.get("resume_maps_healed") >= 1, \
            "the lost map must have been recomputed at resume"
        _assert_no_leaks(sess)


def test_torn_pause_worker_death_pool(tmp_path):
    """Torn pause on a REAL worker pool: pause after the pool-executed map
    stage commits, kill a worker AND destroy one of its committed outputs,
    resume — lineage healing recomputes the loss in-driver and the query
    completes with the right answer."""
    import pyarrow.parquet as pq

    from blaze_tpu.ops.parquet import scan_node_for_files

    n = 40_000
    path = str(tmp_path / "pool.parquet")
    pq.write_table(pa.table({"k": [i % 13 for i in range(n)],
                             "v": [(i * 17) % 997 for i in range(n)]}), path)
    scan = scan_node_for_files([path], num_partitions=2)
    ex = N.ShuffleExchange(scan, N.HashPartitioning([E.Column("k")], 2))
    plan = N.Sort(ex, [E.SortOrder(E.Column("v")),
                       E.SortOrder(E.Column("k"))])
    conf = Config(memory_total=64 << 20, memory_fraction=1.0)
    with Session(conf=conf, num_worker_processes=2) as sess:
        ref = sess.execute_to_table(plan, release_on_finish=True)
        pt = PauseToken()
        pt.request("pause before the kill")
        with pytest.raises(StagePaused) as ei:
            sess.execute_to_table(plan, release_on_finish=True,
                                  pause_token=pt, label="pool_torn")
        cursor = ei.value.cursor
        sess.pool.kill_worker(0)  # the worker dies while the query sleeps
        victims = [p for d in cursor.shuffle_dirs
                   for p in glob.glob(os.path.join(d, "map_*.data"))]
        assert victims
        os.remove(victims[0])
        pt.clear()
        got = sess.execute_to_table(plan, release_on_finish=True,
                                  cursor=cursor, pause_token=pt,
                                    label="pool_torn")
        assert got.equals(ref)
        assert sess.metrics.get("resume_maps_healed") >= 1
        _assert_no_leaks(sess)


# -- tenant isolation under flood ---------------------------------------------


def _run_flood(sess, sched, light_plans, flood_plans):
    """Submit a flood + light mix; return the light tenant's e2e times."""
    floods = []
    for i, p in enumerate(flood_plans):
        try:
            floods.append(sched.submit(p, label=f"flood{i}",
                                       tenant="flood"))
        except Overloaded:
            pass
    lights = [sched.submit(p, label=f"light{i}", tenant="light")
              for i, p in enumerate(light_plans)]
    e2e = []
    for h in lights:
        h.result(timeout=240)
        e2e.append(h.finished_at - h.submitted_at)
    for h in floods:
        try:
            h.result(timeout=240)  # no admitted tenant starves
        except Overloaded:
            pass
    return e2e, floods


def _p99(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * 0.99))]


@pytest.mark.quick
def test_tenant_isolation_quick():
    """Quick-tier isolation check (in-process): a weight-4 light tenant's
    p99 under a weight-1 flood stays within 1.5x of its isolated p99 (plus
    a small absolute slack for CI timer noise), and every admitted flood
    query still completes — fair degradation, not starvation."""
    conf = Config(memory_total=64 << 20, memory_fraction=1.0,
                  serve_tenants="flood:1;light:4",
                  serve_preempt_after_s=0.05, serve_preempt_min_run_s=0.0)
    with Session(conf=conf) as sess:
        light_plans, flood_plans = [], []
        for i in range(4):
            n = 4000
            schema = _register_src(
                sess, f"light{i}", {"k": [j % 5 for j in range(n)],
                                    "v": list(range(n))})
            light_plans.append(_agg_plan(schema, f"light{i}"))
        for i in range(12):
            n = 12_000
            schema = _register_src(
                sess, f"flood{i}", {"k": [j % 7 for j in range(n)],
                                    "v": list(range(n))})
            flood_plans.append(_agg_plan(schema, f"flood{i}"))
        with QueryScheduler(sess, max_concurrent=2,
                            queue_timeout_s=240.0) as sched:
            iso, _ = _run_flood(sess, sched, light_plans, [])
            loaded, floods = _run_flood(sess, sched, light_plans,
                                        flood_plans)
            assert all(h.done() for h in floods), "flood tenant starved"
            assert _p99(loaded) <= 1.5 * _p99(iso) + 1.0, \
                f"light p99 {_p99(loaded):.3f}s vs isolated " \
                f"{_p99(iso):.3f}s — flooding tenant broke isolation"


@pytest.mark.slow
def test_tenant_isolation_worker_pool(tmp_path):
    """The ISSUE's full isolation gate on a real 2-worker pool: one
    flooding tenant, one light tenant; the light tenant's p99 stays within
    1.5x of its isolated run and no admitted tenant starves."""
    import pyarrow.parquet as pq

    from blaze_tpu.ops.parquet import scan_node_for_files

    def pool_plan(path, reducers=3):
        scan = scan_node_for_files([path], num_partitions=2)
        groupings = [("k", E.Column("k"))]
        partial = N.Agg(scan, HASH, groupings,
                        [N.AggColumn(E.AggExpr(F.SUM, [E.Column("v")],
                                               T.I64), M.PARTIAL, "s")])
        ex = N.ShuffleExchange(partial,
                               N.HashPartitioning([E.Column("k")],
                                                  reducers))
        return N.Agg(ex, HASH, groupings,
                     [N.AggColumn(E.AggExpr(F.SUM, [E.Column("v")],
                                            T.I64), M.FINAL, "s")])

    light_path = str(tmp_path / "light.parquet")
    flood_path = str(tmp_path / "flood.parquet")
    pq.write_table(pa.table({"k": [i % 5 for i in range(8_000)],
                             "v": list(range(8_000))}), light_path)
    pq.write_table(pa.table({"k": [i % 9 for i in range(60_000)],
                             "v": list(range(60_000))}), flood_path)
    conf = Config(memory_total=128 << 20, memory_fraction=1.0,
                  serve_tenants="flood:1;light:4",
                  serve_preempt_after_s=0.05, serve_preempt_min_run_s=0.0)
    with Session(conf=conf, num_worker_processes=2) as sess:
        light_plans = [pool_plan(light_path) for _ in range(5)]
        flood_plans = [pool_plan(flood_path) for _ in range(16)]
        with QueryScheduler(sess, max_concurrent=2,
                            queue_timeout_s=300.0) as sched:
            iso, _ = _run_flood(sess, sched, light_plans, [])
            loaded, floods = _run_flood(sess, sched, light_plans,
                                        flood_plans)
            assert all(h.done() for h in floods), "flood tenant starved"
            assert _p99(loaded) <= 1.5 * _p99(iso) + 2.0, \
                f"light p99 {_p99(loaded):.3f}s vs isolated {_p99(iso):.3f}s"

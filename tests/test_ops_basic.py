import pytest
import numpy as np
import pyarrow as pa

from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T
from blaze_tpu.ops.basic import (
    CoalesceBatchesExec,
    DebugExec,
    EmptyPartitionsExec,
    ExpandExec,
    FilterExec,
    LimitExec,
    ProjectExec,
    RenameColumnsExec,
    UnionExec,
)
from tests.util import collect_pydict, mem_scan, run_op


def col(n):
    return E.Column(n)


def lit(v, t):
    return E.Literal(v, t)


def test_project():
    scan = mem_scan({"a": pa.array([1, 2, 3], type=pa.int64())})
    op = ProjectExec(scan, [E.BinaryExpr(E.BinaryOp.ADD, col("a"), lit(10, T.I64))], ["b"])
    assert collect_pydict(op) == {"b": [11, 12, 13]}
    assert op.schema.names == ["b"]


def test_filter():
    scan = mem_scan(
        {"a": pa.array([1, None, 5, 7], type=pa.int64()), "s": pa.array(["w", "x", "y", "z"])},
        num_batches=2,
    )
    op = FilterExec(scan, [E.BinaryExpr(E.BinaryOp.GT, col("a"), lit(2, T.I64))])
    out = collect_pydict(op)
    assert out == {"a": [5, 7], "s": ["y", "z"]}


@pytest.mark.quick
def test_filter_project_fusion():
    scan = mem_scan({"a": pa.array([1, 5], type=pa.int64())})
    op = FilterExec(
        scan,
        [E.BinaryExpr(E.BinaryOp.GT, col("a"), lit(2, T.I64))],
        projection=([E.BinaryExpr(E.BinaryOp.MUL, col("a"), lit(2, T.I64))], ["a2"]),
    )
    assert collect_pydict(op) == {"a2": [10]}


def test_limit():
    scan = mem_scan({"a": list(range(10))}, num_batches=3)
    op = LimitExec(scan, 5)
    assert collect_pydict(op) == {"a": [0, 1, 2, 3, 4]}


def test_coalesce_batches():
    scan = mem_scan({"a": list(range(20))}, num_batches=10)
    op = CoalesceBatchesExec(scan, batch_size=8)
    from tests.util import run_op

    batches = run_op(op)
    assert [b.num_rows for b in batches] == [8, 8, 4]
    assert sum(b.num_rows for b in batches) == 20


def test_rename_and_debug():
    scan = mem_scan({"a": [1], "b": ["x"]})
    op = DebugExec(RenameColumnsExec(scan, ["c1", "c2"]), "t")
    out = collect_pydict(op)
    assert out == {"c1": [1], "c2": ["x"]}


def test_union():
    s1 = mem_scan({"a": [1, 2]})
    s2 = mem_scan({"a": [3]})
    op = UnionExec([s1, s2], num_partitions=2)
    assert collect_pydict(op) == {"a": [1, 2, 3]}


def test_empty_partitions():
    op = EmptyPartitionsExec(T.Schema.of(("a", T.I64)), 3)
    assert op.num_partitions() == 3
    assert collect_pydict(op) == {"a": []}


def test_expand():
    scan = mem_scan({"a": pa.array([1, 2], type=pa.int64())})
    schema = T.Schema.of(("a", T.I64), ("tag", T.I64))
    op = ExpandExec(
        scan,
        [[col("a"), lit(0, T.I64)], [E.BinaryExpr(E.BinaryOp.MUL, col("a"), lit(10, T.I64)), lit(1, T.I64)]],
        schema,
    )
    out = collect_pydict(op)
    assert out["a"] == [1, 2, 10, 20]
    assert out["tag"] == [0, 0, 1, 1]

"""Code-carrying shuffle: dictionary columns cross the exchange as index
codes plus once-per-stream dictionary definitions (``dict_ref`` frames).

Unit coverage of the frame protocol (FRAME_DICT_DEF sequencing, shared
dictionary identity on the decode side, oversized-dictionary pruning, the
legacy non-ref stream), plus a worker-pool roundtrip asserting the final
agg over string keys is bit-identical with codes_shuffle on and off."""

import collections
import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.config import config_override
from blaze_tpu.core import ColumnarBatch
from blaze_tpu.io.batch_serde import (
    FRAME_DICT_DEF,
    BatchReader,
    BatchWriter,
    dict_identity,
    read_frames,
)
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.runtime.session import Session

F = E.AggFunction
M = E.AggMode


def _dict_batches(n=1000, card=37):
    """Two batches sliced off one dictionary-encoded column — the shape a
    partial agg emits (one dictionary shared across every slice)."""
    arr = pa.array([f"key-{i % card}" for i in range(n)]).dictionary_encode()
    big = ColumnarBatch.from_pydict({"k": arr, "v": list(range(n))})
    half = n // 2
    return big, [big.slice(0, half), big.slice(half, half)]


@pytest.mark.quick
def test_dict_def_frame_sequencing():
    """First frame defines the dictionary (FRAME_DICT_DEF), later frames
    ship codes only; the decode side rebuilds every batch dict-encoded over
    one shared dictionary."""
    big, batches = _dict_batches()
    buf = io.BytesIO()
    w = BatchWriter(buf, codec="none", dict_refs=True)
    for b in batches:
        w.write_batch(b)
    assert w.codes_bytes > 0

    buf.seek(0)
    flag_seq = [flags & FRAME_DICT_DEF for flags, _, _ in read_frames(buf)]
    assert flag_seq == [FRAME_DICT_DEF, 0]

    buf.seek(0)
    got = list(BatchReader(buf))
    tbl = pa.Table.from_batches([b.to_arrow() for b in got])
    assert tbl.to_pydict() == big.to_arrow().to_pydict()
    # the wire columns (before to_arrow() normalizes to the schema type)
    # stay dictionary-encoded over one shared dictionary
    arrs = [b.column(0).array for b in got]
    assert all(pa.types.is_dictionary(a.type) for a in arrs)
    assert dict_identity(arrs[0].dictionary) == dict_identity(arrs[1].dictionary)


@pytest.mark.quick
def test_oversized_dictionary_pruned():
    """A huge shared dictionary behind a tiny batch is re-encoded compactly
    per frame instead of being shipped as a ref."""
    big_dict = pa.array([f"val-{i}" for i in range(5000)])
    idx = pa.array(np.arange(10, dtype=np.int32))
    arr = pa.DictionaryArray.from_arrays(idx, big_dict)
    batch = ColumnarBatch.from_pydict({"k": arr})
    buf = io.BytesIO()
    w = BatchWriter(buf, codec="none", dict_refs=True)
    w.write_batch(batch)
    assert w.codes_bytes == 0  # pruned: no ref, no codes accounting
    buf.seek(0)
    (flags, _, _), = list(read_frames(buf))
    assert not flags & FRAME_DICT_DEF
    buf.seek(0)
    (got,) = list(BatchReader(buf))
    assert got.to_arrow().column("k").to_pylist() == arr.to_pylist()


def test_legacy_stream_roundtrips_dicts():
    """dict_refs=False keeps the old wire shape: dictionaries travel inside
    each frame's arrow IPC, no dict-def flags, no codes accounting."""
    big, batches = _dict_batches(n=600)
    buf = io.BytesIO()
    w = BatchWriter(buf, codec="none", dict_refs=False)
    for b in batches:
        w.write_batch(b)
    assert w.codes_bytes == 0
    buf.seek(0)
    assert all(not flags & FRAME_DICT_DEF for flags, _, _ in read_frames(buf))
    buf.seek(0)
    tbl = pa.Table.from_batches([b.to_arrow() for b in BatchReader(buf)])
    assert tbl.to_pydict() == big.to_arrow().to_pydict()


def test_redefined_ref_decodes_in_order():
    """Spilled stream segments restart ref numbering: a second definition of
    ref 0 must replace the first for frames that follow it."""
    a1 = pa.array(["a", "b", "a"]).dictionary_encode()
    a2 = pa.array(["x", "y", "x"]).dictionary_encode()
    buf = io.BytesIO()
    for arr in (a1, a2):
        # separate writers emulate two stream segments concatenated by the
        # spill merge (each restarts at ref 0)
        w = BatchWriter(buf, codec="none", dict_refs=True)
        w.write_batch(ColumnarBatch.from_pydict({"k": arr}))
    buf.seek(0)
    got = [b.to_arrow().column("k").to_pylist() for b in BatchReader(buf)]
    assert got == [a1.to_pylist(), a2.to_pylist()]


def _string_agg_plan(paths, reducers=3):
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files(paths, num_partitions=2)
    partial = N.Agg(scan, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))], [
        N.AggColumn(E.AggExpr(F.SUM, [E.Column("v")]), E.AggMode.PARTIAL, "s"),
        N.AggColumn(E.AggExpr(F.COUNT, []), E.AggMode.PARTIAL, "c"),
    ], supports_partial_skipping=True)
    ex = N.ShuffleExchange(partial, N.HashPartitioning([E.Column("k")], reducers))
    final = N.Agg(ex, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))], [
        N.AggColumn(E.AggExpr(F.SUM, [E.Column("v")]), E.AggMode.FINAL, "s"),
        N.AggColumn(E.AggExpr(F.COUNT, []), E.AggMode.FINAL, "c"),
    ])
    single = N.ShuffleExchange(final, N.SinglePartitioning(1))
    return N.Sort(single, [E.SortOrder(E.Column("k"))])


@pytest.fixture(scope="module")
def string_key_files(tmp_path_factory):
    td = tmp_path_factory.mktemp("codesdata")
    rng = np.random.default_rng(31)
    paths = []
    for p in range(2):
        n = 12000
        tbl = pa.table({
            "k": pa.array([f"user-{i:05d}" for i in rng.integers(0, 4000, n)]),
            "v": pa.array(rng.integers(0, 1000, n), type=pa.int64()),
        })
        path = str(td / f"f{p}.parquet")
        pq.write_table(tbl, path)
        paths.append(path)
    return paths


@pytest.mark.slow
def test_codes_shuffle_bit_identical_on_worker_pool(string_key_files):
    """Dict-encoded partial-agg batches cross a real worker-pool shuffle;
    the final agg is bit-identical to the decoded-values path, codes bytes
    were actually shipped, and no rows were re-interned at merge tables."""
    plan = _string_agg_plan(string_key_files)
    with config_override(codes_shuffle=False):
        with Session(num_worker_processes=2) as s:
            decoded = s.execute_to_table(plan)
    with config_override(codes_shuffle=True):
        with Session(num_worker_processes=2) as s:
            coded = s.execute_to_table(plan)
            codes_bytes = s.metrics.total("codes_shuffle_bytes")
            reintern = s.metrics.total("agg_reintern_rows")
    assert coded.to_pydict() == decoded.to_pydict()
    assert codes_bytes > 0
    assert reintern == 0
    # sanity against an independent oracle
    exp_s = collections.defaultdict(int)
    exp_c = collections.defaultdict(int)
    for path in string_key_files:
        t = pq.read_table(path)
        for k, v in zip(t.column("k").to_pylist(), t.column("v").to_pylist()):
            exp_s[k] += v
            exp_c[k] += 1
    out = coded.to_pydict()
    assert out["k"] == sorted(exp_s)
    assert out["s"] == [exp_s[k] for k in out["k"]]
    assert out["c"] == [exp_c[k] for k in out["k"]]

"""Column-pruning optimizer (projection pushdown into file scans) —
reference: ExecuteWithColumnPruning, common/column_pruning.rs:22-48."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest
from decimal import Decimal

from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.ir.optimizer import expr_columns, prune_plan
from blaze_tpu.ops.parquet import scan_node_for_files
from blaze_tpu.runtime.session import Session


@pytest.fixture
def wide_file(tmp_path):
    rng = np.random.default_rng(3)
    tbl = pa.table({
        "k": pa.array(rng.integers(1, 10, 500), type=pa.int64()),
        "v": pa.array([Decimal(int(x)).scaleb(-2)
                       for x in rng.integers(0, 10000, 500)],
                      type=pa.decimal128(9, 2)),
        "unused1": pa.array(rng.integers(0, 100, 500), type=pa.int64()),
        "unused2": pa.array([f"s{i}" for i in range(500)]),
    })
    path = str(tmp_path / "wide.parquet")
    pq.write_table(tbl, path)
    return path, tbl


def _scans(plan):
    out = []
    stack = [plan]
    while stack:
        n = stack.pop()
        if isinstance(n, (N.ParquetScan, N.OrcScan)):
            out.append(n)
        stack.extend(n.children())
    return out


def _scan_names(plan):
    return [
        [s.conf.file_schema[i].name for i in s.conf.projection]
        for s in _scans(plan)
    ]


def _q01_plan(path):
    scan = scan_node_for_files([path])
    filt = N.Filter(scan, [E.BinaryExpr(
        E.BinaryOp.GT, E.Column("v"), E.Literal("5.00", T.DecimalType(9, 2)))])
    partial = N.Agg(filt, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("v")],
                              T.DecimalType(19, 2)), E.AggMode.PARTIAL, "total")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([E.Column("k")], 2))
    final = N.Agg(ex, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("v")],
                              T.DecimalType(19, 2)), E.AggMode.FINAL, "total")])
    return N.Sort(final, [E.SortOrder(E.Column("total"), ascending=False)])


def test_expr_columns():
    assert expr_columns(E.Column("a")) == frozenset({"a"})
    assert expr_columns(E.BinaryExpr(
        E.BinaryOp.ADD, E.Column("a"), E.Column("b"))) == {"a", "b"}
    assert expr_columns(E.BoundReference(1)) is None
    assert expr_columns(E.Literal(1, T.I64)) == frozenset()


def test_scan_pruned_through_agg_pipeline(wide_file):
    path, _ = wide_file
    pruned = prune_plan(_q01_plan(path))
    assert _scan_names(pruned) == [["k", "v"]]


def test_pruned_plan_results_equal(wide_file):
    path, tbl = wide_file
    plan = _q01_plan(path)
    from blaze_tpu.config import get_config
    import dataclasses as dc

    with Session(conf=dc.replace(get_config(), column_pruning_enable=False)) as s:
        expected = s.execute_to_pydict(plan)
    with Session() as s:
        got = s.execute_to_pydict(_q01_plan(path))
    assert got == expected


def test_count_star_keeps_one_column(wide_file):
    path, _ = wide_file
    scan = scan_node_for_files([path])
    agg = N.Agg(scan, E.AggExecMode.HASH_AGG, [], [
        N.AggColumn(E.AggExpr(E.AggFunction.COUNT, []), E.AggMode.COMPLETE, "n")])
    pruned = prune_plan(agg)
    assert len(_scan_names(pruned)[0]) == 1
    with Session() as s:
        assert s.execute_to_pydict(pruned) == {"n": [500]}


def test_bound_reference_disables_pruning(wide_file):
    path, _ = wide_file
    scan = scan_node_for_files([path])
    filt = N.Filter(scan, [E.BinaryExpr(
        E.BinaryOp.GT, E.BoundReference(0), E.Literal(5, T.I64))])
    agg = N.Agg(filt, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.COUNT, []), E.AggMode.COMPLETE, "n")])
    pruned = prune_plan(agg)
    assert _scan_names(pruned) == [["k", "v", "unused1", "unused2"]]


def test_join_prunes_both_sides(tmp_path):
    left = pa.table({
        "lk": pa.array([1, 2, 3], type=pa.int64()),
        "lv": pa.array([10, 20, 30], type=pa.int64()),
        "lextra": pa.array(["a", "b", "c"]),
    })
    right = pa.table({
        "rk": pa.array([2, 3, 4], type=pa.int64()),
        "rv": pa.array([200, 300, 400], type=pa.int64()),
        "rextra": pa.array(["x", "y", "z"]),
    })
    lp, rp = str(tmp_path / "l.parquet"), str(tmp_path / "r.parquet")
    pq.write_table(left, lp)
    pq.write_table(right, rp)
    join = N.SortMergeJoin(
        N.Sort(scan_node_for_files([lp]), [E.SortOrder(E.Column("lk"))]),
        N.Sort(scan_node_for_files([rp]), [E.SortOrder(E.Column("rk"))]),
        on=[(E.Column("lk"), E.Column("rk"))], join_type=N.JoinType.INNER)
    proj = N.Projection(join, [E.Column("lv"), E.Column("rv")], ["lv", "rv"])
    pruned = prune_plan(proj)
    names = sorted(map(tuple, _scan_names(pruned)))
    assert names == [("lk", "lv"), ("rk", "rv")]
    with Session() as s:
        got = s.execute_to_pydict(pruned)
    assert sorted(zip(got["lv"], got["rv"])) == [(20, 200), (30, 300)]


def test_duplicate_join_names_bail(tmp_path):
    tbl = pa.table({"k": pa.array([1, 2], type=pa.int64()),
                    "v": pa.array([1, 2], type=pa.int64())})
    lp, rp = str(tmp_path / "l.parquet"), str(tmp_path / "r.parquet")
    pq.write_table(tbl, lp)
    pq.write_table(tbl, rp)
    join = N.SortMergeJoin(
        N.Sort(scan_node_for_files([lp]), [E.SortOrder(E.Column("k"))]),
        N.Sort(scan_node_for_files([rp]), [E.SortOrder(E.Column("k"))]),
        on=[(E.Column("k"), E.Column("k"))], join_type=N.JoinType.INNER)
    proj = N.Projection(join, [E.Column("k")], ["k"])
    pruned = prune_plan(proj)
    # both sides have k and v: ambiguous by name, scans stay full
    assert all(names == ["k", "v"] for names in _scan_names(pruned))


def test_rename_prunes_by_new_name(wide_file):
    path, tbl = wide_file
    scan = scan_node_for_files([path])
    renamed = N.RenameColumns(scan, ["rk", "rv", "ru1", "ru2"])
    proj = N.Projection(renamed, [E.Column("rv")], ["rv"])
    pruned = prune_plan(proj)
    assert _scan_names(pruned) == [["v"]]
    with Session() as s:
        got = s.execute_to_pydict(pruned)
    assert got["rv"] == tbl["v"].to_pylist()


def test_generate_keeps_child_columns(tmp_path):
    # Generate uses positional required_child_output: its child must not shrink
    tbl = pa.table({
        "id": pa.array([1, 2], type=pa.int64()),
        "arr": pa.array([[1, 2], [3]], type=pa.list_(pa.int64())),
        "pad": pa.array([9, 9], type=pa.int64()),
    })
    path = str(tmp_path / "g.parquet")
    pq.write_table(tbl, path)
    scan = scan_node_for_files([path])
    gen = N.Generate(
        scan, "explode", [E.Column("arr")], required_child_output=[0],
        generator_output=T.Schema((T.StructField("e", T.I64),)))
    pruned = prune_plan(gen)
    assert _scan_names(pruned) == [["id", "arr", "pad"]]


def test_case_branches_counted(wide_file):
    # regression: Case branches are [(cond, value)] tuples — their columns
    # must be seen by the requirement analysis
    path, _ = wide_file
    scan = scan_node_for_files([path])
    case = E.Case(
        [(E.BinaryExpr(E.BinaryOp.GT, E.Column("unused1"), E.Literal(50, T.I64)),
          E.Column("v"))],
        E.Literal("0.00", T.DecimalType(9, 2)))
    proj = N.Projection(scan, [E.Column("k"), case], ["k", "cv"])
    pruned = prune_plan(proj)
    assert _scan_names(pruned) == [["k", "v", "unused1"]]

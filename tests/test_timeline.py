"""Live health plane tests (ISSUE 20, blaze_tpu/obs/timeline.py): ring
wrap, the slo_specs grammar, counter-rate and histogram-quantile math
against hand-computed values, ``Histogram.snapshot_delta`` under
concurrent observers, burn-rate window goldens driving the full
healthy -> degraded -> critical -> healthy transition arc (exactly one
incident bundle per edge), sampler thread start/stop hygiene across
sessions (no leak), the /debug/health + /debug/timeseries endpoints,
``bench_diff --health`` gating (pre-health artifacts self-diff clean),
the disabled-path <5% overhead guard, and a quick-tier e2e on a real
2-worker pool where the ingest-lag series rises on append and returns
to zero after the cached refresh."""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.config import Config
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.obs.telemetry import (bucket_upper_bound, get_registry,
                                     quantile_from_snapshot)
from blaze_tpu.obs.timeline import (ARTIFACT_SERIES, SUBSYSTEMS, TIMELINE,
                                    Ring, Timeline, get_timeline,
                                    parse_slo_specs,
                                    timeline_artifact_section)
from blaze_tpu.runtime.memmgr import MemManager
from blaze_tpu.runtime.session import Session

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

F = E.AggFunction
M = E.AggMode
HASH = E.AggExecMode.HASH_AGG


@pytest.fixture(autouse=True)
def _fresh():
    MemManager.reset()
    TIMELINE.stop()
    TIMELINE.reset()
    yield
    TIMELINE.stop()
    TIMELINE.reset()
    MemManager.reset()


def _batch(ks, vs):
    return pa.RecordBatch.from_pydict({"k": ks, "v": vs})


def _agg_plan(child, reducers=3):
    g = [("k", E.Column("k"))]
    partial = N.Agg(child, HASH, g, [N.AggColumn(
        E.AggExpr(F.SUM, [E.Column("v")], T.I64), M.PARTIAL, "s")])
    ex = N.ShuffleExchange(partial,
                           N.HashPartitioning([E.Column("k")], reducers))
    return N.Agg(ex, HASH, g, [N.AggColumn(
        E.AggExpr(F.SUM, [E.Column("v")], T.I64), M.FINAL, "s")])


def _tl_threads():
    return [t for t in threading.enumerate() if t.name == "blaze-timeline"]


# -- ring ----------------------------------------------------------------------


def test_ring_wrap_keeps_newest():
    r = Ring(5)
    for i in range(12):
        r.append(float(i), float(i * 10))
    assert len(r) == 5
    assert r.items() == [(float(i), float(i * 10)) for i in range(7, 12)]
    assert r.last() == (11.0, 110.0)
    assert r.since(9.0) == [(9.0, 90.0), (10.0, 100.0), (11.0, 110.0)]


def test_ring_partial_fill_in_order():
    r = Ring(8)
    r.append(1.0, 1.0)
    r.append(2.0, 2.0)
    assert r.items() == [(1.0, 1.0), (2.0, 2.0)]
    assert len(r) == 2


# -- slo_specs grammar ---------------------------------------------------------


def test_parse_slo_specs_grammar():
    specs = parse_slo_specs(
        "serve:serve_deadline_miss_ratio<=0.05;"
        "ingest:ingest_lag_versions<=2; cache:cache_stale_served_rate==0")
    assert [s.subsystem for s in specs] == ["serve", "ingest", "cache"]
    assert specs[0].check(0.05) and not specs[0].check(0.06)
    assert specs[2].check(0.0) and not specs[2].check(0.1)
    assert specs[1].key == "ingest:ingest_lag_versions<=2"


def test_parse_slo_specs_rejects_malformed():
    with pytest.raises(ValueError):
        parse_slo_specs("serve:deadline_miss 0.05")  # no operator
    with pytest.raises(ValueError):
        parse_slo_specs("nosuchsub:x_ratio<=0.1")  # unknown subsystem
    assert parse_slo_specs("") == []
    assert parse_slo_specs(" ; ") == []


def test_configure_from_keeps_objectives_on_malformed_specs():
    tl = Timeline()
    tl.configure(Config(slo_specs="serve:serve_deadline_miss_ratio<=0.05"))
    assert len(tl._slos) == 1
    # a typo'd reconfigure must not silently drop the objectives
    try:
        tl.configure(Config(slo_specs="serve:broken"))
    except ValueError:
        pass
    assert [s.key for s in tl._slos] == \
        ["serve:serve_deadline_miss_ratio<=0.05"]


# -- sampler math: rates and quantiles ----------------------------------------


def test_counter_rate_hand_computed():
    tl = Timeline()
    tl.configure(Config(slo_specs=""))
    c = get_registry().counter("blaze_testtl_ticks_total", "test counter")
    tl.sample_once(now=100.0)  # establishes prev; no rate yet (no dt)
    assert tl.latest("blaze_testtl_ticks_total:rate") is None
    c.inc(30)
    tl.sample_once(now=110.0)
    assert tl.latest("blaze_testtl_ticks_total:rate") == \
        pytest.approx(30.0 / 10.0)
    # flat interval -> zero rate
    tl.sample_once(now=120.0)
    assert tl.latest("blaze_testtl_ticks_total:rate") == 0.0
    # a shrunk total (reset_values between samples) clamps to 0 rate,
    # never negative
    with c._mu:
        c._series.clear()
    tl.sample_once(now=130.0)
    assert tl.latest("blaze_testtl_ticks_total:rate") == 0.0
    assert c is get_registry().counter("blaze_testtl_ticks_total", "")


def test_histogram_quantiles_hand_computed():
    tl = Timeline()
    tl.configure(Config(slo_specs=""))
    h = get_registry().histogram("blaze_testtl_lat_seconds", "test hist")
    tl.sample_once(now=10.0)
    for _ in range(100):
        h.observe(2.0)
    for _ in range(100):
        h.observe(32.0)
    tl.sample_once(now=11.0)
    # log buckets, 4/octave: 2.0 -> idx 4 (le 2^(5/4)), 32.0 -> idx 20
    # (le 2^(21/4)); p50 = target rank 100 lands exactly on the first
    # bucket, p99 interpolates log-linearly inside the second
    p50 = tl.latest("blaze_testtl_lat_seconds:p50")
    p99 = tl.latest("blaze_testtl_lat_seconds:p99")
    le_lo, le_hi = 2.0 ** (5 / 4), 2.0 ** (21 / 4)
    assert p50 == pytest.approx(le_lo)
    frac = (198 - 100) / 100  # rank 198 of 200, 98 into the second bucket
    assert p99 == pytest.approx(le_lo * (le_hi / le_lo) ** frac)
    # the NEXT interval has no new observations -> no quantile sample
    tl.sample_once(now=12.0)
    s = tl.series_since("blaze_testtl_lat_seconds:p99", 0.0)
    assert [t for t, _ in s] == [11.0]


def test_snapshot_delta_concurrent_observers():
    h = get_registry().histogram("blaze_testtl_conc_seconds", "test hist")
    stop = threading.Event()
    observed = [0] * 4

    def worker(i):
        while not stop.is_set():
            h.observe(0.5 + i)
            observed[i] += 1

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    prev = h.snapshot() or {"buckets": {}, "sum": 0.0, "count": 0}
    for t in threads:
        t.start()
    seen = 0
    for _ in range(50):
        cur = h.snapshot()
        d = h.snapshot_delta(prev)
        assert d["count"] >= 0
        assert all(c >= 0 for c in d["buckets"].values())
        assert sum(d["buckets"].values()) == d["count"]
        seen += d["count"]
        prev = cur
    stop.set()
    for t in threads:
        t.join()
    cur = h.snapshot()
    seen += h.snapshot_delta(prev)["count"]
    assert seen == sum(observed)  # chained deltas tile the total exactly
    assert quantile_from_snapshot(cur, 0.5) is not None


# -- burn-rate goldens + health transitions + incident bundles -----------------


def _drive(tl, t, miss):
    """One tick at time ``t``: 10 outcomes, all deadline misses when
    ``miss`` else all served."""
    for _ in range(10):
        tl.note_outcome("dash", "deadline" if miss else "done")
    tl.sample_once(now=float(t))


def test_burn_rate_windows_and_health_arc(tmp_path):
    """Golden arc at 1s cadence: 60 healthy ticks, 60 breaching, 31
    recovering. Fast window catches onset (degraded at the 2nd breach:
    2/11 samples breaching -> burn 1.82 >= 1.0), critical waits for the
    slow window to confirm (multiwindow rule), recovery unwinds through
    degraded back to healthy — and every edge writes exactly one
    incident bundle."""
    tl = Timeline()
    tl.configure(Config(
        slo_specs="serve:serve_deadline_miss_ratio<=0.05;"
                  "cache:cache_hit_ratio>=0.5",
        slo_fast_window_s=10.0, slo_slow_window_s=60.0,
        slo_error_budget_ratio=0.1, slo_degraded_burn=1.0,
        slo_critical_burn=2.0,
        incident_dir=str(tmp_path), incident_max_bundles=32))
    tl.enabled = True  # hot-path hook on, without the thread
    for t in range(60):
        _drive(tl, t, miss=False)
    assert tl._sub_state["serve"] == "healthy"
    serve = tl._slos[0]
    assert serve.burn_fast == 0.0 and serve.burn_slow == 0.0
    for t in range(60, 120):
        _drive(tl, t, miss=True)
    assert tl._sub_state["serve"] == "critical"
    assert serve.burn_fast == pytest.approx(10.0)  # all-breach fast window
    for t in range(120, 151):
        _drive(tl, t, miss=False)
    assert tl._sub_state["serve"] == "healthy"

    rep = tl.health_report(now=151.0)
    arc = [(tr["from"], tr["to"]) for tr in rep["transitions"]
           if tr["subsystem"] == "serve"]
    assert arc == [("healthy", "degraded"), ("degraded", "critical"),
                   ("critical", "degraded"), ("degraded", "healthy")]
    assert rep["critical_intervals"] == 1
    assert rep["degraded_s"] > 0 and rep["critical_s"] > 0
    assert 0.0 < rep["degraded_ratio"] < 1.0
    assert rep["samples"] == 151
    # cache_hit_ratio never produced data: no budget spent, stays healthy
    cache_slo = rep["slo"]["cache:cache_hit_ratio>=0.5"]
    assert cache_slo["state"] == "healthy"
    assert cache_slo["last_value"] is None
    assert rep["subsystems"]["cache"]["state"] == "healthy"
    # exactly one incident bundle per transition edge
    bundles = [f for f in os.listdir(tmp_path) if "_health_" in f]
    assert len(bundles) == 4
    kinds = sorted(json.load(open(os.path.join(tmp_path, f)))["label"]
                   for f in bundles)
    assert kinds == sorted(["serve:healthy-degraded",
                            "serve:degraded-critical",
                            "serve:critical-degraded",
                            "serve:degraded-healthy"])


def test_single_hiccup_never_goes_critical():
    """One breaching sample after healthy history degrades at worst — the
    slow window refuses to confirm, so it cannot page."""
    tl = Timeline()
    tl.configure(Config(
        slo_specs="serve:serve_deadline_miss_ratio<=0.05",
        slo_fast_window_s=10.0, slo_slow_window_s=60.0,
        slo_error_budget_ratio=0.1, slo_degraded_burn=1.0,
        slo_critical_burn=2.0, incident_dir=""))
    tl.enabled = True
    for t in range(60):
        _drive(tl, t, miss=False)
    _drive(tl, 60, miss=True)
    assert tl._sub_state["serve"] != "critical"
    for t in range(61, 75):
        _drive(tl, t, miss=False)
    assert tl._sub_state["serve"] == "healthy"
    assert tl.health_report(now=75.0)["critical_intervals"] == 0


# -- artifact section + bench_diff --health ------------------------------------


def test_artifact_section_and_bench_diff_health(tmp_path):
    tl = get_timeline()
    tl.configure(Config(slo_specs="serve:serve_deadline_miss_ratio<=0.05",
                        incident_dir=""))
    tl.enabled = True
    for t in range(5):
        tl.sample_once(now=float(t))
    out = timeline_artifact_section()
    assert set(out) == {"health", "timeline"}
    assert set(out["timeline"]) == set(ARTIFACT_SERIES)
    for s in ARTIFACT_SERIES:
        assert all(len(p) == 2 for p in out["timeline"][s])
    assert out["health"]["samples"] == 5
    assert set(out["health"]["subsystems"]) == set(SUBSYSTEMS)

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import bench_diff

    art = {"health": out["health"], "timeline": out["timeline"]}
    assert bench_diff.diff_health(art, art) == []
    # pre-health artifacts (no section) self-diff clean, like --attribution
    assert bench_diff.diff_health({}, {}) == []
    assert bench_diff.diff_health({}, art) == []
    # any critical interval in the candidate is a regression
    bad = json.loads(json.dumps(art))
    bad["health"]["critical_intervals"] = 1
    bad["health"]["critical_s"] = 3.0
    assert any("critical" in r for r in bench_diff.diff_health(art, bad))
    # degraded-time ratio gate: over max(base, tol) fails
    slow = json.loads(json.dumps(art))
    slow["health"]["degraded_ratio"] = 0.6
    assert any("degraded_ratio" in r
               for r in bench_diff.diff_health(art, slow))
    assert bench_diff.diff_health(slow, slow) == []  # grandfathered base


# -- lifecycle: thread hygiene across sessions ---------------------------------


def test_sampler_thread_hygiene_no_leak():
    assert _tl_threads() == []
    for _ in range(3):
        with Session(conf=Config(timeline_interval_s=0.05)):
            assert len(_tl_threads()) == 1
        assert _tl_threads() == []  # session close joins the sampler
    # a second session rebinds the one process-global thread
    s1 = Session(conf=Config(timeline_interval_s=0.05))
    s2 = Session(conf=Config(timeline_interval_s=0.05))
    try:
        assert len(_tl_threads()) == 1
    finally:
        s2.close()
        s1.close()
    assert _tl_threads() == []


def test_timeline_disabled_starts_nothing():
    with Session(conf=Config(timeline_enabled=False)):
        assert _tl_threads() == []
        assert not TIMELINE.enabled
        TIMELINE.note_outcome("t", "done")  # cheap no-op, drops the note
        assert TIMELINE._outcomes == {}


def test_env_force_disable_overrides_config(monkeypatch):
    monkeypatch.setenv("BLAZE_TPU_TIMELINE", "0")
    with Session(conf=Config(timeline_enabled=True)):
        assert _tl_threads() == []
        assert not TIMELINE.enabled


# -- disabled-path overhead guard ----------------------------------------------


@pytest.mark.quick
def test_timeline_disabled_overhead_under_5_percent(tmp_path):
    """With the plane off the only per-outcome cost in the scheduler is
    one attribute check in ``note_outcome``; scaled by a generous outcome
    count it stays under 5% of a real query's wall (same bar as the
    tracer/stats/attribution planes)."""
    from blaze_tpu.ops.parquet import scan_node_for_files

    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"k": [i % 97 for i in range(200_000)],
                             "v": list(range(200_000))}), path)
    plan = _agg_plan(scan_node_for_files([path], num_partitions=2))
    with Session(conf=Config(timeline_enabled=False)) as sess:
        t0 = time.perf_counter_ns()
        out = sess.execute_to_pydict(plan)
        wall_ns = time.perf_counter_ns() - t0
        assert len(out["k"]) == 97

        ITER = 100_000
        t0 = time.perf_counter_ns()
        for _ in range(ITER):
            TIMELINE.note_outcome("dash", "done")
        per_call_ns = (time.perf_counter_ns() - t0) / ITER
    overhead_ns = per_call_ns * 10_000  # far more outcomes than any query
    assert overhead_ns < 0.05 * wall_ns, (
        f"disabled timeline {overhead_ns / 1e6:.2f}ms vs query "
        f"{wall_ns / 1e6:.1f}ms: disabled-path overhead exceeds 5%")
    assert per_call_ns < 2_000, f"note_outcome {per_call_ns:.0f}ns"


# -- HTTP endpoints ------------------------------------------------------------


def test_debug_health_and_timeseries_endpoints():
    from blaze_tpu.runtime.http import ProfilingService

    def _get(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.read().decode()

    with Session(conf=Config(timeline_interval_s=0.05)) as sess:
        get_timeline().sample_once()
        svc = ProfilingService.start(sess)
        try:
            health = json.loads(_get(svc.port, "/debug/health"))
            assert health["enabled"] is True
            assert set(health["subsystems"]) == set(SUBSYSTEMS)
            listing = json.loads(_get(svc.port, "/debug/timeseries"))
            assert "serve_deadline_miss_ratio" in listing["series"]
            one = json.loads(_get(
                svc.port,
                "/debug/timeseries?name=serve_deadline_miss_ratio&since=0"))
            assert one["name"] == "serve_deadline_miss_ratio"
            assert one["samples"] and len(one["samples"][0]) == 2
            try:
                _get(svc.port, "/debug/timeseries?name=no_such_series")
                assert False, "expected 404"
            except urllib.error.HTTPError as exc:
                assert exc.code == 404
        finally:
            ProfilingService.stop()


# -- e2e: real 2-worker pool, lag rises then returns to zero -------------------


@pytest.mark.quick
def test_timeline_e2e_ingest_lag_round_trip():
    tl = get_timeline()
    with Session(conf=Config(timeline_interval_s=0.2),
                 num_worker_processes=2) as sess:
        tl.reset()
        sess.append("t", [_batch([0, 1, 0], [1, 2, 3])], num_partitions=2)
        plan = _agg_plan(sess.table_scan("t"))
        filled = sess.execute_cached(plan)
        tl.sample_once()
        assert tl.latest("ingest_lag_versions") == 0.0
        appends0 = get_registry().counter(
            "blaze_ingest_appends_total", "").total()
        sess.append("t", [_batch([1], [10])])
        tl.sample_once()
        assert tl.latest("ingest_lag_versions") >= 1.0
        assert tl.latest("ingest_lag_versions.t") >= 1.0
        refreshed = sess.execute_cached(plan)  # refresh folds the tail
        tl.sample_once()
        assert tl.latest("ingest_lag_versions") == 0.0
        vals = [v for _, v in tl.series_since("ingest_lag_versions", 0.0)]
        assert max(vals) >= 1.0 and vals[-1] == 0.0
        d = dict(zip(refreshed.to_pydict()["k"], refreshed.to_pydict()["s"]))
        assert d == {0: 4, 1: 12}
        assert get_registry().counter(
            "blaze_ingest_appends_total", "").total() - appends0 >= 1
        rep = tl.health_report()
        assert rep["samples"] >= 3
        assert rep["critical_intervals"] == 0
    assert _tl_threads() == []

"""Remote filesystem provider (VERDICT round-1 item 6): scan, sink, and
spill run against a non-posix filesystem (fsspec ``memory://``) — the
standalone analogue of hadoop_fs.rs routing all IO through Hadoop
FileSystem."""

import decimal

import numpy as np
import pyarrow as pa
import pyarrow.orc
import pyarrow.parquet as pq
import pytest

from blaze_tpu.config import config_override
from blaze_tpu.io import fs as FS
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.runtime.executor import build_operator
from blaze_tpu.runtime.session import Session
from tests.util import collect_pydict, mem_scan


@pytest.fixture
def memfs():
    import fsspec

    fs = fsspec.filesystem("memory")
    # each test starts with a clean store
    for p in list(fs.store):
        try:
            fs.rm(p)
        except Exception:
            pass
    return fs


def _write_remote_parquet(fs, path, tbl):
    with fs.open(path, "wb") as f:
        pq.write_table(tbl, f)


def test_parquet_scan_from_memory_fs(memfs):
    tbl = pa.table({
        "id": pa.array(range(5000), type=pa.int64()),
        "name": pa.array([f"n{i % 11}" for i in range(5000)]),
    })
    _write_remote_parquet(memfs, "/data/t.parquet", tbl)
    from blaze_tpu.ops.parquet import scan_node_for_files

    node = scan_node_for_files(["memory:///data/t.parquet"])
    out = collect_pydict(build_operator(node))
    assert out["id"] == list(range(5000))
    assert out["name"][:3] == ["n0", "n1", "n2"]


def test_parquet_sink_to_memory_fs(memfs):
    data = {
        "k": pa.array([1, 2, 1, 3], type=pa.int64()),
        "v": pa.array(["a", "b", "c", "d"]),
    }
    scan = mem_scan(data)
    from blaze_tpu.ops.parquet import ParquetSinkExec

    sink = ParquetSinkExec(scan, "memory:///out", num_dyn_parts=0)
    from blaze_tpu.ops.base import ExecContext

    list(sink.execute(0, ExecContext()))
    files = [p for p in memfs.ls("/out", detail=False)]
    assert files, "sink must write into the remote fs"
    with memfs.open(files[0], "rb") as f:
        back = pq.read_table(f)
    assert back.to_pydict() == {"k": [1, 2, 1, 3], "v": ["a", "b", "c", "d"]}


def test_orc_scan_from_memory_fs(memfs):
    tbl = pa.table({"x": pa.array(range(2000), type=pa.int64())})
    with memfs.open("/data/t.orc", "wb") as f:
        pyarrow.orc.write_table(tbl, f)
    from blaze_tpu.ops.orc import OrcScanExec

    schema = T.Schema.of(("x", T.I64))
    conf = N.FileScanConf(
        file_groups=[N.FileGroup(files=[
            N.PartitionedFile("memory:///data/t.orc", FS.getsize("memory:///data/t.orc"))])],
        file_schema=schema,
        projection=[0],
    )
    out = collect_pydict(OrcScanExec(conf))
    assert out["x"] == list(range(2000))


def test_spill_to_memory_fs(memfs):
    from blaze_tpu.runtime.memmgr import SpillFile
    from blaze_tpu.core.batch import ColumnarBatch

    with config_override(spill_dir="memory:///spills"):
        sp = SpillFile("t")
        b = ColumnarBatch.from_pydict({"a": pa.array([1, 2, 3], type=pa.int64())})
        sp.writer.write_batch(b)
        sp.finish_write()
        assert memfs.ls("/spills", detail=False), "spill object must exist remotely"
        got = [bb.to_pydict() for bb in sp.read_batches()]
        assert got == [{"a": [1, 2, 3]}]
        sp.release()
        assert not memfs.ls("/spills", detail=False)


def test_end_to_end_query_over_memory_fs(memfs):
    rng = np.random.default_rng(31)
    n = 10_000
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 30, n), type=pa.int64()),
        "amt": pa.array([decimal.Decimal(int(v)).scaleb(-2)
                         for v in rng.integers(0, 10000, n)],
                        type=pa.decimal128(9, 2)),
    })
    _write_remote_parquet(memfs, "/warehouse/t1.parquet", tbl.slice(0, n // 2))
    _write_remote_parquet(memfs, "/warehouse/t2.parquet", tbl.slice(n // 2))
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files(["memory:///warehouse/t1.parquet",
                                "memory:///warehouse/t2.parquet"],
                               num_partitions=2)
    partial = N.Agg(scan, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("amt")],
                              T.DecimalType(19, 2)), E.AggMode.PARTIAL, "s")])
    ex = N.ShuffleExchange(partial, N.SinglePartitioning(1))
    final = N.Agg(ex, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("amt")],
                              T.DecimalType(19, 2)), E.AggMode.FINAL, "s")])
    plan = N.Sort(final, [E.SortOrder(E.Column("k"))])
    with Session() as s:
        out = s.execute_to_table(plan).to_pydict()
    df = tbl.to_pandas().groupby("k").amt.sum()
    assert out["k"] == sorted(df.index.tolist())
    assert out["s"] == [df[k] for k in out["k"]]

"""String predicates on dictionary codes (round-2 verdict item 5,
exprs/compiler._dict_fast): EQ/IN/LIKE/StartsWith against literals run as a
K-entry host compute over the dictionary VALUES plus a device gather over
int32 codes — never a host scan over the rows. Covers: device-mask
engagement, null handling, flipped literal-vs-column compares, null list
items, and the non-dictionary fallback."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from blaze_tpu.core.batch import ColumnarBatch
from blaze_tpu.exprs.compiler import DevVal, ExprEvaluator, HostVal
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T
from blaze_tpu.runtime.session import Session


def _dict_batch(values):
    arr = pa.array(values).dictionary_encode()
    t = pa.table({"s": arr, "v": pa.array(range(len(values)),
                                          type=pa.int64())})
    return ColumnarBatch.from_arrow(t)


VALUES = ["apple", "banana", None, "apricot", "banana", "cherry", None,
          "apple"]


def _mask(ev, batch):
    """(data, validity) numpy bools from the single-expr evaluator."""
    ev._reset_cse(batch)
    out = ev._eval(ev.exprs[0], batch)
    assert isinstance(out, DevVal), "dictionary fast path must engage"
    n = batch.num_rows
    return (np.asarray(out.data)[:n], np.asarray(out.validity)[:n])


def test_eq_literal_on_codes():
    b = _dict_batch(VALUES)
    ev = ExprEvaluator([E.BinaryExpr(E.BinaryOp.EQ, E.Column("s"),
                                     E.Literal("banana", T.STRING))],
                       b.schema)
    data, valid = _mask(ev, b)
    assert data.tolist() == [False, True, False, False, True, False, False,
                             False]
    assert valid.tolist() == [True, True, False, True, True, True, False,
                              True]


def test_flipped_literal_lt_column():
    b = _dict_batch(VALUES)
    # 'banana' < s  ==  s > 'banana'
    ev = ExprEvaluator([E.BinaryExpr(E.BinaryOp.LT,
                                     E.Literal("banana", T.STRING),
                                     E.Column("s"))], b.schema)
    data, valid = _mask(ev, b)
    want = [v is not None and v > "banana" for v in VALUES]
    assert data.tolist() == want
    assert valid.tolist() == [v is not None for v in VALUES]


def test_in_list_on_codes_with_null_item():
    b = _dict_batch(VALUES)
    ev = ExprEvaluator([E.InList(E.Column("s"),
                                 [E.Literal("apple", T.STRING),
                                  E.Literal(None, T.STRING)], False)],
                       b.schema)
    data, valid = _mask(ev, b)
    # hits true; misses NULL (null list item); null rows NULL
    assert data.tolist() == [True, False, False, False, False, False, False,
                             True]
    assert valid.tolist() == [True, False, False, False, False, False, False,
                              True]


def test_starts_with_and_like_on_codes():
    b = _dict_batch(VALUES)
    ev = ExprEvaluator([E.StringStartsWith(E.Column("s"), "ap")], b.schema)
    data, valid = _mask(ev, b)
    assert data.tolist() == [True, False, False, True, False, False, False,
                             True]
    ev = ExprEvaluator([E.Like(E.Column("s"), "%an%")], b.schema)
    data, valid = _mask(ev, b)
    assert data.tolist() == [False, True, False, False, True, False, False,
                             False]
    assert valid.tolist() == [v is not None for v in VALUES]


def test_non_dictionary_fallback_stays_host():
    t = pa.table({"s": pa.array(VALUES)})
    b = ColumnarBatch.from_arrow(t)
    ev = ExprEvaluator([E.BinaryExpr(E.BinaryOp.EQ, E.Column("s"),
                                     E.Literal("banana", T.STRING))],
                       b.schema)
    ev._reset_cse(b)
    out = ev._eval(ev.exprs[0], b)
    assert isinstance(out, HostVal), "plain string arrays keep the host path"


def test_parquet_scan_string_filter_end_to_end(tmp_path):
    """The scan now emits dictionary-encoded strings, so a string filter
    over parquet runs on codes; results must match the pandas oracle."""
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ops.parquet import scan_node_for_files

    rng = np.random.default_rng(21)
    n = 20_000
    cats = ["Books", "Home", "Electronics", "Music", "Sports", None]
    s = [cats[i] for i in rng.integers(0, len(cats), n)]
    tbl = pa.table({"cat": pa.array(s, type=pa.string()),
                    "v": pa.array(rng.integers(0, 100, n), type=pa.int64())})
    path = str(tmp_path / "t.parquet")
    pq.write_table(tbl, path)
    scan = scan_node_for_files([path], num_partitions=2)
    filt = N.Filter(scan, [E.BinaryExpr(E.BinaryOp.EQ, E.Column("cat"),
                                        E.Literal("Music", T.STRING))])
    agg = N.Agg(filt, E.AggExecMode.HASH_AGG, [("cat", E.Column("cat"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("v")]),
                    E.AggMode.PARTIAL, "sv"),
        N.AggColumn(E.AggExpr(E.AggFunction.COUNT, []),
                    E.AggMode.PARTIAL, "c")])
    final = N.Agg(N.ShuffleExchange(agg, N.SinglePartitioning(1)),
                  E.AggExecMode.HASH_AGG, [("cat", E.Column("cat"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("v")]),
                    E.AggMode.FINAL, "sv"),
        N.AggColumn(E.AggExpr(E.AggFunction.COUNT, []),
                    E.AggMode.FINAL, "c")])
    with Session() as sess:
        got = sess.execute_to_table(final).to_pydict()
    df = tbl.to_pandas()
    m = df[df.cat == "Music"]
    assert got["cat"] == ["Music"]
    assert got["sv"] == [int(m.v.sum())]
    assert got["c"] == [len(m)]


def test_string_functions_still_work_on_dict_columns(tmp_path):
    """Host string kernels have no dictionary variants: _to_host must decode
    at the boundary so upper/substring/concat over a parquet string column
    keep working now that scans emit dictionary-encoded strings."""
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ops.parquet import scan_node_for_files

    tbl = pa.table({"s": pa.array(["a", "Bc", None, "def"]),
                    "v": pa.array([1, 2, 3, 4], type=pa.int64())})
    path = str(tmp_path / "t.parquet")
    pq.write_table(tbl, path)
    scan = scan_node_for_files([path])
    proj = N.Projection(scan,
                        [E.ScalarFunction("upper", [E.Column("s")], T.STRING),
                         E.ScalarFunction("length", [E.Column("s")], T.I32)],
                        ["u", "l"])
    with Session() as sess:
        got = sess.execute_to_table(proj).to_pydict()
    assert got["u"] == ["A", "BC", None, "DEF"]
    assert got["l"] == [1, 2, None, 3]


def test_host_decimal_divide_honors_declared_result_type():
    """Round-4 review: host decimal arithmetic must honor the PLAN's
    declared result type (Spark's exact promotion), not re-infer — a
    declared decimal(38,6) division must keep its 6-digit scale."""
    import decimal

    from blaze_tpu.exprs.compiler import ExprEvaluator

    t = pa.table({
        "x": pa.array([decimal.Decimal("1.00")], type=pa.decimal128(38, 2)),
        "y": pa.array([decimal.Decimal("3.00")], type=pa.decimal128(19, 2)),
    })
    b = ColumnarBatch.from_arrow(t)
    expr = E.BinaryExpr(E.BinaryOp.DIV, E.Column("x"), E.Column("y"),
                        result_type=T.DecimalType(38, 6))
    ev = ExprEvaluator([expr], b.schema)
    out = ev.evaluate(b)[0].to_arrow(1)
    assert out.type == pa.decimal128(38, 6)
    assert out[0].as_py() == decimal.Decimal("0.333333")

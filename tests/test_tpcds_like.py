"""TPC-DS-shaped end-to-end queries through the Session, validated against a
pandas oracle — the miniature analogue of the reference's TPC-DS sf=1
correctness gate (SURVEY.md §4.3), covering the BASELINE.md query shapes:
q01 (scan->filter->2-stage agg), q06/q07 (broadcast join + group), q17/q25
(multi-way join), q47/q67 (window rank over sorted partitions), plus
grouping-sets via Expand."""

import collections
from decimal import Decimal

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.ops.parquet import scan_node_for_files
from blaze_tpu.runtime.session import Session


def col(n):
    return E.Column(n)


def lit(v, t):
    return E.Literal(v, t)


F = E.AggFunction
M = E.AggMode
HASH = E.AggExecMode.HASH_AGG


@pytest.fixture(scope="module")
def warehouse(tmp_path_factory):
    """Tiny deterministic star schema on parquet."""
    d = tmp_path_factory.mktemp("tpcds")
    rng = np.random.default_rng(7)
    n_sales = 20_000
    n_items = 200
    n_customers = 500

    store_sales = pa.table({
        "ss_item_sk": pa.array(rng.integers(1, n_items + 1, n_sales), type=pa.int64()),
        "ss_customer_sk": pa.array(rng.integers(1, n_customers + 1, n_sales), type=pa.int64()),
        "ss_store_sk": pa.array(rng.integers(1, 10, n_sales), type=pa.int64()),
        "ss_sold_date_sk": pa.array(rng.integers(2450000, 2450100, n_sales), type=pa.int64()),
        "ss_quantity": pa.array(rng.integers(1, 100, n_sales), type=pa.int32()),
        "ss_sales_price": pa.array(
            [Decimal(int(v)).scaleb(-2) for v in rng.integers(50, 20000, n_sales)],
            type=pa.decimal128(7, 2)),
    })
    item = pa.table({
        "i_item_sk": pa.array(np.arange(1, n_items + 1), type=pa.int64()),
        "i_category": pa.array([f"Category{v % 8}" for v in range(n_items)]),
        "i_brand": pa.array([f"Brand{v % 25}" for v in range(n_items)]),
        "i_current_price": pa.array(
            [Decimal(int(v)).scaleb(-2) for v in rng.integers(100, 9999, n_items)],
            type=pa.decimal128(7, 2)),
    })
    customer = pa.table({
        "c_customer_sk": pa.array(np.arange(1, n_customers + 1), type=pa.int64()),
        "c_state": pa.array([f"S{v % 12}" for v in range(n_customers)]),
    })
    paths = {}
    for name, tbl in [("store_sales", store_sales), ("item", item),
                      ("customer", customer)]:
        p = str(d / f"{name}.parquet")
        pq.write_table(tbl, p, row_group_size=4096)
        paths[name] = p
    dfs = {"store_sales": store_sales.to_pandas(),
           "item": item.to_pandas(), "customer": customer.to_pandas()}
    return paths, dfs


def two_stage_agg(child, groupings, aggs, n_reducers=3):
    partial = N.Agg(child, HASH, groupings,
                    [N.AggColumn(E.AggExpr(a.fn, a.args, rt), M.PARTIAL, name)
                     for name, a, rt in aggs])
    ex = N.ShuffleExchange(partial, N.HashPartitioning(
        [e for _, e in groupings], n_reducers))
    final = N.Agg(ex, HASH, groupings,
                  [N.AggColumn(E.AggExpr(a.fn, a.args, rt), M.FINAL, name)
                   for name, a, rt in aggs])
    return final


def test_q01_shape(warehouse):
    """scan -> filter -> 2-stage agg -> topk (q01/BASELINE config 1)."""
    paths, dfs = warehouse
    scan = scan_node_for_files([paths["store_sales"]], num_partitions=2)
    filt = N.Filter(scan, [E.BinaryExpr(E.BinaryOp.GT, col("ss_sales_price"),
                                        lit("100.00", T.DecimalType(7, 2)))])
    agg = two_stage_agg(filt, [("ss_store_sk", col("ss_store_sk"))], [
        ("total", E.AggExpr(F.SUM, [col("ss_sales_price")]), T.DecimalType(17, 2)),
        ("cnt", E.AggExpr(F.COUNT, []), None),
    ])
    plan = N.Sort(N.ShuffleExchange(agg, N.SinglePartitioning(1)),
                  [E.SortOrder(col("total"), ascending=False)], fetch_limit=5)
    out = Session().execute_to_pydict(plan)

    df = dfs["store_sales"]
    df = df[df.ss_sales_price > Decimal("100.00")]
    exp = df.groupby("ss_store_sk").agg(
        total=("ss_sales_price", "sum"), cnt=("ss_store_sk", "size"))
    exp = exp.sort_values("total", ascending=False).head(5)
    assert out["ss_store_sk"] == exp.index.tolist()
    assert out["total"] == exp.total.tolist()
    assert out["cnt"] == exp.cnt.tolist()


def test_q06_q07_shape(warehouse):
    """broadcast join + group-by (BASELINE config 2)."""
    paths, dfs = warehouse
    sales = scan_node_for_files([paths["store_sales"]], num_partitions=2)
    items = scan_node_for_files([paths["item"]])
    join = N.BroadcastJoin(sales, N.BroadcastExchange(items),
                           [(col("ss_item_sk"), col("i_item_sk"))],
                           N.JoinType.INNER, N.JoinSide.RIGHT, "tpcds_items")
    agg = two_stage_agg(join, [("i_category", col("i_category"))], [
        ("qty", E.AggExpr(F.SUM, [col("ss_quantity")]), T.I64),
        ("avg_price", E.AggExpr(F.AVG, [col("ss_sales_price")]), T.DecimalType(11, 6)),
    ])
    plan = N.Sort(N.ShuffleExchange(agg, N.SinglePartitioning(1)),
                  [E.SortOrder(col("i_category"))])
    out = Session().execute_to_pydict(plan)

    m = dfs["store_sales"].merge(dfs["item"], left_on="ss_item_sk",
                                 right_on="i_item_sk")
    exp = m.groupby("i_category").agg(qty=("ss_quantity", "sum"),
                                      ap=("ss_sales_price", "mean")).sort_index()
    assert out["i_category"] == exp.index.tolist()
    assert out["qty"] == exp.qty.tolist()
    for got, want in zip(out["avg_price"], exp.ap.tolist()):
        assert abs(float(got) - float(want)) < 1e-4


def test_q17_q25_shape_multiway(warehouse):
    """star-schema multi-way join + exchange (BASELINE config 3)."""
    paths, dfs = warehouse
    sales = scan_node_for_files([paths["store_sales"]], num_partitions=2)
    items = scan_node_for_files([paths["item"]])
    customers = scan_node_for_files([paths["customer"]])
    j1 = N.BroadcastJoin(sales, N.BroadcastExchange(items),
                         [(col("ss_item_sk"), col("i_item_sk"))],
                         N.JoinType.INNER, N.JoinSide.RIGHT, "tpcds_items2")
    j2 = N.BroadcastJoin(j1, N.BroadcastExchange(customers),
                         [(col("ss_customer_sk"), col("c_customer_sk"))],
                         N.JoinType.INNER, N.JoinSide.RIGHT, "tpcds_cust")
    agg = two_stage_agg(j2, [("c_state", col("c_state")),
                             ("i_category", col("i_category"))], [
        ("n", E.AggExpr(F.COUNT, []), None),
    ])
    plan = N.Sort(N.ShuffleExchange(agg, N.SinglePartitioning(1)),
                  [E.SortOrder(col("c_state")), E.SortOrder(col("i_category"))])
    out = Session().execute_to_pydict(plan)

    m = dfs["store_sales"].merge(dfs["item"], left_on="ss_item_sk", right_on="i_item_sk")
    m = m.merge(dfs["customer"], left_on="ss_customer_sk", right_on="c_customer_sk")
    exp = m.groupby(["c_state", "i_category"]).size().sort_index()
    assert list(zip(out["c_state"], out["i_category"])) == exp.index.tolist()
    assert out["n"] == exp.tolist()


def test_q47_q67_shape_window(warehouse):
    """sort + window rank within category, keep top rows (BASELINE cfg 4)."""
    paths, dfs = warehouse
    sales = scan_node_for_files([paths["store_sales"]], num_partitions=2)
    items = scan_node_for_files([paths["item"]])
    join = N.BroadcastJoin(sales, N.BroadcastExchange(items),
                           [(col("ss_item_sk"), col("i_item_sk"))],
                           N.JoinType.INNER, N.JoinSide.RIGHT, "tpcds_items3")
    agg = two_stage_agg(join, [("i_category", col("i_category")),
                               ("i_brand", col("i_brand"))], [
        ("qty", E.AggExpr(F.SUM, [col("ss_quantity")]), T.I64),
    ])
    single = N.ShuffleExchange(agg, N.SinglePartitioning(1))
    srt = N.Sort(single, [E.SortOrder(col("i_category")),
                          E.SortOrder(col("qty"), ascending=False)])
    win = N.Window(srt, [N.WindowExpr("rank", "rk")],
                   [col("i_category")],
                   [E.SortOrder(col("qty"), ascending=False)])
    plan = N.Filter(win, [E.BinaryExpr(E.BinaryOp.LTEQ, col("rk"), lit(2, T.I32))])
    out = Session().execute_to_pydict(plan)

    m = dfs["store_sales"].merge(dfs["item"], left_on="ss_item_sk", right_on="i_item_sk")
    g = m.groupby(["i_category", "i_brand"]).ss_quantity.sum().reset_index()
    g["rk"] = g.groupby("i_category").ss_quantity.rank(method="min", ascending=False)
    exp = g[g.rk <= 2].sort_values(["i_category", "ss_quantity"],
                                   ascending=[True, False])
    got = sorted(zip(out["i_category"], out["i_brand"], out["qty"]))
    want = sorted(zip(exp.i_category, exp.i_brand, exp.ss_quantity))
    assert got == want


def test_grouping_sets_via_expand(warehouse):
    """rollup(category) via Expand + two-stage agg (q67-style rollup)."""
    paths, dfs = warehouse
    sales = scan_node_for_files([paths["store_sales"]], num_partitions=2)
    items = scan_node_for_files([paths["item"]])
    join = N.BroadcastJoin(sales, N.BroadcastExchange(items),
                           [(col("ss_item_sk"), col("i_item_sk"))],
                           N.JoinType.INNER, N.JoinSide.RIGHT, "tpcds_items4")
    # expand into (category) and (NULL) grouping sets
    expand_schema = T.Schema.of(("cat", T.STRING), ("gid", T.I32),
                                ("q", T.I32))
    expand = N.Expand(join, [
        [col("i_category"), lit(0, T.I32), col("ss_quantity")],
        [lit(None, T.STRING), lit(1, T.I32), col("ss_quantity")],
    ], expand_schema)
    agg = two_stage_agg(expand, [("cat", col("cat")), ("gid", col("gid"))], [
        ("qty", E.AggExpr(F.SUM, [col("q")]), T.I64),
    ])
    plan = N.Sort(N.ShuffleExchange(agg, N.SinglePartitioning(1)),
                  [E.SortOrder(col("gid")), E.SortOrder(col("cat"))])
    out = Session().execute_to_pydict(plan)

    m = dfs["store_sales"].merge(dfs["item"], left_on="ss_item_sk", right_on="i_item_sk")
    per_cat = m.groupby("i_category").ss_quantity.sum().sort_index()
    total = int(m.ss_quantity.sum())
    n_cat = len(per_cat)
    assert out["cat"][:n_cat] == per_cat.index.tolist()
    assert out["qty"][:n_cat] == per_cat.tolist()
    assert out["cat"][n_cat:] == [None]
    assert out["qty"][n_cat:] == [total]


def test_not_exists_shape_anti_join(warehouse):
    """customers with no store sales (NOT EXISTS -> left anti join)."""
    paths, dfs = warehouse
    customers = scan_node_for_files([paths["customer"]])
    sales = scan_node_for_files([paths["store_sales"]], num_partitions=2)
    # shuffle both sides by key, anti join per partition
    cust_ex = N.ShuffleExchange(customers, N.HashPartitioning(
        [col("c_customer_sk")], 3))
    sales_ex = N.ShuffleExchange(sales, N.HashPartitioning(
        [col("ss_customer_sk")], 3))
    anti = N.HashJoin(cust_ex, sales_ex,
                      [(col("c_customer_sk"), col("ss_customer_sk"))],
                      N.JoinType.LEFT_ANTI, N.JoinSide.RIGHT)
    plan = N.Sort(N.ShuffleExchange(anti, N.SinglePartitioning(1)),
                  [E.SortOrder(col("c_customer_sk"))])
    out = Session().execute_to_pydict(plan)
    buyers = set(dfs["store_sales"].ss_customer_sk.unique().tolist())
    exp = sorted(sk for sk in dfs["customer"].c_customer_sk.tolist()
                 if sk not in buyers)
    assert out["c_customer_sk"] == exp


def test_union_all_shape(warehouse):
    """UNION ALL of two filtered scans, aggregated (q-style set op)."""
    paths, dfs = warehouse
    low = N.Filter(scan_node_for_files([paths["store_sales"]]),
                   [E.BinaryExpr(E.BinaryOp.LT, col("ss_quantity"),
                                 lit(10, T.I32))])
    high = N.Filter(scan_node_for_files([paths["store_sales"]]),
                    [E.BinaryExpr(E.BinaryOp.GTEQ, col("ss_quantity"),
                                  lit(90, T.I32))])
    union = N.Union([low, high], num_partitions=2)
    agg = two_stage_agg(union, [("ss_store_sk", col("ss_store_sk"))], [
        ("n", E.AggExpr(F.COUNT, []), None),
    ])
    plan = N.Sort(N.ShuffleExchange(agg, N.SinglePartitioning(1)),
                  [E.SortOrder(col("ss_store_sk"))])
    out = Session().execute_to_pydict(plan)
    df = dfs["store_sales"]
    sub = df[(df.ss_quantity < 10) | (df.ss_quantity >= 90)]
    exp = sub.groupby("ss_store_sk").size().sort_index()
    assert out["ss_store_sk"] == exp.index.tolist()
    assert out["n"] == exp.tolist()


# -- round 2: null-heavy, skewed, CASE WHEN, and scale (VERDICT item 10) ------


@pytest.fixture(scope="module")
def dirty_warehouse(tmp_path_factory):
    """Null-heavy + skewed data: ~20% null keys/values, one store taking
    half of all rows (the AQE-skew shape), nullable strings."""
    d = tmp_path_factory.mktemp("tpcds_dirty")
    rng = np.random.default_rng(41)
    n = 30_000
    store = np.where(rng.random(n) < 0.5, 7,
                     rng.integers(1, 40, n))  # store 7 holds ~50% of rows
    store_null = rng.random(n) < 0.2
    qty = rng.integers(1, 50, n)
    qty_null = rng.random(n) < 0.2
    cat = [None if rng.random() < 0.15 else f"Cat{int(v) % 6}"
           for v in rng.integers(0, 1000, n)]
    sales = pa.table({
        "store": pa.array([None if m else int(v)
                           for v, m in zip(store, store_null)], type=pa.int64()),
        "qty": pa.array([None if m else int(v)
                         for v, m in zip(qty, qty_null)], type=pa.int64()),
        "cat": pa.array(cat, type=pa.string()),
        "price": pa.array([Decimal(int(v)).scaleb(-2)
                           for v in rng.integers(1, 10000, n)],
                          type=pa.decimal128(9, 2)),
    })
    stores = pa.table({
        "s_store_sk": pa.array(list(range(1, 40)) + [None], type=pa.int64()),
        "s_city": pa.array([f"city{i % 4}" for i in range(1, 40)] + [None]),
    })
    paths = {}
    for name, tbl in [("sales", sales), ("stores", stores)]:
        p = str(d / f"{name}.parquet")
        pq.write_table(tbl, p, row_group_size=4096)
        paths[name] = p
    return paths, {"sales": sales.to_pandas(), "stores": stores.to_pandas()}


def test_null_heavy_two_stage_agg(dirty_warehouse):
    """Null group keys form their own group; null agg args are skipped —
    across a real exchange with skewed + null keys."""
    paths, dfs = dirty_warehouse
    sales = scan_node_for_files([paths["sales"]], num_partitions=3)
    agg = two_stage_agg(sales, [("store", col("store"))], [
        ("s", E.AggExpr(F.SUM, [col("qty")]), T.I64),
        ("n", E.AggExpr(F.COUNT, [col("qty")]), None),
        ("mx", E.AggExpr(F.MAX, [col("price")]), T.DecimalType(9, 2)),
    ], n_reducers=4)
    plan = N.Sort(N.ShuffleExchange(agg, N.SinglePartitioning(1)),
                  [E.SortOrder(col("store"))])
    out = Session().execute_to_pydict(plan)

    df = dfs["sales"]
    exp = df.groupby("store", dropna=False).agg(
        s=("qty", "sum"), n=("qty", "count"), mx=("price", "max"))
    exp = exp.sort_index(na_position="first")
    # engine: nulls-first ordering
    assert out["store"] == [None if pd.isna(k) else int(k) for k in exp.index]
    got_s = [None if v is None else v for v in out["s"]]
    exp_s = [None if n == 0 else int(s) for s, n in zip(exp.s, exp.n)]
    assert got_s == exp_s
    assert out["n"] == exp.n.tolist()


def test_null_keys_never_join(dirty_warehouse):
    """Null join keys match nothing on either side (Spark equi-join), even
    with 20% null probe keys and a null build key."""
    paths, dfs = dirty_warehouse
    sales = scan_node_for_files([paths["sales"]], num_partitions=2)
    stores = scan_node_for_files([paths["stores"]])
    join = N.BroadcastJoin(sales, N.BroadcastExchange(stores),
                           [(col("store"), col("s_store_sk"))],
                           N.JoinType.LEFT, N.JoinSide.RIGHT, "dirty_stores")
    agg = two_stage_agg(join, [("s_city", col("s_city"))], [
        ("n", E.AggExpr(F.COUNT, []), None)])
    plan = N.Sort(N.ShuffleExchange(agg, N.SinglePartitioning(1)),
                  [E.SortOrder(col("s_city"))])
    out = Session().execute_to_pydict(plan)

    m = dfs["sales"].merge(dfs["stores"].dropna(subset=["s_store_sk"]),
                           left_on="store", right_on="s_store_sk", how="left")
    exp = m.groupby("s_city", dropna=False).size().sort_index(na_position="first")
    assert out["s_city"] == [None if pd.isna(k) else k for k in exp.index]
    assert out["n"] == exp.tolist()


def test_skewed_key_shuffle_balance(dirty_warehouse):
    """The 50%-skew key routes to exactly one reducer and still aggregates
    exactly (the engine-side invariant AQE skew splitting relies on)."""
    paths, dfs = dirty_warehouse
    sales = scan_node_for_files([paths["sales"]], num_partitions=3)
    partial = N.Agg(sales, HASH, [("store", col("store"))], [
        N.AggColumn(E.AggExpr(F.COUNT, []), M.PARTIAL, "n")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([col("store")], 5))
    final = N.Agg(ex, HASH, [("store", col("store"))], [
        N.AggColumn(E.AggExpr(F.COUNT, []), M.FINAL, "n")])
    plan = N.Sort(N.ShuffleExchange(final, N.SinglePartitioning(1)),
                  [E.SortOrder(col("n"), ascending=False)])
    out = Session().execute_to_pydict(plan)
    df = dfs["sales"]
    exp = df.groupby("store", dropna=False).size().sort_values(ascending=False)
    assert out["n"][0] == int(exp.iloc[0])  # the skewed store's exact count
    assert sum(out["n"]) == len(df)


def test_case_when_conditional_agg(warehouse):
    """q66-style conditional aggregation: SUM(CASE WHEN qty < 50 THEN price
    ELSE 0 END) per store."""
    paths, dfs = warehouse
    sales = scan_node_for_files([paths["store_sales"]], num_partitions=2)
    case = E.Case(
        [(E.BinaryExpr(E.BinaryOp.LT, col("ss_quantity"), lit(50, T.I32)),
          col("ss_sales_price"))],
        lit("0.00", T.DecimalType(7, 2)))
    proj = N.Projection(sales, [col("ss_store_sk"), case], ["store", "cond_price"])
    agg = two_stage_agg(proj, [("store", col("store"))], [
        ("s", E.AggExpr(F.SUM, [col("cond_price")], T.DecimalType(17, 2)), T.DecimalType(17, 2)),
    ])
    plan = N.Sort(N.ShuffleExchange(agg, N.SinglePartitioning(1)),
                  [E.SortOrder(col("store"))])
    out = Session().execute_to_pydict(plan)
    df = dfs["store_sales"].copy()
    df["cond"] = df.apply(
        lambda r: r.ss_sales_price if r.ss_quantity < 50 else Decimal("0.00"),
        axis=1)
    exp = df.groupby("ss_store_sk").cond.sum().sort_index()
    assert out["store"] == exp.index.tolist()
    assert out["s"] == exp.tolist()


@pytest.mark.slow
def test_q01_scale_200k(tmp_path):
    """Scale gate: the q01 pipeline at 200k rows x 4 partitions stays exact
    (the miniature stand-in for the sf>=0.1 oracle run)."""
    rng = np.random.default_rng(53)
    paths = []
    for p in range(4):
        n = 50_000
        tbl = pa.table({
            "store": pa.array(rng.integers(1, 400, n), type=pa.int64()),
            "amt": pa.array([Decimal(int(v)).scaleb(-2)
                             for v in rng.integers(0, 100000, n)],
                            type=pa.decimal128(9, 2)),
        })
        path = str(tmp_path / f"s{p}.parquet")
        pq.write_table(tbl, path)
        paths.append(path)
    sales = scan_node_for_files(paths, num_partitions=4)
    filt = N.Filter(sales, [E.BinaryExpr(E.BinaryOp.GT, col("amt"),
                                         lit("500.00", T.DecimalType(9, 2)))])
    agg = two_stage_agg(filt, [("store", col("store"))], [
        ("total", E.AggExpr(F.SUM, [col("amt")], T.DecimalType(17, 2)), T.DecimalType(17, 2)),
        ("cnt", E.AggExpr(F.COUNT, []), None),
    ], n_reducers=4)
    plan = N.Sort(N.ShuffleExchange(agg, N.SinglePartitioning(1)),
                  [E.SortOrder(col("total"), ascending=False)], fetch_limit=100)
    out = Session().execute_to_pydict(plan)
    df = pd.concat([pq.read_table(p).to_pandas() for p in paths])
    df = df[df.amt > Decimal("500.00")]
    g = df.groupby("store").agg(total=("amt", "sum"), cnt=("store", "size"))
    g = g.sort_values("total", ascending=False).head(100)
    assert out["store"] == g.index.tolist()
    assert out["total"] == g.total.tolist()
    assert out["cnt"] == g.cnt.tolist()

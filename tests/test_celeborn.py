"""Celeborn PushData wire framing (io/celeborn.py): golden byte-level
fixtures pinning the transport layout, round-trips, the merge heuristic of
CelebornPartitionWriter, and the framed path through the native RSS server
(round-2 verdict item 8; reference: CelebornPartitionWriter.scala:27-74 +
Celeborn's network protocol)."""

import struct

import pytest

from blaze_tpu.io import celeborn as cb


def test_push_data_golden_bytes():
    frame = cb.encode_push_data(7, "app1-3", "5-0", b"DATA")
    # layout: len(8) type(1) reqId(8) mode(1) key(4+6) puid(4+3) body(4)
    assert frame == (
        struct.pack(">q", 8 + 1 + 8 + 1 + 10 + 7 + 4)
        + b"\x0b"                                  # PUSH_DATA = 11
        + struct.pack(">q", 7)                     # requestId
        + b"\x00"                                  # MODE_PRIMARY
        + struct.pack(">i", 6) + b"app1-3"         # shuffleKey
        + struct.pack(">i", 3) + b"5-0"            # partitionUniqueId
        + b"DATA")
    assert len(frame) == struct.unpack(">q", frame[:8])[0]


def test_push_merged_data_golden_bytes():
    frame = cb.encode_push_merged_data(
        9, "a-0", ["1-0", "2-0"], [b"xx", b"yyy"])
    want = (
        b"\x0c"                                    # PUSH_MERGED_DATA = 12
        + struct.pack(">q", 9) + b"\x00"
        + struct.pack(">i", 3) + b"a-0"
        + struct.pack(">i", 2)                     # partition count
        + struct.pack(">i", 3) + b"1-0"
        + struct.pack(">i", 3) + b"2-0"
        + struct.pack(">i", 2)                     # offsets count
        + struct.pack(">i", 0) + struct.pack(">i", 2)
        + b"xxyyy")
    assert frame == struct.pack(">q", 8 + len(want)) + want


def test_round_trip_both_frames():
    f1 = cb.decode_frame(cb.encode_push_data(
        42, "myapp-12", "99-1", b"\x00\x01payload", mode=cb.MODE_REPLICA))
    assert isinstance(f1, cb.PushDataFrame)
    assert (f1.request_id, f1.mode) == (42, cb.MODE_REPLICA)
    assert cb.parse_shuffle_key(f1.shuffle_key) == ("myapp", 12)
    assert cb.parse_partition_unique_id(f1.partition_unique_id) == (99, 1)
    assert f1.body == b"\x00\x01payload"

    f2 = cb.decode_frame(cb.encode_push_merged_data(
        1, "a-0", ["3-0", "7-0", "3-1"], [b"", b"abc", b"defg"]))
    assert isinstance(f2, cb.PushMergedDataFrame)
    assert f2.bodies == [b"", b"abc", b"defg"]
    assert [cb.parse_partition_unique_id(p)[0]
            for p in f2.partition_unique_ids] == [3, 7, 3]


def test_decode_rejects_bad_frames():
    good = cb.encode_push_data(1, "a-0", "0-0", b"x")
    with pytest.raises(ValueError):
        cb.decode_frame(good[:-1])  # truncated
    bad_type = bytearray(good)
    bad_type[8] = 99
    with pytest.raises(ValueError):
        cb.decode_frame(bytes(bad_type))


def test_partition_writer_merges_small_pushes():
    frames = []
    w = cb.CelebornPartitionWriter(frames.append, "app", 5, map_id=2)
    w.write(0, b"a" * 10)      # small: buffered
    w.write(1, b"b" * 20)      # small: buffered
    w.write(2, b"c" * (64 * 1024))  # large: immediate PushData
    w.close(success=True)      # flush buffers the two small ones merged
    assert len(frames) == 2
    big = cb.decode_frame(frames[0])
    assert isinstance(big, cb.PushDataFrame)
    assert cb.parse_partition_unique_id(big.partition_unique_id)[0] == 2
    merged = cb.decode_frame(frames[1])
    assert isinstance(merged, cb.PushMergedDataFrame)
    assert merged.bodies == [b"a" * 10, b"b" * 20]
    assert w.get_partition_length_map() == {0: 10, 1: 20, 2: 64 * 1024}


def test_framed_push_through_rss_server():
    from blaze_tpu.runtime.rss import CelebornMapWriter, RssClient, RssServer

    server = RssServer()
    try:
        client = RssClient(server.sock_path, app="appX", shuffle_id=4)
        w = CelebornMapWriter(client, map_id=0)
        w.write(0, b"p0-block")
        w.write(1, b"small1")
        w.write(1, b"small2")
        w.flush()
        # a second attempt of the same map must be deduped at commit
        w2 = CelebornMapWriter(client, map_id=0)
        w2.write(0, b"dup-block")
        w2.flush()
        assert client.fetch(0) == [b"p0-block"]
        assert client.fetch(1) == [b"small1", b"small2"]
    finally:
        server.close()


def test_malformed_frame_gets_error_reply_not_dead_socket():
    from blaze_tpu.runtime.rss import RssClient, RssServer

    server = RssServer()
    try:
        client = RssClient(server.sock_path, app="a", shuffle_id=0)
        with pytest.raises(RuntimeError, match="bad frame"):
            client._call({"op": "push_framed", "payload": b"garbage",
                          "map_id": 0, "attempt": "x"})
        # the connection survives: a well-formed push on the same client
        w = __import__("blaze_tpu.runtime.rss",
                       fromlist=["CelebornMapWriter"]).CelebornMapWriter(
            client, map_id=0)
        w.write(0, b"ok-block")
        w.flush()
        assert client.fetch(0) == [b"ok-block"]
    finally:
        server.close()

"""Celeborn PushData wire framing (io/celeborn.py): golden byte-level
fixtures pinning the transport layout, round-trips, the merge heuristic of
CelebornPartitionWriter, and the framed path through the native RSS server
(round-2 verdict item 8; reference: CelebornPartitionWriter.scala:27-74 +
Celeborn's network protocol)."""

import struct

import pytest

from blaze_tpu.io import celeborn as cb


def test_push_data_golden_bytes():
    frame = cb.encode_push_data(7, "app1-3", "5-0", b"DATA")
    # layout: len(8) type(1) reqId(8) mode(1) key(4+6) puid(4+3) body(4)
    assert frame == (
        struct.pack(">q", 8 + 1 + 8 + 1 + 10 + 7 + 4)
        + b"\x0b"                                  # PUSH_DATA = 11
        + struct.pack(">q", 7)                     # requestId
        + b"\x00"                                  # MODE_PRIMARY
        + struct.pack(">i", 6) + b"app1-3"         # shuffleKey
        + struct.pack(">i", 3) + b"5-0"            # partitionUniqueId
        + b"DATA")
    assert len(frame) == struct.unpack(">q", frame[:8])[0]


def test_push_merged_data_golden_bytes():
    frame = cb.encode_push_merged_data(
        9, "a-0", ["1-0", "2-0"], [b"xx", b"yyy"])
    want = (
        b"\x0c"                                    # PUSH_MERGED_DATA = 12
        + struct.pack(">q", 9) + b"\x00"
        + struct.pack(">i", 3) + b"a-0"
        + struct.pack(">i", 2)                     # partition count
        + struct.pack(">i", 3) + b"1-0"
        + struct.pack(">i", 3) + b"2-0"
        + struct.pack(">i", 2)                     # offsets count
        + struct.pack(">i", 0) + struct.pack(">i", 2)
        + b"xxyyy")
    assert frame == struct.pack(">q", 8 + len(want)) + want


@pytest.mark.quick
def test_round_trip_both_frames():
    f1 = cb.decode_frame(cb.encode_push_data(
        42, "myapp-12", "99-1", b"\x00\x01payload", mode=cb.MODE_REPLICA))
    assert isinstance(f1, cb.PushDataFrame)
    assert (f1.request_id, f1.mode) == (42, cb.MODE_REPLICA)
    assert cb.parse_shuffle_key(f1.shuffle_key) == ("myapp", 12)
    assert cb.parse_partition_unique_id(f1.partition_unique_id) == (99, 1)
    assert f1.body == b"\x00\x01payload"

    f2 = cb.decode_frame(cb.encode_push_merged_data(
        1, "a-0", ["3-0", "7-0", "3-1"], [b"", b"abc", b"defg"]))
    assert isinstance(f2, cb.PushMergedDataFrame)
    assert f2.bodies == [b"", b"abc", b"defg"]
    assert [cb.parse_partition_unique_id(p)[0]
            for p in f2.partition_unique_ids] == [3, 7, 3]


def test_decode_rejects_bad_frames():
    good = cb.encode_push_data(1, "a-0", "0-0", b"x")
    with pytest.raises(ValueError):
        cb.decode_frame(good[:-1])  # truncated
    bad_type = bytearray(good)
    bad_type[8] = 99
    with pytest.raises(ValueError):
        cb.decode_frame(bytes(bad_type))


def test_partition_writer_merges_small_pushes():
    frames = []
    w = cb.CelebornPartitionWriter(frames.append, "app", 5, map_id=2)
    w.write(0, b"a" * 10)      # small: buffered
    w.write(1, b"b" * 20)      # small: buffered
    w.write(2, b"c" * (64 * 1024))  # large: immediate PushData
    w.close(success=True)      # flush buffers the two small ones merged
    assert len(frames) == 2
    big = cb.decode_frame(frames[0])
    assert isinstance(big, cb.PushDataFrame)
    assert cb.parse_partition_unique_id(big.partition_unique_id)[0] == 2
    merged = cb.decode_frame(frames[1])
    assert isinstance(merged, cb.PushMergedDataFrame)
    assert merged.bodies == [b"a" * 10, b"b" * 20]
    assert w.get_partition_length_map() == {0: 10, 1: 20, 2: 64 * 1024}


def test_framed_push_through_rss_server():
    from blaze_tpu.runtime.rss import (CelebornShuffleClient, RssClient,
                                       RssServer)

    server = RssServer()
    try:
        client = RssClient(server.sock_path, app="appX", shuffle_id=4)
        sc = CelebornShuffleClient(client, num_mappers=1, num_partitions=2)
        locs = sc.register()
        assert [p.id for p in locs] == [0, 1]
        w = sc.writer_for_map(0, attempt_id=0)
        w.write(0, b"p0-block")
        w.write(1, b"small1")
        w.write(1, b"small2")
        w.flush()
        # a second attempt of the same map must be deduped at commit
        w2 = sc.writer_for_map(0, attempt_id=1)
        w2.write(0, b"dup-block")
        w2.flush()
        assert client.fetch(0) == [b"p0-block"]
        assert client.fetch(1) == [b"small1", b"small2"]
    finally:
        server.close()


def test_malformed_frame_gets_error_reply_not_dead_socket():
    from blaze_tpu.runtime.rss import (CelebornShuffleClient, RssClient,
                                       RssServer)

    server = RssServer()
    try:
        client = RssClient(server.sock_path, app="a", shuffle_id=0)
        with pytest.raises(RuntimeError, match="bad frame"):
            client._call({"op": "push_framed", "payload": b"garbage",
                          "map_id": 0, "attempt": "x"})
        # the connection survives: a well-formed push on the same client
        sc = CelebornShuffleClient(client, num_mappers=1, num_partitions=1)
        sc.register()
        w = sc.writer_for_map(0)
        w.write(0, b"ok-block")
        w.flush()
        assert client.fetch(0) == [b"ok-block"]
    finally:
        server.close()


# --- control plane + read path (round-4 verdict item 6) --------------------


def test_register_shuffle_golden_bytes():
    """Full RpcRequest frame for registerShuffle: transport framing + the
    PbTransportMessage envelope + PbRegisterShuffle protobuf payload."""
    msg = cb.RegisterShuffle("app1", 3, num_mappers=2, num_partitions=4)
    frame = cb.encode_control_rpc(17, msg)
    payload = (b"\x0a\x04app1"      # field 1 (app_id): "app1"
               b"\x10\x03"          # field 2 (shuffle_id): 3
               b"\x18\x02"          # field 3 (num_mappers): 2
               b"\x20\x04")         # field 4 (num_partitions): 4
    tmsg = (b"\x08\x01"             # field 1: messageTypeValue = 1
            + b"\x12" + bytes([len(payload)]) + payload)
    want = (struct.pack(">q", 8 + 1 + 8 + len(tmsg))
            + bytes([cb.RPC_REQUEST]) + struct.pack(">q", 17) + tmsg)
    assert frame == want


def test_control_messages_roundtrip():
    for msg in (
        cb.RegisterShuffle("a", 1, 2, 3),
        cb.RegisterShuffleResponse(0, [
            cb.PartitionLocation(0, 0, "h1", 90, 91),
            cb.PartitionLocation(1, 2, "h2", 92, 93, cb.MODE_REPLICA)]),
        cb.MapperEnd("a", 1, 5, 2, 8),
        cb.MapperEndResponse(cb.STATUS_SUCCESS),
        cb.CommitFiles("a", 1, ["0-0", "1-0"], [0, 1, 0]),
        cb.CommitFilesResponse(0, ["0-0"]),
        cb.OpenStream("a-1", "7-0", 0, 100),
        cb.StreamHandler(42, 3),
        cb.UnregisterShuffle("a", 1),
    ):
        rid, back = cb.decode_control_rpc(cb.encode_control_rpc(9, msg))
        assert rid == 9 and back == msg
        rid2, back2 = cb.decode_control_rpc(
            cb.encode_control_response(10, msg))
        assert rid2 == 10 and back2 == msg


def test_chunk_fetch_roundtrip():
    req = cb.encode_chunk_fetch_request(cb.StreamChunkSlice(7, 2))
    f = cb.decode_chunk_frame(req)
    assert isinstance(f, cb.ChunkFetchRequestFrame)
    assert (f.slice.stream_id, f.slice.chunk_index) == (7, 2)
    ok = cb.encode_chunk_fetch_success(cb.StreamChunkSlice(7, 2), b"BLOCK")
    g = cb.decode_chunk_frame(ok)
    assert isinstance(g, cb.ChunkFetchSuccessFrame) and g.body == b"BLOCK"


def test_full_protocol_loop_register_push_commit_fetch():
    """register -> framed pushes -> mapperEnd -> commitFiles -> openStream
    -> chunk fetches: every control + data message is a wire frame."""
    from blaze_tpu.runtime.rss import (CelebornShuffleClient, RssClient,
                                       RssServer)

    server = RssServer()
    try:
        client = RssClient(server.sock_path, app="loop", shuffle_id=9)
        sc = CelebornShuffleClient(client, num_mappers=2, num_partitions=2)
        sc.register()
        for m in range(2):
            w = sc.writer_for_map(m)
            w.write(0, f"m{m}p0".encode())
            w.write(1, f"m{m}p1".encode())
            w.flush()
        committed = sc.commit_files()
        assert committed == ["0-0", "1-0"]
        assert sorted(sc.fetch(0)) == [b"m0p0", b"m1p0"]
        assert sorted(sc.fetch(1)) == [b"m0p1", b"m1p1"]
    finally:
        server.close()


def test_open_stream_before_commit_rejected():
    from blaze_tpu.runtime.rss import (CelebornShuffleClient, RssClient,
                                       RssServer)

    server = RssServer()
    try:
        client = RssClient(server.sock_path, app="early", shuffle_id=1)
        sc = CelebornShuffleClient(client, num_mappers=1, num_partitions=1)
        sc.register()
        w = sc.writer_for_map(0)
        w.write(0, b"x")
        w.flush()
        with pytest.raises(RuntimeError, match="commitFiles"):
            sc.fetch(0)
    finally:
        server.close()


def test_mapper_end_requires_registration():
    from blaze_tpu.runtime.rss import (CelebornMapWriter, RssClient,
                                       RssServer)

    server = RssServer()
    try:
        client = RssClient(server.sock_path, app="noreg", shuffle_id=2)
        w = CelebornMapWriter(client, map_id=0)
        w.write(0, b"x")
        with pytest.raises(RuntimeError, match="mapperEnd"):
            w.flush()
    finally:
        server.close()


def test_session_shuffle_over_celeborn_protocol(tmp_path):
    """A real plan's exchange rides the full protocol loop and matches the
    file-shuffle result byte for byte."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.config import Config
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime.rss import RssServer
    from blaze_tpu.runtime.session import Session

    rng = np.random.default_rng(5)
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 50, 5000), type=pa.int64()),
        "v": pa.array(rng.integers(0, 1000, 5000), type=pa.int64()),
    })
    path = str(tmp_path / "t.parquet")
    pq.write_table(tbl, path)
    scan = scan_node_for_files([path], num_partitions=2)
    partial = N.Agg(scan, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))],
                    [N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("v")]),
                                 E.AggMode.PARTIAL, "s")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([E.Column("k")], 3))
    final = N.Agg(ex, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))],
                  [N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("v")]),
                               E.AggMode.FINAL, "s")])
    plan = N.Sort(N.ShuffleExchange(final, N.SinglePartitioning(1)),
                  [E.SortOrder(E.Column("k"))])
    with Session() as s_file:
        want = s_file.execute_to_table(plan).to_pydict()
    server = RssServer()
    try:
        conf = Config(rss_protocol="celeborn")
        with Session(conf=conf, rss_sock_path=server.sock_path) as s:
            got = s.execute_to_table(plan).to_pydict()
        assert got == want
    finally:
        server.close()


def test_retry_without_explicit_attempt_is_deduped():
    """A retried map task constructs a FRESH writer with no attempt id;
    its pushes must not merge with the failed attempt's (the factory
    draws random attempt ids — regression: defaulting every writer to
    attempt 0 served both attempts' blocks)."""
    from blaze_tpu.runtime.rss import (CelebornShuffleClient, RssClient,
                                       RssServer)

    server = RssServer()
    try:
        client = RssClient(server.sock_path, app="retry", shuffle_id=3)
        sc = CelebornShuffleClient(client, num_mappers=1, num_partitions=1)
        sc.register()
        w1 = sc.writer_for_map(0)
        # LARGE payload: crosses the merge threshold so it goes on the
        # wire immediately (a small buffered write never reaches the
        # server and would mask the dedup check)
        w1.write(0, b"X" * (64 * 1024))     # pushed, then the task died
        w2 = sc.writer_for_map(0)           # retry, fresh writer
        w2.write(0, b"retry-block")
        w2.flush()
        sc.commit_files()
        assert sc.fetch(0) == [b"retry-block"]
    finally:
        server.close()

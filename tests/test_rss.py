"""Remote shuffle service stand-in (round-1 missing item 5): push-based
shuffle through a socket server, single- and multi-process, with
retry-safe attempt commits (reference: Celeborn/Uniffle integration,
SURVEY.md §2.6)."""

import decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.runtime.rss import RssClient, RssServer
from blaze_tpu.runtime.session import Session
from tests.test_cluster import _q01


@pytest.fixture(scope="module")
def rss_server():
    srv = RssServer()
    yield srv
    srv.close()


@pytest.fixture(scope="module")
def q01_files(tmp_path_factory):
    td = tmp_path_factory.mktemp("rssdata")
    rng = np.random.default_rng(29)
    paths = []
    for p in range(2):
        n = 6000
        tbl = pa.table({
            "store": pa.array(rng.integers(1, 40, n), type=pa.int64()),
            "amt": pa.array([decimal.Decimal(int(v)).scaleb(-2)
                             for v in rng.integers(0, 100000, n)],
                            type=pa.decimal128(9, 2)),
        })
        path = str(td / f"f{p}.parquet")
        pq.write_table(tbl, path)
        paths.append(path)
    return paths


def test_rss_shuffle_equals_file_shuffle(rss_server, q01_files):
    plan = _q01(q01_files)
    with Session() as s_file:
        expect = s_file.execute_to_table(plan).to_pydict()
    with Session(rss_sock_path=rss_server.sock_path) as s_rss:
        got = s_rss.execute_to_table(plan).to_pydict()
    assert got == expect
    assert len(got["store"]) > 0


@pytest.mark.quick
def test_duplicate_attempt_blocks_deduped(rss_server):
    """A retried map task's pushes are invisible: only the first committed
    attempt's blocks serve fetches."""
    c = RssClient(rss_server.sock_path, app="dedup-test", shuffle_id=1)
    w1 = c.writer_for_map(0)
    w1.write(0, b"attempt1-block")
    w1.flush()
    # retry of the same map pushes again with a new attempt id
    w2 = c.writer_for_map(0)
    w2.write(0, b"attempt2-block")
    w2.flush()
    assert c.fetch(0) == [b"attempt1-block"]


def test_uncommitted_attempt_invisible(rss_server):
    c = RssClient(rss_server.sock_path, app="uncommitted-test", shuffle_id=2)
    w = c.writer_for_map(3)
    w.write(1, b"half-written")
    # no flush: a map task that died mid-push leaves nothing visible
    assert c.fetch(1) == []


@pytest.mark.slow
def test_rss_shuffle_through_worker_processes(rss_server, q01_files):
    plan = _q01(q01_files)
    with Session() as s_file:
        expect = s_file.execute_to_table(plan).to_pydict()
    with Session(rss_sock_path=rss_server.sock_path,
                 num_worker_processes=2) as s:
        got = s.execute_to_table(plan).to_pydict()
    assert got == expect

"""Query stats plane (ISSUE 11): per-stage runtime statistics, per-operator
device-time attribution, and fingerprint-keyed query profiles.

Covers the acceptance surface: a QueryProfile with per-stage partition
sizes/rows, skew summaries, est-vs-actual cardinalities and per-operator
``device_time_fraction``; fingerprint stability across runs (and across
data directories — paths are normalized out); the capped/GC'd profile
store and its HTTP surface (``/debug/profiles[/<fp>]``, ``stage_stats``
lines in ``/debug/queries``); the union kernel timer's
``kernel_time_s <= wall`` invariant (the BENCH_r09 double-count fix); the
stats-disabled overhead guard; and the real 2-worker pool across shuffle
tiers (slow tier)."""

import json
import os
import time
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu.config import Config, config_override
from blaze_tpu.core import ColumnarBatch
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.obs.stats import (STATS_HUB, StatsPlane, list_profiles,
                                 load_profile, plan_fingerprint, save_profile,
                                 skew_summary, stage_summary_line)
from blaze_tpu.runtime.session import Session
from blaze_tpu.utils.device import DEVICE_STATS

F = E.AggFunction
M = E.AggMode
HASH = E.AggExecMode.HASH_AGG


def _col(n):
    return E.Column(n)


def _two_stage_plan(schema, nparts, reducers=3):
    scan = N.FFIReader(schema=schema, resource_id="src", num_partitions=nparts)
    partial = N.Agg(scan, HASH, [("k", _col("k"))],
                    [N.AggColumn(E.AggExpr(F.SUM, [_col("v")], T.I64),
                                 M.PARTIAL, "s")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([_col("k")], reducers))
    return N.Agg(ex, HASH, [("k", _col("k"))],
                 [N.AggColumn(E.AggExpr(F.SUM, [_col("v")], T.I64),
                              M.FINAL, "s")])


def _make_parts(seed=7, n=20_000, nparts=2, keys=300):
    rng = np.random.default_rng(seed)
    b = ColumnarBatch.from_pydict({
        "k": rng.integers(0, keys, n).tolist(),
        "v": rng.integers(0, 1000, n).tolist()})
    per = n // nparts
    return [[b.slice(i * per, per)] for i in range(nparts)]


def _run_profiled(tmp_path, parts, **conf_kw):
    """Run the two-stage agg in a fresh session with the profile store
    pointed at tmp; returns (pydict result, profile, session query record)."""
    store = str(tmp_path / "profiles")
    with config_override(profile_store_dir=store, **conf_kw):
        with Session() as sess:
            sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
            out = sess.execute_to_pydict(
                _two_stage_plan(parts[0][0].schema, len(parts)))
            profile = sess.profile()
            record = sess.query_log[-1]
    return out, profile, record


def _pq_plan(tmp_path, fname="t.parquet", rows=10_000, keys=7):
    """Parquet-backed two-stage agg (pool-shippable: no resource lambdas)."""
    import pyarrow.parquet as pq

    from blaze_tpu.ops.parquet import scan_node_for_files

    path = str(tmp_path / fname)
    pq.write_table(pa.table({"k": [i % keys for i in range(rows)],
                             "v": list(range(rows))}), path)
    scan = scan_node_for_files([path], num_partitions=2)
    partial = N.Agg(scan, HASH, [("k", _col("k"))],
                    [N.AggColumn(E.AggExpr(F.SUM, [_col("v")], T.I64),
                                 M.PARTIAL, "s")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([_col("k")], 3))
    return N.Agg(ex, HASH, [("k", _col("k"))],
                 [N.AggColumn(E.AggExpr(F.SUM, [_col("v")], T.I64),
                              M.FINAL, "s")])


# -- skew / hub units ----------------------------------------------------------


@pytest.mark.quick
def test_skew_summary_unit():
    rec = {"bucket_rows": [10, 0, 100, 12, 9], "bucket_groups": [5, 0, 2, 6, 4],
           "radix_passes": 3}
    s = skew_summary(rec)
    assert s["buckets"] == 5
    assert s["min_bucket_rows"] == 9
    assert s["max_bucket_rows"] == 100
    assert s["p50_bucket_rows"] in (10, 12)  # median of live buckets
    assert s["hot_bucket_ids"] == [2]  # 100 > 2x median; index into rows
    assert s["radix_passes"] == 3
    assert skew_summary(None) is None
    assert skew_summary({"bucket_rows": [0, 0]}) is None
    line = stage_summary_line({"stage": 0, "kind": "shuffle_map/shm",
                               "partitions": 4, "total_bytes": 2048,
                               "total_rows": 10, "partition_skew_ratio": 2.5,
                               "skew": s})
    assert "stage 0" in line and "max/med=2.5" in line and "radix[" in line


@pytest.mark.quick
def test_stats_hub_scoping_and_drain():
    key = ("test", 1)
    with STATS_HUB.scoped(key):
        STATS_HUB.note_radix([1, 2], [1, 1])
        STATS_HUB.note_radix([3, 4, 5], [1, 2, 3])
    rec = STATS_HUB.drain(key)
    assert rec["bucket_rows"] == [4, 6, 5]
    assert rec["radix_passes"] == 2
    assert STATS_HUB.drain(key) is None  # drained once
    # disabled: one attribute check, nothing recorded
    STATS_HUB.enabled = False
    try:
        with STATS_HUB.scoped(key):
            STATS_HUB.note_radix([9], [9])
        assert STATS_HUB.drain(key) is None
    finally:
        STATS_HUB.enabled = True


@pytest.mark.quick
def test_worker_radix_merges_into_stage(tmp_path):
    """The pool merge path: reply["stats"] folds into the stage record the
    next on_map_stage commits (same stage id)."""
    plane = StatsPlane(N.FFIReader(schema=ColumnarBatch.from_pydict(
        {"k": [1]}).schema, resource_id="x", num_partitions=1), Config())
    plane.merge_task_stats(0, {"bucket_rows": [10, 50], "bucket_groups": [1, 2],
                               "radix_passes": 1})
    plane.merge_task_stats(0, {"bucket_rows": [5, 5], "bucket_groups": [1, 1],
                               "radix_passes": 1})
    plane.on_map_stage(0, "shuffle_map/shm", 2, 3,
                       indexes=[("d0", [0, 10, 20, 60]),
                                ("d1", [0, 10, 20, 40])])
    rec = plane._stages[0]
    assert rec["skew"]["max_bucket_rows"] == 55
    assert rec["skew"]["radix_passes"] == 2
    assert rec["partition_bytes"] == [20, 20, 60]
    assert rec["partition_skew_ratio"] == 3.0


# -- fingerprints --------------------------------------------------------------


@pytest.mark.quick
def test_fingerprint_stable_and_path_normalized(tmp_path):
    import pyarrow.parquet as pq

    from blaze_tpu.ops.parquet import scan_node_for_files

    t = pa.table({"k": [1, 2], "v": [3, 4]})
    d1, d2 = tmp_path / "a", tmp_path / "b"
    d1.mkdir(), d2.mkdir()
    pq.write_table(t, str(d1 / "t.parquet"))
    pq.write_table(t, str(d2 / "t.parquet"))
    p1 = scan_node_for_files([str(d1 / "t.parquet")], num_partitions=1)
    p2 = scan_node_for_files([str(d2 / "t.parquet")], num_partitions=1)
    # same plan shape from different data directories -> same fingerprint
    assert plan_fingerprint(p1) == plan_fingerprint(p2)
    # built twice -> deterministic
    assert plan_fingerprint(p1) == plan_fingerprint(
        scan_node_for_files([str(d1 / "t.parquet")], num_partitions=1))
    # a different plan -> different fingerprint
    assert plan_fingerprint(N.Filter(p1, [E.BinaryExpr(
        E.BinaryOp.GT, _col("k"), E.Literal(1, T.I64))])) \
        != plan_fingerprint(p1)


@pytest.mark.quick
def test_fingerprint_stable_across_runs(tmp_path):
    parts = _make_parts()
    _, prof1, _ = _run_profiled(tmp_path, parts)
    _, prof2, _ = _run_profiled(tmp_path, parts)
    assert prof1["fingerprint"] == prof2["fingerprint"]


# -- the end-to-end profile ----------------------------------------------------


@pytest.mark.quick
def test_profile_process_tier_end_to_end(tmp_path):
    parts = _make_parts()
    out, profile, record = _run_profiled(tmp_path, parts)
    assert len(out["k"]) == 300
    assert profile is not None and record["stats"] is profile
    assert profile["state"] == "done"
    assert profile["rows"] == 300

    # one map stage with per-reducer partition sizes + row counts
    stages = [s for s in profile["stages"] if s["stage"] >= 0]
    assert stages and stages[0]["kind"].startswith("shuffle_map/")
    s0 = stages[0]
    assert s0["partitions"] == 3 and len(s0["partition_bytes"]) == 3
    assert s0["total_bytes"] == sum(s0["partition_bytes"])
    # map-OUTPUT rows: each of 2 maps partial-aggs to <=300 groups, so the
    # shuffle carries between 300 (disjoint) and 600 (full overlap) rows
    assert sum(s0["partition_rows"]) == s0["total_rows"]
    assert 300 <= s0["total_rows"] <= 600
    assert s0["partition_skew_ratio"] >= 1.0
    assert 0.0 <= s0["device_time_fraction"] <= 1.0

    # operators: est-vs-actual pairing (scan + both aggs have estimates,
    # exchange plumbing pairs to None), device fraction bounded
    ops = {o["op"]: o for o in profile["operators"]}
    assert ops["FFIReaderExec"]["actual_rows"] == 20_000
    agg_recs = [o for o in profile["operators"] if o["op"] == "AggExec"]
    assert len(agg_recs) == 2
    assert all(o["est_rows"] is not None for o in agg_recs)
    assert any(o["est_rows"] is None for o in profile["operators"])
    assert all(0.0 <= o["device_time_fraction"] <= 1.0
               for o in profile["operators"])
    assert 0.0 <= profile["device_time_fraction"] <= 1.0

    # residency tripwires: process tier elides all serde
    assert profile["residency"]["shuffle_bytes_serialized"] == 0
    assert profile["residency"]["serde_elided_batches"] > 0
    assert profile["recovery"] == []


@pytest.mark.quick
def test_session_profile_lookup_forms(tmp_path):
    parts = _make_parts(seed=11)
    store = str(tmp_path / "profiles")
    with config_override(profile_store_dir=store):
        with Session() as sess:
            sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
            plan = _two_stage_plan(parts[0][0].schema, len(parts))
            sess.execute_to_pydict(plan)
            prof = sess.profile()  # None -> last finished query
            assert prof is not None
            fp = prof["fingerprint"]
            assert sess.profile(fp)["fingerprint"] == fp  # by fingerprint
            assert sess.profile(plan)["fingerprint"] == fp  # by plan
            assert sess.profile(sess.query_log[-1]) is prof  # by record
        # store outlives the session: a NEW session reads it back
        with Session() as sess2:
            assert sess2.profile(fp)["fingerprint"] == fp
    assert os.path.exists(os.path.join(store, fp + ".json"))


@pytest.mark.quick
def test_explain_analyze_includes_stats(tmp_path):
    parts = _make_parts(seed=13)
    with config_override(profile_store_dir=str(tmp_path / "p")):
        with Session() as sess:
            sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
            text = sess.explain_analyze(
                _two_stage_plan(parts[0][0].schema, len(parts)))
    assert "stage 0" in text and "partitions=3" in text
    assert "Cardinality (estimated vs actual)" in text
    assert "part_rows[" in text  # writer per-reducer rows summarized


# -- the profile store ---------------------------------------------------------


@pytest.mark.quick
def test_profile_store_cap_and_gc(tmp_path):
    store = str(tmp_path / "profiles")
    conf = Config(profile_store_dir=store, profile_store_max=3)
    for i in range(5):
        save_profile({"fingerprint": f"fp{i:02d}", "wall_s": i}, conf)
        time.sleep(0.01)  # distinct mtimes for deterministic GC order
    names = sorted(os.listdir(store))
    assert len(names) == 3
    assert names == ["fp02.json", "fp03.json", "fp04.json"]  # newest kept
    # listing is newest-first
    listed = [p["fingerprint"] for p in list_profiles(conf)]
    assert listed == ["fp04", "fp03", "fp02"]
    assert load_profile("fp04", conf)["wall_s"] == 4
    assert load_profile("fp00", conf) is None  # GC'd
    assert load_profile("../../etc/passwd", conf) is None  # sanitized
    # disabled store: no writes, no raise
    assert save_profile({"fingerprint": "x"},
                        Config(profile_store_dir="", profile_store_max=3)) \
        is None


# -- HTTP surface --------------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=10) as r:
        return r.read().decode()


@pytest.mark.quick
def test_http_profiles_and_query_stage_stats(tmp_path):
    from blaze_tpu.runtime.http import ProfilingService

    parts = _make_parts(seed=17)
    store = str(tmp_path / "profiles")
    with config_override(profile_store_dir=store):
        with Session() as sess:
            sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
            sess.execute_to_pydict(
                _two_stage_plan(parts[0][0].schema, len(parts)))
            fp = sess.profile()["fingerprint"]
            svc = ProfilingService.start(sess)
            try:
                idx = json.loads(_get(svc.port, "/debug/profiles"))
                assert any(p["fingerprint"] == fp for p in idx)
                full = json.loads(_get(svc.port, f"/debug/profiles/{fp}"))
                assert full["fingerprint"] == fp and full["stages"]
                try:
                    _get(svc.port, "/debug/profiles/nope")
                    assert False, "unknown fingerprint must 404"
                except urllib.error.HTTPError as exc:
                    assert exc.code == 404
                queries = json.loads(_get(svc.port, "/debug/queries"))
                done = [q for q in queries if q.get("state") == "done"]
                assert done and any("stage 0" in line
                                    for line in done[-1]["stage_stats"])
                assert done[-1]["fingerprint"] == fp
            finally:
                ProfilingService.stop()


# -- kernel timer invariant (BENCH_r09 q01 fix) --------------------------------


@pytest.mark.quick
def test_kernel_time_union_not_exceeding_wall():
    """Nested and overlapping kernel spans must count wall time ONCE:
    kernel_time_s <= wall by construction (BENCH_r09 reported q01 kernel
    0.543s vs wall 0.336s from summing nested phase + dispatch timers)."""
    DEVICE_STATS.reset()
    t0 = time.perf_counter()
    # nested: the agg phase span wrapping two inner dispatch spans
    with DEVICE_STATS.kernel_span():
        with DEVICE_STATS.kernel_span():
            time.sleep(0.02)
        with DEVICE_STATS.kernel_span():
            time.sleep(0.02)
    wall = time.perf_counter() - t0
    snap = DEVICE_STATS.snapshot()
    assert snap["kernel_calls"] == 3
    assert 0.0 < snap["kernel_time_s"] <= wall
    # the old sum-of-durations would have booked ~2x the sleep time
    assert snap["kernel_time_s"] < 0.06


@pytest.mark.quick
def test_kernel_time_below_wall_on_real_query(tmp_path):
    parts = _make_parts(seed=19)
    DEVICE_STATS.reset()
    t0 = time.perf_counter()
    _run_profiled(tmp_path, parts)
    wall = time.perf_counter() - t0
    snap = DEVICE_STATS.snapshot()
    assert snap["kernel_calls"] > 0
    assert snap["kernel_time_s"] <= wall


# -- disabled-path overhead guard ----------------------------------------------


@pytest.mark.quick
def test_stats_disabled_overhead_under_5_percent(tmp_path):
    """Mirror of the telemetry guard: with stats_enabled=False no plane is
    built, and the per-note cost of the disabled hub (one attribute check)
    scaled by a generous event count stays under 5% of the query wall."""
    n = 500_000
    b = ColumnarBatch.from_pydict({"k": [i % 97 for i in range(n)],
                                   "v": list(range(n))})
    with Session(conf=Config(batch_size=65_536, stats_enabled=False)) as sess:
        assert not STATS_HUB.enabled
        sess.resources["src"] = lambda p: [b.to_arrow()]
        scan = N.FFIReader(schema=b.schema, resource_id="src",
                           num_partitions=1)
        plan = N.Agg(scan, HASH, [("k", _col("k"))],
                     [N.AggColumn(E.AggExpr(F.SUM, [_col("v")], T.I64),
                                  M.COMPLETE, "total")])
        t0 = time.perf_counter_ns()
        out = sess.execute_to_pydict(plan)
        wall_ns = time.perf_counter_ns() - t0
        assert len(out["k"]) == 97
        assert sess.profile() is None  # no plane, no profile
        events = sess.metrics.total("output_batches")

        ITER = 100_000
        t0 = time.perf_counter_ns()
        for _ in range(ITER):
            STATS_HUB.note_radix([1], [1])
        bench_ns = time.perf_counter_ns() - t0
    STATS_HUB.enabled = True
    per_note_ns = bench_ns / ITER
    overhead_ns = per_note_ns * 4 * max(events, 32)
    assert overhead_ns < 0.05 * wall_ns, (
        f"disabled stats {overhead_ns / 1e6:.2f}ms vs query "
        f"{wall_ns / 1e6:.1f}ms: disabled-path overhead exceeds 5%")
    assert per_note_ns < 2_000, f"disabled note {per_note_ns:.0f}ns"


# -- real 2-worker pool across tiers (slow) ------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("tier_conf,expect_kind", [
    ({}, "shuffle_map/shm"),  # pool forces shm
    ({"zero_copy_shuffle": False}, "shuffle_map/ipc"),
])
def test_pool_two_workers_stats(tmp_path, tier_conf, expect_kind):
    """StatsPlane over a real 2-worker pool: partition rows recorded from
    worker-side writers, stage kind labels the negotiated tier, and the
    profile reaches the store."""
    plan = _pq_plan(tmp_path)
    store = str(tmp_path / "profiles")
    with config_override(profile_store_dir=store, **tier_conf):
        with Session(num_worker_processes=2) as sess:
            out = sess.execute_to_pydict(plan)
            profile = sess.profile()
    assert len(out["k"]) == 7
    assert profile is not None
    stages = [s for s in profile["stages"] if s.get("kind", "").startswith(
        "shuffle_map/")]
    assert stages and stages[0]["kind"] == expect_kind
    assert stages[0]["total_rows"] == 7
    assert sum(stages[0]["partition_rows"]) == 7
    assert os.path.exists(os.path.join(
        store, profile["fingerprint"] + ".json"))


@pytest.mark.slow
def test_pool_worker_radix_rides_reply(tmp_path):
    """A radix-agg map stage run IN WORKER PROCESSES must still produce a
    driver-side skew summary: the histogram rides reply["stats"]."""
    import pyarrow.parquet as pq

    from blaze_tpu.ops.parquet import scan_node_for_files

    rng = np.random.default_rng(5)
    n = 200_000
    path = str(tmp_path / "hi.parquet")
    pq.write_table(pa.table({
        "a": pa.array(rng.integers(0, 2000, n), type=pa.int64()),
        "b": pa.array(rng.integers(0, 100, n), type=pa.int64()),
        "v": pa.array(rng.integers(0, 100, n), type=pa.int64())}), path)
    scan = scan_node_for_files([path], num_partitions=2)
    groupings = [("a", _col("a")), ("b", _col("b"))]
    partial = N.Agg(scan, HASH, groupings,
                    [N.AggColumn(E.AggExpr(F.SUM, [_col("v")], T.I64),
                                 M.PARTIAL, "s")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([_col("a")], 3))
    plan = N.Agg(ex, HASH, groupings,
                 [N.AggColumn(E.AggExpr(F.SUM, [_col("v")], T.I64),
                              M.FINAL, "s")])
    with config_override(radix_agg=True,
                         profile_store_dir=str(tmp_path / "p")):
        with Session(num_worker_processes=2) as sess:
            out = sess.execute_to_pydict(plan)
            profile = sess.profile()
    assert len(out["a"]) > 100_000
    assert profile is not None
    skews = [s["skew"] for s in profile["stages"] if s.get("skew")]
    assert skews, "worker radix histograms must reach the driver profile"
    assert skews[0]["buckets"] > 0 and skews[0]["max_bucket_rows"] > 0

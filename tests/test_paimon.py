"""Paimon table format: real metadata layout (snapshot JSON, Avro OCF
manifests, BinaryRow partitions) read end-to-end through the engine and the
LakeTableScanExec provider SPI (round-4 verdict item 8 — replaces the
own-format stand-in for the Paimon role; reference:
``thirdparty/auron-paimon``)."""

import io
import json
from decimal import Decimal

import pyarrow as pa
import pytest

from blaze_tpu.io import avro
from blaze_tpu.io.paimon import (MANIFEST_LIST_SCHEMA, MANIFEST_SCHEMA,
                                 PaimonTable, binary_row_decode,
                                 binary_row_encode)
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T
from blaze_tpu.runtime.session import Session


def test_binary_row_roundtrip():
    types = [T.I32, T.I64, T.STRING, T.STRING, T.BOOL, T.F64, T.DATE,
             T.DecimalType(10, 2)]
    vals = (7, -(1 << 40), "eu", "a-partition-value-longer-than-7-bytes",
            True, 2.5, 19723, Decimal("123.45"))
    enc = binary_row_encode(vals, types)
    assert binary_row_decode(enc, types) == vals
    # nulls set the per-field bit (offset by the 8 header bits)
    enc2 = binary_row_encode((None,) * len(types), types)
    assert binary_row_decode(enc2, types) == (None,) * len(types)
    # fixed section: null bits word + 8 bytes per field
    assert len(enc2) == 8 + 8 * len(types)


def test_binary_row_short_string_inline():
    enc = binary_row_encode(("short",), [T.STRING])
    # inlined: marker byte 0x80|len at slot end, no var section
    assert len(enc) == 16 and enc[15] == 0x80 | 5
    assert binary_row_decode(enc, [T.STRING]) == ("short",)


@pytest.fixture
def orders(tmp_path):
    t = PaimonTable(str(tmp_path / "orders"))
    tbl = pa.table({
        "id": pa.array([1, 2, 3, 4], type=pa.int64()),
        "amt": pa.array([10, 20, 30, 40], type=pa.int64()),
        "region": pa.array(["eu", "eu", "us", "us"]),
    })
    t.create(tbl, partition_by=["region"])
    return t


def _sorted_rows(out):
    return sorted(zip(out["id"], out["amt"], out["region"]))


def test_layout_is_real_paimon(orders, tmp_path):
    root = tmp_path / "orders"
    assert (root / "snapshot" / "LATEST").read_text() == "1"
    snap = json.loads((root / "snapshot" / "snapshot-1").read_text())
    assert snap["commitKind"] == "APPEND" and snap["schemaId"] == 0
    schema = json.loads((root / "schema" / "schema-0").read_text())
    assert schema["partitionKeys"] == ["region"]
    assert {f["name"]: f["type"] for f in schema["fields"]} == {
        "id": "BIGINT", "amt": "BIGINT", "region": "STRING"}
    # manifest list + manifest are genuine Avro OCF streams
    ml = (root / "manifest" / snap["deltaManifestList"]).read_bytes()
    metas = list(avro.read_ocf(io.BytesIO(ml)))
    assert metas[0]["_NUM_ADDED_FILES"] == 2
    mf = (root / "manifest" / metas[0]["_FILE_NAME"]).read_bytes()
    entries = list(avro.read_ocf(io.BytesIO(mf)))
    assert {binary_row_decode(e["_PARTITION"], [T.STRING])[0]
            for e in entries} == {"eu", "us"}
    # data files live under <k>=<v>/bucket-0/
    assert (root / "region=eu" / "bucket-0").is_dir()


def test_scan_through_engine(orders):
    with Session() as s:
        out = s.execute_to_pydict(orders.scan_node())
    assert _sorted_rows(out) == [
        (1, 10, "eu"), (2, 20, "eu"), (3, 30, "us"), (4, 40, "us")]


def test_append_and_time_travel(orders):
    orders.append(pa.table({
        "id": pa.array([5], type=pa.int64()),
        "amt": pa.array([50], type=pa.int64()),
        "region": pa.array(["eu"]),
    }))
    with Session() as s:
        now = s.execute_to_pydict(orders.scan_node())
        v1 = s.execute_to_pydict(orders.scan_node(version=1))
    assert len(now["id"]) == 5 and (5, 50, "eu") in _sorted_rows(now)
    assert len(v1["id"]) == 4
    snap2 = orders.snapshot()
    assert snap2["totalRecordCount"] == 5 and snap2["deltaRecordCount"] == 1


def test_partition_pruning(orders):
    pred = E.BinaryExpr(E.BinaryOp.EQ, E.Column("region"),
                        E.Literal("eu", T.STRING))
    plan = orders.scan_node(partition_predicate=pred)
    # only the eu files survive manifest pruning
    files = []

    def walk(n):
        if hasattr(n, "conf"):
            for g in n.conf.file_groups:
                files.extend(f.path for f in g.files)
        for c in n.children():
            walk(c)

    walk(plan)
    assert files and all("region=eu" in p for p in files)
    with Session() as s:
        out = s.execute_to_pydict(plan)
    assert _sorted_rows(out) == [(1, 10, "eu"), (2, 20, "eu")]


def test_provider_scans_paimon_layout(orders, tmp_path):
    """A LakeTableScanExec node over a Paimon-layout directory converts
    through the provider SPI into a pruned native scan."""
    from tests.test_frontend import attr

    node = {
        "class": "org.apache.spark.sql.execution.LakeTableScanExec",
        "num-children": 0,
        "location": str(tmp_path / "orders"),
        "output": [[attr("id", "long", 1)], [attr("amt", "long", 2)],
                   [attr("region", "string", 3)]],
        "partitionFilters": [],
        "dataFilters": [],
    }
    from blaze_tpu.frontend import SparkPlanConverter

    res = SparkPlanConverter().convert(json.dumps([node]))
    assert not [t for t in res.tags if "fallback" in t[1]], res.tags
    with Session() as s:
        out = s.execute_to_pydict(res.plan)
    assert sorted(zip(*out.values()))[0][0] == 1


def test_manifest_delete_entries(orders, tmp_path):
    """A DELETE manifest entry retires its file from the scan (Paimon
    compaction/delete semantics at the metadata level)."""
    root = tmp_path / "orders"
    snap = orders.snapshot()
    ml = (root / "manifest" / snap["deltaManifestList"]).read_bytes()
    metas = list(avro.read_ocf(io.BytesIO(ml)))
    mf = (root / "manifest" / metas[0]["_FILE_NAME"]).read_bytes()
    entries = list(avro.read_ocf(io.BytesIO(mf)))
    eu = [e for e in entries
          if binary_row_decode(e["_PARTITION"], [T.STRING]) == ("eu",)]
    delete = {**eu[0], "_KIND": 1}
    # write a follow-up manifest holding the DELETE, new list, new snapshot
    buf = io.BytesIO()
    avro.write_ocf(buf, MANIFEST_SCHEMA, [delete])
    (root / "manifest" / "manifest-del-0.avro").write_bytes(buf.getvalue())
    lbuf = io.BytesIO()
    avro.write_ocf(lbuf, MANIFEST_LIST_SCHEMA, [{
        "_VERSION": 2, "_FILE_NAME": "manifest-del-0.avro",
        "_FILE_SIZE": len(buf.getvalue()), "_NUM_ADDED_FILES": 0,
        "_NUM_DELETED_FILES": 1,
        "_PARTITION_STATS": {"_MIN_VALUES": b"", "_MAX_VALUES": b"",
                             "_NULL_COUNTS": []},
        "_SCHEMA_ID": 0}])
    (root / "manifest" / "manifest-list-del-1.avro").write_bytes(
        lbuf.getvalue())
    snap2 = dict(snap, id=2, baseManifestList=snap["baseManifestList"],
                 deltaManifestList="manifest-list-del-1.avro")
    # keep snapshot-1's delta visible via the base list: fold old delta in
    base = (root / "manifest" / snap["baseManifestList"]).read_bytes()
    base_metas = list(avro.read_ocf(io.BytesIO(base))) + metas
    bbuf = io.BytesIO()
    avro.write_ocf(bbuf, MANIFEST_LIST_SCHEMA, base_metas)
    (root / "manifest" / "manifest-list-base-2.avro").write_bytes(
        bbuf.getvalue())
    snap2["baseManifestList"] = "manifest-list-base-2.avro"
    (root / "snapshot" / "snapshot-2").write_text(json.dumps(snap2))
    (root / "snapshot" / "LATEST").write_text("2")
    with Session() as s:
        out = s.execute_to_pydict(orders.scan_node())
    rows = _sorted_rows(out)
    assert (3, 30, "us") in rows and (4, 40, "us") in rows
    assert len(rows) == 2 or all(r[2] != "eu" for r in rows)


def test_add_column_schema_evolution(orders):
    """Paimon-style evolution: new schema-<id> + snapshot; old files keep
    their schemaId and null-fill the added column on read."""
    orders.add_column("discount", T.I64)
    orders.append(pa.table({
        "id": pa.array([9], type=pa.int64()),
        "amt": pa.array([90], type=pa.int64()),
        "region": pa.array(["eu"]),
        "discount": pa.array([7], type=pa.int64()),
    }))
    snap = orders.snapshot()
    assert snap["schemaId"] == 1 and snap["id"] == 3
    with Session() as s:
        out = s.execute_to_pydict(orders.scan_node())
    rows = sorted(zip(out["id"], out["discount"]), key=lambda r: r[0])
    assert rows == [(1, None), (2, None), (3, None), (4, None), (9, 7)]

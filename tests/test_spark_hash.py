"""Golden tests for spark-exact hashing.

Golden vectors were generated with Spark's Murmur3Hash(...).eval() /
XxHash64(...).eval() (recorded in the reference's
datafusion-ext-commons/src/spark_hash.rs test suite, which asserts the same
values). A scalar pure-python re-implementation cross-checks the vectorized
paths on random data, including the >=32-byte xxhash64 stripe path.
"""

import numpy as np
import jax.numpy as jnp
import pyarrow as pa

from blaze_tpu.core import ColumnarBatch
from blaze_tpu.exprs import spark_hash as H


def u32(x):
    return np.uint32(x & 0xFFFFFFFF)


# --- scalar reference implementations (independent of the vectorized code) ---

def mmh3_scalar(data: bytes, seed: int) -> int:
    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & 0xFFFFFFFF

    def mix_k1(k1):
        k1 = (k1 * 0xCC9E2D51) & 0xFFFFFFFF
        k1 = rotl(k1, 15)
        return (k1 * 0x1B873593) & 0xFFFFFFFF

    def mix_h1(h1, k1):
        h1 ^= k1
        h1 = rotl(h1, 13)
        return (h1 * 5 + 0xE6546B64) & 0xFFFFFFFF

    h1 = seed & 0xFFFFFFFF
    n = len(data)
    aligned = n - n % 4
    for i in range(0, aligned, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        h1 = mix_h1(h1, mix_k1(k))
    for i in range(aligned, n):
        b = data[i] - 256 if data[i] >= 128 else data[i]  # signed byte
        h1 = mix_h1(h1, mix_k1(b & 0xFFFFFFFF))
    h1 ^= n
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & 0xFFFFFFFF
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & 0xFFFFFFFF
    h1 ^= h1 >> 16
    return h1


P1, P2, P3, P4, P5 = (
    0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9,
    0x85EBCA77C2B2AE63, 0x27D4EB2F165667C5,
)
M64 = (1 << 64) - 1


def xxh64_scalar(data: bytes, seed: int) -> int:
    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M64

    n = len(data)
    pos = 0
    if n >= 32:
        v1, v2, v3, v4 = (
            (seed + P1 + P2) & M64, (seed + P2) & M64, seed & M64, (seed - P1) & M64,
        )
        while pos + 32 <= n:
            for i, v in enumerate((v1, v2, v3, v4)):
                k = int.from_bytes(data[pos + 8 * i : pos + 8 * i + 8], "little")
                v = rotl((v + k * P2) & M64, 31) * P1 & M64
                if i == 0: v1 = v
                elif i == 1: v2 = v
                elif i == 2: v3 = v
                else: v4 = v
            pos += 32
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M64
        for v in (v1, v2, v3, v4):
            h ^= rotl((v * P2) & M64, 31) * P1 & M64
            h = (h * P1 + P4) & M64
    else:
        h = (seed + P5) & M64
    h = (h + n) & M64
    while pos + 8 <= n:
        k = int.from_bytes(data[pos : pos + 8], "little")
        k = rotl((k * P2) & M64, 31) * P1 & M64
        h = (rotl(h ^ k, 27) * P1 + P4) & M64
        pos += 8
    if pos + 4 <= n:
        k = int.from_bytes(data[pos : pos + 4], "little")
        h = (rotl(h ^ (k * P1) & M64, 23) * P2 + P3) & M64
        pos += 4
    while pos < n:
        h = (rotl(h ^ (data[pos] * P5) & M64, 11) * P1) & M64
        pos += 1
    h = ((h ^ (h >> 33)) * P2) & M64
    h = ((h ^ (h >> 29)) * P3) & M64
    return h ^ (h >> 32)


# --- golden vectors (Spark-generated) ----------------------------------------

def test_murmur3_i32_golden():
    vals = jnp.array([1, 2, 3, 4], dtype=jnp.int32)
    seeds = jnp.full(4, 42, dtype=jnp.uint32)
    out = np.asarray(H.murmur3_int32(vals, seeds)).view(np.int32)
    np.testing.assert_array_equal(out, [-559580957, 1765031574, -1823081949, -397064898])


def test_murmur3_i8_promotes_golden():
    vals = jnp.array([1, 0, -1, 127, -128], dtype=jnp.int8)
    seeds = jnp.full(5, 42, dtype=jnp.uint32)
    out = np.asarray(H.murmur3_int32(vals.astype(jnp.int32), seeds))
    expected = np.array([0xDEA578E3, 0x379FAE8F, 0xA0590E3D, 0x43B4D8ED, 0x422A1365],
                        dtype=np.uint32)
    np.testing.assert_array_equal(out, expected)


def test_murmur3_i64_golden():
    vals = jnp.array([1, 0, -1, 2**63 - 1, -(2**63)], dtype=jnp.int64)
    seeds = jnp.full(5, 42, dtype=jnp.uint32)
    out = np.asarray(H.murmur3_int64(vals, seeds))
    expected = np.array([0x99F0149D, 0x9C67B85D, 0xC8008529, 0xA05B5D7B, 0xCD1E64FB],
                        dtype=np.uint32)
    np.testing.assert_array_equal(out, expected)


def test_xxhash64_i64_golden():
    vals = jnp.array([1, 0, -1, 2**63 - 1, -(2**63)], dtype=jnp.int64)
    seeds = jnp.full(5, 42, dtype=jnp.uint64)
    out = np.asarray(H.xxhash64_int64(vals, seeds)).view(np.int64)
    np.testing.assert_array_equal(
        out,
        [-7001672635703045582, -5252525462095825812, 3858142552250413010,
         -3246596055638297850, -8619748838626508300],
    )


def _str_arrays(strings):
    enc = [s.encode("utf-8") for s in strings]
    offsets = np.zeros(len(enc) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in enc], out=offsets[1:])
    data = np.frombuffer(b"".join(enc), dtype=np.uint8)
    return offsets, data


def test_murmur3_strings_golden():
    offsets, data = _str_arrays(["hello", "bar", "", "😁", "天地"])
    seeds = np.full(5, 42, dtype=np.uint32)
    out = H.murmur3_bytes_np(offsets, data, seeds)
    expected = np.array([3286402344, 2486176763, 142593372, 885025535, 2395000894],
                        dtype=np.uint32)
    np.testing.assert_array_equal(out, expected)


def test_xxhash64_strings_golden():
    offsets, data = _str_arrays(["hello", "bar", "", "😁", "天地"])
    seeds = np.full(5, 42, dtype=np.uint64)
    out = H.xxhash64_bytes_np(offsets, data, seeds).view(np.int64)
    np.testing.assert_array_equal(
        out,
        [-4367754540140381902, -1798770879548125814, -7444071767201028348,
         -6337236088984028203, -235771157374669727],
    )


# --- cross-checks against scalar implementations ----------------------------

def test_murmur3_bytes_random_crosscheck():
    rng = np.random.default_rng(0)
    strings = ["".join(chr(rng.integers(32, 1000)) for _ in range(rng.integers(0, 40)))
               for _ in range(200)]
    offsets, data = _str_arrays(strings)
    seeds = rng.integers(0, 2**32, size=len(strings), dtype=np.uint32)
    out = H.murmur3_bytes_np(offsets, data, seeds)
    expected = np.array(
        [mmh3_scalar(s.encode(), int(seed)) for s, seed in zip(strings, seeds)],
        dtype=np.uint32,
    )
    np.testing.assert_array_equal(out, expected)


def test_xxhash64_bytes_random_crosscheck():
    rng = np.random.default_rng(1)
    # include >=32-byte strings to exercise the stripe path
    strings = ["".join(chr(rng.integers(32, 1000)) for _ in range(rng.integers(0, 100)))
               for _ in range(200)]
    offsets, data = _str_arrays(strings)
    seeds = rng.integers(0, 2**63, size=len(strings), dtype=np.uint64)
    out = H.xxhash64_bytes_np(offsets, data, seeds)
    expected = np.array(
        [xxh64_scalar(s.encode(), int(seed)) for s, seed in zip(strings, seeds)],
        dtype=np.uint64,
    )
    np.testing.assert_array_equal(out, expected)


def test_xxh64_known_vector():
    # XXH64 official: seed 0, empty input
    assert xxh64_scalar(b"", 0) == 0xEF46DB3751D8E999
    out = H.xxhash64_bytes_np(np.array([0, 0], dtype=np.int64)[0:2],
                              np.zeros(0, dtype=np.uint8),
                              np.zeros(1, dtype=np.uint64))
    assert out[0] == 0xEF46DB3751D8E999


def test_numpy_matches_jax_fixed_width():
    rng = np.random.default_rng(2)
    v32 = rng.integers(-(2**31), 2**31, size=100, dtype=np.int64).astype(np.int32)
    v64 = rng.integers(-(2**62), 2**62, size=100, dtype=np.int64)
    seeds = rng.integers(0, 2**32, size=100, dtype=np.uint32)
    np.testing.assert_array_equal(
        np.asarray(H.murmur3_int32(jnp.asarray(v32), jnp.asarray(seeds))),
        H.murmur3_int32_np(v32, seeds),
    )
    np.testing.assert_array_equal(
        np.asarray(H.murmur3_int64(jnp.asarray(v64), jnp.asarray(seeds))),
        H.murmur3_int64_np(v64, seeds),
    )
    seeds64 = seeds.astype(np.uint64)
    np.testing.assert_array_equal(
        np.asarray(H.xxhash64_int64(jnp.asarray(v64), jnp.asarray(seeds64))),
        H.xxhash64_int64_np(v64, seeds64),
    )
    np.testing.assert_array_equal(
        np.asarray(H.xxhash64_int32(jnp.asarray(v32), jnp.asarray(seeds64))),
        H.xxhash64_int32_np(v32, seeds64),
    )


# --- batch-level chaining ----------------------------------------------------

def test_hash_batch_multi_column_chaining():
    b = ColumnarBatch.from_pydict(
        {
            "i": pa.array([1, None, 3], type=pa.int64()),
            "s": pa.array(["hello", "x", None], type=pa.string()),
            "j": pa.array([7, 8, 9], type=pa.int32()),
        }
    )
    out = H.hash_batch(b.columns, b.num_rows, b.capacity, seed=42, algo="murmur3")

    def expected_row(i_val, s_val, j_val):
        h = 42
        if i_val is not None:
            h = mmh3_scalar(int(i_val).to_bytes(8, "little", signed=True), h)
        if s_val is not None:
            h = mmh3_scalar(s_val.encode(), h)
        if j_val is not None:
            h = mmh3_scalar(int(j_val).to_bytes(4, "little", signed=True), h)
        return np.uint32(h).astype(np.int32)

    expected = np.array(
        [expected_row(1, "hello", 7), expected_row(None, "x", 8), expected_row(3, None, 9)],
        dtype=np.int32,
    )
    np.testing.assert_array_equal(out, expected)


def test_hash_batch_xxhash64_chaining():
    b = ColumnarBatch.from_pydict(
        {"i": pa.array([5, 6], type=pa.int64()), "s": pa.array(["abc", None])}
    )
    out = H.hash_batch(b.columns, b.num_rows, b.capacity, seed=42, algo="xxhash64")

    def expected_row(i_val, s_val):
        h = 42
        if i_val is not None:
            h = xxh64_scalar(int(i_val).to_bytes(8, "little", signed=True), h)
        if s_val is not None:
            h = xxh64_scalar(s_val.encode(), h)
        return np.uint64(h).astype(np.int64)

    expected = np.array([expected_row(5, "abc"), expected_row(6, None)], dtype=np.int64)
    np.testing.assert_array_equal(out, expected)

"""Whole-stage fusion: pass rewrites, golden equality vs the unfused
engine on every bench shape, static + runtime fallbacks, jit-closure reuse
across queries, the escape hatch, and the fused-dispatch-count guard.

The contract under test: with ``fusion_enabled`` on, chains of
project/filter/rename/expand between exchanges execute as ONE jitted
dispatch per batch with results bit-identical to the eager operators; with
it off, the built operator tree is exactly the pre-fusion one."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.config import config_override
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.ir.fusion import fuse_plan
from blaze_tpu.ops.fused import FusedStageExec, clear_fused_cache
from blaze_tpu.runtime.metrics import tripwire_totals
from blaze_tpu.runtime.session import Session
from tests.util import collect_pydict, mem_scan, run_op


def col(n):
    return E.Column(n)


def lit(v, t):
    return E.Literal(v, t)


def _conf():
    from blaze_tpu.config import get_config

    return get_config()


@pytest.fixture(scope="module")
def table_path(tmp_path_factory):
    d = tmp_path_factory.mktemp("fusion")
    rng = np.random.default_rng(11)
    n = 6000
    p = str(d / "t.parquet")
    pq.write_table(pa.table({
        "a": pa.array(rng.integers(0, 100, n), type=pa.int64()),
        "b": pa.array(rng.standard_normal(n), type=pa.float64()),
        "c": pa.array(rng.integers(0, 10, n), type=pa.int64()),
        "d": pa.array(rng.integers(0, 1000, n), type=pa.int64()),
    }), p, row_group_size=1024)
    return p


def _chain_plan(path):
    """project -> filter -> project -> filter over a parquet scan: the
    canonical fusable chain."""
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files([path], num_partitions=2)
    return N.Projection(
        N.Filter(
            N.Projection(
                N.Filter(scan, [E.BinaryExpr(E.BinaryOp.GT, col("a"),
                                             lit(10, T.I64))]),
                [col("a"),
                 E.BinaryExpr(E.BinaryOp.MUL, col("b"), lit(2.0, T.F64)),
                 col("c")],
                ["a", "b2", "c"]),
            [E.BinaryExpr(E.BinaryOp.LT, col("c"), lit(7, T.I64))]),
        [E.BinaryExpr(E.BinaryOp.ADD, col("a"), col("c")), col("b2")],
        ["ac", "b2"])


def _op_names(op):
    names = [type(op).__name__]
    for c in op.children:
        names.extend(_op_names(c))
    return names


# -- the pass -----------------------------------------------------------------


def test_pass_rewrites_maximal_chain(table_path):
    plan = _chain_plan(table_path)
    fused = fuse_plan(plan, _conf())
    assert isinstance(fused, N.FusedStage)
    assert [type(o).__name__ for o in fused.ops] == \
        ["Filter", "Projection", "Filter", "Projection"]  # innermost-first
    assert not isinstance(fused.child, N.FusedStage)
    # idempotent: re-running over a fused tree is a no-op
    assert fuse_plan(fused, _conf()) is fused


def test_pass_skips_trivial_chain(table_path):
    # a lone column-reference projection saves no dispatches: stays unfused
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files([table_path])
    plan = N.Projection(scan, [col("a")], ["a"])
    assert fuse_plan(plan, _conf()) is plan


def test_pass_leaves_aggs_filter_alone(table_path):
    # a filter directly under Agg feeds the fused_filter_agg device kernel;
    # the chain must start BELOW it
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files([table_path])
    proj = N.Projection(
        scan,
        [col("a"),
         E.BinaryExpr(E.BinaryOp.MUL, col("d"), lit(3, T.I64)),
         E.BinaryExpr(E.BinaryOp.ADD, col("c"), lit(1, T.I64))],
        ["a", "d3", "c1"])
    filt = N.Filter(proj, [E.BinaryExpr(E.BinaryOp.GT, col("d3"),
                                        lit(100, T.I64))])
    agg = N.Agg(filt, E.AggExecMode.HASH_AGG, [("a", col("a"))],
                [N.AggColumn(E.AggExpr(E.AggFunction.SUM, [col("d3")], T.I64),
                             E.AggMode.PARTIAL, "s")])
    fused = fuse_plan(agg, _conf())
    assert isinstance(fused, N.Agg)
    assert isinstance(fused.child, N.Filter), \
        "agg's filter must stay a direct child (fused_filter_agg guard)"
    assert isinstance(fused.child.child, N.FusedStage)


def test_escape_hatch_restores_unfused_tree(table_path):
    from blaze_tpu.runtime.executor import build_operator

    plan = _chain_plan(table_path)
    with config_override(fusion_enabled=False):
        assert fuse_plan(plan, _conf()) is plan
        names = _op_names(build_operator(plan))
        assert "FusedStageExec" not in names
        assert names.count("ProjectExec") == 2
        assert names.count("FilterExec") == 2
    names_on = _op_names(build_operator(plan))
    assert "FusedStageExec" in names_on
    assert "ProjectExec" not in names_on


# -- golden equality ----------------------------------------------------------


def test_chain_golden_equality(table_path):
    plan = _chain_plan(table_path)
    with config_override(fusion_enabled=False):
        off = Session().execute_to_table(plan)
    sess = Session()
    on = sess.execute_to_table(plan)
    assert on.num_rows > 0
    assert on.equals(off)
    trips = tripwire_totals(sess.metrics)
    assert trips["fused_stages"] > 0
    assert trips["fused_fallback_batches"] == 0


def test_expand_rename_chain_golden(table_path):
    # expand (grouping-sets shape) + rename inside one fused stage
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files([table_path], num_partitions=2)
    schema = T.Schema.of(("a", T.I64), ("v", T.I64), ("tag", T.I64))
    plan = N.RenameColumns(
        N.Filter(
            N.Expand(
                N.Filter(scan, [E.BinaryExpr(E.BinaryOp.LT, col("c"),
                                             lit(8, T.I64))]),
                [[col("a"), col("d"), lit(0, T.I64)],
                 [col("a"),
                  E.BinaryExpr(E.BinaryOp.MUL, col("d"), lit(10, T.I64)),
                  lit(1, T.I64)]],
                schema),
            [E.BinaryExpr(E.BinaryOp.GT, col("v"), lit(50, T.I64))]),
        ["g_a", "g_v", "g_tag"])
    fused = fuse_plan(plan, _conf())
    assert isinstance(fused, N.FusedStage)
    with config_override(fusion_enabled=False):
        off = Session().execute_to_table(plan)
    on = Session().execute_to_table(plan)
    assert on.num_rows > 0
    assert on.equals(off)


@pytest.fixture(scope="module")
def bench_paths(tmp_path_factory):
    """The real bench shapes at reduced scale (same generators/seeds)."""
    import bench

    old = bench.ROWS
    bench.ROWS = 40_000
    try:
        yield bench.make_data(str(tmp_path_factory.mktemp("fusion_bench")))
    finally:
        bench.ROWS = old


@pytest.mark.parametrize("shape", ["q01", "q06", "q17", "q47", "q67"])
def test_bench_shape_golden_equality(bench_paths, shape):
    """Every BENCH shape must be bit-identical with fusion on vs off."""
    import bench

    plan_fn = {name: fn for name, fn, *_ in bench.SHAPES}[shape]
    with config_override(fusion_enabled=False):
        off = Session().execute_to_table(plan_fn(bench_paths))
    on = Session().execute_to_table(plan_fn(bench_paths))
    assert on.num_rows == off.num_rows
    assert on.equals(off), f"{shape}: fused result differs from unfused"


# -- fallbacks ----------------------------------------------------------------


def test_unfusable_expr_breaks_chain(table_path):
    # a PyUDF mid-chain must NOT be swallowed: the chain splits around it
    # and results still match the unfused engine
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files([table_path], num_partitions=2)
    udf = E.PyUDF(
        lambda a: pa.array([v * 2 for v in a.to_pylist()], type=pa.int64()),
        [col("a")], T.I64, "dbl")
    plan = N.Filter(
        N.Projection(
            N.Filter(scan, [E.BinaryExpr(E.BinaryOp.GT, col("a"),
                                         lit(20, T.I64))]),
            [udf, col("c")], ["a2", "c"]),
        [E.BinaryExpr(E.BinaryOp.LT, col("c"), lit(5, T.I64))])
    fused = fuse_plan(plan, _conf())

    def has_udf_in_fused(node):
        if isinstance(node, N.FusedStage):
            for op in node.ops:
                if isinstance(op, N.Projection) and any(
                        isinstance(e, E.PyUDF) for e in op.exprs):
                    return True
        return any(has_udf_in_fused(c) for c in node.children())

    assert not has_udf_in_fused(fused)
    with config_override(fusion_enabled=False):
        off = Session().execute_to_table(plan)
    on = Session().execute_to_table(plan)
    assert on.equals(off)


def test_runtime_fallback_on_host_columns():
    # device-typed column that arrives dictionary-encoded (HostColumn at
    # runtime): the static gate can't see it, the per-batch fallback must
    schema = T.Schema.of(("k", T.I64), ("v", T.I64))
    from blaze_tpu.core.batch import ColumnarBatch, HostColumn

    ref = ColumnarBatch.from_pydict({
        "k": pa.array([1, 2, 2, 3, 3, 3, 4, 4], type=pa.int64()),
        "v": pa.array([10, 20, 21, 30, 31, 32, 40, 41], type=pa.int64()),
    }, schema)
    # force the k plane host-resident (the shape a dictionary-encoded device
    # dtype lands in): the static gate saw a device schema, only the
    # operator's per-batch check can catch this
    batch = ColumnarBatch(schema, [
        HostColumn(T.I64, pa.array([1, 2, 2, 3, 3, 3, 4, 4],
                                   type=pa.int64())),
        ref.columns[1],
    ], ref.num_rows)
    scan = mem_scan([[batch]], schema=schema)

    leaf = N.BatchSource(schema, "unused", 1)  # schema carrier for the ops
    filt = N.Filter(leaf, [E.BinaryExpr(E.BinaryOp.GT, col("k"),
                                        lit(1, T.I64))])
    proj = N.Projection(filt, [E.BinaryExpr(E.BinaryOp.ADD, col("k"),
                                            col("v"))], ["kv"])
    node = N.FusedStage(child=leaf, ops=(filt, proj))
    op = FusedStageExec(scan, node)
    out = collect_pydict(op)
    assert out == {"kv": [22, 23, 33, 34, 35, 44, 45]}

    from blaze_tpu.ops.base import ExecContext

    ctx = ExecContext()
    list(op.execute(0, ctx))
    assert ctx.metrics.total("fused_fallback_batches") > 0


def test_jit_closure_reuse_across_queries(table_path):
    clear_fused_cache()
    plan = _chain_plan(table_path)
    s1 = Session()
    t1 = s1.execute_to_table(plan)
    trips1 = tripwire_totals(s1.metrics)
    assert trips1["jit_cache_misses"] >= 1  # first query compiles
    s2 = Session()
    t2 = s2.execute_to_table(plan)
    trips2 = tripwire_totals(s2.metrics)
    assert trips2["jit_cache_misses"] == 0, \
        "second query with the same plan fingerprint recompiled"
    assert trips2["jit_cache_hits"] >= 1
    assert t1.equals(t2)


# -- dispatch-count guard (quick tier) ----------------------------------------


@pytest.mark.quick
def test_fused_dispatch_count_guard(table_path):
    """A filter-heavy pipeline must cost <= 1/3 the counted kernel
    dispatches of the unfused engine (one fused dispatch per batch vs one
    compaction per filter per batch)."""
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.utils.device import DEVICE_STATS

    scan = scan_node_for_files([table_path], num_partitions=2)
    plan = N.Filter(
        N.Filter(
            N.Filter(
                N.Projection(
                    N.Filter(scan, [E.BinaryExpr(E.BinaryOp.GT, col("a"),
                                                 lit(5, T.I64))]),
                    [col("a"), col("c"), col("d")], ["a", "c", "d"]),
                [E.BinaryExpr(E.BinaryOp.LT, col("c"), lit(9, T.I64))]),
            [E.BinaryExpr(E.BinaryOp.LT, col("d"), lit(900, T.I64))]),
        [E.BinaryExpr(E.BinaryOp.GT, col("d"), lit(50, T.I64))])

    def run(fusion):
        with config_override(fusion_enabled=fusion):
            Session().execute_to_table(plan)  # warmup compiles
            DEVICE_STATS.reset()
            out = Session().execute_to_table(plan)
            return out, DEVICE_STATS.snapshot()["kernel_calls"]

    out_off, unfused_calls = run(False)
    out_on, fused_calls = run(True)
    assert out_on.equals(out_off)
    assert unfused_calls >= 4
    assert fused_calls <= unfused_calls / 3, \
        (fused_calls, unfused_calls)


# -- observability ------------------------------------------------------------


def test_explain_renders_fusion_boundary(table_path):
    plan = _chain_plan(table_path)
    sess = Session()
    text = sess.explain_analyze(plan)
    assert "FusedStageExec" in text
    assert "+ ProjectExec (fused)" in text
    assert "+ FilterExec (fused)" in text
    # absorbed ops carry no self-time of their own
    for line in text.splitlines():
        if "(fused)" in line:
            assert "elapsed_compute" not in line
    # the /debug/queries record embeds the same boundary, compactly
    from blaze_tpu.runtime.http import _query_record

    rec = _query_record(sess.query_log[-1])
    assert any("+ FilterExec (fused)" in ln for ln in rec["plan"])
    assert "shape" not in rec

"""Failpoint registry smoke (quick tier): spec grammar, every action's
behavior, deterministic triggers (including the per-worker slot salt), and
the static call-site lint. All in-process — the cross-process arming path
(conf -> worker) is exercised by tests/test_cluster_recovery.py and the
chaos soaks."""

import errno
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from blaze_tpu.runtime import failpoints
from blaze_tpu.runtime.failpoints import (ACTIONS, SITES, arm, arm_from,
                                          disarm, failpoint, fired,
                                          is_armed, parse_spec, unhang)


@pytest.fixture(autouse=True)
def _disarmed():
    disarm()
    yield
    disarm()


# -- spec grammar --------------------------------------------------------------


@pytest.mark.quick
def test_parse_spec_rejects_malformed_entries():
    for bad in ("nosuch.site=enospc",        # unknown site
                "shm.commit=frobnicate",     # unknown action
                "shm.commit",                # missing =action
                "shm.commit=enospc:everyX",  # bad every token
                "shm.commit=enospc:every0",  # every < 1
                "shm.commit=delay:pzzz"):    # bad probability token
        with pytest.raises(ValueError):
            parse_spec(bad)


@pytest.mark.quick
def test_parse_spec_tokens_and_multi_entry():
    rules = parse_spec(
        "shm.commit=enospc:every3:x2; frame.decode=corrupt:p0.25;"
        "worker.task=hang:600")
    assert set(rules) == {"shm.commit", "frame.decode", "worker.task"}
    assert rules["shm.commit"].every == 3
    assert rules["shm.commit"].max_fires == 2
    assert rules["frame.decode"].prob == 0.25
    assert rules["worker.task"].param == 600.0
    assert parse_spec("") == {}


# -- actions -------------------------------------------------------------------


@pytest.mark.quick
def test_enospc_and_ioerror_raise_typed_oserrors():
    arm("shm.commit=enospc; shuffle.fetch=ioerror")
    with pytest.raises(OSError) as ei:
        failpoint("shm.commit")
    assert ei.value.errno == errno.ENOSPC
    with pytest.raises(OSError) as ei:
        failpoint("shuffle.fetch")
    assert ei.value.errno == errno.EIO
    # unarmed sites pass payloads through untouched
    assert failpoint("map.commit", b"xyz") == b"xyz"


@pytest.mark.quick
def test_delay_returns_payload_and_hang_is_releasable():
    arm("device.put=delay:0.01")
    t0 = time.perf_counter()
    assert failpoint("device.put", "p") == "p"
    assert time.perf_counter() - t0 >= 0.01
    arm("worker.task=hang:600")
    done = threading.Event()

    def victim():
        failpoint("worker.task")
        done.set()

    threading.Thread(target=victim, daemon=True).start()
    time.sleep(0.2)
    assert not done.is_set()  # genuinely stuck
    unhang()
    assert done.wait(5.0)


@pytest.mark.quick
def test_corrupt_flips_bytes_and_files(tmp_path):
    arm("frame.decode=corrupt")
    before = b"\x00" * 64
    after = failpoint("frame.decode", before)
    assert after != before and len(after) == len(before)
    assert sum(a != b for a, b in zip(before, after)) == 1
    # path payload: one byte of the payload region flipped in place, and
    # the 24-byte footer region is never the target
    p = tmp_path / "block.bin"
    p.write_bytes(b"\x00" * 40 + b"F" * 24)
    arm("frame.decode=corrupt")
    assert failpoint("frame.decode", str(p)) == str(p)
    got = p.read_bytes()
    assert got[40:] == b"F" * 24 and got[:40] != b"\x00" * 40


# -- triggers ------------------------------------------------------------------


@pytest.mark.quick
def test_every_n_and_x_cap_fire_pattern():
    arm("map.commit=ioerror:every3:x2")
    pattern = []
    for _ in range(9):
        try:
            failpoint("map.commit")
            pattern.append(0)
        except OSError:
            pattern.append(1)
    # 3rd and 6th calls fire; the x2 cap silences the 9th
    assert pattern == [0, 0, 1, 0, 0, 1, 0, 0, 0]
    assert fired("map.commit") == 2
    assert fired() == {"map.commit": 2}


def _prob_pattern(seed, salt, n=200):
    os.environ["BLAZE_TPU_FAILPOINT_SALT"] = str(salt)
    try:
        arm("worker.task=delay:p0.05:0", seed=seed)
        pat = [bool(failpoints._ARMED["worker.task"].should_fire())
               for _ in range(n)]
    finally:
        os.environ.pop("BLAZE_TPU_FAILPOINT_SALT", None)
    return pat


@pytest.mark.quick
def test_probability_trigger_is_seeded_and_slot_salted():
    a = _prob_pattern(seed=42, salt=0)
    assert a == _prob_pattern(seed=42, salt=0)      # reproducible
    assert a != _prob_pattern(seed=43, salt=0)      # seed-keyed
    # slot salt decorrelates symmetric workers without losing determinism
    s1 = _prob_pattern(seed=42, salt=1)
    assert s1 != a and s1 != _prob_pattern(seed=42, salt=2)
    assert s1 == _prob_pattern(seed=42, salt=1)


@pytest.mark.quick
def test_arm_from_is_idempotent_and_respects_env_override():
    class C:
        failpoints = "shm.commit=enospc:every2"
        failpoint_seed = 9

    arm_from(C())
    with pytest.raises(OSError):
        for _ in range(2):
            failpoint("shm.commit")
    # re-arming with an UNCHANGED (spec, seed) must keep counters: the
    # worker calls arm_from on EVERY task conf, and every-N triggers count
    # per process lifetime, not per task
    assert failpoints._ARMED["shm.commit"].calls == 2
    arm_from(C())
    assert failpoints._ARMED["shm.commit"].calls == 2
    os.environ["BLAZE_TPU_FAILPOINTS"] = ""
    try:
        arm_from(C())  # env overrides conf: disarms
        assert not is_armed()
    finally:
        os.environ.pop("BLAZE_TPU_FAILPOINTS")


# -- static lint ---------------------------------------------------------------


@pytest.mark.quick
def test_check_failpoints_lint_passes():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_failpoints.py")],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.quick
def test_lint_catches_unknown_and_unused_sites(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import check_failpoints as lint
    finally:
        sys.path.pop(0)
    (tmp_path / "blaze_tpu").mkdir()
    (tmp_path / "scripts").mkdir()
    (tmp_path / "blaze_tpu" / "x.py").write_text(
        "failpoint('nosuch.site')\nfailpoints.failpoint('BadForm')\n")
    violations = lint.run_lint(str(tmp_path))
    assert any("'nosuch.site' not in" in v for v in violations)
    assert any("'BadForm'" in v and "snake.dotted" in v for v in violations)
    # every real SITES entry is unused in this fake tree
    for site in SITES:
        assert any(f"{site!r} has no failpoint() call site" in v
                   for v in violations)
    assert ACTIONS  # imported: the registry tuple is public API

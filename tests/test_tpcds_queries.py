"""The real-TPC-DS gate: genuine query texts, Spark-shaped physical plans
through the frontend, executed end to end, checked against pandas oracles
(round-2 verdict item 6 — replaces the hand-built shape suite as the
correctness gate; reference: the 99-query Spark-vs-native workflow in
``tpcds-reusable.yml``)."""

import decimal
import json

import pytest

from blaze_tpu.frontend.converter import SparkPlanConverter
from blaze_tpu.runtime.session import Session
from tests.tpcds import data as tpcds_data
from tests.tpcds.queries import QUERIES


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpcds_sf_tiny")
    tables = tpcds_data.generate(str(d))
    return tables, tpcds_data.load_dfs(tables)


def _norm(v):
    if isinstance(v, float):
        return round(v, 4)
    if isinstance(v, decimal.Decimal):
        return v
    return v


def _normrows(rows):
    return [tuple(_norm(v) for v in r) for r in rows]


def _sorted_if_tied(rows, flags):
    # queries whose ORDER BY does not fully determinize row order within
    # equal sort keys compare as sets of rows
    rows = _normrows(rows)
    return sorted(rows, key=repr) if "ties" in flags else rows


def _rows_equal(got, want, flags):
    if "approx" not in flags:
        return got == want
    # AVG queries: the engine divides decimals exactly (HALF_UP) while the
    # pandas oracle uses float means — compare numerics with tolerance
    if len(got) != len(want):
        return False
    for g, w in zip(got, want):
        if len(g) != len(w):
            return False
        for gv, wv in zip(g, w):
            if isinstance(gv, (float, decimal.Decimal)) and \
                    isinstance(wv, (float, decimal.Decimal)):
                if abs(float(gv) - float(wv)) > 0.02:
                    return False
            elif gv != wv:
                return False
    return True


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_tpcds_query(name, dataset):
    tables, dfs = dataset
    plan_json, oracle, extract, flags = QUERIES[name]()
    conv = SparkPlanConverter(tables=tables)
    result = conv.convert(json.dumps(plan_json))
    fallbacks = [t for t in result.tags if "fallback" in t[1]]
    assert not fallbacks, f"{name}: unconverted nodes {fallbacks}"
    with Session() as sess:
        out = sess.execute_to_table(result.plan)
    if extract is None:
        # positional: converted column names carry Spark exprId suffixes;
        # the oracle emits tuples in the same (groups..., aggs...) order
        d = out.to_pydict()
        rows = list(zip(*d.values())) if d else []
    else:
        rows = extract(out)
    got = _sorted_if_tied(rows, flags)
    want = _sorted_if_tied(oracle(dfs), flags)
    assert _rows_equal(got, want, flags), (
        f"{name}: {len(got)} rows vs oracle {len(want)};"
        f" first diff: {next(((g, w) for g, w in zip(got, want) if g != w), None)}")

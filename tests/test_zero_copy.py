"""Zero-copy data plane (ISSUE 10): tier negotiation, serde elision,
shared-memory segments, and mapped device hand-off.

Covers the acceptance surface end to end: bit-identical results across the
three tiers (including the real 2-worker pool over the five bench shapes),
torn/truncated shm segments recovering through lineage, readers outliving
unlinked segments (POSIX mapping semantics), the tier fallback when
/dev/shm is unusable, mid-write degradation past the mem budget, and the
quick-tier guard pinning ``shuffle_bytes_serialized == 0`` on a
single-process plan."""

import glob
import os

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu.config import Config, config_override
from blaze_tpu.core import ColumnarBatch
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.runtime.session import Session


def _col(n):
    return E.Column(n)


def _summed(sess, name: str) -> int:
    """Sum one metric across the session's whole metric tree."""
    total = 0

    def walk(node):
        nonlocal total
        total += node.get("values", {}).get(name, 0)
        for c in node.get("children", []):
            walk(c)

    walk(sess.metrics.to_dict())
    return total


def _two_stage_plan(batch_parts, reducers=4):
    """partial agg -> hash exchange -> final agg -> single-collect topk:
    exercises both the multi-reducer shuffle and the collect path."""
    schema = batch_parts[0][0].schema
    scan = N.FFIReader(schema=schema, resource_id="src",
                       num_partitions=len(batch_parts))
    partial = N.Agg(scan, E.AggExecMode.HASH_AGG, [("k", _col("k"))],
                    [N.AggColumn(E.AggExpr(E.AggFunction.SUM, [_col("v")],
                                           T.I64),
                                 E.AggMode.PARTIAL, "s")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([_col("k")], reducers))
    final = N.Agg(ex, E.AggExecMode.HASH_AGG, [("k", _col("k"))],
                  [N.AggColumn(E.AggExpr(E.AggFunction.SUM, [_col("v")],
                                         T.I64),
                               E.AggMode.FINAL, "s")])
    return N.Sort(N.ShuffleExchange(final, N.SinglePartitioning(1)),
                  [E.SortOrder(_col("k"))])


def _make_parts(seed=7, n=20_000, nparts=2):
    rng = np.random.default_rng(seed)
    b = ColumnarBatch.from_pydict({
        "k": rng.integers(0, 300, n).tolist(),
        "v": rng.integers(0, 1000, n).tolist()})
    per = n // nparts
    return [[b.slice(i * per, per)] for i in range(nparts)]


def _run(parts, **conf_kw):
    with config_override(**conf_kw):
        with Session() as sess:
            sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
            out = sess.execute_to_table(_two_stage_plan(parts))
            metrics = {m: _summed(sess, m) for m in (
                "shuffle_bytes_serialized", "serde_elided_batches",
                "shm_bytes_mapped")}
    return out, metrics


# -- tier negotiation ---------------------------------------------------------


def test_tier_negotiation():
    with Session() as sess:  # pool-less, auto
        assert sess._shuffle_tier() == "process"
        # a worker pool forces shm: references cannot cross processes
        sess.pool = object()
        assert sess._shuffle_tier() == "shm"
        sess.pool = None
    with Session(conf=Config(zero_copy_tier="shm")) as sess:
        assert sess._shuffle_tier() == "shm"
    with Session(conf=Config(zero_copy_tier="ipc")) as sess:
        assert sess._shuffle_tier() == "ipc"
    with Session(conf=Config(zero_copy_shuffle=False)) as sess:
        assert sess._shuffle_tier() == "ipc"
        assert sess.shuffle_root == sess.work_dir  # no shm root either


# -- bit-identity + tripwires -------------------------------------------------


@pytest.mark.quick
def test_single_process_plan_elides_all_serde():
    """The quick-tier guard: a single-process plan (auto -> process tier)
    serializes ZERO shuffle bytes; every exchanged batch is counted as a
    serde-elided reference instead."""
    parts = _make_parts()
    out, m = _run(parts)
    assert m["shuffle_bytes_serialized"] == 0
    assert m["serde_elided_batches"] > 0
    # and the result matches the classic serde path bit for bit
    ipc_out, ipc_m = _run(parts, zero_copy_shuffle=False)
    assert ipc_m["serde_elided_batches"] == 0
    assert ipc_m["shuffle_bytes_serialized"] > 0
    assert out.equals(ipc_out)


def test_shm_tier_maps_and_matches():
    parts = _make_parts(seed=8)
    shm_out, shm_m = _run(parts, zero_copy_tier="shm")
    ipc_out, _ = _run(parts, zero_copy_shuffle=False)
    assert shm_out.equals(ipc_out)
    assert shm_m["shm_bytes_mapped"] > 0


def test_mem_budget_degrades_to_files():
    """A process-tier map that outgrows zero_copy_mem_segment_max_bytes
    degrades mid-write to real (raw) shuffle files; results are unchanged
    and the reducer serves the degraded maps transparently."""
    parts = _make_parts(seed=9)
    small, _ = _run(parts, zero_copy_mem_segment_max_bytes=1024)
    ref, _ = _run(parts, zero_copy_shuffle=False)
    assert small.equals(ref)


def test_shm_root_lifecycle():
    """The session's shm root exists while it serves and is removed at
    close; per-query release drops the query's shuffle dirs under it."""
    parts = _make_parts(seed=10)
    with config_override(zero_copy_tier="shm"):
        sess = Session()
        root = sess.shuffle_root
        if root == sess.work_dir:
            pytest.skip("/dev/shm not usable in this environment")
        assert os.path.isdir(root)
        sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
        for _ in sess.execute(_two_stage_plan(parts),
                              release_on_finish=True):
            pass
        # released with the query: no shuffle dirs linger under the root
        assert glob.glob(os.path.join(root, "shuffle_*")) == []
        sess.close()
        assert not os.path.exists(root)


def test_shm_root_reclaimed_without_close():
    """tmpfs pages are RAM: a session dropped without close() (test code,
    crashed callers) must still give its /dev/shm root back via the GC
    finalizer."""
    import gc

    with config_override(zero_copy_tier="shm"):
        sess = Session()
        root = sess.shuffle_root
        if root == sess.work_dir:
            pytest.skip("/dev/shm not usable in this environment")
        assert os.path.isdir(root)
        del sess
        gc.collect()
        assert not os.path.exists(root)


# -- lineage recovery over shm segments ---------------------------------------


def _lower_and_files(sess, plan):
    from blaze_tpu.runtime.session import _QueryRun

    before = set(glob.glob(
        os.path.join(sess.shuffle_root, "shuffle_*", "map_*.data")))
    qrun = _QueryRun(0)
    sess._tls.qrun = qrun
    lowered = sess._lower(plan)
    sess._tls.qrun = None
    after = sorted(glob.glob(
        os.path.join(sess.shuffle_root, "shuffle_*", "map_*.data")))
    return lowered, [f for f in after if f not in before]


def test_torn_shm_segment_recovers_via_lineage():
    """Truncating a committed shm segment between the map stage and the
    reduce is detected by the footer check and recomputed from lineage —
    the PR 9 recovery semantics survive the raw mappable format."""
    parts = _make_parts(seed=11)
    with config_override(zero_copy_tier="shm"):
        with Session() as sess:
            sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
            oracle = sess.execute_to_table(_two_stage_plan(parts))

            lowered, files = _lower_and_files(sess, _two_stage_plan(parts))
            assert files, "shm tier must commit real segment files"
            victim = max(files, key=os.path.getsize)
            with open(victim, "r+b") as fh:
                fh.truncate(max(0, os.path.getsize(victim) - 9))
            got = sess.execute_to_table(lowered)
            assert got.equals(oracle)

            # deleted outright: same recovery
            lowered, files = _lower_and_files(sess, _two_stage_plan(parts))
            os.remove(max(files, key=os.path.getsize))
            assert sess.execute_to_table(lowered).equals(oracle)


def test_process_tier_marker_deletion_recovers():
    """The process tier keeps lineage file-shaped with footer-only marker
    files: chaos-deleting a marker recomputes and re-commits the registry
    segment through the ordinary recovery path."""
    parts = _make_parts(seed=12)
    with Session() as sess:  # default: process tier
        sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
        oracle = sess.execute_to_table(_two_stage_plan(parts))

        lowered, files = _lower_and_files(sess, _two_stage_plan(parts))
        assert files, "process tier must still publish marker files"
        from blaze_tpu.runtime.recovery import FOOTER_LEN

        assert any(os.path.getsize(f) == FOOTER_LEN for f in files), \
            "mem-committed maps publish footer-only markers"
        os.remove(files[0])
        assert sess.execute_to_table(lowered).equals(oracle)


def test_released_registry_entry_is_typed_missing():
    """A registry entry dropped while its marker survives (the
    released-too-early shape) fails the index-size check and surfaces as
    ShuffleOutputMissing -> recovery recomputes it."""
    parts = _make_parts(seed=13)
    with Session() as sess:
        sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
        lowered, _files = _lower_and_files(sess, _two_stage_plan(parts))
        assert len(sess.mem_segments) > 0
        sess.mem_segments.clear()  # simulate premature release
        out = sess.execute_to_table(lowered)  # recovers, no error
        with config_override(zero_copy_shuffle=False):
            with Session() as ref_sess:
                ref_sess.resources["src"] = \
                    lambda p: [x.to_arrow() for x in parts[p]]
                ref = ref_sess.execute_to_table(_two_stage_plan(parts))
        assert out.equals(ref)


# -- mapped segments & device hand-off ----------------------------------------


def test_reader_outlives_unlinked_segment(tmp_path):
    """POSIX mapping semantics end to end: decode batches from a mapped
    raw segment, unlink the file, and the batches stay intact — the
    mapping (and the pages) live until the last view dies. Mapped plane
    bytes are booked as DEVICE_STATS.mapped, not as host copies."""
    import io as _io

    from blaze_tpu.io.batch_serde import (BatchWriter, decode_frame,
                                          read_frames)
    from blaze_tpu.io.shm_segments import MappedSegmentStream, open_mapped
    from blaze_tpu.utils.device import DEVICE_STATS

    rng = np.random.default_rng(14)
    b = ColumnarBatch.from_pydict({
        "a": rng.integers(0, 10**9, 4096).tolist(),
        "s": [f"x{i}" for i in range(4096)]})
    buf = _io.BytesIO()
    bw = BatchWriter(buf, raw=True)
    bw.write_batch(b)
    path = str(tmp_path / "seg.data")
    with open(path, "wb") as f:
        f.write(buf.getvalue())

    before = DEVICE_STATS.snapshot()
    mf = open_mapped(path)
    stream = MappedSegmentStream(mf.view(0, os.path.getsize(path)))
    frames = list(read_frames(stream))
    assert frames
    batches = [decode_frame(*fr, mapped=True) for fr in frames]
    after = DEVICE_STATS.snapshot()
    assert after["mapped_bytes"] > before["mapped_bytes"]

    os.remove(path)  # unlink while mapped: reader keeps serving
    del mf, stream
    got = pa.Table.from_batches([x.to_arrow() for x in batches])
    assert got.equals(pa.Table.from_batches([b.to_arrow()]))


def test_tier_fallback_without_dev_shm():
    """When /dev/shm is unusable (here: an impossibly high free-space
    floor) segments fall back to the session work dir — mmap still works,
    results are unchanged, nothing lands in /dev/shm."""
    parts = _make_parts(seed=15)
    shm_before = set(glob.glob("/dev/shm/blaze_tpu_shm_*"))
    with config_override(zero_copy_tier="shm",
                         shm_min_free_bytes=1 << 62):
        with Session() as sess:
            assert sess.shuffle_root == sess.work_dir
            sess.resources["src"] = lambda p: [x.to_arrow() for x in parts[p]]
            out = sess.execute_to_table(_two_stage_plan(parts))
    ref, _ = _run(parts, zero_copy_shuffle=False)
    assert out.equals(ref)
    assert set(glob.glob("/dev/shm/blaze_tpu_shm_*")) == shm_before

    # explicit shm_dir wins over the probe
    with config_override(zero_copy_tier="shm", shm_dir="/dev/shm",
                         shm_min_free_bytes=1 << 62):
        with Session() as sess:
            assert sess.shuffle_root.startswith("/dev/shm/blaze_tpu_shm_")


# -- the five bench shapes on a real worker pool ------------------------------


@pytest.fixture(scope="module")
def bench_paths(tmp_path_factory):
    import bench

    bench.ROWS = 60_000
    bench.PARTS = 2
    td = str(tmp_path_factory.mktemp("zcbench"))
    return bench.make_data(td)


@pytest.mark.parametrize("shape", ["q01", "q06", "q17", "q47", "q67"])
def test_bench_shapes_bit_identical_on_pool(bench_paths, shape):
    """Each bench shape runs on a real 2-worker pool (shm tier: workers
    write raw mappable segments, the driver's reducers mmap them) and must
    be bit-identical to the classic-serde run of the same plan."""
    import bench

    plan_fn = {s[0]: s[1] for s in bench.SHAPES}[shape]
    with config_override(zero_copy_shuffle=False):
        with Session(num_worker_processes=2) as sess:
            ref = sess.execute_to_table(plan_fn(bench_paths))
    with Session(num_worker_processes=2) as sess:
        assert sess._shuffle_tier() == "shm"
        got = sess.execute_to_table(plan_fn(bench_paths))
        mapped = _summed(sess, "shm_bytes_mapped")
    assert got.equals(ref)
    assert mapped > 0, "pool shuffle reads must come from mapped segments"

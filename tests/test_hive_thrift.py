"""HMS Thrift transport (round-4 verdict weak #7): the real
TBinaryProtocol + framed wire behind the HiveMetastore client surface —
golden bytes, both-direction round trips, and the catalog/scan glue fed
through a live loopback socket."""

import struct

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.hive import HiveMetastore
from blaze_tpu.io import thriftwire as tw
from blaze_tpu.io.hive_thrift import (ThriftMetastoreClient,
                                      ThriftMetastoreServer, decode_frame,
                                      encode_call)


def test_get_table_call_golden_bytes():
    frame = encode_call("get_table", 7, [(1, tw.T_STRING, "default"),
                                         (2, tw.T_STRING, "orders")])
    body = (b"\x0b\x00\x01" + struct.pack(">i", 7) + b"default"
            + b"\x0b\x00\x02" + struct.pack(">i", 6) + b"orders"
            + b"\x00")
    msg = (struct.pack(">I", 0x80010000 | 1)          # strict CALL
           + struct.pack(">i", 9) + b"get_table"
           + struct.pack(">i", 7)                     # seqid
           + body)
    assert frame == struct.pack(">i", len(msg)) + msg


def test_message_roundtrip():
    frame = encode_call("get_partitions", 3,
                        [(1, tw.T_STRING, "db"), (2, tw.T_STRING, "t"),
                         (3, tw.T_I16, -1)])
    name, mt, seq, args = decode_frame(frame)
    assert (name, mt, seq) == ("get_partitions", tw.MSG_CALL, 3)
    assert args == {1: "db", 2: "t", 3: -1}


@pytest.fixture
def served_metastore(tmp_path):
    ms = HiveMetastore()
    loc = str(tmp_path / "warehouse" / "orders")
    ms.create_table("default", "orders", loc,
                    cols=[("id", "bigint"), ("amt", "decimal(7,2)")],
                    partition_keys=[("region", "string")])
    for region in ("eu", "us"):
        part_dir = f"{loc}/region={region}"
        import os

        os.makedirs(part_dir, exist_ok=True)
        import decimal

        pq.write_table(pa.table({
            "id": pa.array([1, 2] if region == "eu" else [3],
                           type=pa.int64()),
            "amt": pa.array([decimal.Decimal("1.50")] *
                            (2 if region == "eu" else 1),
                            type=pa.decimal128(7, 2)),
        }), f"{part_dir}/part-0.parquet")
        ms.add_partition("default", "orders", [region], part_dir)
    server = ThriftMetastoreServer(ms)
    yield server
    server.close()


def test_client_server_loop(served_metastore):
    c = ThriftMetastoreClient(sock_path=served_metastore.sock_path)
    assert c.get_all_tables("default") == ["orders"]
    t = c.get_table("default", "orders")
    assert t.name == "orders" and t.db == "default"
    assert t.sd.cols == [("id", "bigint"), ("amt", "decimal(7,2)")]
    assert t.partition_keys == [("region", "string")]
    assert [p.values for p in t.partitions] == [["eu"], ["us"]]
    assert all("region=" in p.sd.location for p in t.partitions)
    with pytest.raises(KeyError, match="NoSuchObject"):
        c.get_table("default", "missing")


def test_catalog_scan_through_wire(served_metastore):
    """End to end: metadata fetched OVER THE WIRE feeds the catalog and a
    partition-pruned engine scan."""
    from blaze_tpu.runtime.session import Session

    c = ThriftMetastoreClient(sock_path=served_metastore.sock_path)
    catalog = c.as_catalog("default")
    plan = catalog.scan_node("orders")
    with Session() as s:
        out = s.execute_to_pydict(plan)
    assert sorted(out["id"]) == [1, 2, 3]
    assert sorted(set(out["region"])) == ["eu", "us"]

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T
from blaze_tpu.ir.nodes import WindowExpr
from blaze_tpu.ops.generate import GenerateExec
from blaze_tpu.ops.sort import SortExec
from blaze_tpu.ops.window import WindowExec
from tests.util import collect_pydict, mem_scan


def col(n):
    return E.Column(n)


def sorted_scan(data, keys, num_batches=2):
    return SortExec(mem_scan(data, num_batches=num_batches),
                    [E.SortOrder(col(k)) for k in keys])


DATA = {
    "g": pa.array([1, 1, 1, 2, 2, 3], type=pa.int64()),
    "o": pa.array([10, 20, 20, 5, 6, 9], type=pa.int64()),
    "v": pa.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0], type=pa.float64()),
}


def test_row_number_rank_dense():
    scan = sorted_scan(DATA, ["g", "o"])
    op = WindowExec(scan, [
        WindowExpr("row_number", "rn"),
        WindowExpr("rank", "rk"),
        WindowExpr("dense_rank", "dr"),
    ], [col("g")], [E.SortOrder(col("o"))])
    out = collect_pydict(op)
    assert out["rn"] == [1, 2, 3, 1, 2, 1]
    assert out["rk"] == [1, 2, 2, 1, 2, 1]
    assert out["dr"] == [1, 2, 2, 1, 2, 1]


def test_window_running_sum_with_peers():
    scan = sorted_scan(DATA, ["g", "o"])
    op = WindowExec(scan, [
        WindowExpr("agg", "rsum", agg=E.AggExpr(E.AggFunction.SUM, [col("v")])),
    ], [col("g")], [E.SortOrder(col("o"))])
    out = collect_pydict(op)
    # peers (o=20,20) share the frame value
    assert out["rsum"] == [1.0, 6.0, 6.0, 4.0, 9.0, 6.0]


def test_window_group_limit():
    scan = sorted_scan(DATA, ["g", "o"])
    op = WindowExec(scan, [WindowExpr("row_number", "rn")],
                    [col("g")], [E.SortOrder(col("o"))], group_limit=2)
    out = collect_pydict(op)
    assert out["g"] == [1, 1, 2, 2, 3]
    assert out["rn"] == [1, 2, 1, 2, 1]


def test_window_partition_spans_batches():
    data = {"g": [1] * 10 + [2] * 6, "o": list(range(10)) + list(range(6))}
    scan = sorted_scan(data, ["g", "o"], num_batches=4)
    op = WindowExec(scan, [WindowExpr("row_number", "rn")],
                    [col("g")], [E.SortOrder(col("o"))])
    out = collect_pydict(op)
    assert out["rn"] == list(range(1, 11)) + list(range(1, 7))


def test_explode():
    schema = T.Schema.of(("id", T.I64), ("xs", T.ArrayType(T.I64)))
    data = {"id": [1, 2, 3], "xs": [[10, 20], [], [30]]}
    scan = mem_scan(data, schema)
    op = GenerateExec(scan, "explode", [col("xs")], [0],
                      T.Schema.of(("x", T.I64)))
    out = collect_pydict(op)
    assert out == {"id": [1, 1, 3], "x": [10, 20, 30]}
    # outer keeps empty rows
    op = GenerateExec(scan, "explode", [col("xs")], [0],
                      T.Schema.of(("x", T.I64)), outer=True)
    out = collect_pydict(op)
    assert out == {"id": [1, 1, 2, 3], "x": [10, 20, None, 30]}


def test_pos_explode():
    schema = T.Schema.of(("id", T.I64), ("xs", T.ArrayType(T.STRING)))
    data = {"id": [7], "xs": [["a", "b"]]}
    scan = mem_scan(data, schema)
    op = GenerateExec(scan, "pos_explode", [col("xs")], [0],
                      T.Schema.of(("pos", T.I32), ("x", T.STRING)))
    out = collect_pydict(op)
    assert out == {"id": [7, 7], "pos": [0, 1], "x": ["a", "b"]}


def test_json_tuple():
    data = {"id": [1, 2], "j": ['{"a": 1, "b": "x"}', "bad"]}
    scan = mem_scan(data)
    op = GenerateExec(scan, "json_tuple",
                      [col("j"), E.Literal("a", T.STRING), E.Literal("b", T.STRING)],
                      [0], T.Schema.of(("a", T.STRING), ("b", T.STRING)))
    out = collect_pydict(op)
    assert out == {"id": [1, 2], "a": ["1", None], "b": ["x", None]}


def test_udtf():
    def split_udtf(s):
        if s is None:
            return
        for part in s.split(","):
            yield (part, len(part))

    data = {"id": [1, 2], "s": ["a,bb", None]}
    scan = mem_scan(data)
    op = GenerateExec(scan, "udtf", [col("s")], [0],
                      T.Schema.of(("part", T.STRING), ("len", T.I32)),
                      outer=True, udtf=split_udtf)
    out = collect_pydict(op)
    assert out == {"id": [1, 1, 2], "part": ["a", "bb", None], "len": [1, 2, None]}


def test_window_agg_peers_span_batches():
    # regression: peer group crossing a batch boundary must share one frame
    # value; partition spanning batches must aggregate fully
    data = {"g": [1, 1, 1, 1], "o": [10, 20, 20, 20], "v": [1.0, 2.0, 3.0, 4.0]}
    scan = mem_scan(data, num_batches=2)  # split inside the o=20 peer group
    op = WindowExec(scan, [
        WindowExpr("agg", "rsum", agg=E.AggExpr(E.AggFunction.SUM, [col("v")])),
    ], [col("g")], [E.SortOrder(col("o"))])
    out = collect_pydict(op)
    assert out["rsum"] == [1.0, 10.0, 10.0, 10.0]


def test_window_whole_partition_agg_spans_batches():
    data = {"g": [1, 1, 1, 1, 2], "v": [1.0, 2.0, 3.0, 4.0, 9.0]}
    scan = mem_scan(data, num_batches=2)
    op = WindowExec(scan, [
        WindowExpr("agg", "tot", agg=E.AggExpr(E.AggFunction.SUM, [col("v")])),
        WindowExpr("agg", "mx", agg=E.AggExpr(E.AggFunction.MAX, [col("v")])),
    ], [col("g")], [])
    out = collect_pydict(op)
    assert out["tot"] == [10.0, 10.0, 10.0, 10.0, 9.0]
    assert out["mx"] == [4.0, 4.0, 4.0, 4.0, 9.0]


def test_window_running_min_with_nulls():
    data = {"g": [1, 1, 1], "o": [1, 2, 3],
            "v": pa.array([None, 5.0, 3.0], type=pa.float64())}
    scan = mem_scan(data)
    op = WindowExec(scan, [
        WindowExpr("agg", "rmin", agg=E.AggExpr(E.AggFunction.MIN, [col("v")])),
        WindowExpr("agg", "rcnt", agg=E.AggExpr(E.AggFunction.COUNT, [col("v")])),
    ], [col("g")], [E.SortOrder(col("o"))])
    out = collect_pydict(op)
    assert out["rmin"] == [None, 5.0, 3.0]
    assert out["rcnt"] == [0, 1, 2]


def test_explode_map():
    schema = T.Schema.of(("id", T.I64), ("m", T.MapType(T.STRING, T.I64)))
    data = {"id": [1, 2], "m": [[("a", 10), ("b", 20)], None]}
    scan = mem_scan(data, schema)
    op = GenerateExec(scan, "explode", [col("m")], [0],
                      T.Schema.of(("k", T.STRING), ("v", T.I64)), outer=True)
    out = collect_pydict(op)
    assert out == {"id": [1, 1, 2], "k": ["a", "b", None], "v": [10, 20, None]}


# -- explicit ROWS frames (round 2: reference SpecifiedWindowFrame) -----------


def test_rows_frame_sliding_sum():
    """SUM OVER (ROWS BETWEEN 2 PRECEDING AND CURRENT ROW)."""
    data = {"g": pa.array([1] * 6, type=pa.int64()),
            "o": pa.array(range(6), type=pa.int64()),
            "v": pa.array([1, 2, 3, 4, 5, 6], type=pa.int64())}
    scan = sorted_scan(data, ["g", "o"])
    from blaze_tpu.ir.nodes import WindowExpr
    from blaze_tpu.ops.window import WindowExec

    op = WindowExec(scan, [
        WindowExpr("agg", "s", agg=E.AggExpr(E.AggFunction.SUM, [col("v")]),
                   frame=("rows", -2, 0)),
    ], [col("g")], [E.SortOrder(col("o"))])
    out = collect_pydict(op)
    assert out["s"] == [1, 3, 6, 9, 12, 15]


def test_rows_frame_min_max_and_following():
    data = {"g": pa.array([1] * 5 + [2] * 3, type=pa.int64()),
            "o": pa.array(list(range(5)) + list(range(3)), type=pa.int64()),
            "v": pa.array([5, 1, 4, 2, 3, 9, 7, 8], type=pa.int64())}
    scan = sorted_scan(data, ["g", "o"])
    from blaze_tpu.ir.nodes import WindowExpr
    from blaze_tpu.ops.window import WindowExec

    op = WindowExec(scan, [
        WindowExpr("agg", "mn", agg=E.AggExpr(E.AggFunction.MIN, [col("v")]),
                   frame=("rows", -1, 1)),
        WindowExpr("agg", "mx", agg=E.AggExpr(E.AggFunction.MAX, [col("v")]),
                   frame=("rows", 0, None)),  # current .. unbounded following
    ], [col("g")], [E.SortOrder(col("o"))])
    out = collect_pydict(op)
    assert out["mn"] == [1, 1, 1, 2, 2, 7, 7, 7]
    assert out["mx"] == [5, 4, 4, 3, 3, 9, 8, 8]


def test_rows_frame_proto_round_trip():
    from blaze_tpu.ir.nodes import WindowExpr
    from blaze_tpu.ir.protoserde import plan_from_bytes, plan_to_bytes
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T

    w = N.Window(
        N.EmptyPartitions(T.Schema.of(("g", T.I64), ("v", T.I64)), 1),
        [WindowExpr("agg", "s", agg=E.AggExpr(E.AggFunction.SUM, [col("v")]),
                    frame=("rows", -3, None))],
        [col("g")], [])
    back = plan_from_bytes(plan_to_bytes(w))
    assert back.window_exprs[0].frame == ("rows", -3, None)


def test_frontend_rows_frame_converts():
    """The converter now accepts RowFrame specs (was a fallback)."""
    import json

    import numpy as np
    import pyarrow.parquet as pq
    import tempfile, os

    from tests.test_frontend import P, X, attr, sort_order
    from blaze_tpu.frontend import convert_spark_plan
    from blaze_tpu.runtime.session import Session

    td = tempfile.mkdtemp()
    path = os.path.join(td, "t.parquet")
    pq.write_table(pa.table({"k": pa.array([1, 1, 1, 1], type=pa.int64()),
                             "v": pa.array([10, 20, 30, 40], type=pa.int64())}),
                   path)
    scan = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
            "output": [[attr("k", "long", 1)], [attr("v", "long", 2)]],
            "partitionFilters": [], "dataFilters": [],
            "tableIdentifier": "t"}
    srt = {"class": f"{P}.SortExec", "num-children": 1,
           "sortOrder": [sort_order([attr("v", "long", 2)])],
           "global": False, "child": 0}
    wexpr = [{"class": f"{X}.Alias", "num-children": 1, "child": 0, "name": "s",
              "exprId": {"product-class": f"{X}.ExprId", "id": 20,
                         "jvmId": "00000000-0000-0000-0000-000000000000"},
              "qualifier": []},
             {"class": f"{X}.WindowExpression", "num-children": 2,
              "windowFunction": 0, "windowSpec": 1},
             {"class": f"{X}.aggregate.AggregateExpression", "num-children": 1,
              "aggregateFunction": 0,
              "mode": {"object": f"{X}.aggregate.Complete$"},
              "isDistinct": False,
              "resultId": {"product-class": f"{X}.ExprId", "id": 21,
                           "jvmId": "00000000-0000-0000-0000-000000000000"}},
             {"class": f"{X}.aggregate.Sum", "num-children": 1, "child": 0},
             attr("v", "long", 2),
             {"class": f"{X}.WindowSpecDefinition", "num-children": 0,
              "partitionSpec": [], "orderSpec": [],
              "frameSpecification": {
                  "class": f"{X}.SpecifiedWindowFrame",
                  "frameType": {"object": f"{X}.RowFrame$"},
                  "lower": {"class": f"{X}.Literal", "value": "-1",
                            "dataType": "integer"},
                  "upper": {"object": f"{X}.CurrentRow$"}}}]
    window = {"class": f"{P}.window.WindowExec", "num-children": 1,
              "windowExpression": [wexpr],
              "partitionSpec": [[attr("k", "long", 1)]],
              "orderSpec": [sort_order([attr("v", "long", 2)])],
              "child": 0}
    res = convert_spark_plan(json.dumps([window, srt, scan]),
                             tables={"t": [path]})
    assert res.fully_native, res.tags
    with Session() as s:
        out = s.execute_to_table(res.plan).to_pydict()
    assert out["s#20"] == [10, 30, 50, 70]  # sliding 2-row sums


def test_range_frame_value_windows():
    """RANGE BETWEEN 2 PRECEDING AND 1 FOLLOWING over a numeric order key:
    bounds are VALUE offsets resolved against the sorted key (peers
    included), unlike ROWS index offsets."""
    data = {"g": pa.array([1] * 6, type=pa.int64()),
            "o": pa.array([1, 2, 2, 5, 6, 10], type=pa.int64()),
            "v": pa.array([1, 10, 100, 1000, 10000, 100000], type=pa.int64())}
    scan = sorted_scan(data, ["g", "o"])
    from blaze_tpu.ir.nodes import WindowExpr
    from blaze_tpu.ops.window import WindowExec

    op = WindowExec(scan, [
        WindowExpr("agg", "s", agg=E.AggExpr(E.AggFunction.SUM, [col("v")]),
                   frame=("range", -2, 1)),
    ], [col("g")], [E.SortOrder(col("o"))])
    out = collect_pydict(op)
    # windows: o=1 -> keys in [-1,2] = {1,2,2}; o=2 -> [0,3] = {1,2,2};
    # o=5 -> [3,6] = {5,6}; o=6 -> [4,7] = {5,6}; o=10 -> [8,11] = {10}
    assert out["s"] == [111, 111, 111, 11000, 11000, 100000]


def test_range_frame_nulls_and_descending():
    data = {"g": pa.array([1] * 5, type=pa.int64()),
            "o": pa.array([None, 1, 2, 5, 6], type=pa.int64()),
            "v": pa.array([7, 1, 10, 100, 1000], type=pa.int64())}
    scan = sorted_scan(data, ["g", "o"])
    from blaze_tpu.ir.nodes import WindowExpr
    from blaze_tpu.ops.window import WindowExec

    op = WindowExec(scan, [
        WindowExpr("agg", "s", agg=E.AggExpr(E.AggFunction.SUM, [col("v")]),
                   frame=("range", -1, 0)),
    ], [col("g")], [E.SortOrder(col("o"))])
    out = collect_pydict(op)
    # null row frames over the null run only; o=1 -> [0,1]={1}; o=2 ->
    # [1,2]={1,2}; o=5 -> [4,5]={5}; o=6 -> [5,6]={5,6}
    assert out["s"] == [7, 1, 11, 100, 1100]

    desc = {"g": pa.array([1] * 3, type=pa.int64()),
            "o": pa.array([6, 5, 1], type=pa.int64()),
            "v": pa.array([1000, 100, 1], type=pa.int64())}
    from blaze_tpu.ops.sort import SortExec

    dscan = SortExec(mem_scan(desc), [E.SortOrder(col("g")),
                                      E.SortOrder(col("o"), ascending=False)])
    op2 = WindowExec(dscan, [
        WindowExpr("agg", "s", agg=E.AggExpr(E.AggFunction.SUM, [col("v")]),
                   frame=("range", -1, 0)),
    ], [col("g")], [E.SortOrder(col("o"), ascending=False)])
    out2 = collect_pydict(op2)
    # descending: PRECEDING walks toward LARGER values: o=6 -> {6}; o=5 ->
    # {6,5}; o=1 -> {1}
    assert out2["s"] == [1000, 1100, 1]


def test_range_frame_minmax_peers_and_all_null():
    """Regression (review): RANGE min/max must use value windows, not index
    windows; all-null order keys frame over the whole null run."""
    data = {"g": pa.array([1, 1, 1], type=pa.int64()),
            "o": pa.array([1, 2, 2], type=pa.int64()),
            "v": pa.array([5, 1, 3], type=pa.int64())}
    scan = sorted_scan(data, ["g", "o"])
    from blaze_tpu.ir.nodes import WindowExpr
    from blaze_tpu.ops.window import WindowExec

    op = WindowExec(scan, [
        WindowExpr("agg", "mn", agg=E.AggExpr(E.AggFunction.MIN, [col("v")]),
                   frame=("range", 0, 0)),  # CURRENT ROW..CURRENT ROW = peers
    ], [col("g")], [E.SortOrder(col("o"))])
    out = collect_pydict(op)
    assert out["mn"] == [5, 1, 1]  # the o=2 peers share frame {1,3}

    nulls = {"g": pa.array([1, 1], type=pa.int64()),
             "o": pa.array([None, None], type=pa.int64()),
             "v": pa.array([4, 9], type=pa.int64())}
    scan2 = sorted_scan(nulls, ["g", "o"])
    op2 = WindowExec(scan2, [
        WindowExpr("agg", "s", agg=E.AggExpr(E.AggFunction.SUM, [col("v")]),
                   frame=("range", -1, 0)),
    ], [col("g")], [E.SortOrder(col("o"))])
    out2 = collect_pydict(op2)
    assert out2["s"] == [13, 13]  # whole null run


def test_range_frame_unbounded_includes_null_run():
    data = {"g": pa.array([1, 1, 1], type=pa.int64()),
            "o": pa.array([None, 1, 2], type=pa.int64()),
            "v": pa.array([7, 1, 10], type=pa.int64())}
    scan = sorted_scan(data, ["g", "o"])
    from blaze_tpu.ir.nodes import WindowExpr
    from blaze_tpu.ops.window import WindowExec

    op = WindowExec(scan, [
        WindowExpr("agg", "s", agg=E.AggExpr(E.AggFunction.SUM, [col("v")]),
                   frame=("range", None, 1)),  # UNBOUNDED PRECEDING..1 FOLLOWING
    ], [col("g")], [E.SortOrder(col("o"))])
    out = collect_pydict(op)
    assert out["s"] == [7, 18, 18]  # unbounded side spans the null run

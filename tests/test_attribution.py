"""The why-is-it-slow plane (ISSUE 17): exclusive wall-time attribution,
critical-path extraction, the fusion/placement decision audit, and the
per-fingerprint regression watch.

Covers the acceptance surface: the priority interval sweep's exclusivity
invariant ``sum(categories) <= wall`` (unit + real queries + all five
bench shapes over a real 2-worker pool), worker-span merge onto the
driver timeline, critical-path structural stability on a fixed plan,
fusion-break-reason goldens (pyudf / cost_below_min_saved / blocking_op
and the ``fused_op_fraction`` tripwire), the disabled-path overhead
guard, humanized duration rendering above one hour, Chrome-trace cname/
flow export, ``bench_diff --attribution`` gating (pre-attribution
BENCH_r10 self-diffs clean), and the regression watch's incident bundle
on a category breach."""

import json
import os
import sys
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.config import Config, config_override
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.ir.fusion import fuse_plan
from blaze_tpu.obs.attribution import (CATEGORIES, CATEGORY_CNAME,
                                       CATEGORY_FIELDS, audit_snapshot,
                                       classify_span, critical_path,
                                       decision_audit, exclusive_times,
                                       query_attribution)
from blaze_tpu.obs.explain import fmt_ns
from blaze_tpu.obs.tracer import TRACER
from blaze_tpu.runtime.session import Session

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

F = E.AggFunction
M = E.AggMode
HASH = E.AggExecMode.HASH_AGG


def col(n):
    return E.Column(n)


def lit(v, t):
    return E.Literal(v, t)


def _conf():
    from blaze_tpu.config import get_config

    return get_config()


def _pq_agg_plan(tmp_path, fname="t.parquet", rows=10_000, keys=7):
    """Parquet-backed two-stage agg (pool-shippable: no resource lambdas)."""
    from blaze_tpu.ops.parquet import scan_node_for_files

    path = str(tmp_path / fname)
    pq.write_table(pa.table({"k": [i % keys for i in range(rows)],
                             "v": list(range(rows))}), path)
    scan = scan_node_for_files([path], num_partitions=2)
    partial = N.Agg(scan, HASH, [("k", col("k"))],
                    [N.AggColumn(E.AggExpr(F.SUM, [col("v")], T.I64),
                                 M.PARTIAL, "s")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([col("k")], 3))
    return N.Agg(ex, HASH, [("k", col("k"))],
                 [N.AggColumn(E.AggExpr(F.SUM, [col("v")], T.I64),
                              M.FINAL, "s")])


def _cat_sum(attr):
    return sum(attr[f] for f in CATEGORY_FIELDS)


# -- classification + the exclusivity sweep (units) ----------------------------


@pytest.mark.quick
def test_classify_span_taxonomy():
    assert classify_span("jit_compile:agg", "kernel") == "jit_compile"
    assert classify_span("agg_sum", "kernel") == "kernel_compute"
    assert classify_span("mesh_exchange", "collective") == "collective"
    assert classify_span("to_host", "transfer") == "transfer"
    assert classify_span("spill", "spill") == "spill"
    assert classify_span("shuffle_write", "shuffle") == "shuffle_write"
    assert classify_span("shuffle_fetch", "shuffle") == "shuffle_fetch"
    assert classify_span("queue_wait", "queue") == "queue_wait"
    assert classify_span("AggExec", "operator") == "framework"
    assert classify_span("task", "task") == "framework"
    # container/meta spans must never claim exclusive time
    assert classify_span("stage_0", "stage") is None
    assert classify_span("query_1", "query") is None


def _X(name, cat, ts, dur, **args):
    return {"ph": "X", "name": name, "cat": cat, "ts": ts, "dur": dur,
            "pid": 1, "tid": 1, "args": args}


@pytest.mark.quick
def test_exclusive_sweep_priority_and_invariant():
    """A kernel inside a task counts once as kernel time; jit outranks
    kernel where they overlap; container spans claim nothing; the values
    tile the window exactly (sum == covered time <= window)."""
    events = [
        _X("stage_0", "stage", 0.0, 100_000.0),        # container: no claim
        _X("task", "task", 0.0, 100_000.0),            # framework remainder
        _X("agg_sum", "kernel", 10_000.0, 20_000.0),   # [10ms, 30ms)
        _X("jit_compile:agg", "kernel", 20_000.0, 20_000.0),  # [20ms, 40ms)
        _X("to_host", "transfer", 50_000.0, 10_000.0),
    ]
    out = exclusive_times(events, 0.0, 100_000.0)
    assert out["jit_compile"] == pytest.approx(20_000.0)
    # [20, 30)ms lost to the higher-priority compile span
    assert out["kernel_compute"] == pytest.approx(10_000.0)
    assert out["transfer"] == pytest.approx(10_000.0)
    assert out["framework"] == pytest.approx(60_000.0)
    assert sum(out.values()) == pytest.approx(100_000.0)
    # clipped window: spans straddling the edges never overflow it
    clipped = exclusive_times(events, 15_000.0, 35_000.0)
    assert sum(clipped.values()) <= 20_000.0 + 1e-6


@pytest.mark.quick
def test_exclusive_sweep_empty_and_unclassified():
    assert sum(exclusive_times([], 0.0, 1000.0).values()) == 0.0
    only_meta = [_X("query_1", "query", 0.0, 1000.0)]
    assert sum(exclusive_times(only_meta, 0.0, 1000.0).values()) == 0.0


# -- per-query attribution on real queries -------------------------------------


@pytest.mark.quick
def test_query_attribution_invariant_in_process(tmp_path):
    with config_override(trace_enable=True,
                         profile_store_dir=str(tmp_path / "p")):
        with Session() as sess:
            out = sess.execute_to_pydict(_pq_agg_plan(tmp_path))
            profile = sess.profile()
    assert len(out["k"]) == 7
    attr = profile["attribution"]
    assert attr["wall_ns"] > 0
    assert _cat_sum(attr) == attr["attributed_ns"] <= attr["wall_ns"]
    assert 0.0 < attr["coverage_fraction"] <= 1.0
    # a real two-stage query spends SOME classified time
    assert attr["attributed_ns"] > 0
    # the critical path reaches the profile with a stage segment
    cp = profile["critical_path"]
    assert any(seg["kind"] == "stage" for seg in cp)
    # and the decision audit is attached with the coverage tripwire
    audit = profile["decision_audit"]
    assert "fused_op_fraction" in audit
    assert audit["placement_decisions"]


@pytest.mark.quick
def test_critical_path_stable_on_fixed_plan(tmp_path):
    """Segment structure (kinds, names, stage ids) is a golden for a fixed
    plan — only the times move between runs."""
    def run():
        with config_override(trace_enable=True):
            with Session() as sess:
                sess.execute_to_pydict(_pq_agg_plan(tmp_path))
                return sess.profile()["critical_path"]

    def shape(cp):
        return [(seg["kind"], seg["name"], seg.get("stage"))
                for seg in cp if seg["kind"] != "driver"]

    cp1, cp2 = run(), run()
    assert shape(cp1) == shape(cp2)
    stage_segs = [seg for seg in cp1 if seg["kind"] == "stage"]
    assert stage_segs
    # the binding task and its operators are attributed
    assert all(seg.get("task_ms", 0) >= 0 for seg in stage_segs)
    assert any(seg.get("operators") for seg in cp1)


@pytest.mark.quick
def test_explain_analyze_renders_attribution(tmp_path):
    with config_override(trace_enable=True):
        with Session() as sess:
            text = sess.explain_analyze(_pq_agg_plan(tmp_path))
    assert "Wall-time attribution (exclusive)" in text
    assert "coverage" in text
    assert "Critical path" in text


# -- chrome trace export: stable colors + shuffle flow links -------------------


@pytest.mark.quick
def test_chrome_trace_cnames_and_shuffle_flows(tmp_path):
    with config_override(trace_enable=True):
        with Session() as sess:
            sess.execute_to_pydict(_pq_agg_plan(tmp_path))
            trace = TRACER.to_chrome_trace()
    evs = trace["traceEvents"]
    named = [e for e in evs if e.get("ph") == "X" and e.get("cname")]
    assert named, "classified spans must carry a stable cname"
    assert all(e["cname"] in CATEGORY_CNAME.values() for e in named)
    # same category -> same color, every time
    for e in named:
        cat = classify_span(e.get("name", ""), e.get("cat", ""))
        assert e["cname"] == CATEGORY_CNAME[cat]
    flows_s = [e for e in evs if e.get("ph") == "s"]
    flows_f = [e for e in evs if e.get("ph") == "f"]
    assert flows_s and flows_f, "shuffle write->fetch flow links missing"
    assert {e["id"] for e in flows_s} & {e["id"] for e in flows_f}


# -- humanized durations above one hour (satellite fix) ------------------------


@pytest.mark.quick
def test_fmt_ns_hours_and_minutes():
    assert fmt_ns(90 * 60 * 1_000_000_000) == "1h30m"
    assert fmt_ns(3600 * 1_000_000_000) == "1h00m"
    assert fmt_ns(25 * 3600 * 1_000_000_000) == "25h00m"
    assert fmt_ns(90 * 1_000_000_000) == "1m30s"
    assert fmt_ns(59 * 1_000_000_000).endswith("s")  # below the minute tier
    assert "h" not in fmt_ns(59 * 60 * 1_000_000_000)


# -- decision-audit goldens ----------------------------------------------------


def _chain_plan(path):
    """project -> filter -> project -> filter: the canonical fusable chain."""
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files([path], num_partitions=2)
    return N.Projection(
        N.Filter(
            N.Projection(
                N.Filter(scan, [E.BinaryExpr(E.BinaryOp.GT, col("a"),
                                             lit(10, T.I64))]),
                [col("a"),
                 E.BinaryExpr(E.BinaryOp.MUL, col("b"), lit(2.0, T.F64)),
                 col("c")],
                ["a", "b2", "c"]),
            [E.BinaryExpr(E.BinaryOp.LT, col("c"), lit(7, T.I64))]),
        [E.BinaryExpr(E.BinaryOp.ADD, col("a"), col("c")), col("b2")],
        ["ac", "b2"])


@pytest.fixture()
def fusion_table(tmp_path):
    rng = np.random.default_rng(11)
    n = 2000
    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({
        "a": pa.array(rng.integers(0, 100, n), type=pa.int64()),
        "b": pa.array(rng.standard_normal(n), type=pa.float64()),
        "c": pa.array(rng.integers(0, 10, n), type=pa.int64()),
    }), p)
    return p


@pytest.mark.quick
def test_fusion_audit_fused_chain(fusion_table):
    before = audit_snapshot()
    fused = fuse_plan(_chain_plan(fusion_table), _conf())
    assert isinstance(fused, N.FusedStage)
    audit = decision_audit(before)
    assert audit["ops_fused"] >= 4 and audit["ops_eligible"] >= 4
    assert audit["fused_op_fraction"] > 0.0
    # the chain still ended somewhere structural (the scan below it)
    assert audit["fusion_break_reasons"].get("blocking_op", 0) >= 1


@pytest.mark.quick
def test_fusion_audit_pyudf_break(fusion_table):
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files([fusion_table], num_partitions=2)
    udf = E.PyUDF(
        lambda a: pa.array([v * 2 for v in a.to_pylist()], type=pa.int64()),
        [col("a")], T.I64, "dbl")
    plan = N.Filter(
        N.Projection(
            N.Filter(scan, [E.BinaryExpr(E.BinaryOp.GT, col("a"),
                                         lit(20, T.I64))]),
            [udf, col("c")], ["a2", "c"]),
        [E.BinaryExpr(E.BinaryOp.LT, col("c"), lit(5, T.I64))])
    before = audit_snapshot()
    fuse_plan(plan, _conf())
    audit = decision_audit(before)
    assert audit["fusion_break_reasons"].get("pyudf", 0) >= 1


@pytest.mark.quick
def test_fusion_audit_cost_cut(fusion_table):
    # a lone column-reference projection saves no dispatches: the pass
    # declines on cost and the audit says so (fraction 0.0, not None)
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files([fusion_table])
    plan = N.Projection(scan, [col("a")], ["a"])
    before = audit_snapshot()
    assert fuse_plan(plan, _conf()) is plan
    audit = decision_audit(before)
    assert audit["fusion_break_reasons"].get("cost_below_min_saved", 0) >= 1
    assert audit["ops_fused"] == 0 and audit["ops_eligible"] >= 1
    assert audit["fused_op_fraction"] == 0.0


@pytest.mark.quick
def test_placement_audit_forced_host(tmp_path):
    before = audit_snapshot()
    with config_override(device_placement="host"):
        with Session() as sess:
            b = pa.table({"k": [1, 2, 3], "v": [1, 2, 3]})
            p = str(tmp_path / "s.parquet")
            pq.write_table(b, p)
            from blaze_tpu.ops.parquet import scan_node_for_files
            sess.execute_to_pydict(N.Agg(
                scan_node_for_files([p]), HASH, [("k", col("k"))],
                [N.AggColumn(E.AggExpr(F.SUM, [col("v")], T.I64),
                             M.COMPLETE, "s")]))
    audit = decision_audit(before)
    assert audit["placement_decisions"].get("host", 0) >= 1
    assert audit["placement_decline_reasons"].get("conf_forced_host", 0) >= 1


# -- disabled-path overhead guard ----------------------------------------------


@pytest.mark.quick
def test_attribution_disabled_overhead_under_5_percent(tmp_path):
    """With attribution off the only per-span cost on the hot path is the
    ``TRACER.active`` check; scaled by a generous span count it stays
    under 5% of a real query's wall."""
    plan = _pq_agg_plan(tmp_path, rows=200_000, keys=97)
    with Session(conf=Config(attribution_enabled=False)) as sess:
        t0 = time.perf_counter_ns()
        out = sess.execute_to_pydict(plan)
        wall_ns = time.perf_counter_ns() - t0
        assert len(out["k"]) == 97
        prof = sess.profile()
        assert prof is None or "attribution" not in prof

    ITER = 100_000
    t0 = time.perf_counter_ns()
    for _ in range(ITER):
        TRACER.active  # noqa: B018  — the guard under measurement
    per_check_ns = (time.perf_counter_ns() - t0) / ITER
    overhead_ns = per_check_ns * 10_000  # far more spans than any query emits
    assert overhead_ns < 0.05 * wall_ns, (
        f"disabled attribution {overhead_ns / 1e6:.2f}ms vs query "
        f"{wall_ns / 1e6:.1f}ms: disabled-path overhead exceeds 5%")
    assert per_check_ns < 2_000, f"active check {per_check_ns:.0f}ns"


# -- bench_diff --attribution gates --------------------------------------------


def _bench_diff():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import bench_diff
    finally:
        sys.path.pop(0)
    return bench_diff


@pytest.mark.quick
def test_bench_diff_attribution_r10_self_diff_clean():
    """Pre-attribution artifacts carry no sections: the gate must skip
    them clean, so BENCH_r10 -> BENCH_r10 (and r10 -> any successor with
    sections) exits 0."""
    bd = _bench_diff()
    art = os.path.join(REPO, "BENCH_r10.json")
    assert os.path.exists(art)
    assert bd.main(["--attribution", art, art]) == 0


@pytest.mark.quick
def test_bench_diff_attribution_category_gate():
    bd = _bench_diff()

    def art(jit_ms, kern_ms, frac=0.5):
        return {"shapes": {"q": {
            "attribution": {"jit_compile_time_ns": int(jit_ms * 1e6),
                            "kernel_compute_time_ns": int(kern_ms * 1e6)},
            "decision_audit": {"fused_op_fraction": frac}}}}

    # jit tripled-plus over a >=floor base: breach even with other cats flat
    r = bd.diff_attribution(art(100, 400), art(400, 400))
    assert any("jit_compile_time_ns" in s for s in r)
    # 2.5x jit is under the 3.0 jit ratio; 2.5x kernel is over its 2.0
    assert bd.diff_attribution(art(100, 400), art(250, 400)) == []
    assert any("kernel_compute_time_ns" in s
               for s in bd.diff_attribution(art(100, 400), art(100, 1000)))
    # sub-floor noise never trips (5ms -> 40ms is under 2x the 50ms floor)
    assert bd.diff_attribution(art(100, 5), art(100, 40)) == []
    # fusion coverage tripwire: a 0.3 drop fails, 0.1 passes
    assert any("fused_op_fraction" in s for s in bd.diff_attribution(
        art(100, 100, frac=0.8), art(100, 100, frac=0.5)))
    assert bd.diff_attribution(art(100, 100, frac=0.8),
                               art(100, 100, frac=0.7)) == []
    # missing sections skip clean in either direction
    assert bd.diff_attribution({"shapes": {"q": {}}}, art(1, 1)) == []
    assert bd.diff_attribution(art(1, 1), {"shapes": {"q": {}}}) == []


# -- the regression watch ------------------------------------------------------


def _profile(fp, samples, cur_jit_ms, base_jit_ms):
    return {"fingerprint": fp, "label": fp,
            "attribution": {"jit_compile_time_ns": int(cur_jit_ms * 1e6),
                            "wall_ns": int(1e9)},
            "attribution_baseline": {"samples": samples,
                                     "jit_compile_time_ns":
                                         int(base_jit_ms * 1e6)}}


@pytest.mark.quick
def test_regression_watch_breach_writes_incident(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import regression_watch as rw
    finally:
        sys.path.pop(0)
    store = tmp_path / "profiles"
    inc = tmp_path / "incidents"
    store.mkdir(), inc.mkdir()
    for prof in (_profile("ok", 5, 100, 100),       # within baseline
                 _profile("bad", 5, 400, 100),      # jit 4x: breach
                 _profile("fresh", 1, 400, 400)):   # no history: skipped
        with open(store / (prof["fingerprint"] + ".json"), "w") as f:
            json.dump(prof, f)
    report = rw.watch(str(store), 2.0, 3.0, 50.0, str(inc))
    assert report["checked"] == 2
    assert report["skipped_no_history"] == 1
    assert [b["fingerprint"] for b in report["breaches"]] == ["bad"]
    breach = report["breaches"][0]["breaches"][0]
    assert breach["category"] == "jit_compile_time_ns"
    assert breach["ratio"] == pytest.approx(4.0)
    # the incident bundle landed with the offending categories
    bundles = os.listdir(inc)
    assert len(bundles) == 1 and "attribution_regression" in bundles[0]
    with open(inc / bundles[0]) as f:
        bundle = json.load(f)
    assert bundle["kind"] == "attribution_regression"
    assert bundle["extra"]["breaches"][0]["category"] == "jit_compile_time_ns"
    # CLI contract: breach -> exit 1, clean store -> exit 0
    assert rw.main(["--store", str(store), "--incident-dir", ""]) == 1
    os.unlink(store / "bad.json")
    assert rw.main(["--store", str(store), "--incident-dir", ""]) == 0


@pytest.mark.quick
def test_attribution_baseline_rolls_in_store(tmp_path):
    """save_profile folds each run into the capped-window mean the watch
    compares against."""
    from blaze_tpu.obs.stats import save_profile

    conf = Config(profile_store_dir=str(tmp_path / "p"), profile_store_max=8)
    attr1 = {f: 0 for f in CATEGORY_FIELDS}
    attr1.update({"jit_compile_time_ns": 100, "wall_ns": 1000})
    save_profile({"fingerprint": "fp", "attribution": attr1}, conf)
    attr2 = dict(attr1, jit_compile_time_ns=300)
    save_profile({"fingerprint": "fp", "attribution": attr2}, conf)
    with open(tmp_path / "p" / "fp.json") as f:
        stored = json.load(f)
    base = stored["attribution_baseline"]
    assert base["samples"] == 2
    assert base["jit_compile_time_ns"] == 200  # mean of 100 and 300


# -- the five bench shapes over a real 2-worker pool (slow) --------------------


@pytest.fixture(scope="module")
def bench_paths(tmp_path_factory):
    import bench

    bench.ROWS = 60_000
    bench.PARTS = 2
    td = str(tmp_path_factory.mktemp("attrbench"))
    return bench.make_data(td)


@pytest.mark.slow
@pytest.mark.parametrize("shape", ["q01", "q06", "q17", "q47", "q67"])
def test_pool_bench_shapes_exclusivity(bench_paths, shape, tmp_path):
    """The acceptance invariant on every bench shape, workers included:
    worker spans absorbed onto the driver timeline, and
    sum(categories) <= wall exactly."""
    import bench

    plan_fn = {s[0]: s[1] for s in bench.SHAPES}[shape]
    with config_override(trace_enable=True,
                         profile_store_dir=str(tmp_path / "p")):
        with Session(num_worker_processes=2) as sess:
            sess.execute_to_pydict(plan_fn(bench_paths))
            profile = sess.profile()
            events = TRACER.snapshot()
    attr = profile["attribution"]
    assert _cat_sum(attr) == attr["attributed_ns"] <= attr["wall_ns"]
    assert attr["attributed_ns"] > 0
    assert 0.0 < attr["coverage_fraction"] <= 1.0
    # worker-side task spans were absorbed onto the driver timeline
    driver_pid = os.getpid()
    worker_spans = [e for e in events if e.get("ph") == "X"
                    and e.get("pid") not in (None, driver_pid)]
    assert worker_spans, "no worker spans absorbed into the driver trace"
    assert any(e.get("cat") == "task" for e in worker_spans)
    # and the critical path binds each stage to a task
    assert any(seg["kind"] == "stage" and seg.get("task") is not None
               for seg in profile["critical_path"])


@pytest.mark.slow
def test_pool_worker_span_merge_attributes_shuffle(tmp_path):
    """Worker shuffle writes land in the exclusive decomposition: the
    spans ride reply merge (Tracer.absorb) and classify as
    shuffle_write."""
    plan = _pq_agg_plan(tmp_path, rows=50_000, keys=101)
    with config_override(trace_enable=True):
        with Session(num_worker_processes=2) as sess:
            sess.execute_to_pydict(plan)
            events = TRACER.snapshot()
            profile = sess.profile()
    writes = [e for e in events if e.get("name") == "shuffle_write"]
    assert writes, "worker shuffle_write spans missing from driver trace"
    assert all((e.get("args") or {}).get("stage") is not None
               for e in writes)
    attr = profile["attribution"]
    assert _cat_sum(attr) <= attr["wall_ns"]

import numpy as np
import pyarrow as pa
import pytest
from decimal import Decimal

from blaze_tpu.config import config_override
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.ops.agg import AggExec
from blaze_tpu.ops.sort import SortExec
from blaze_tpu.runtime.memmgr import MemManager
from tests.util import collect, collect_pydict, mem_scan


def col(n):
    return E.Column(n)


def agg_col(fn, args, mode, name, return_type=None):
    return N.AggColumn(E.AggExpr(fn, args, return_type), mode, name)


def _sorted_out(op, by):
    tbl = collect(op).to_pydict()
    order = sorted(range(len(tbl[by])), key=lambda i: (tbl[by][i] is None, tbl[by][i]))
    return {k: [v[i] for i in order] for k, v in tbl.items()}


F = E.AggFunction
M = E.AggMode
HASH = E.AggExecMode.HASH_AGG


def test_final_agg_basic():
    scan = mem_scan(
        {
            "k": pa.array([1, 2, 1, 2, 1], type=pa.int64()),
            "v": pa.array([10, 20, 30, None, 50], type=pa.int64()),
        },
        num_batches=2,
    )
    op = AggExec(scan, HASH, [("k", col("k"))], [
        agg_col(F.SUM, [col("v")], M.COMPLETE, "s"),
        agg_col(F.COUNT, [col("v")], M.COMPLETE, "c"),
        agg_col(F.MIN, [col("v")], M.COMPLETE, "mn"),
        agg_col(F.MAX, [col("v")], M.COMPLETE, "mx"),
        agg_col(F.AVG, [col("v")], M.COMPLETE, "a"),
    ])
    out = _sorted_out(op, "k")
    assert out["k"] == [1, 2]
    assert out["s"] == [90, 20]
    assert out["c"] == [3, 1]
    assert out["mn"] == [10, 20]
    assert out["mx"] == [50, 20]
    assert out["a"] == [30.0, 20.0]


@pytest.mark.quick
def test_partial_then_final_two_stage():
    data = {
        "k": pa.array(["x", "y", "x", None], type=pa.string()),
        "v": pa.array([1.5, 2.5, 3.0, 4.0], type=pa.float64()),
    }
    scan = mem_scan(data, num_batches=2)
    partial = AggExec(scan, HASH, [("k", col("k"))], [
        agg_col(F.SUM, [col("v")], M.PARTIAL, "s"),
        agg_col(F.AVG, [col("v")], M.PARTIAL, "a"),
        agg_col(F.COUNT, [], M.PARTIAL, "c"),
    ])
    # partial output schema: k + typed state cols
    assert partial.schema.names == ["k", "s#sum", "s#has", "a#sum", "a#count", "c#count"]
    final = AggExec(partial, HASH, [("k", col("k"))], [
        agg_col(F.SUM, [col("v")], M.FINAL, "s"),
        agg_col(F.AVG, [col("v")], M.FINAL, "a"),
        agg_col(F.COUNT, [], M.FINAL, "c"),
    ])
    out = _sorted_out(final, "k")
    assert out["k"] == ["x", "y", None]
    assert out["s"] == [4.5, 2.5, 4.0]
    assert out["a"] == [2.25, 2.5, 4.0]
    assert out["c"] == [2, 1, 1]


def test_global_agg_no_groups():
    scan = mem_scan({"v": pa.array([1, 2, 3], type=pa.int64())})
    op = AggExec(scan, HASH, [], [
        agg_col(F.SUM, [col("v")], M.COMPLETE, "s"),
        agg_col(F.COUNT, [], M.COMPLETE, "c"),
    ])
    out = collect_pydict(op)
    assert out == {"s": [6], "c": [3]}


def test_global_agg_empty_input():
    scan = mem_scan({"v": pa.array([], type=pa.int64())})
    op = AggExec(scan, HASH, [], [
        agg_col(F.SUM, [col("v")], M.COMPLETE, "s"),
        agg_col(F.COUNT, [], M.COMPLETE, "c"),
    ])
    out = collect_pydict(op)
    assert out == {"s": [None], "c": [0]}


def test_decimal_sum_avg():
    schema = T.Schema.of(("k", T.I32), ("v", T.DecimalType(7, 2)))
    data = {
        "k": pa.array([1, 1, 2], type=pa.int32()),
        "v": pa.array([Decimal("1.10"), Decimal("2.05"), None], type=pa.decimal128(7, 2)),
    }
    scan = mem_scan(data, schema)
    op = AggExec(scan, HASH, [("k", col("k"))], [
        agg_col(F.SUM, [col("v")], M.COMPLETE, "s", T.DecimalType(17, 2)),
        agg_col(F.AVG, [col("v")], M.COMPLETE, "a", T.DecimalType(11, 6)),
    ])
    out = _sorted_out(op, "k")
    assert out["s"] == [Decimal("3.15"), None]
    assert out["a"] == [Decimal("1.575000"), None]


def test_first_and_collect():
    data = {
        "k": pa.array([1, 1, 2, 2], type=pa.int64()),
        "v": pa.array([None, 7, 8, 9], type=pa.int64()),
        "s": pa.array(["a", "b", "c", "c"]),
    }
    scan = mem_scan(data, num_batches=2)
    op = AggExec(scan, HASH, [("k", col("k"))], [
        agg_col(F.FIRST, [col("v")], M.COMPLETE, "f"),
        agg_col(F.FIRST_IGNORES_NULL, [col("v")], M.COMPLETE, "fi"),
        agg_col(F.COLLECT_LIST, [col("s")], M.COMPLETE, "cl"),
        agg_col(F.COLLECT_SET, [col("s")], M.COMPLETE, "cs"),
    ])
    out = _sorted_out(op, "k")
    assert out["f"] == [None, 8]
    assert out["fi"] == [7, 8]
    assert out["cl"] == [["a", "b"], ["c", "c"]]
    assert out["cs"] == [["a", "b"], ["c"]]


def test_min_max_strings():
    data = {"k": pa.array([1, 1, 2], type=pa.int64()),
            "s": pa.array(["pear", "apple", None])}
    scan = mem_scan(data)
    op = AggExec(scan, HASH, [("k", col("k"))], [
        agg_col(F.MIN, [col("s")], M.COMPLETE, "mn"),
        agg_col(F.MAX, [col("s")], M.COMPLETE, "mx"),
    ])
    out = _sorted_out(op, "k")
    assert out["mn"] == ["apple", None]
    assert out["mx"] == ["pear", None]


def test_agg_spill():
    rng = np.random.default_rng(0)
    n = 30_000
    keys = rng.integers(0, 5000, size=n)
    vals = rng.integers(0, 100, size=n)
    scan = mem_scan({"k": keys.tolist(), "v": vals.tolist()}, num_batches=12)
    MemManager.reset()
    with config_override(memory_total=100_000, memory_fraction=1.0):
        op = AggExec(scan, HASH, [("k", col("k"))], [
            agg_col(F.SUM, [col("v")], M.COMPLETE, "s"),
            agg_col(F.COUNT, [], M.COMPLETE, "c"),
        ])
        from blaze_tpu.ops.base import ExecContext
        from blaze_tpu.runtime.metrics import MetricNode

        ctx = ExecContext()
        m = MetricNode("root")
        batches = []
        for p in range(op.num_partitions()):
            batches.extend(b.to_arrow() for b in op.execute(p, ctx, m) if b.num_rows)
        import pyarrow as _pa

        tbl = _pa.Table.from_batches(batches).to_pydict()
        assert m.total("spill_count") >= 1, "spill must actually fire"
        order = sorted(range(len(tbl["k"])), key=lambda i: tbl["k"][i])
        out = {kk: [vv[i] for i in order] for kk, vv in tbl.items()}
    MemManager.reset()
    import collections

    expected_sum = collections.defaultdict(int)
    expected_cnt = collections.defaultdict(int)
    for k, v in zip(keys.tolist(), vals.tolist()):
        expected_sum[k] += v
        expected_cnt[k] += 1
    ks = sorted(expected_sum)
    assert out["k"] == ks
    assert out["s"] == [expected_sum[k] for k in ks]
    assert out["c"] == [expected_cnt[k] for k in ks]


def test_partial_skipping_passthrough():
    # high-cardinality keys -> skipper engages, output stays correct after
    # a final agg over the partials
    n = 60_000
    data = {"k": list(range(n)), "v": [1] * n}
    scan = mem_scan(data, num_batches=8)
    with config_override(partial_agg_skipping_min_rows=10_000):
        partial = AggExec(scan, HASH, [("k", col("k"))],
                          [agg_col(F.SUM, [col("v")], M.PARTIAL, "s")],
                          supports_partial_skipping=True)
        final = AggExec(partial, HASH, [("k", col("k"))],
                        [agg_col(F.SUM, [col("v")], M.FINAL, "s")])
        out = collect_pydict(final)
    assert len(out["k"]) == n
    assert sum(out["s"]) == n


def test_bloom_filter_agg_and_probe():
    scan = mem_scan({"v": pa.array([10, 20, 30], type=pa.int64())})
    op = AggExec(scan, HASH, [], [agg_col(F.BLOOM_FILTER, [col("v")], M.COMPLETE, "bf")])
    out = collect_pydict(op)
    blob = out["bf"][0]
    from blaze_tpu.ops.bloom import SparkBloomFilter

    bf = SparkBloomFilter.deserialize(blob)
    assert bf.might_contain_longs_np(np.array([10, 20, 30])).all()
    assert not bf.might_contain_longs_np(np.arange(1000, 1100)).any()


def test_wide_decimal_host_exact():
    # decimal(20,2) exceeds int64 -> host object path must stay exact
    schema = T.Schema.of(("k", T.I64), ("v", T.DecimalType(20, 2)))
    data = {
        "k": pa.array([1, 1, 2], type=pa.int64()),
        "v": pa.array([Decimal("1.25"), Decimal("3.25"),
                       Decimal("123456789012345678.99")], type=pa.decimal128(20, 2)),
    }
    scan = mem_scan(data, schema)
    op = AggExec(scan, HASH, [("k", col("k"))], [
        agg_col(F.SUM, [col("v")], M.COMPLETE, "s", T.DecimalType(30, 2)),
        agg_col(F.AVG, [col("v")], M.COMPLETE, "a", T.DecimalType(24, 6)),
        agg_col(F.MIN, [col("v")], M.COMPLETE, "mn"),
        agg_col(F.MAX, [col("v")], M.COMPLETE, "mx"),
    ])
    out = _sorted_out(op, "k")
    assert out["s"] == [Decimal("4.50"), Decimal("123456789012345678.99")]
    assert out["a"] == [Decimal("2.250000"), Decimal("123456789012345678.990000")]
    assert out["mn"] == [Decimal("1.25"), Decimal("123456789012345678.99")]
    assert out["mx"] == [Decimal("3.25"), Decimal("123456789012345678.99")]


def test_host_state_spill_reorder():
    # spilled aggregation with a host-state fn: per-group values must follow
    # their keys through the key-sorted spill emit
    rng = np.random.default_rng(3)
    n = 4000
    keys = rng.integers(0, 500, size=n).tolist()
    svals = [f"s{k:04d}-{i}" for i, k in enumerate(keys)]
    scan = mem_scan({"k": keys, "s": svals}, num_batches=6)
    MemManager.reset()
    with config_override(memory_total=30_000, memory_fraction=1.0):
        op = AggExec(scan, HASH, [("k", col("k"))], [
            agg_col(F.MIN, [col("s")], M.COMPLETE, "mn"),
            agg_col(F.SUM, [col("k")], M.COMPLETE, "ks"),
        ])
        from blaze_tpu.ops.base import ExecContext
        from blaze_tpu.runtime.metrics import MetricNode

        ctx = ExecContext()
        m = MetricNode("root")
        import pyarrow as _pa

        batches = [b.to_arrow() for b in op.execute(0, ctx, m) if b.num_rows]
        assert m.total("spill_count") >= 1, "spill must actually fire"
        tbl = _pa.Table.from_batches(batches).to_pydict()
        order = sorted(range(len(tbl["k"])), key=lambda i: tbl["k"][i])
        out = {kk: [vv[i] for i in order] for kk, vv in tbl.items()}
    MemManager.reset()
    import collections

    exp_min = {}
    exp_sum = collections.defaultdict(int)
    for k, s in zip(keys, svals):
        exp_min[k] = min(exp_min.get(k, s), s)
        exp_sum[k] += k
    ks = sorted(exp_min)
    assert out["k"] == ks
    assert out["mn"] == [exp_min[k] for k in ks]
    assert out["ks"] == [exp_sum[k] for k in ks]


def test_hash_wide_decimal_matches_binary():
    from blaze_tpu.core.batch import HostColumn
    from blaze_tpu.exprs import spark_hash as H

    arr = pa.array([Decimal("12345678901234567890.12"), None],
                   type=pa.decimal128(22, 2))
    colh = HostColumn(T.DecimalType(22, 2), arr)
    out = H.hash_batch([colh], 2, 256, seed=42)
    # row hashing as BigInteger bytes: second row (null) keeps the seed
    assert out[1] == 42
    u = int(Decimal("12345678901234567890.12").scaleb(2))
    nbytes = (u.bit_length() // 8) + 1
    blob = u.to_bytes(nbytes, "big", signed=True)
    import tests.test_spark_hash as tsh

    assert out[0] == np.uint32(tsh.mmh3_scalar(blob, 42)).astype(np.int32)


def test_device_partial_widening_sum_i32():
    # regression: sum(int32) must accumulate in int64 on the device fast path
    schema = T.Schema.of(("k", T.I32), ("v", T.I32))
    n = 3000
    data = {"k": pa.array([1] * n, type=pa.int32()),
            "v": pa.array([2_000_000] * n, type=pa.int32())}
    scan = mem_scan(data, schema)
    partial = AggExec(scan, HASH, [("k", col("k"))],
                      [agg_col(F.SUM, [col("v")], M.PARTIAL, "s"),
                       agg_col(F.AVG, [col("v")], M.PARTIAL, "a")])
    final = AggExec(partial, HASH, [("k", col("k"))],
                    [agg_col(F.SUM, [col("v")], M.FINAL, "s"),
                     agg_col(F.AVG, [col("v")], M.FINAL, "a")])
    out = collect_pydict(final)
    assert out["s"] == [2_000_000 * n]  # > 2^31, would wrap in int32
    assert out["a"] == [2_000_000.0]


def test_device_partial_expr_keys_multi_batch():
    # regression: device partial agg with a NON-trivial grouping expression
    # across multiple batches (CSE must reset per batch in the direct-_eval
    # flow)
    data = {"k": [1, 1, 2, 5, 5, 6], "v": [1, 1, 1, 1, 1, 1]}
    scan = mem_scan(data, num_batches=2)  # [1,1,2] then [5,5,6]
    gexpr = E.BinaryExpr(E.BinaryOp.ADD, col("k"), E.Literal(0, T.I64))
    partial = AggExec(scan, HASH, [("g", gexpr)],
                      [agg_col(F.COUNT, [], M.PARTIAL, "c")])
    final = AggExec(partial, HASH, [("g", col("g"))],
                    [agg_col(F.COUNT, [], M.FINAL, "c")])
    out = _sorted_out(final, "g")
    assert out["g"] == [1, 2, 5, 6]
    assert out["c"] == [2, 1, 2, 1]


def test_sort_agg_streaming():
    from blaze_tpu.ops.sort import SortExec

    rng = np.random.default_rng(11)
    n = 20_000
    keys = np.sort(rng.integers(0, 400, n)).tolist()  # pre-sorted input
    vals = rng.integers(0, 100, n).tolist()
    scan = mem_scan({"k": keys, "v": vals}, num_batches=8)
    op = AggExec(scan, E.AggExecMode.SORT_AGG, [("k", col("k"))], [
        agg_col(F.SUM, [col("v")], M.COMPLETE, "s"),
        agg_col(F.MIN, [col("v")], M.COMPLETE, "mn"),
        agg_col(F.COUNT, [], M.COMPLETE, "c"),
    ])
    out = _sorted_out(op, "k")
    import collections

    es = collections.defaultdict(int)
    em = {}
    ec = collections.defaultdict(int)
    for k, v in zip(keys, vals):
        es[k] += v
        em[k] = min(em.get(k, v), v)
        ec[k] += 1
    ks = sorted(es)
    assert out["k"] == ks
    assert out["s"] == [es[k] for k in ks]
    assert out["mn"] == [em[k] for k in ks]
    assert out["c"] == [ec[k] for k in ks]


def test_sort_agg_two_stage_with_exchange():
    # partial sort-agg -> exchange -> final sort-agg through the session
    from blaze_tpu.runtime.session import Session
    from blaze_tpu.core import ColumnarBatch
    from blaze_tpu.ir import nodes as NN

    rng = np.random.default_rng(12)
    n = 6000
    keys = np.sort(rng.integers(0, 50, n))
    vals = rng.integers(0, 10, n)
    b = ColumnarBatch.from_pydict({"k": keys.tolist(), "v": vals.tolist()})
    sess = Session()
    half = n // 2
    sess.resources["src"] = lambda p: [b.slice(p * half, half).to_arrow()]
    scan = NN.FFIReader(schema=b.schema, resource_id="src", num_partitions=2)
    partial = NN.Agg(scan, E.AggExecMode.SORT_AGG, [("k", col("k"))],
                     [NN.AggColumn(E.AggExpr(F.SUM, [col("v")]), M.PARTIAL, "s")])
    ex = NN.ShuffleExchange(partial, NN.HashPartitioning([col("k")], 3))
    final = NN.Agg(ex, E.AggExecMode.HASH_AGG, [("k", col("k"))],
                   [NN.AggColumn(E.AggExpr(F.SUM, [col("v")]), M.FINAL, "s")])
    out = sess.execute_to_pydict(final)
    import collections

    exp = collections.defaultdict(int)
    for k, v in zip(keys.tolist(), vals.tolist()):
        exp[k] += v
    assert dict(zip(out["k"], out["s"])) == dict(exp)


def test_device_final_merge_matches_host_table():
    """FINAL-mode merge on device (round-1 weak #4): merged states equal the
    host intern table bit-for-bit, including decimal sum/avg, min/max, and
    null group keys."""
    from decimal import Decimal

    from blaze_tpu.config import config_override
    from blaze_tpu.ops.base import ExecContext
    from blaze_tpu.runtime.metrics import MetricNode

    rng = np.random.default_rng(71)
    n = 5000
    keys = [None if i % 50 == 0 else int(rng.integers(0, 40)) for i in range(n)]
    amts = [Decimal(int(v)).scaleb(-2) for v in rng.integers(0, 10000, n)]
    vals = rng.integers(-100, 100, n).tolist()
    data = {
        "k": pa.array(keys, type=pa.int64()),
        "amt": pa.array(amts, type=pa.decimal128(7, 2)),
        "v": pa.array(vals, type=pa.int64()),
    }
    scan = mem_scan(data, num_batches=4)
    partial = AggExec(scan, HASH, [("k", col("k"))], [
        agg_col(F.SUM, [col("amt")], M.PARTIAL, "s", T.DecimalType(17, 2)),
        agg_col(F.AVG, [col("amt")], M.PARTIAL, "a", T.DecimalType(11, 6)),
        agg_col(F.MIN, [col("v")], M.PARTIAL, "mn"),
        agg_col(F.MAX, [col("v")], M.PARTIAL, "mx"),
        agg_col(F.COUNT, [], M.PARTIAL, "c"),
    ])
    staged = []
    ctx0 = ExecContext()
    for p in range(partial.num_partitions()):
        staged.extend(b for b in partial.execute(p, ctx0) if b.num_rows)

    def run_final(**conf):
        from blaze_tpu.ops.basic import MemoryScanExec

        src = MemoryScanExec(partial.schema, [list(staged)])
        final = AggExec(src, HASH, [("k", col("k"))], [
            agg_col(F.SUM, [col("amt")], M.FINAL, "s", T.DecimalType(17, 2)),
            agg_col(F.AVG, [col("amt")], M.FINAL, "a", T.DecimalType(11, 6)),
            agg_col(F.MIN, [col("v")], M.FINAL, "mn"),
            agg_col(F.MAX, [col("v")], M.FINAL, "mx"),
            agg_col(F.COUNT, [], M.FINAL, "c"),
        ])
        m = MetricNode("root")
        with config_override(**conf):
            ctx = ExecContext()
            rows = [b.to_arrow() for b in final.execute(0, ctx, m) if b.num_rows]
        tbl = pa.Table.from_batches(rows).to_pydict()
        order = sorted(range(len(tbl["k"])),
                       key=lambda i: (tbl["k"][i] is not None, tbl["k"][i]))
        return {kk: [vv[i] for i in order] for kk, vv in tbl.items()}, m

    got, m_dev = run_final()
    assert m_dev.total("device_merge_batches") >= 1, "device merge not engaged"
    expect, m_host = run_final(device_merge_max_bytes=0)
    assert m_host.total("device_merge_batches") == 0
    assert got == expect


def test_brickhouse_collect_and_combine_unique():
    """Reference auron.proto AggFunction BRICKHOUSE_COLLECT /
    BRICKHOUSE_COMBINE_UNIQUE (agg/brickhouse.rs): collect keeps
    duplicates; combine_unique unions array inputs per group."""
    data = {
        "k": pa.array([1, 1, 2, 2], type=pa.int64()),
        "v": pa.array(["a", "a", "b", "c"]),
        "arr": pa.array([["x", "y"], ["y", "z"], ["q"], None],
                        type=pa.list_(pa.string())),
    }
    scan = mem_scan(data, num_batches=2)
    op = AggExec(scan, HASH, [("k", col("k"))], [
        agg_col(F.BRICKHOUSE_COLLECT, [col("v")], M.COMPLETE, "c"),
        agg_col(F.BRICKHOUSE_COMBINE_UNIQUE, [col("arr")], M.COMPLETE, "u"),
    ])
    out = _sorted_out(op, "k")
    assert out["k"] == [1, 2]
    assert out["c"] == [["a", "a"], ["b", "c"]]  # duplicates kept
    assert [sorted(u) for u in out["u"]] == [["x", "y", "z"], ["q"]]

    # two-stage: states cross a real exchange
    import tempfile, os

    import pyarrow.parquet as pq

    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime.session import Session

    td = tempfile.mkdtemp()
    path = os.path.join(td, "t.parquet")
    pq.write_table(pa.table(data), path)
    scan_node = scan_node_for_files([path], num_partitions=2)
    arr_t = T.ArrayType(T.STRING)
    partial = N.Agg(scan_node, HASH, [("k", col("k"))], [
        N.AggColumn(E.AggExpr(F.BRICKHOUSE_COMBINE_UNIQUE, [col("arr")], arr_t),
                    M.PARTIAL, "u")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([col("k")], 2))
    final = N.Agg(ex, HASH, [("k", col("k"))], [
        N.AggColumn(E.AggExpr(F.BRICKHOUSE_COMBINE_UNIQUE, [col("arr")], arr_t),
                    M.FINAL, "u")])
    plan = N.Sort(N.ShuffleExchange(final, N.SinglePartitioning(1)),
                  [E.SortOrder(col("k"))])
    with Session() as s:
        out2 = s.execute_to_table(plan).to_pydict()
    assert out2["k"] == [1, 2]
    assert [sorted(u) for u in out2["u"]] == [["x", "y", "z"], ["q"]]


def test_fused_filter_agg_matches_unfused():
    """Filter->partial-agg fusion (auto-on for CPU-effective stages) must
    be result-identical to the separate compaction path, including null
    keys, null agg args, and a predicate that rejects rows."""
    from blaze_tpu.ops.basic import FilterExec

    rng = np.random.default_rng(11)
    n = 4000
    keys = rng.integers(0, 37, n).astype("int64")
    vals = rng.integers(-1000, 1000, n).astype("int64")
    keys_pa = pa.array([None if i % 13 == 0 else int(k)
                        for i, k in enumerate(keys)], type=pa.int64())
    vals_pa = pa.array([None if i % 7 == 0 else int(v)
                        for i, v in enumerate(vals)], type=pa.int64())

    def two_stage():
        scan = mem_scan({"k": keys_pa, "v": vals_pa}, num_batches=3)
        filt = FilterExec(scan, [E.BinaryExpr(E.BinaryOp.GT, col("v"),
                                              E.Literal(-500, T.I64))])
        partial = AggExec(filt, HASH, [("k", col("k"))], [
            agg_col(F.SUM, [col("v")], M.PARTIAL, "s", T.I64),
            agg_col(F.COUNT, [], M.PARTIAL, "c"),
            agg_col(F.MIN, [col("v")], M.PARTIAL, "mn", T.I64),
        ])
        return AggExec(partial, HASH, [("k", col("k"))], [
            agg_col(F.SUM, [col("s")], M.FINAL, "s", T.I64),
            agg_col(F.COUNT, [], M.FINAL, "c"),
            agg_col(F.MIN, [col("mn")], M.FINAL, "mn", T.I64),
        ])

    outs = {}
    for fused in (True, False):
        with config_override(fused_filter_agg=fused):
            outs[fused] = _sorted_out(two_stage(), "k")
    assert outs[True] == outs[False]
    # cross-check non-null keys against a pandas oracle
    import pandas as pd

    df = pd.DataFrame({"k": keys_pa.to_pandas(), "v": vals_pa.to_pandas()})
    df = df[df.v > -500]
    g = df.groupby("k").v.agg(["sum", "count", "min"])
    got = outs[True]
    nonnull = [k for k in got["k"] if k is not None]
    assert nonnull == sorted(int(k) for k in g.index.tolist())
    for i, k in enumerate(got["k"]):
        if k is None:
            continue
        assert got["s"][i] == int(g.loc[k, "sum"])
        assert got["c"][i] == int(g.loc[k, "count"])
        assert got["mn"][i] == int(g.loc[k, "min"])


def test_partial_consolidation_single_output_batch():
    """Per-task consolidation: multi-batch device partials merge into ONE
    state batch at stream end (reference: AggTable accumulates across the
    whole partition), shrinking the exchange payload."""
    from blaze_tpu.ops.base import ExecContext, TaskContext
    from blaze_tpu.runtime.metrics import MetricNode
    from blaze_tpu.config import get_config

    rng = np.random.default_rng(3)
    n = 9000
    data = {
        "k": pa.array(rng.integers(0, 23, n), type=pa.int64()),
        "v": pa.array(rng.integers(0, 100, n), type=pa.int64()),
    }
    scan = mem_scan(data, num_batches=5)
    partial = AggExec(scan, HASH, [("k", col("k"))], [
        agg_col(F.SUM, [col("v")], M.PARTIAL, "s", T.I64),
        agg_col(F.AVG, [col("v")], M.PARTIAL, "a", T.F64),
    ])
    metrics = MetricNode("t")
    ctx = ExecContext(task=TaskContext(0, 0), conf=get_config(), resources={})
    outs = list(partial.execute(0, ctx, metrics))
    assert len(outs) == 1, [o.num_rows for o in outs]
    assert outs[0].num_rows == 23
    assert metrics.to_dict()["values"].get("partials_consolidated") == 1
    # merged states finalize to the right totals
    final = AggExec(mem_scan([[o for o in outs]], schema=outs[0].schema),
                    HASH, [("k", col("k"))], [
        agg_col(F.SUM, [col("s")], M.FINAL, "s", T.I64),
        agg_col(F.AVG, [col("a")], M.FINAL, "a", T.F64),
    ])
    out = _sorted_out(final, "k")
    import pandas as pd

    df = pd.DataFrame({"k": data["k"].to_pandas(), "v": data["v"].to_pandas()})
    g = df.groupby("k").v.agg(["sum", "mean"])
    assert out["k"] == [int(k) for k in g.index.tolist()]
    assert out["s"] == [int(x) for x in g["sum"].tolist()]
    assert out["a"] == pytest.approx(g["mean"].tolist())


def test_string_group_keys_intern_via_dictionary_codes():
    """Var-width group keys intern as vectorized dictionary-code gathers
    (SURVEY §7.4.3): correctness across batches with DIFFERENT
    dictionaries, null keys, and mixed dict/plain encodings."""
    import numpy as np
    import pyarrow as pa

    from blaze_tpu.core.batch import ColumnarBatch
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.runtime.session import Session
    from tests.util import mem_scan

    b1 = {"k": pa.array(["a", "b", None, "a"]).dictionary_encode(),
          "v": pa.array([1, 2, 3, 4], type=pa.int64())}
    # different dictionary (order + values) and a PLAIN (non-dict) batch
    b2 = {"k": pa.array(["c", "a", "b", None]).dictionary_encode(),
          "v": pa.array([10, 20, 30, 40], type=pa.int64())}
    b3 = {"k": pa.array(["b", "d", "a", "d"]),
          "v": pa.array([100, 200, 300, 400], type=pa.int64())}
    batches = [ColumnarBatch.from_arrow(pa.table(b)) for b in (b1, b2, b3)]

    from blaze_tpu.ops.agg import AggExec, AggTable
    from blaze_tpu.runtime.metrics import MetricNode

    scan = mem_scan({"k": pa.array(["a"]), "v": pa.array([0])})
    op = AggExec(scan, E.AggExecMode.HASH_AGG,
                 [("k", E.Column("k"))],
                 [N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("v")]),
                              E.AggMode.COMPLETE, "s")])
    table = AggTable(op, op.children[0].schema, None, MetricNode("t"))
    for b in batches:
        table.process_batch(b)
    # slot count: a, b, c, d, NULL = 5 distinct keys
    assert table.num_slots == 5
    sums = np.asarray(table.states[0][0][:table.num_slots])
    got = {table.key_values[0][i]: int(sums[i])
           for i in range(table.num_slots)}
    # a: 1+4+20+300, b: 2+30+100, c: 10, d: 200+400, NULL: 3+40
    assert got == {"a": 325, "b": 132, "c": 10, "d": 600, None: 43}


def test_agg_spill_with_string_keys_stays_exact():
    """Round-4 review: slot key BYTES must be a pure function of the key
    VALUE — gid-based bytes would desynchronize spill-run merging across
    table epochs and emit duplicate groups for string keys."""
    rng = np.random.default_rng(4)
    n = 30_000
    keys = [f"key{v:05d}" for v in rng.integers(0, 4000, size=n)]
    vals = rng.integers(0, 100, size=n)
    scan = mem_scan({"k": keys, "v": vals.tolist()}, num_batches=12)
    MemManager.reset()
    with config_override(memory_total=100_000, memory_fraction=1.0):
        op = AggExec(scan, HASH, [("k", col("k"))], [
            agg_col(F.SUM, [col("v")], M.COMPLETE, "s")])
        from blaze_tpu.ops.base import ExecContext
        from blaze_tpu.runtime.metrics import MetricNode

        ctx = ExecContext()
        m = MetricNode("root")
        batches = []
        for p in range(op.num_partitions()):
            batches.extend(b.to_arrow() for b in op.execute(p, ctx, m)
                           if b.num_rows)
        import pyarrow as _pa

        tbl = _pa.Table.from_batches(batches).to_pydict()
        assert m.total("spill_count") >= 1, "spill must actually fire"
    MemManager.reset()
    import collections

    expected = collections.defaultdict(int)
    for k, v in zip(keys, vals.tolist()):
        expected[k] += v
    assert len(tbl["k"]) == len(expected), "duplicate groups after spill"
    got = dict(zip(tbl["k"], tbl["s"]))
    assert got == dict(expected)


def test_null_in_dictionary_values_folds_into_null_group():
    """A DictionaryArray with None stored in its VALUES (non-null indices)
    must land in the same NULL group as index-level nulls."""
    import pyarrow as pa

    from blaze_tpu.core.batch import ColumnarBatch
    from blaze_tpu.ops.agg import AggExec, AggTable
    from blaze_tpu.runtime.metrics import MetricNode

    arr1 = pa.DictionaryArray.from_arrays(
        pa.array([0, 1, 0], type=pa.int32()), pa.array(["a", None]))
    b1 = ColumnarBatch.from_arrow(pa.table(
        {"k": arr1, "v": pa.array([1, 2, 4], type=pa.int64())}))
    b2 = ColumnarBatch.from_arrow(pa.table(
        {"k": pa.array(["a", None]).dictionary_encode(),
         "v": pa.array([10, 20], type=pa.int64())}))
    scan = mem_scan({"k": ["a"], "v": [0]})
    op = AggExec(scan, HASH, [("k", col("k"))],
                 [agg_col(F.SUM, [col("v")], M.COMPLETE, "s")])
    table = AggTable(op, op.children[0].schema, None, MetricNode("t"))
    table.process_batch(b1)
    table.process_batch(b2)
    assert table.num_slots == 2  # "a" and ONE null group
    sums = np.asarray(table.states[0][0][:2])
    got = {table.key_values[0][i]: int(sums[i]) for i in range(2)}
    assert got == {"a": 15, None: 22}

"""Input-side converter: Spark physical-plan JSON -> proto IR -> execution
(VERDICT round-1 item 4). Fixtures follow Spark's ``TreeNode.toJSON`` wire
form: pre-order node arrays with ``class``/``num-children``, expression
trees nested as such arrays inside plan fields."""

import decimal
import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.frontend import SparkPlanConverter, convert_spark_plan
from blaze_tpu.ir.protoserde import plan_from_bytes
from blaze_tpu.runtime.session import Session

SPARK = "org.apache.spark.sql"
X = f"{SPARK}.catalyst.expressions"
P = f"{SPARK}.execution"


def attr(name, dtype, eid):
    return {"class": f"{X}.AttributeReference", "num-children": 0,
            "name": name, "dataType": dtype, "nullable": True, "metadata": {},
            "exprId": {"product-class": f"{X}.ExprId", "id": eid,
                       "jvmId": "00000000-0000-0000-0000-000000000000"},
            "qualifier": []}


def lit(value, dtype):
    return {"class": f"{X}.Literal", "num-children": 0,
            "value": value, "dataType": dtype}


def binop(cls, l, r):
    return [{"class": f"{X}.{cls}", "num-children": 2, "left": 0, "right": 1}] \
        + l + r


def agg_expr(fn_cls, mode, rid, children):
    fn = [{"class": f"{X}.aggregate.{fn_cls}",
           "num-children": len(children)}] + [c for ch in children for c in ch]
    return [{"class": f"{X}.aggregate.AggregateExpression", "num-children": 1,
             "aggregateFunction": 0,
             "mode": {"object": f"{X}.aggregate.{mode}$"},
             "isDistinct": False,
             "resultId": {"product-class": f"{X}.ExprId", "id": rid,
                          "jvmId": "00000000-0000-0000-0000-000000000000"}}] + fn


def sort_order(child, asc=True):
    d = "Ascending$" if asc else "Descending$"
    n = "NullsFirst$" if asc else "NullsLast$"
    return [{"class": f"{X}.SortOrder", "num-children": 1, "child": 0,
             "direction": {"object": f"{X}.{d}"},
             "nullOrdering": {"object": f"{X}.{n}"},
             "sameOrderExpressions": []}] + child


@pytest.fixture
def store_returns(tmp_path):
    rng = np.random.default_rng(17)
    n = 20_000
    paths = []
    for p in range(2):
        amt = pa.array([decimal.Decimal(int(v)).scaleb(-2)
                        for v in rng.integers(0, 100000, n // 2)],
                       type=pa.decimal128(7, 2))
        tbl = pa.table({
            "sr_store_sk": pa.array(rng.integers(1, 50, n // 2), type=pa.int64()),
            "sr_return_amt": amt,
        })
        path = str(tmp_path / f"sr_{p}.parquet")
        pq.write_table(tbl, path)
        paths.append(path)
    return paths


def _bench_pipeline_json():
    """scan -> filter(amt > 500.00) -> partial agg -> exchange -> final agg:
    the q01 shape, as Spark serializes it."""
    scan = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
            "output": [[attr("sr_store_sk", "long", 1)],
                       [attr("sr_return_amt", "decimal(7,2)", 2)]],
            "requiredSchema": {"type": "struct", "fields": []},
            "partitionFilters": [], "dataFilters": [],
            "tableIdentifier": "store_returns"}
    filt = {"class": f"{P}.FilterExec", "num-children": 1, "condition":
            binop("GreaterThan", [attr("sr_return_amt", "decimal(7,2)", 2)],
                  [lit("500.00", "decimal(7,2)")]),
            "child": 0}
    partial = {"class": f"{P}.aggregate.HashAggregateExec", "num-children": 1,
               "requiredChildDistributionExpressions": None,
               "groupingExpressions": [[attr("sr_store_sk", "long", 1)]],
               "aggregateExpressions": [
                   agg_expr("Sum", "Partial", 10,
                            [[attr("sr_return_amt", "decimal(7,2)", 2)]])],
               "aggregateAttributes": [],
               "initialInputBufferOffset": 0,
               "resultExpressions": [], "child": 0}
    exchange = {"class": f"{P}.exchange.ShuffleExchangeExec", "num-children": 1,
                "outputPartitioning": [
                    {"class": f"{SPARK}.catalyst.plans.physical.HashPartitioning",
                     "num-children": 1, "expressions": [0],
                     "numPartitions": 4},
                    attr("sr_store_sk", "long", 1)],
                "shuffleOrigin": {"object": f"{P}.exchange.ENSURE_REQUIREMENTS$"},
                "child": 0}
    final = {"class": f"{P}.aggregate.HashAggregateExec", "num-children": 1,
             "requiredChildDistributionExpressions": [],
             "groupingExpressions": [[attr("sr_store_sk", "long", 1)]],
             "aggregateExpressions": [
                 agg_expr("Sum", "Final", 10,
                          [[attr("sr_return_amt", "decimal(7,2)", 2)]])],
             "aggregateAttributes": [],
             "initialInputBufferOffset": 0,
             "resultExpressions": [], "child": 0}
    return [final, exchange, partial, filt, scan]


def test_bench_pipeline_via_serialized_ir(store_returns):
    conv = SparkPlanConverter(tables={"store_returns": store_returns})
    blob = conv.convert_to_proto(json.dumps(_bench_pipeline_json()))
    assert isinstance(blob, bytes) and len(blob) > 50
    plan = plan_from_bytes(blob)  # arrives from "outside" as proto bytes
    with Session() as s:
        out = s.execute_to_table(plan).to_pydict()
    # oracle
    tbl = pa.concat_tables([pq.read_table(p) for p in store_returns]).to_pandas()
    tbl = tbl[tbl.sr_return_amt > decimal.Decimal("500.00")]
    g = tbl.groupby("sr_store_sk").sr_return_amt.sum()
    got = dict(zip(out["sr_store_sk#1"], out["sum#10"]))
    assert got == g.to_dict()


def test_join_query_via_converter(store_returns, tmp_path):
    stores = pa.table({
        "s_store_sk": pa.array(list(range(1, 50)), type=pa.int64()),
        "s_city": pa.array([f"city{i % 5}" for i in range(1, 50)]),
    })
    spath = str(tmp_path / "store.parquet")
    pq.write_table(stores, spath)

    scan_sr = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
               "output": [[attr("sr_store_sk", "long", 1)],
                          [attr("sr_return_amt", "decimal(7,2)", 2)]],
               "partitionFilters": [], "dataFilters": [],
               "tableIdentifier": "store_returns"}
    scan_st = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
               "output": [[attr("s_store_sk", "long", 3)],
                          [attr("s_city", "string", 4)]],
               "partitionFilters": [], "dataFilters": [],
               "tableIdentifier": "store"}
    bcast = {"class": f"{P}.exchange.BroadcastExchangeExec", "num-children": 1,
             "mode": {}, "child": 0}
    join = {"class": f"{P}.joins.BroadcastHashJoinExec", "num-children": 2,
            "leftKeys": [[attr("sr_store_sk", "long", 1)]],
            "rightKeys": [[attr("s_store_sk", "long", 3)]],
            "joinType": {"object": f"{SPARK}.catalyst.plans.Inner$"},
            "buildSide": {"object": f"{P}.joins.BuildRight$"},
            "condition": None, "left": 0, "right": 1}
    plan_json = [join, scan_sr, bcast, scan_st]

    conv = SparkPlanConverter(tables={"store_returns": store_returns,
                                      "store": [spath]})
    res = conv.convert(json.dumps(plan_json))
    assert res.fully_native, res.tags
    with Session() as s:
        out = s.execute_to_table(res.plan).to_pydict()
    n_sr = sum(pq.read_table(p).num_rows for p in store_returns)
    assert len(out["s_city#4"]) == n_sr  # every sr row matches one store


def test_window_query_via_converter(store_returns):
    scan = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
            "output": [[attr("sr_store_sk", "long", 1)],
                       [attr("sr_return_amt", "decimal(7,2)", 2)]],
            "partitionFilters": [], "dataFilters": [],
            "tableIdentifier": "store_returns"}
    exchange = {"class": f"{P}.exchange.ShuffleExchangeExec", "num-children": 1,
                "outputPartitioning": [
                    {"class": f"{SPARK}.catalyst.plans.physical.HashPartitioning",
                     "num-children": 1, "expressions": [0],
                     "numPartitions": 3},
                    attr("sr_store_sk", "long", 1)],
                "shuffleOrigin": {"object": f"{P}.exchange.ENSURE_REQUIREMENTS$"},
                "child": 0}
    sort = {"class": f"{P}.SortExec", "num-children": 1,
            "sortOrder": [sort_order([attr("sr_store_sk", "long", 1)]),
                          sort_order([attr("sr_return_amt", "decimal(7,2)", 2)])],
            "global": False, "child": 0}
    wexpr = [{"class": f"{X}.Alias", "num-children": 1, "child": 0,
              "name": "rn",
              "exprId": {"product-class": f"{X}.ExprId", "id": 20,
                         "jvmId": "00000000-0000-0000-0000-000000000000"},
              "qualifier": []},
             {"class": f"{X}.WindowExpression", "num-children": 2,
              "windowFunction": 0, "windowSpec": 1},
             {"class": f"{X}.RowNumber", "num-children": 0},
             {"class": f"{X}.WindowSpecDefinition", "num-children": 0,
              "partitionSpec": [], "orderSpec": [], "frameSpecification": {}}]
    window = {"class": f"{P}.window.WindowExec", "num-children": 1,
              "windowExpression": [wexpr],
              "partitionSpec": [[attr("sr_store_sk", "long", 1)]],
              "orderSpec": [sort_order([attr("sr_return_amt", "decimal(7,2)", 2)])],
              "child": 0}
    res = convert_spark_plan(json.dumps([window, sort, exchange, scan]),
                             tables={"store_returns": store_returns})
    assert res.fully_native, res.tags
    with Session() as s:
        out = s.execute_to_table(res.plan).to_pydict()
    # row_number restarts at 1 per store and is dense
    import collections

    seen = collections.defaultdict(int)
    by_store_rows = collections.defaultdict(list)
    for sk, rn in zip(out["sr_store_sk#1"], out["rn#20"]):
        by_store_rows[sk].append(rn)
    for sk, rns in by_store_rows.items():
        assert sorted(rns) == list(range(1, len(rns) + 1))


def test_unsupported_node_falls_back_with_tag(store_returns):
    scan = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
            "output": [[attr("sr_store_sk", "long", 1)]],
            "partitionFilters": [], "dataFilters": [],
            "tableIdentifier": "store_returns"}
    exotic = {"class": f"{P}.python.ArrowEvalPythonExec", "num-children": 1,
              "udfs": [], "child": 0}
    res = convert_spark_plan(json.dumps([exotic, scan]),
                             tables={"store_returns": store_returns})
    assert not res.fully_native
    assert res.plan is None
    kinds = dict(res.tags)
    assert kinds["FileSourceScanExec"] == "converted"  # child still converts
    assert "no converter" in kinds["ArrowEvalPythonExec"]


def test_disabled_operator_falls_back(store_returns):
    from blaze_tpu.config import config_override

    scan = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
            "output": [[attr("sr_store_sk", "long", 1)]],
            "partitionFilters": [], "dataFilters": [],
            "tableIdentifier": "store_returns"}
    filt = {"class": f"{P}.FilterExec", "num-children": 1,
            "condition": binop("GreaterThan", [attr("sr_store_sk", "long", 1)],
                               [lit(10, "long")]),
            "child": 0}
    with config_override(enabled_ops={"filter": False}):
        res = convert_spark_plan(json.dumps([filt, scan]),
                                 tables={"store_returns": store_returns})
    assert not res.fully_native
    assert any("disabled" in t for _, t in res.tags)


def test_scan_data_filters_prune(store_returns):
    scan = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
            "output": [[attr("sr_store_sk", "long", 1)],
                       [attr("sr_return_amt", "decimal(7,2)", 2)]],
            "partitionFilters": [],
            "dataFilters": [binop("LessThan", [attr("sr_store_sk", "long", 1)],
                                  [lit(5, "long")])],
            "tableIdentifier": "store_returns"}
    res = convert_spark_plan(json.dumps([scan]),
                             tables={"store_returns": store_returns})
    assert res.fully_native, res.tags
    with Session() as s:
        out = s.execute_to_table(res.plan).to_pydict()
    assert out["sr_store_sk#1"] and max(out["sr_store_sk#1"]) < 5


def test_final_agg_result_expressions_projection(store_returns):
    """Final-stage resultExpressions rename/reorder the agg output."""
    plan_json = _bench_pipeline_json()
    final = plan_json[0]
    final["resultExpressions"] = [
        [{"class": f"{X}.Alias", "num-children": 1, "child": 0, "name": "total",
          "exprId": {"product-class": f"{X}.ExprId", "id": 30,
                     "jvmId": "00000000-0000-0000-0000-000000000000"},
          "qualifier": []},
         {"class": f"{X}.AttributeReference", "num-children": 0,
          "name": "sum", "dataType": "decimal(17,2)", "nullable": True,
          "metadata": {},
          "exprId": {"product-class": f"{X}.ExprId", "id": 10,
                     "jvmId": "00000000-0000-0000-0000-000000000000"},
          "qualifier": []}],
        [attr("sr_store_sk", "long", 1)],
    ]
    res = convert_spark_plan(json.dumps(plan_json),
                             tables={"store_returns": store_returns})
    assert res.fully_native, res.tags
    with Session() as s:
        out = s.execute_to_table(res.plan).to_pydict()
    assert list(out.keys()) == ["total#30", "sr_store_sk#1"]  # renamed+reordered
    tbl = pa.concat_tables([pq.read_table(p) for p in store_returns]).to_pandas()
    tbl = tbl[tbl.sr_return_amt > decimal.Decimal("500.00")]
    g = tbl.groupby("sr_store_sk").sr_return_amt.sum()
    assert dict(zip(out["sr_store_sk#1"], out["total#30"])) == g.to_dict()


def test_non_default_window_frame_falls_back(store_returns):
    scan = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
            "output": [[attr("sr_store_sk", "long", 1)]],
            "partitionFilters": [], "dataFilters": [],
            "tableIdentifier": "store_returns"}
    wexpr = [{"class": f"{X}.Alias", "num-children": 1, "child": 0, "name": "s",
              "exprId": {"product-class": f"{X}.ExprId", "id": 21,
                         "jvmId": "00000000-0000-0000-0000-000000000000"},
              "qualifier": []},
             {"class": f"{X}.WindowExpression", "num-children": 2,
              "windowFunction": 0, "windowSpec": 1},
             {"class": f"{X}.RowNumber", "num-children": 0},
             {"class": f"{X}.WindowSpecDefinition", "num-children": 0,
              "partitionSpec": [], "orderSpec": [],
              "frameSpecification": {
                  "class": f"{X}.SpecifiedWindowFrame",
                  "frameType": {"object": f"{X}.RowFrame$"},
                  "lower": {"class": f"{X}.Literal", "value": "-2",
                            "dataType": "integer"},
                  "upper": {"object": f"{X}.CurrentRow$"}}}]
    window = {"class": f"{P}.window.WindowExec", "num-children": 1,
              "windowExpression": [wexpr],
              "partitionSpec": [[attr("sr_store_sk", "long", 1)]],
              "orderSpec": [], "child": 0}
    res = convert_spark_plan(json.dumps([window, scan]),
                             tables={"store_returns": store_returns})
    assert not res.fully_native
    assert any("frame" in t for _, t in res.tags)


def test_partition_filters_fall_back(store_returns):
    scan = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
            "output": [[attr("sr_store_sk", "long", 1)]],
            "partitionFilters": [binop("EqualTo",
                                       [attr("dt", "string", 9)],
                                       [lit("2020-01-01", "string")])],
            "dataFilters": [], "tableIdentifier": "store_returns"}
    res = convert_spark_plan(json.dumps([scan]),
                             tables={"store_returns": store_returns})
    assert not res.fully_native
    assert any("partitionFilters" in t for _, t in res.tags)


def test_existence_join_converts(store_returns, tmp_path):
    """Spark's ExistenceJoin(exprId#n) (IN/EXISTS subquery rewrite) maps to
    the engine's EXISTENCE join."""
    stores = pa.table({"s_store_sk": pa.array([1, 2, 3], type=pa.int64())})
    spath = str(tmp_path / "exist_store.parquet")
    pq.write_table(stores, spath)
    scan_sr = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
               "output": [[attr("sr_store_sk", "long", 1)]],
               "partitionFilters": [], "dataFilters": [],
               "tableIdentifier": "store_returns"}
    scan_st = {"class": f"{P}.FileSourceScanExec", "num-children": 0,
               "output": [[attr("s_store_sk", "long", 3)]],
               "partitionFilters": [], "dataFilters": [],
               "tableIdentifier": "store"}
    bcast = {"class": f"{P}.exchange.BroadcastExchangeExec", "num-children": 1,
             "mode": {}, "child": 0}
    join = {"class": f"{P}.joins.BroadcastHashJoinExec", "num-children": 2,
            "leftKeys": [[attr("sr_store_sk", "long", 1)]],
            "rightKeys": [[attr("s_store_sk", "long", 3)]],
            "joinType": {"product-class": f"{SPARK}.catalyst.plans.ExistenceJoin",
                         "exists": {"product-class": f"{X}.ExprId", "id": 99}},
            "buildSide": {"object": f"{P}.joins.BuildRight$"},
            "condition": None, "left": 0, "right": 1}
    res = convert_spark_plan(json.dumps([join, scan_sr, bcast, scan_st]),
                             tables={"store_returns": store_returns,
                                     "store": [spath]})
    assert res.fully_native, res.tags
    with Session() as s:
        out = s.execute_to_table(res.plan).to_pydict()
    keys = list(out.values())
    exists_col = [k for k in out if "exists" in k.lower() or k == list(out)[-1]]
    n_sr = sum(pq.read_table(p).num_rows for p in store_returns)
    assert len(keys[0]) == n_sr  # every probe row kept, exists flag added

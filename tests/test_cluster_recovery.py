"""Worker-death survival (ISSUE 9): lineage-based stage recovery, worker
supervision + exclusion + circuit breaker, atomic shuffle commits, and the
serve layer's typed retryable error (reference: Spark's DAGScheduler
resubmitting stages on FetchFailedException + executor blacklisting,
SURVEY.md §5.3/§5.4)."""

import glob
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.config import Config, config_override
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N
from blaze_tpu.ir import types as T
from blaze_tpu.runtime.session import Session, _QueryRun
from tests.util import CrashAlways, CrashOnce


@pytest.fixture(scope="module")
def data_files(tmp_path_factory):
    td = tmp_path_factory.mktemp("recoverydata")
    rng = np.random.default_rng(31)
    paths = []
    for p in range(2):
        n = 4000
        tbl = pa.table({
            "store": pa.array(rng.integers(1, 40, n), type=pa.int64()),
            "amt": pa.array(rng.integers(0, 1000, n), type=pa.int64()),
        })
        path = str(td / f"f{p}.parquet")
        pq.write_table(tbl, path)
        paths.append(path)
    return paths


def _agg_plan(paths, parts=2, reducers=3):
    from blaze_tpu.ops.parquet import scan_node_for_files

    scan = scan_node_for_files(paths, num_partitions=parts)
    ex = N.ShuffleExchange(scan,
                           N.HashPartitioning([E.Column("store")], reducers))
    return N.Agg(ex, E.AggExecMode.HASH_AGG, [("store", E.Column("store"))], [
        N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("amt")], T.I64),
                    E.AggMode.COMPLETE, "total")])


def _sorted_rows(pydict):
    return sorted(zip(pydict["store"], pydict["total"]))


# -- atomic commit footer -----------------------------------------------------


def test_map_output_footer_verifies(data_files, tmp_path):
    """Committed map outputs end in a valid footer; truncation (a torn
    write surviving a crash) and garbage tails read as invalid."""
    import shutil

    from blaze_tpu.runtime.recovery import (FOOTER_LEN, check_map_output,
                                            ShuffleOutputMissing,
                                            verify_map_output)

    # force the shm tier: this test pokes committed map FILES, and the
    # pool-less default (zero-copy process tier) commits in-memory segments
    # with footer-only marker files instead
    with Session(conf=Config(zero_copy_tier="shm")) as sess:
        qrun = _QueryRun(0)
        sess._tls.qrun = qrun
        sess._lower(_agg_plan(data_files))
        sess._tls.qrun = None
        datafiles = sorted(glob.glob(
            os.path.join(sess.shuffle_root, "shuffle_*", "map_*.data")))
        assert datafiles, "map stage must have committed outputs"
        for f in datafiles:
            assert verify_map_output(f) is None
            assert verify_map_output(f, full=True) is None
            assert os.path.getsize(f) > FOOTER_LEN

        # torn file: footer gone -> invalid
        torn = str(tmp_path / "torn.data")
        shutil.copy(datafiles[0], torn)
        with open(torn, "r+b") as fh:
            fh.truncate(os.path.getsize(torn) - 5)
        assert verify_map_output(torn) is not None
        with pytest.raises(ShuffleOutputMissing):
            check_map_output(torn)

        # bit flip inside the payload: only the full crc check sees it
        flipped = str(tmp_path / "flip.data")
        shutil.copy(datafiles[0], flipped)
        with open(flipped, "r+b") as fh:
            fh.seek(3)
            b = fh.read(1)
            fh.seek(3)
            fh.write(bytes([b[0] ^ 0xFF]))
        assert verify_map_output(flipped, full=True) is not None

    assert verify_map_output(datafiles[0]) == "missing"  # session closed


# -- lineage recompute (in-driver reduce side) --------------------------------


def test_missing_and_torn_map_recompute(data_files):
    """A reduce task hitting a missing or torn upstream map output triggers
    lineage recompute of exactly those maps instead of failing the query."""
    from blaze_tpu.obs.telemetry import get_registry

    # shm tier for the same reason as above: deleting/truncating committed
    # map files is the scenario under test, so the maps must write real
    # data files, not process-tier markers
    with Session(conf=Config(zero_copy_tier="shm")) as sess:
        oracle = _sorted_rows(sess.execute_to_table(
            _agg_plan(data_files)).to_pydict())

        def lower_and_files(plan):
            before = set(glob.glob(
                os.path.join(sess.shuffle_root, "shuffle_*", "map_*.data")))
            qrun = _QueryRun(0)
            sess._tls.qrun = qrun
            lowered = sess._lower(plan)
            sess._tls.qrun = None
            after = sorted(glob.glob(
                os.path.join(sess.shuffle_root, "shuffle_*", "map_*.data")))
            return lowered, [f for f in after if f not in before]

        def recovered_count():
            snap = get_registry().to_raw()
            series = snap["blaze_cluster_maps_recomputed_total"]["series"]
            return series[0]["value"] if series else 0

        # missing: the file is deleted outright
        lowered, files = lower_and_files(_agg_plan(data_files, reducers=4))
        n0 = recovered_count()
        os.remove(files[0])
        got = _sorted_rows(sess.execute_to_table(lowered).to_pydict())
        assert got == oracle
        assert recovered_count() == n0 + 1

        # torn: the footer is cut off mid-file
        lowered, files = lower_and_files(_agg_plan(data_files, reducers=5))
        with open(files[1], "r+b") as fh:
            fh.truncate(max(0, os.path.getsize(files[1]) - 7))
        got = _sorted_rows(sess.execute_to_table(lowered).to_pydict())
        assert got == oracle
        assert recovered_count() == n0 + 2


# -- worker supervision / exclusion / breaker ---------------------------------


def test_exclusion_list_and_death_dedup():
    """_note_death counts one death per worker generation, excludes the
    slot (TTL'd), and the liveness guarantee keeps an all-excluded pool
    serving."""
    from blaze_tpu.runtime.cluster import WorkerPool

    pool = WorkerPool(2)
    try:
        w0, w1 = pool.workers
        assert pool._note_death(w0, "test") is True
        assert pool._note_death(w0, "test") is False  # same generation
        assert pool.deaths_total == 1
        assert 0 in pool.excluded_workers()
        assert pool._sit_out(w0) is True  # w1 is eligible
        assert pool._note_death(w1, "test") is True
        assert pool._sit_out(w0) is False  # everyone excluded: keep serving
        # TTL expiry clears the exclusion on the next check
        with pool._mu:
            pool._excluded[0] = time.monotonic() - 1.0
        assert pool._sit_out(w0) is False
        assert 0 not in pool.excluded_workers()
    finally:
        pool.close()


@pytest.mark.slow
def test_circuit_breaker_aborts_stage(data_files, tmp_path):
    """More worker deaths than fault_max_worker_deaths within one stage
    aborts with the typed WorkerPoolBroken instead of retrying forever."""
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime.cluster import WorkerPoolBroken

    scan = scan_node_for_files(data_files, num_partitions=2)
    proj = N.Projection(scan, [
        E.Column("store"),
        E.PyUDF(CrashOnce(str(tmp_path / "breaker.marker")),
                [E.Column("store")], T.I64, "crash1"),
    ], ["store", "crashed"])
    plan = N.ShuffleExchange(proj,
                             N.HashPartitioning([E.Column("store")], 2))
    conf = Config(fault_max_worker_deaths=0)
    with Session(conf=conf, num_worker_processes=2) as s:
        with pytest.raises(WorkerPoolBroken):
            s.execute_to_table(plan)


# -- chaos: kill a real worker mid-stage --------------------------------------


@pytest.mark.quick
def test_chaos_smoke_one_kill(data_files, tmp_path):
    """Quick-tier chaos smoke: one deterministic worker death mid-map-stage
    (CrashOnce hard-kills its host on first call); the query's result is
    bit-identical to the unkilled in-driver run, the death is counted, and
    the lost worker has a retrievable incident bundle."""
    from blaze_tpu.obs.dump import list_incidents, load_incident
    from blaze_tpu.obs.telemetry import get_registry
    from blaze_tpu.ops.parquet import scan_node_for_files

    def plan(crash_marker=None):
        scan = scan_node_for_files(data_files, num_partitions=2)
        # "crashed" is store passed through the crash UDF (identity after
        # the kill) — and the agg CONSUMES it, so pruning can't drop it
        crashed = E.Column("store") if crash_marker is None else \
            E.PyUDF(CrashOnce(crash_marker), [E.Column("store")], T.I64,
                    "crash1")
        proj = N.Projection(scan,
                            [E.Column("store"), E.Column("amt"), crashed],
                            ["store", "amt", "crashed"])
        ex = N.ShuffleExchange(
            proj, N.HashPartitioning([E.Column("store")], 2))
        return N.Agg(ex, E.AggExecMode.HASH_AGG,
                     [("store", E.Column("store"))], [
            N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("amt")],
                                  T.I64), E.AggMode.COMPLETE, "total"),
            N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("crashed")],
                                  T.I64), E.AggMode.COMPLETE, "chk")])

    with Session() as s_local:
        expect = _sorted_rows(s_local.execute_to_table(
            plan()).to_pydict())

    marker = str(tmp_path / "chaos.marker")
    incident_dir = str(tmp_path / "incidents")
    conf = Config(incident_dir=incident_dir)

    def deaths():
        snap = get_registry().to_raw()
        series = snap["blaze_cluster_worker_deaths_total"]["series"]
        return series[0]["value"] if series else 0

    d0 = deaths()
    with Session(conf=conf, num_worker_processes=2) as s:
        got = _sorted_rows(s.execute_to_table(
            plan(crash_marker=marker)).to_pydict())
    assert os.path.exists(marker), "the chaos kill must actually have fired"
    assert got == expect, "result after worker death differs from clean run"
    assert deaths() > d0
    lost = [i for i in list_incidents(conf) if i["kind"] == "worker_lost"]
    assert lost, "every killed worker writes an incident bundle"
    bundle = load_incident(lost[0]["id"], conf)
    assert bundle["extra"]["context"] in ("mid_task", "heartbeat",
                                          "push_shared")
    assert "wid" in bundle["extra"]


@pytest.mark.slow
def test_kill_worker_mid_stage_bit_identical(data_files):
    """An asynchronous hard kill (the chaos-soak primitive) mid-query: the
    task retries elsewhere, the worker is excluded + respawned, and the
    result matches the unkilled run exactly."""
    plan = _agg_plan(data_files, parts=6, reducers=4)
    with Session() as s_local:
        expect = _sorted_rows(s_local.execute_to_table(plan).to_pydict())
    with Session(num_worker_processes=2) as s:
        killer = threading.Timer(0.4, lambda: s.pool.kill_worker(0))
        killer.start()
        try:
            got = _sorted_rows(s.execute_to_table(plan).to_pydict())
        finally:
            killer.cancel()
        deaths = s.pool.deaths_total
    assert got == expect
    # the timer may fire before, during, or (rarely, tiny stage) after the
    # stage window — but the kill itself always lands and is always noticed
    assert deaths >= 1


# -- RSS: attempt-id dedup on re-commit ---------------------------------------


@pytest.mark.quick
def test_celeborn_recommit_attempt_dedup():
    """A re-committed map (retry after a worker death) must not double-serve:
    MapperEnd's first-wins commit pins the winning attempt id, and fetches
    serve only that attempt's pushed blocks (runtime/rss.py
    CelebornShuffleClient.writer_for_map)."""
    from blaze_tpu.runtime.rss import (CelebornShuffleClient, RssClient,
                                       RssServer)

    srv = RssServer()
    try:
        c = RssClient(srv.sock_path, app="recommit-test", shuffle_id=9)
        sc = CelebornShuffleClient(c, num_mappers=1, num_partitions=1)
        sc.register()
        w1 = sc.writer_for_map(0, attempt_id=1)
        w1.write(0, b"attempt1-payload")
        w1.flush()
        sc.commit_files()
        first = sc.fetch(0)
        assert first, "committed attempt must serve"
        # the retry re-commits the same map under a fresh attempt id
        w2 = sc.writer_for_map(0, attempt_id=2)
        w2.write(0, b"attempt2-payload")
        w2.flush()
        sc.commit_files()
        assert sc.fetch(0) == first, "re-commit must not replace or add"
        # distinct writers drew distinct attempt ids by default too
        wa, wb = sc.writer_for_map(0), sc.writer_for_map(0)
        assert wa.attempt_id != wb.attempt_id
    finally:
        srv.close()


# -- serve: typed retryable error after retry exhaustion ----------------------


@pytest.mark.slow
def test_serve_worker_loss_is_typed_retryable(data_files, tmp_path):
    """A query whose workers keep dying exhausts the retry budget and fails
    with QueryRetryable (retryable=True, incident bundle id attached); the
    scheduler releases its memory exactly once and keeps serving."""
    from blaze_tpu.obs.dump import load_incident
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.serve import QueryRetryable, QueryScheduler

    scan = scan_node_for_files(data_files, num_partitions=2)
    proj = N.Projection(scan, [
        E.Column("store"),
        E.PyUDF(CrashAlways(), [E.Column("store")], T.I64, "crashN"),
    ], ["store", "crashed"])
    doomed = N.ShuffleExchange(proj,
                               N.HashPartitioning([E.Column("store")], 2))
    conf = Config(incident_dir=str(tmp_path / "incidents"))
    with Session(conf=conf, num_worker_processes=2) as sess:
        with QueryScheduler(sess, max_concurrent=1) as sched:
            h = sched.submit(doomed, label="doomed")
            with pytest.raises(QueryRetryable) as ei:
                h.result(timeout=120)
            err = ei.value
            assert err.retryable is True
            assert err.incident_id, "the retryable error carries forensics"
            bundle = load_incident(err.incident_id, conf)
            assert bundle is not None
            assert bundle["label"] == "doomed"
            # memory group released exactly once, nothing leaked
            assert h._released is True
            mm = MemManager._instance
            assert h.mem_group not in mm.stats()["reservations"]
            # the pool still serves: a clean query right after succeeds
            h2 = sched.submit(_agg_plan(data_files), label="after")
            table = h2.result(timeout=120)
            assert table.num_rows > 0


# -- failpoint-driven degradation (ISSUE 12) ----------------------------------
#
# Paranoid-mode corruption, resource-exhaustion fallbacks, hard task
# timeouts, and the serve layer's transparent auto-retry — each proven
# bit-identical against an uninjected oracle on a real 2-worker pool.


@pytest.fixture(autouse=True)
def _disarm_failpoints():
    """No failpoint armed in one test may leak into the next (the registry
    is process-global; Session.__init__ arms from conf)."""
    from blaze_tpu.runtime import failpoints

    failpoints.disarm()
    yield
    failpoints.disarm()
    failpoints.unhang()


@pytest.mark.parametrize("tier", ["shm", "ipc"])
def test_corrupt_frame_recovers_bit_identical(data_files, tier):
    """Paranoid mode (full crc verification) + the frame.decode failpoint
    flipping committed payload bytes on disk: corruption is detected as a
    crc mismatch, routed into lineage recompute like a lost output, and the
    result matches the clean run exactly — on both the shm and ipc tiers,
    over a real 2-worker pool."""
    from blaze_tpu.obs.telemetry import get_registry

    with Session() as s_clean:
        oracle = _sorted_rows(s_clean.execute_to_table(
            _agg_plan(data_files, parts=2, reducers=3)).to_pydict())

    def recomputed():
        snap = get_registry().to_raw()
        series = snap["blaze_cluster_maps_recomputed_total"]["series"]
        return series[0]["value"] if series else 0

    n0 = recomputed()
    # triggers count per PROCESS: every2:x1 makes each worker corrupt the
    # 2nd output it verifies, exactly once, wherever the schedule lands it.
    # config_override (not just Session(conf=...)) because the paranoia
    # level must also reach the DRIVER's global-config readers (providers,
    # lineage recompute), not only the conf shipped to workers.
    with config_override(zero_copy_tier=tier, shuffle_verify_checksum=True,
                         failpoints="frame.decode=corrupt:every2:x1",
                         failpoint_seed=12):
        with Session(num_worker_processes=2) as sess:
            got = _sorted_rows(sess.execute_to_table(
                _agg_plan(data_files, parts=2, reducers=3)).to_pydict())
    assert got == oracle, "corrupted frames must recompute, not change rows"
    assert recomputed() > n0, "corruption must route through lineage"


def test_shm_enospc_degrades_to_spill_tier(data_files):
    """A shm-tier commit hitting ENOSPC mid-query degrades that map output
    to the spill dir behind a redirect marker: same rows, the
    shuffle_tier_degraded tripwire fires, and the degraded copies are
    reclaimed with the query (no leaks outlive the session)."""
    with Session() as s_clean:
        oracle = _sorted_rows(s_clean.execute_to_table(
            _agg_plan(data_files, parts=2, reducers=3)).to_pydict())

    # every1: triggers count per PROCESS, and each pool worker only commits
    # a couple of maps — firing on every commit keeps this deterministic
    conf = Config(zero_copy_tier="shm",
                  failpoints="shm.commit=enospc:every1", failpoint_seed=12)
    with Session(conf=conf, num_worker_processes=2) as sess:
        got = _sorted_rows(sess.execute_to_table(
            _agg_plan(data_files, parts=2, reducers=3)).to_pydict())
        degraded = sess.metrics.total("shuffle_tier_degraded")
        spill_dir = sess.conf.spill_dir
    assert got == oracle, "degraded outputs must serve identical rows"
    assert degraded > 0, "the enospc failpoint must exercise the degrade"
    leaks = glob.glob(os.path.join(spill_dir, "degraded_shuffle", "*"))
    assert not leaks, f"degraded copies leaked: {leaks}"


@pytest.mark.slow
def test_hung_task_times_out_and_reroutes(data_files):
    """task_timeout_s on top of speculation: a task hung past the hard
    timeout is cancelled at the process level, charged to the retry budget,
    rerouted, and the hung worker is marked suspect — the query still
    returns the exact clean-run rows."""
    from blaze_tpu.obs.telemetry import get_registry

    with Session() as s_clean:
        oracle = _sorted_rows(s_clean.execute_to_table(
            _agg_plan(data_files, parts=4, reducers=3)).to_pydict())

    def timed_out():
        # the counter has no series until its first inc — tolerate absence
        snap = get_registry().to_raw()
        series = snap.get("blaze_cluster_tasks_timed_out_total", {}).get(
            "series", [])
        return series[0]["value"] if series else 0

    n0 = timed_out()
    conf = Config(task_timeout_s=1.5, fault_exclusion_ttl_s=2.0,
                  failpoints="worker.task=hang:every2:600",
                  failpoint_seed=12)
    t0 = time.monotonic()
    with Session(conf=conf, num_worker_processes=2) as sess:
        got = _sorted_rows(sess.execute_to_table(
            _agg_plan(data_files, parts=4, reducers=3)).to_pydict())
        deaths = sess.pool.deaths_total
    wall = time.monotonic() - t0
    assert got == oracle
    assert timed_out() > n0, "the hard timeout must have fired"
    assert deaths >= 1, "a timed-out attempt kills its worker"
    assert wall < 120, "hung attempts must not stall the query"


class CrashFirstNTasks:
    """Crash fixture UDF: hard-kills the hosting WORKER on each call until
    ``n`` crash markers exist, then passes through. Lets a test exhaust the
    pool's per-task retry budget on the FIRST query attempt and succeed on
    the serve layer's transparent re-execution."""

    def __init__(self, marker_dir, n):
        self.marker_dir = marker_dir
        self.n = n

    def __call__(self, x):
        import os

        if os.environ.get("BLAZE_WORKER_PLATFORM") is None:
            return x  # in-driver recompute paths survive
        os.makedirs(self.marker_dir, exist_ok=True)
        done = len(os.listdir(self.marker_dir))
        if done < self.n:
            with open(os.path.join(self.marker_dir, f"crash_{done}"), "w"):
                pass
            os._exit(9)
        return x


@pytest.mark.slow
def test_serve_auto_retry_hides_worker_loss(data_files, tmp_path):
    """A query whose first execution exhausts the pool retry budget is
    transparently re-executed by the scheduler (backoff + jitter inside the
    deadline): the CLIENT sees a clean result, never QueryRetryable, and
    the handle records the retry history."""
    from blaze_tpu.obs.telemetry import get_registry
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.serve import QueryScheduler

    scan = scan_node_for_files(data_files, num_partitions=2)
    proj = N.Projection(scan, [
        E.Column("store"),
        # n=5 over 2 tasks: one task is guaranteed 3 crashing attempts,
        # exhausting the pool's max_task_retries=2 budget on the FIRST
        # execution — which is what forces a serve-layer retry
        E.PyUDF(CrashFirstNTasks(str(tmp_path / "crashes"), 5),
                [E.Column("store")], T.I64, "crashN"),
    ], ["store", "crashed"])
    plan = N.ShuffleExchange(proj,
                             N.HashPartitioning([E.Column("store")], 2))

    def retries():
        # the counter has no series until its first inc — tolerate absence
        snap = get_registry().to_raw()
        series = snap.get("blaze_serve_retries_total", {}).get("series", [])
        return series[0]["value"] if series else 0

    n0 = retries()
    conf = Config(incident_dir=str(tmp_path / "incidents"),
                  fault_max_worker_deaths=8, fault_exclusion_ttl_s=1.0)
    with Session(conf=conf, num_worker_processes=2) as sess:
        with QueryScheduler(sess, max_concurrent=1) as sched:
            h = sched.submit(plan, label="flaky")
            table = h.result(timeout=180)  # no QueryRetryable raised
    assert table.num_rows > 0
    assert h.retries, "the handle must record its transparent retries"
    assert retries() > n0
    assert h.snapshot().get("retries") == len(h.retries)

// Native host-side kernels for blaze_tpu.
//
// The reference implements its entire engine in Rust; here the TPU executes
// the columnar compute (JAX/XLA) and this library accelerates the host-side
// runtime hot paths the reference also keeps native: byte-plane transpose
// for shuffle/spill compression (reference: io/batch_serde.rs TransposeOpt),
// spark-exact murmur3/xxhash64 over variable-length byte arrays (reference:
// hash/mur.rs, hash/xxhash.rs — bit-exactness mandatory for partition
// routing), and zstd frame codecs. Exposed via a plain C ABI consumed with
// ctypes (pybind11 is not available in this environment).

#include <cstdint>
#include <cstring>
#include <cstddef>

#ifdef HAVE_ZSTD
#include <zstd.h>
#endif

#define EXPORT extern "C" __attribute__((visibility("default")))

// ---------------------------------------------------------------------------
// byte-plane transpose: (n, itemsize) <-> (itemsize, n), cache-blocked
// ---------------------------------------------------------------------------

EXPORT void bt_transpose(const uint8_t* src, uint8_t* dst, size_t n,
                         size_t itemsize, int forward) {
  constexpr size_t BLOCK = 512;
  if (forward) {  // row-major values -> byte planes
    for (size_t b = 0; b < n; b += BLOCK) {
      size_t end = b + BLOCK < n ? b + BLOCK : n;
      for (size_t k = 0; k < itemsize; ++k) {
        uint8_t* d = dst + k * n + b;
        const uint8_t* s = src + b * itemsize + k;
        for (size_t i = b; i < end; ++i, ++d, s += itemsize) *d = *s;
      }
    }
  } else {  // byte planes -> row-major values
    for (size_t b = 0; b < n; b += BLOCK) {
      size_t end = b + BLOCK < n ? b + BLOCK : n;
      for (size_t k = 0; k < itemsize; ++k) {
        const uint8_t* s = src + k * n + b;
        uint8_t* d = dst + b * itemsize + k;
        for (size_t i = b; i < end; ++i, ++s, d += itemsize) *d = *s;
      }
    }
  }
}

// ---------------------------------------------------------------------------
// spark murmur3 (x86_32) over variable-length byte strings
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t mmh3_mix_k1(uint32_t k1) {
  k1 *= 0xcc9e2d51u;
  k1 = rotl32(k1, 15);
  return k1 * 0x1b873593u;
}

static inline uint32_t mmh3_mix_h1(uint32_t h1, uint32_t k1) {
  h1 ^= k1;
  h1 = rotl32(h1, 13);
  return h1 * 5 + 0xe6546b64u;
}

static inline uint32_t mmh3_fmix(uint32_t h1, uint32_t len) {
  h1 ^= len;
  h1 ^= h1 >> 16;
  h1 *= 0x85ebca6bu;
  h1 ^= h1 >> 13;
  h1 *= 0xc2b2ae35u;
  h1 ^= h1 >> 16;
  return h1;
}

// spark hashUnsafeBytes: 4-byte LE words, then each tail byte SIGN-EXTENDED
// through a full mix round.
EXPORT void bt_murmur3_bytes(const int64_t* offsets, const uint8_t* data,
                             const uint32_t* seeds, uint32_t* out, size_t n) {
  for (size_t i = 0; i < n; ++i) {
    const uint8_t* p = data + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    int64_t aligned = len & ~int64_t(3);
    uint32_t h1 = seeds[i];
    for (int64_t j = 0; j < aligned; j += 4) {
      uint32_t k;
      std::memcpy(&k, p + j, 4);  // little-endian host
      h1 = mmh3_mix_h1(h1, mmh3_mix_k1(k));
    }
    for (int64_t j = aligned; j < len; ++j) {
      int32_t b = static_cast<int8_t>(p[j]);  // signed byte
      h1 = mmh3_mix_h1(h1, mmh3_mix_k1(static_cast<uint32_t>(b)));
    }
    out[i] = mmh3_fmix(h1, static_cast<uint32_t>(len));
  }
}

// ---------------------------------------------------------------------------
// xxhash64 over variable-length byte strings (spark XXH64)
// ---------------------------------------------------------------------------

static const uint64_t P1 = 0x9E3779B185EBCA87ull;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4Full;
static const uint64_t P3 = 0x165667B19E3779F9ull;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ull;
static const uint64_t P5 = 0x27D4EB2F165667C5ull;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t xxh_round(uint64_t acc, uint64_t k) {
  return rotl64(acc + k * P2, 31) * P1;
}

EXPORT void bt_xxh64_bytes(const int64_t* offsets, const uint8_t* data,
                           const uint64_t* seeds, uint64_t* out, size_t n) {
  for (size_t i = 0; i < n; ++i) {
    const uint8_t* p = data + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    uint64_t seed = seeds[i];
    const uint8_t* end = p + len;
    uint64_t h;
    if (len >= 32) {
      uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
      const uint8_t* limit = end - 32;
      do {
        uint64_t k;
        std::memcpy(&k, p, 8); v1 = xxh_round(v1, k);
        std::memcpy(&k, p + 8, 8); v2 = xxh_round(v2, k);
        std::memcpy(&k, p + 16, 8); v3 = xxh_round(v3, k);
        std::memcpy(&k, p + 24, 8); v4 = xxh_round(v4, k);
        p += 32;
      } while (p <= limit);
      h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
      h = (h ^ xxh_round(0, v1)) * P1 + P4;
      h = (h ^ xxh_round(0, v2)) * P1 + P4;
      h = (h ^ xxh_round(0, v3)) * P1 + P4;
      h = (h ^ xxh_round(0, v4)) * P1 + P4;
    } else {
      h = seed + P5;
    }
    h += static_cast<uint64_t>(len);
    while (p + 8 <= end) {
      uint64_t k;
      std::memcpy(&k, p, 8);
      h = rotl64(h ^ xxh_round(0, k), 27) * P1 + P4;
      p += 8;
    }
    if (p + 4 <= end) {
      uint32_t k;
      std::memcpy(&k, p, 4);
      h = rotl64(h ^ (uint64_t(k) * P1), 23) * P2 + P3;
      p += 4;
    }
    while (p < end) {
      h = rotl64(h ^ (uint64_t(*p) * P5), 11) * P1;
      ++p;
    }
    h = (h ^ (h >> 33)) * P2;
    h = (h ^ (h >> 29)) * P3;
    out[i] = h ^ (h >> 32);
  }
}

// ---------------------------------------------------------------------------
// zstd frame codec
// ---------------------------------------------------------------------------

EXPORT int64_t bt_zstd_compress_bound(int64_t src_len) {
#ifdef HAVE_ZSTD
  return static_cast<int64_t>(ZSTD_compressBound(static_cast<size_t>(src_len)));
#else
  return -1;
#endif
}

EXPORT int64_t bt_zstd_compress(const uint8_t* src, int64_t src_len,
                                uint8_t* dst, int64_t dst_cap, int level) {
#ifdef HAVE_ZSTD
  size_t r = ZSTD_compress(dst, static_cast<size_t>(dst_cap), src,
                           static_cast<size_t>(src_len), level);
  if (ZSTD_isError(r)) return -1;
  return static_cast<int64_t>(r);
#else
  (void)src; (void)src_len; (void)dst; (void)dst_cap; (void)level;
  return -1;
#endif
}

EXPORT int64_t bt_zstd_decompress(const uint8_t* src, int64_t src_len,
                                  uint8_t* dst, int64_t dst_cap) {
#ifdef HAVE_ZSTD
  size_t r = ZSTD_decompress(dst, static_cast<size_t>(dst_cap), src,
                             static_cast<size_t>(src_len));
  if (ZSTD_isError(r)) return -1;
  return static_cast<int64_t>(r);
#else
  (void)src; (void)src_len; (void)dst; (void)dst_cap;
  return -1;
#endif
}

// ---------------------------------------------------------------------------
// lz4 block codec (reference supports lz4 + zstd shuffle/spill codecs,
// common/ipc_compression.rs:34-260). The image ships liblz4.so.1 without
// headers, so the three stable-ABI entry points are declared here and
// resolved with dlopen at first use.
// ---------------------------------------------------------------------------

#include <dlfcn.h>

namespace {
typedef int (*lz4_bound_fn)(int);
typedef int (*lz4_compress_fn)(const char*, char*, int, int);
typedef int (*lz4_decompress_fn)(const char*, char*, int, int);

struct Lz4Api {
  lz4_bound_fn bound = nullptr;
  lz4_compress_fn compress = nullptr;
  lz4_decompress_fn decompress = nullptr;
  bool ok = false;
};

const Lz4Api& lz4_api() {
  static Lz4Api api = [] {
    Lz4Api a;
    void* h = dlopen("liblz4.so.1", RTLD_NOW | RTLD_GLOBAL);
    if (!h) h = dlopen("liblz4.so", RTLD_NOW | RTLD_GLOBAL);
    if (h) {
      a.bound = reinterpret_cast<lz4_bound_fn>(dlsym(h, "LZ4_compressBound"));
      a.compress = reinterpret_cast<lz4_compress_fn>(
          dlsym(h, "LZ4_compress_default"));
      a.decompress = reinterpret_cast<lz4_decompress_fn>(
          dlsym(h, "LZ4_decompress_safe"));
      a.ok = a.bound && a.compress && a.decompress;
    }
    return a;
  }();
  return api;
}
}  // namespace

EXPORT int bt_lz4_available() { return lz4_api().ok ? 1 : 0; }

EXPORT int64_t bt_lz4_compress_bound(int64_t src_len) {
  const Lz4Api& a = lz4_api();
  if (!a.ok || src_len > INT32_MAX) return -1;
  return a.bound(static_cast<int>(src_len));
}

EXPORT int64_t bt_lz4_compress(const uint8_t* src, int64_t src_len,
                               uint8_t* dst, int64_t dst_cap) {
  const Lz4Api& a = lz4_api();
  if (!a.ok || src_len > INT32_MAX || dst_cap > INT32_MAX) return -1;
  int r = a.compress(reinterpret_cast<const char*>(src),
                     reinterpret_cast<char*>(dst),
                     static_cast<int>(src_len), static_cast<int>(dst_cap));
  return r > 0 ? r : -1;
}

EXPORT int64_t bt_lz4_decompress(const uint8_t* src, int64_t src_len,
                                 uint8_t* dst, int64_t dst_cap) {
  const Lz4Api& a = lz4_api();
  if (!a.ok || src_len > INT32_MAX || dst_cap > INT32_MAX) return -1;
  int r = a.decompress(reinterpret_cast<const char*>(src),
                       reinterpret_cast<char*>(dst),
                       static_cast<int>(src_len), static_cast<int>(dst_cap));
  return r >= 0 ? r : -1;
}

EXPORT int bt_version() { return 2; }

file(REMOVE_RECURSE
  "CMakeFiles/blaze_native.dir/src/blaze_native.cc.o"
  "CMakeFiles/blaze_native.dir/src/blaze_native.cc.o.d"
  "libblaze_native.pdb"
  "libblaze_native.so"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/blaze_native.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()

CMakeFiles/blaze_native.dir/src/blaze_native.cc.o: \
 /root/repo/native/src/blaze_native.cc /usr/include/stdc-predef.h \
 /usr/include/c++/12/cstdint \
 /usr/include/x86_64-linux-gnu/c++/12/bits/c++config.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/os_defines.h \
 /usr/include/features.h /usr/include/features-time64.h \
 /usr/include/x86_64-linux-gnu/bits/wordsize.h \
 /usr/include/x86_64-linux-gnu/bits/timesize.h \
 /usr/include/x86_64-linux-gnu/sys/cdefs.h \
 /usr/include/x86_64-linux-gnu/bits/long-double.h \
 /usr/include/x86_64-linux-gnu/gnu/stubs.h \
 /usr/include/x86_64-linux-gnu/gnu/stubs-64.h \
 /usr/include/x86_64-linux-gnu/c++/12/bits/cpu_defines.h \
 /usr/include/c++/12/pstl/pstl_config.h \
 /usr/lib/gcc/x86_64-linux-gnu/12/include/stdint.h /usr/include/stdint.h \
 /usr/include/x86_64-linux-gnu/bits/libc-header-start.h \
 /usr/include/x86_64-linux-gnu/bits/types.h \
 /usr/include/x86_64-linux-gnu/bits/typesizes.h \
 /usr/include/x86_64-linux-gnu/bits/time64.h \
 /usr/include/x86_64-linux-gnu/bits/wchar.h \
 /usr/include/x86_64-linux-gnu/bits/stdint-intn.h \
 /usr/include/x86_64-linux-gnu/bits/stdint-uintn.h \
 /usr/include/c++/12/cstring /usr/include/string.h \
 /usr/lib/gcc/x86_64-linux-gnu/12/include/stddef.h \
 /usr/include/x86_64-linux-gnu/bits/types/locale_t.h \
 /usr/include/x86_64-linux-gnu/bits/types/__locale_t.h \
 /usr/include/strings.h /usr/include/c++/12/cstddef /usr/include/zstd.h \
 /usr/lib/gcc/x86_64-linux-gnu/12/include/limits.h \
 /usr/lib/gcc/x86_64-linux-gnu/12/include/syslimits.h \
 /usr/include/limits.h /usr/include/x86_64-linux-gnu/bits/posix1_lim.h \
 /usr/include/x86_64-linux-gnu/bits/local_lim.h \
 /usr/include/linux/limits.h \
 /usr/include/x86_64-linux-gnu/bits/pthread_stack_min-dynamic.h \
 /usr/include/x86_64-linux-gnu/bits/posix2_lim.h \
 /usr/include/x86_64-linux-gnu/bits/xopen_lim.h \
 /usr/include/x86_64-linux-gnu/bits/uio_lim.h

# Empty compiler generated dependencies file for blaze_native.
# This may be replaced when dependencies are built.

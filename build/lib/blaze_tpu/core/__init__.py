from blaze_tpu.core.batch import ColumnarBatch, DeviceColumn, HostColumn, Column  # noqa: F401

"""Spark-exact hash functions: Murmur3_x86_32 (seed 42) and XXH64 (seed 42).

Bit-exactness is mandatory — hash partition routing and hash joins depend on
it (reference: ``datafusion-ext-commons/src/spark_hash.rs``, ``hash/mur.rs``,
``hash/xxhash.rs``; golden vectors in ``spark_hash.rs`` tests are generated
with Spark's ``Murmur3Hash(...).eval()`` / ``XxHash64(...).eval()``).

Semantics (matching Spark's ``hashUnsafeBytes``/``hashLong``/``hashInt``):

- multi-column hashing chains: each row's running hash is the seed for the
  next column; NULL values leave the hash unchanged
- fixed-width values hash as their little-endian bytes: int8/16/32/date/bool
  promote to 4-byte int; int64/timestamp/double are 8 bytes; float is 4
- decimal(p<=18) hashes its unscaled int64 as 8 LE bytes (Spark hashLong)
- byte strings: 4-byte LE words, then each tail byte *sign-extended* through
  a full mix round (murmur3); xxhash64 follows the standard XXH64 tail rules
  with unsigned bytes

Two implementations: jax (device columns, vectorized uint32/uint64 ops that
wrap mod 2^32/2^64 — VPU-friendly, no MXU needed) and numpy (host var-width
columns).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593

# --------------------------------------------------------------------------
# Murmur3_x86_32 — jax (device)
# --------------------------------------------------------------------------


def _u32(x):
    return x.astype(jnp.uint32)


def _rotl32(x, r: int):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    k1 = k1 * jnp.uint32(_C1)
    k1 = _rotl32(k1, 15)
    return k1 * jnp.uint32(_C2)


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h1, length):
    h1 = h1 ^ length
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def murmur3_int32(values, seeds):
    """hashInt: values int32-like array, seeds uint32 array -> uint32."""
    w = _u32(values.astype(jnp.int32))
    return _fmix(_mix_h1(_u32(seeds), _mix_k1(w)), jnp.uint32(4))


def murmur3_int64(values, seeds):
    """hashLong: low word then high word."""
    v = values.astype(jnp.int64)
    lo = _u32(v.astype(jnp.uint64) & jnp.uint64(0xFFFFFFFF))
    hi = _u32((v.astype(jnp.uint64) >> jnp.uint64(32)) & jnp.uint64(0xFFFFFFFF))
    h = _mix_h1(_u32(seeds), _mix_k1(lo))
    h = _mix_h1(h, _mix_k1(hi))
    return _fmix(h, jnp.uint32(8))


def murmur3_update_column(hashes, data, validity, dtype_kind: str):
    """One column's contribution to the running row hashes (uint32).

    dtype_kind: "i32" (int8/16/32/date/bool promoted), "i64"
    (int64/timestamp/decimal), "f32", "f64".
    """
    if dtype_kind == "f32":
        word = data.view(jnp.int32) if data.dtype == jnp.float32 else data.astype(jnp.float32).view(jnp.int32)
        new = murmur3_int32(word, hashes)
    elif dtype_kind == "f64":
        word = data.view(jnp.int64) if data.dtype == jnp.float64 else data.astype(jnp.float64).view(jnp.int64)
        new = murmur3_int64(word, hashes)
    elif dtype_kind == "i64":
        new = murmur3_int64(data, hashes)
    else:
        new = murmur3_int32(data, hashes)
    return jnp.where(validity, new, hashes)


# --------------------------------------------------------------------------
# Murmur3_x86_32 — numpy (host, incl. variable-length bytes)
# --------------------------------------------------------------------------


def _np_rotl32(x, r):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _np_mix_k1(k1):
    k1 = k1 * np.uint32(_C1)
    k1 = _np_rotl32(k1, 15)
    return k1 * np.uint32(_C2)


def _np_mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _np_rotl32(h1, 13)
    return h1 * np.uint32(5) + np.uint32(0xE6546B64)


def _np_fmix(h1, length):
    h1 = h1 ^ length
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = h1 * np.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = h1 * np.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> np.uint32(16))


def murmur3_int32_np(values, seeds):
    w = values.astype(np.int32).view(np.uint32)
    return _np_fmix(_np_mix_h1(seeds.astype(np.uint32), _np_mix_k1(w)), np.uint32(4))


def murmur3_int64_np(values, seeds):
    v = values.astype(np.int64).view(np.uint64)
    lo = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (v >> np.uint64(32)).astype(np.uint32)
    h = _np_mix_h1(seeds.astype(np.uint32), _np_mix_k1(lo))
    h = _np_mix_h1(h, _np_mix_k1(hi))
    return _np_fmix(h, np.uint32(8))


def murmur3_bytes_np(offsets: np.ndarray, data: np.ndarray, seeds: np.ndarray) -> np.ndarray:
    """Spark hashUnsafeBytes over n variable-length byte strings.

    offsets: int64 (n+1), data: uint8 concatenated bytes, seeds: uint32 (n,).
    Uses the native C++ kernel when built (native/src/blaze_native.cc);
    numpy fallback is vectorized per word position, then per tail byte
    (tail bytes are *signed*, each through a full mix round).
    """
    from blaze_tpu.utils import native

    out = native.murmur3_bytes(offsets, data, seeds)
    if out is not None:
        return out
    offsets = np.asarray(offsets, dtype=np.int64)
    data = np.asarray(data, dtype=np.uint8)
    starts = offsets[:-1]
    lengths = (offsets[1:] - starts).astype(np.int64)
    h = seeds.astype(np.uint32).copy()
    aligned = lengths & ~np.int64(3)
    max_aligned = int(aligned.max(initial=0))
    for wstart in range(0, max_aligned, 4):
        mask = aligned > wstart
        idx = starts[mask] + wstart
        k = (
            data[idx].astype(np.uint32)
            | (data[idx + 1].astype(np.uint32) << np.uint32(8))
            | (data[idx + 2].astype(np.uint32) << np.uint32(16))
            | (data[idx + 3].astype(np.uint32) << np.uint32(24))
        )
        h[mask] = _np_mix_h1(h[mask], _np_mix_k1(k))
    tail_len = lengths - aligned
    for t in range(3):
        mask = tail_len > t
        if not mask.any():
            break
        idx = starts[mask] + aligned[mask] + t
        b = data[idx].view(np.int8).astype(np.int32).view(np.uint32)
        h[mask] = _np_mix_h1(h[mask], _np_mix_k1(b))
    return _np_fmix(h, lengths.astype(np.uint32))


# --------------------------------------------------------------------------
# XXH64 — jax (device) and numpy (host)
# --------------------------------------------------------------------------

_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5


def _rotl64(x, r: int):
    return (x << r) | (x >> (64 - r))


def xxhash64_int64(values, seeds):
    """XXH64 of the 8 LE bytes of each int64, per-row uint64 seeds."""
    u = lambda c: jnp.uint64(c)  # noqa: E731
    v = values.astype(jnp.int64).view(jnp.uint64)
    acc = seeds.astype(jnp.uint64) + u(_P5) + u(8)
    k1 = _rotl64(v * u(_P2), 31) * u(_P1)
    acc = acc ^ k1
    acc = _rotl64(acc, 27) * u(_P1) + u(_P4)
    acc = (acc ^ (acc >> 33)) * u(_P2)
    acc = (acc ^ (acc >> 29)) * u(_P3)
    return acc ^ (acc >> 32)


def xxhash64_int32(values, seeds):
    """XXH64 of the 4 LE bytes of each int32 (Spark promotes small ints)."""
    u = lambda c: jnp.uint64(c)  # noqa: E731
    v = values.astype(jnp.int32).view(jnp.uint32).astype(jnp.uint64)
    acc = seeds.astype(jnp.uint64) + u(_P5) + u(4)
    acc = acc ^ (v * u(_P1))
    acc = _rotl64(acc, 23) * u(_P2) + u(_P3)
    acc = (acc ^ (acc >> 33)) * u(_P2)
    acc = (acc ^ (acc >> 29)) * u(_P3)
    return acc ^ (acc >> 32)


def xxhash64_update_column(hashes, data, validity, dtype_kind: str):
    if dtype_kind == "f32":
        new = xxhash64_int32(data.view(jnp.int32), hashes)
    elif dtype_kind == "f64":
        new = xxhash64_int64(data.view(jnp.int64), hashes)
    elif dtype_kind == "i64":
        new = xxhash64_int64(data, hashes)
    else:
        new = xxhash64_int32(data, hashes)
    return jnp.where(validity, new, hashes)


def _np_rotl64(x, r):
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def xxhash64_int64_np(values, seeds):
    with np.errstate(over="ignore"):
        v = values.astype(np.int64).view(np.uint64)
        acc = seeds.astype(np.uint64) + np.uint64(_P5) + np.uint64(8)
        k1 = _np_rotl64(v * np.uint64(_P2), 31) * np.uint64(_P1)
        acc = acc ^ k1
        acc = _np_rotl64(acc, 27) * np.uint64(_P1) + np.uint64(_P4)
        acc = (acc ^ (acc >> np.uint64(33))) * np.uint64(_P2)
        acc = (acc ^ (acc >> np.uint64(29))) * np.uint64(_P3)
        return acc ^ (acc >> np.uint64(32))


def xxhash64_int32_np(values, seeds):
    with np.errstate(over="ignore"):
        v = values.astype(np.int32).view(np.uint32).astype(np.uint64)
        acc = seeds.astype(np.uint64) + np.uint64(_P5) + np.uint64(4)
        acc = acc ^ (v * np.uint64(_P1))
        acc = _np_rotl64(acc, 23) * np.uint64(_P2) + np.uint64(_P3)
        acc = (acc ^ (acc >> np.uint64(33))) * np.uint64(_P2)
        acc = (acc ^ (acc >> np.uint64(29))) * np.uint64(_P3)
        return acc ^ (acc >> np.uint64(32))


def xxhash64_bytes_np(offsets: np.ndarray, data: np.ndarray, seeds: np.ndarray) -> np.ndarray:
    """Standard XXH64 over n variable-length byte strings (Spark XXH64).

    Native C++ kernel when built; numpy fallback runs the stripe loop
    (32-byte blocks with 4 lanes), then 8-byte chunks, 4-byte chunk, single
    unsigned bytes, then the final avalanche.
    """
    from blaze_tpu.utils import native

    out = native.xxh64_bytes(offsets, data, seeds)
    if out is not None:
        return out
    offsets = np.asarray(offsets, dtype=np.int64)
    data = np.asarray(data, dtype=np.uint8)
    starts = offsets[:-1]
    lengths = (offsets[1:] - starts).astype(np.int64)
    n = len(starts)
    u64 = np.uint64

    def get_u64(idx):
        out = np.zeros(len(idx), dtype=np.uint64)
        for b in range(8):
            out |= data[idx + b].astype(np.uint64) << u64(8 * b)
        return out

    def get_u32(idx):
        out = np.zeros(len(idx), dtype=np.uint64)
        for b in range(4):
            out |= data[idx + b].astype(np.uint64) << u64(8 * b)
        return out

    with np.errstate(over="ignore"):
        seeds = seeds.astype(np.uint64)
        acc = np.empty(n, dtype=np.uint64)
        long_mask = lengths >= 32
        # --- stripe phase for strings >= 32 bytes
        if long_mask.any():
            lm = long_mask
            v1 = seeds[lm] + u64(_P1) + u64(_P2)
            v2 = seeds[lm] + u64(_P2)
            v3 = seeds[lm].copy()
            v4 = seeds[lm] - u64(_P1)
            nstripes = (lengths[lm] >> 5).astype(np.int64)
            max_stripes = int(nstripes.max())
            pos = starts[lm].copy()
            for s in range(max_stripes):
                m = nstripes > s
                base = pos[m] + 32 * s

                def rnd(v, off):
                    k = get_u64(base + off)
                    return _np_rotl64(v + k * u64(_P2), 31) * u64(_P1)

                v1[m] = rnd(v1[m], 0)
                v2[m] = rnd(v2[m], 8)
                v3[m] = rnd(v3[m], 16)
                v4[m] = rnd(v4[m], 24)
            h = (
                _np_rotl64(v1, 1)
                + _np_rotl64(v2, 7)
                + _np_rotl64(v3, 12)
                + _np_rotl64(v4, 18)
            )

            def merge(h, v):
                h = h ^ (_np_rotl64(v * u64(_P2), 31) * u64(_P1))
                return h * u64(_P1) + u64(_P4)

            h = merge(h, v1)
            h = merge(h, v2)
            h = merge(h, v3)
            h = merge(h, v4)
            acc[lm] = h
        acc[~long_mask] = seeds[~long_mask] + u64(_P5)
        acc += lengths.astype(np.uint64)

        # --- tail: position after stripes
        pos = starts + (lengths & ~np.int64(31))
        rem = lengths & np.int64(31)
        # 8-byte chunks
        max_chunks = int((rem >> 3).max(initial=0))
        for c in range(max_chunks):
            m = (rem >> 3) > c
            k = get_u64(pos[m] + 8 * c)
            k = _np_rotl64(k * u64(_P2), 31) * u64(_P1)
            acc[m] = _np_rotl64(acc[m] ^ k, 27) * u64(_P1) + u64(_P4)
        pos = pos + (rem & ~np.int64(7))
        rem = rem & np.int64(7)
        # 4-byte chunk
        m = rem >= 4
        if m.any():
            k = get_u32(pos[m])
            acc[m] = _np_rotl64(acc[m] ^ (k * u64(_P1)), 23) * u64(_P2) + u64(_P3)
            pos = pos + np.where(m, 4, 0)
            rem = rem - np.where(m, 4, 0)
        # single bytes (unsigned)
        for t in range(3):
            m = rem > t
            if not m.any():
                break
            b = data[pos[m] + t].astype(np.uint64)
            acc[m] = _np_rotl64(acc[m] ^ (b * u64(_P5)), 11) * u64(_P1)
        # avalanche
        acc = (acc ^ (acc >> u64(33))) * u64(_P2)
        acc = (acc ^ (acc >> u64(29))) * u64(_P3)
        return acc ^ (acc >> u64(32))


# --------------------------------------------------------------------------
# Batch-level hashing (mixed device/host columns)
# --------------------------------------------------------------------------


def _dtype_is_fixed(dt) -> bool:
    from blaze_tpu.ir import types as T

    if isinstance(dt, T.DecimalType):
        return dt.fits_int64
    return dt.is_fixed_width


def _host_fixed_words(arr, dt):
    """pa fixed-width array -> (word array for hashing, validity)."""
    import pyarrow as pa

    from blaze_tpu.ir import types as T

    validity = ~np.asarray(arr.is_null()) if arr.null_count else np.ones(len(arr), bool)
    fill = False if pa.types.is_boolean(arr.type) else 0
    vals = arr.fill_null(fill).to_numpy(zero_copy_only=False)
    if np.issubdtype(vals.dtype, np.datetime64):
        if isinstance(dt, T.DateType):
            vals = vals.astype("datetime64[D]").view(np.int64).astype(np.int32)
        else:
            vals = vals.astype("datetime64[us]").view(np.int64)
    elif isinstance(dt, T.DecimalType):
        vals = np.array([int(d.scaleb(dt.scale)) if d is not None else 0
                         for d in arr.to_pylist()], dtype=np.int64)
    elif vals.dtype == np.bool_:
        vals = vals.astype(np.int32)
    elif vals.dtype == np.float64:
        vals = vals.view(np.int64)
    elif vals.dtype == np.float32:
        vals = vals.view(np.int32)
    return vals, validity


def _dtype_kind(dt) -> str:
    from blaze_tpu.ir import types as T

    if isinstance(dt, (T.Float32Type,)):
        return "f32"
    if isinstance(dt, (T.Float64Type,)):
        return "f64"
    if isinstance(dt, (T.Int64Type, T.TimestampType, T.DecimalType)):
        return "i64"
    return "i32"


@functools.partial(jax.jit, static_argnames=("kinds", "is64"))
def _hash_device_run(h, datas, valids, kinds, is64):
    """Fold a run of device columns into the running hashes in one dispatch."""
    for d, v, kind in zip(datas, valids, kinds):
        if is64:
            h = xxhash64_update_column(h, d, v, kind)
        else:
            h = murmur3_update_column(h, d, v, kind)
    return h


def hash_batch(columns, num_rows: int, capacity: int, seed: int = 42,
               algo: str = "murmur3"):
    """Hash a list of core Columns (device or host) into per-row hashes.

    Returns a numpy array of length ``num_rows``: int32 for murmur3, int64
    for xxhash64. Device columns are hashed on device; host (string/binary)
    columns force a host pass over the running hashes.
    """
    from blaze_tpu.core.batch import DeviceColumn, HostColumn

    is64 = algo == "xxhash64"
    h_dev: Optional[jnp.ndarray] = None
    h_host: Optional[np.ndarray] = None

    def to_host():
        nonlocal h_host, h_dev
        if h_host is None:
            h_host = np.asarray(h_dev)[:num_rows].copy() if h_dev is not None else np.full(
                num_rows, seed, dtype=np.uint64 if is64 else np.uint32
            )
            h_dev = None
        return h_host

    def to_dev():
        nonlocal h_host, h_dev
        if h_dev is None:
            if h_host is not None:
                buf = np.zeros(capacity, dtype=h_host.dtype)
                buf[:num_rows] = h_host
                h_dev = jnp.asarray(buf)
                h_host = None
            else:
                h_dev = jnp.full(capacity, seed, dtype=jnp.uint64 if is64 else jnp.uint32)
        return h_dev

    # consecutive device columns hash in ONE jitted dispatch (the eager
    # per-op murmur3 chain was a profiler hotspot: ~15 dispatches per column)
    i = 0
    while i < len(columns):
        col = columns[i]
        if isinstance(col, DeviceColumn):
            run = []
            while i < len(columns) and isinstance(columns[i], DeviceColumn):
                run.append(columns[i])
                i += 1
            h_dev = _hash_device_run(
                to_dev(),
                tuple(c.data for c in run),
                tuple(c.validity for c in run),
                tuple(_dtype_kind(c.dtype) for c in run),
                is64)
            continue
        i += 1
        if isinstance(col, HostColumn):
            h = to_host()
            arr = col.array
            import pyarrow as pa

            from blaze_tpu.ir import types as T

            if pa.types.is_decimal(arr.type):
                # Spark hashes wide decimals (p > 18) as the minimal
                # big-endian two's-complement bytes of the unscaled
                # BigInteger (java BigInteger.toByteArray)
                scale = arr.type.scale
                chunks, validity = [], []
                for d in arr.to_pylist():
                    if d is None:
                        validity.append(False)
                        chunks.append(b"")
                    else:
                        validity.append(True)
                        u = int(d.scaleb(scale))
                        nbytes = (u + (u < 0)).bit_length() // 8 + 1
                        chunks.append(u.to_bytes(nbytes, "big", signed=True))
                offsets = np.zeros(len(chunks) + 1, dtype=np.int64)
                np.cumsum([len(b) for b in chunks], out=offsets[1:])
                data = np.frombuffer(b"".join(chunks), dtype=np.uint8)
                validity = np.array(validity, dtype=bool)
                if is64:
                    new = xxhash64_bytes_np(offsets, data, h)
                else:
                    new = murmur3_bytes_np(offsets, data, h)
                h_host = np.where(validity, new, h)
                continue
            if _dtype_is_fixed(col.dtype):
                # fixed-width values living on host (agg keys, f64-on-tpu)
                vals, validity = _host_fixed_words(arr, col.dtype)
                kind = _dtype_kind(col.dtype)
                if is64:
                    new = (xxhash64_int64_np(vals, h) if kind in ("i64", "f64")
                           else xxhash64_int32_np(vals, h))
                else:
                    new = (murmur3_int64_np(vals, h) if kind in ("i64", "f64")
                           else murmur3_int32_np(vals, h))
                h_host = np.where(validity, new, h)
                continue
            if not (pa.types.is_large_string(arr.type) or pa.types.is_large_binary(arr.type)):
                arr = arr.cast(pa.large_binary())
            offsets = np.frombuffer(arr.buffers()[1], dtype=np.int64,
                                    count=len(arr) + 1, offset=arr.offset * 8)
            dbuf = arr.buffers()[2]
            data = (np.frombuffer(dbuf, dtype=np.uint8) if dbuf is not None
                    else np.zeros(0, dtype=np.uint8))
            validity = ~np.asarray(arr.is_null()) if arr.null_count else np.ones(len(arr), bool)
            if is64:
                new = xxhash64_bytes_np(offsets, data, h)
            else:
                new = murmur3_bytes_np(offsets, data, h)
            h_host = np.where(validity, new, h)

    if h_host is not None:
        out = h_host
    else:
        out = np.asarray(h_dev)[:num_rows]
    return out.view(np.int64 if is64 else np.int32)

"""Spark-exact decimal128 arithmetic on the scaled-int64 fast path.

The reference implements these as ``spark_check_overflow``,
``spark_make_decimal``, ``spark_unscaled_value`` and decimal binary arithmetic
with precision promotion (``datafusion-ext-functions/src/spark_make_decimal.rs``
etc., promotion rules mirrored from ``NativeConverters.scala:521-697``).

On device a decimal(p<=18, s) value is its unscaled int64; all ops below
detect int64 overflow explicitly and turn affected rows into NULL (matching
Spark's non-ANSI behavior of nulling on decimal overflow).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

POW10 = np.array([10**i for i in range(19)], dtype=np.int64)


def pow10(k):
    """10**k as int64 for 0 <= k <= 18 (static python int k)."""
    return jnp.int64(10 ** int(k))


def check_overflow(data, validity, precision: int):
    """Null out rows where |unscaled| >= 10^precision (spark_check_overflow)."""
    if precision >= 19:
        return data, validity
    bound = pow10(precision)
    ok = (data < bound) & (data > -bound)
    return data, validity & ok


def add(l_data, l_valid, r_data, r_valid):
    """Same-scale add with int64 overflow -> null."""
    s = l_data + r_data
    # overflow iff operands share sign and sum flips sign
    ovf = ((l_data >= 0) == (r_data >= 0)) & ((s >= 0) != (l_data >= 0)) & (l_data != 0)
    return s, l_valid & r_valid & ~ovf


def sub(l_data, l_valid, r_data, r_valid):
    return add(l_data, l_valid, -r_data, r_valid)


def _mul_overflows(a, b):
    p = a * b
    bad = (a != 0) & ((p // jnp.where(a == 0, 1, a)) != b)
    return p, bad


def mul(l_data, l_valid, r_data, r_valid, rescale_down: int = 0):
    """Multiply unscaled values (result scale = s1+s2), optionally divide by
    10^rescale_down with HALF_UP rounding when the bounded result type has a
    smaller scale."""
    p, bad = _mul_overflows(l_data, r_data)
    validity = l_valid & r_valid & ~bad
    if rescale_down > 0:
        p = _div_half_up(p, pow10(rescale_down))
    return p, validity


def _div_half_up(num, den):
    """Integer division with HALF_UP rounding (den > 0)."""
    q = num // den
    r = num - q * den
    # python-style floor division: adjust toward java truncation + half-up
    neg = num < 0
    q_trunc = jnp.where(neg & (r != 0), q + 1, q)
    r_trunc = num - q_trunc * den
    bump = (2 * jnp.abs(r_trunc)) >= den
    return jnp.where(bump, q_trunc + jnp.where(neg, -1, 1), q_trunc)


def div(l_data, l_valid, r_data, r_valid, scale_adjust: int):
    """Divide: result_unscaled = l * 10^scale_adjust / r, HALF_UP, where
    scale_adjust = result_scale - s1 + s2 (so result has result_scale).
    Division by zero -> null (Spark non-ANSI)."""
    m = pow10(scale_adjust) if scale_adjust >= 0 else jnp.int64(1)
    num, bad = _mul_overflows(l_data, m)
    if scale_adjust < 0:
        num = _div_half_up(l_data, pow10(-scale_adjust))
        bad = jnp.zeros_like(l_valid)
    den_zero = r_data == 0
    den = jnp.where(den_zero, 1, r_data)
    q = _div_half_up(num * jnp.where(den < 0, -1, 1), jnp.abs(den))
    return q, l_valid & r_valid & ~bad & ~den_zero


def rescale(data, validity, from_scale: int, to_scale: int, to_precision: int):
    """Change scale with HALF_UP rounding; overflow -> null (decimal cast)."""
    if to_scale > from_scale:
        m = pow10(to_scale - from_scale)
        out, bad = _mul_overflows(data, m)
        validity = validity & ~bad
    elif to_scale < from_scale:
        out = _div_half_up(data, pow10(from_scale - to_scale))
    else:
        out = data
    return check_overflow(out, validity, to_precision)

"""Spark-semantics casts (non-ANSI: invalid conversions yield NULL).

Reference: the spark-compatible cast in
``datafusion-ext-commons/src/arrow/cast.rs`` (float->int uses Java truncation
semantics with NaN->0 and saturation; decimal<->numeric via unscaled values;
string parsing trims and coerces failures to NULL).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from blaze_tpu.exprs import decimal as dec
from blaze_tpu.ir import types as T

_INT_TYPES = (T.Int8Type, T.Int16Type, T.Int32Type, T.Int64Type)
_FLOAT_TYPES = (T.Float32Type, T.Float64Type)

_US_PER_DAY = 86_400_000_000


def _is_int(dt):
    return isinstance(dt, _INT_TYPES)


def _is_float(dt):
    return isinstance(dt, _FLOAT_TYPES)


def cast_dev(data, validity, frm: T.DataType, to: T.DataType):
    """Cast a device column; returns (data, validity)."""
    if frm == to:
        return data, validity
    # decimal source
    if isinstance(frm, T.DecimalType):
        if isinstance(to, T.DecimalType):
            return dec.rescale(data, validity, frm.scale, to.scale, to.precision)
        if _is_int(to):
            scaled = data // dec.pow10(frm.scale)
            r = data - scaled * dec.pow10(frm.scale)
            trunc = jnp.where((r != 0) & (data < 0), scaled + 1, scaled)
            return trunc.astype(to.np_dtype), validity
        if _is_float(to):
            return (data.astype(jnp.float64) / float(10**frm.scale)).astype(to.np_dtype), validity
        if isinstance(to, T.BooleanType):
            return data != 0, validity
        raise NotImplementedError(f"cast decimal -> {to!r}")
    # decimal target
    if isinstance(to, T.DecimalType):
        if _is_int(frm) or isinstance(frm, T.BooleanType):
            v = data.astype(jnp.int64)
            if to.scale > 0:
                out, bad = dec._mul_overflows(v, dec.pow10(to.scale))
                validity = validity & ~bad
            else:
                out = v
            return dec.check_overflow(out, validity, to.precision)
        if _is_float(frm):
            scaled = data.astype(jnp.float64) * float(10**to.scale)
            rounded = jnp.where(scaled >= 0, jnp.floor(scaled + 0.5), jnp.ceil(scaled - 0.5))
            ok = jnp.isfinite(scaled) & (jnp.abs(rounded) < float(2**63))
            out = jnp.where(ok, rounded, 0.0).astype(jnp.int64)
            return dec.check_overflow(out, validity & ok, to.precision)
        raise NotImplementedError(f"cast {frm!r} -> decimal")
    # float -> int: Java semantics (NaN -> 0, saturate at bounds). XLA's
    # float->int convert is undefined out of range and off-by-one at the
    # boundary, so mask out-of-range lanes before converting.
    if _is_float(frm) and _is_int(to):
        info = np.iinfo(to.np_dtype)
        x = jnp.trunc(jnp.nan_to_num(data.astype(jnp.float64), nan=0.0))
        max_f, min_f = float(info.max), float(info.min)
        in_bounds = (x > min_f) & (x < max_f)
        xi = jnp.where(in_bounds, x, 0.0).astype(to.np_dtype)
        out = jnp.where(x >= max_f, info.max, jnp.where(x <= min_f, info.min, xi))
        return out, validity
    # bool target
    if isinstance(to, T.BooleanType):
        return data != 0, validity
    # date/timestamp
    if isinstance(frm, T.DateType) and isinstance(to, T.TimestampType):
        return data.astype(jnp.int64) * _US_PER_DAY, validity
    if isinstance(frm, T.TimestampType) and isinstance(to, T.DateType):
        return (data // _US_PER_DAY).astype(jnp.int32), validity
    if isinstance(frm, T.TimestampType) and _is_int(to):
        # spark: timestamp -> long is seconds
        return (data // 1_000_000).astype(to.np_dtype), validity
    if _is_int(frm) and isinstance(to, T.TimestampType):
        return data.astype(jnp.int64) * 1_000_000, validity
    # plain numeric/bool widening or wrapping narrow (java cast wraps ints)
    if to.np_dtype is not None:
        return data.astype(to.np_dtype), validity
    raise NotImplementedError(f"device cast {frm!r} -> {to!r}")


def cast_host(arr: pa.Array, frm: T.DataType, to: T.DataType, try_mode: bool) -> pa.Array:
    """Cast a host (arrow) array with Spark non-ANSI semantics."""
    at = T.to_arrow_type(to)
    if frm == to:
        return arr
    if isinstance(frm, T.StringType):
        return _cast_from_string(arr, to, at)
    if isinstance(to, T.StringType):
        return _cast_to_string(arr, frm)
    try:
        return pc.cast(arr, at)
    except pa.ArrowInvalid:
        if not try_mode:
            raise
        out = [None] * len(arr)
        return pa.array(out, type=at)


def _cast_from_string(arr: pa.Array, to: T.DataType, at) -> pa.Array:
    import pandas as pd

    trimmed = pc.utf8_trim_whitespace(arr)
    if isinstance(to, (T.Int8Type, T.Int16Type, T.Int32Type, T.Int64Type,
                       T.Float32Type, T.Float64Type)):
        s = trimmed.to_pandas()
        num = pd.to_numeric(s, errors="coerce")
        vals = num.to_numpy(dtype="float64")
        input_null = pd.isna(s).to_numpy()
        if isinstance(to, _INT_TYPES):
            # exact integer parse first — the float64 path corrupts > 2^53
            info = np.iinfo(to.np_dtype)
            out = np.zeros(len(s), dtype=to.np_dtype)
            mask = np.ones(len(s), dtype=bool)
            for i, v in enumerate(s):
                if v is None or (isinstance(v, float) and v != v):
                    continue
                try:
                    iv = int(v)
                except ValueError:
                    f = vals[i]
                    if np.isnan(f) or f > info.max or f < info.min:
                        continue
                    iv = int(np.trunc(f))
                if info.min <= iv <= info.max:
                    out[i] = iv
                    mask[i] = False
            return pa.Array.from_pandas(out, mask=mask, type=at)
        # float target: "nan" parses to NaN (valid); other failures -> null
        mask = np.isnan(vals) & ~input_null & ~_is_nan_str(s)
        return pa.Array.from_pandas(vals.astype(to.np_dtype), mask=mask | input_null, type=at)
    if isinstance(to, T.BooleanType):
        lowered = pc.utf8_lower(trimmed)
        out = []
        for v in lowered.to_pylist():
            if v is None:
                out.append(None)
            elif v in ("t", "true", "y", "yes", "1"):
                out.append(True)
            elif v in ("f", "false", "n", "no", "0"):
                out.append(False)
            else:
                out.append(None)
        return pa.array(out, type=at)
    if isinstance(to, (T.DecimalType, T.DateType, T.TimestampType)):
        out = []
        for v in trimmed.to_pylist():
            if v is None:
                out.append(None)
                continue
            try:
                if isinstance(to, T.DecimalType):
                    from decimal import Decimal, ROUND_HALF_UP

                    d = Decimal(v).quantize(Decimal(1).scaleb(-to.scale), rounding=ROUND_HALF_UP)
                    if len(d.as_tuple().digits) - to.scale > to.precision - to.scale:
                        out.append(None)
                    else:
                        out.append(d)
                elif isinstance(to, T.DateType):
                    import datetime

                    out.append(datetime.date.fromisoformat(v[:10]))
                else:
                    out.append(pa.scalar(v, type=pa.timestamp("us")).as_py())
            except Exception:
                out.append(None)
        return pa.array(out, type=at)
    if isinstance(to, T.BinaryType):
        return trimmed.cast(pa.large_binary())
    raise NotImplementedError(f"cast string -> {to!r}")


def _is_nan_str(s):
    return (s.str.strip().str.lower() == "nan").fillna(False).to_numpy()


def _cast_to_string(arr: pa.Array, frm: T.DataType) -> pa.Array:
    if isinstance(frm, T.BooleanType):
        return pc.cast(arr, pa.large_utf8())
    if isinstance(frm, (T.Float32Type, T.Float64Type)):
        # java Double.toString writes "1.0", arrow writes "1" — fix up integers
        out = []
        for v in arr.to_pylist():
            if v is None:
                out.append(None)
            elif v != v:
                out.append("NaN")
            elif v in (float("inf"), float("-inf")):
                out.append("Infinity" if v > 0 else "-Infinity")
            elif float(v) == int(v) and abs(v) < 1e16:
                out.append(f"{int(v)}.0")
            else:
                out.append(repr(float(v)))
        return pa.array(out, type=pa.large_utf8())
    return pc.cast(arr, pa.large_utf8())

"""Filesystem provider: scheme-dispatched IO for scans, sinks and spills.

Reference: ``datafusion-ext-commons/src/hadoop_fs.rs:28-120`` — FsProvider/
Fs/FsDataInputWrapper route every file operation through the JVM's Hadoop
FileSystem, so the native engine reads HDFS/S3/... transparently. The
standalone analogue: paths with a URL scheme (``s3://``, ``gs://``,
``memory://`` ...) dispatch through fsspec; bare paths stay on fast posix
calls. pyarrow's dataset/parquet readers accept fsspec filesystems
directly, so scans keep their C++ IO path."""

from __future__ import annotations

import os
import re
from typing import BinaryIO, List, Optional, Tuple

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")

# test/instrumentation hook: fs_instances[scheme] -> fsspec filesystem
_REGISTERED = {}


def register_filesystem(scheme: str, fs) -> None:
    """Pin a pre-built fsspec filesystem for a scheme (e.g. a moto S3 stub
    or an in-memory fs shared with a test)."""
    _REGISTERED[scheme] = fs


def has_scheme(path: str) -> bool:
    return bool(_SCHEME_RE.match(str(path))) and not str(path).startswith("file://")


def get_fs(path: str) -> Tuple[Optional[object], str]:
    """(fsspec filesystem or None for posix, in-fs path)."""
    p = str(path)
    if p.startswith("file://"):
        return None, p[len("file://"):]
    if not has_scheme(p):
        return None, p
    scheme = p.split("://", 1)[0]
    if scheme in _REGISTERED:
        return _REGISTERED[scheme], p.split("://", 1)[1]
    import fsspec

    fs, fpath = fsspec.core.url_to_fs(p)
    return fs, fpath


def open_input(path: str) -> BinaryIO:
    fs, p = get_fs(path)
    if fs is None:
        return open(p, "rb")
    return fs.open(p, "rb")


def open_output(path: str) -> BinaryIO:
    fs, p = get_fs(path)
    if fs is None:
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        return open(p, "wb")
    return fs.open(p, "wb")


def getsize(path: str) -> int:
    fs, p = get_fs(path)
    if fs is None:
        return os.path.getsize(p)
    return int(fs.size(p))


def exists(path: str) -> bool:
    fs, p = get_fs(path)
    if fs is None:
        return os.path.exists(p)
    return bool(fs.exists(p))


def makedirs(path: str) -> None:
    fs, p = get_fs(path)
    if fs is None:
        os.makedirs(p, exist_ok=True)
    else:
        fs.makedirs(p, exist_ok=True)


def listdir(path: str) -> List[str]:
    """Child paths with the original scheme preserved."""
    fs, p = get_fs(path)
    if fs is None:
        return [os.path.join(p, n) for n in sorted(os.listdir(p))]
    scheme = str(path).split("://", 1)[0]
    return [f"{scheme}://{c}" for c in sorted(fs.ls(p, detail=False))]


def arrow_filesystem(path: str):
    """(pyarrow-compatible filesystem or None, in-fs path) — what
    pyarrow.dataset / ParquetFile want."""
    fs, p = get_fs(path)
    if fs is None:
        return None, p
    from pyarrow.fs import FSSpecHandler, PyFileSystem

    return PyFileSystem(FSSpecHandler(fs)), p

"""Spark ``DataType`` JSON -> engine types.

Spark serializes types inside TreeNode JSON either as short strings
("integer", "decimal(7,2)") or as structured objects ({"type": "struct",
"fields": [...]}) — `org.apache.spark.sql.types.DataType.fromJson` is the
JVM-side inverse. Reference analogue: ``NativeConverters.convertDataType``
(spark-extension/src/main/scala/.../NativeConverters.scala:117)."""

from __future__ import annotations

import re
from typing import Union

from blaze_tpu.ir import types as T

_SIMPLE = {
    "null": T.NULL,
    "boolean": T.BOOL,
    "byte": T.I8,
    "tinyint": T.I8,
    "short": T.I16,
    "smallint": T.I16,
    "integer": T.I32,
    "int": T.I32,
    "long": T.I64,
    "bigint": T.I64,
    "float": T.F32,
    "double": T.F64,
    "string": T.STRING,
    "binary": T.BINARY,
    "date": T.DATE,
    "timestamp": T.TIMESTAMP,
    "timestamp_ntz": T.TIMESTAMP,
}

_DECIMAL_RE = re.compile(r"decimal\((\d+),\s*(-?\d+)\)")


def from_spark_json(dt: Union[str, dict]) -> T.DataType:
    if isinstance(dt, str):
        s = dt.strip().lower()
        if s in _SIMPLE:
            return _SIMPLE[s]
        m = _DECIMAL_RE.fullmatch(s)
        if m:
            return T.DecimalType(int(m.group(1)), int(m.group(2)))
        if s == "decimal":
            return T.DecimalType(10, 0)
        raise NotImplementedError(f"spark type {dt!r}")
    kind = dt.get("type")
    if kind == "struct":
        fields = tuple(
            T.StructField(f["name"], from_spark_json(f["type"]),
                          bool(f.get("nullable", True)))
            for f in dt.get("fields", ()))
        return T.StructType(fields)
    if kind == "array":
        return T.ArrayType(from_spark_json(dt["elementType"]),
                           bool(dt.get("containsNull", True)))
    if kind == "map":
        return T.MapType(from_spark_json(dt["keyType"]),
                         from_spark_json(dt["valueType"]),
                         bool(dt.get("valueContainsNull", True)))
    if kind == "udt":
        return from_spark_json(dt.get("sqlType", "string"))
    raise NotImplementedError(f"spark type {dt!r}")

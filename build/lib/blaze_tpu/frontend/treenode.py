"""Decoder for Spark's ``TreeNode.toJSON`` wire form.

Spark serializes any TreeNode (physical plans AND expressions) as a JSON
array of node objects in PRE-ORDER: each object carries ``class`` (the JVM
class name), ``num-children``, and its constructor fields; the node's
children are the next ``num-children`` subtrees of the array, depth-first.
Constructor fields that ARE children (e.g. ``left``/``right`` of Add) hold
the child's ordinal instead of the subtree. Nested expression trees inside a
plan field are themselves serialized as such arrays (possibly doubly nested
for sequences-of-sequences like Expand projections).

This module rebuilds the tree shape; interpretation of classes/fields lives
in frontend/exprs.py + frontend/converter.py (reference analogue of the
conversion layer: AuronConverters.scala / NativeConverters.scala)."""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Union


@dataclasses.dataclass
class TreeNode:
    cls: str           # fully-qualified JVM class
    fields: Dict[str, Any]
    children: List["TreeNode"]

    @property
    def name(self) -> str:
        """Class base name (after the last dot, '$' suffixes stripped)."""
        return self.cls.rsplit(".", 1)[-1].rstrip("$")

    def field(self, key: str, default=None):
        return self.fields.get(key, default)


def decode(nodes: Union[str, List[dict]]) -> TreeNode:
    """One pre-order node array -> tree."""
    if isinstance(nodes, str):
        nodes = json.loads(nodes)
    if not isinstance(nodes, list) or not nodes:
        raise ValueError("expected a non-empty TreeNode array")
    pos = 0

    def build() -> TreeNode:
        nonlocal pos
        obj = nodes[pos]
        pos += 1
        n = int(obj.get("num-children", 0))
        fields = {k: v for k, v in obj.items()
                  if k not in ("class", "num-children")}
        children = [build() for _ in range(n)]
        return TreeNode(obj["class"], fields, children)

    root = build()
    if pos != len(nodes):
        raise ValueError(
            f"dangling nodes in TreeNode array: consumed {pos} of {len(nodes)}")
    return root


def is_tree_array(v: Any) -> bool:
    return (isinstance(v, list) and v and isinstance(v[0], dict)
            and "class" in v[0])


def decode_field_trees(v: Any) -> List[TreeNode]:
    """A plan field holding expression trees: either one tree array or a
    list of tree arrays (Seq[Expression])."""
    if v is None:
        return []
    if is_tree_array(v):
        return [decode(v)]
    if isinstance(v, list):
        out = []
        for item in v:
            if is_tree_array(item):
                out.append(decode(item))
            elif isinstance(item, list) and not item:
                continue
            else:
                raise NotImplementedError(f"unrecognized expression field {item!r}")
        return out
    raise NotImplementedError(f"unrecognized expression field {v!r}")

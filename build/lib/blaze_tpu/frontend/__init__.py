"""Input-side plan conversion: external (Spark-serialized) physical plans
into the engine's IR — the standalone analogue of the reference's
spark-extension conversion layer (SURVEY.md §2.1, AuronConverters.scala)."""

from blaze_tpu.frontend.converter import (ConversionResult, SparkPlanConverter,
                                          convert_spark_plan)

__all__ = ["ConversionResult", "SparkPlanConverter", "convert_spark_plan"]

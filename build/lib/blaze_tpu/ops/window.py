"""Window functions over partition/order-sorted input.

Reference: ``window_exec.rs`` (489) + ``window/processors/*`` — rank,
dense_rank, row_number and aggregates-over-window driven by a WindowContext
that detects group boundaries via row-format keys; WindowGroupLimit arrives
as ``group_limit``. Input is sorted by (partition_spec, order_spec) — the
converter guarantees it, as Spark does.

Execution buffers each window partition until complete (partitions may span
input batches), then computes every function vectorized over the whole
partition: counters are numpy prefix scans over peer-boundary masks, and
agg-over-window uses Spark's default frames (whole partition without ORDER
BY; RANGE unbounded-preceding..current-row with ORDER BY, peers sharing the
frame value via segment backfill). Partitions must fit in memory — the
reference holds the same constraint per window group."""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np
import pyarrow as pa

from blaze_tpu.core.batch import ColumnarBatch, DeviceColumn, HostColumn
from blaze_tpu.exprs.compiler import ExprEvaluator
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T
from blaze_tpu.ir.nodes import WindowExpr
from blaze_tpu.ops.base import Operator
from blaze_tpu.runtime.memmgr import MemConsumer, SpillFile


def _partition_codes(batch: ColumnarBatch, exprs: List[E.Expr]) -> np.ndarray:
    """Within-batch partition codes (consecutive equal keys share a code):
    vectorized via the join keymap interning."""
    if not exprs:
        return np.zeros(batch.num_rows, dtype=np.int64)
    from blaze_tpu.ops.joins.keymap import key_codes

    ev = ExprEvaluator(exprs, batch.schema)
    cols = ev.evaluate(batch)
    # fresh map per batch: codes only need to distinguish neighbors
    codes = key_codes(batch, cols, {}, insert=True)
    # null keys (-1) form their own partitions: remap by run boundaries
    change = np.empty(batch.num_rows, dtype=bool)
    change[0] = True
    change[1:] = codes[1:] != codes[:-1]
    return np.cumsum(change) - 1


def _peer_mask(batch: ColumnarBatch, order_spec: List[E.SortOrder]) -> np.ndarray:
    """True where a new peer group starts (order-key change), within one
    partition batch."""
    n = batch.num_rows
    if not order_spec:
        out = np.zeros(n, dtype=bool)
        if n:
            out[0] = True
        return out
    from blaze_tpu.ops.joins.keymap import key_codes

    ev = ExprEvaluator([so.child for so in order_spec], batch.schema)
    cols = ev.evaluate(batch)
    codes = key_codes(batch, cols, {}, insert=True)
    out = np.empty(n, dtype=bool)
    out[0] = True
    out[1:] = codes[1:] != codes[:-1]
    return out


class _PartitionBuffer(MemConsumer):
    """Memmgr-watched buffer for the current window partition: batches
    accumulate in memory, spill to a compressed disk stream under pressure
    (keeping the tail batch resident — the partition-continuation check
    reads its last row), and replay in order at process time."""

    def __init__(self, schema: T.Schema, metrics):
        super().__init__("WindowExec", spillable=True)
        self.schema = schema
        self.metrics = metrics
        self.mem: List[ColumnarBatch] = []
        self.spills: List["SpillFile"] = []
        self.nbytes = 0

    def append(self, b: ColumnarBatch):
        self.mem.append(b)
        self.nbytes += b.nbytes()
        self.update_mem_used(self.nbytes)

    def spill(self) -> int:
        from blaze_tpu.runtime.memmgr import SpillFile

        if len(self.mem) <= 1:
            return 0
        sp = SpillFile("window")
        with self.metrics.timer("spill_io_time"):
            for b in self.mem[:-1]:
                sp.writer.write_batch(b)
            sp.finish_write()
        self.metrics.add("spill_count", 1)
        self.metrics.add("spilled_bytes", sp.size)
        last = self.mem[-1]
        freed = self.nbytes - last.nbytes()
        self.mem = [last]
        self.nbytes = last.nbytes()
        self.spills.append(sp)
        return freed

    def empty(self) -> bool:
        return not self.mem and not self.spills

    def last(self) -> ColumnarBatch:
        return self.mem[-1]

    def drain(self) -> List[ColumnarBatch]:
        batches: List[ColumnarBatch] = []
        for sp in self.spills:
            batches.extend(sp.read_batches())
            sp.release()
        batches.extend(self.mem)
        self.spills = []
        self.mem = []
        self.nbytes = 0
        self.update_mem_used(0)
        return batches

    def release(self):
        for sp in self.spills:
            sp.release()
        self.spills = []


class WindowExec(Operator):
    def __init__(self, child: Operator, window_exprs: List[WindowExpr],
                 partition_spec: List[E.Expr], order_spec: List[E.SortOrder],
                 group_limit: Optional[int] = None, output_window_cols: bool = True):
        self.window_exprs = window_exprs
        self.partition_spec = partition_spec
        self.order_spec = order_spec
        self.group_limit = group_limit
        self.output_window_cols = output_window_cols
        schema = self._output_schema(child.schema)
        super().__init__(schema, [child])

    def _output_schema(self, child_schema: T.Schema) -> T.Schema:
        if not self.output_window_cols:
            return child_schema
        extra = []
        for w in self.window_exprs:
            if w.kind == "agg":
                arg_t = (E.infer_type(w.agg.args[0], child_schema)
                         if w.agg.args else T.NULL)
                dt = w.return_type or w.agg.return_type or \
                    E.agg_result_type(w.agg.fn, arg_t)
            else:
                dt = w.return_type or (T.I32 if w.kind in ("rank", "dense_rank") else T.I64)
            extra.append(T.StructField(w.name, dt))
        return T.Schema(child_schema.fields + tuple(extra))

    def _execute(self, partition, ctx, metrics):
        child_schema = self.children[0].schema
        # buffered partition slices are memmgr-watched: accumulation spills
        # to disk under pressure (reference holds the same must-fit-at-
        # process-time constraint per group, but its MemManager watches the
        # buffering — weak #9 of the round-1 verdict)
        pending = _PartitionBuffer(child_schema, metrics)
        ctx.mem.register(pending)
        bs = ctx.conf.batch_size

        def process_partition() -> Iterator[ColumnarBatch]:
            if pending.empty():
                return
            part = ColumnarBatch.concat(pending.drain(), child_schema)
            out = self._process_one_partition(part)
            for off in range(0, out.num_rows, bs):
                yield out.slice(off, bs)

        try:
            yield from self._execute_buffered(partition, ctx, metrics,
                                              pending, process_partition)
        finally:
            ctx.mem.unregister(pending)
            pending.release()

    def _execute_buffered(self, partition, ctx, metrics, pending,
                          process_partition):
        for batch in self.execute_child(0, partition, ctx, metrics):
            if batch.num_rows == 0:
                continue
            with metrics.timer("elapsed_compute"):
                codes = _partition_codes(batch, self.partition_spec)
                boundaries = np.nonzero(np.diff(codes))[0] + 1
                starts = np.concatenate([[0], boundaries])
                ends = np.concatenate([boundaries, [batch.num_rows]])
                pieces = [(int(s), int(e)) for s, e in zip(starts, ends)]
            # all but the trailing piece complete earlier partitions; the
            # trailing piece may continue into the next batch — but only if
            # its key equals the next batch's first key, which we can't see
            # yet, so: first piece joins the pending partition ONLY if keys
            # match; simplest correct rule: flush pending before the first
            # piece iff this batch starts a new partition
            first_s, first_e = pieces[0]
            if not pending.empty() and not self._continues(pending.last(), batch):
                yield from process_partition()
            pending.append(batch.slice(first_s, first_e - first_s))
            for s, e in pieces[1:]:
                yield from process_partition()
                pending.append(batch.slice(s, e - s))
        yield from process_partition()

    def _continues(self, prev_tail: ColumnarBatch, batch: ColumnarBatch) -> bool:
        """Does batch's first row belong to the pending partition?"""
        if not self.partition_spec:
            return True
        last = prev_tail.slice(prev_tail.num_rows - 1, 1)
        first = batch.slice(0, 1)
        def key_of(b):
            ev = ExprEvaluator(self.partition_spec, b.schema)
            cols = ev.evaluate(b)
            return tuple(c.to_arrow(1).to_pylist()[0] for c in cols)
        return key_of(last) == key_of(first)

    # -- per-partition computation (vectorized) -------------------------------

    def _process_one_partition(self, part: ColumnarBatch) -> ColumnarBatch:
        n = part.num_rows
        new_peer = _peer_mask(part, self.order_spec)
        rn = np.arange(1, n + 1, dtype=np.int64)
        # rank: row number at each peer-group start, broadcast over the group
        peer_start_rn = np.where(new_peer, rn, 0)
        rank = np.maximum.accumulate(peer_start_rn)
        dense = np.cumsum(new_peer)

        out_cols = list(part.columns)
        fields = list(part.schema.fields)
        for w in self.window_exprs:
            if w.kind == "row_number":
                col, dt = DeviceColumn.from_numpy(T.I64, rn, None, part.capacity), T.I64
            elif w.kind == "rank":
                col, dt = DeviceColumn.from_numpy(
                    T.I32, rank.astype(np.int32), None, part.capacity), T.I32
            elif w.kind == "dense_rank":
                col, dt = DeviceColumn.from_numpy(
                    T.I32, dense.astype(np.int32), None, part.capacity), T.I32
            elif w.kind == "agg":
                col, dt = self._window_agg(w, part, new_peer)
            else:
                raise NotImplementedError(f"window function {w.kind}")
            if self.output_window_cols:
                out_cols.append(col)
                fields.append(T.StructField(w.name, dt))
        out = ColumnarBatch(T.Schema(tuple(fields)), out_cols, n) \
            if self.output_window_cols else part
        if self.group_limit is not None:
            # Filter on the produced window function's values (reference:
            # window_exec.rs:227-236), not the raw row number: rank() <= K and
            # dense_rank() <= K keep ALL boundary-tied rows.
            kinds = {w.kind for w in self.window_exprs}
            if kinds == {"rank"}:
                limit_vals = rank
            elif kinds == {"dense_rank"}:
                limit_vals = dense
            else:
                limit_vals = rn
            keep = np.nonzero(limit_vals <= self.group_limit)[0]
            if len(keep) < n:
                out = out.take(keep)
        return out

    def _range_frame_bounds(self, part: ColumnarBatch, lo, hi, n: int):
        """Per-row [start, end) over a RANGE frame: searchsorted against the
        partition's single numeric order key (input is sorted by it). Null
        order keys form their own run whose frame is exactly that run
        (Spark: null peers). Descending orders negate the key axis."""
        if len(self.order_spec) != 1:
            raise NotImplementedError("RANGE frame needs a single order key")
        so = self.order_spec[0]
        ev = ExprEvaluator([so.child], part.schema)
        col = ev.evaluate(part)[0]
        arr = col.to_arrow(n)
        valid = (~np.asarray(arr.is_null())) if arr.null_count else np.ones(n, bool)
        keys = arr.fill_null(0).to_numpy(zero_copy_only=False)
        if np.issubdtype(keys.dtype, np.datetime64):
            keys = keys.view(np.int64)
        if not np.issubdtype(keys.dtype, np.integer):
            keys = keys.astype(np.float64)  # ints stay exact (2^53+ keys)
        if not so.ascending:
            keys = -keys
        start = np.zeros(n, np.int64)
        end_excl = np.full(n, n, np.int64)
        if valid.all():
            nn_lo, nn_hi, kk = 0, n, keys
        elif not valid.any():
            # whole partition is one null peer run: every frame is all of it
            return start, end_excl
        else:
            # the null run is contiguous (sorted input): its rows frame over
            # the run itself for offset bounds; UNBOUNDED sides span the
            # whole partition (Spark UnboundedPreceding/FollowingWindow
            # FunctionFrame starts/ends at the partition edge, nulls
            # included). Non-null rows search the non-null span for offset
            # bounds, partition edges for unbounded ones.
            nn_idx = np.nonzero(valid)[0]
            nn_lo, nn_hi = int(nn_idx[0]), int(nn_idx[-1]) + 1
            if not valid[nn_lo:nn_hi].all():
                raise NotImplementedError("non-contiguous null order keys")
            null_rows = ~valid
            run_lo = 0 if null_rows[0] else nn_hi
            run_hi = nn_lo if null_rows[0] else n
            start[null_rows] = 0 if lo is None else run_lo
            end_excl[null_rows] = n if hi is None else run_hi
            kk = keys[nn_lo:nn_hi]
        # lower bound: key + lo (lo <= 0 for PRECEDING offsets)
        if lo is not None:
            s = np.searchsorted(kk, keys + _offset(keys, lo),
                                side="left") + nn_lo
            start[valid] = s[valid]
        else:
            start[valid] = 0
        if hi is not None:
            e = np.searchsorted(kk, keys + _offset(keys, hi),
                                side="right") + nn_lo
            end_excl[valid] = e[valid]
        else:
            end_excl[valid] = n
        return start, end_excl

    def _window_agg(self, w: WindowExpr, part: ColumnarBatch, new_peer: np.ndarray):
        n = part.num_rows
        agg = w.agg
        child_schema = part.schema
        arg_t = E.infer_type(agg.args[0], child_schema) if agg.args else T.NULL
        result_t = w.return_type or agg.return_type or E.agg_result_type(agg.fn, arg_t)

        if agg.args:
            ev = ExprEvaluator(list(agg.args), part.schema)
            col = ev.evaluate(part)[0]
            arr = col.to_arrow(n)
            valid = (~np.asarray(arr.is_null())) if arr.null_count else np.ones(n, bool)
            if isinstance(arg_t, T.DecimalType):
                from decimal import Decimal

                nv = np.array([Decimal(0) if v is None else v for v in arr.to_pylist()],
                              dtype=object)
            else:
                nv = arr.fill_null(0).to_numpy(zero_copy_only=False)
        else:
            valid = np.ones(n, bool)
            nv = np.zeros(n, dtype=np.int64)

        F = E.AggFunction
        has_order = bool(self.order_spec)
        masked = np.where(valid, nv, 0) if nv.dtype != object else nv
        frame = tuple(w.frame) if w.frame is not None else None
        if frame is not None and frame[0] in ("rows", "range"):
            # explicit frame (reference: SpecifiedWindowFrame). ROWS: per-row
            # [i+lo, i+hi] index windows. RANGE: value windows
            # [key-|lo|, key+hi] resolved by searchsorted over the
            # partition's (already sorted) single order key — CURRENT ROW
            # bounds include peers, matching Spark RANGE semantics.
            lo, hi = frame[1], frame[2]
            idx = np.arange(n)
            if frame[0] == "rows":
                start = np.zeros(n, np.int64) if lo is None else \
                    np.clip(idx + int(lo), 0, n)
                end_excl = np.full(n, n, np.int64) if hi is None else \
                    np.clip(idx + int(hi) + 1, 0, n)
            else:
                start, end_excl = self._range_frame_bounds(part, lo, hi, n)
            end_excl = np.maximum(end_excl, start)
            general_minmax = frame[0] == "range"
            zero = masked[0] * 0 if n else 0  # object-safe (Decimal) zero
            cs0 = np.concatenate([[zero], np.cumsum(masked)])
            cc0 = np.concatenate([[0], np.cumsum(valid.astype(np.int64))])
            fsum = cs0[end_excl] - cs0[start]
            fcnt = cc0[end_excl] - cc0[start]
            if agg.fn in (F.MIN, F.MAX):
                fval = _frame_minmax(nv, valid, lo, hi, start, end_excl,
                                     agg.fn == F.MIN, fcnt > 0,
                                     general=general_minmax)
        elif has_order:
            csum = np.cumsum(masked)
            ccnt = np.cumsum(valid.astype(np.int64))
            # frame value at each row = value at its peer-group END
            grp = np.cumsum(new_peer) - 1
            last_idx_of_grp = np.concatenate([np.nonzero(new_peer)[0][1:] - 1, [n - 1]])
            end_idx = last_idx_of_grp[grp]
            fsum = csum[end_idx]
            fcnt = ccnt[end_idx]
            if agg.fn in (F.MIN, F.MAX):
                accfn = np.minimum if agg.fn == F.MIN else np.maximum
                run = _masked_running(nv, valid, accfn, agg.fn == F.MIN)
                fval = run[end_idx]
        else:
            fsum = np.full(n, masked.sum())
            fcnt = np.full(n, int(valid.sum()))
            if agg.fn in (F.MIN, F.MAX):
                vv = [v for v, ok in zip(nv.tolist(), valid.tolist()) if ok]
                m = (min(vv) if agg.fn == F.MIN else max(vv)) if vv else None
                fval = np.array([m] * n, dtype=object)

        if agg.fn == F.COUNT:
            out = fcnt.tolist()
        elif agg.fn == F.SUM:
            out = [s if c > 0 else None for s, c in zip(fsum.tolist(), fcnt.tolist())]
        elif agg.fn == F.AVG:
            out = [(s / c if c > 0 else None) for s, c in zip(fsum.tolist(), fcnt.tolist())]
        elif agg.fn in (F.MIN, F.MAX):
            out = [v if c > 0 else None for v, c in zip(fval.tolist(), fcnt.tolist())]
        else:
            raise NotImplementedError(f"window agg {agg.fn}")
        if isinstance(result_t, T.DecimalType):
            from decimal import ROUND_HALF_UP, Decimal

            q = Decimal(1).scaleb(-result_t.scale)
            out = [None if v is None else Decimal(v).quantize(q, rounding=ROUND_HALF_UP)
                   for v in out]
        elif result_t == T.F64:
            out = [None if v is None else float(v) for v in out]
        return HostColumn(result_t, pa.array(out, type=T.to_arrow_type(result_t))), result_t


def _offset(keys: np.ndarray, off) -> np.ndarray:
    """Frame offset in the key's dtype (integer keys keep exact int64
    arithmetic; float offsets on int keys promote)."""
    if np.issubdtype(keys.dtype, np.integer) and float(off) == int(off):
        return np.int64(int(off))
    return np.float64(off)


def _frame_minmax(vals, valid, lo, hi, start, end_excl, is_min: bool,
                  has: np.ndarray, general: bool = False) -> np.ndarray:
    """Per-row min/max over ROWS-frame windows [start, end); ``has`` marks
    rows whose frame holds at least one valid value (the caller's fcnt>0).
    Numeric values vectorize: finite (lo, hi) via sentinel-padded sliding
    windows, half-unbounded via running accumulates; object (decimal)
    values fall back to per-row slice scans."""
    n = len(vals)
    out = np.empty(n, dtype=object)
    if n == 0:
        return out
    if lo is not None:
        lo = max(int(lo), -n)  # clamp: a billion-row PRECEDING offset must
    if hi is not None:
        hi = min(int(hi), n)   # not allocate billion-entry sentinel padding
    numeric = vals.dtype != object and not general
    # ``general`` (RANGE value windows): lo/hi are VALUE offsets, so the
    # index-based fast paths below do not apply — use the per-row scan over
    # the exact [start, end) bounds
    if numeric:
        if np.issubdtype(vals.dtype, np.floating):
            sent = np.array(np.inf if is_min else -np.inf, vals.dtype)
        else:
            info = np.iinfo(vals.dtype)
            sent = np.array(info.max if is_min else info.min, vals.dtype)
        x = np.where(valid, vals, sent)
        red = np.minimum if is_min else np.maximum
        if lo is not None and hi is not None:
            w = int(hi) - int(lo) + 1
            if w <= 0:
                out[:] = None
                return out
            pad_lo = max(0, -int(lo))
            pad_hi = max(0, int(hi))
            xp = np.concatenate([np.full(pad_lo, sent, vals.dtype), x,
                                 np.full(pad_hi, sent, vals.dtype)])
            sw = np.lib.stride_tricks.sliding_window_view(xp, w)
            got = (sw.min(axis=1) if is_min else sw.max(axis=1))[
                np.arange(n) + int(lo) + pad_lo]
        elif lo is None:
            run = red.accumulate(x)  # unbounded preceding .. i+hi
            got = run[np.clip(end_excl - 1, 0, n - 1)]
        else:
            run = red.accumulate(x[::-1])[::-1]  # i+lo .. unbounded following
            got = run[np.clip(start, 0, n - 1)]
        out[has] = got[has]
        out[~has] = None
        return out
    better = (lambda a, b: a < b) if is_min else (lambda a, b: a > b)
    for i in range(n):
        s, e = int(start[i]), int(end_excl[i])
        best = None
        for j in range(s, e):
            if valid[j]:
                v = vals[j]
                if best is None or better(v, best):
                    best = v
        out[i] = best
    return out


def _masked_running(vals, valid, accfn, is_min: bool):
    """Running min/max ignoring invalid entries (numpy accumulate with
    sentinel substitution)."""
    if vals.dtype == object:
        out = np.empty(len(vals), dtype=object)
        cur = None
        better = (lambda a, b: a < b) if is_min else (lambda a, b: a > b)
        for i, (v, ok) in enumerate(zip(vals.tolist(), valid.tolist())):
            if ok and (cur is None or better(v, cur)):
                cur = v
            out[i] = cur
        return out
    if np.issubdtype(vals.dtype, np.floating):
        sent = np.inf if is_min else -np.inf
    else:
        info = np.iinfo(vals.dtype)
        sent = info.max if is_min else info.min
    subst = np.where(valid, vals, sent)
    return accfn.accumulate(subst)
"""Sort-merge join: streaming cursors over key-sorted inputs.

Reference: ``sort_merge_join_exec.rs:57-375`` + ``joins/smj/*.rs`` +
``joins/stream_cursor.rs`` — inner/left/right/full/semi/anti/existence over
StreamCursors that advance equal-key runs. Here cursors compare host
key-tuples (total order incl. null rank, shared with the sort operator) and
each equal-key run pair emits its cross product via vectorized gathers;
rows with null join keys never match (Spark equi-join semantics)."""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np

from blaze_tpu.core.batch import ColumnarBatch, DeviceColumn
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T
from blaze_tpu.ir.nodes import JoinType, _join_output_schema
from blaze_tpu.ops import sort_keys as SK
from blaze_tpu.ops.base import Operator


class _SideCursor:
    """Iterates a sorted child as (key_tuple, rows) runs; a run's rows may
    span batches (reference: StreamCursor)."""

    def __init__(self, batch_iter, key_exprs: List[E.Expr],
                 sort_options: List[Tuple[bool, bool]], schema):
        self.it = batch_iter
        self.orders = [
            E.SortOrder(e, asc, nf) for e, (asc, nf) in zip(key_exprs, sort_options)
        ]
        self.schema = schema
        self.batch: Optional[ColumnarBatch] = None
        self.keys: Optional[list] = None
        self.pos = 0
        self.exhausted = False
        self._advance_batch()

    def _advance_batch(self) -> bool:
        for b in self.it:
            if b.num_rows == 0:
                continue
            self.batch = b
            self.keys = SK.host_keys_matrix(b, self.orders)
            self.pos = 0
            return True
        self.batch = None
        self.exhausted = True
        return False

    def peek_key(self):
        return self.keys[self.pos]

    def key_is_null(self) -> bool:
        return any(part[0] != 1 for part in self.peek_key())

    def next_run(self) -> Tuple[tuple, List[Tuple[ColumnarBatch, int, int]]]:
        """Pop the run of rows equal to the current key."""
        key = self.peek_key()
        segments = []
        while True:
            start = self.pos
            n = self.batch.num_rows
            while self.pos < n and self.keys[self.pos] == key:
                self.pos += 1
            if self.pos > start:
                segments.append((self.batch, start, self.pos))
            if self.pos < n:
                return key, segments
            if not self._advance_batch():
                return key, segments

    def skip_nulls(self) -> List[Tuple[ColumnarBatch, int, int]]:
        """Pop all leading null-keyed rows (they sort together at the null
        rank); returns their segments for outer emission."""
        segments = []
        while not self.exhausted and self.key_is_null():
            _, segs = self.next_run()
            segments.extend(segs)
        return segments


def _materialize(segments: List[Tuple[ColumnarBatch, int, int]], schema) -> ColumnarBatch:
    parts = [b.slice(s, e - s) for b, s, e in segments]
    return ColumnarBatch.concat(parts, schema)


class SortMergeJoinExec(Operator):
    def __init__(self, left: Operator, right: Operator,
                 on: List[Tuple[E.Expr, E.Expr]], join_type: JoinType,
                 sort_options: Optional[List[Tuple[bool, bool]]] = None,
                 condition: Optional[E.Expr] = None):
        self.on = on
        self.join_type = join_type
        self.sort_options = sort_options or [(True, True)] * len(on)
        # extra non-equi condition over left+right columns (reference: SMJ
        # inequality-join option); key-matched pairs failing it are unmatched
        self.condition = condition
        self._pair_schema = left.schema + right.schema
        schema = _join_output_schema(left.schema, right.schema, join_type)
        super().__init__(schema, [left, right])

    def num_partitions(self):
        return self.children[0].num_partitions()

    def _execute(self, partition, ctx, metrics):
        jt = self.join_type
        lcur = _SideCursor(self.execute_child(0, partition, ctx, metrics),
                           [l for l, _ in self.on], self.sort_options,
                           self.children[0].schema)
        rcur = _SideCursor(self.execute_child(1, partition, ctx, metrics),
                           [r for _, r in self.on], self.sort_options,
                           self.children[1].schema)
        emitter = _Emitter(self, ctx.conf.batch_size)

        keep_left_unmatched = jt in (JoinType.LEFT, JoinType.FULL,
                                     JoinType.LEFT_ANTI, JoinType.EXISTENCE)
        keep_right_unmatched = jt in (JoinType.RIGHT, JoinType.FULL,
                                      JoinType.RIGHT_ANTI)

        while not lcur.exhausted or not rcur.exhausted:
            # null-keyed rows can never match: treat as unmatched
            lnull = lcur.skip_nulls() if not lcur.exhausted else []
            rnull = rcur.skip_nulls() if not rcur.exhausted else []
            if lnull and keep_left_unmatched:
                yield from emitter.left_unmatched(_materialize(lnull, lcur.schema))
            if rnull and keep_right_unmatched:
                yield from emitter.right_unmatched(_materialize(rnull, rcur.schema))
            if lcur.exhausted and rcur.exhausted:
                break
            if lcur.exhausted:
                if keep_right_unmatched:
                    _, segs = rcur.next_run()
                    yield from emitter.right_unmatched(_materialize(segs, rcur.schema))
                else:
                    rcur.next_run()
                continue
            if rcur.exhausted:
                if keep_left_unmatched:
                    _, segs = lcur.next_run()
                    yield from emitter.left_unmatched(_materialize(segs, lcur.schema))
                else:
                    lcur.next_run()
                continue
            lk, rk = lcur.peek_key(), rcur.peek_key()
            if lk < rk:
                _, segs = lcur.next_run()
                if keep_left_unmatched:
                    yield from emitter.left_unmatched(_materialize(segs, lcur.schema))
            elif rk < lk:
                _, segs = rcur.next_run()
                if keep_right_unmatched:
                    yield from emitter.right_unmatched(_materialize(segs, rcur.schema))
            else:
                _, lsegs = lcur.next_run()
                _, rsegs = rcur.next_run()
                lrun = _materialize(lsegs, lcur.schema)
                rrun = _materialize(rsegs, rcur.schema)
                yield from emitter.matched(lrun, rrun)
        yield from emitter.flush()


class _Emitter:
    """Join-type-aware output assembly with batch-size buffering."""

    def __init__(self, op: SortMergeJoinExec, batch_size: int):
        self.op = op
        self.batch_size = batch_size
        self.buf: List[ColumnarBatch] = []
        self.rows = 0
        if op.condition is not None:
            from blaze_tpu.exprs.compiler import ExprEvaluator

            # one evaluator for all runs: keeps the CSE/jit caches warm
            self.cond_ev = ExprEvaluator([op.condition], op._pair_schema)

    def _push(self, batch: Optional[ColumnarBatch]):
        if batch is None or batch.num_rows == 0:
            return
        self.buf.append(batch)
        self.rows += batch.num_rows
        while self.rows >= self.batch_size:
            merged = ColumnarBatch.concat(self.buf, self.op.schema)
            out, rest = merged.slice(0, self.batch_size), merged.slice(
                self.batch_size, merged.num_rows)
            self.buf = [rest] if rest.num_rows else []
            self.rows = rest.num_rows
            yield out

    def flush(self):
        if self.buf:
            yield ColumnarBatch.concat(self.buf, self.op.schema)
            self.buf, self.rows = [], 0

    # -- emission by join type ------------------------------------------------

    def matched(self, lrun: ColumnarBatch, rrun: ColumnarBatch):
        jt = self.op.join_type
        nl, nr = lrun.num_rows, rrun.num_rows
        cond = self.op.condition
        if cond is None:
            # no pair expansion for the non-pair join types (a skewed run
            # would otherwise allocate O(nl*nr) just to learn "all matched")
            if jt == JoinType.LEFT_SEMI:
                yield from self._push(lrun)
                return
            if jt == JoinType.RIGHT_SEMI:
                yield from self._push(rrun)
                return
            if jt in (JoinType.LEFT_ANTI, JoinType.RIGHT_ANTI):
                return
            if jt == JoinType.EXISTENCE:
                yield from self._push(
                    self._with_exists(lrun, np.ones(nl, dtype=bool)))
                return
        li = np.repeat(np.arange(nl), nr)
        ri = np.tile(np.arange(nr), nl)
        if cond is not None:
            lout = lrun.take(li)
            rout = rrun.take(ri)
            pair = ColumnarBatch(self.op._pair_schema,
                                 lout.columns + rout.columns, nl * nr)
            keep = np.asarray(self.cond_ev.evaluate_predicate(pair))[: nl * nr]
            li, ri = li[keep], ri[keep]
        l_matched = np.zeros(nl, dtype=bool)
        l_matched[li] = True
        r_matched = np.zeros(nr, dtype=bool)
        r_matched[ri] = True

        if jt == JoinType.LEFT_SEMI:
            idx = np.nonzero(l_matched)[0]
            if len(idx):
                yield from self._push(lrun.take(idx))
            return
        if jt == JoinType.RIGHT_SEMI:
            idx = np.nonzero(r_matched)[0]
            if len(idx):
                yield from self._push(rrun.take(idx))
            return
        if jt == JoinType.LEFT_ANTI:
            idx = np.nonzero(~l_matched)[0]  # condition-failed rows
            if len(idx):
                yield from self._push(lrun.take(idx))
            return
        if jt == JoinType.RIGHT_ANTI:
            idx = np.nonzero(~r_matched)[0]
            if len(idx):
                yield from self._push(rrun.take(idx))
            return
        if jt == JoinType.EXISTENCE:
            yield from self._push(self._with_exists(lrun, l_matched))
            return
        if len(li):
            lout = lrun.take(li)
            rout = rrun.take(ri)
            yield from self._push(
                ColumnarBatch(self.op.schema, lout.columns + rout.columns, len(li)))
        # key-matched rows whose every pair failed the condition are
        # unmatched for outer purposes
        if cond is not None:
            lun = np.nonzero(~l_matched)[0]
            if len(lun):
                yield from self.left_unmatched(lrun.take(lun))
            run_ = np.nonzero(~r_matched)[0]
            if len(run_):
                yield from self.right_unmatched(rrun.take(run_))

    def left_unmatched(self, lrun: ColumnarBatch):
        jt = self.op.join_type
        if jt in (JoinType.LEFT_ANTI,):
            yield from self._push(lrun)
            return
        if jt == JoinType.EXISTENCE:
            yield from self._push(
                self._with_exists(lrun, np.zeros(lrun.num_rows, dtype=bool)))
            return
        if jt in (JoinType.LEFT, JoinType.FULL):
            rnulls = ColumnarBatch.empty(self.op.children[1].schema).take_nullable(
                np.full(lrun.num_rows, -1, np.int64))
            yield from self._push(
                ColumnarBatch(self.op.schema, lrun.columns + rnulls.columns,
                              lrun.num_rows))

    def right_unmatched(self, rrun: ColumnarBatch):
        jt = self.op.join_type
        if jt == JoinType.RIGHT_ANTI:
            yield from self._push(rrun)
            return
        if jt in (JoinType.RIGHT, JoinType.FULL):
            lnulls = ColumnarBatch.empty(self.op.children[0].schema).take_nullable(
                np.full(rrun.num_rows, -1, np.int64))
            yield from self._push(
                ColumnarBatch(self.op.schema, lnulls.columns + rrun.columns,
                              rrun.num_rows))

    def _with_exists(self, lrun: ColumnarBatch, flags: np.ndarray) -> ColumnarBatch:
        exists = DeviceColumn.from_numpy(T.BOOL, np.asarray(flags, dtype=bool),
                                         None, lrun.capacity)
        return ColumnarBatch(self.op.schema, lrun.columns + [exists], lrun.num_rows)

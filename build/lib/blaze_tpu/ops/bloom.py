"""Spark-compatible bloom filter.

Wire/semantics parity with Spark's BloomFilterImpl (reference:
``datafusion-ext-commons/src/spark_bloom_filter.rs`` and
``spark_bit_array.rs``): serialized as big-endian [version=1 i32,
num_hash_functions i32, word_count i32, words i64...]; per item the two
base hashes are murmur3(long_le_bytes, 0) and murmur3(long_le_bytes, h1),
combined as ``h1 + i*h2`` (int32 wraparound), bit-flipped when negative,
modulo bit_size. Probing is vectorized (numpy on host, jax on device)."""

from __future__ import annotations

import struct

import jax.numpy as jnp
import numpy as np

from blaze_tpu.exprs.spark_hash import murmur3_int64_np


class SparkBloomFilter:
    def __init__(self, words: np.ndarray, num_hash_functions: int):
        self.words = words  # uint64 array
        self.num_hash_functions = num_hash_functions
        self._dev_words = None

    # -- construction ---------------------------------------------------------

    @staticmethod
    def create(expected_items: int, num_bits: int) -> "SparkBloomFilter":
        num_bits = max(64, num_bits)
        k = max(1, round(num_bits / max(expected_items, 1) * np.log(2.0)))
        words = np.zeros((num_bits + 63) // 64, dtype=np.uint64)
        return SparkBloomFilter(words, k)

    @property
    def bit_size(self) -> int:
        return len(self.words) * 64

    # -- spark wire format ----------------------------------------------------

    def serialize(self) -> bytes:
        out = struct.pack(">ii", 1, self.num_hash_functions)
        out += struct.pack(">i", len(self.words))
        out += self.words.astype(">i8").tobytes()
        return out

    @staticmethod
    def deserialize(blob: bytes) -> "SparkBloomFilter":
        version, k = struct.unpack_from(">ii", blob, 0)
        assert version == 1, f"unsupported bloom filter version {version}"
        (nwords,) = struct.unpack_from(">i", blob, 8)
        words = np.frombuffer(blob, dtype=">i8", count=nwords, offset=12).astype(np.int64).view(np.uint64)
        return SparkBloomFilter(words.copy(), k)

    # -- hashing --------------------------------------------------------------

    def _bit_indices(self, values: np.ndarray) -> np.ndarray:
        """(n, k) bit positions for int64 values."""
        n = len(values)
        h1 = murmur3_int64_np(values, np.zeros(n, np.uint32)).view(np.int32)
        h2 = murmur3_int64_np(values, h1.view(np.uint32)).view(np.int32)
        ks = np.arange(1, self.num_hash_functions + 1, dtype=np.int32)
        with np.errstate(over="ignore"):
            combined = h1[:, None] + ks[None, :] * h2[:, None]
        combined = np.where(combined < 0, ~combined, combined)
        return (combined % np.int32(self.bit_size)).astype(np.int64)

    # -- mutation -------------------------------------------------------------

    def put_longs(self, values: np.ndarray):
        if len(values) == 0:
            return
        idx = self._bit_indices(np.asarray(values, dtype=np.int64)).ravel()
        np.bitwise_or.at(self.words, idx >> 6, np.uint64(1) << (idx & 63).astype(np.uint64))
        self._dev_words = None

    def merge(self, other: "SparkBloomFilter"):
        assert self.num_hash_functions == other.num_hash_functions
        assert len(self.words) == len(other.words)
        self.words |= other.words
        self._dev_words = None

    # -- probing --------------------------------------------------------------

    def might_contain_longs_np(self, values: np.ndarray) -> np.ndarray:
        if len(values) == 0:
            return np.zeros(0, dtype=bool)
        idx = self._bit_indices(np.asarray(values, dtype=np.int64))
        bits = (self.words[idx >> 6] >> (idx & 63).astype(np.uint64)) & np.uint64(1)
        return bits.all(axis=1)

    def might_contain_long(self, values: jnp.ndarray) -> jnp.ndarray:
        """Device probe: values (n,) int64 -> (n,) bool, bitmap resident in HBM."""
        if self._dev_words is None:
            self._dev_words = jnp.asarray(self.words)
        n = values.shape[0]
        v = values.astype(jnp.int64)
        from blaze_tpu.exprs.spark_hash import murmur3_int64

        h1 = murmur3_int64(v, jnp.zeros(n, jnp.uint32)).view(jnp.int32)
        h2 = murmur3_int64(v, h1.view(jnp.uint32)).view(jnp.int32)
        ks = jnp.arange(1, self.num_hash_functions + 1, dtype=jnp.int32)
        combined = h1[:, None] + ks[None, :] * h2[:, None]
        combined = jnp.where(combined < 0, ~combined, combined)
        idx = (combined % jnp.int32(self.bit_size)).astype(jnp.int64)
        bits = (self._dev_words[idx >> 6] >> (idx & 63).astype(jnp.uint64)) & jnp.uint64(1)
        return bits.astype(bool).all(axis=1)

"""Length-prefixed pickle frames over a stream socket — the driver<->worker
control/data channel (reference analogue: the netty block transport +
executor RPC Spark provides around the native engine, SURVEY.md §5.8;
standalone, a unix socket plays netty's role)."""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any

_LEN = struct.Struct("<Q")


def send_msg(sock: socket.socket, obj: Any):
    payload = pickle.dumps(obj, protocol=4)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Any:
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        raise EOFError("peer closed")
    (n,) = _LEN.unpack(head)
    body = _recv_exact(sock, n)
    if body is None:
        raise EOFError("peer closed mid-frame")
    return pickle.loads(body)


def _recv_exact(sock: socket.socket, n: int):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)

"""Task-context logging.

Reference: ``auron/src/logging.rs:23-43`` — stderr logging with thread-local
``[stage.partition tid]`` prefixes, level from conf. Here a logging.Filter
injects the current task context set by the executor."""

from __future__ import annotations

import logging
import os
import threading

_ctx = threading.local()


def set_task_context(stage_id: int, partition_id: int):
    _ctx.stage = stage_id
    _ctx.partition = partition_id


def clear_task_context():
    _ctx.stage = None
    _ctx.partition = None


class TaskContextFilter(logging.Filter):
    def filter(self, record):
        stage = getattr(_ctx, "stage", None)
        part = getattr(_ctx, "partition", None)
        if stage is None:
            record.task = "driver"
        else:
            record.task = f"{stage}.{part}"
        return True


def init_logging(level: str = None):
    """Configure engine logging (idempotent): stderr with task prefixes,
    level from BLAZE_TPU_LOG_LEVEL (reference: spark.auron.native.log.level)."""
    root = logging.getLogger("blaze_tpu")
    if getattr(root, "_blaze_configured", False):
        return root
    level = level or os.environ.get("BLAZE_TPU_LOG_LEVEL", "WARNING")
    handler = logging.StreamHandler()
    handler.addFilter(TaskContextFilter())
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s [%(task)s %(threadName)s] %(name)s: %(message)s"))
    root.addHandler(handler)
    root.setLevel(level.upper())
    root._blaze_configured = True
    return root

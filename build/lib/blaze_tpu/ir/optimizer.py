"""Plan optimizer: scan column pruning (projection pushdown).

The reference prunes columns operator-side via ``ExecuteWithColumnPruning``
(``datafusion-ext-plans/src/common/column_pruning.rs:22-48``): each operator
asks its child for only the columns it needs, and the parquet/orc scans read
only those. Here the same analysis runs once over the plan IR before
execution: walk top-down carrying the set of column NAMES the parent needs,
and shrink each file scan's ``conf.projection`` to it.

On a TPU whose host link is bandwidth-bound, pruning a scan column saves
three times: parquet decode, host->device upload, and device compute over
the padded planes.

Safety rules (this pass must never change results):
- Analysis is name-based. Any ``BoundReference`` (positional) in a relevant
  expression makes that subtree's requirement "all columns".
- Nodes with positional semantics (Union/Expand/Generate) pass "all columns"
  to their children.
- Join requirement splitting bails when the two input schemas share a column
  name (ambiguous by name).
- Pruning is best-effort: a child may return MORE columns than requested
  (when something bailed below); every rewritten parent tolerates that
  because all rebuilt nodes reference columns by name.
"""

from __future__ import annotations

import dataclasses
from typing import FrozenSet, Optional

from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import nodes as N

# requirement lattice: None = "all columns" (top); frozenset = exactly these
Req = Optional[FrozenSet[str]]


def expr_columns(e) -> Req:
    """Column names referenced by an expression; None if unknowable
    (positional references)."""
    if isinstance(e, E.BoundReference):
        return None
    if isinstance(e, E.Column):
        return frozenset((e.name,))
    if isinstance(e, E.ScalarSubquery):
        # evaluated over its own subplan, not the current scope
        return frozenset()
    cols = set()

    def walk(v) -> bool:
        # descend nested containers: Case branches are [(cond, value), ...],
        # and future exprs may nest arbitrarily — missing a reference here
        # would prune a live column, so over-approximate
        if isinstance(v, E.Expr):
            sub = expr_columns(v)
            if sub is None:
                return False
            cols.update(sub)
        elif isinstance(v, (list, tuple)):
            return all(walk(x) for x in v)
        elif isinstance(v, dict):
            return all(walk(x) for x in v.values())
        return True

    for f in dataclasses.fields(e):
        if not walk(getattr(e, f.name)):
            return None
    return frozenset(cols)


def _union(req: Req, *exprs) -> Req:
    """required ∪ columns of exprs; None-absorbing."""
    if req is None:
        return None
    out = set(req)
    for e in exprs:
        if e is None:
            continue
        c = expr_columns(e)
        if c is None:
            return None
        out |= c
    return frozenset(out)


def prune_plan(node: N.PlanNode, required: Req = None) -> N.PlanNode:
    """Rewrite ``node`` so file scans read only columns transitively needed
    to produce ``required`` output columns (None = all)."""
    if isinstance(node, (N.ParquetScan, N.OrcScan)):
        if required is None:
            return node
        conf = node.conf
        keep = [i for i in conf.projection
                if conf.file_schema[i].name in required]
        if not keep:
            # keep one column as the row-count carrier (COUNT(*)-style plans)
            keep = list(conf.projection[:1])
        if keep == list(conf.projection):
            return node
        return dataclasses.replace(
            node, conf=dataclasses.replace(conf, projection=keep))

    if isinstance(node, N.Projection):
        kept = [(n, e) for n, e in zip(node.names, node.exprs)
                if required is None or n in required]
        if not kept:
            kept = [(node.names[0], node.exprs[0])]
        child_req: Req = frozenset()
        for _, e in kept:
            child_req = _union(child_req, e)
        child = prune_plan(node.child, child_req)
        if len(kept) == len(node.names) and child is node.child:
            return node
        return dataclasses.replace(
            node, child=child, exprs=[e for _, e in kept],
            names=[n for n, _ in kept])

    if isinstance(node, N.Filter):
        return _rebuild(node, "child",
                        prune_plan(node.child, _union(required, *node.predicates)))

    if isinstance(node, N.Sort):
        return _rebuild(node, "child",
                        prune_plan(node.child, _union(required, *node.sort_orders)))

    if isinstance(node, (N.Limit, N.CoalesceBatches, N.Debug, N.BroadcastExchange)):
        return _rebuild(node, "child", prune_plan(node.child, required))

    if isinstance(node, N.Agg):
        if any(a.mode in (E.AggMode.PARTIAL_MERGE, E.AggMode.FINAL)
               for a in node.aggs):
            # ANY merge/final-mode agg consumes positional state columns
            # ('<agg>#<field>', read after the groupings in declaration
            # order) the expression walk cannot see — need everything.
            # Per-column check, not input_is_partial: mixed-mode aggs (the
            # one-distinct rewrite shape) still carry state columns
            child_req: Req = None
        else:
            child_req = frozenset()
            for _, ge in node.groupings:
                child_req = _union(child_req, ge)
            for ac in node.aggs:
                child_req = _union(child_req, ac.agg)
        return _rebuild(node, "child", prune_plan(node.child, child_req))

    if isinstance(node, N.Window):
        if required is None:
            child_req: Req = None
        else:
            child_names = set(node.child.output_schema.names)
            child_req = frozenset(c for c in required if c in child_names)
            child_req = _union(child_req, *node.partition_spec)
            child_req = _union(child_req, *node.order_spec)
            for w in node.window_exprs:
                if w.agg is not None:
                    child_req = _union(child_req, w.agg)
        return _rebuild(node, "child", prune_plan(node.child, child_req))

    if isinstance(node, N.ShuffleExchange):
        part = node.partitioning
        if isinstance(part, N.HashPartitioning):
            child_req = _union(required, *part.exprs)
        elif isinstance(part, N.RangePartitioning):
            child_req = _union(required, *part.sort_orders)
        else:
            child_req = required
        return _rebuild(node, "child", prune_plan(node.child, child_req))

    if isinstance(node, N.RenameColumns):
        child_schema = node.child.output_schema
        if required is None or len(set(child_schema.names)) != len(child_schema.names):
            child = prune_plan(node.child, None)
            return _rebuild(node, "child", child)
        pairs = list(zip(child_schema.names, node.renamed_names))
        keep = frozenset(cn for cn, rn in pairs if rn in required) or \
            frozenset((pairs[0][0],))
        child = prune_plan(node.child, keep)
        rename_map = dict(pairs)
        try:
            new_names = [rename_map[cn] for cn in child.output_schema.names]
        except KeyError:
            # pruned child surfaced a name outside the original schema —
            # shouldn't happen, but never let the optimizer break a plan
            return node
        if child is node.child and new_names == list(node.renamed_names):
            return node
        return dataclasses.replace(node, child=child, renamed_names=new_names)

    if isinstance(node, (N.SortMergeJoin, N.HashJoin, N.BroadcastJoin)):
        return _prune_join(node, required)

    if isinstance(node, N.BroadcastJoinBuildHashMap):
        # build-side schema participates in an executor-level cache keyed
        # externally — never reshape it
        return _rebuild(node, "child", prune_plan(node.child, None))

    # default: positional semantics (Union/Expand/Generate), sinks
    # (ShuffleWriter/IpcWriter/ParquetSink/Rss), leaves (IpcReader/FFIReader/
    # BatchSource/EmptyPartitions) — children must keep their full schema
    return N.map_children(node, lambda c: prune_plan(c, None))


def _rebuild(node: N.PlanNode, field: str, child: N.PlanNode) -> N.PlanNode:
    if child is getattr(node, field):
        return node
    return dataclasses.replace(node, **{field: child})


def _prune_join(node, required: Req) -> N.PlanNode:
    left_names = list(node.left.output_schema.names)
    right_names = list(node.right.output_schema.names)
    if set(left_names) & set(right_names):
        # duplicate names across sides: name-based splitting is ambiguous
        return N.map_children(node, lambda c: prune_plan(c, None))
    left_req: Req = frozenset()
    right_req: Req = frozenset()
    if required is None:
        left_req = right_req = None
    else:
        jt = node.join_type
        if jt in (N.JoinType.LEFT_SEMI, N.JoinType.LEFT_ANTI):
            left_req = frozenset(c for c in required if c in set(left_names))
        elif jt in (N.JoinType.RIGHT_SEMI, N.JoinType.RIGHT_ANTI):
            right_req = frozenset(c for c in required if c in set(right_names))
        else:  # inner/left/right/full/existence output both sides
            left_req = frozenset(c for c in required if c in set(left_names))
            right_req = frozenset(c for c in required if c in set(right_names))
    for le, re in node.on:
        left_req = _union(left_req, le)
        right_req = _union(right_req, re)
    if node.condition is not None:
        cond_cols = expr_columns(node.condition)
        if cond_cols is None:
            left_req = right_req = None
        else:
            left_req = None if left_req is None else \
                left_req | frozenset(c for c in cond_cols if c in set(left_names))
            right_req = None if right_req is None else \
                right_req | frozenset(c for c in cond_cols if c in set(right_names))
    if isinstance(node, N.BroadcastJoin):
        # the build side feeds the executor-level hash-map cache — keep its
        # schema stable (see BroadcastJoinBuildHashMap above)
        if node.broadcast_side == N.JoinSide.RIGHT:
            right_req = None
        else:
            left_req = None
    left = prune_plan(node.left, left_req)
    right = prune_plan(node.right, right_req)
    if left is node.left and right is node.right:
        return node
    return dataclasses.replace(node, left=left, right=right)

"""Plan and expression IR — the wire contract between a frontend (e.g. a Spark
plugin in the role of the reference's ``spark-extension``) and the TPU engine.

Reference contract: ``native-engine/auron-serde/proto/auron.proto`` (25 operator
nodes, expression oneof, AggFunction/AggMode enums, PhysicalRepartition oneof).
"""

from blaze_tpu.ir.types import (  # noqa: F401
    DataType,
    NullType,
    BooleanType,
    Int8Type,
    Int16Type,
    Int32Type,
    Int64Type,
    Float32Type,
    Float64Type,
    StringType,
    BinaryType,
    DateType,
    TimestampType,
    DecimalType,
    ArrayType,
    MapType,
    StructType,
    StructField,
    Schema,
)
from blaze_tpu.ir import exprs  # noqa: F401
from blaze_tpu.ir import nodes  # noqa: F401

"""Partial-aggregate state-field layout — pure IR-level helper.

Single source of truth for the typed columnar state each aggregate carries in
partial output (see blaze_tpu/ops/aggfns.py module docs for the design
rationale). Used by both the plan IR (``nodes.Agg.output_schema``) and the
operator layer, keeping IR free of operator imports.
"""

from __future__ import annotations

from typing import List, Tuple

from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T


def avg_sum_type(arg_t: T.DataType) -> T.DataType:
    if isinstance(arg_t, T.DecimalType):
        return T.DecimalType(min(arg_t.precision + 10, 38), arg_t.scale)
    return T.F64


def agg_state_fields(fn: E.AggFunction, arg_t: T.DataType,
                     result_t: T.DataType) -> List[Tuple[str, T.DataType]]:
    F = E.AggFunction
    if fn == F.SUM:
        return [("sum", result_t), ("has", T.BOOL)]
    if fn == F.COUNT:
        return [("count", T.I64)]
    if fn == F.AVG:
        return [("sum", avg_sum_type(arg_t)), ("count", T.I64)]
    if fn in (F.MIN, F.MAX):
        return [("val", result_t), ("has", T.BOOL)]
    if fn in (F.FIRST, F.FIRST_IGNORES_NULL):
        return [("val", result_t), ("valid", T.BOOL), ("order", T.I64)]
    if fn in (F.COLLECT_LIST, F.COLLECT_SET, F.BRICKHOUSE_COLLECT):
        return [("items", T.ArrayType(arg_t))]
    if fn == F.BRICKHOUSE_COMBINE_UNIQUE:
        # arg is already an array; state unions its elements
        elem = arg_t.element_type if isinstance(arg_t, T.ArrayType) else arg_t
        return [("items", T.ArrayType(elem))]
    if fn == F.BLOOM_FILTER:
        return [("bloom", T.BINARY)]
    if fn == F.UDAF:
        return [("acc", T.BINARY)]
    raise NotImplementedError(f"agg function {fn}")


def agg_output_schema(child_schema: T.Schema, groupings, aggs,
                      input_is_partial: bool, is_partial_output: bool) -> T.Schema:
    """Output schema of an Agg node (groupings + state fields or final values)."""
    if input_is_partial:
        gfields = [
            T.StructField(n, child_schema[i].dtype)
            for i, (n, _) in enumerate(groupings)
        ]
    else:
        gfields = [
            T.StructField(n, E.infer_type(e, child_schema)) for n, e in groupings
        ]
    out = list(gfields)
    pos = len(groupings)
    for a in aggs:
        agg = a.agg
        if input_is_partial:
            arg_t = _arg_type_from_state(agg, child_schema, pos)
        else:
            arg_t = E.infer_type(agg.args[0], child_schema) if agg.args else T.NULL
        result_t = agg.return_type or E.agg_result_type(agg.fn, arg_t)
        if agg.fn == E.AggFunction.COUNT:
            result_t = T.I64
        elif agg.fn == E.AggFunction.BLOOM_FILTER:
            result_t = T.BINARY
        fields = agg_state_fields(agg.fn, arg_t, result_t)
        if is_partial_output:
            out.extend(T.StructField(f"{a.name}#{s}", dt) for s, dt in fields)
        else:
            out.append(T.StructField(a.name, result_t))
        pos += len(fields)
    return T.Schema(tuple(out))


def _arg_type_from_state(agg: E.AggExpr, child_schema: T.Schema, pos: int) -> T.DataType:
    """Reconstruct the argument type from the value-typed first state field
    (partial input has no raw arg columns)."""
    dt = child_schema[pos].dtype
    if isinstance(dt, T.DecimalType) and agg.fn in (E.AggFunction.SUM, E.AggFunction.AVG):
        return T.DecimalType(max(dt.precision - 10, 1), dt.scale)
    if agg.fn == E.AggFunction.AVG and isinstance(dt, T.Float64Type):
        return T.F64
    if isinstance(dt, T.ArrayType):
        return dt.element_type
    return dt

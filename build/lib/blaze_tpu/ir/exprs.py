"""Expression IR.

Equivalent coverage to the reference's ``PhysicalExprNode`` oneof
(``native-engine/auron-serde/proto/auron.proto:58-119``): column refs,
literals, binary ops, null checks, case/cast/try_cast, in-list, like,
short-circuit and/or, scalar functions, string fast paths, row_num,
get_indexed_field / get_map_value / named_struct, bloom-filter probe,
python-UDF wrapper, scalar subquery, and aggregate expressions
(``AggFunction``/``AggMode`` enums, proto ``:127-141,687-700``).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, List, Optional, Tuple

from blaze_tpu.ir import types as T


class Expr:
    """Base expression node."""

    def children(self) -> List["Expr"]:
        out = []
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Expr):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                out.extend(x for x in v if isinstance(x, Expr))
        return out


@dataclasses.dataclass
class Column(Expr):
    """By-name column reference (reference: PhysicalColumn)."""

    name: str


@dataclasses.dataclass
class BoundReference(Expr):
    """By-index column reference (reference: BoundReference)."""

    index: int


@dataclasses.dataclass
class Literal(Expr):
    """Typed literal; value None means typed NULL. The reference ships
    literals as single-row Arrow IPC (auron.proto:824-826); we carry the
    python value + IR type."""

    value: Any
    dtype: T.DataType


class BinaryOp(str, enum.Enum):
    ADD = "+"
    SUB = "-"
    MUL = "*"
    DIV = "/"
    MOD = "%"
    EQ = "=="
    NEQ = "!="
    LT = "<"
    LTEQ = "<="
    GT = ">"
    GTEQ = ">="
    AND = "and"
    OR = "or"
    BIT_AND = "&"
    BIT_OR = "|"
    BIT_XOR = "^"
    SHIFT_LEFT = "<<"
    SHIFT_RIGHT = ">>"


_COMPARISON_OPS = {BinaryOp.EQ, BinaryOp.NEQ, BinaryOp.LT, BinaryOp.LTEQ,
                   BinaryOp.GT, BinaryOp.GTEQ}
_LOGICAL_OPS = {BinaryOp.AND, BinaryOp.OR}


@dataclasses.dataclass
class BinaryExpr(Expr):
    op: BinaryOp
    left: Expr
    right: Expr
    # Spark decimal arithmetic promotes precision/scale; the converter records
    # the result type here (reference: NativeConverters.scala:521-697).
    result_type: Optional[T.DataType] = None

    def __post_init__(self):
        if isinstance(self.op, str):
            self.op = BinaryOp(self.op)


@dataclasses.dataclass
class IsNull(Expr):
    child: Expr


@dataclasses.dataclass
class IsNotNull(Expr):
    child: Expr


@dataclasses.dataclass
class Not(Expr):
    child: Expr


@dataclasses.dataclass
class Case(Expr):
    """CASE WHEN c1 THEN v1 ... ELSE e END (searched form; the optional
    case-operand form is desugared by the converter into equality whens)."""

    branches: List[Tuple[Expr, Expr]]
    else_expr: Optional[Expr] = None

    def children(self):
        out = []
        for c, v in self.branches:
            out += [c, v]
        if self.else_expr is not None:
            out.append(self.else_expr)
        return out


@dataclasses.dataclass
class Cast(Expr):
    """Spark-semantics cast (reference: spark-compatible cast in
    datafusion-ext-commons/src/arrow/cast.rs)."""

    child: Expr
    dtype: T.DataType


@dataclasses.dataclass
class TryCast(Expr):
    """Cast that yields NULL on conversion failure instead of erroring."""

    child: Expr
    dtype: T.DataType


@dataclasses.dataclass
class InList(Expr):
    child: Expr
    values: List[Expr]
    negated: bool = False


@dataclasses.dataclass
class Like(Expr):
    child: Expr
    pattern: str
    negated: bool = False
    escape_char: str = "\\"
    case_insensitive: bool = False


@dataclasses.dataclass
class ScalarFunction(Expr):
    """Named scalar function with Spark semantics (reference:
    datafusion-ext-functions crate + DataFusion built-ins)."""

    name: str
    args: List[Expr]
    return_type: Optional[T.DataType] = None


@dataclasses.dataclass
class StringStartsWith(Expr):
    child: Expr
    prefix: str


@dataclasses.dataclass
class StringEndsWith(Expr):
    child: Expr
    suffix: str


@dataclasses.dataclass
class StringContains(Expr):
    child: Expr
    infix: str


@dataclasses.dataclass
class RowNum(Expr):
    """Stateful monotonically-increasing row number across a partition's
    stream (reference: datafusion-ext-exprs RowNum)."""


@dataclasses.dataclass
class GetIndexedField(Expr):
    child: Expr
    ordinal: Expr  # array index (0-based after converter adjustment) or struct field ordinal


@dataclasses.dataclass
class GetMapValue(Expr):
    child: Expr
    key: Expr


@dataclasses.dataclass
class NamedStruct(Expr):
    names: List[str]
    exprs: List[Expr]
    dtype: Optional[T.StructType] = None


@dataclasses.dataclass
class BloomFilterMightContain(Expr):
    bloom_filter: Expr  # binary column/literal holding a serialized SparkBloomFilter
    value: Expr


@dataclasses.dataclass
class PyUDF(Expr):
    """Host-callback UDF: the analogue of the reference's SparkUDFWrapperExpr
    JNI round-trip — here a python callable invoked per batch on host
    (jax.pure_callback at the device boundary when jitted)."""

    fn: Any  # Callable[..., np.ndarray] over host arrays
    args: List[Expr]
    return_type: T.DataType = None
    name: str = "pyudf"


@dataclasses.dataclass
class ScalarSubquery(Expr):
    """Pre-evaluated scalar subquery result (the frontend evaluates and ships
    the value, as the reference does)."""

    value: Any
    dtype: T.DataType


# --- sort / aggregate ---------------------------------------------------------


@dataclasses.dataclass
class SortOrder(Expr):
    child: Expr
    ascending: bool = True
    nulls_first: bool = True


class AggFunction(enum.Enum):
    MIN = "min"
    MAX = "max"
    SUM = "sum"
    AVG = "avg"
    COUNT = "count"
    COLLECT_LIST = "collect_list"
    COLLECT_SET = "collect_set"
    FIRST = "first"
    FIRST_IGNORES_NULL = "first_ignores_null"
    BLOOM_FILTER = "bloom_filter"
    # brickhouse UDAFs the reference ships natively (auron.proto AggFunction
    # BRICKHOUSE_COLLECT / BRICKHOUSE_COMBINE_UNIQUE, agg/brickhouse.rs)
    BRICKHOUSE_COLLECT = "brickhouse_collect"
    BRICKHOUSE_COMBINE_UNIQUE = "brickhouse_combine_unique"
    UDAF = "udaf"


class AggMode(enum.Enum):
    PARTIAL = "partial"          # raw input -> state output
    PARTIAL_MERGE = "partial_merge"  # state input -> state output
    FINAL = "final"              # state input -> value output
    COMPLETE = "complete"        # raw input -> value output (single stage)


class AggExecMode(enum.Enum):
    HASH_AGG = "hash_agg"
    SORT_AGG = "sort_agg"


@dataclasses.dataclass
class AggExpr(Expr):
    fn: AggFunction
    args: List[Expr]
    # result type recorded by the converter (e.g. spark sum/avg decimal
    # promotion rules)
    return_type: Optional[T.DataType] = None
    udaf: Any = None  # python UDAF object when fn == UDAF

    def children(self):
        return list(self.args)


# --- type inference -----------------------------------------------------------

def infer_type(expr: Expr, schema: T.Schema) -> T.DataType:
    """Output type of an expression against an input schema."""
    if isinstance(expr, Column):
        return schema[expr.name].dtype
    if isinstance(expr, BoundReference):
        return schema[expr.index].dtype
    if isinstance(expr, Literal):
        return expr.dtype
    if isinstance(expr, (Cast, TryCast)):
        return expr.dtype
    if isinstance(expr, BinaryExpr):
        if expr.result_type is not None:
            return expr.result_type
        if expr.op in _COMPARISON_OPS or expr.op in _LOGICAL_OPS:
            return T.BOOL
        lt = infer_type(expr.left, schema)
        rt = infer_type(expr.right, schema)
        return common_type(lt, rt)
    if isinstance(expr, (IsNull, IsNotNull, Not, InList, Like, StringStartsWith,
                         StringEndsWith, StringContains, BloomFilterMightContain)):
        return T.BOOL
    if isinstance(expr, Case):
        for _, v in expr.branches:
            return infer_type(v, schema)
        return infer_type(expr.else_expr, schema)
    if isinstance(expr, ScalarFunction):
        if expr.return_type is not None:
            return expr.return_type
        from blaze_tpu.exprs.functions import infer_function_type

        return infer_function_type(expr.name, [infer_type(a, schema) for a in expr.args])
    if isinstance(expr, RowNum):
        return T.I64
    if isinstance(expr, GetIndexedField):
        ct = infer_type(expr.child, schema)
        if isinstance(ct, T.ArrayType):
            return ct.element_type
        if isinstance(ct, T.StructType):
            assert isinstance(expr.ordinal, Literal)
            return ct.fields[expr.ordinal.value].dtype
        raise TypeError(f"get_indexed_field on {ct!r}")
    if isinstance(expr, GetMapValue):
        ct = infer_type(expr.child, schema)
        assert isinstance(ct, T.MapType)
        return ct.value_type
    if isinstance(expr, NamedStruct):
        if expr.dtype is not None:
            return expr.dtype
        return T.StructType(
            tuple(
                T.StructField(n, infer_type(e, schema))
                for n, e in zip(expr.names, expr.exprs)
            )
        )
    if isinstance(expr, PyUDF):
        return expr.return_type
    if isinstance(expr, ScalarSubquery):
        return expr.dtype
    if isinstance(expr, SortOrder):
        return infer_type(expr.child, schema)
    if isinstance(expr, AggExpr):
        if expr.return_type is not None:
            return expr.return_type
        arg_t = infer_type(expr.args[0], schema) if expr.args else T.NULL
        return agg_result_type(expr.fn, arg_t)
    raise NotImplementedError(f"infer_type: {type(expr).__name__}")


_NUMERIC_RANK = [T.I8, T.I16, T.I32, T.I64, T.F32, T.F64]


def common_type(lt: T.DataType, rt: T.DataType) -> T.DataType:
    if lt == rt:
        return lt
    if isinstance(lt, T.DecimalType) and isinstance(rt, T.DecimalType):
        # widest; exact promotion comes from the converter's result_type
        scale = max(lt.scale, rt.scale)
        intd = max(lt.precision - lt.scale, rt.precision - rt.scale)
        return T.DecimalType(min(intd + scale, T.DecimalType.MAX_PRECISION), scale)
    if lt in _NUMERIC_RANK and rt in _NUMERIC_RANK:
        return max(lt, rt, key=_NUMERIC_RANK.index)
    if isinstance(lt, T.NullType):
        return rt
    if isinstance(rt, T.NullType):
        return lt
    raise TypeError(f"no common type for {lt!r} and {rt!r}")


def agg_result_type(fn: AggFunction, arg_t: T.DataType) -> T.DataType:
    if fn == AggFunction.COUNT:
        return T.I64
    if fn == AggFunction.AVG:
        if isinstance(arg_t, T.DecimalType):
            # Spark: avg(decimal(p,s)) -> decimal(p+4, s+4) bounded
            return T.DecimalType(
                min(arg_t.precision + 4, 38), min(arg_t.scale + 4, 38)
            )
        return T.F64
    if fn == AggFunction.SUM:
        if isinstance(arg_t, T.DecimalType):
            # Spark: sum(decimal(p,s)) -> decimal(p+10, s) bounded
            return T.DecimalType(min(arg_t.precision + 10, 38), arg_t.scale)
        if arg_t in (T.I8, T.I16, T.I32, T.I64):
            return T.I64
        return T.F64
    if fn in (AggFunction.COLLECT_LIST, AggFunction.COLLECT_SET,
              AggFunction.BRICKHOUSE_COLLECT):
        return T.ArrayType(arg_t)
    if fn == AggFunction.BRICKHOUSE_COMBINE_UNIQUE:
        # array in, array out; a scalar argument still yields an array of
        # its deduped values (matches CombineUniqueAgg/agg_state_fields)
        return arg_t if isinstance(arg_t, T.ArrayType) else T.ArrayType(arg_t)
    return arg_t

"""Logical data types of the plan IR.

Covers the Arrow-type subset the reference wire IR supports
(``auron.proto:860-896``: null/bool/ints/floats/utf8/binary/date32/
timestamp-micros/decimal128/list/map/struct) with Spark semantics.

Physical mapping on TPU (see blaze_tpu.core.batch):

- fixed-width types -> dense jax arrays in HBM + validity mask
- decimal(p<=18)    -> scaled int64 (fast path); p>18 -> 2x int64 limbs
- string/binary     -> host (offsets, bytes) numpy pair, with on-demand
                       device dictionary codes for filtering/grouping
- nested types      -> host-side arrow representation (compute falls back)
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np


class DataType:
    """Base class. Concrete types are frozen dataclasses; simple types are
    singletons by construction equality."""

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))

    def __repr__(self):
        return type(self).__name__.replace("Type", "").lower()

    # --- physical properties -------------------------------------------------

    @property
    def is_fixed_width(self) -> bool:
        return self.np_dtype is not None

    @property
    def np_dtype(self) -> Optional[np.dtype]:
        """numpy/jax dtype of the dense device representation, or None if the
        type is host-resident (strings, binary, nested)."""
        return _NP_DTYPES.get(type(self))

    @property
    def byte_width(self) -> int:
        dt = self.np_dtype
        return 0 if dt is None else dt.itemsize


class NullType(DataType):
    pass


class BooleanType(DataType):
    pass


class Int8Type(DataType):
    pass


class Int16Type(DataType):
    pass


class Int32Type(DataType):
    pass


class Int64Type(DataType):
    pass


class Float32Type(DataType):
    pass


class Float64Type(DataType):
    pass


class StringType(DataType):
    pass


class BinaryType(DataType):
    pass


class DateType(DataType):
    """Days since the unix epoch, int32 (Arrow date32, Spark DateType)."""


class TimestampType(DataType):
    """Microseconds since the unix epoch, int64 (Spark TimestampType)."""


@dataclasses.dataclass(frozen=True, eq=False)
class DecimalType(DataType):
    """Spark decimal(precision, scale). precision<=18 is carried as a scaled
    int64 on device; larger precisions use two int64 limbs (hi, lo)."""

    precision: int = 10
    scale: int = 0

    MAX_PRECISION = 38
    MAX_INT64_PRECISION = 18

    def __eq__(self, other):
        return (
            isinstance(other, DecimalType)
            and self.precision == other.precision
            and self.scale == other.scale
        )

    def __hash__(self):
        return hash((DecimalType, self.precision, self.scale))

    def __repr__(self):
        return f"decimal({self.precision},{self.scale})"

    @property
    def np_dtype(self):
        return np.dtype(np.int64)

    @property
    def fits_int64(self) -> bool:
        return self.precision <= self.MAX_INT64_PRECISION


@dataclasses.dataclass(frozen=True, eq=False)
class ArrayType(DataType):
    element_type: DataType = None
    contains_null: bool = True

    def __eq__(self, other):
        return isinstance(other, ArrayType) and self.element_type == other.element_type

    def __hash__(self):
        return hash((ArrayType, self.element_type))

    def __repr__(self):
        return f"array<{self.element_type!r}>"


@dataclasses.dataclass(frozen=True, eq=False)
class MapType(DataType):
    key_type: DataType = None
    value_type: DataType = None
    value_contains_null: bool = True

    def __eq__(self, other):
        return (
            isinstance(other, MapType)
            and self.key_type == other.key_type
            and self.value_type == other.value_type
        )

    def __hash__(self):
        return hash((MapType, self.key_type, self.value_type))

    def __repr__(self):
        return f"map<{self.key_type!r},{self.value_type!r}>"


@dataclasses.dataclass(frozen=True)
class StructField:
    name: str
    dtype: DataType
    nullable: bool = True


@dataclasses.dataclass(frozen=True, eq=False)
class StructType(DataType):
    fields: Tuple[StructField, ...] = ()

    def __eq__(self, other):
        return isinstance(other, StructType) and self.fields == other.fields

    def __hash__(self):
        return hash((StructType, self.fields))

    def __repr__(self):
        inner = ",".join(f"{f.name}:{f.dtype!r}" for f in self.fields)
        return f"struct<{inner}>"


_NP_DTYPES = {
    BooleanType: np.dtype(np.bool_),
    Int8Type: np.dtype(np.int8),
    Int16Type: np.dtype(np.int16),
    Int32Type: np.dtype(np.int32),
    Int64Type: np.dtype(np.int64),
    Float32Type: np.dtype(np.float32),
    Float64Type: np.dtype(np.float64),
    DateType: np.dtype(np.int32),
    TimestampType: np.dtype(np.int64),
}

# Convenience singletons
NULL = NullType()
BOOL = BooleanType()
I8 = Int8Type()
I16 = Int16Type()
I32 = Int32Type()
I64 = Int64Type()
F32 = Float32Type()
F64 = Float64Type()
STRING = StringType()
BINARY = BinaryType()
DATE = DateType()
TIMESTAMP = TimestampType()


@dataclasses.dataclass(frozen=True)
class Schema:
    """Named, typed, nullable columns — the schema of every batch and every
    plan node's output (reference: arrow ``Schema`` via ``auron.proto:841-858``)."""

    fields: Tuple[StructField, ...]

    def __post_init__(self):
        object.__setattr__(self, "fields", tuple(self.fields))

    @staticmethod
    def of(*cols) -> "Schema":
        """Schema.of(("a", I64), ("b", STRING, False), StructField(...))"""
        fields = []
        for c in cols:
            if isinstance(c, StructField):
                fields.append(c)
            else:
                name, dtype, *rest = c
                fields.append(StructField(name, dtype, rest[0] if rest else True))
        return Schema(tuple(fields))

    @property
    def names(self):
        return [f.name for f in self.fields]

    @property
    def types(self):
        return [f.dtype for f in self.fields]

    def __len__(self):
        return len(self.fields)

    def __getitem__(self, i) -> StructField:
        if isinstance(i, str):
            return self.fields[self.index_of(i)]
        return self.fields[i]

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(f"column {name!r} not in schema {self.names}")

    def select(self, indices) -> "Schema":
        return Schema(tuple(self.fields[i] for i in indices))

    def rename(self, names) -> "Schema":
        assert len(names) == len(self.fields)
        return Schema(
            tuple(
                StructField(n, f.dtype, f.nullable)
                for n, f in zip(names, self.fields)
            )
        )

    def __add__(self, other: "Schema") -> "Schema":
        return Schema(self.fields + other.fields)


# ---------------------------------------------------------------------------
# Arrow interop (host boundary only)
# ---------------------------------------------------------------------------

def to_arrow_type(dt: DataType):
    import pyarrow as pa

    if isinstance(dt, NullType):
        return pa.null()
    if isinstance(dt, BooleanType):
        return pa.bool_()
    if isinstance(dt, Int8Type):
        return pa.int8()
    if isinstance(dt, Int16Type):
        return pa.int16()
    if isinstance(dt, Int32Type):
        return pa.int32()
    if isinstance(dt, Int64Type):
        return pa.int64()
    if isinstance(dt, Float32Type):
        return pa.float32()
    if isinstance(dt, Float64Type):
        return pa.float64()
    if isinstance(dt, StringType):
        return pa.large_utf8()
    if isinstance(dt, BinaryType):
        return pa.large_binary()
    if isinstance(dt, DateType):
        return pa.date32()
    if isinstance(dt, TimestampType):
        return pa.timestamp("us")
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, ArrayType):
        return pa.large_list(to_arrow_type(dt.element_type))
    if isinstance(dt, MapType):
        return pa.map_(to_arrow_type(dt.key_type), to_arrow_type(dt.value_type))
    if isinstance(dt, StructType):
        return pa.struct(
            [pa.field(f.name, to_arrow_type(f.dtype), f.nullable) for f in dt.fields]
        )
    raise NotImplementedError(f"no arrow mapping for {dt!r}")


def from_arrow_type(at) -> DataType:
    import pyarrow as pa
    import pyarrow.types as pat

    if pat.is_null(at):
        return NULL
    if pat.is_boolean(at):
        return BOOL
    if pat.is_int8(at):
        return I8
    if pat.is_int16(at):
        return I16
    if pat.is_int32(at):
        return I32
    if pat.is_int64(at):
        return I64
    if pat.is_uint8(at):
        return I16
    if pat.is_uint16(at):
        return I32
    if pat.is_uint32(at) or pat.is_uint64(at):
        return I64
    if pat.is_float32(at):
        return F32
    if pat.is_float16(at) or pat.is_float64(at):
        return F64
    if pat.is_string(at) or pat.is_large_string(at):
        return STRING
    if pat.is_binary(at) or pat.is_large_binary(at) or pat.is_fixed_size_binary(at):
        return BINARY
    if pat.is_date32(at):
        return DATE
    if pat.is_date64(at) or pat.is_timestamp(at):
        return TIMESTAMP
    if pat.is_decimal(at):
        return DecimalType(at.precision, at.scale)
    if pat.is_list(at) or pat.is_large_list(at):
        return ArrayType(from_arrow_type(at.value_type))
    if pat.is_map(at):
        return MapType(from_arrow_type(at.key_type), from_arrow_type(at.item_type))
    if pat.is_struct(at):
        return StructType(
            tuple(
                StructField(f.name, from_arrow_type(f.type), f.nullable) for f in at
            )
        )
    if pat.is_dictionary(at):
        return from_arrow_type(at.value_type)
    raise NotImplementedError(f"no IR mapping for arrow type {at}")


def schema_to_arrow(schema: Schema):
    import pyarrow as pa

    return pa.schema(
        [pa.field(f.name, to_arrow_type(f.dtype), f.nullable) for f in schema.fields]
    )


def schema_from_arrow(aschema) -> Schema:
    return Schema(
        tuple(
            StructField(f.name, from_arrow_type(f.type), f.nullable) for f in aschema
        )
    )

"""Generate the vendored Spark-3.5 wire-form fixtures
(tests/fixtures/spark35/*.json).

No JVM exists in this environment, so these dumps cannot be captured from a
live session; they are RECONSTRUCTIONS of ``df.queryExecution.executedPlan
.toJSON`` output, written field-for-field to Spark 3.5's TreeNode
serializer conventions — including the parts the test-suite's plan builder
(tests/tpcds/plans.py) simplifies:

- every physical node carries its full constructor field set
  (``isStreaming``/``numShufflePartitions`` on HashAggregateExec, ``offset``
  on TakeOrderedAndProjectExec, ``relation``/``optionalBucketSet``/
  ``disableBucketedScan`` on FileSourceScanExec, ...);
- ``tableIdentifier`` is a TableIdentifier PRODUCT with database+table;
- WindowExpression serializes with TWO children — the function and a
  WindowSpecDefinition whose children are partitionSpec ++ orderSpec ++
  frameSpecification (SpecifiedWindowFrame with bound trees);
- AggregateExpression carries ``filter: null``; aggregate functions carry
  their child-ordinal fields.

tests/test_spark_wire_fixtures.py asserts these convert to the SAME engine
plans/results as the builder-synthesized forms — the round-4 verdict's
wire-fidelity gate (item 3), as far as it can be closed without a JVM."""

import itertools
import json
import os

SPARK = "org.apache.spark.sql"
X = f"{SPARK}.catalyst.expressions"
P = f"{SPARK}.execution"
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))), "tests", "fixtures", "spark35")

_ids = itertools.count(200)


class A:
    """Attribute registry emitting the full AttributeReference field set."""

    def __init__(self):
        self.ids = {}
        self.types = {}

    def d(self, name, dtype):
        if name not in self.ids:
            self.ids[name] = next(_ids)
            self.types[name] = dtype

    def __call__(self, name):
        return [{
            "class": f"{X}.AttributeReference", "num-children": 0,
            "name": name, "dataType": self.types[name], "nullable": True,
            "metadata": {},
            "exprId": {"product-class": f"{X}.ExprId",
                       "id": self.ids[name],
                       "jvmId": "b0a2cfbf-16d1-4b6e-8e5c-27f1d1e0f8a1"},
            "qualifier": ["spark_catalog", "default",
                          name.split("_")[0] + "_tbl"]}]

    def new(self):
        return next(_ids)


def lit(value, dtype):
    return [{"class": f"{X}.Literal", "num-children": 0,
             "value": value, "dataType": dtype}]


def binop(cls, left, right, **extra):
    return [{"class": f"{X}.{cls}", "num-children": 2,
             "left": 0, "right": 1, **extra}] + left + right


def and_(a, b):
    return binop("And", a, b)


def sort_order(child, asc=True):
    d = "Ascending$" if asc else "Descending$"
    nf = "NullsFirst$" if asc else "NullsLast$"
    return [{"class": f"{X}.SortOrder", "num-children": 1, "child": 0,
             "direction": {"object": f"{X}.{d}"},
             "nullOrdering": {"object": f"{X}.{nf}"},
             "sameOrderExpressions": []}] + child


def alias(child, name, eid):
    return [{"class": f"{X}.Alias", "num-children": 1, "child": 0,
             "name": name,
             "exprId": {"product-class": f"{X}.ExprId", "id": eid,
                        "jvmId": "b0a2cfbf-16d1-4b6e-8e5c-27f1d1e0f8a1"},
             "qualifier": [], "explicitMetadata": {},
             "nonInheritableMetadataKeys": ["__dataset_id", "__col_position"]
             }] + child


def agg_expr(fn_cls, mode, rid, children, child_fields=None):
    fn = [{"class": f"{X}.aggregate.{fn_cls}",
           "num-children": len(children),
           **(child_fields or {})}] + \
        [c for ch in children for c in ch]
    return [{"class": f"{X}.aggregate.AggregateExpression", "num-children": 1,
             "aggregateFunction": 0,
             "mode": {"object": f"{X}.aggregate.{mode}$"},
             "isDistinct": False,
             "filter": None,
             "resultId": {"product-class": f"{X}.ExprId", "id": rid,
                          "jvmId": "b0a2cfbf-16d1-4b6e-8e5c-27f1d1e0f8a1"}}]\
        + fn


def scan(table, a, cols):
    struct_fields = [{"name": c, "type": a.types[c], "nullable": True,
                      "metadata": {}} for c in cols]
    return [{"class": f"{P}.FileSourceScanExec", "num-children": 0,
             "relation": None,
             "output": [a(c) for c in cols],
             "requiredSchema": {"type": "struct", "fields": struct_fields},
             "partitionFilters": [],
             "optionalBucketSet": None,
             "optionalNumCoalescedBuckets": None,
             "dataFilters": [],
             "tableIdentifier": {
                 "product-class": f"{SPARK}.catalyst.TableIdentifier",
                 "table": table, "database": "default"},
             "disableBucketedScan": False}]


def filt(cond, child):
    return [{"class": f"{P}.FilterExec", "num-children": 1,
             "condition": cond, "child": 0}] + child


def hash_agg(groups, aggs, child, required_dist=None):
    return [{"class": f"{P}.aggregate.HashAggregateExec", "num-children": 1,
             "requiredChildDistributionExpressions": required_dist,
             "isStreaming": False,
             "numShufflePartitions": None,
             "groupingExpressions": groups,
             "aggregateExpressions": aggs,
             "aggregateAttributes": [],
             "initialInputBufferOffset": 0,
             "resultExpressions": [],
             "child": 0}] + child


def range_exchange(child, orders, nparts=4):
    """What Spark plans under a global SortExec: RangePartitioning."""
    part = [{"class": f"{SPARK}.catalyst.plans.physical.RangePartitioning",
             "num-children": len(orders),
             "ordering": list(range(len(orders))),
             "numPartitions": nparts}] + \
        [x for o in orders for x in o]
    return [{"class": f"{P}.exchange.ShuffleExchangeExec", "num-children": 1,
             "outputPartitioning": part,
             "shuffleOrigin": {"object": f"{P}.exchange."
                                         "ENSURE_REQUIREMENTS$"},
             "advisoryPartitionSize": None,
             "child": 0}] + child


def exchange(child, keys=None, nparts=4):
    if keys is None:
        part = [{"class": f"{SPARK}.catalyst.plans.physical."
                          "SinglePartition$", "num-children": 0}]
    else:
        part = [{"class": f"{SPARK}.catalyst.plans.physical."
                          "HashPartitioning",
                 "num-children": len(keys),
                 "expressions": list(range(len(keys))),
                 "numPartitions": nparts}] + \
            [x for k in keys for x in k]
    return [{"class": f"{P}.exchange.ShuffleExchangeExec", "num-children": 1,
             "outputPartitioning": part,
             "shuffleOrigin": {"object": f"{P}.exchange."
                                         "ENSURE_REQUIREMENTS$"},
             "advisoryPartitionSize": None,
             "child": 0}] + child


def bcast(child):
    return [{"class": f"{P}.exchange.BroadcastExchangeExec",
             "num-children": 1,
             "mode": {"product-class":
                      f"{P}.joins.HashedRelationBroadcastMode",
                      "key": [], "isNullAware": False},
             "child": 0}] + child


def bhj(left, right, lkeys, rkeys, jt="Inner", build="BuildRight"):
    return [{"class": f"{P}.joins.BroadcastHashJoinExec", "num-children": 2,
             "leftKeys": lkeys, "rightKeys": rkeys,
             "joinType": {"object": f"{SPARK}.catalyst.plans.{jt}$"},
             "buildSide": {"object": f"{P}.joins.{build}$"},
             "condition": None, "left": 0, "right": 1,
             "isNullAwareAntiJoin": False}] + left + right


def smj(left, right, lkeys, rkeys, jt):
    return [{"class": f"{P}.joins.SortMergeJoinExec", "num-children": 2,
             "leftKeys": lkeys, "rightKeys": rkeys,
             "joinType": jt,
             "condition": None, "isSkewJoin": False,
             "left": 0, "right": 1}] + left + right


def sort_node(orders, child, global_=False):
    return [{"class": f"{P}.SortExec", "num-children": 1,
             "sortOrder": orders, "global": global_,
             "child": 0}] + child


def take_ordered(limit, orders, plist, child):
    return [{"class": f"{P}.TakeOrderedAndProjectExec", "num-children": 1,
             "limit": limit, "sortOrder": orders,
             "projectList": plist, "offset": 0, "child": 0}] + child


def project(plist, child):
    return [{"class": f"{P}.ProjectExec", "num-children": 1,
             "projectList": plist, "child": 0}] + child


def window_spec(part_exprs, order_exprs, frame_nodes):
    """WindowSpecDefinition as a real TreeNode: children are partition
    exprs ++ order SortOrders ++ the frame tree; fields hold ordinals."""
    n_part, n_order = len(part_exprs), len(order_exprs)
    node = {"class": f"{X}.WindowSpecDefinition",
            "num-children": n_part + n_order + 1,
            "partitionSpec": list(range(n_part)),
            "orderSpec": list(range(n_part, n_part + n_order)),
            "frameSpecification": n_part + n_order}
    out = [node]
    for e in part_exprs:
        out += e
    for e in order_exprs:
        out += e
    out += frame_nodes
    return out


def specified_frame(frame_type, lower_nodes, upper_nodes):
    return [{"class": f"{X}.SpecifiedWindowFrame", "num-children": 2,
             "frameType": {"object": f"{X}.{frame_type}$"},
             "lower": 0, "upper": 1}] + lower_nodes + upper_nodes


UNBOUNDED_PRECEDING = [{"class": f"{X}.UnboundedPreceding$",
                        "num-children": 0}]
CURRENT_ROW = [{"class": f"{X}.CurrentRow$", "num-children": 0}]


def window_exec(wexprs, part_spec, order_spec, child):
    return [{"class": f"{P}.window.WindowExec", "num-children": 1,
             "windowExpression": wexprs, "partitionSpec": part_spec,
             "orderSpec": order_spec, "child": 0}] + child


# --------------------------------------------------------------------------
# fixture q55: brand revenue (scan -> filter -> 2 BHJ -> 2-stage agg ->
# TakeOrderedAndProject)
# --------------------------------------------------------------------------


def fixture_q55():
    a = A()
    for c, t in [("ss_sold_date_sk", "long"), ("ss_item_sk", "long"),
                 ("ss_ext_sales_price", "decimal(7,2)"),
                 ("d_date_sk", "long"), ("d_year", "long"), ("d_moy", "long"),
                 ("i_item_sk", "long"), ("i_brand_id", "long"),
                 ("i_brand", "string"), ("i_manager_id", "long")]:
        a.d(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    dt = filt(and_(binop("EqualTo", a("d_moy"), lit(11, "long")),
                   binop("EqualTo", a("d_year"), lit(1999, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_moy"]))
    it = filt(binop("EqualTo", a("i_manager_id"), lit(13, "long")),
              scan("item", a, ["i_item_sk", "i_brand_id", "i_brand",
                               "i_manager_id"]))
    j1 = bhj(ss, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    j2 = bhj(j1, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    rid = a.new()
    sum_fields = {"child": 0}
    partial = hash_agg(
        [a("i_brand_id"), a("i_brand")],
        [agg_expr("Sum", "Partial", rid, [a("ss_ext_sales_price")],
                  sum_fields)], j2)
    ex = exchange(partial, keys=[a("i_brand_id"), a("i_brand")])
    final = hash_agg(
        [a("i_brand_id"), a("i_brand")],
        [agg_expr("Sum", "Final", rid, [a("ss_ext_sales_price")],
                  sum_fields)], ex,
        required_dist=[0, 1])
    a.ids["ext_price"] = rid
    a.types["ext_price"] = "decimal(17,2)"
    return take_ordered(100, [sort_order(a("ext_price"), asc=False),
                              sort_order(a("i_brand_id"))], [], final)


# --------------------------------------------------------------------------
# fixture q96: count(*) over 3 BHJs
# --------------------------------------------------------------------------


def fixture_q96():
    a = A()
    for c, t in [("ss_sold_time_sk", "long"), ("ss_hdemo_sk", "long"),
                 ("ss_store_sk", "long"),
                 ("t_time_sk", "long"), ("t_hour", "long"),
                 ("t_minute", "long"),
                 ("hd_demo_sk", "long"), ("hd_dep_count", "long"),
                 ("s_store_sk", "long"), ("s_store_name", "string")]:
        a.d(c, t)
    ss = scan("store_sales", a,
              ["ss_sold_time_sk", "ss_hdemo_sk", "ss_store_sk"])
    td = filt(and_(binop("EqualTo", a("t_hour"), lit(20, "long")),
                   binop("GreaterThanOrEqual", a("t_minute"),
                         lit(30, "long"))),
              scan("time_dim", a, ["t_time_sk", "t_hour", "t_minute"]))
    hd = filt(binop("EqualTo", a("hd_dep_count"), lit(3, "long")),
              scan("household_demographics", a,
                   ["hd_demo_sk", "hd_dep_count"]))
    st = filt(binop("EqualTo", a("s_store_name"), lit("store a", "string")),
              scan("store", a, ["s_store_sk", "s_store_name"]))
    j1 = bhj(ss, bcast(td), [a("ss_sold_time_sk")], [a("t_time_sk")])
    j2 = bhj(j1, bcast(hd), [a("ss_hdemo_sk")], [a("hd_demo_sk")])
    j3 = bhj(j2, bcast(st), [a("ss_store_sk")], [a("s_store_sk")])
    rid = a.new()
    partial = hash_agg([], [agg_expr("Count", "Partial", rid,
                                     [lit(1, "integer")])], j3)
    ex = exchange(partial, keys=None)
    return hash_agg([], [agg_expr("Count", "Final", rid,
                                  [lit(1, "integer")])], ex,
                    required_dist=[])


# --------------------------------------------------------------------------
# fixture q98-window: sum-over-partition with a REAL WindowSpecDefinition
# child (RANGE UNBOUNDED PRECEDING .. CURRENT ROW — Spark's default frame,
# serialized explicitly the way the JVM emits it)
# --------------------------------------------------------------------------


def fixture_q98_window():
    a = A()
    for c, t in [("ss_item_sk", "long"), ("ss_sold_date_sk", "long"),
                 ("ss_ext_sales_price", "decimal(7,2)"),
                 ("i_item_sk", "long"), ("i_item_id", "string"),
                 ("i_item_desc", "string"), ("i_category", "string"),
                 ("i_class", "string"), ("i_current_price", "decimal(7,2)"),
                 ("d_date_sk", "long"), ("d_year", "long"),
                 ("d_moy", "long")]:
        a.d(c, t)
    in_cat = [{"class": f"{X}.In", "num-children": 4,
               "value": 0, "list": [1, 2, 3]}] + a("i_category") + \
        lit("Sports", "string") + lit("Books", "string") + \
        lit("Home", "string")
    ss = scan("store_sales", a,
              ["ss_item_sk", "ss_sold_date_sk", "ss_ext_sales_price"])
    it = filt(in_cat,
              scan("item", a, ["i_item_sk", "i_item_id", "i_item_desc",
                               "i_category", "i_class", "i_current_price"]))
    dt = filt(and_(binop("EqualTo", a("d_year"), lit(1999, "long")),
                   binop("EqualTo", a("d_moy"), lit(2, "long"))),
              scan("date_dim", a, ["d_date_sk", "d_year", "d_moy"]))
    j = bhj(ss, bcast(it), [a("ss_item_sk")], [a("i_item_sk")])
    j = bhj(j, bcast(dt), [a("ss_sold_date_sk")], [a("d_date_sk")])
    rid = a.new()
    groups = ["i_item_id", "i_item_desc", "i_category", "i_class",
              "i_current_price"]
    partial = hash_agg([a(c) for c in groups],
                       [agg_expr("Sum", "Partial", rid,
                                 [a("ss_ext_sales_price")],
                                 {"child": 0})], j)
    ex = exchange(partial, keys=[a(c) for c in groups])
    final = hash_agg([a(c) for c in groups],
                     [agg_expr("Sum", "Final", rid,
                               [a("ss_ext_sales_price")],
                               {"child": 0})], ex, required_dist=[0])
    a.ids["itemrevenue"] = rid
    a.types["itemrevenue"] = "decimal(17,2)"
    wex = exchange(final, keys=[a("i_class")])
    wsort = sort_node([sort_order(a("i_class"))], wex)
    wid = a.new()
    spec = window_spec(
        [a("i_class")], [],
        specified_frame("RangeFrame", UNBOUNDED_PRECEDING, CURRENT_ROW))
    wagg = agg_expr("Sum", "Complete", a.new(), [a("itemrevenue")],
                    {"child": 0})
    wexpr_inner = [{"class": f"{X}.WindowExpression", "num-children": 2,
                    "windowFunction": 0, "windowSpec": 1}] + wagg + spec
    win = window_exec([alias(wexpr_inner, "_we0", wid)],
                      [a("i_class")], [], wsort)
    a.ids["_we0"] = wid
    a.types["_we0"] = "decimal(27,2)"
    ratio_id = a.new()
    ratio = alias(
        binop("Divide",
              binop("Multiply", a("itemrevenue"),
                    lit("100", "decimal(3,0)")),
              a("_we0")),
        "revenueratio", ratio_id)
    proj = project([a(c) for c in groups] + [a("itemrevenue")] + [ratio],
                   win)
    a.ids["revenueratio"] = ratio_id
    a.types["revenueratio"] = "decimal(38,11)"

    def orders():
        return [sort_order(a("i_category")), sort_order(a("i_class")),
                sort_order(a("i_item_id")), sort_order(a("i_item_desc")),
                sort_order(a("revenueratio"))]

    return sort_node(orders(), range_exchange(proj, orders()),
                     global_=True)


# --------------------------------------------------------------------------
# fixture q10-core: LeftSemi + ExistenceJoin over SMJ with the exists
# attribute serialized as a nested tree array inside the joinType product
# --------------------------------------------------------------------------


def fixture_q10_core():
    a = A()
    for c, t in [("c_customer_sk", "long"), ("c_current_cdemo_sk", "long"),
                 ("ss_customer_sk", "long"), ("ss_sold_date_sk", "long"),
                 ("ws_bill_customer_sk", "long"),
                 ("ws_sold_date_sk", "long"),
                 ("cs_bill_customer_sk", "long"),
                 ("cs_sold_date_sk", "long"),
                 ("d_date_sk", "long"), ("d_year", "long"),
                 ("d_moy", "long")]:
        a.d(c, t)

    def activity(table, cust, date):
        dta = A()
        dta.d("d_date_sk", "long")
        dta.d("d_year", "long")
        dta.d("d_moy", "long")
        s = scan(table, a, [cust, date])
        dt = filt(and_(binop("EqualTo", dta("d_year"), lit(1999, "long")),
                       and_(binop("GreaterThanOrEqual", dta("d_moy"),
                                  lit(1, "long")),
                            binop("LessThanOrEqual", dta("d_moy"),
                                  lit(4, "long")))),
                  scan("date_dim", dta, ["d_date_sk", "d_year", "d_moy"]))
        j = bhj(s, bcast(dt), [a(date)], [dta("d_date_sk")])
        return project([a(cust)], j)

    def sorted_ex(child, key):
        return sort_node([sort_order(key)], exchange(child, keys=[key]))

    cu = scan("customer", a, ["c_customer_sk", "c_current_cdemo_sk"])
    ss = activity("store_sales", "ss_customer_sk", "ss_sold_date_sk")
    ws = activity("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk")
    cs = activity("catalog_sales", "cs_bill_customer_sk", "cs_sold_date_sk")
    j = smj(sorted_ex(cu, a("c_customer_sk")),
            sorted_ex(ss, a("ss_customer_sk")),
            [a("c_customer_sk")], [a("ss_customer_sk")],
            {"object": f"{SPARK}.catalyst.plans.LeftSemi$"})
    e1, e2 = a.new(), a.new()

    def exists_attr(eid, n):
        return [[{"class": f"{X}.AttributeReference", "num-children": 0,
                  "name": "exists", "dataType": "boolean",
                  "nullable": False, "metadata": {},
                  "exprId": {"product-class": f"{X}.ExprId", "id": eid,
                             "jvmId":
                                 "b0a2cfbf-16d1-4b6e-8e5c-27f1d1e0f8a1"},
                  "qualifier": []}]]

    j = smj(sorted_ex(j, a("c_customer_sk")),
            sorted_ex(ws, a("ws_bill_customer_sk")),
            [a("c_customer_sk")], [a("ws_bill_customer_sk")],
            {"product-class": f"{SPARK}.catalyst.plans.ExistenceJoin",
             "exists": exists_attr(e1, 1)})
    j = smj(sorted_ex(j, a("c_customer_sk")),
            sorted_ex(cs, a("cs_bill_customer_sk")),
            [a("c_customer_sk")], [a("cs_bill_customer_sk")],
            {"product-class": f"{SPARK}.catalyst.plans.ExistenceJoin",
             "exists": exists_attr(e2, 2)})
    a.ids["exists1"], a.types["exists1"] = e1, "boolean"
    a.ids["exists2"], a.types["exists2"] = e2, "boolean"
    ex1 = [dict(a("exists1")[0], name="exists")]
    ex2 = [dict(a("exists2")[0], name="exists")]
    f = filt(binop("Or", ex1, ex2), j)
    rid = a.new()
    partial = hash_agg([], [agg_expr("Count", "Partial", rid,
                                     [lit(1, "integer")])], f)
    return hash_agg([], [agg_expr("Count", "Final", rid,
                                  [lit(1, "integer")])],
                    exchange(partial, keys=None), required_dist=[])


def main():
    os.makedirs(OUT, exist_ok=True)
    for name, fn in (("q55", fixture_q55), ("q96", fixture_q96),
                     ("q98_window", fixture_q98_window),
                     ("q10_core", fixture_q10_core)):
        path = os.path.join(OUT, f"{name}.json")
        with open(path, "w") as f:
            json.dump(fn(), f, indent=1)
        print("wrote", path)


if __name__ == "__main__":
    main()

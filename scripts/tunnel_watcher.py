"""Standing TPU tunnel watcher (round-4 verdict, next-round item #1).

The axon tunnel to the one real TPU chip has been down for rounds 2-4; every
bench shipped CPU-fallback numbers. This daemon probes the tunnel every few
minutes for the whole round and, the moment a probe answers with a real TPU
platform, fires the full on-hardware evidence capture:

  1. scripts/tpu_smoke.py      -> scripts/tpu_smoke_r05.log
  2. bench.py                  -> BENCH_r05_tpu.json  (the on-silicon number)
  3. scripts/placement_check.py-> PLACEMENT_r05.json  (auto vs forced)

Every probe attempt is appended to TUNNEL_PROBES.jsonl (timestamp, outcome,
elapsed) — if the tunnel never answers, that log IS the round's deliverable
for item #1. After a successful capture the watcher keeps probing (cheaply)
and re-captures at most twice more, >= 1h apart, to show stability.

Run detached:  nohup python scripts/tunnel_watcher.py >/dev/null 2>&1 &
Env: WATCH_INTERVAL_S (180), WATCH_MAX_HOURS (12), WATCH_PROBE_TIMEOUT (120).
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBE_LOG = os.path.join(REPO, "TUNNEL_PROBES.jsonl")
STATE = os.path.join(REPO, "scripts", ".tunnel_watcher_state.json")
INTERVAL = float(os.environ.get("WATCH_INTERVAL_S", 180))
MAX_HOURS = float(os.environ.get("WATCH_MAX_HOURS", 12))
PROBE_TIMEOUT = float(os.environ.get("WATCH_PROBE_TIMEOUT", 120))
MAX_CAPTURES = 3
RECAPTURE_GAP_S = 3600.0

PROBE_SNIPPET = (
    "import jax, jax.numpy as jnp; d = jax.devices();"
    "assert d and d[0].platform != 'cpu', f'cpu-only: {d}';"
    "x = float(jnp.arange(128.0).sum()); assert x == 8128.0;"
    "print(d[0].platform)"
)


def _log(rec: dict):
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(PROBE_LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _load_state() -> dict:
    try:
        with open(STATE) as f:
            return json.load(f)
    except Exception:
        return {"captures": 0, "last_capture_ts": 0.0}


def _save_state(st: dict):
    with open(STATE, "w") as f:
        json.dump(st, f)


def probe() -> tuple:
    """(ok, platform_or_error, elapsed_s). Runs in a subprocess: a wedged
    tunnel hangs un-cancellably inside backend init, so only a process
    boundary gives us a deadline."""
    t0 = time.monotonic()
    try:
        r = subprocess.run([sys.executable, "-c", PROBE_SNIPPET],
                           capture_output=True, text=True,
                           timeout=PROBE_TIMEOUT, cwd=REPO)
        el = time.monotonic() - t0
        if r.returncode == 0:
            return True, r.stdout.strip(), el
        return False, (r.stderr or r.stdout).strip()[-300:], el
    except subprocess.TimeoutExpired:
        return False, f"timeout>{PROBE_TIMEOUT:.0f}s", time.monotonic() - t0
    except Exception as e:  # pragma: no cover - defensive
        return False, repr(e)[:300], time.monotonic() - t0


def _run_step(name: str, argv, log_path: str, timeout_s: float,
              env_extra=None) -> dict:
    """stdout goes to ``log_path``, stderr to ``log_path + '.err'`` —
    kept apart so JSON records can be parsed off stdout (jax backends
    always chatter on stderr)."""
    env = dict(os.environ)
    env.update(env_extra or {})
    t0 = time.monotonic()
    try:
        r = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout_s, cwd=REPO, env=env)
        with open(log_path, "w") as f:
            f.write(r.stdout)
        if r.stderr:
            with open(log_path + ".err", "w") as f:
                f.write(r.stderr)
        return {"step": name, "rc": r.returncode,
                "elapsed_s": round(time.monotonic() - t0, 1),
                "tail": r.stdout.strip()[-400:]}
    except subprocess.TimeoutExpired:
        return {"step": name, "rc": -1, "timeout": timeout_s,
                "elapsed_s": round(time.monotonic() - t0, 1)}


def _last_json_line(path: str):
    """Last stdout line that parses as a JSON object (probes/benches print
    exactly one such record)."""
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        return None
    for ln in reversed(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue
        if isinstance(rec, dict):
            return rec
    return None


def capture(platform: str):
    """The tunnel answered: grab every on-hardware artifact in order of
    value-per-minute (smoke first — it's the cheapest proof the chip works;
    bench second — the headline; placement last — it runs q01 nine times)."""
    _log({"event": "capture_start", "platform": platform})
    results = []
    results.append(_run_step(
        "tpu_smoke", [sys.executable, "scripts/tpu_smoke.py"],
        os.path.join(REPO, "scripts", "tpu_smoke_r05.log"), 1800))
    bench_log = os.path.join(REPO, "scripts", "bench_r05_tpu.log")
    res = _run_step(
        "bench", [sys.executable, "bench.py"], bench_log, 3600,
        {"BLAZE_BENCH_TUNNEL_WAIT_S": "120"})
    results.append(res)
    if res.get("rc") == 0:
        rec = _last_json_line(bench_log)
        if rec is not None:
            rec["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())
            rec["platform"] = platform
            with open(os.path.join(REPO, "BENCH_r05_tpu.json"), "w") as f:
                json.dump(rec, f, indent=1)
        else:
            results.append({"step": "bench_parse",
                            "error": "no JSON record in bench stdout"})
    pl_log = os.path.join(REPO, "scripts", "placement_r05.log")
    res_p = _run_step(
        "placement", [sys.executable, "scripts/placement_check.py"],
        pl_log, 3600)
    results.append(res_p)
    if res_p.get("rc") == 0:
        rec = _last_json_line(pl_log)
        if rec is not None:
            with open(os.path.join(REPO, "PLACEMENT_r05.json"), "w") as f:
                json.dump(rec, f, indent=1)
    _log({"event": "capture_done", "results": results})


def main():
    deadline = time.monotonic() + MAX_HOURS * 3600
    st = _load_state()
    _log({"event": "watcher_start", "interval_s": INTERVAL,
          "max_hours": MAX_HOURS, "pid": os.getpid()})
    while time.monotonic() < deadline:
        ok, info, el = probe()
        _log({"ok": ok, "info": info, "elapsed_s": round(el, 1)})
        # wall-clock (NOT monotonic: the state file outlives this process)
        # gap applies only between captures — never blocks the first one
        if ok and st["captures"] < MAX_CAPTURES and (
                st["captures"] == 0 or
                time.time() - st["last_capture_ts"] > RECAPTURE_GAP_S):
            try:
                capture(info)
            except Exception as e:  # pragma: no cover - defensive
                _log({"event": "capture_error", "error": repr(e)[:300]})
            st["captures"] += 1
            st["last_capture_ts"] = time.time()
            _save_state(st)
        time.sleep(INTERVAL)
    _log({"event": "watcher_exit", "captures": st["captures"]})


if __name__ == "__main__":
    main()

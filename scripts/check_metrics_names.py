#!/usr/bin/env python3
"""Static lint for metrics-registry instrument names.

Walks every registration call site (``<reg>.counter("...")`` /
``.gauge("...")`` / ``.histogram("...")`` with a literal name) under
``blaze_tpu/`` and ``scripts/`` and enforces:

1. every name matches the ``blaze_<area>_<name>_<unit>`` convention with a
   unit from ``telemetry.ALLOWED_UNITS`` (same check the registry applies at
   runtime — this catches names on paths tests never execute);
2. no two call sites register the same name via different instrument types
   (the runtime would raise on whichever loses the import race; the lint
   reports both locations deterministically);
3. every field the stats plane emits into QueryProfile JSON
   (``obs.stats.ALL_PROFILE_FIELDS``) is snake_case — profiles are an
   external artifact surface (HTTP, bench records, the on-disk store), so
   field names are API;
4. the attribution taxonomy (``obs.attribution``) is internally
   consistent: categories snake_case and unique, the priority sweep order
   a permutation of them, every category carrying a Chrome-trace color
   and a ``<category>_time_ns`` artifact field, and the fusion-break /
   placement-decline reason vocabularies snake_case — these strings land
   verbatim in artifacts and metric labels, so they are API too.

Tests are deliberately NOT scanned: they register intentionally-bad names
to assert the runtime validation. Standalone: exits 1 with a report on any
violation. Also run by ``tests/test_telemetry.py`` in the quick tier.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_DIRS = ("blaze_tpu", "scripts")
METHODS = ("counter", "gauge", "histogram")


def iter_registrations(root: str):
    """Yield (path, lineno, method, name) for literal-name registrations."""
    for scan in SCAN_DIRS:
        base = os.path.join(root, scan)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                with open(path) as f:
                    try:
                        tree = ast.parse(f.read(), filename=path)
                    except SyntaxError as exc:
                        yield (path, exc.lineno or 0, "syntax", str(exc))
                        continue
                for node in ast.walk(tree):
                    if not (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr in METHODS
                            and node.args
                            and isinstance(node.args[0], ast.Constant)
                            and isinstance(node.args[0].value, str)):
                        continue
                    name = node.args[0].value
                    if not name.startswith("blaze_"):
                        continue  # MetricNode.timer etc. — not registry names
                    yield (os.path.relpath(path, root), node.lineno,
                           node.func.attr, name)


def run_lint(root: str = REPO):
    """Returns a list of violation strings (empty = clean)."""
    sys.path.insert(0, root)
    from blaze_tpu.obs.telemetry import validate_name

    violations = []
    seen = {}  # name -> (method, where)
    count = 0
    for path, lineno, method, name in iter_registrations(root):
        where = f"{path}:{lineno}"
        if method == "syntax":
            violations.append(f"{where}: unparseable: {name}")
            continue
        count += 1
        try:
            validate_name(name)
        except ValueError as exc:
            violations.append(f"{where}: {exc}")
        prev = seen.get(name)
        if prev is not None and prev[0] != method:
            violations.append(
                f"{where}: {name!r} registered as {method} but as "
                f"{prev[0]} at {prev[1]}")
        else:
            seen.setdefault(name, (method, where))
    if count == 0:
        violations.append("no registrations found — scan roots wrong?")
    violations.extend(check_profile_fields())
    violations.extend(check_attribution_taxonomy())
    violations.extend(check_cache_instruments(seen))
    violations.extend(check_timeline_taxonomy(seen))
    return violations


def check_cache_instruments(seen: dict):
    """The cache instrument families are a dashboard contract (ISSUE 19):
    every one of the five blaze_cache_* families must stay registered
    somewhere in the scanned tree — a rename or deletion silently breaks
    hit-rate panels and the soak tripwires that scrape them."""
    violations = []
    names = list(seen)
    for prefix in ("blaze_cache_hits_", "blaze_cache_misses_",
                   "blaze_cache_evictions_", "blaze_cache_stale_"):
        if not any(n.startswith(prefix) for n in names):
            violations.append(
                f"no registration found for required cache instrument "
                f"family {prefix}*")
    if not any(n.startswith("blaze_cache_") and "bytes" in n for n in names):
        violations.append(
            "no registration found for required cache instrument family "
            "blaze_cache_*bytes*")
    return violations


def check_timeline_taxonomy(seen: dict):
    """Validate the health plane (ISSUE 20): the blaze_timeline_* /
    blaze_slo_* instrument families must stay registered, and the
    timeline's vocabularies — subsystems, health states, derived series
    names, health-artifact fields — are API (they land verbatim in soak
    artifacts, /debug/health responses, and metric labels), so they must
    be snake_case (dots allowed in series names: ``<series>.<tenant>``
    variants), unique, and internally consistent."""
    import re

    try:
        from blaze_tpu.obs import timeline as tl
    except Exception as exc:
        return [f"obs.timeline unimportable: {exc}"]
    violations = []
    names = list(seen)
    for prefix in ("blaze_timeline_samples_", "blaze_timeline_sample_",
                   "blaze_timeline_series_", "blaze_slo_breaches_",
                   "blaze_slo_transitions_"):
        if not any(n.startswith(prefix) for n in names):
            violations.append(
                f"no registration found for required health-plane "
                f"instrument family {prefix}*")
    snake = re.compile(r"^[a-z][a-z0-9_]*$")
    for vocab_name, vocab in (
            ("SUBSYSTEMS", tl.SUBSYSTEMS),
            ("HEALTH_STATES", tl.HEALTH_STATES),
            ("DERIVED_SERIES", tl.DERIVED_SERIES),
            ("HEALTH_FIELDS", tl.HEALTH_FIELDS)):
        if len(set(vocab)) != len(vocab):
            violations.append(f"obs/timeline.py: duplicate in {vocab_name}")
        for v in vocab:
            if not snake.match(v):
                violations.append(
                    f"obs/timeline.py: {vocab_name} entry {v!r}"
                    " is not snake_case")
    for s in tl.COUNTER_TRACK_SERIES:
        if s not in tl.DERIVED_SERIES:
            violations.append(
                f"obs/timeline.py: COUNTER_TRACK_SERIES entry {s!r} not "
                f"in DERIVED_SERIES — the Chrome counter track would "
                f"sample a series the timeline never produces")
    for s in tl.ARTIFACT_SERIES:
        if s not in tl.DERIVED_SERIES:
            violations.append(
                f"obs/timeline.py: ARTIFACT_SERIES entry {s!r} not in "
                f"DERIVED_SERIES — soak artifacts would carry an empty "
                f"series")
    for hs in ("healthy", "degraded", "critical"):
        if hs not in tl.HEALTH_STATES:
            violations.append(
                f"obs/timeline.py: HEALTH_STATES missing {hs!r} — the "
                f"state machine vocabulary is a gate contract")
    # every derived series leads with the subsystem it reports on, so a
    # reader (and the slo_specs grammar) can route it without a table
    known = set(tl.SUBSYSTEMS) | {"worker"}
    for s in tl.DERIVED_SERIES:
        if s.split("_", 1)[0] not in known:
            violations.append(
                f"obs/timeline.py: derived series {s!r} does not lead "
                f"with a subsystem prefix from SUBSYSTEMS")
    return violations


def check_profile_fields():
    """Validate the stats plane's QueryProfile field names: snake_case,
    no duplicates within one record schema."""
    import re

    try:
        from blaze_tpu.obs import stats
    except Exception as exc:  # import must not take the lint down
        return [f"obs.stats unimportable: {exc}"]
    snake = re.compile(r"^[a-z][a-z0-9_]*$")
    violations = []
    schemas = [
        ("PROFILE_FIELDS", stats.PROFILE_FIELDS),
        ("STAGE_FIELDS", stats.STAGE_FIELDS),
        ("OPERATOR_FIELDS", stats.OPERATOR_FIELDS),
        ("SKEW_FIELDS", stats.SKEW_FIELDS),
        ("RESIDENCY_FIELDS", stats.RESIDENCY_FIELDS),
        ("SPILL_FIELDS", stats.SPILL_FIELDS),
        ("RECOVERY_FIELDS", stats.RECOVERY_FIELDS),
        ("ATTRIBUTION_FIELDS", stats.ATTRIBUTION_FIELDS),
        ("CRITICAL_PATH_FIELDS", stats.CRITICAL_PATH_FIELDS),
        ("AUDIT_FIELDS", stats.AUDIT_FIELDS),
        ("BASELINE_FIELDS", stats.BASELINE_FIELDS),
        ("CACHE_FIELDS", stats.CACHE_FIELDS),
    ]
    for schema_name, fields in schemas:
        if len(set(fields)) != len(fields):
            violations.append(
                f"obs/stats.py: duplicate field in {schema_name}")
        for f in fields:
            if not snake.match(f):
                violations.append(
                    f"obs/stats.py: {schema_name} field {f!r}"
                    " is not snake_case")
    return violations


def check_attribution_taxonomy():
    """Validate the attribution plane's category/reason vocabularies —
    strings that appear verbatim in artifacts, metric labels, and the
    Chrome-trace color map, so internal consistency is an API contract."""
    import re

    try:
        from blaze_tpu.obs import attribution as attr
    except Exception as exc:
        return [f"obs.attribution unimportable: {exc}"]
    snake = re.compile(r"^[a-z][a-z0-9_]*$")
    violations = []
    cats = attr.CATEGORIES
    if len(set(cats)) != len(cats):
        violations.append("obs/attribution.py: duplicate in CATEGORIES")
    for c in cats:
        if not snake.match(c):
            violations.append(
                f"obs/attribution.py: category {c!r} is not snake_case")
    if sorted(attr.PRIORITY) != sorted(cats):
        violations.append(
            "obs/attribution.py: PRIORITY is not a permutation of "
            "CATEGORIES — the exclusivity sweep would drop or invent "
            "a category")
    missing_cname = [c for c in cats if c not in attr.CATEGORY_CNAME]
    if missing_cname:
        violations.append(
            f"obs/attribution.py: CATEGORY_CNAME missing {missing_cname}"
            " (uncolored spans in the Chrome trace)")
    if attr.CATEGORY_FIELDS != tuple(f"{c}_time_ns" for c in cats):
        violations.append(
            "obs/attribution.py: CATEGORY_FIELDS out of sync with "
            "CATEGORIES — artifact keys diverge from the taxonomy")
    for vocab_name, vocab in (
            ("FUSION_BREAK_REASONS", attr.FUSION_BREAK_REASONS),
            ("PLACEMENT_DECLINE_REASONS", attr.PLACEMENT_DECLINE_REASONS)):
        if len(set(vocab)) != len(vocab):
            violations.append(
                f"obs/attribution.py: duplicate in {vocab_name}")
        for r in vocab:
            if not snake.match(r):
                violations.append(
                    f"obs/attribution.py: {vocab_name} reason {r!r}"
                    " is not snake_case")
    try:
        from blaze_tpu.obs import stats
        for f in ("fused_op_fraction", "fusion_break_reasons"):
            if f not in stats.AUDIT_FIELDS:
                violations.append(
                    f"obs/stats.py: AUDIT_FIELDS missing {f!r} — the "
                    f"fusion-coverage tripwire left the profile schema")
    except Exception as exc:
        violations.append(f"obs.stats unimportable: {exc}")
    return violations


def main() -> int:
    violations = run_lint()
    if violations:
        print(f"check_metrics_names: {len(violations)} violation(s)")
        for v in violations:
            print(f"  {v}")
        return 1
    print("check_metrics_names: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

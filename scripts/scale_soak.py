"""Scale soak (round-4 verdict item 4): ~1 GB of fact data through the
four bench shapes + five real TPC-DS queries under a CONSTRAINED memory
budget, so spill/merge/window-stream paths genuinely engage at volume.

Defaults: 10M bench fact rows over 32 partitions (~0.95 GB parquet across
the star tables) with a 512 MB engine budget, plus the real-query gate's
dataset scaled ~40x (2.4M store_sales). Records wall-clock, spill
count/bytes, window-stream counts, and peak RSS — the numbers BASELINE.md
cites. Reference analogue: the 1 GB TPC-DS dataset gate
(``tpcds-reusable.yml:168-260``).

Run: python scripts/scale_soak.py   (CPU; ~15-30 min)
Env: SOAK_ROWS (10_000_000), SOAK_PARTS (32), SOAK_BUDGET_MB (512),
SOAK_TPCDS_SCALE (40). SOAK_PROFILE_DIR=<dir> additionally enables span
tracing and dumps per-query trace/metrics artifacts there
(obs/dump.dump_profile; load the *_trace.json files in Perfetto).
"""

import json
import os
import resource
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROWS = int(os.environ.get("SOAK_ROWS", 10_000_000))
PARTS = int(os.environ.get("SOAK_PARTS", 32))
BUDGET_MB = int(os.environ.get("SOAK_BUDGET_MB", 128))
TPCDS_SCALE = int(os.environ.get("SOAK_TPCDS_SCALE", 40))
PROFILE_DIR = os.environ.get("SOAK_PROFILE_DIR", "")

os.environ["BENCH_ROWS"] = str(ROWS)
os.environ["BENCH_PARTITIONS"] = str(PARTS)
os.environ["BLAZE_BENCH_TUNNEL_WAIT_S"] = "5"

import jax

jax.config.update("jax_platforms", "cpu")


def peak_rss_mb() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024


def main():
    import bench  # repo-root bench.py (shapes, generators, oracles)
    from blaze_tpu.config import Config, set_config
    from blaze_tpu.runtime.session import Session
    from blaze_tpu.runtime.memmgr import MemManager

    set_config(Config(memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                      mem_wait_timeout_s=5.0))
    out = {"rows": ROWS, "partitions": PARTS, "budget_mb": BUDGET_MB,
           "shapes": {}, "tpcds": {}}
    with tempfile.TemporaryDirectory(prefix="blaze_soak_") as tmpdir:
        t0 = time.perf_counter()
        paths = bench.make_data(tmpdir)
        out["datagen_s"] = round(time.perf_counter() - t0, 1)
        out["data_bytes"] = sum(os.path.getsize(p)
                                for ps in paths.values() for p in ps)
        _, oracles = bench.run_baseline(paths)

        # a full-fact global sort: the one shape whose buffers CANNOT fit
        # the constrained budget — 32 concurrent range-partition sorts over
        # ~30 MB each force the sort spill/merge machinery to churn real
        # files (the round-4 verdict's "merge width, spill-file churn"
        # evidence; the agg shapes stream and never hold rows)
        def plan_big_sort(paths):
            from blaze_tpu.ir import exprs as E
            from blaze_tpu.ir import nodes as N
            from blaze_tpu.ops.parquet import scan_node_for_files

            scan = scan_node_for_files(paths["store_sales"],
                                       num_partitions=PARTS)
            orders = [E.SortOrder(E.Column("ss_sales_price"),
                                  ascending=False),
                      E.SortOrder(E.Column("ss_item_sk"))]
            ex = N.ShuffleExchange(scan, N.RangePartitioning(
                orders, PARTS, []))
            return N.Sort(ex, orders)

        def check_big_sort(table, _oracle):
            import pyarrow.compute as pc

            assert table.num_rows == ROWS, table.num_rows
            prices = table["ss_sales_price"].combine_chunks()
            # global descending order across ALL partitions
            assert pc.min(pc.subtract(
                prices.cast("float64").slice(0, len(prices) - 1),
                prices.cast("float64").slice(1))).as_py() >= 0

        shapes = list(bench.SHAPES) + [
            ("sort10M", plan_big_sort, None, None, check_big_sort, ())]
        oracles["sort10M"] = None
        for name, plan_fn, _o, _a, check_fn, _t in shapes:
            MemManager.reset()
            t0 = time.perf_counter()
            conf = Config(memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                          mem_wait_timeout_s=5.0,
                          trace_enable=bool(PROFILE_DIR))
            with Session(conf=conf) as sess:
                table = sess.execute_to_table(plan_fn(paths))
                spills = sess.metrics.total("spill_count")
                spill_bytes = sess.metrics.total("spilled_bytes")
                # invariant tripwires (runtime/metrics.TRIPWIRE_METRICS):
                # split_gathers == split_batches, window_group_loops == 0,
                # window_segments > 0 on window-bearing shapes — a degraded
                # fast path shows up as a counter diff in the artifact
                from blaze_tpu.runtime.metrics import tripwire_totals

                trips = tripwire_totals(sess.metrics)
                if PROFILE_DIR:
                    from blaze_tpu.obs import TRACER, dump_profile

                    dump_profile(sess, PROFILE_DIR, name)
                    TRACER.reset()
            mgr = MemManager._instance
            peak_used = int(mgr.peak_used) if mgr is not None else 0
            wall = time.perf_counter() - t0
            check_fn(table, oracles[name])  # correctness AT SCALE
            out["shapes"][name] = {
                "wall_s": round(wall, 1), "spill_count": int(spills),
                "spilled_bytes": int(spill_bytes),
                "streamed_window_partitions": trips["streamed_partitions"],
                "split_batches": trips["split_batches"],
                "split_gathers": trips["split_gathers"],
                "window_segments": trips["window_segments"],
                "window_group_loops": trips["window_group_loops"],
                "ipc_decode_in_prefetch": trips["ipc_decode_in_prefetch"],
                "fused_stages": trips["fused_stages"],
                "fused_ops": trips["fused_ops"],
                "jit_cache_hits": trips["jit_cache_hits"],
                "jit_cache_misses": trips["jit_cache_misses"],
                "fused_fallback_batches": trips["fused_fallback_batches"],
                "agg_reintern_rows": trips["agg_reintern_rows"],
                "agg_radix_buckets": trips["agg_radix_buckets"],
                "codes_shuffle_bytes": trips["codes_shuffle_bytes"],
                "peak_mem_used": peak_used,
                "peak_rss_mb": peak_rss_mb(),
            }
            print(json.dumps({name: out["shapes"][name]}), flush=True)

    soak_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SOAK_r08.json")
    if "tpcds" not in os.environ.get("SOAK_PHASES", "shapes,tpcds"):
        out["peak_rss_mb"] = peak_rss_mb()
        # keep a previous run's tpcds section (phase-scoped reruns merge)
        try:
            with open(soak_path) as f:
                prev = json.load(f)
            if prev.get("tpcds") and not out.get("tpcds"):
                out["tpcds"] = prev["tpcds"]
        except (OSError, ValueError):
            pass
        print(json.dumps(out))
        with open(soak_path, "w") as f:
            json.dump(out, f, indent=1)
        return

    # real-query gate at ~40x its CI size
    import tests.tpcds.data as D

    D.N_SS *= TPCDS_SCALE
    D.N_CS *= TPCDS_SCALE
    D.N_WS *= TPCDS_SCALE
    D.N_INV *= TPCDS_SCALE
    D.N_CUSTOMERS *= 4
    D.N_ADDRS *= 4
    from tests.tpcds.queries import QUERIES
    from tests.test_tpcds_queries import (_rows_equal, _sorted_if_tied)

    with tempfile.TemporaryDirectory(prefix="blaze_soak_tpcds_") as td:
        t0 = time.perf_counter()
        tables = D.generate(td)
        dfs = D.load_dfs(tables)
        out["tpcds"]["datagen_s"] = round(time.perf_counter() - t0, 1)
        out["tpcds"]["data_bytes"] = sum(os.path.getsize(p)
                                         for ps in tables.values()
                                         for p in ps)
        from blaze_tpu.frontend.converter import SparkPlanConverter

        for name in ("q3", "q7", "q53", "q67", "q96"):
            plan_json, oracle, extract, flags = QUERIES[name]()
            conv = SparkPlanConverter(tables=tables)
            res = conv.convert(json.dumps(plan_json))
            assert not [t for t in res.tags if "fallback" in t[1]]
            MemManager.reset()
            t0 = time.perf_counter()
            conf = Config(memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                          mem_wait_timeout_s=5.0,
                          trace_enable=bool(PROFILE_DIR))
            with Session(conf=conf) as sess:
                table = sess.execute_to_table(res.plan)
                spills = sess.metrics.total("spill_count")
                spill_bytes = sess.metrics.total("spilled_bytes")
                from blaze_tpu.runtime.metrics import tripwire_totals

                trips = tripwire_totals(sess.metrics)
                if PROFILE_DIR:
                    from blaze_tpu.obs import TRACER, dump_profile

                    dump_profile(sess, PROFILE_DIR, name)
                    TRACER.reset()
            wall = time.perf_counter() - t0
            if extract is None:
                d = table.to_pydict()
                rows = list(zip(*d.values())) if d else []
            else:
                rows = extract(table)
            got = _sorted_if_tied(rows, flags)
            want = _sorted_if_tied(oracle(dfs), flags)
            assert _rows_equal(got, want, flags), f"{name} wrong at scale"
            out["tpcds"][name] = {
                "wall_s": round(wall, 1), "rows_out": len(got),
                "spill_count": int(spills),
                "spilled_bytes": int(spill_bytes),
                "agg_reintern_rows": trips["agg_reintern_rows"],
                "agg_radix_buckets": trips["agg_radix_buckets"],
                "codes_shuffle_bytes": trips["codes_shuffle_bytes"],
                "peak_rss_mb": peak_rss_mb(),
            }
            print(json.dumps({name: out["tpcds"][name]}), flush=True)
    out["peak_rss_mb"] = peak_rss_mb()
    print(json.dumps(out))
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "SOAK_r08.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()

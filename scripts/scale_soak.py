"""Scale soak (round-4 verdict item 4): ~1 GB of fact data through the
four bench shapes + five real TPC-DS queries under a CONSTRAINED memory
budget, so spill/merge/window-stream paths genuinely engage at volume.

Defaults: 10M bench fact rows over 32 partitions (~0.95 GB parquet across
the star tables) with a 512 MB engine budget, plus the real-query gate's
dataset scaled ~40x (2.4M store_sales). Records wall-clock, spill
count/bytes, window-stream counts, and peak RSS — the numbers BASELINE.md
cites. Reference analogue: the 1 GB TPC-DS dataset gate
(``tpcds-reusable.yml:168-260``).

Run: python scripts/scale_soak.py   (CPU; ~15-30 min)
Env: SOAK_ROWS (10_000_000), SOAK_PARTS (32), SOAK_BUDGET_MB (512),
SOAK_TPCDS_SCALE (40). SOAK_PROFILE_DIR=<dir> additionally enables span
tracing and dumps per-query trace/metrics artifacts there
(obs/dump.dump_profile; load the *_trace.json files in Perfetto).
"""

import json
import os
import resource
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROWS = int(os.environ.get("SOAK_ROWS", 10_000_000))
PARTS = int(os.environ.get("SOAK_PARTS", 32))
BUDGET_MB = int(os.environ.get("SOAK_BUDGET_MB", 128))
TPCDS_SCALE = int(os.environ.get("SOAK_TPCDS_SCALE", 40))
PROFILE_DIR = os.environ.get("SOAK_PROFILE_DIR", "")

os.environ["BENCH_ROWS"] = str(ROWS)
os.environ["BENCH_PARTITIONS"] = str(PARTS)
os.environ["BLAZE_BENCH_TUNNEL_WAIT_S"] = "5"

# ``--devices N`` (the multichip round) needs the forced host-device count
# in place BEFORE jax initializes its backends, so honor the flag here at
# import time — one command, no manual XLA_FLAGS incantation:
#   python scripts/scale_soak.py --devices 8
if "--devices" in sys.argv[1:]:
    try:
        _n_dev = int(sys.argv[sys.argv.index("--devices") + 1])
    except (IndexError, ValueError):
        _n_dev = 0
    if _n_dev > 1 and "xla_force_host_platform_device_count" \
            not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={_n_dev}").strip()
        os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")


def peak_rss_mb() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024


def shm_roots(baseline=()) -> list:
    """blaze_tpu_shm_* roots in /dev/shm beyond ``baseline`` — the
    zero-copy plane's leak surface (segment files are unlink-safe while
    mapped, so directory entries are what a leak looks like)."""
    import glob

    return sorted(set(glob.glob("/dev/shm/blaze_tpu_shm_*")) - set(baseline))


def main():
    import bench  # repo-root bench.py (shapes, generators, oracles)
    from blaze_tpu.config import Config, set_config
    from blaze_tpu.runtime.session import Session
    from blaze_tpu.runtime.memmgr import MemManager

    set_config(Config(memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                      mem_wait_timeout_s=5.0))
    out = {"rows": ROWS, "partitions": PARTS, "budget_mb": BUDGET_MB,
           "shapes": {}, "tpcds": {}}
    shm0 = shm_roots()  # roots that predate this run are not ours to gate
    with tempfile.TemporaryDirectory(prefix="blaze_soak_") as tmpdir:
        t0 = time.perf_counter()
        paths = bench.make_data(tmpdir)
        out["datagen_s"] = round(time.perf_counter() - t0, 1)
        out["data_bytes"] = sum(os.path.getsize(p)
                                for ps in paths.values() for p in ps)
        _, oracles = bench.run_baseline(paths)

        # a full-fact global sort: the one shape whose buffers CANNOT fit
        # the constrained budget — 32 concurrent range-partition sorts over
        # ~30 MB each force the sort spill/merge machinery to churn real
        # files (the round-4 verdict's "merge width, spill-file churn"
        # evidence; the agg shapes stream and never hold rows)
        def plan_big_sort(paths):
            from blaze_tpu.ir import exprs as E
            from blaze_tpu.ir import nodes as N
            from blaze_tpu.ops.parquet import scan_node_for_files

            scan = scan_node_for_files(paths["store_sales"],
                                       num_partitions=PARTS)
            orders = [E.SortOrder(E.Column("ss_sales_price"),
                                  ascending=False),
                      E.SortOrder(E.Column("ss_item_sk"))]
            ex = N.ShuffleExchange(scan, N.RangePartitioning(
                orders, PARTS, []))
            return N.Sort(ex, orders)

        def check_big_sort(table, _oracle):
            import pyarrow.compute as pc

            assert table.num_rows == ROWS, table.num_rows
            prices = table["ss_sales_price"].combine_chunks()
            # global descending order across ALL partitions
            assert pc.min(pc.subtract(
                prices.cast("float64").slice(0, len(prices) - 1),
                prices.cast("float64").slice(1))).as_py() >= 0

        shapes = list(bench.SHAPES) + [
            ("sort10M", plan_big_sort, None, None, check_big_sort, ())]
        oracles["sort10M"] = None
        for name, plan_fn, _o, _a, check_fn, _t in shapes:
            MemManager.reset()
            t0 = time.perf_counter()
            conf = Config(memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                          mem_wait_timeout_s=5.0,
                          trace_enable=bool(PROFILE_DIR))
            with Session(conf=conf) as sess:
                table = sess.execute_to_table(plan_fn(paths))
                spills = sess.metrics.total("spill_count")
                spill_bytes = sess.metrics.total("spilled_bytes")
                # invariant tripwires (runtime/metrics.TRIPWIRE_METRICS):
                # split_gathers == split_batches, window_group_loops == 0,
                # window_segments > 0 on window-bearing shapes — a degraded
                # fast path shows up as a counter diff in the artifact
                from blaze_tpu.runtime.metrics import tripwire_totals

                trips = tripwire_totals(sess.metrics)
                profile = sess.profile()
                if PROFILE_DIR:
                    from blaze_tpu.obs import TRACER, dump_profile

                    dump_profile(sess, PROFILE_DIR, name)
                    TRACER.reset()
            mgr = MemManager._instance
            peak_used = int(mgr.peak_used) if mgr is not None else 0
            wall = time.perf_counter() - t0
            check_fn(table, oracles[name])  # correctness AT SCALE
            out["shapes"][name] = {
                "wall_s": round(wall, 1), "spill_count": int(spills),
                "spilled_bytes": int(spill_bytes),
                "streamed_window_partitions": trips["streamed_partitions"],
                "split_batches": trips["split_batches"],
                "split_gathers": trips["split_gathers"],
                "window_segments": trips["window_segments"],
                "window_group_loops": trips["window_group_loops"],
                "ipc_decode_in_prefetch": trips["ipc_decode_in_prefetch"],
                "fused_stages": trips["fused_stages"],
                "fused_ops": trips["fused_ops"],
                "jit_cache_hits": trips["jit_cache_hits"],
                "jit_cache_misses": trips["jit_cache_misses"],
                "fused_fallback_batches": trips["fused_fallback_batches"],
                "agg_reintern_rows": trips["agg_reintern_rows"],
                "agg_radix_buckets": trips["agg_radix_buckets"],
                "codes_shuffle_bytes": trips["codes_shuffle_bytes"],
                "shuffle_bytes_serialized": trips["shuffle_bytes_serialized"],
                "shm_bytes_mapped": trips["shm_bytes_mapped"],
                "serde_elided_batches": trips["serde_elided_batches"],
                "sharded_stages": trips["sharded_stages"],
                "device_shuffle_bytes": trips["device_shuffle_bytes"],
                "collective_bytes": trips["collective_bytes"],
                "peak_mem_used": peak_used,
                "peak_rss_mb": peak_rss_mb(),
            }
            if profile is not None:
                # stats-plane summary: the skew + partition-shape numbers a
                # soak diff (scripts/bench_diff.py) compares across runs
                out["shapes"][name]["stats"] = {
                    "fingerprint": profile["fingerprint"],
                    "device_time_fraction": profile["device_time_fraction"],
                    "stages": [{k: s.get(k) for k in (
                        "stage", "kind", "partitions", "total_bytes",
                        "total_rows", "partition_skew_ratio", "skew")}
                        for s in profile["stages"]],
                }
            print(json.dumps({name: out["shapes"][name]}), flush=True)

    soak_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SOAK_r10.json")
    if "tpcds" not in os.environ.get("SOAK_PHASES", "shapes,tpcds"):
        from blaze_tpu.obs.attribution import artifact_section
        from blaze_tpu.obs.timeline import timeline_artifact_section

        out.update(artifact_section())
        out.update(timeline_artifact_section())
        out["peak_rss_mb"] = peak_rss_mb()
        leaked = shm_roots(shm0)
        out["shm_segments_leaked"] = len(leaked)
        assert not leaked, f"/dev/shm leak: {leaked}"
        assert out["health"]["critical_intervals"] == 0, out["health"]
        assert out["health"]["degraded_ratio"] <= 0.5, out["health"]
        # keep a previous run's tpcds section (phase-scoped reruns merge)
        try:
            with open(soak_path) as f:
                prev = json.load(f)
            if prev.get("tpcds") and not out.get("tpcds"):
                out["tpcds"] = prev["tpcds"]
        except (OSError, ValueError):
            pass
        print(json.dumps(out))
        with open(soak_path, "w") as f:
            json.dump(out, f, indent=1)
        return

    # real-query gate at ~40x its CI size
    import tests.tpcds.data as D

    D.N_SS *= TPCDS_SCALE
    D.N_CS *= TPCDS_SCALE
    D.N_WS *= TPCDS_SCALE
    D.N_INV *= TPCDS_SCALE
    D.N_CUSTOMERS *= 4
    D.N_ADDRS *= 4
    from tests.tpcds.queries import QUERIES
    from tests.test_tpcds_queries import (_rows_equal, _sorted_if_tied)

    with tempfile.TemporaryDirectory(prefix="blaze_soak_tpcds_") as td:
        t0 = time.perf_counter()
        tables = D.generate(td)
        dfs = D.load_dfs(tables)
        out["tpcds"]["datagen_s"] = round(time.perf_counter() - t0, 1)
        out["tpcds"]["data_bytes"] = sum(os.path.getsize(p)
                                         for ps in tables.values()
                                         for p in ps)
        from blaze_tpu.frontend.converter import SparkPlanConverter

        for name in ("q3", "q7", "q53", "q67", "q96"):
            plan_json, oracle, extract, flags = QUERIES[name]()
            conv = SparkPlanConverter(tables=tables)
            res = conv.convert(json.dumps(plan_json))
            assert not [t for t in res.tags if "fallback" in t[1]]
            MemManager.reset()
            t0 = time.perf_counter()
            conf = Config(memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                          mem_wait_timeout_s=5.0,
                          trace_enable=bool(PROFILE_DIR))
            with Session(conf=conf) as sess:
                table = sess.execute_to_table(res.plan)
                spills = sess.metrics.total("spill_count")
                spill_bytes = sess.metrics.total("spilled_bytes")
                from blaze_tpu.runtime.metrics import tripwire_totals

                trips = tripwire_totals(sess.metrics)
                profile = sess.profile()
                if PROFILE_DIR:
                    from blaze_tpu.obs import TRACER, dump_profile

                    dump_profile(sess, PROFILE_DIR, name)
                    TRACER.reset()
            wall = time.perf_counter() - t0
            if extract is None:
                d = table.to_pydict()
                rows = list(zip(*d.values())) if d else []
            else:
                rows = extract(table)
            got = _sorted_if_tied(rows, flags)
            want = _sorted_if_tied(oracle(dfs), flags)
            assert _rows_equal(got, want, flags), f"{name} wrong at scale"
            out["tpcds"][name] = {
                "wall_s": round(wall, 1), "rows_out": len(got),
                "spill_count": int(spills),
                "spilled_bytes": int(spill_bytes),
                "agg_reintern_rows": trips["agg_reintern_rows"],
                "agg_radix_buckets": trips["agg_radix_buckets"],
                "codes_shuffle_bytes": trips["codes_shuffle_bytes"],
                "shuffle_bytes_serialized": trips["shuffle_bytes_serialized"],
                "shm_bytes_mapped": trips["shm_bytes_mapped"],
                "serde_elided_batches": trips["serde_elided_batches"],
                "sharded_stages": trips["sharded_stages"],
                "device_shuffle_bytes": trips["device_shuffle_bytes"],
                "collective_bytes": trips["collective_bytes"],
                "peak_rss_mb": peak_rss_mb(),
            }
            if profile is not None:
                out["tpcds"][name]["stats"] = {
                    "fingerprint": profile["fingerprint"],
                    "device_time_fraction": profile["device_time_fraction"],
                    "stages": [{k: s.get(k) for k in (
                        "stage", "kind", "partitions", "total_bytes",
                        "total_rows", "partition_skew_ratio", "skew")}
                        for s in profile["stages"]],
                }
            print(json.dumps({name: out["tpcds"][name]}), flush=True)
    from blaze_tpu.obs.attribution import artifact_section
    from blaze_tpu.obs.timeline import timeline_artifact_section

    out.update(artifact_section())
    out.update(timeline_artifact_section())
    out["peak_rss_mb"] = peak_rss_mb()
    leaked = shm_roots(shm0)
    out["shm_segments_leaked"] = len(leaked)
    print(json.dumps(out))
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "SOAK_r10.json"), "w") as f:
        json.dump(out, f, indent=1)
    assert not leaked, f"/dev/shm leak: {leaked}"
    # health-state history over the whole soak: never critical, bounded
    # non-healthy time (obs/timeline.py)
    assert out["health"]["critical_intervals"] == 0, out["health"]
    assert out["health"]["degraded_ratio"] <= 0.5, out["health"]


def _result_digest(table) -> str:
    """Stable content hash of an arrow result table, for the multichip
    round's bit-identity gate. ``repr`` of python scalars is exact
    (shortest-roundtrip floats), so two tables hash equal iff every cell —
    including null positions and -0.0 vs 0.0 — is identical, independent
    of chunking."""
    import hashlib

    h = hashlib.sha256()
    h.update(repr(table.schema).encode())
    for name in table.column_names:
        h.update(repr(table[name].to_pylist()).encode())
    return h.hexdigest()[:16]


def multichip_main(n_devices: int):
    """Multichip round: the five bench shapes + the full-fact global sort,
    each run over 1/2/N-device meshes with device-primary execution on
    (``multichip_enabled``), gated on bit-identical results across mesh
    sizes and on the oracle checks at mesh size 1. Writes the structured
    MULTICHIP_r06.json artifact — per-shape wall, n_devices,
    device_time_fraction (stats plane), sharded_stages, collective/device
    shuffle bytes — replacing the raw-stderr-tail format of earlier
    rounds (``scripts/bench_diff.py --multichip`` diffs two of these).

    Dev boxes emulate the mesh: the ``--devices N`` preamble above forces
    ``--xla_force_host_platform_device_count=N`` before jax initializes.
    Env: MULTICHIP_ROWS (2_000_000), MULTICHIP_PARTS (8),
    MULTICHIP_WARMUP (1 — per-(shape, mesh) compile warmup run).
    """
    mc_rows = int(os.environ.get("MULTICHIP_ROWS", 2_000_000))
    mc_parts = int(os.environ.get("MULTICHIP_PARTS", 8))
    warmup = int(os.environ.get("MULTICHIP_WARMUP", 1))
    os.environ["BENCH_ROWS"] = str(mc_rows)
    os.environ["BENCH_PARTITIONS"] = str(mc_parts)

    import bench  # repo-root bench.py (shapes, generators, oracles)
    from blaze_tpu.config import Config
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.runtime.metrics import tripwire_totals
    from blaze_tpu.runtime.session import Session

    avail = len(jax.devices())
    assert avail >= n_devices, \
        f"{n_devices} devices requested, jax sees {avail} " \
        f"(XLA_FLAGS={os.environ.get('XLA_FLAGS')!r})"
    mesh_sizes = sorted({k for k in (1, 2, n_devices) if k <= avail})
    emulated = "xla_force_host_platform_device_count" in \
        os.environ.get("XLA_FLAGS", "")

    def plan_big_sort(paths):
        from blaze_tpu.ir import exprs as E
        from blaze_tpu.ir import nodes as N
        from blaze_tpu.ops.parquet import scan_node_for_files

        scan = scan_node_for_files(paths["store_sales"],
                                   num_partitions=mc_parts)
        orders = [E.SortOrder(E.Column("ss_sales_price"), ascending=False),
                  E.SortOrder(E.Column("ss_item_sk"))]
        ex = N.ShuffleExchange(scan, N.RangePartitioning(
            orders, mc_parts, []))
        return N.Sort(ex, orders)

    def check_big_sort(table, _oracle):
        import pyarrow.compute as pc

        assert table.num_rows == mc_rows, table.num_rows
        prices = table["ss_sales_price"].combine_chunks()
        assert pc.min(pc.subtract(
            prices.cast("float64").slice(0, len(prices) - 1),
            prices.cast("float64").slice(1))).as_py() >= 0

    out = {"metric": "multichip_device_primary",
           "forced_devices": n_devices, "emulated": emulated,
           "rows": mc_rows, "partitions": mc_parts,
           "mesh_sizes": mesh_sizes, "shapes": {}}
    with tempfile.TemporaryDirectory(prefix="blaze_mchip_") as tmpdir:
        t0 = time.perf_counter()
        paths = bench.make_data(tmpdir)
        out["datagen_s"] = round(time.perf_counter() - t0, 1)
        _, oracles = bench.run_baseline(paths)
        oracles["sort10M"] = None

        shapes = list(bench.SHAPES) + [
            ("sort10M", plan_big_sort, None, None, check_big_sort, ())]
        for name, plan_fn, _o, _a, check_fn, _t in shapes:
            per_mesh = {}
            for k in mesh_sizes:
                MemManager.reset()
                conf = Config(multichip_enabled=True, multichip_devices=k)
                for _ in range(warmup):  # compile outside the timed run
                    with Session(conf=conf) as sess:
                        sess.execute_to_table(plan_fn(paths))
                MemManager.reset()
                t0 = time.perf_counter()
                with Session(conf=conf) as sess:
                    table = sess.execute_to_table(plan_fn(paths))
                    wall = time.perf_counter() - t0
                    trips = tripwire_totals(sess.metrics)
                    profile = sess.profile()
                if k == mesh_sizes[0]:
                    check_fn(table, oracles[name])  # absolute correctness
                per_mesh[str(k)] = {
                    "wall_s": round(wall, 3), "n_devices": k,
                    "device_time_fraction":
                        (profile or {}).get("device_time_fraction", 0.0),
                    "sharded_stages": trips["sharded_stages"],
                    "collective_bytes": trips["collective_bytes"],
                    "device_shuffle_bytes": trips["device_shuffle_bytes"],
                    "shuffle_bytes_serialized":
                        trips["shuffle_bytes_serialized"],
                    "serde_elided_batches": trips["serde_elided_batches"],
                    "digest": _result_digest(table),
                }
            digests = {r["digest"] for r in per_mesh.values()}
            top = per_mesh[str(mesh_sizes[-1])]
            out["shapes"][name] = dict(top, per_mesh=per_mesh,
                                       bit_identical=len(digests) == 1)
            print(json.dumps({name: out["shapes"][name]}), flush=True)

    sort_rec = out["shapes"].get("sort10M", {}).get("per_mesh", {})
    w1 = (sort_rec.get(str(mesh_sizes[0])) or {}).get("wall_s")
    wn = (sort_rec.get(str(mesh_sizes[-1])) or {}).get("wall_s")
    out["gates"] = {
        "bit_identical": all(s["bit_identical"]
                             for s in out["shapes"].values()),
        "sort_wall_1dev_s": w1,
        f"sort_wall_{mesh_sizes[-1]}dev_s": wn,
        "sort_speedup": round(w1 / wn, 2) if w1 and wn else None,
    }
    from blaze_tpu.obs.attribution import artifact_section

    out.update(artifact_section())
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "MULTICHIP_r06.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"gates": out["gates"], "artifact": path}), flush=True)
    # the hard gates: every shape must agree across mesh sizes, and the
    # device tiers must not re-serialize shuffle traffic
    for name, rec in out["shapes"].items():
        assert rec["bit_identical"], (name, rec)
    if out["gates"]["sort_speedup"] is not None \
            and out["gates"]["sort_speedup"] < 1.0:
        print(f"WARNING: {mesh_sizes[-1]}-way sort did not beat 1-device "
              f"({wn}s vs {w1}s) — emulated meshes share host cores",
              flush=True)
    print("MULTICHIP ROUND PASSED", flush=True)


def _pctl(vals, q):
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, int(q * len(s)))]


def _write_chaos_section(section: str, data: dict,
                         fname: str = "CHAOS_r01.json") -> str:
    """Merge one section into a chaos artifact at the repo root (the scale
    and serve chaos runs each own a section; reruns replace only their own)."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), fname)
    try:
        with open(path) as f:
            out = json.load(f)
    except (OSError, ValueError):
        out = {}
    out[section] = data
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return path


def chaos_main(kill_every_s: float):
    """Chaos soak (--chaos-kill-every): run the three shuffle-bearing shapes
    repeatedly against a 2-worker pool while a ChaosMonkey hard-kills a
    random worker every ``kill_every_s`` seconds, then gate on

      * zero wrong results (every query bit-identical to the in-driver oracle),
      * zero leaked memory-manager bytes,
      * worker deaths observed and every kill with an incident bundle,
      * >= 1 stage recovered from persisted shuffle outputs (a map output is
        deleted mid-query on a fixed cadence in BOTH phases, so the latency
        populations stay comparable),
      * chaos-phase p99 <= 3x the no-chaos baseline p99.

    The full evidence lands in CHAOS_r01.json (section "scale") BEFORE the
    gates are asserted, so a failing run still leaves its forensics behind.
    Env: CHAOS_ROWS (200_000), CHAOS_ITERS (12).
    """
    import glob

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.config import Config
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T
    from blaze_tpu.obs.dump import list_incidents
    from blaze_tpu.obs.telemetry import get_registry
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime.cluster import ChaosMonkey
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.runtime.session import Session, _QueryRun

    rows = int(os.environ.get("CHAOS_ROWS", 200_000))
    iters = int(os.environ.get("CHAOS_ITERS", 12))

    COUNTERS = ("blaze_cluster_worker_deaths_total",
                "blaze_cluster_tasks_retried_total",
                "blaze_cluster_stages_recovered_total",
                "blaze_cluster_maps_recomputed_total",
                "blaze_chaos_kills_total")

    def counters() -> dict:
        snap = get_registry().to_raw()
        out = {}
        for name in COUNTERS:
            series = snap.get(name, {}).get("series", [])
            out[name] = series[0]["value"] if series else 0
        return out

    def agg_by(col, reducers):
        def mk(paths):
            scan = scan_node_for_files(paths, num_partitions=4)
            ex = N.ShuffleExchange(
                scan, N.HashPartitioning([E.Column(col)], reducers))
            return N.Agg(ex, E.AggExecMode.HASH_AGG, [(col, E.Column(col))], [
                N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("paid")],
                                      T.I64), E.AggMode.COMPLETE, "total")])
        return mk

    def sort_top(paths):
        scan = scan_node_for_files(paths, num_partitions=4)
        orders = [E.SortOrder(E.Column("paid"), ascending=False),
                  E.SortOrder(E.Column("item"))]
        ex = N.ShuffleExchange(scan, N.SinglePartitioning(1))
        return N.Limit(N.Sort(ex, orders), 500)

    shapes = [("agg_store", agg_by("store", 4)),
              ("agg_item", agg_by("item", 8)),
              ("sort_top", sort_top)]

    def canon(table):
        d = table.to_pydict()
        return sorted(zip(*d.values())) if d else []

    import tempfile

    section = {"kill_every_s": kill_every_s, "rows": rows, "iters": iters,
               "phases": {}}
    with tempfile.TemporaryDirectory(prefix="blaze_chaos_") as tmpdir:
        rng = np.random.default_rng(11)
        paths = []
        for p in range(2):
            n = rows // 2
            tbl = pa.table({
                "store": pa.array(rng.integers(1, 41, n), type=pa.int64()),
                "item": pa.array(rng.integers(1, 201, n), type=pa.int64()),
                "paid": pa.array(rng.integers(0, 10_000, n), type=pa.int64()),
            })
            path = os.path.join(tmpdir, f"chaos_{p}.parquet")
            pq.write_table(tbl, path)
            paths.append(path)

        # in-driver oracle: the answers every clustered run must reproduce
        # bit-identically, worker deaths or not
        with Session() as s_local:
            oracle = {name: canon(s_local.execute_to_table(mk(paths)))
                      for name, mk in shapes}

        def run_phase(with_chaos: bool) -> dict:
            MemManager.reset()
            conf = Config(incident_dir=os.path.join(
                tmpdir, "incidents_chaos" if with_chaos else "incidents_base"))
            lats, wrong, injected = [], [], 0
            c0 = counters()
            shm0 = shm_roots()
            with Session(conf=conf, num_worker_processes=2) as sess:
                monkey = None
                if with_chaos:
                    monkey = ChaosMonkey(sess.pool, kill_every_s,
                                         seed=11).start()
                try:
                    for it in range(iters):
                        for name, mk in shapes:
                            t0 = time.perf_counter()
                            if name == "agg_store" and it % 3 == 2:
                                # deterministic lineage exercise: lower (runs
                                # the map stage), delete one committed map
                                # output, then execute — the reduce MUST
                                # recover via lineage recompute
                                before = set(glob.glob(os.path.join(
                                    sess.shuffle_root, "shuffle_*",
                                    "map_*.data")))
                                qrun = _QueryRun(0)
                                sess._tls.qrun = qrun
                                lowered = sess._lower(mk(paths))
                                sess._tls.qrun = None
                                fresh = sorted(
                                    f for f in glob.glob(os.path.join(
                                        sess.shuffle_root, "shuffle_*",
                                        "map_*.data")) if f not in before)
                                if fresh:
                                    # the largest output: an empty map (a
                                    # scan range with no rows writes just
                                    # the footer) wouldn't exercise anything
                                    os.remove(max(fresh,
                                                  key=os.path.getsize))
                                    injected += 1
                                got = canon(sess.execute_to_table(lowered))
                            else:
                                got = canon(sess.execute_to_table(mk(paths)))
                            lats.append(time.perf_counter() - t0)
                            if got != oracle[name]:
                                wrong.append({"iter": it, "shape": name})
                        print(json.dumps({
                            "phase": "chaos" if with_chaos else "baseline",
                            "iter": it, "p99_s": round(_pctl(lats, 0.99), 3),
                            "wrong": len(wrong)}), flush=True)
                finally:
                    if monkey is not None:
                        monkey.stop()
                        # grace: the heartbeat supervisor notices a kill that
                        # landed between the last query and stop()
                        time.sleep(2.0)
                kills = list(monkey.kills) if monkey else []
                from blaze_tpu.runtime.metrics import tripwire_totals

                trips = tripwire_totals(sess.metrics)
                leaked_metric = int(sess.metrics.total(
                    "query_leaked_mem_reclaimed"))
                mm = MemManager._instance
                stats = mm.stats() if mm is not None else {"used": 0,
                                                           "reservations": {}}
                incidents = [i for i in list_incidents(conf)
                             if i["kind"] == "worker_lost"]
            c1 = counters()
            return {
                "lat_s": [round(v, 4) for v in lats],
                "p50_s": round(_pctl(lats, 0.50), 4),
                "p99_s": round(_pctl(lats, 0.99), 4),
                "queries": len(lats),
                "wrong_results": wrong,
                "injected_missing_maps": injected,
                "kills_injected": len(kills),
                "kills": kills,
                "incident_bundles_worker_lost": len(incidents),
                "leaked_mem_reclaimed": leaked_metric,
                "mem_used_after": int(stats["used"]),
                "mem_reservations_after": list(stats["reservations"]),
                "counters_delta": {k: c1[k] - c0[k] for k in COUNTERS},
                # zero-copy tripwires: pool mode negotiates the shm tier, so
                # mapped bytes must flow and shm roots must not outlive the
                # session even with workers dying mid-query
                "shuffle_bytes_serialized": trips["shuffle_bytes_serialized"],
                "shm_bytes_mapped": trips["shm_bytes_mapped"],
                "serde_elided_batches": trips["serde_elided_batches"],
                "shm_segments_leaked": len(shm_roots(shm0)),
            }

        section["phases"]["baseline"] = base = run_phase(with_chaos=False)
        section["phases"]["chaos"] = chaos = run_phase(with_chaos=True)

    d = chaos["counters_delta"]
    section["gates"] = gates = {
        "wrong_results": len(base["wrong_results"])
        + len(chaos["wrong_results"]),
        "leaked_bytes": base["leaked_mem_reclaimed"] + base["mem_used_after"]
        + chaos["leaked_mem_reclaimed"] + chaos["mem_used_after"],
        "shm_segments_leaked": base["shm_segments_leaked"]
        + chaos["shm_segments_leaked"],
        "worker_deaths_total": d["blaze_cluster_worker_deaths_total"],
        "stages_recovered_total": d["blaze_cluster_stages_recovered_total"],
        "maps_recomputed_total": d["blaze_cluster_maps_recomputed_total"],
        "kills_injected": chaos["kills_injected"],
        "incident_bundles": chaos["incident_bundles_worker_lost"],
        "p99_no_chaos_s": base["p99_s"],
        "p99_chaos_s": chaos["p99_s"],
        "p99_inflation": round(chaos["p99_s"] / max(base["p99_s"], 1e-9), 2),
    }
    from blaze_tpu.obs.attribution import artifact_section

    section.update(artifact_section())
    path = _write_chaos_section("scale", section)
    print(json.dumps({"gates": gates, "artifact": path}), flush=True)

    # evidence is on disk; now enforce the gates
    assert gates["wrong_results"] == 0, gates
    assert gates["leaked_bytes"] == 0, gates
    assert gates["shm_segments_leaked"] == 0, gates
    assert gates["worker_deaths_total"] > 0, gates
    assert gates["stages_recovered_total"] >= 1, gates
    assert gates["maps_recomputed_total"] >= 1, gates
    assert gates["kills_injected"] > 0, gates
    assert gates["incident_bundles"] >= gates["kills_injected"], gates
    assert gates["p99_chaos_s"] <= 3.0 * gates["p99_no_chaos_s"], gates
    print("CHAOS SOAK (scale) PASSED", flush=True)


# mid_ingest_kill is a serve-matrix-only mode (serve_soak.py): it needs the
# streaming ingest path and the result cache, which the scale soak doesn't
# exercise — chaos_mode_conf_kwargs contributes nothing for it
CHAOS_MODES = ("kill", "hang", "enospc", "corrupt", "preempt",
               "mid_ingest_kill")


def parse_chaos_spec(spec: str) -> dict:
    """``kill:N,hang:N,enospc:N,corrupt:N,preempt:N`` -> ordered {mode: N}.
    N means seconds-between-kills for ``kill`` and a failpoint every-N
    trigger for the others. Any subset of modes is allowed; unknown modes
    fail. ``preempt`` is scheduler-driven and only meaningful under the
    serve soak's matrix (the scale matrix runs sessions directly)."""
    modes = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        mode, _, val = entry.partition(":")
        if mode not in CHAOS_MODES:
            raise SystemExit(
                f"--chaos-spec: unknown mode {mode!r} "
                f"(one of {', '.join(CHAOS_MODES)})")
        try:
            modes[mode] = float(val) if val else 1.0
        except ValueError:
            raise SystemExit(f"--chaos-spec: bad value in {entry!r}")
    if not modes:
        raise SystemExit("--chaos-spec: empty spec")
    return modes


def chaos_mode_conf_kwargs(mode: str, n: float, seed: int = 3044) -> dict:
    """Config field overrides that arm one injection mode (``kill`` uses a
    ChaosMonkey, not a failpoint, so it contributes none). Shared by the
    scale and serve soaks so both matrices inject identically."""
    if mode == "hang":
        # hang far past the hard timeout: every firing MUST be cancelled by
        # the task_timeout_s monitor, never by the hang expiring. N means
        # "one in N task entries hangs": a probability trigger (an every-N
        # counter would tick in near-lockstep on symmetric workers), drawn
        # from the slot-salted streams so only one worker of the pair
        # hangs and the retry lands on a WARM survivor. The default seed's
        # slot-1 stream fires once at draw ~26 — inside both soaks'
        # per-worker armed call windows (~36 scale, ~51 serve) but past
        # what any respawned worker has left, so one firing cannot cascade
        return {"failpoints": f"worker.task=hang:p{1.0 / max(n, 1):.5f}:600",
                "failpoint_seed": seed, "task_timeout_s": 1.0,
                "fault_exclusion_ttl_s": 2.0}
    if mode == "enospc":
        # shm tier armed so the per-commit headroom/ENOSPC path is the one
        # that fires; the degrade target is the spill-dir tier
        return {"zero_copy_tier": "shm", "failpoint_seed": seed,
                "failpoints": f"shm.commit=enospc:every{int(n)}"}
    if mode == "corrupt":
        # paranoid verification ON: a flipped payload byte must be caught as
        # a crc mismatch and routed into lineage recompute
        return {"shuffle_verify_checksum": True, "failpoint_seed": seed,
                "failpoints": f"frame.decode=corrupt:every{int(n)}"}
    if mode == "preempt":
        # preemption storm: the scheduler preempts on ANY contention (no
        # priority/vtime test), the pause window opens instantly, and a
        # delay at every Nth stage-boundary commit stretches the window the
        # dispatcher needs to land a pause request mid-plan
        return {"serve_preempt_aggressive": True,
                "serve_preempt_after_s": 0.05,
                "serve_preempt_min_run_s": 0.0,
                "failpoint_seed": seed,
                "failpoints":
                    f"serve.preempt=delay:every{max(int(n), 1)}:0.02"}
    return {}


def chaos_matrix_main(spec: str):
    """Chaos matrix (--chaos-spec kill:N,hang:N,enospc:N,corrupt:N): run the
    shuffle-bearing shapes against a 2-worker pool once uninjected, then once
    per requested injection mode, and gate EVERY mode on

      * zero wrong results (bit-identical to the in-driver oracle),
      * zero leaked memory-manager bytes and zero leaked /dev/shm roots,
      * p99 <= 2x the uninjected phase,

    plus per-mode evidence: kill -> worker deaths observed; hang -> hard
    task timeouts fired; enospc -> ``shuffle_tier_degraded`` > 0 (the query
    degraded tiers instead of failing); corrupt -> lineage recomputes > 0.
    Evidence lands in CHAOS_r02.json (section "scale") BEFORE gates are
    asserted. Env: CHAOS_ROWS (200_000), CHAOS_ITERS (6).
    """
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.config import Config, set_config
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T
    from blaze_tpu.obs.telemetry import get_registry
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime import failpoints
    from blaze_tpu.runtime.cluster import ChaosMonkey
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.runtime.session import Session

    modes = parse_chaos_spec(spec)
    if "preempt" in modes:
        # stage-boundary preemption lives in the serve scheduler; the scale
        # matrix calls Session.execute_to_table directly so nothing would
        # ever pause — refuse rather than green-light a vacuous phase
        raise SystemExit("--chaos-spec: mode 'preempt' is scheduler-driven; "
                         "run it under scripts/serve_soak.py --chaos-spec")
    rows = int(os.environ.get("CHAOS_ROWS", 200_000))
    iters = int(os.environ.get("CHAOS_ITERS", 6))

    COUNTERS = ("blaze_cluster_worker_deaths_total",
                "blaze_cluster_tasks_retried_total",
                "blaze_cluster_tasks_timed_out_total",
                "blaze_cluster_stages_recovered_total",
                "blaze_cluster_maps_recomputed_total",
                "blaze_chaos_kills_total")

    def counters() -> dict:
        snap = get_registry().to_raw()
        out = {}
        for name in COUNTERS:
            series = snap.get(name, {}).get("series", [])
            out[name] = series[0]["value"] if series else 0
        return out

    def agg_by(col, reducers):
        def mk(paths):
            scan = scan_node_for_files(paths, num_partitions=4)
            ex = N.ShuffleExchange(
                scan, N.HashPartitioning([E.Column(col)], reducers))
            return N.Agg(ex, E.AggExecMode.HASH_AGG, [(col, E.Column(col))], [
                N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("paid")],
                                      T.I64), E.AggMode.COMPLETE, "total")])
        return mk

    def sort_top(paths):
        scan = scan_node_for_files(paths, num_partitions=4)
        orders = [E.SortOrder(E.Column("paid"), ascending=False),
                  E.SortOrder(E.Column("item"))]
        ex = N.ShuffleExchange(scan, N.SinglePartitioning(1))
        return N.Limit(N.Sort(ex, orders), 500)

    shapes = [("agg_store", agg_by("store", 4)),
              ("agg_item", agg_by("item", 8)),
              ("sort_top", sort_top)]

    def canon(table):
        d = table.to_pydict()
        return sorted(zip(*d.values())) if d else []

    section = {"spec": spec, "rows": rows, "iters": iters, "phases": {}}
    with tempfile.TemporaryDirectory(prefix="blaze_chaosm_") as tmpdir:
        rng = np.random.default_rng(11)
        paths = []
        for p in range(2):
            n = rows // 2
            tbl = pa.table({
                "store": pa.array(rng.integers(1, 41, n), type=pa.int64()),
                "item": pa.array(rng.integers(1, 201, n), type=pa.int64()),
                "paid": pa.array(rng.integers(0, 10_000, n), type=pa.int64()),
            })
            path = os.path.join(tmpdir, f"chaos_{p}.parquet")
            pq.write_table(tbl, path)
            paths.append(path)

        with Session() as s_local:
            oracle = {name: canon(s_local.execute_to_table(mk(paths)))
                      for name, mk in shapes}

        def run_phase(mode, n) -> dict:
            MemManager.reset()
            kwargs = dict(chaos_mode_conf_kwargs(mode, n)) if mode else {}
            # injection starts AFTER a one-pass JIT warmup (identically in
            # every phase, warmup latencies recorded in every phase): a
            # failpoint landing inside worker compilation would measure the
            # compiler, not the recovery path
            arm_spec = kwargs.pop("failpoints", "")
            arm_timeout = kwargs.pop("task_timeout_s", 0.0)
            conf = Config(incident_dir=os.path.join(
                tmpdir, f"incidents_{mode or 'baseline'}"), **kwargs)
            # the GLOBAL config must match the session conf: driver-side
            # readers (recompute pre-checks, tier selection outside a query)
            # consult get_config(), and the corrupt mode's paranoia level
            # must be coherent between them or recompute pre-checks would
            # pass a crc-corrupt file as healthy
            set_config(conf)
            lats, wrong = [], []
            c0 = counters()
            shm0 = shm_roots()
            with Session(conf=conf, num_worker_processes=2) as sess:
                for name, mk in shapes:  # warmup pass, uninjected
                    t0 = time.perf_counter()
                    if canon(sess.execute_to_table(mk(paths))) != oracle[name]:
                        wrong.append({"iter": "warmup", "shape": name})
                    lats.append(time.perf_counter() - t0)
                if arm_spec:
                    # conf is shared by reference with the pool, so workers
                    # pick the spec up from the next task's shipped conf and
                    # the timeout monitor reads it per stage
                    conf.failpoints = arm_spec
                    conf.task_timeout_s = arm_timeout
                    failpoints.arm_from(conf)
                monkey = ChaosMonkey(sess.pool, n, seed=11).start() \
                    if mode == "kill" else None
                try:
                    for it in range(iters):
                        for name, mk in shapes:
                            t0 = time.perf_counter()
                            got = canon(sess.execute_to_table(mk(paths)))
                            lats.append(time.perf_counter() - t0)
                            if got != oracle[name]:
                                wrong.append({"iter": it, "shape": name})
                        print(json.dumps({
                            "phase": mode or "baseline", "iter": it,
                            "p99_s": round(_pctl(lats, 0.99), 3),
                            "wrong": len(wrong)}), flush=True)
                finally:
                    if monkey is not None:
                        monkey.stop()
                        time.sleep(2.0)  # heartbeat grace for the last kill
                    failpoints.unhang()
                kills = list(monkey.kills) if monkey else []
                tier_degraded = int(sess.metrics.total(
                    "shuffle_tier_degraded"))
                leaked_metric = int(sess.metrics.total(
                    "query_leaked_mem_reclaimed"))
                mm = MemManager._instance
                used_after = int(mm.used) if mm is not None else 0
            fired = failpoints.fired()  # driver-process firings (workers
            failpoints.disarm()         # report through session metrics)
            c1 = counters()
            return {
                "p50_s": round(_pctl(lats, 0.50), 4),
                "p99_s": round(_pctl(lats, 0.99), 4),
                "queries": len(lats),
                "wrong_results": wrong,
                "kills_injected": len(kills),
                "failpoints_fired_in_driver": fired,
                "shuffle_tier_degraded": tier_degraded,
                "leaked_mem_reclaimed": leaked_metric,
                "mem_used_after": used_after,
                "shm_segments_leaked": len(shm_roots(shm0)),
                "counters_delta": {k: c1[k] - c0[k] for k in COUNTERS},
            }

        section["phases"]["baseline"] = base = run_phase(None, 0)
        for mode, n in modes.items():
            section["phases"][mode] = run_phase(mode, n)

    gates = {"p99_baseline_s": base["p99_s"], "modes": {}}
    for mode in modes:
        ph = section["phases"][mode]
        d = ph["counters_delta"]
        gates["modes"][mode] = {
            "wrong_results": len(ph["wrong_results"]),
            "leaked_bytes": ph["leaked_mem_reclaimed"]
            + ph["mem_used_after"],
            "shm_segments_leaked": ph["shm_segments_leaked"],
            "p99_s": ph["p99_s"],
            "p99_inflation": round(ph["p99_s"] / max(base["p99_s"], 1e-9),
                                   2),
            "worker_deaths": d["blaze_cluster_worker_deaths_total"],
            "tasks_timed_out": d["blaze_cluster_tasks_timed_out_total"],
            "maps_recomputed": d["blaze_cluster_maps_recomputed_total"],
            "shuffle_tier_degraded": ph["shuffle_tier_degraded"],
            "kills_injected": ph["kills_injected"],
        }
    section["gates"] = gates
    path = _write_chaos_section("scale", section, fname="CHAOS_r02.json")
    print(json.dumps({"gates": gates, "artifact": path}), flush=True)

    # evidence is on disk; now enforce the matrix gates
    for mode in modes:
        g = gates["modes"][mode]
        assert g["wrong_results"] == 0, (mode, g)
        assert g["leaked_bytes"] == 0, (mode, g)
        assert g["shm_segments_leaked"] == 0, (mode, g)
        assert g["p99_s"] <= 2.0 * gates["p99_baseline_s"], (mode, g)
    if "kill" in modes:
        g = gates["modes"]["kill"]
        assert g["kills_injected"] > 0 and g["worker_deaths"] > 0, g
    if "hang" in modes:
        assert gates["modes"]["hang"]["tasks_timed_out"] > 0, gates
    if "enospc" in modes:
        assert gates["modes"]["enospc"]["shuffle_tier_degraded"] > 0, gates
    if "corrupt" in modes:
        assert gates["modes"]["corrupt"]["maps_recomputed"] > 0, gates
    print("CHAOS MATRIX (scale) PASSED", flush=True)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, metavar="N",
                    help="multichip round: run the bench shapes + the "
                         "global sort over 1/2/N-device meshes (emulated "
                         "via --xla_force_host_platform_device_count, set "
                         "automatically) and write the structured "
                         "MULTICHIP_r06.json artifact instead of soaking")
    ap.add_argument("--chaos-kill-every", type=float, metavar="N",
                    help="chaos mode: hard-kill a random worker every N "
                         "seconds and gate on recovery (CHAOS_r01.json) "
                         "instead of running the scale soak")
    ap.add_argument("--chaos-spec", metavar="SPEC",
                    help="chaos matrix: comma-separated modes "
                         "kill:N,hang:N,enospc:N,corrupt:N — one injected "
                         "phase per mode plus an uninjected baseline, gated "
                         "per mode (CHAOS_r02.json)")
    args = ap.parse_args()
    if args.devices:
        multichip_main(args.devices)
    elif args.chaos_spec:
        chaos_matrix_main(args.chaos_spec)
    elif args.chaos_kill_every:
        chaos_main(args.chaos_kill_every)
    else:
        main()

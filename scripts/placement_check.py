"""Placement-model validation on real hardware (round-2 verdict weak #8).

Runs the bench's q01 shape three ways — device_placement forced "device",
forced "host", and "auto" — on whatever backend `jax.devices()` resolves to,
and prints ONE JSON line with the three wall-clocks plus which choice "auto"
made. Evidence goal: show auto ~= min(host, device) on a chip, i.e. the
measured-link cost model (runtime/placement.py) picks the right side.

Run only when the accelerator is reachable (the tunnel watcher gates this).
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402  (repo-root bench.py: shapes + data generator)


def _run(paths, mode: str) -> float:
    from blaze_tpu.config import Config
    from blaze_tpu.runtime.session import Session

    conf = Config(device_placement=mode)
    t0 = time.perf_counter()
    with Session(conf=conf) as sess:
        sess.execute_to_table(bench.plan_q01(paths))
    return time.perf_counter() - t0


def main():
    import jax

    platform = jax.devices()[0].platform
    with tempfile.TemporaryDirectory(prefix="blaze_placement_") as tmpdir:
        paths = bench.make_data(tmpdir)
        out = {"platform": platform, "rows": bench.ROWS, "modes": {}}
        for mode in ("device", "host", "auto"):
            _run(paths, mode)  # warmup/compile
            times = [_run(paths, mode) for _ in range(2)]
            out["modes"][mode] = round(min(times), 3)
        best = min(out["modes"]["device"], out["modes"]["host"])
        out["auto_overhead_vs_best"] = round(
            out["modes"]["auto"] / best, 3) if best else None
        print(json.dumps(out))


if __name__ == "__main__":
    main()

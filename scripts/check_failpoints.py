#!/usr/bin/env python3
"""Static lint for failpoint injection sites.

Walks every ``failpoint("...")`` call site (bare name or attribute form,
literal first argument) under ``blaze_tpu/`` and ``scripts/`` and enforces:

1. every site name is registered in ``runtime.failpoints.SITES`` — the
   registry is CLOSED, so a typo'd site silently never fires and a chaos
   spec naming it raises only at arm time; this catches both statically;
2. site names are ``<area>.<name>`` with snake_case segments (sites are
   part of the chaos-spec surface, so names are API);
3. every registered site has at least one call site — a SITES entry whose
   hook was refactored away is dead spec surface that arms successfully
   but can never fire;
4. at least one call site exists at all (scan-root tripwire, mirroring
   check_metrics_names.py).

Tests are deliberately NOT scanned: they call failpoint() with made-up
names to assert the no-rule fast path. Standalone: exits 1 with a report
on any violation. Also run by ``tests/test_failpoints.py`` in the quick
tier.
"""

from __future__ import annotations

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_DIRS = ("blaze_tpu", "scripts")
SITE_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$")


def _called_name(func) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def iter_call_sites(root: str):
    """Yield (relpath, lineno, site) for literal-name failpoint() calls."""
    for scan in SCAN_DIRS:
        base = os.path.join(root, scan)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                with open(path) as f:
                    try:
                        tree = ast.parse(f.read(), filename=path)
                    except SyntaxError as exc:
                        yield (os.path.relpath(path, root),
                               exc.lineno or 0, f"<syntax: {exc}>")
                        continue
                for node in ast.walk(tree):
                    if not (isinstance(node, ast.Call)
                            and _called_name(node.func) == "failpoint"
                            and node.args
                            and isinstance(node.args[0], ast.Constant)
                            and isinstance(node.args[0].value, str)):
                        continue
                    yield (os.path.relpath(path, root), node.lineno,
                           node.args[0].value)


def run_lint(root: str = REPO):
    """Returns a list of violation strings (empty = clean)."""
    sys.path.insert(0, root)
    from blaze_tpu.runtime.failpoints import SITES

    violations = []
    used = set()
    count = 0
    for path, lineno, site in iter_call_sites(root):
        where = f"{path}:{lineno}"
        if site.startswith("<syntax:"):
            violations.append(f"{where}: unparseable: {site}")
            continue
        count += 1
        used.add(site)
        if site not in SITES:
            violations.append(
                f"{where}: failpoint site {site!r} not in "
                f"runtime.failpoints.SITES (registered: "
                f"{', '.join(SITES)})")
        if not SITE_RE.match(site):
            violations.append(
                f"{where}: failpoint site {site!r} is not "
                f"<area>.<name> snake.dotted form")
    for site in SITES:
        if site not in used:
            violations.append(
                f"runtime/failpoints.py: SITES entry {site!r} has no "
                f"failpoint() call site — dead injection surface")
    if count == 0:
        violations.append("no failpoint() call sites found — "
                          "scan roots wrong?")
    return violations


def main() -> int:
    violations = run_lint()
    if violations:
        print(f"check_failpoints: {len(violations)} violation(s)")
        for v in violations:
            print(f"  {v}")
        return 1
    print("check_failpoints: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

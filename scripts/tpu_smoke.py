"""TPU hardware smoke test: runs a q01-class pipeline on the real chip.

The pytest suite runs on a forced-CPU 8-device mesh (semantics + sharding);
this script validates the pieces whose behavior differs on real TPU hardware:
int64 emulation, the f64->host routing (utils/device.py), device sort with
native-dtype operands, scatter-based aggregation, and spark hashes on device.

Run: python scripts/tpu_smoke.py   (from the repo root, no JAX_PLATFORMS set)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # CPU validation run (CI): drop the axon plugin entirely — its
    # registration can hang on a wedged tunnel even under a cpu pin
    sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pyarrow as pa
from decimal import Decimal

import jax

import blaze_tpu  # noqa: F401
from blaze_tpu.core.batch import ColumnarBatch, DeviceColumn, HostColumn
from blaze_tpu.exprs import spark_hash as H
from blaze_tpu.ir import exprs as E
from blaze_tpu.ir import types as T
from blaze_tpu.ops.agg import AggExec
from blaze_tpu.ops.basic import FilterExec, MemoryScanExec, ProjectExec
from blaze_tpu.ops.base import ExecContext
from blaze_tpu.ops.sort import SortExec
from blaze_tpu.utils.device import supports_f64


def main():
    dev = jax.devices()[0]
    print(f"device: {dev} platform={dev.platform}")
    print(f"supports_f64: {supports_f64()}")

    rng = np.random.default_rng(0)
    n = 50_000
    tbl = pa.table({
        "store_sk": pa.array(rng.integers(1, 100, n), type=pa.int64()),
        "return_amt": pa.array(
            [Decimal(int(v)).scaleb(-2) for v in rng.integers(0, 100_000, n)],
            type=pa.decimal128(7, 2)),
        "ratio": pa.array(rng.random(n) * 1e200, type=pa.float64()),
        "reason": pa.array(rng.choice(["DAMAGED", "OTHER", "EXPIRED"], n)),
    })
    batches = [ColumnarBatch.from_arrow(tbl.slice(i, 8192)) for i in range(0, n, 8192)]
    b0 = batches[0]
    # f64 must be host-resident on TPU (exactness), decimal on device
    f64_col = b0.columns[2]
    dec_col = b0.columns[1]
    if not supports_f64():
        assert isinstance(f64_col, HostColumn), "f64 must route host on TPU"
    assert isinstance(dec_col, DeviceColumn), "decimal(7,2) must be on device"
    # exactness probe: 1e200-scale doubles survive round trip
    assert all(np.isfinite(v) for v in b0.to_pydict()["ratio"][:100])

    scan = MemoryScanExec(b0.schema, [batches])
    pipeline = AggExec(
        FilterExec(scan, [E.BinaryExpr(E.BinaryOp.GT, E.Column("return_amt"),
                                       E.Literal("100.00", T.DecimalType(7, 2)))]),
        E.AggExecMode.HASH_AGG,
        [("store_sk", E.Column("store_sk"))],
        [
            __import__("blaze_tpu.ir.nodes", fromlist=["AggColumn"]).AggColumn(
                E.AggExpr(E.AggFunction.SUM, [E.Column("return_amt")],
                          T.DecimalType(17, 2)), E.AggMode.COMPLETE, "total"),
            __import__("blaze_tpu.ir.nodes", fromlist=["AggColumn"]).AggColumn(
                E.AggExpr(E.AggFunction.COUNT, []), E.AggMode.COMPLETE, "cnt"),
        ],
    )
    top = SortExec(pipeline, [E.SortOrder(E.Column("total"), ascending=False)],
                   fetch_limit=10)

    t0 = time.perf_counter()
    out = []
    for batch in top.execute(0, ExecContext()):
        out.append(batch.to_arrow())
    t1 = time.perf_counter()
    result = pa.Table.from_batches(out).to_pydict()
    print(f"pipeline: {n} rows -> top {len(result['store_sk'])} groups "
          f"in {t1 - t0:.2f}s (first run includes compile)")

    # cross-check against pandas
    df = tbl.to_pandas()
    df = df[df.return_amt > Decimal("100.00")]
    exp = df.groupby("store_sk").agg(total=("return_amt", "sum"), cnt=("store_sk", "size"))
    exp = exp.sort_values("total", ascending=False).head(10)
    assert result["store_sk"] == exp.index.tolist(), "group keys mismatch"
    assert result["total"] == exp.total.tolist(), "sums mismatch"
    assert result["cnt"] == exp.cnt.tolist(), "counts mismatch"

    # device murmur3 partition routing matches host
    col = batches[0].columns[0]
    h_dev = H.hash_batch([col], batches[0].num_rows, batches[0].capacity)
    vals = np.asarray(col.data[: batches[0].num_rows])
    h_np = H.murmur3_int64_np(vals, np.full(len(vals), 42, np.uint32)).view(np.int32)
    assert (h_dev == h_np).all(), "device murmur3 != host murmur3"

    # second run: compiled cache
    t0 = time.perf_counter()
    for batch in top.execute(0, ExecContext()):
        batch.to_arrow()
    t1 = time.perf_counter()
    print(f"second run: {t1 - t0:.2f}s")

    # broadcast join + agg through the Session (q06-class)
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.runtime.session import Session

    items = pa.table({
        "store_sk": pa.array(np.arange(1, 100), type=pa.int64()),
        "region": pa.array([f"r{v % 5}" for v in range(1, 100)]),
    })
    sess = Session()
    sess.resources["sales"] = lambda p: [tbl.slice(p * 25_000, 25_000)]
    sess.resources["stores"] = lambda p: [items]
    scan_s = N.FFIReader(schema=b0.schema, resource_id="sales", num_partitions=2)
    scan_i = N.FFIReader(schema=T.schema_from_arrow(items.schema),
                         resource_id="stores", num_partitions=1)
    join = N.BroadcastJoin(scan_s, N.BroadcastExchange(scan_i),
                           [(E.Column("store_sk"), E.Column("store_sk"))],
                           N.JoinType.INNER, N.JoinSide.RIGHT, "smoke_stores")
    partial = N.Agg(join, E.AggExecMode.HASH_AGG, [("region", E.Column("region"))],
                    [N.AggColumn(E.AggExpr(E.AggFunction.COUNT, []),
                                 E.AggMode.PARTIAL, "n")])
    ex = N.ShuffleExchange(partial, N.HashPartitioning([E.Column("region")], 2))
    final = N.Agg(ex, E.AggExecMode.HASH_AGG, [("region", E.Column("region"))],
                  [N.AggColumn(E.AggExpr(E.AggFunction.COUNT, []),
                               E.AggMode.FINAL, "n")])
    plan = N.Sort(N.ShuffleExchange(final, N.SinglePartitioning(1)),
                  [E.SortOrder(E.Column("region"))])
    t0 = time.perf_counter()
    out2 = sess.execute_to_pydict(plan)
    t1 = time.perf_counter()
    m = tbl.to_pandas().merge(items.to_pandas(), on="store_sk")
    exp2 = m.groupby("region").size().sort_index()
    assert out2["region"] == exp2.index.tolist()
    assert out2["n"] == exp2.tolist()
    print(f"broadcast-join pipeline OK in {t1 - t0:.2f}s")

    # single-chip mesh step (all_to_all degenerates but the kernel compiles)
    from blaze_tpu.parallel.mesh import make_mesh, run_distributed_sum

    keys = np.asarray(tbl["store_sk"][:4096]).astype(np.int64)
    ones = np.ones(len(keys), dtype=np.int64)
    mesh_out = run_distributed_sum(keys, ones, make_mesh(1))
    assert sum(c for _, c in mesh_out.values()) == len(keys)
    print("mesh exchange kernel OK on device")

    # round-2 device paths on real hardware: the general batch exchange
    # through Session(mesh) and the device FINAL merge kernel
    from blaze_tpu.runtime.session import Session as _S
    from blaze_tpu.utils.device import DEVICE_STATS

    DEVICE_STATS.reset()
    with _S(mesh=make_mesh(1)) as sm:
        sm.resources["sales"] = sess.resources["sales"]
        sm.resources["stores"] = sess.resources["stores"]
        t0 = time.perf_counter()
        out3 = sm.execute_to_pydict(plan)
        t1 = time.perf_counter()
    assert out3["region"] == exp2.index.tolist()
    assert out3["n"] == exp2.tolist()
    print(f"mesh-exchange Session OK in {t1 - t0:.2f}s; "
          f"device stats: {DEVICE_STATS.snapshot()}")

    # wide-decimal limb SUM on the chip (round-2 continuation): totals
    # overflow int64, partial+merge run as two-int64-limb device kernels
    import tempfile

    import pyarrow.parquet as pq

    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ops.parquet import scan_node_for_files

    rng2 = np.random.default_rng(19)
    nw = 20000
    wk = rng2.integers(1, 9, nw)
    wu = rng2.integers(7 * 10**16, 9 * 10**16, nw)
    wtbl = pa.table({
        "k": pa.array(wk, type=pa.int64()),
        "v": pa.array([Decimal(int(u)).scaleb(-2) for u in wu],
                      type=pa.decimal128(17, 2)),
        "unused": pa.array(rng2.integers(0, 5, nw), type=pa.int64()),
    })
    D27 = T.DecimalType(27, 2)
    with tempfile.TemporaryDirectory() as td:
        fp = os.path.join(td, "wide.parquet")
        pq.write_table(wtbl, fp)
        scan = scan_node_for_files([fp])
        partial = N.Agg(scan, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))], [
            N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("v")], D27),
                        E.AggMode.PARTIAL, "total")])
        ex = N.ShuffleExchange(partial, N.HashPartitioning([E.Column("k")], 2))
        final = N.Agg(ex, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))], [
            N.AggColumn(E.AggExpr(E.AggFunction.SUM, [E.Column("v")], D27),
                        E.AggMode.FINAL, "total")])
        t0 = time.perf_counter()
        wout = sess.execute_to_pydict(
            N.Sort(N.ShuffleExchange(final, N.SinglePartitioning(1)),
                   [E.SortOrder(E.Column("k"))]))
        t1 = time.perf_counter()
        wexp = {}
        for k, u in zip(wk, wu):
            wexp[int(k)] = wexp.get(int(k), 0) + int(u)
        assert any(tot > 2**63 for tot in wexp.values())
        assert wout["k"] == sorted(wexp)
        assert wout["total"] == [Decimal(wexp[k]).scaleb(-2) for k in sorted(wexp)]
        print(f"wide-decimal limb SUM (pruned scan) OK in {t1 - t0:.2f}s")

    # round-5 operator classes on the chip: union of two scans, an
    # EXISTENCE join, and a rank window over the aggregated output —
    # the shapes the 28-query gate exercises on the CPU mesh
    rng5 = np.random.default_rng(23)
    nu = 20000
    t_a = pa.table({"k": pa.array(rng5.integers(1, 40, nu), type=pa.int64()),
                    "v": pa.array(rng5.integers(0, 500, nu), type=pa.int64())})
    t_b = pa.table({"k": pa.array(rng5.integers(1, 40, nu), type=pa.int64()),
                    "v": pa.array(rng5.integers(0, 500, nu), type=pa.int64())})
    act = pa.table({"ak": pa.array(np.arange(1, 40, 3), type=pa.int64())})
    s5 = Session()
    s5.resources["u_a"] = lambda p: [t_a]
    s5.resources["u_b"] = lambda p: [t_b]
    s5.resources["u_act"] = lambda p: [act]
    sc_a = N.FFIReader(schema=T.schema_from_arrow(t_a.schema),
                       resource_id="u_a", num_partitions=1)
    sc_b = N.FFIReader(schema=T.schema_from_arrow(t_b.schema),
                       resource_id="u_b", num_partitions=1)
    sc_act = N.FFIReader(schema=T.schema_from_arrow(act.schema),
                         resource_id="u_act", num_partitions=1)
    u = N.Union([sc_a, sc_b])
    ej = N.BroadcastJoin(u, N.BroadcastExchange(sc_act),
                         [(E.Column("k"), E.Column("ak"))],
                         N.JoinType.EXISTENCE, N.JoinSide.RIGHT, "smoke_act")
    f5 = N.Filter(ej, [E.Column("exists#0")])
    partial5 = N.Agg(f5, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))],
                     [N.AggColumn(E.AggExpr(E.AggFunction.SUM,
                                            [E.Column("v")]),
                                  E.AggMode.PARTIAL, "s")])
    ex5 = N.ShuffleExchange(partial5, N.HashPartitioning([E.Column("k")], 2))
    final5 = N.Agg(ex5, E.AggExecMode.HASH_AGG, [("k", E.Column("k"))],
                   [N.AggColumn(E.AggExpr(E.AggFunction.SUM,
                                          [E.Column("v")]),
                                E.AggMode.FINAL, "s")])
    srt5 = N.Sort(N.ShuffleExchange(final5, N.SinglePartitioning(1)),
                  [E.SortOrder(E.Column("s"), ascending=False)])
    win5 = N.Window(srt5, [N.WindowExpr("rank", "rk")], [],
                    [E.SortOrder(E.Column("s"), ascending=False)])
    plan5 = N.Filter(win5, [E.BinaryExpr(E.BinaryOp.LTEQ, E.Column("rk"),
                                         E.Literal(5, T.I32))])
    t0 = time.perf_counter()
    out5 = s5.execute_to_pydict(plan5)
    t1 = time.perf_counter()
    import pandas as pd

    dfu = pd.concat([t_a.to_pandas(), t_b.to_pandas()])
    dfu = dfu[dfu.k.isin(set(np.arange(1, 40, 3).tolist()))]
    g5 = dfu.groupby("k").v.sum().sort_values(ascending=False)
    top = g5[g5.rank(method="min", ascending=False) <= 5]
    assert sorted(out5["s"]) == sorted(top.tolist())
    print(f"union+existence+rank pipeline OK in {t1 - t0:.2f}s")
    print("TPU SMOKE OK")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Diff two bench/soak artifacts and fail on regression.

The repo accumulates BENCH_rNN.json / SOAK_rNN.json artifacts per round;
until now comparing them was a by-eye job. This script makes the comparison
mechanical so a round gate (or CI) can run::

    python scripts/bench_diff.py BENCH_r09.json BENCH_r10.json

and get exit 1 iff the candidate regressed against the base:

- per-shape wall clock grew beyond ``--wall-tol`` (default 25% — bench
  boxes are noisy; this catches step-function regressions, not jitter)
- a zero-expected invariant tripwire went nonzero in the candidate
  (``window_group_loops``, ``fused_fallback_batches``, ``agg_reintern_rows``
  — a silently-degraded fast path, regardless of timing)
- ``shuffle_bytes_serialized`` grew beyond ``--bytes-tol`` (default 10%)
  over the base: the zero-copy tiers (serde elision, shm hand-off) started
  re-serializing shuffle traffic
- ``kernel_time_s`` exceeds the shape's wall clock in the candidate but
  not in the base: the union-of-intervals kernel timer guarantees
  ``kernel_time_s <= wall`` by construction, so a NEW violation means the
  timer is double-counting again (pre-fix artifacts like BENCH_r09 carry
  the old double-counted numbers; a self-diff of those must stay clean)

Both BENCH artifacts (``shapes.<q>.value`` + ``kernel_stats``) and SOAK
artifacts (``shapes.<q>.wall_s`` with tripwires inline) are understood;
shapes present in only one artifact are reported but not failed (new
shapes are growth, not regression).

``--chaos`` switches to the CHAOS_rNN.json matrix schema (PR 12's
``--chaos-spec`` soaks) and gates on fault-injection semantics instead::

    python scripts/bench_diff.py --chaos CHAOS_r02.json CHAOS_r03.json

- correctness/leak fields (wrong results, leaked bytes/segments, hard
  failures, client-visible retryables, gave-up queries) must be 0 in the
  candidate — absolute, not relative;
- a mode's injection EVIDENCE counter (kill -> worker deaths, hang ->
  tasks timed out, enospc -> shuffle_tier_degraded, corrupt ->
  maps_recomputed, mid_ingest_kill -> worker deaths + cache epoch
  evictions) must not drop to zero when the base proves it fired:
  a refactor that silently unhooks a failpoint site still "passes" every
  latency gate, and this is the check that catches it;
- per-mode p99 inflation over the in-artifact baseline must stay within
  ``--inflation-tol`` of the base's AND under the 2.0x hard ceiling;
- a mode covered by the base must still be covered by the candidate, and
  the serve section's auto-retry proof must stay present and correct.

``--multichip`` diffs two MULTICHIP_rNN.json device-primary rounds
(``scripts/scale_soak.py --devices N``)::

    python scripts/bench_diff.py --multichip MULTICHIP_r06.json MULTICHIP_r07.json

- every candidate shape must be bit-identical across its mesh sizes —
  absolute, the multichip contract;
- per-shape wall at the top mesh size must stay within ``--wall-tol`` of
  the base;
- ``device_time_fraction`` must not drop more than ``--frac-tol`` below
  the base (host round-trips crept back into a device-resident plan);
- ``sharded_stages`` proven live by the base must not fall to 0 (the
  mesh path silently stopped engaging), and ``shuffle_bytes_serialized``
  must not appear where the base had none (serde crept back in).
  Pre-r06 raw-stderr artifacts carry no ``shapes`` section: as a base
  they contribute no relative gates; as a candidate they fail.

``--serve`` diffs two SERVE_rNN.json serving soaks (PR 13's multi-tenant
QoS artifacts)::

    python scripts/bench_diff.py --serve SERVE_r02.json SERVE_r03.json

- hygiene fields (failed, leaked_mem, shm_segments_leaked) must be 0 in
  the candidate — absolute;
- door give-ups (``shed_door``) must not grow over the base: Retry-After
  backpressure turns blind abandonment into bounded waiting;
- the candidate's ``light_p99_ratio`` must stay under the 1.5x isolation
  ceiling, and per-tenant p99s within ``--p99-tol`` of the base;
- the preemption tripwires (``queries_preempted``,
  ``stages_resumed_from_cursor``, ``backpressure_429s``) must not fall to
  zero once a base artifact proves them live (skipped when the candidate
  records no tripwire section — the cache soak's SERVE_r04 schema), and
  the preemption proof must still resume bit-identical;
- the result-cache gates (SERVE_r04+): ``cache_hit_rate`` must not drop
  more than 0.2 below the base, and ``cache_stale_served`` must be 0 —
  a stale entry is never served without a refresh.

``--attribution`` gates on the per-category exclusive wall decomposition
(PR 15's why-is-it-slow plane) instead of total wall clock::

    python scripts/bench_diff.py --attribution BENCH_r10.json BENCH_r11.json

- for each shape present in BOTH artifacts with an ``attribution``
  section, each ``<category>_time_ns`` in the candidate must stay under
  ``ratio x max(base, floor)`` where the floor is ``--attr-min-ms``
  (default 50ms — sub-floor categories are noise) and the ratio is
  ``--attr-jit-ratio`` for ``jit_compile_time_ns`` (default 3.0 —
  compile time is the classic flat-wall regression: caching broke but a
  faster kernel hid it) and ``--attr-ratio`` for everything else
  (default 2.0). This catches category-level regressions even when the
  shape's total wall is flat;
- ``fused_op_fraction`` (from the shape's ``decision_audit``) must not
  drop more than 0.2 below the base: the fusion tripwire — chains
  silently stopped fusing;
- shapes or sections missing from either artifact are skipped clean
  (pre-attribution artifacts like BENCH_r10 carry no sections; a
  self-diff of those must stay clean).

``--health`` gates on the ``health`` section soak artifacts carry since
the live health plane (SERVE_r05 / SOAK_r10)::

    python scripts/bench_diff.py --health SOAK_r10.json SOAK_r11.json

- any ``critical`` interval in the candidate's health history is a
  regression — absolute (the burn-rate evaluator needs both its fast and
  slow windows burning, so this is never a one-sample blip);
- a subsystem still ``critical`` at the end of the run fails;
- the degraded-time ratio must stay under ``max(base,
  --degraded-tol)`` (default 0.25);
- artifacts without a ``health`` section are skipped clean, like
  ``--attribution`` does for pre-attribution rounds.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

# tripwires that must be 0 in every healthy run (runtime/metrics.py keeps
# the authoritative list; these are the subset whose nonzero value means a
# degraded fast path rather than a workload property)
ZERO_EXPECTED = ("window_group_loops", "fused_fallback_batches",
                 "agg_reintern_rows")


def _shape_wall(rec: dict):
    for key in ("value", "wall_s"):
        if key in rec:
            return float(rec[key])
    return None


def _shape_counters(rec: dict) -> dict:
    # BENCH nests tripwires under kernel_stats; SOAK inlines them
    return rec.get("kernel_stats") or rec


def diff_artifacts(base: dict, cand: dict, wall_tol: float = 0.25,
                   bytes_tol: float = 0.10) -> List[str]:
    """Return regression descriptions (empty == candidate is no worse)."""
    regressions: List[str] = []
    base_shapes = base.get("shapes") or {}
    cand_shapes = cand.get("shapes") or {}
    for name, crec in sorted(cand_shapes.items()):
        brec = base_shapes.get(name)
        cwall = _shape_wall(crec)
        ctr = _shape_counters(crec)

        for t in ZERO_EXPECTED:
            if int(ctr.get(t, 0) or 0) != 0:
                regressions.append(
                    f"{name}: zero-expected tripwire {t}={ctr[t]}")

        kt = ctr.get("kernel_time_s")
        if kt is not None and cwall is not None and float(kt) > cwall:
            bctr0 = _shape_counters(brec) if brec is not None else {}
            bkt = bctr0.get("kernel_time_s")
            bwall0 = _shape_wall(brec) if brec is not None else None
            base_broken = (bkt is not None and bwall0 is not None
                           and float(bkt) > bwall0)
            if not base_broken:
                regressions.append(
                    f"{name}: kernel_time_s {kt} > wall {cwall}"
                    " (union timer invariant broken)")
            else:
                print(f"  {name}: kernel_time_s > wall in BOTH artifacts"
                      " (pre-fix base), not treated as regression")

        if brec is None:
            print(f"  {name}: new shape (no base), skipped comparison")
            continue
        bwall = _shape_wall(brec)
        if bwall and cwall is not None and cwall > bwall * (1 + wall_tol):
            regressions.append(
                f"{name}: wall {cwall}s vs base {bwall}s"
                f" (+{(cwall / bwall - 1) * 100:.0f}% > {wall_tol * 100:.0f}%)")

        bctr = _shape_counters(brec)
        bser = int(bctr.get("shuffle_bytes_serialized", 0) or 0)
        cser = int(ctr.get("shuffle_bytes_serialized", 0) or 0)
        # +4KB absolute slack: a base of 0 must not fail on any nonzero
        if cser > bser * (1 + bytes_tol) + 4096:
            regressions.append(
                f"{name}: shuffle_bytes_serialized {cser} vs base {bser}"
                " (zero-copy tier regression)")
    return regressions


# chaos-matrix fields that must be 0 in every candidate, wherever present
CHAOS_ZERO = ("wrong_results", "leaked_bytes", "shm_segments_leaked",
              "hard_failures", "client_visible_retryable", "gave_up",
              "cache_stale_served", "stale_entries_surviving")
# per-mode proof that the injection actually reached its target
CHAOS_EVIDENCE = {"kill": ("worker_deaths", "kills_injected"),
                  "hang": ("tasks_timed_out",),
                  "enospc": ("shuffle_tier_degraded",),
                  "corrupt": ("maps_recomputed",),
                  "preempt": ("queries_preempted", "stage_resumes"),
                  "mid_ingest_kill": ("worker_deaths", "kills_injected",
                                      "cache_epoch_evictions")}
# modes whose latency is allowed to blow out by design (a preemption storm
# parks victims at stage boundaries; the ingest-kill phase measures
# recovery refreshes); correctness/evidence gates still bind
CHAOS_P99_WAIVED = ("preempt", "mid_ingest_kill")


def diff_chaos(base: dict, cand: dict,
               inflation_tol: float = 0.25) -> List[str]:
    """Regressions between two CHAOS_rNN.json matrices (empty == clean)."""
    regressions: List[str] = []
    for sec_name, csec in sorted(cand.items()):
        bsec = base.get(sec_name) or {}
        cmodes = (csec.get("gates") or {}).get("modes") or {}
        bmodes = (bsec.get("gates") or {}).get("modes") or {}
        if not cmodes:
            print(f"  {sec_name}: no gates.modes (pre-matrix artifact?),"
                  " skipped")
            continue
        for mode in sorted(bmodes):
            if mode not in cmodes:
                regressions.append(
                    f"{sec_name}/{mode}: mode covered by base but absent "
                    f"from candidate (injection coverage loss)")
        for mode, cg in sorted(cmodes.items()):
            for field in CHAOS_ZERO:
                if int(cg.get(field, 0) or 0) != 0:
                    regressions.append(
                        f"{sec_name}/{mode}: {field}={cg[field]} (must "
                        f"be 0 under injection)")
            bg = bmodes.get(mode)
            for field in CHAOS_EVIDENCE.get(mode, ()):
                if bg is not None and int(bg.get(field, 0) or 0) > 0 \
                        and int(cg.get(field, 0) or 0) == 0:
                    regressions.append(
                        f"{sec_name}/{mode}: {field} fell to 0 (base "
                        f"{bg[field]}) — injection no longer reaches "
                        f"its target")
            cinf = cg.get("p99_inflation")
            if mode in CHAOS_P99_WAIVED:
                print(f"  {sec_name}/{mode}: p99 gates waived "
                      f"(inflation {cinf}; storm mode is correctness-gated)")
            elif cinf is not None:
                if float(cinf) > 2.0:
                    regressions.append(
                        f"{sec_name}/{mode}: p99_inflation {cinf} over "
                        f"the 2.0x hard ceiling")
                binf = (bg or {}).get("p99_inflation")
                if binf is not None and \
                        float(cinf) > float(binf) + inflation_tol:
                    regressions.append(
                        f"{sec_name}/{mode}: p99_inflation {cinf} vs "
                        f"base {binf} (+>{inflation_tol})")
            if bg is None:
                print(f"  {sec_name}/{mode}: new mode (no base), zero/"
                      f"ceiling gates only")
        cgates = csec.get("gates") or {}
        if "retry_proof_serve_retries" in ((bsec.get("gates")) or {}):
            if not cgates.get("retry_proof_correct") \
                    or int(cgates.get("retry_proof_serve_retries", 0)
                           or 0) < 1:
                regressions.append(
                    f"{sec_name}: serve auto-retry proof regressed "
                    f"(correct={cgates.get('retry_proof_correct')}, "
                    f"retries={cgates.get('retry_proof_serve_retries')})")
    return regressions


def diff_multichip(base: dict, cand: dict, wall_tol: float = 0.25,
                   frac_tol: float = 0.10) -> List[str]:
    """Regressions between two MULTICHIP_rNN.json device-primary rounds
    (empty == candidate is no worse). Absolute gates (bit-identity) apply
    to every candidate shape; relative gates (wall, device fraction,
    mesh-path liveness, serde creep) apply where the base measured the
    same shape."""
    regressions: List[str] = []
    cand_shapes = cand.get("shapes") or {}
    if not cand_shapes:
        return ["candidate has no shapes section (pre-r06 raw artifact"
                " cannot be gated)"]
    base_shapes = base.get("shapes") or {}
    if not base_shapes:
        print("  base has no shapes section (pre-r06 raw artifact);"
              " absolute gates only")
    for name, crec in sorted(cand_shapes.items()):
        if not crec.get("bit_identical", False):
            regressions.append(
                f"{name}: results not bit-identical across mesh sizes "
                f"{sorted((crec.get('per_mesh') or {}))}")
        brec = base_shapes.get(name)
        if brec is None:
            if base_shapes:
                print(f"  {name}: new shape (no base), absolute gates only")
            continue
        bwall, cwall = brec.get("wall_s"), crec.get("wall_s")
        if bwall and cwall is not None and \
                float(cwall) > float(bwall) * (1 + wall_tol):
            regressions.append(
                f"{name}: wall {cwall}s vs base {bwall}s at "
                f"{crec.get('n_devices')} devices "
                f"(+{(float(cwall) / float(bwall) - 1) * 100:.0f}% > "
                f"{wall_tol * 100:.0f}%)")
        bfrac = float(brec.get("device_time_fraction") or 0.0)
        cfrac = float(crec.get("device_time_fraction") or 0.0)
        if bfrac > 0 and cfrac < bfrac - frac_tol:
            regressions.append(
                f"{name}: device_time_fraction {cfrac} vs base {bfrac} "
                f"(-{bfrac - cfrac:.3f} > {frac_tol}; host round-trips "
                f"crept back into the device-resident plan)")
        if int(brec.get("sharded_stages", 0) or 0) > 0 and \
                int(crec.get("sharded_stages", 0) or 0) == 0:
            regressions.append(
                f"{name}: sharded_stages fell to 0 (base "
                f"{brec['sharded_stages']}) — the mesh path no longer "
                f"engages")
        bser = int(brec.get("shuffle_bytes_serialized", 0) or 0)
        cser = int(crec.get("shuffle_bytes_serialized", 0) or 0)
        if cser > bser * 1.10 + 4096:
            regressions.append(
                f"{name}: shuffle_bytes_serialized {cser} vs base {bser} "
                f"(serde crept back into the device tiers)")
    return regressions


def diff_attribution(base: dict, cand: dict, ratio: float = 2.0,
                     jit_ratio: float = 3.0,
                     min_ms: float = 50.0) -> List[str]:
    """Regressions between the per-shape ``attribution`` sections of two
    BENCH artifacts (empty == clean). A category regresses when the
    candidate exceeds ``ratio x max(base, floor)``; the floor keeps noise
    categories (sub-``min_ms``) from tripping on jitter. Shapes/sections
    absent from either side are skipped clean so pre-attribution
    artifacts (BENCH_r10 and earlier) gate trivially."""
    regressions: List[str] = []
    floor_ns = min_ms * 1e6
    base_shapes = base.get("shapes") or {}
    cand_shapes = cand.get("shapes") or {}
    for name, crec in sorted(cand_shapes.items()):
        brec = base_shapes.get(name)
        cattr = crec.get("attribution")
        battr = (brec or {}).get("attribution")
        if cattr is None or battr is None:
            which = "candidate" if cattr is None else "base"
            print(f"  {name}: no attribution section in {which}, skipped")
        else:
            cats = sorted(k for k in set(battr) | set(cattr)
                          if k.endswith("_time_ns"))
            for cat in cats:
                bv = float(battr.get(cat, 0) or 0)
                cv = float(cattr.get(cat, 0) or 0)
                r = jit_ratio if cat == "jit_compile_time_ns" else ratio
                limit = r * max(bv, floor_ns)
                if cv > limit:
                    regressions.append(
                        f"{name}: {cat} {cv / 1e6:.1f}ms vs base "
                        f"{bv / 1e6:.1f}ms (> {r:.1f}x max(base, "
                        f"{min_ms:.0f}ms) — category-level regression"
                        f" even if wall is flat)")
        bfrac = ((brec or {}).get("decision_audit")
                 or {}).get("fused_op_fraction")
        cfrac = (crec.get("decision_audit") or {}).get("fused_op_fraction")
        if bfrac is not None and cfrac is not None and \
                float(cfrac) < float(bfrac) - 0.2:
            regressions.append(
                f"{name}: fused_op_fraction {cfrac} vs base {bfrac} "
                f"(-{float(bfrac) - float(cfrac):.2f} > 0.2 — chains "
                f"silently stopped fusing)")
    return regressions


def diff_health(base: dict, cand: dict,
                degraded_tol: float = 0.25) -> List[str]:
    """Regressions between the ``health`` sections two soak artifacts carry
    (PR 20's live health plane; empty == clean). Any ``critical`` interval
    in the candidate's health HISTORY is a regression — absolute, not
    relative: the burn-rate evaluator only reaches critical when both the
    fast and slow windows are burning, so a single sampling hiccup cannot
    trip this. The degraded-time ratio must stay under
    ``max(base, --degraded-tol)`` (a base that ran degraded grandfathers
    its own ratio; the floor keeps a clean base from failing the candidate
    on one short brownout). Artifacts without a ``health`` section (every
    round before SERVE_r05/SOAK_r10) are skipped clean, like
    ``--attribution`` does for pre-attribution rounds."""
    regressions: List[str] = []
    bh, ch = base.get("health"), cand.get("health")
    if ch is None or bh is None:
        which = "candidate" if ch is None else "base"
        print(f"  health: no health section in {which} (pre-health "
              f"artifact), skipped")
        return regressions
    # "enabled" reflects the instant the report was taken (soaks build
    # artifacts after the session closes, which stops the sampler), so
    # judge by recorded history: 0 samples with the plane off is a
    # legitimately disabled run; 0 samples otherwise means the sampler
    # never ran — itself a regression
    if int(ch.get("samples", 0) or 0) == 0:
        if not ch.get("enabled", True):
            print("  health: candidate ran with the timeline disabled, "
                  "history gates vacuous")
        else:
            regressions.append(
                "health: candidate recorded 0 samples (the sampler "
                "thread never ran)")
        return regressions
    crit = int(ch.get("critical_intervals", 0) or 0)
    if crit != 0:
        secs = float(ch.get("critical_s", 0.0) or 0.0)
        regressions.append(
            f"health: {crit} critical interval(s) totalling {secs:.1f}s "
            f"(any critical state in the history is a regression)")
    for sub, state in sorted((ch.get("subsystems") or {}).items()):
        if state == "critical":
            regressions.append(
                f"health: subsystem {sub} ended the run critical")
    bratio = float(bh.get("degraded_ratio", 0.0) or 0.0)
    cratio = float(ch.get("degraded_ratio", 0.0) or 0.0)
    limit = max(bratio, degraded_tol)
    if cratio > limit:
        regressions.append(
            f"health: degraded_ratio {cratio:.3f} vs base {bratio:.3f} "
            f"(> max(base, {degraded_tol}) — the run spent too much of "
            f"its wall degraded)")
    return regressions


# serve-soak tripwires: once an artifact proves the machinery fires, a
# successor where it reads 0 has silently unhooked it
SERVE_TRIPWIRES = ("queries_preempted", "stages_resumed_from_cursor",
                   "backpressure_429s")


def _serve_field(art: dict, key: str):
    """SERVE_r02 kept tallies at the top level; r03+ nests totals/gates.
    Look in gates, then totals, then the root."""
    for scope in (art.get("gates") or {}, art.get("totals") or {}, art):
        if key in scope:
            return scope[key]
    return None


def diff_serve(base: dict, cand: dict, p99_tol: float = 0.25) -> List[str]:
    """Regressions between two SERVE_rNN.json soak artifacts."""
    regressions: List[str] = []
    # absolute hygiene: these are zero in every healthy serve soak
    for field in ("failed", "leaked_mem", "shm_segments_leaked"):
        v = _serve_field(cand, field)
        if v is not None and int(v) != 0:
            regressions.append(f"{field}={v} (must be 0)")
    # door give-ups must not grow: backpressure clients wait, not abandon
    bshed, cshed = _serve_field(base, "shed_door"), _serve_field(
        cand, "shed_door")
    if bshed is not None and cshed is not None and int(cshed) > int(bshed):
        regressions.append(
            f"shed_door {cshed} vs base {bshed} (door give-ups grew)")
    # the QoS contract: loaded light p99 within 1.5x isolated, absolute
    # (with the cache soak's small-percentile floor: when both sides sit
    # within ~25ms, ratio alone is scheduler jitter, not starvation)
    cgates = cand.get("gates") or {}
    ratio = cgates.get("light_p99_ratio")
    iso = cgates.get("light_p99_isolated_ms")
    loaded = cgates.get("light_p99_loaded_ms")
    close = (iso is not None and loaded is not None
             and float(loaded) <= float(iso) + 25.0)
    if ratio is not None and float(ratio) > 1.5 and not close:
        regressions.append(
            f"light_p99_ratio {ratio} over the 1.5x isolation ceiling")
    # cache contract (SERVE_r04+): zipfian repeats must keep hitting, and
    # a stale entry must never be served as-is
    bhit = _serve_field(base, "cache_hit_rate")
    chit = _serve_field(cand, "cache_hit_rate")
    if bhit is not None and chit is not None and \
            float(chit) < float(bhit) - 0.2:
        regressions.append(
            f"cache_hit_rate {chit} vs base {bhit} (dropped > 0.2 — "
            f"fingerprinting or admission broke reuse)")
    cstale = _serve_field(cand, "cache_stale_served")
    if cstale is not None and int(cstale) != 0:
        regressions.append(
            f"cache_stale_served={cstale} (a stale entry was served "
            f"without refresh — must be 0)")
    # per-tenant p99s, for tenants both artifacts measured
    btenants = base.get("tenants") or {}
    for tname, crec in sorted((cand.get("tenants") or {}).items()):
        cp99 = (crec.get("latency_ms") or {}).get("p99")
        brec = btenants.get(tname)
        if brec is None:
            print(f"  tenant {tname}: new in candidate, skipped")
            continue
        bp99 = (brec.get("latency_ms") or {}).get("p99")
        if bp99 and cp99 is not None and \
                float(cp99) > float(bp99) * (1 + p99_tol):
            regressions.append(
                f"tenant {tname}: p99 {cp99}ms vs base {bp99}ms "
                f"(+>{p99_tol * 100:.0f}%)")
    # preemption tripwires: proven-live machinery must not fall silent.
    # Only when the candidate carries the section at all — the cache soak
    # (SERVE_r04) measures a different workload and records none.
    btrip = base.get("tripwires") or {}
    ctrip = cand.get("tripwires")
    if ctrip is None:
        print("  tripwires: candidate records none (cache-soak schema), "
              "skipped")
    else:
        for t in SERVE_TRIPWIRES:
            if int(btrip.get(t, 0) or 0) > 0 and \
                    int(ctrip.get(t, 0) or 0) == 0:
                regressions.append(
                    f"tripwire {t} fell to 0 (base {btrip[t]}) — the "
                    f"preempt/backpressure path no longer fires")
    proof = cand.get("preempt_proof")
    if proof is not None and not proof.get("bit_identical"):
        regressions.append(
            f"preempt_proof did not resume bit-identical: {proof}")
    return regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("base", help="base artifact (BENCH/SOAK json)")
    ap.add_argument("cand", help="candidate artifact to gate")
    ap.add_argument("--wall-tol", type=float, default=0.25,
                    help="per-shape wall-clock growth tolerance (frac)")
    ap.add_argument("--bytes-tol", type=float, default=0.10,
                    help="shuffle_bytes_serialized growth tolerance (frac)")
    ap.add_argument("--chaos", action="store_true",
                    help="diff CHAOS_rNN.json injection matrices instead")
    ap.add_argument("--serve", action="store_true",
                    help="diff SERVE_rNN.json serving soaks instead "
                         "(per-tenant p99, shed counts, preemption "
                         "tripwires)")
    ap.add_argument("--multichip", action="store_true",
                    help="diff MULTICHIP_rNN.json device-primary rounds "
                         "instead (bit-identity, top-mesh wall, "
                         "device_time_fraction, mesh-path liveness)")
    ap.add_argument("--frac-tol", type=float, default=0.10,
                    help="--multichip: device_time_fraction drop "
                         "tolerance (abs)")
    ap.add_argument("--inflation-tol", type=float, default=0.25,
                    help="--chaos: p99_inflation growth tolerance (abs)")
    ap.add_argument("--p99-tol", type=float, default=0.25,
                    help="--serve: per-tenant p99 growth tolerance (frac)")
    ap.add_argument("--attribution", action="store_true",
                    help="diff per-shape exclusive-time attribution "
                         "sections instead (per-category ratio gates; "
                         "catches regressions hidden by a flat wall)")
    ap.add_argument("--attr-ratio", type=float, default=2.0,
                    help="--attribution: growth ratio per category")
    ap.add_argument("--attr-jit-ratio", type=float, default=3.0,
                    help="--attribution: growth ratio for jit_compile")
    ap.add_argument("--attr-min-ms", type=float, default=50.0,
                    help="--attribution: noise floor (ms) under which a "
                         "category never regresses")
    ap.add_argument("--health", action="store_true",
                    help="diff the health sections of two soak artifacts "
                         "instead (any critical interval fails; degraded-"
                         "time ratio gate; pre-health artifacts skip "
                         "clean)")
    ap.add_argument("--degraded-tol", type=float, default=0.25,
                    help="--health: degraded-time ratio floor under which "
                         "the candidate never regresses (abs)")
    args = ap.parse_args(argv)
    with open(args.base) as f:
        base = json.load(f)
    with open(args.cand) as f:
        cand = json.load(f)
    print(f"diffing {args.cand} against {args.base}")
    if args.chaos:
        regressions = diff_chaos(base, cand, args.inflation_tol)
    elif args.multichip:
        regressions = diff_multichip(base, cand, args.wall_tol,
                                     args.frac_tol)
    elif args.serve:
        regressions = diff_serve(base, cand, args.p99_tol)
    elif args.attribution:
        regressions = diff_attribution(base, cand, args.attr_ratio,
                                       args.attr_jit_ratio,
                                       args.attr_min_ms)
    elif args.health:
        regressions = diff_health(base, cand, args.degraded_tol)
    else:
        regressions = diff_artifacts(base, cand, args.wall_tol,
                                     args.bytes_tol)
    if regressions:
        print(f"REGRESSIONS ({len(regressions)}):")
        for r in regressions:
            print(f"  - {r}")
        return 1
    print("ok: candidate is no worse than base")
    return 0


if __name__ == "__main__":
    sys.exit(main())

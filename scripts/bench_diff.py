#!/usr/bin/env python
"""Diff two bench/soak artifacts and fail on regression.

The repo accumulates BENCH_rNN.json / SOAK_rNN.json artifacts per round;
until now comparing them was a by-eye job. This script makes the comparison
mechanical so a round gate (or CI) can run::

    python scripts/bench_diff.py BENCH_r09.json BENCH_r10.json

and get exit 1 iff the candidate regressed against the base:

- per-shape wall clock grew beyond ``--wall-tol`` (default 25% — bench
  boxes are noisy; this catches step-function regressions, not jitter)
- a zero-expected invariant tripwire went nonzero in the candidate
  (``window_group_loops``, ``fused_fallback_batches``, ``agg_reintern_rows``
  — a silently-degraded fast path, regardless of timing)
- ``shuffle_bytes_serialized`` grew beyond ``--bytes-tol`` (default 10%)
  over the base: the zero-copy tiers (serde elision, shm hand-off) started
  re-serializing shuffle traffic
- ``kernel_time_s`` exceeds the shape's wall clock in the candidate but
  not in the base: the union-of-intervals kernel timer guarantees
  ``kernel_time_s <= wall`` by construction, so a NEW violation means the
  timer is double-counting again (pre-fix artifacts like BENCH_r09 carry
  the old double-counted numbers; a self-diff of those must stay clean)

Both BENCH artifacts (``shapes.<q>.value`` + ``kernel_stats``) and SOAK
artifacts (``shapes.<q>.wall_s`` with tripwires inline) are understood;
shapes present in only one artifact are reported but not failed (new
shapes are growth, not regression).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

# tripwires that must be 0 in every healthy run (runtime/metrics.py keeps
# the authoritative list; these are the subset whose nonzero value means a
# degraded fast path rather than a workload property)
ZERO_EXPECTED = ("window_group_loops", "fused_fallback_batches",
                 "agg_reintern_rows")


def _shape_wall(rec: dict):
    for key in ("value", "wall_s"):
        if key in rec:
            return float(rec[key])
    return None


def _shape_counters(rec: dict) -> dict:
    # BENCH nests tripwires under kernel_stats; SOAK inlines them
    return rec.get("kernel_stats") or rec


def diff_artifacts(base: dict, cand: dict, wall_tol: float = 0.25,
                   bytes_tol: float = 0.10) -> List[str]:
    """Return regression descriptions (empty == candidate is no worse)."""
    regressions: List[str] = []
    base_shapes = base.get("shapes") or {}
    cand_shapes = cand.get("shapes") or {}
    for name, crec in sorted(cand_shapes.items()):
        brec = base_shapes.get(name)
        cwall = _shape_wall(crec)
        ctr = _shape_counters(crec)

        for t in ZERO_EXPECTED:
            if int(ctr.get(t, 0) or 0) != 0:
                regressions.append(
                    f"{name}: zero-expected tripwire {t}={ctr[t]}")

        kt = ctr.get("kernel_time_s")
        if kt is not None and cwall is not None and float(kt) > cwall:
            bctr0 = _shape_counters(brec) if brec is not None else {}
            bkt = bctr0.get("kernel_time_s")
            bwall0 = _shape_wall(brec) if brec is not None else None
            base_broken = (bkt is not None and bwall0 is not None
                           and float(bkt) > bwall0)
            if not base_broken:
                regressions.append(
                    f"{name}: kernel_time_s {kt} > wall {cwall}"
                    " (union timer invariant broken)")
            else:
                print(f"  {name}: kernel_time_s > wall in BOTH artifacts"
                      " (pre-fix base), not treated as regression")

        if brec is None:
            print(f"  {name}: new shape (no base), skipped comparison")
            continue
        bwall = _shape_wall(brec)
        if bwall and cwall is not None and cwall > bwall * (1 + wall_tol):
            regressions.append(
                f"{name}: wall {cwall}s vs base {bwall}s"
                f" (+{(cwall / bwall - 1) * 100:.0f}% > {wall_tol * 100:.0f}%)")

        bctr = _shape_counters(brec)
        bser = int(bctr.get("shuffle_bytes_serialized", 0) or 0)
        cser = int(ctr.get("shuffle_bytes_serialized", 0) or 0)
        # +4KB absolute slack: a base of 0 must not fail on any nonzero
        if cser > bser * (1 + bytes_tol) + 4096:
            regressions.append(
                f"{name}: shuffle_bytes_serialized {cser} vs base {bser}"
                " (zero-copy tier regression)")
    return regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("base", help="base artifact (BENCH/SOAK json)")
    ap.add_argument("cand", help="candidate artifact to gate")
    ap.add_argument("--wall-tol", type=float, default=0.25,
                    help="per-shape wall-clock growth tolerance (frac)")
    ap.add_argument("--bytes-tol", type=float, default=0.10,
                    help="shuffle_bytes_serialized growth tolerance (frac)")
    args = ap.parse_args(argv)
    with open(args.base) as f:
        base = json.load(f)
    with open(args.cand) as f:
        cand = json.load(f)
    print(f"diffing {args.cand} against {args.base}")
    regressions = diff_artifacts(base, cand, args.wall_tol, args.bytes_tol)
    if regressions:
        print(f"REGRESSIONS ({len(regressions)}):")
        for r in regressions:
            print(f"  - {r}")
        return 1
    print("ok: candidate is no worse than base")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Per-fingerprint attribution regression watch.

Walks the profile store (``conf.profile_store_dir``) and compares each
fingerprint's LAST run's per-category exclusive times (``attribution``)
against its own rolling baseline (``attribution_baseline``, the
capped-window mean ``obs/stats.py`` folds on every save). A category
breaches when::

    current > ratio x max(baseline, floor)

with ``--jit-ratio`` (default ``conf.attribution_regress_jit_ratio``,
3.0) for ``jit_compile_time_ns`` and ``--ratio`` (default
``conf.attribution_regress_ratio``, 2.0) for everything else; the floor
``--min-ms`` (default ``conf.attribution_regress_min_ms``, 50ms) keeps
sub-noise categories from tripping. This is the category-level watch the
wall-clock gates can't provide: a query whose compile time tripled but
whose kernels got faster shows a flat wall and still breaches here.

On breach the watch emits a flight-recorder incident bundle
(``kind="attribution_regression"`` under ``conf.incident_dir``, browsable
at GET /debug/incidents) carrying the offending categories, and exits 1.
Fingerprints with fewer than 2 baseline samples are skipped — a
first-observed shape has no history to regress against (its baseline IS
its first run).

Run it after a soak/bench round, or from cron against a production
profile store::

    python scripts/regression_watch.py --store /tmp/blaze_tpu_profiles
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from blaze_tpu.config import get_config  # noqa: E402
from blaze_tpu.obs.attribution import CATEGORY_FIELDS  # noqa: E402


def check_profile(profile: dict, ratio: float, jit_ratio: float,
                  min_ms: float):
    """Breached categories for one stored profile:
    ``[{category, current_ns, baseline_ns, ratio, limit_ns}, ...]``
    (empty == within baseline, or no history yet)."""
    attr = profile.get("attribution") or {}
    base = profile.get("attribution_baseline") or {}
    if not attr or int(base.get("samples") or 0) < 2:
        return []
    floor_ns = min_ms * 1e6
    breaches = []
    for field in CATEGORY_FIELDS:
        cur = float(attr.get(field) or 0.0)
        bl = float(base.get(field) or 0.0)
        r = jit_ratio if field == "jit_compile_time_ns" else ratio
        limit = r * max(bl, floor_ns)
        if cur > limit:
            breaches.append({"category": field,
                             "current_ns": int(cur),
                             "baseline_ns": int(bl),
                             "ratio": round(cur / max(bl, floor_ns), 2),
                             "limit_ns": int(limit)})
    return breaches


def watch(store: str, ratio: float, jit_ratio: float, min_ms: float,
          incident_dir: str = "") -> dict:
    """Scan every stored profile; returns the report dict. Writes one
    incident bundle per breached fingerprint when ``incident_dir`` is
    set."""
    report = {"store": store, "checked": 0, "skipped_no_history": 0,
              "breaches": []}
    names = []
    if os.path.isdir(store):
        names = sorted(n for n in os.listdir(store) if n.endswith(".json"))
    for name in names:
        try:
            with open(os.path.join(store, name)) as f:
                profile = json.load(f)
        except (OSError, ValueError):
            continue
        if not (profile.get("attribution") or {}):
            continue
        if int((profile.get("attribution_baseline") or {})
               .get("samples") or 0) < 2:
            report["skipped_no_history"] += 1
            continue
        report["checked"] += 1
        breaches = check_profile(profile, ratio, jit_ratio, min_ms)
        if not breaches:
            continue
        fp = profile.get("fingerprint") or name[:-5]
        entry = {"fingerprint": fp, "label": profile.get("label"),
                 "breaches": breaches}
        if incident_dir:
            import dataclasses

            from blaze_tpu.obs.dump import record_incident

            conf = dataclasses.replace(get_config(),
                                       incident_dir=incident_dir)
            entry["incident"] = record_incident(
                kind="attribution_regression", label=str(fp), conf=conf,
                extra={"breaches": breaches,
                       "wall_ns": (profile.get("attribution")
                                   or {}).get("wall_ns"),
                       "baseline_samples": (
                           profile.get("attribution_baseline")
                           or {}).get("samples")})
        report["breaches"].append(entry)
    return report


def main(argv=None) -> int:
    conf = get_config()
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--store", default=conf.profile_store_dir,
                    help="profile store directory to scan")
    ap.add_argument("--ratio", type=float,
                    default=conf.attribution_regress_ratio,
                    help="per-category growth ratio over baseline")
    ap.add_argument("--jit-ratio", type=float,
                    default=conf.attribution_regress_jit_ratio,
                    help="growth ratio for jit_compile (compile-cache "
                         "breakage hides behind flat walls)")
    ap.add_argument("--min-ms", type=float,
                    default=conf.attribution_regress_min_ms,
                    help="noise floor: categories under this never breach")
    ap.add_argument("--incident-dir", default=conf.incident_dir,
                    help="write incident bundles here on breach "
                         "('' disables)")
    args = ap.parse_args(argv)
    report = watch(args.store, args.ratio, args.jit_ratio, args.min_ms,
                   args.incident_dir)
    print(json.dumps(report, indent=2))
    if report["breaches"]:
        print(f"REGRESSION: {len(report['breaches'])} fingerprint(s) "
              f"breached their attribution baseline", file=sys.stderr)
        return 1
    print(f"ok: {report['checked']} fingerprint(s) within baseline "
          f"({report['skipped_no_history']} without history)",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Serving soak: N client threads hammer one QueryScheduler with mixed
TPC-DS-like query shapes under a constrained memory budget, measuring
end-to-end latency percentiles, shed rate, and peak in-flight concurrency.

Three shapes over a store_sales-like parquet fact table:
  agg    — two-stage hash agg (partial -> hash exchange -> final)
  sort   — global sort over a single-partition exchange + limit
  window — per-store rank() window over a hash exchange

A fraction of submissions carry tight deadlines (exercising the cancel
path) and the queue is kept small relative to the client count so the
admission controller genuinely sheds.

Round 2 (telemetry): latency percentiles now come from the registry's
serve SLO histograms scraped over HTTP ``GET /metrics`` while the
scheduler is open — the same numbers a Prometheus deployment would see —
and every client-side tally is cross-checked EXACTLY against the
registry's counters (``/debug/metrics?format=raw`` returns exact
integers). Deadline-expired queries must leave a retrievable forensic
bundle at ``/debug/incidents/<id>``. Writes SERVE_r02.json at the repo
root — the numbers BASELINE.md cites.

Run: python scripts/serve_soak.py   (CPU; ~1-3 min)
Env: SERVE_CLIENTS (8), SERVE_QUERIES (48 total), SERVE_CONCURRENT (2),
SERVE_BUDGET_MB (64), SERVE_ROWS (300_000), SERVE_QUEUE (4),
SERVE_QUEUE_TIMEOUT_S (20).
"""

import json
import os
import random
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CLIENTS = int(os.environ.get("SERVE_CLIENTS", 8))
QUERIES = int(os.environ.get("SERVE_QUERIES", 48))
CONCURRENT = int(os.environ.get("SERVE_CONCURRENT", 2))
BUDGET_MB = int(os.environ.get("SERVE_BUDGET_MB", 64))
ROWS = int(os.environ.get("SERVE_ROWS", 300_000))
QUEUE = int(os.environ.get("SERVE_QUEUE", 4))
QUEUE_TIMEOUT_S = float(os.environ.get("SERVE_QUEUE_TIMEOUT_S", 20.0))

import jax

jax.config.update("jax_platforms", "cpu")


def pctl(xs, p):
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(p / 100.0 * (len(xs) - 1))))]


def _get(base, path):
    return urllib.request.urlopen(base + path, timeout=10).read().decode()


def _counter(raw_registry, name, **labels):
    """Exact integer value of one counter series out of format=raw (0 when
    the series never fired — drain/exposition skip empty series)."""
    fam = raw_registry.get(name)
    if not fam:
        return 0
    for s in fam["series"]:
        if s.get("labels", {}) == labels:
            return int(s["value"])
    return 0


def shm_roots(baseline=()):
    """Zero-copy shm roots currently present, minus a baseline snapshot —
    sessions must unlink theirs at close, so any delta is a leak."""
    import glob

    return sorted(set(glob.glob("/dev/shm/blaze_tpu_shm_*")) - set(baseline))


def main():
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.config import Config, set_config
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T
    from blaze_tpu.obs.telemetry import (get_registry,
                                         histogram_quantiles_from_text,
                                         parse_prometheus_text)
    from blaze_tpu.ops.base import QueryCancelled
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime.http import ProfilingService
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.runtime.session import Session
    from blaze_tpu.serve import Overloaded, QueryScheduler

    F, M, HASH = E.AggFunction, E.AggMode, E.AggExecMode.HASH_AGG

    out = {"clients": CLIENTS, "queries": QUERIES, "concurrent": CONCURRENT,
           "budget_mb": BUDGET_MB, "rows": ROWS}
    t_all = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="blaze_serve_soak_") as tmpdir:
        set_config(Config(memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                          mem_wait_timeout_s=5.0,
                          incident_dir=os.path.join(tmpdir, "incidents"),
                          incident_max_bundles=64))
        MemManager.reset()

        # store_sales-like fact: (store, item, qty, price)
        rng = random.Random(7)
        path = os.path.join(tmpdir, "store_sales.parquet")
        pq.write_table(pa.table({
            "ss_store_sk": [rng.randrange(12) for _ in range(ROWS)],
            "ss_item_sk": [rng.randrange(2000) for _ in range(ROWS)],
            "ss_quantity": [rng.randrange(1, 100) for _ in range(ROWS)],
            "ss_net_paid": [rng.randrange(1, 50_000) for _ in range(ROWS)],
        }), path)

        def scan():
            return scan_node_for_files([path], num_partitions=4)

        def agg_plan():
            # sum(net_paid) group by store (Q3/Q7-style rollup)
            g = [("ss_store_sk", E.Column("ss_store_sk"))]
            partial = N.Agg(scan(), HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.PARTIAL, "paid")])
            ex = N.ShuffleExchange(
                partial, N.HashPartitioning([E.Column("ss_store_sk")], 4))
            return N.Agg(ex, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.FINAL, "paid")])

        def sort_plan():
            # global top ordering by net_paid (Q98-style ordered report)
            ex = N.ShuffleExchange(scan(), N.SinglePartitioning(1))
            srt = N.Sort(ex, [E.SortOrder(E.Column("ss_net_paid"),
                                          ascending=False)])
            return N.Limit(srt, 1000)

        def window_plan():
            # rank() over (partition by store order by net_paid) (Q67-style)
            ex = N.ShuffleExchange(
                scan(), N.HashPartitioning([E.Column("ss_store_sk")], 4))
            return N.Window(
                ex,
                [N.WindowExpr(kind="rank", name="rnk")],
                [E.Column("ss_store_sk")],
                [E.SortOrder(E.Column("ss_net_paid"), ascending=False)])

        # explicit per-shape admission estimates (measured: peak engine
        # usage for these plans at SERVE_ROWS=300k is ~12 MB); the generic
        # plan-based estimate is sized for unknown clients and would keep
        # a 64 MB budget to one query at a time
        shapes = [("agg", agg_plan, 12 << 20),
                  ("sort", sort_plan, 24 << 20),
                  ("window", window_plan, 24 << 20)]

        client_ms = []
        # client-truth tallies, split by WHERE the failure surfaced:
        #   door_overloads — every Overloaded raised by submit() (retries
        #                    each count: mirrors rejected_total{queue_full})
        #   shed_door      — queries abandoned after exhausting retries
        #   shed_queued    — accepted, then shed out of the queue (Overloaded
        #                    raised by result()): mirrors outcome="shed"
        counts = {"completed": 0, "shed_door": 0, "shed_queued": 0,
                  "cancelled": 0, "failed": 0, "door_overloads": 0}
        mu = threading.Lock()
        seq = iter(range(QUERIES))

        shm0 = shm_roots()
        with Session() as sess:
            from blaze_tpu.utils.device import DEVICE_STATS

            DEVICE_STATS.reset()
            get_registry().reset_values()  # exact-match bookkeeping below
            svc = ProfilingService.start(sess)
            base = f"http://127.0.0.1:{svc.port}"
            scrape_errors = []
            stop_sampler = threading.Event()

            def sampler():
                # a live Prometheus would scrape mid-soak: prove /metrics
                # stays parseable and cheap under concurrent load
                while not stop_sampler.wait(1.0):
                    try:
                        parse_prometheus_text(_get(base, "/metrics"))
                    except Exception as exc:  # noqa: BLE001
                        scrape_errors.append(repr(exc))

            try:
                with QueryScheduler(sess, max_concurrent=CONCURRENT,
                                    max_queue=QUEUE,
                                    queue_timeout_s=QUEUE_TIMEOUT_S) as sched:
                    def client(cid):
                        rng = random.Random(100 + cid)
                        while True:
                            with mu:
                                i = next(seq, None)
                            if i is None:
                                return
                            name, mk, est = shapes[i % len(shapes)]
                            # ~1 in 8 queries carries a hopeless deadline:
                            # exercises mid-flight cancel + reclamation
                            deadline = 0.05 if i % 8 == 5 else None
                            t0 = time.perf_counter()
                            h = None
                            for attempt in range(4):
                                try:
                                    h = sched.submit(mk(), deadline_s=deadline,
                                                     mem_estimate=est,
                                                     label=f"{name}_{i}")
                                    break
                                except Overloaded:
                                    # real clients back off on a full queue;
                                    # give up (counted shed) after 3 retries
                                    with mu:
                                        counts["door_overloads"] += 1
                                    if attempt == 3:
                                        break
                                    time.sleep(rng.uniform(0.1, 0.4))
                            if h is None:
                                with mu:
                                    counts["shed_door"] += 1
                                continue
                            try:
                                h.result(timeout=300)
                                ms = (time.perf_counter() - t0) * 1e3
                                with mu:
                                    counts["completed"] += 1
                                    client_ms.append(ms)
                            except Overloaded:
                                with mu:
                                    counts["shed_queued"] += 1
                            except QueryCancelled:
                                with mu:
                                    counts["cancelled"] += 1
                            except BaseException as exc:
                                print(f"[client {cid}] {name}_{i} failed: "
                                      f"{type(exc).__name__}: {exc}",
                                      file=sys.stderr)
                                with mu:
                                    counts["failed"] += 1
                            time.sleep(rng.uniform(0, 0.05))

                    smp = threading.Thread(target=sampler, daemon=True)
                    smp.start()
                    ts = [threading.Thread(target=client, args=(c,),
                                           daemon=True)
                          for c in range(CLIENTS)]
                    for t in ts:
                        t.start()
                    for t in ts:
                        t.join()
                    stop_sampler.set()
                    smp.join(timeout=5)

                    # -- scrape while the scheduler is still open ---------
                    prom_text = _get(base, "/metrics")
                    parsed = parse_prometheus_text(prom_text)
                    raw = json.loads(_get(base, "/debug/metrics?format=raw"))
                    reg = raw["registry"]
                    incidents = json.loads(_get(base, "/debug/incidents"))
                    dl = [i for i in incidents if i["kind"] == "deadline"]
                    dl_bundle = (
                        json.loads(_get(
                            base, f"/debug/incidents/{dl[0]['id']}"))
                        if dl else None)
                    # stats plane: served queries leave fingerprint-keyed
                    # profiles; the artifact keeps the index head as proof
                    # the plane stays live under concurrency
                    profiles = json.loads(_get(base, "/debug/profiles"))

                    out["peak_inflight"] = sched.peak_inflight
                    out["serve_metrics"] = sched.metrics.to_dict()
                    out["query_profiles"] = {"count": len(profiles),
                                             "head": profiles[:3]}
            finally:
                ProfilingService.stop()

            assert not scrape_errors, scrape_errors

            # device + fusion counters next to the SLOs — the same
            # kernel_stats shape bench records (DEVICE_STATS snapshot merged
            # with the invariant tripwires, fused-stage jit cache included)
            from blaze_tpu.runtime.metrics import tripwire_totals

            out["kernel_stats"] = dict(DEVICE_STATS.snapshot(),
                                       **tripwire_totals(sess.metrics))

            # -- latency SLOs from the scraped histograms ------------------
            def hist_ms(name, **labels):
                qs = histogram_quantiles_from_text(
                    parsed, name, labels, [0.5, 0.95, 0.99])
                return {f"p{int(q * 100)}":
                        None if v is None else round(v * 1e3, 2)
                        for q, v in qs.items()}

            out["latency_ms"] = hist_ms("blaze_serve_e2e_seconds",
                                        outcome="done")
            out["run_ms"] = hist_ms("blaze_serve_run_seconds")
            out["queue_wait_ms"] = hist_ms("blaze_serve_queue_wait_seconds")
            out["client_latency_ms"] = {"p50": pctl(client_ms, 50),
                                        "p95": pctl(client_ms, 95),
                                        "p99": pctl(client_ms, 99)}

            # -- exact reconciliation: registry vs client ground truth -----
            reg_counts = {
                "door_overloads": _counter(reg, "blaze_serve_rejected_total",
                                           reason="queue_full"),
                "shed_queued": _counter(reg, "blaze_serve_queries_total",
                                        outcome="shed"),
                "completed": _counter(reg, "blaze_serve_queries_total",
                                      outcome="done"),
                "deadline": _counter(reg, "blaze_serve_queries_total",
                                     outcome="deadline"),
                "cancelled": _counter(reg, "blaze_serve_queries_total",
                                      outcome="cancelled"),
                "failed": _counter(reg, "blaze_serve_queries_total",
                                   outcome="failed"),
            }
            recon = {
                "door_overloads": (counts["door_overloads"],
                                   reg_counts["door_overloads"]),
                "shed_queued": (counts["shed_queued"],
                                reg_counts["shed_queued"]),
                "completed": (counts["completed"], reg_counts["completed"]),
                "cancelled": (counts["cancelled"],
                              reg_counts["deadline"]
                              + reg_counts["cancelled"]),
                "failed": (counts["failed"], reg_counts["failed"]),
            }
            mismatches = {k: v for k, v in recon.items() if v[0] != v[1]}
            assert not mismatches, (
                f"registry counters disagree with client truth "
                f"(client, registry): {mismatches}")
            out["registry_counts"] = reg_counts
            out["reconciled"] = {k: v[0] for k, v in recon.items()}

            # every accepted query must land in exactly one outcome bucket
            accepted_total = sum(
                int(s["value"])
                for s in reg["blaze_serve_queries_total"]["series"])
            assert accepted_total == (counts["completed"]
                                      + counts["shed_queued"]
                                      + counts["cancelled"]
                                      + counts["failed"]), accepted_total

            # -- the histogram must agree with the counters too ------------
            done_in_hist = sum(
                int(v) for labels, v in
                parsed.get("blaze_serve_e2e_seconds_count",
                           {}).get("samples", [])
                if labels.get("outcome") == "done")
            assert done_in_hist == counts["completed"], (
                done_in_hist, counts["completed"])

            # -- deadline forensics: bundle must be retrievable over HTTP --
            assert reg_counts["deadline"] > 0, \
                "soak never exercised the deadline path"
            assert dl, f"no deadline bundle among {len(incidents)} incidents"
            assert dl_bundle["spans"], "bundle is missing ring-buffer spans"
            assert dl_bundle["memmgr"] is not None
            out["incidents"] = {"total": len(incidents),
                                "deadline_bundle": dl[0]["id"],
                                "bundle_spans": len(dl_bundle["spans"])}

        mm = MemManager._instance
        out.update({
            **counts,
            "spill_count": mm.spill_count if mm else 0,
            "peak_mem_used": mm.peak_used if mm else None,
            "leaked_mem": mm.used if mm else 0,
            "shm_segments_leaked": len(shm_roots(shm0)),
            "wall_s": round(time.perf_counter() - t_all, 2),
        })

    dst = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVE_r02.json")
    with open(dst, "w") as f:
        json.dump(out, f, indent=2, default=str)
    print(json.dumps(out, indent=2, default=str))
    assert counts["failed"] == 0, "soak had hard failures"
    assert out["leaked_mem"] == 0, "memory leaked across queries"
    assert out["shm_segments_leaked"] == 0, "/dev/shm segment roots leaked"
    print(f"\nwrote {dst}")


def chaos_main(kill_every_s: float):
    """Serve chaos soak (--chaos-kill-every): clients hammer a 2-worker
    clustered scheduler while a ChaosMonkey hard-kills a random worker every
    ``kill_every_s`` seconds. Worker loss mid-query is absorbed by task retry
    + respawn; a query that exhausts its retry budget surfaces as the typed
    ``QueryRetryable`` (incident id attached) and the client RESUBMITS it.
    Gates: zero wrong results, zero hard failures, zero leaked memory bytes,
    worker deaths observed with incident bundles retrievable over HTTP at
    ``/debug/incidents``, chaos p99 <= 3x the no-chaos baseline p99. Evidence
    merges into CHAOS_r01.json (section "serve") BEFORE gates are asserted.
    Env: CHAOS_ROWS (200_000), CHAOS_QUERIES (24), CHAOS_CLIENTS (4).
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.config import Config, set_config
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T
    from blaze_tpu.obs.telemetry import get_registry
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime.cluster import ChaosMonkey
    from blaze_tpu.runtime.http import ProfilingService
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.runtime.session import Session
    from blaze_tpu.serve import Overloaded, QueryRetryable, QueryScheduler
    from scale_soak import _pctl, _write_chaos_section

    F, M, HASH = E.AggFunction, E.AggMode, E.AggExecMode.HASH_AGG
    rows = int(os.environ.get("CHAOS_ROWS", 200_000))
    queries = int(os.environ.get("CHAOS_QUERIES", 24))
    clients = int(os.environ.get("CHAOS_CLIENTS", 4))

    COUNTERS = ("blaze_cluster_worker_deaths_total",
                "blaze_cluster_tasks_retried_total",
                "blaze_cluster_stages_recovered_total",
                "blaze_cluster_maps_recomputed_total",
                "blaze_chaos_kills_total")

    def counters() -> dict:
        snap = get_registry().to_raw()
        out = {}
        for name in COUNTERS:
            series = snap.get(name, {}).get("series", [])
            out[name] = series[0]["value"] if series else 0
        return out

    section = {"kill_every_s": kill_every_s, "rows": rows,
               "queries": queries, "clients": clients, "phases": {}}
    with tempfile.TemporaryDirectory(prefix="blaze_serve_chaos_") as tmpdir:
        rng = random.Random(11)
        path = os.path.join(tmpdir, "store_sales.parquet")
        pq.write_table(pa.table({
            "ss_store_sk": [rng.randrange(12) for _ in range(rows)],
            "ss_item_sk": [rng.randrange(2000) for _ in range(rows)],
            "ss_net_paid": [rng.randrange(1, 50_000) for _ in range(rows)],
        }), path)

        def scan():
            return scan_node_for_files([path], num_partitions=4)

        def agg_plan():
            g = [("ss_store_sk", E.Column("ss_store_sk"))]
            partial = N.Agg(scan(), HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.PARTIAL, "paid")])
            ex = N.ShuffleExchange(
                partial, N.HashPartitioning([E.Column("ss_store_sk")], 4))
            return N.Agg(ex, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.FINAL, "paid")])

        def sort_plan():
            ex = N.ShuffleExchange(scan(), N.SinglePartitioning(1))
            srt = N.Sort(ex, [E.SortOrder(E.Column("ss_net_paid"),
                                          ascending=False)])
            return N.Limit(srt, 1000)

        def window_plan():
            ex = N.ShuffleExchange(
                scan(), N.HashPartitioning([E.Column("ss_store_sk")], 4))
            return N.Window(
                ex,
                [N.WindowExpr(kind="rank", name="rnk")],
                [E.Column("ss_store_sk")],
                [E.SortOrder(E.Column("ss_net_paid"), ascending=False)])

        def canon_rows(table):
            d = table.to_pydict()
            return sorted(zip(*d.values())) if d else []

        def canon_sort(table):
            # ties at the limit boundary make the exact top-1000 row set
            # attempt-dependent; the sort-key multiset is deterministic
            return sorted(table["ss_net_paid"].to_pylist())

        shapes = [("agg", agg_plan, 12 << 20, canon_rows),
                  ("sort", sort_plan, 24 << 20, canon_sort),
                  ("window", window_plan, 24 << 20, canon_rows)]

        with Session() as s_local:
            oracle = {name: cn(s_local.execute_to_table(mk()))
                      for name, mk, _e, cn in shapes}

        def run_phase(with_chaos: bool) -> dict:
            MemManager.reset()
            conf = Config(
                memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                mem_wait_timeout_s=5.0,
                incident_dir=os.path.join(
                    tmpdir,
                    "incidents_chaos" if with_chaos else "incidents_base"))
            set_config(conf)
            lats, wrong, hard_failures, retryable_ids = [], [], [], []
            tallies = {"completed": 0, "resubmits": 0, "gave_up": 0}
            mu = threading.Lock()
            seq = iter(range(queries))
            http_incidents, http_bundle = [], None
            shm0 = shm_roots()
            with Session(conf=conf, num_worker_processes=2) as sess:
                svc = ProfilingService.start(sess) if with_chaos else None
                monkey = ChaosMonkey(sess.pool, kill_every_s,
                                     seed=13).start() if with_chaos else None
                try:
                    with QueryScheduler(sess, max_concurrent=2, max_queue=8,
                                        queue_timeout_s=60.0) as sched:
                        def client(cid):
                            rngc = random.Random(200 + cid)
                            while True:
                                with mu:
                                    i = next(seq, None)
                                if i is None:
                                    return
                                name, mk, est, cn = shapes[i % len(shapes)]
                                t0 = time.perf_counter()
                                got = None
                                for _attempt in range(5):
                                    try:
                                        h = sched.submit(
                                            mk(), mem_estimate=est,
                                            label=f"{name}_{i}")
                                        got = h.result(timeout=300)
                                        break
                                    except Overloaded:
                                        time.sleep(rngc.uniform(0.05, 0.2))
                                    except QueryRetryable as exc:
                                        # the typed retryable contract: the
                                        # client just resubmits
                                        with mu:
                                            tallies["resubmits"] += 1
                                            if exc.incident_id:
                                                retryable_ids.append(
                                                    exc.incident_id)
                                    except BaseException as exc:
                                        with mu:
                                            hard_failures.append(
                                                f"{name}_{i}: "
                                                f"{type(exc).__name__}: "
                                                f"{exc}")
                                        return
                                with mu:
                                    if got is None:
                                        tallies["gave_up"] += 1
                                        return
                                    tallies["completed"] += 1
                                    lats.append(time.perf_counter() - t0)
                                    if cn(got) != oracle[name]:
                                        wrong.append(
                                            {"query": i, "shape": name})

                        ts = [threading.Thread(target=client, args=(c,),
                                               daemon=True)
                              for c in range(clients)]
                        for t in ts:
                            t.start()
                        for t in ts:
                            t.join()
                finally:
                    if monkey is not None:
                        monkey.stop()
                        time.sleep(2.0)  # heartbeat grace for the last kill
                    if svc is not None:
                        # the ISSUE's contract: every killed worker's bundle
                        # is retrievable over HTTP under /debug/incidents
                        base_url = f"http://127.0.0.1:{svc.port}"
                        all_inc = json.loads(_get(base_url,
                                                  "/debug/incidents"))
                        http_incidents = [b for b in all_inc
                                          if b["kind"] == "worker_lost"]
                        if http_incidents:
                            http_bundle = json.loads(_get(
                                base_url, "/debug/incidents/"
                                f"{http_incidents[0]['id']}"))
                        ProfilingService.stop()
                kills = list(monkey.kills) if monkey else []
                mm = MemManager._instance
                leaked = int(mm.used) if mm is not None else 0
            return {
                "lat_s": [round(v, 4) for v in lats],
                "p50_s": round(_pctl(lats, 0.50), 4),
                "p99_s": round(_pctl(lats, 0.99), 4),
                **tallies,
                "wrong_results": wrong,
                "hard_failures": hard_failures,
                "retryable_incident_ids": retryable_ids,
                "kills_injected": len(kills),
                "kills": kills,
                "incident_bundles_worker_lost": len(http_incidents),
                "bundle_has_wid": bool(http_bundle
                                       and "wid" in http_bundle["extra"]),
                "leaked_mem": leaked,
                "shm_segments_leaked": len(shm_roots(shm0)),
            }

        section["phases"]["baseline"] = base = run_phase(with_chaos=False)
        c1 = counters()
        section["phases"]["chaos"] = chaos = run_phase(with_chaos=True)
        c2 = counters()
        section["counters_delta_chaos"] = {k: c2[k] - c1[k] for k in COUNTERS}

    d = section["counters_delta_chaos"]
    section["gates"] = gates = {
        "wrong_results": len(base["wrong_results"])
        + len(chaos["wrong_results"]),
        "hard_failures": len(base["hard_failures"])
        + len(chaos["hard_failures"]),
        "gave_up": base["gave_up"] + chaos["gave_up"],
        "leaked_bytes": base["leaked_mem"] + chaos["leaked_mem"],
        "shm_segments_leaked": base["shm_segments_leaked"]
        + chaos["shm_segments_leaked"],
        "worker_deaths_total": d["blaze_cluster_worker_deaths_total"],
        "kills_injected": chaos["kills_injected"],
        "incident_bundles": chaos["incident_bundles_worker_lost"],
        "p99_no_chaos_s": base["p99_s"],
        "p99_chaos_s": chaos["p99_s"],
        "p99_inflation": round(chaos["p99_s"] / max(base["p99_s"], 1e-9), 2),
    }
    path = _write_chaos_section("serve", section)
    print(json.dumps({"gates": gates, "artifact": path}), flush=True)

    assert gates["wrong_results"] == 0, gates
    assert gates["hard_failures"] == 0, (gates,
                                         chaos["hard_failures"],
                                         base["hard_failures"])
    assert gates["gave_up"] == 0, gates
    assert gates["leaked_bytes"] == 0, gates
    assert gates["shm_segments_leaked"] == 0, gates
    assert gates["worker_deaths_total"] > 0, gates
    assert gates["kills_injected"] > 0, gates
    assert gates["incident_bundles"] >= gates["kills_injected"], gates
    assert chaos["bundle_has_wid"], "bundle must identify the lost worker"
    assert gates["p99_chaos_s"] <= 3.0 * gates["p99_no_chaos_s"], gates
    print("CHAOS SOAK (serve) PASSED", flush=True)


def chaos_matrix_main(spec: str):
    """Serve chaos matrix (--chaos-spec kill:N,hang:N,enospc:N,corrupt:N):
    client threads hammer a 2-worker clustered scheduler once uninjected,
    then once per requested injection mode. EVERY mode gates on zero wrong
    results, zero client-visible failures (the serve layer's auto-retry must
    absorb worker loss — clients never see ``QueryRetryable``), zero leaked
    memory bytes / shm roots, and p99 <= 2x the uninjected phase; plus the
    same per-mode evidence as the scale matrix.

    A deterministic retry-proof prologue runs first: a query whose first
    execution is forced (``worker.task=ioerror`` failpoint, x-capped) to
    exhaust the pool's task retry budget MUST complete via the scheduler's
    transparent re-execution, with the retry recorded on the handle.
    Evidence lands in CHAOS_r02.json (section "serve") BEFORE gates are
    asserted. Env: CHAOS_ROWS (200_000), CHAOS_QUERIES (24),
    CHAOS_CLIENTS (4).
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.config import Config, set_config
    from blaze_tpu.ir import exprs as E
    from blaze_tpu.ir import nodes as N
    from blaze_tpu.ir import types as T
    from blaze_tpu.obs.telemetry import get_registry
    from blaze_tpu.ops.parquet import scan_node_for_files
    from blaze_tpu.runtime import failpoints
    from blaze_tpu.runtime.cluster import ChaosMonkey
    from blaze_tpu.runtime.memmgr import MemManager
    from blaze_tpu.runtime.session import Session
    from blaze_tpu.serve import Overloaded, QueryRetryable, QueryScheduler
    from scale_soak import (_pctl, _write_chaos_section,
                            chaos_mode_conf_kwargs, parse_chaos_spec)

    F, M, HASH = E.AggFunction, E.AggMode, E.AggExecMode.HASH_AGG
    modes = parse_chaos_spec(spec)
    rows = int(os.environ.get("CHAOS_ROWS", 200_000))
    queries = int(os.environ.get("CHAOS_QUERIES", 24))
    clients = int(os.environ.get("CHAOS_CLIENTS", 4))

    COUNTERS = ("blaze_cluster_worker_deaths_total",
                "blaze_cluster_tasks_retried_total",
                "blaze_cluster_tasks_timed_out_total",
                "blaze_cluster_maps_recomputed_total",
                "blaze_serve_retries_total",
                "blaze_chaos_kills_total")

    def counters() -> dict:
        snap = get_registry().to_raw()
        out = {}
        for name in COUNTERS:
            series = snap.get(name, {}).get("series", [])
            out[name] = series[0]["value"] if series else 0
        return out

    section = {"spec": spec, "rows": rows, "queries": queries,
               "clients": clients, "phases": {}}
    with tempfile.TemporaryDirectory(prefix="blaze_serve_chaosm_") as tmpdir:
        rng = random.Random(11)
        path = os.path.join(tmpdir, "store_sales.parquet")
        pq.write_table(pa.table({
            "ss_store_sk": [rng.randrange(12) for _ in range(rows)],
            "ss_item_sk": [rng.randrange(2000) for _ in range(rows)],
            "ss_net_paid": [rng.randrange(1, 50_000) for _ in range(rows)],
        }), path)

        def scan():
            return scan_node_for_files([path], num_partitions=4)

        def agg_plan():
            g = [("ss_store_sk", E.Column("ss_store_sk"))]
            partial = N.Agg(scan(), HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.PARTIAL, "paid")])
            ex = N.ShuffleExchange(
                partial, N.HashPartitioning([E.Column("ss_store_sk")], 4))
            return N.Agg(ex, HASH, g, [N.AggColumn(
                E.AggExpr(F.SUM, [E.Column("ss_net_paid")], T.I64),
                M.FINAL, "paid")])

        def sort_plan():
            ex = N.ShuffleExchange(scan(), N.SinglePartitioning(1))
            srt = N.Sort(ex, [E.SortOrder(E.Column("ss_net_paid"),
                                          ascending=False)])
            return N.Limit(srt, 1000)

        def canon_rows(table):
            d = table.to_pydict()
            return sorted(zip(*d.values())) if d else []

        def canon_sort(table):
            # ties at the limit boundary make the exact top-1000 row set
            # attempt-dependent; the sort-key multiset is deterministic
            return sorted(table["ss_net_paid"].to_pylist())

        shapes = [("agg", agg_plan, 12 << 20, canon_rows),
                  ("sort", sort_plan, 24 << 20, canon_sort)]

        with Session() as s_local:
            oracle = {name: cn(s_local.execute_to_table(mk()))
                      for name, mk, _e, cn in shapes}

        # -- deterministic serve-retry proof -----------------------------
        # x6 per worker: with 4 map tasks and a 3-attempt budget, 12 fires
        # guarantee one task fails 3 attempts on the FIRST execution
        # (TaskFailed), and the caps are spent before the scheduler's
        # transparent re-execution, which must then succeed
        MemManager.reset()
        proof_conf = Config(
            incident_dir=os.path.join(tmpdir, "incidents_proof"),
            failpoints="worker.task=ioerror:every1:x6", failpoint_seed=7)
        set_config(proof_conf)
        c0 = counters()
        with Session(conf=proof_conf, num_worker_processes=2) as sess:
            with QueryScheduler(sess, max_concurrent=1) as sched:
                h = sched.submit(agg_plan(), label="retry_proof")
                table = h.result(timeout=180)  # QueryRetryable = hard fail
        failpoints.disarm()
        c1 = counters()
        section["retry_proof"] = proof = {
            "serve_retries": len(h.retries),
            "retry_history": h.retries,
            "serve_retries_counter_delta":
                c1["blaze_serve_retries_total"]
                - c0["blaze_serve_retries_total"],
            "correct": canon_rows(table) == oracle["agg"],
        }
        print(json.dumps({"retry_proof": proof}), flush=True)

        def run_phase(mode, n) -> dict:
            MemManager.reset()
            kwargs = dict(chaos_mode_conf_kwargs(mode, n)) if mode else {}
            arm_spec = kwargs.pop("failpoints", "")
            arm_timeout = kwargs.pop("task_timeout_s", 0.0)
            conf = Config(
                memory_total=BUDGET_MB << 20, memory_fraction=1.0,
                mem_wait_timeout_s=5.0,
                incident_dir=os.path.join(
                    tmpdir, f"incidents_{mode or 'baseline'}"), **kwargs)
            set_config(conf)
            lats, wrong, hard_failures = [], [], []
            tallies = {"completed": 0, "client_visible_retryable": 0,
                       "gave_up": 0}
            mu = threading.Lock()
            seq = iter(range(queries))
            shm0 = shm_roots()
            c0 = counters()
            with Session(conf=conf, num_worker_processes=2) as sess:
                # warmup pass: uninjected, but RECORDED in every phase's
                # latency population alike — worker JIT warmup is part of
                # each phase's tail in both the baseline and injected runs
                for name, mk, _e, cn in shapes:
                    t0 = time.perf_counter()
                    if cn(sess.execute_to_table(mk())) != oracle[name]:
                        wrong.append({"query": "warmup", "shape": name})
                    lats.append(time.perf_counter() - t0)
                if arm_spec:
                    conf.failpoints = arm_spec
                    conf.task_timeout_s = arm_timeout
                    failpoints.arm_from(conf)
                monkey = ChaosMonkey(sess.pool, n, seed=13).start() \
                    if mode == "kill" else None
                try:
                    with QueryScheduler(sess, max_concurrent=2, max_queue=8,
                                        queue_timeout_s=60.0) as sched:
                        def client(cid):
                            rngc = random.Random(200 + cid)
                            while True:
                                with mu:
                                    i = next(seq, None)
                                if i is None:
                                    return
                                name, mk, est, cn = shapes[i % len(shapes)]
                                t0 = time.perf_counter()
                                got = None
                                for _attempt in range(5):
                                    try:
                                        h = sched.submit(
                                            mk(), mem_estimate=est,
                                            label=f"{name}_{i}")
                                        got = h.result(timeout=300)
                                        break
                                    except Overloaded:
                                        time.sleep(rngc.uniform(0.05, 0.2))
                                    except QueryRetryable:
                                        # the auto-retry contract: clients
                                        # must never see this now
                                        with mu:
                                            tallies[
                                                "client_visible_retryable"
                                            ] += 1
                                    except BaseException as exc:
                                        with mu:
                                            hard_failures.append(
                                                f"{name}_{i}: "
                                                f"{type(exc).__name__}: "
                                                f"{exc}")
                                        return
                                with mu:
                                    if got is None:
                                        tallies["gave_up"] += 1
                                        return
                                    tallies["completed"] += 1
                                    lats.append(time.perf_counter() - t0)
                                    if cn(got) != oracle[name]:
                                        wrong.append(
                                            {"query": i, "shape": name})

                        ts = [threading.Thread(target=client, args=(c,),
                                               daemon=True)
                              for c in range(clients)]
                        for t in ts:
                            t.start()
                        for t in ts:
                            t.join()
                finally:
                    if monkey is not None:
                        monkey.stop()
                        time.sleep(2.0)  # heartbeat grace for the last kill
                    failpoints.unhang()
                kills = list(monkey.kills) if monkey else []
                tier_degraded = int(sess.metrics.total(
                    "shuffle_tier_degraded"))
                mm = MemManager._instance
                leaked = int(mm.used) if mm is not None else 0
            failpoints.disarm()
            c1 = counters()
            return {
                "p50_s": round(_pctl(lats, 0.50), 4),
                "p99_s": round(_pctl(lats, 0.99), 4),
                **tallies,
                "wrong_results": wrong,
                "hard_failures": hard_failures,
                "kills_injected": len(kills),
                "shuffle_tier_degraded": tier_degraded,
                "leaked_mem": leaked,
                "shm_segments_leaked": len(shm_roots(shm0)),
                "counters_delta": {k: c1[k] - c0[k] for k in COUNTERS},
            }

        section["phases"]["baseline"] = base = run_phase(None, 0)
        for mode, n in modes.items():
            section["phases"][mode] = run_phase(mode, n)

    gates = {"p99_baseline_s": base["p99_s"],
             "retry_proof_serve_retries": proof["serve_retries"],
             "retry_proof_correct": proof["correct"], "modes": {}}
    for mode in modes:
        ph = section["phases"][mode]
        d = ph["counters_delta"]
        gates["modes"][mode] = {
            "wrong_results": len(ph["wrong_results"]),
            "hard_failures": len(ph["hard_failures"]),
            "client_visible_retryable": ph["client_visible_retryable"],
            "gave_up": ph["gave_up"],
            "leaked_bytes": ph["leaked_mem"],
            "shm_segments_leaked": ph["shm_segments_leaked"],
            "p99_s": ph["p99_s"],
            "p99_inflation": round(ph["p99_s"] / max(base["p99_s"], 1e-9),
                                   2),
            "worker_deaths": d["blaze_cluster_worker_deaths_total"],
            "tasks_timed_out": d["blaze_cluster_tasks_timed_out_total"],
            "maps_recomputed": d["blaze_cluster_maps_recomputed_total"],
            "serve_retries": d["blaze_serve_retries_total"],
            "shuffle_tier_degraded": ph["shuffle_tier_degraded"],
            "kills_injected": ph["kills_injected"],
        }
    section["gates"] = gates
    path = _write_chaos_section("serve", section, fname="CHAOS_r02.json")
    print(json.dumps({"gates": gates, "artifact": path}), flush=True)

    # evidence is on disk; now enforce the matrix gates
    assert proof["serve_retries"] >= 1 and proof["correct"], proof
    assert proof["serve_retries_counter_delta"] >= 1, proof
    for mode in modes:
        g = gates["modes"][mode]
        assert g["wrong_results"] == 0, (mode, g)
        assert g["hard_failures"] == 0, (mode, g,
                                         section["phases"][mode]
                                         ["hard_failures"])
        assert g["client_visible_retryable"] == 0, (mode, g)
        assert g["gave_up"] == 0, (mode, g)
        assert g["leaked_bytes"] == 0, (mode, g)
        assert g["shm_segments_leaked"] == 0, (mode, g)
        assert g["p99_s"] <= 2.0 * gates["p99_baseline_s"], (mode, g)
    if "kill" in modes:
        g = gates["modes"]["kill"]
        assert g["kills_injected"] > 0 and g["worker_deaths"] > 0, g
    if "hang" in modes:
        assert gates["modes"]["hang"]["tasks_timed_out"] > 0, gates
    if "enospc" in modes:
        assert gates["modes"]["enospc"]["shuffle_tier_degraded"] > 0, gates
    if "corrupt" in modes:
        assert gates["modes"]["corrupt"]["maps_recomputed"] > 0, gates
    print("CHAOS MATRIX (serve) PASSED", flush=True)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--chaos-kill-every", type=float, metavar="N",
                    help="chaos mode: hard-kill a random worker every N "
                         "seconds under serving load and gate on recovery "
                         "(CHAOS_r01.json) instead of the plain serve soak")
    ap.add_argument("--chaos-spec", metavar="SPEC",
                    help="chaos matrix: comma-separated modes "
                         "kill:N,hang:N,enospc:N,corrupt:N — one injected "
                         "phase per mode plus an uninjected baseline, gated "
                         "per mode (CHAOS_r02.json)")
    args = ap.parse_args()
    if args.chaos_spec:
        chaos_matrix_main(args.chaos_spec)
    elif args.chaos_kill_every:
        chaos_main(args.chaos_kill_every)
    else:
        main()
